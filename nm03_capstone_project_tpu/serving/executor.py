"""Warm per-bucket, per-lane executables behind per-lane fault domains.

The r05 bench showed per-batch dispatch overhead — not device FLOPs — is
what a cold path pays on every call: tracing, compilation, and executable
lookup all sit between an arriving request and the chip. An online service
cannot amortize that over a cohort, so this executor warms ONE executable
per (replica lane, batch-size bucket) at startup and serve-time dispatch
is a registry lookup plus an XLA execute — the always-warm model that
makes dynamic batching worth doing at all.

**Replica lanes** are the sharded-serving unlock (ROADMAP item 1): every
local device becomes a lane, each lane holds its own compile-hub
executables pinned to its chip (``SingleDeviceSharding``), and the
batcher fans coalesced batches out across lanes so capacity scales with
chips, not processes. One device degenerates to exactly the PR-4
single-executable behavior. Compilation itself lives in
:mod:`nm03_capstone_project_tpu.compilehub` — this class holds no compile
cache of its own, only lane state.

Supervision is inherited, not reimplemented — but the fault domain is now
the **lane**, not the process (ISSUE 8): each lane runs its dispatches
through its own PR-3 :class:`DispatchSupervisor`, and a deadline expiry
or exhausted retry budget *quarantines that lane*
(:mod:`~nm03_capstone_project_tpu.serving.lanes`) instead of draining
the replica. A background probation probe re-executes the quarantined
lane's warm executable on a canary batch, supervised, off the request
path, and reinstates the lane when it passes. The one-way process-wide
CPU degradation remains the last resort: it fires only when EVERY lane
is quarantined — a replica keeps serving at (N−1)/N capacity through a
single-chip failure instead of degrading to CPU
(docs/OPERATIONS.md, "Multi-chip serving").
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from nm03_capstone_project_tpu.compilehub import programs
from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.obs import flightrec
from nm03_capstone_project_tpu.obs.trace import NULL_TRACE, TraceContext
from nm03_capstone_project_tpu.resilience import (
    DispatchSupervisor,
    FaultPlan,
    InjectedTransientError,
    ResilienceConfig,
    execute_hang,
)
from nm03_capstone_project_tpu.resilience.policy import (
    DeadlineExceeded,
    is_retryable,
)
from nm03_capstone_project_tpu.serving.lanes import (
    PROBATION,
    QUARANTINED,
    LaneFaultDomains,
    LaneQuarantined,
)
from nm03_capstone_project_tpu.serving.metrics import (
    SERVING_LANE_BATCHES_TOTAL,
    SERVING_LANE_INFLIGHT,
    SERVING_LANES_READY,
    SERVING_WARMUP_SECONDS,
)
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("serving")

DEFAULT_BUCKETS: Tuple[int, ...] = (1, 2, 4, 8, 16)

# how long the probation prober sleeps between passes over the
# quarantined set; a quarantined chip gets its first canary after one
# interval, so the knob trades reinstatement latency against probe load
DEFAULT_LANE_PROBE_INTERVAL_S = 5.0


class WarmExecutor:
    """Per-lane, per-bucket warm ``slice_pipeline`` executables.

    ``supports_trace`` tells the batcher this executor accepts the
    ``trace=`` chunk-trace argument on :meth:`run_batch` (test fakes
    without it get a coarse batcher-side dispatch span instead).

    ``buckets`` is the ascending list of batch sizes an executable exists
    for; a coalesced chunk is padded up to the smallest bucket that fits
    (:meth:`bucket_for`), so the compile-shape set is fixed at startup and
    serve-time traffic can never trigger a recompile stall. ``lanes``
    caps the replica-lane count (None = every local device, resolved
    lazily so constructing the executor never initializes a backend).

    Fault domains: each lane owns a supervisor and a state in the
    :class:`LaneFaultDomains` machine. :meth:`run_batch` on a lane whose
    supervised dispatch times out (or exhausts its transient-retry
    budget) raises :class:`LaneQuarantined` toward the batcher — which
    re-dispatches the chunk to a healthy lane — and the probation prober
    (one daemon thread, spawned at first quarantine) re-warms the lane
    off the request path. ``degraded`` flips one-way only when the LAST
    healthy lane quarantines; from then on every dispatch runs the CPU
    fallback (or fails fast with ``--no-fallback-cpu``).
    """

    supports_trace = True

    def __init__(
        self,
        cfg: PipelineConfig,
        buckets: Tuple[int, ...] = DEFAULT_BUCKETS,
        resilience: Optional[ResilienceConfig] = None,
        obs=None,
        fault_plan: Optional[FaultPlan] = None,
        lanes: Optional[int] = None,
        lane_probe_interval_s: float = DEFAULT_LANE_PROBE_INTERVAL_S,
        saturation=None,
        ledger=None,
    ):
        if not buckets or list(buckets) != sorted(set(int(b) for b in buckets)):
            raise ValueError(
                f"buckets must be strictly increasing, got {buckets}"
            )
        if any(b < 1 for b in buckets):
            raise ValueError(f"buckets must be >= 1, got {buckets}")
        if lanes is not None and lanes < 1:
            raise ValueError(f"lanes must be >= 1 (or None = all), got {lanes}")
        if lane_probe_interval_s <= 0:
            raise ValueError(
                f"lane_probe_interval_s must be > 0, got {lane_probe_interval_s}"
            )
        self.cfg = cfg
        self.buckets: Tuple[int, ...] = tuple(int(b) for b in buckets)
        self.obs = obs
        self.res = resilience if resilience is not None else ResilienceConfig()
        self.fault_plan = fault_plan
        self.lane_probe_interval_s = float(lane_probe_interval_s)
        # efficiency accounting (obs.saturation.SaturationMonitor, ISSUE
        # 10): every supervised dispatch records its busy interval (+ the
        # executable's flops for MFU); None = no accounting (tests' fakes)
        self.saturation = saturation
        # device-time ledger (obs.ledger.DeviceTimeLedger, ISSUE 16): fed
        # the per-bucket HLO stage map + memory analysis at warmup; the
        # batcher charges it per chunk from the busy seconds run_batch
        # accumulates on the ChunkTrace. None = no attribution (fakes)
        self.ledger = ledger
        self._fallback_fn = None
        self._lock = threading.Lock()
        self._dispatch_seq = itertools.count()
        self._probe_seq = itertools.count()
        self._warm = False
        self._requested_lanes = lanes
        self._lane_devices: Optional[List] = None
        self._lane_warm: List[bool] = []
        self._lane_inflight: List[int] = []
        self._lane_batches: List[int] = []
        self._lane_supervisors: List[DispatchSupervisor] = []
        self.fleet: Optional[LaneFaultDomains] = None
        self._prober: Optional[threading.Thread] = None
        self._degraded = False
        self._degraded_cause: Optional[str] = None

    def _new_supervisor(self) -> DispatchSupervisor:
        """One quiet-mode supervisor (a lane's, or a probe's): deadline +
        retry semantics identical to PR 3, but its one-way degradation is
        a LANE outcome — the process-level event/dump fires here, in
        :meth:`_process_degrade`, only when the last lane goes."""
        retry = self.res.make_retry_policy(
            seed=self.fault_plan.seed if self.fault_plan is not None else 0
        )
        retry.obs = self.obs
        return DispatchSupervisor(
            self.res, retry=retry, obs=self.obs, emit_degraded=False
        )

    # -- lanes -------------------------------------------------------------

    def _resolve_lanes(self) -> List:
        """The lane device list, resolving (and initializing jax) once."""
        with self._lock:
            if self._lane_devices is not None:
                return self._lane_devices
        devs = programs.lane_devices(self._requested_lanes)
        with self._lock:
            if self._lane_devices is None:
                # fleet construction INSIDE the winner check:
                # LaneFaultDomains.__init__ publishes every lane's state
                # gauge, so a losing racer's throwaway fleet would reset
                # a live quarantine's gauge back to healthy
                self._lane_devices = devs
                self._lane_warm = [self._warm] * len(devs)
                self._lane_inflight = [0] * len(devs)
                self._lane_batches = [0] * len(devs)
                self._lane_supervisors = [
                    self._new_supervisor() for _ in devs
                ]
                self.fleet = LaneFaultDomains(len(devs), obs=self.obs)
                sat = self.saturation
            else:
                sat = None
        if sat is not None:
            # outside the lock (set_lanes publishes gauges); winner-only,
            # like the fleet: a losing racer must not reset the rings
            sat.set_lanes(
                [
                    (d.platform, getattr(d, "device_kind", ""))
                    for d in devs
                ]
            )
        with self._lock:
            return self._lane_devices

    @property
    def lane_count(self) -> Optional[int]:
        """Resolved lane count; the requested cap before resolution (None
        = unknown until a backend exists)."""
        with self._lock:
            if self._lane_devices is not None:
                return len(self._lane_devices)
        return self._requested_lanes

    @property
    def lanes_ready(self) -> int:
        """Warm AND healthy lanes — the ``serving_lanes_ready`` gauge.

        A quarantined lane's executables stay warm, but it takes no
        traffic, so it is not *ready*; probation reinstatement returns
        the gauge to the full lane count.
        """
        with self._lock:
            fleet = self.fleet
            if self._lane_devices is not None:
                return sum(
                    1
                    for i, w in enumerate(self._lane_warm)
                    if w and (fleet is None or fleet.is_healthy(i))
                )
            return (self._requested_lanes or 1) if self._warm else 0

    def healthy_lanes(self) -> Optional[List[int]]:
        """Lane ids currently accepting traffic; None before resolution."""
        with self._lock:
            fleet = self.fleet
        if fleet is None:
            return None
        return fleet.healthy_lanes()

    def healthy_lane_devices(self) -> List[Tuple[int, object]]:
        """``[(lane, device)]`` for the lanes currently taking traffic.

        The volume gang's mesh pool (ISSUE 15): a whole-volume request
        spans every healthy lane's chip, so the gang builds its z-mesh
        from exactly this set — a quarantined lane is out of the mesh the
        same way it is out of the slice fan-out. Resolves lanes (and so
        the backend) on first use, like every dispatch path.
        """
        devs = self._resolve_lanes()
        healthy = self.healthy_lanes()
        ids = healthy if healthy is not None else range(len(devs))
        return [(i, devs[i]) for i in ids]

    def quarantine_lane(self, lane: int, cause: str) -> None:
        """Quarantine one lane from OUTSIDE the dispatch path.

        The volume gang's lane-death attribution hook (ISSUE 15): when a
        mesh-wide dispatch failure is attributable to one lane, the gang
        books it through the same state machine a slice dispatch failure
        uses — probation, telemetry, and the process-wide degradation
        (last healthy lane) all behave identically.
        """
        self._resolve_lanes()
        self._quarantine_lane(lane, cause, NULL_TRACE)

    def new_supervisor(self) -> DispatchSupervisor:
        """A fresh quiet-mode supervisor with this executor's policy.

        Public for the volume gang: supervisors degrade one-way, so every
        caller that can outlive a failure (probation probes, gang
        retries) takes a fresh one per attempt.
        """
        return self._new_supervisor()

    @property
    def quarantined_count(self) -> int:
        with self._lock:
            fleet = self.fleet
        return fleet.quarantined_count() if fleet is not None else 0

    @property
    def capacity(self) -> Optional[float]:
        """Healthy-lane fraction of the fleet (the ``/readyz`` field);
        None before lane resolution."""
        with self._lock:
            fleet = self.fleet
            n = len(self._lane_devices) if self._lane_devices else 0
        if fleet is None or n == 0:
            return None
        return round(fleet.healthy_count() / n, 4)

    def lane_state(self) -> List[dict]:
        """Per-lane readiness/inflight/dispatch/fault-domain state (the
        ``/readyz`` ``lanes.per_lane`` payload); [] before resolution."""
        with self._lock:
            if self._lane_devices is None:
                return []
            fleet = self.fleet
            rows = [
                {
                    "lane": i,
                    "device": str(d),
                    "warm": self._lane_warm[i],
                    "inflight": self._lane_inflight[i],
                    "batches": self._lane_batches[i],
                }
                for i, d in enumerate(self._lane_devices)
            ]
        if fleet is not None:
            for row, st in zip(rows, fleet.snapshot()):
                row["state"] = st["state"]
                row["quarantine_cause"] = st["cause"]
                row["quarantines"] = st["quarantines"]
        return rows

    def _set_lanes_ready_gauge(self) -> None:
        if self.obs is not None:
            self.obs.registry.gauge(
                SERVING_LANES_READY,
                help="warm, healthy replica lanes (chips) taking traffic "
                "in this serving process",
            ).set(self.lanes_ready)

    # -- state -------------------------------------------------------------

    @property
    def warm(self) -> bool:
        """True once every lane's every bucket is built and executed.

        Read by handler threads (via ``/readyz``) while ``warmup`` runs on
        the startup thread; the write is lock-guarded (nm03-lint NM331) so
        a reader observing True also observes the fully-populated lane
        registry, not just the flag.
        """
        with self._lock:
            return self._warm

    @warm.setter
    def warm(self, value: bool) -> None:
        with self._lock:
            self._warm = bool(value)
            if self._lane_devices is not None:
                for i in range(len(self._lane_warm)):
                    self._lane_warm[i] = bool(value)

    @property
    def degraded(self) -> bool:
        """True once the LAST healthy lane quarantined and the one-way
        process-wide CPU degradation tripped (the PR-3 last resort)."""
        with self._lock:
            return self._degraded

    @property
    def degraded_cause(self) -> Optional[str]:
        with self._lock:
            return self._degraded_cause

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest warm bucket that fits ``n`` requests."""
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket {self.buckets[-1]}"
        )

    # -- compilation (delegated to the compile hub) ------------------------

    def _get_compiled(self, bucket: int, lane: int = 0):
        """The (lane, bucket) executable from the hub's registry.

        AOT lowered+compiled at the bucket shape and pinned to the lane's
        device; the hub caches, so two executors with one config share
        warm executables and a post-warmup call here is a dict lookup.
        """
        devs = self._resolve_lanes()
        if not 0 <= lane < len(devs):
            raise ValueError(f"lane {lane} outside [0, {len(devs)})")
        return programs.serve_mask(self.cfg, bucket=bucket, device=devs[lane])

    def warmup(self) -> Dict[str, Dict[int, float]]:
        """Compile + execute every (lane, bucket) once; nested timings.

        Returns ``{"lane0": {bucket: seconds}, ...}``. The execute (on
        zeros) is part of warmup on purpose: first-run allocator and
        executable setup must be paid here, behind ``/readyz``, not by the
        first unlucky request. Lanes warm in order and the
        ``serving_lanes_ready`` gauge rises as each completes, so a probe
        mid-warmup sees honest partial readiness.
        """
        c = self.cfg.canvas
        devs = self._resolve_lanes()
        timings: Dict[str, Dict[int, float]] = {}
        for lane in range(len(devs)):
            lane_t: Dict[int, float] = {}
            for b in self.buckets:
                t0 = time.perf_counter()
                fn = self._get_compiled(b, lane)
                px = np.zeros((b, c, c), np.float32)
                dm = np.full((b, 2), self.cfg.min_dim, np.int32)
                mask, conv = fn(px, dm)
                np.asarray(mask), np.asarray(conv)  # block until executed
                lane_t[b] = round(time.perf_counter() - t0, 3)
                if self.saturation is not None:
                    # pin the executable's flops once: every serve-time
                    # dispatch of this (lane, bucket) credits them to the
                    # MFU window (executable_cost returns {} where the
                    # jaxlib exposes no analysis — MFU is then unpublished)
                    from nm03_capstone_project_tpu.compilehub import (
                        executable_cost,
                    )

                    self.saturation.set_lane_bucket_flops(
                        lane, b, executable_cost(fn).get("flops")
                    )
                if self.ledger is not None and lane == 0:
                    # lane 0 only: every lane compiles the same program per
                    # bucket, so one HLO parse / memory analysis per bucket
                    # feeds the ledger's stage map and HBM table — N more
                    # would be identical work
                    try:
                        from nm03_capstone_project_tpu.compilehub import (
                            executable_cost,
                        )

                        self.ledger.set_bucket_hbm(b, executable_cost(fn))
                        self.ledger.ingest_hlo(fn.as_text())
                    except Exception:
                        # attribution is best-effort evidence; a jaxlib
                        # without as_text()/analysis must not fail warmup
                        pass
            timings[f"lane{lane}"] = lane_t
            with self._lock:
                self._lane_warm[lane] = True
            self._set_lanes_ready_gauge()
        if self.obs is not None:
            for lane_key, lane_t in timings.items():
                for b, s in lane_t.items():
                    self.obs.registry.gauge(
                        SERVING_WARMUP_SECONDS,
                        help="startup compile+first-execute time per lane and batch bucket",
                        bucket=str(b),
                        lane=lane_key[len("lane"):],
                    ).set(s)
        # nm03-lint: disable=NM331 goes through the lock-guarded property setter above; the linter cannot see through the descriptor
        self.warm = True
        self._set_lanes_ready_gauge()
        return timings

    # -- degradation target ------------------------------------------------

    def _fallback_call(self):
        """CPU recompute of the same batch from host arrays (PR-3 ladder).

        One deferred-trace hub program shared across buckets and lanes —
        XLA retraces per bucket shape, which is acceptable on the degraded
        path (correct-but-slower is the contract; every-lane-quarantined
        means the service flips not-ready and the balancer drains the
        replica while this keeps answering).
        """
        with self._lock:
            if self._fallback_fn is not None:
                return self._fallback_fn
        import dataclasses

        import jax

        cpu = jax.local_devices(backend="cpu")[0]
        cfg = (
            dataclasses.replace(self.cfg, use_pallas=False)
            if self.cfg.use_pallas
            else self.cfg
        )
        inner = programs.serve_mask(cfg)  # deferred-trace, default device

        def call(px, dm):
            with jax.default_device(cpu):
                out = inner(
                    jax.device_put(np.asarray(px), cpu),  # nm03-lint: disable=NM401 CPU-degradation target: committing host arrays to the FALLBACK device is the escape from the wedged one — routing through ingest would touch the very device path being escaped
                    jax.device_put(np.asarray(dm), cpu),  # nm03-lint: disable=NM401 CPU-degradation target: committing host arrays to the FALLBACK device is the escape from the wedged one — routing through ingest would touch the very device path being escaped
                )
            return tuple(np.asarray(a) for a in out)

        # first builder wins: concurrent degraded dispatches must agree on
        # ONE callable (two jitted twins would double the retrace cost)
        with self._lock:
            if self._fallback_fn is None:
                self._fallback_fn = call
            return self._fallback_fn

    # -- chaos hook --------------------------------------------------------

    def _pre(
        self,
        index: Optional[int],
        lane: Optional[int] = None,
        lane_only: bool = False,
    ):
        """Dispatch-site fault hook (resilience.FaultPlan); None when off.

        ``lane`` reaches the plan's selectors, so a rule like
        ``{"site": "dispatch", "kind": "hang", "lane": 2}`` wedges one
        chosen lane deterministically. Probation probes pass
        ``lane_only=True``: only rules that explicitly select their lane
        are consulted — a still-sick chip keeps failing its canary — and
        generic dispatch rules keep their ordinal/``count`` budgets for
        the request traffic they were written against.
        """
        plan = self.fault_plan
        if plan is None or not plan.has_site("dispatch"):
            return None

        def pre(cancel):
            rule = plan.fire(
                "dispatch", obs=self.obs, index=index, lane=lane,
                lane_only=lane_only,
            )
            if rule is None:
                return
            if rule.kind == "hang":
                execute_hang(rule, cancel)
            else:  # transient
                raise InjectedTransientError(
                    f"injected transient device error (serve dispatch "
                    f"{index} lane {lane})"
                )

        return pre

    # -- quarantine / probation -------------------------------------------

    @staticmethod
    def _quarantine_cause(exc: BaseException) -> Optional[str]:
        """Map a supervised-dispatch failure to a lane-quarantine cause.

        Deadline expiry and an exhausted transient-retry budget are LANE
        faults (the chip, or its tunnel, is sick); anything else is a
        deterministic error that must propagate to the riders unchanged.
        """
        if isinstance(exc, DeadlineExceeded):
            return "deadline"
        if is_retryable(exc):
            return "device_lost"
        return None

    def _quarantine_lane(self, lane: int, cause: str, trace) -> None:
        fleet = self.fleet
        if fleet is None:
            return
        changed, healthy_left = fleet.quarantine(
            lane, cause, trace_ids=getattr(trace, "trace_ids", [])
        )
        if not changed:
            return
        self._set_lanes_ready_gauge()
        if healthy_left == 0:
            self._process_degrade(cause)
        else:
            self._ensure_prober()

    def _process_degrade(self, cause: str) -> None:
        """Every lane is quarantined: trip the one-way PR-3 last resort.

        This is the ONLY site that emits the process-level ``degraded``
        event / ``pipeline_degraded_total`` / ``degraded_<cause>`` flight
        dump — single-lane quarantines carry their own telemetry
        (serving/lanes.py) and must not masquerade as a dead replica.
        """
        with self._lock:
            if self._degraded:
                return
            self._degraded = True
            self._degraded_cause = str(cause)
        log.warning(
            "all %s lanes quarantined: one-way CPU degradation (%s)",
            self.lane_count, cause,
        )
        if self.obs is not None:
            try:
                self.obs.degraded(
                    cause=cause,
                    site="serve_fleet",
                    timeout_s=self.res.dispatch_timeout_s,
                    lanes=self.lane_count,
                )
            except Exception:  # noqa: BLE001 — telemetry never costs the run
                pass
        flightrec.auto_dump(reason=f"degraded_{cause}")

    def _ensure_prober(self) -> None:
        # start() INSIDE the lock: a created-but-unstarted Thread reports
        # is_alive() False, so releasing the lock before start() would let
        # a racing quarantine spawn a duplicate probe loop
        with self._lock:
            if self._prober is not None and self._prober.is_alive():
                return
            self._prober = threading.Thread(
                target=self._probe_loop, name="nm03-lane-probe", daemon=True
            )
            self._prober.start()

    def _probe_loop(self) -> None:
        """The probation loop: canary every quarantined lane, reinstate on
        success. Exits when nothing is quarantined (re-spawned by the next
        quarantine) or when the process-wide degradation tripped
        (degradation is one-way — a dead replica gets replaced, not
        resurrected lane by lane)."""
        try:
            while True:
                time.sleep(self.lane_probe_interval_s)
                if self.degraded:
                    return
                fleet = self.fleet
                if fleet is None:
                    return
                quarantined = fleet.lanes_in(QUARANTINED)
                if not quarantined and not fleet.lanes_in(PROBATION):
                    return
                for lane in quarantined:
                    if self.degraded:
                        return
                    if not fleet.begin_probation(lane):
                        continue
                    if self._probe_lane(lane) and not self.degraded:
                        # the degraded re-read is only a fast path; the
                        # authoritative guard is reinstate() itself, which
                        # refuses once the fleet retired — atomic with the
                        # quarantine that drained the last healthy lane, so
                        # a canary racing that quarantine can never
                        # resurrect a lane into a drained replica (the lane
                        # stays in PROBATION and the loop exits above)
                        with self._lock:
                            self._lane_supervisors[lane] = (
                                self._new_supervisor()
                            )
                        if fleet.reinstate(lane):
                            self._set_lanes_ready_gauge()
                    elif not self.degraded:
                        fleet.fail_probation(lane)
        finally:
            # single unregister for EVERY exit path (including an
            # unexpected exception), BEFORE the liveness gap closes: a
            # quarantine landing between the exit decision and thread
            # death saw a live prober in _ensure_prober and skipped
            # spawning — re-checking after the unregister reclaims
            # exactly that window (the respawn sees self._prober is None;
            # degraded / no-fleet exits never respawn)
            with self._lock:
                self._prober = None
            fleet = self.fleet
            if (
                fleet is not None
                and fleet.lanes_in(QUARANTINED)
                and not self.degraded
            ):
                self._ensure_prober()

    def _probe_lane(self, lane: int) -> bool:
        """One supervised canary on the lane's smallest warm bucket.

        Runs the SAME hub executable the request path uses (re-warming is
        free — the hub still holds it), under a fresh supervisor so the
        probe gets the full deadline/retry budget, with the fault plan
        consulted (a chaos drill's still-wedged lane keeps failing its
        canary). The ``probe`` span lands in the flight-recorder ring
        under a synthetic ``probe-l<lane>-<n>`` trace id.
        """
        c = self.cfg.canvas
        b = self.buckets[0]
        ctx = TraceContext(f"probe-l{lane}-{next(self._probe_seq)}")
        try:
            fn = self._get_compiled(b, lane)
            px = np.zeros((b, c, c), np.float32)
            dm = np.full((b, 2), self.cfg.min_dim, np.int32)

            def primary():
                mask, conv = fn(px, dm)
                # nm03-lint: disable=NM321 the canary must prove the fetch path too — a wedged fetch is the same wedge (supervisor contract)
                return np.asarray(mask), np.asarray(conv)

            sup = self._new_supervisor()
            with ctx.span("probe", lane=lane):
                sup.run(
                    primary,
                    fallback=None,
                    pre=self._pre(None, lane, lane_only=True),
                    label="serve_probe",
                )
            return True
        except BaseException as e:  # noqa: BLE001 — a failed canary is data
            log.warning("lane %d probation probe failed: %s", lane, e)
            return False

    # -- the serve-time entry point ----------------------------------------

    def run_batch(
        self, pixels: np.ndarray, dims: np.ndarray, lane: int = 0, trace=None
    ):
        """Execute one bucket-padded batch on one lane, under supervision.

        ``pixels`` is (bucket, canvas, canvas) float32, ``dims`` (bucket, 2)
        int32 — already padded by the batcher; ``lane`` picks the replica
        lane whose pinned executable (and chip) runs it. ``trace`` is the
        chunk's :class:`~nm03_capstone_project_tpu.obs.trace.ChunkTrace`:
        each supervised attempt records a ``device_dispatch`` + ``fetch``
        span pair (and the degraded path a ``cpu_fallback`` span) shared
        by every rider — retries show up as repeated attempts on the
        timeline. Returns host-side ``(mask, converged)`` arrays.

        Raises :class:`LaneQuarantined` when THIS lane's supervised
        ladder gave up (deadline / exhausted transient retries) — the
        batcher re-dispatches the chunk to a healthy lane. Raises the
        original error unchanged on a deterministic failure (the riders
        fail, the lane stays healthy). Once every lane is quarantined,
        dispatches run the process-wide CPU fallback here (or raise
        ``DeadlineExceeded`` with ``--no-fallback-cpu``).
        """
        trace = trace if trace is not None else NULL_TRACE
        bucket = int(pixels.shape[0])
        devs = self._resolve_lanes()
        if not 0 <= lane < len(devs):
            raise ValueError(f"lane {lane} outside [0, {len(devs)})")
        if self.degraded:
            return self._run_degraded(pixels, dims, trace)
        fleet = self.fleet
        if fleet is not None and not fleet.is_healthy(lane):
            # racing assignment: the batcher picked this lane before the
            # quarantine landed — bounce the chunk back for re-dispatch
            raise LaneQuarantined(lane, fleet.cause(lane) or "quarantined")
        fn = self._get_compiled(bucket, lane)
        index = next(self._dispatch_seq)
        with self._lock:
            sup = self._lane_supervisors[lane]
        reg = self.obs.registry if self.obs is not None else None
        if reg is not None:
            inflight_g = reg.gauge(
                SERVING_LANE_INFLIGHT,
                help="device batches in flight per replica lane",
                lane=str(lane),
            )
            inflight_g.inc()
        with self._lock:
            if lane < len(self._lane_inflight):
                self._lane_inflight[lane] += 1

        attempts = {"n": 0}  # shared so retried primaries number their spans

        def primary():
            # fetch INSIDE the supervised call: a wedged fetch is the same
            # wedge as a wedged dispatch (supervisor contract)
            attempts["n"] += 1
            with trace.span("device_dispatch", attempt=attempts["n"]):
                mask, conv = fn(pixels, dims)
            with trace.span("fetch", attempt=attempts["n"]):
                # nm03-lint: disable=NM321 the fetch span MEASURES this device sync — that is its entire purpose (trace schema, docs/OBSERVABILITY.md)
                return np.asarray(mask), np.asarray(conv)

        t_busy0 = time.monotonic()
        dispatched_ok = False
        try:
            out = sup.run(
                primary,
                fallback=None,
                pre=self._pre(index, lane),
                label="serve_dispatch",
            )
            dispatched_ok = True
        except BaseException as e:  # noqa: BLE001 — classified below
            cause = self._quarantine_cause(e)
            if cause is None:
                raise  # deterministic failure: the riders' problem
            self._quarantine_lane(lane, cause, trace)
            raise LaneQuarantined(lane, cause) from e
        finally:
            if self.saturation is not None:
                # busy is busy either way — a dispatch that hung to its
                # deadline occupied the chip; only a SUCCESS credits the
                # executable's flops to the MFU window
                self.saturation.record_dispatch(
                    lane, t_busy0, time.monotonic(), bucket=bucket,
                    counted=dispatched_ok,
                )
            if hasattr(trace, "device_busy_s"):
                # accumulate onto the chunk's OWN trace (requeued attempts
                # add up): the batcher's success path prorates the total
                # into the device-time ledger. hasattr-gated like
                # served_by_fallback — NULL_TRACE/TraceContext callers
                # must be neither written nor crashed on
                trace.device_busy_s += time.monotonic() - t_busy0
            if reg is not None:
                inflight_g.dec()
            with self._lock:
                if lane < len(self._lane_inflight):
                    self._lane_inflight[lane] -= 1
        with self._lock:
            if lane < len(self._lane_batches):
                self._lane_batches[lane] += 1
        if reg is not None:
            reg.counter(
                SERVING_LANE_BATCHES_TOTAL,
                help="device batches dispatched per replica lane",
                lane=str(lane),
            ).inc()
        return out

    def _run_degraded(self, pixels: np.ndarray, dims: np.ndarray, trace):
        """Every lane is quarantined: the one-way CPU fallback serves.

        Mirrors the PR-3 degraded contract exactly — correct-but-slower
        from host arrays, or an immediate ``DeadlineExceeded`` when the
        operator disabled the fallback (``--no-fallback-cpu``)."""
        if hasattr(trace, "served_by_fallback"):
            # the chunk ran on NO lane: flag it on the chunk's OWN trace
            # so the batcher's lane_batches credit agrees with
            # serving_lane_batches_total without re-reading `degraded`
            # after the dispatch (that read races a concurrent last-lane
            # quarantine and would miscount a chunk that DID run on a
            # lane). hasattr-gated: only ChunkTrace declares the slot —
            # a TraceContext or the shared NULL_TRACE singleton passed
            # directly to run_batch must be neither written nor crashed on
            trace.served_by_fallback = True
        if not self.res.fallback_cpu:
            raise DeadlineExceeded(
                f"all {self.lane_count} lanes quarantined "
                f"({self.degraded_cause}) and CPU fallback is disabled"
            )
        with trace.span("cpu_fallback"):
            return self._fallback_call()(pixels, dims)
