"""Online serving subsystem: the always-warm request path.

The batch drivers answer "process this cohort"; this package answers
"process whatever arrives, now" — the ROADMAP's heavy-traffic north star.
Four pieces, each alone testable:

* :mod:`~nm03_capstone_project_tpu.serving.queue` — bounded admission
  with load-shedding backpressure (:class:`AdmissionQueue`);
* :mod:`~nm03_capstone_project_tpu.serving.batcher` — dynamic request
  coalescing into padded, bucket-shaped batches
  (:class:`DynamicBatcher`);
* :mod:`~nm03_capstone_project_tpu.serving.executor` — one warm compiled
  executable per batch bucket and replica lane, each lane's dispatches
  supervised by its own PR-3
  :class:`~nm03_capstone_project_tpu.resilience.DispatchSupervisor`
  (:class:`WarmExecutor`);
* :mod:`~nm03_capstone_project_tpu.serving.lanes` — the per-lane fault
  domains (ISSUE 8): HEALTHY → QUARANTINED → PROBATION → HEALTHY, so one
  sick chip costs 1/N capacity, not the replica
  (:class:`LaneFaultDomains`);
* :mod:`~nm03_capstone_project_tpu.serving.server` — the stdlib HTTP
  front end (``nm03-serve``): ``POST /v1/segment``, ``/healthz``,
  ``/readyz``, ``/metrics``, SIGTERM graceful drain.

:mod:`~nm03_capstone_project_tpu.serving.loadgen` (``nm03-loadgen``)
closes the loop: a closed/open-loop generator whose p50/p95/p99 +
throughput report puts serving numbers in the bench evidence chain.
"""

from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher  # noqa: F401
from nm03_capstone_project_tpu.serving.executor import (  # noqa: F401
    DEFAULT_BUCKETS,
    WarmExecutor,
)
from nm03_capstone_project_tpu.serving.lanes import (  # noqa: F401
    LaneFaultDomains,
    LaneQuarantined,
)
from nm03_capstone_project_tpu.serving.metrics import (  # noqa: F401
    SERVING_BATCHES_TOTAL,
    SERVING_BATCH_SIZE,
    SERVING_DEGRADED,
    SERVING_INFLIGHT,
    SERVING_QUEUE_WAIT_SECONDS,
    SERVING_READY,
    SERVING_REQUESTS_TOTAL,
    SERVING_REQUEST_SECONDS,
    SERVING_SHED_TOTAL,
)
from nm03_capstone_project_tpu.serving.queue import (  # noqa: F401
    AdmissionQueue,
    QueueClosed,
    QueueFull,
    ServeRequest,
)
from nm03_capstone_project_tpu.serving.server import (  # noqa: F401
    RequestRejected,
    ServingApp,
    make_http_server,
    serve_in_thread,
)
from nm03_capstone_project_tpu.serving.volumes import (  # noqa: F401
    DEFAULT_VOLUME_DEPTH_BUCKETS,
    GangUnavailable,
    VolumeGang,
    VolumeRequest,
)
