"""Bounded admission queue with load-shedding backpressure.

The batch drivers process a *fixed* cohort: work arrives all at once and
backpressure is meaningless. An online service faces the opposite regime —
arrival rate is set by clients, not capacity — so admission control is the
first line of defense: a bounded queue that REJECTS at the door (HTTP 503 +
``Retry-After``) instead of buffering unboundedly and timing every request
out. Shedding early is the serving-systems orthodoxy (bounded queues in
front of batched accelerators; see PAPERS.md on continuous batching): a
request that cannot be served inside its latency budget is cheapest to
refuse before any work is spent on it.

jax-free and HTTP-free by design: this module is pure stdlib data
structure + policy, unit-testable without a backend or a socket.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Optional


class QueueFull(RuntimeError):
    """Admission refused: the bounded queue is at capacity (shed the load)."""


class QueueClosed(RuntimeError):
    """Admission refused: the server is draining (SIGTERM received)."""


@dataclass
class ServeRequest:
    """One in-flight segmentation request, from admission to response.

    ``pixels``/``dims`` are the decoded host-side inputs (the HTTP layer
    decodes before admission so a malformed body is a 400, never a wasted
    batch slot). The result travels back through ``done``: the batcher
    fills ``mask``/``converged``/``batch_size`` (or ``error``) and sets the
    event; the handler thread blocks on it with a timeout.
    """

    request_id: str
    pixels: object  # np.ndarray (h, w) float32, raw intensities
    dims: tuple  # (h, w)
    t_admitted: float = field(default_factory=time.monotonic)
    # request-scoped tracing (ISSUE 7): the obs.trace.TraceContext whose
    # trace id rode in on X-Nm03-Request-Id (or was minted at admission);
    # every hop appends its span here. None for trace-less callers (tests).
    trace: object = None
    # stamped by AdmissionQueue.get_batch when the batcher pops this
    # request — splits the queue_wait span from the coalesce span
    t_popped: float = 0.0
    # filled by the batcher
    mask: object = None  # np.ndarray (h, w) uint8, cropped to dims
    converged: bool = True
    batch_size: int = 0
    queue_wait_s: float = 0.0
    lane: Optional[int] = None  # the replica lane that served it
    # how many times this request's chunk was re-dispatched because its
    # lane quarantined mid-flight (ISSUE 8): 0 on the happy path; >0 means
    # the rider outlived a sick chip without ever seeing an error
    requeues: int = 0
    # a fleet probation canary (X-Nm03-Probe, ISSUE 14): served and traced
    # normally, but excluded from request metrics and SLO accounting —
    # the canary cadence must not pollute the series the SLO layer reads
    probe: bool = False
    # the rider's prorated device cost (ISSUE 16): its row's share of the
    # chunk's accumulated device-busy seconds, stamped by the batcher and
    # echoed in the response payload as `device_seconds`
    device_seconds: float = 0.0
    # content-addressed result-key digest (ISSUE 19): identical digests in
    # one batch window ride a single dispatch — the batcher elects a leader
    # and fans its mask out to the dup riders. None = dedup not in play.
    digest: Optional[str] = None
    error: Optional[BaseException] = None
    done: threading.Event = field(default_factory=threading.Event)

    @property
    def trace_id(self) -> Optional[str]:
        return self.trace.trace_id if self.trace is not None else None

    def fail(self, exc: BaseException) -> None:
        # nm03-lint: disable=NM331 release ordering via the Event: the write is sequenced before done.set(), and the waiter reads error only after wait() returns
        self.error = exc
        self.done.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self.done.wait(timeout_s)


class AdmissionQueue:
    """Bounded FIFO between the HTTP handler threads and the batcher.

    * ``put`` never blocks: at capacity it raises :class:`QueueFull`
      immediately (the handler turns that into 503 + ``Retry-After``) —
      queueing delay is bounded by construction, not by hope.
    * ``get_batch`` is the batcher's coalescing pop: it blocks for the
      first request, then keeps collecting until ``max_batch`` items are
      in hand or ``max_wait_s`` has elapsed since the first one — the
      dynamic-batching window.
    * ``close`` flips the queue into drain mode: every later ``put`` is
      refused with :class:`QueueClosed`, while ``get_batch`` keeps
      returning the already-admitted tail until empty (an admitted request
      is a promise; drain finishes it).
    """

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"queue capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._items: collections.deque = collections.deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = False

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def put(self, req: ServeRequest) -> None:
        with self._lock:
            if self._closed:
                raise QueueClosed("server is draining; not admitting")
            if len(self._items) >= self.capacity:
                raise QueueFull(
                    f"admission queue at capacity ({self.capacity})"
                )
            self._items.append(req)
            self._not_empty.notify()

    def get_batch(
        self,
        max_batch: int,
        max_wait_s: float,
        poll_s: float = 0.05,
    ) -> list:
        """Coalesce up to ``max_batch`` requests inside one wait window.

        Blocks (in ``poll_s`` slices, so ``close`` is noticed promptly) for
        the first request; once one is in hand, keeps popping until the
        batch is full or ``max_wait_s`` has passed since the first pop.
        Returns [] when the queue is closed AND empty — the batcher's exit
        signal.
        """
        def pop() -> ServeRequest:
            req = self._items.popleft()
            # the queue_wait/coalesce trace boundary: waited until HERE
            req.t_popped = time.monotonic()
            return req

        batch: list = []
        with self._not_empty:
            while not self._items:
                if self._closed:
                    return []
                self._not_empty.wait(timeout=poll_s)
            batch.append(pop())
            window_end = time.monotonic() + max_wait_s
            while len(batch) < max_batch:
                if self._items:
                    batch.append(pop())
                    continue
                remaining = window_end - time.monotonic()
                if remaining <= 0 or self._closed:
                    break
                self._not_empty.wait(timeout=min(remaining, poll_s))
        return batch

    def close(self) -> None:
        """Stop admissions; wake any batcher blocked on an empty queue."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()

    def drain_pending(self) -> list:
        """Pop everything (used on abort paths to fail pending requests)."""
        with self._lock:
            items = list(self._items)
            self._items.clear()
        return items
