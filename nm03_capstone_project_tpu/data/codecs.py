"""Compressed-pixel codecs for the DICOM importer (host-side, pure Python).

Closes the round-2 breadth gap vs the reference importer: FAST sits on DCMTK
(reference src/include/FAST/FAST_directives.hpp:30 via ``DICOMFileImporter``)
and reads compressed transfer syntaxes; dicomlite previously rejected them
all with transcode instructions. This module implements the two lossless
families that dominate medical archives — both bit-exact, so the decoded
float32 slice is identical to the uncompressed path:

* **RLE Lossless** (1.2.840.10008.1.2.5): the DICOM PackBits variant,
  PS3.5 §8.2.2 + Annex G — a 64-byte segment-offset header, one
  byte-plane segment per sample byte (MSB plane first), each PackBits
  run-length coded. Encoder + decoder (the encoder backs the writer's
  round-trip tests and ``write_dicom(..., transfer_syntax=RLE_LOSSLESS)``).

* **JPEG Lossless, Non-Hierarchical** (1.2.840.10008.1.2.4.57 and the
  first-order-prediction .70 that DCMTK emits by default): ITU-T T.81
  process 14, SOF3 — Huffman-coded prediction residuals, any selection
  value 1-7, point transform, 2-16 bit precision, single component.
  Decoder is general; the encoder emits selection value 1 (SV1), the .70
  profile.

Baseline 8-bit JPEG (1.2.840.10008.1.2.4.50, lossy) is handled in
dicomlite via PIL — re-implementing a lossy DCT decoder buys no exactness
and PIL ships in the image.

These run on the host IO path (decode feeds the host->HBM prefetch queue),
not on the TPU: entropy decoding is branchy byte-chasing, the exact shape
of work a systolic array cannot express. NumPy vectorization keeps the
byte-plane recomposition and prediction sweeps array-shaped.
"""

from __future__ import annotations

import struct

import numpy as np


class CodecError(ValueError):
    """Raised when a compressed pixel stream is malformed."""


# ---------------------------------------------------------------------------
# RLE Lossless (PS3.5 Annex G)
# ---------------------------------------------------------------------------


def packbits_decode(seg: bytes, expected: int) -> bytes:
    """Decode one PackBits-coded RLE segment to exactly ``expected`` bytes."""
    out = bytearray()
    i, n = 0, len(seg)
    while i < n and len(out) < expected:
        ctrl = seg[i]
        i += 1
        if ctrl < 128:  # literal run: copy next ctrl+1 bytes
            j = i + ctrl + 1
            if j > n:
                raise CodecError("RLE literal run overruns segment")
            out += seg[i:j]
            i = j
        elif ctrl > 128:  # replicate run: next byte repeated 257-ctrl times
            if i >= n:
                raise CodecError("RLE replicate run missing its byte")
            out += seg[i : i + 1] * (257 - ctrl)
            i += 1
        # ctrl == 128: no-op (spec: reserved, skip)
    if len(out) < expected:
        raise CodecError(f"RLE segment decoded {len(out)} bytes, expected {expected}")
    return bytes(out[:expected])


def packbits_encode(seg: bytes) -> bytes:
    """PackBits-encode one byte plane (replicate runs >= 3, literals else)."""
    out = bytearray()
    i, n = 0, len(seg)
    while i < n:
        run = 1
        while i + run < n and run < 128 and seg[i + run] == seg[i]:
            run += 1
        if run >= 3:
            out += bytes((257 - run, seg[i]))
            i += run
            continue
        # literal: extend until a >=3 replicate run starts (or 128 bytes)
        j = i + run
        while j < n and j - i < 128:
            r = 1
            while j + r < n and r < 3 and seg[j + r] == seg[j]:
                r += 1
            if r >= 3:
                break
            j += r
        j = min(j, i + 128)
        out += bytes((j - i - 1,)) + seg[i:j]
        i = j
    if len(out) % 2:
        out.append(0)  # segments are padded to even length (Annex G.3.1)
    return bytes(out)


def rle_decode_frame(frame: bytes, rows: int, cols: int, itemsize: int) -> np.ndarray:
    """Decode one RLE frame -> uint8/uint16 (rows, cols) array.

    Segments are byte planes of the composite pixel code, most-significant
    plane first (Annex G.2), so a 16-bit image recomposes as
    ``(plane0 << 8) | plane1``.
    """
    if len(frame) < 64:
        raise CodecError("RLE frame shorter than its 64-byte header")
    header = struct.unpack_from("<16I", frame, 0)
    nseg = header[0]
    if nseg != itemsize:
        raise CodecError(
            f"RLE frame has {nseg} segments, expected {itemsize} "
            "(one byte plane per sample byte, monochrome)"
        )
    offsets = list(header[1 : 1 + nseg])
    if any(o < 64 or o > len(frame) for o in offsets) or sorted(offsets) != offsets:
        raise CodecError(f"RLE segment offsets invalid: {offsets}")
    npix = rows * cols
    planes = []
    for i, off in enumerate(offsets):
        end = offsets[i + 1] if i + 1 < nseg else len(frame)
        planes.append(
            np.frombuffer(packbits_decode(frame[off:end], npix), np.uint8)
        )
    if itemsize == 1:
        return planes[0].reshape(rows, cols).copy()
    return (
        (planes[0].astype(np.uint16) << 8) | planes[1].astype(np.uint16)
    ).reshape(rows, cols)


def rle_encode_frame(pixels: np.ndarray) -> bytes:
    """Encode a uint8/uint16 (rows, cols) array as one RLE frame."""
    if pixels.dtype == np.uint16:
        flat = pixels.ravel()
        planes = [(flat >> 8).astype(np.uint8).tobytes(), (flat & 0xFF).astype(np.uint8).tobytes()]
    elif pixels.dtype == np.uint8:
        planes = [pixels.ravel().tobytes()]
    else:
        raise CodecError(f"RLE encoder expects uint8/uint16, got {pixels.dtype}")
    segs = [packbits_encode(p) for p in planes]
    offsets, pos = [], 64
    for s in segs:
        offsets.append(pos)
        pos += len(s)
    header = struct.pack(
        "<16I", len(segs), *offsets, *([0] * (15 - len(segs)))
    )
    return header + b"".join(segs)


# ---------------------------------------------------------------------------
# JPEG Lossless (ITU-T T.81 process 14, SOF3)
# ---------------------------------------------------------------------------

_SOI, _EOI, _SOF3, _DHT, _SOS = 0xD8, 0xD9, 0xC3, 0xC4, 0xDA


class _BitReader:
    """MSB-first bit reader over entropy-coded data with FF00 byte stuffing."""

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos
        self.bits = 0
        self.nbits = 0

    def read_bit(self) -> int:
        if self.nbits == 0:
            if self.pos >= len(self.buf):
                raise CodecError("JPEG entropy data truncated")
            b = self.buf[self.pos]
            self.pos += 1
            if b == 0xFF:
                if self.pos >= len(self.buf):
                    raise CodecError("JPEG entropy data truncated at FF")
                nxt = self.buf[self.pos]
                if nxt == 0x00:
                    self.pos += 1  # stuffed byte
                else:
                    # a real marker mid-scan (e.g. premature EOI)
                    raise CodecError(f"unexpected JPEG marker FF{nxt:02x} in scan")
            self.bits = b
            self.nbits = 8
        self.nbits -= 1
        return (self.bits >> self.nbits) & 1

    def read_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v


def _build_huffman(bits_counts, values):
    """Canonical Huffman -> {(length, code): value} (T.81 Annex C)."""
    table = {}
    code = 0
    k = 0
    for length in range(1, 17):
        for _ in range(bits_counts[length - 1]):
            table[(length, code)] = values[k]
            code += 1
            k += 1
        code <<= 1
    return table


def _huff_decode(reader: _BitReader, table) -> int:
    code, length = 0, 0
    while length < 16:
        code = (code << 1) | reader.read_bit()
        length += 1
        v = table.get((length, code))
        if v is not None:
            return v
    raise CodecError("invalid JPEG Huffman code")


def _extend(bits: int, ssss: int) -> int:
    """T.81 F.2.2.1: map SSSS magnitude bits to a signed difference."""
    if ssss == 0:
        return 0
    if ssss == 16:
        return 32768  # no magnitude bits follow (lossless-mode special case)
    if bits < (1 << (ssss - 1)):
        return bits - (1 << ssss) + 1
    return bits


def jpeg_lossless_decode(data: bytes, expect_shape=None) -> np.ndarray:
    """Decode a single-component lossless JPEG (SOF3) stream.

    Supports any predictor selection value 1-7, point transform, 2-16 bit
    precision; restart intervals are not supported (DCMTK does not emit them
    for single-frame medical images). Returns uint16 (rows, cols).

    ``expect_shape``: when the caller knows the frame dimensions (the DICOM
    header's Rows/Columns), a disagreeing SOF3 is rejected BEFORE the
    output allocates — a corrupt header must not drive a multi-GB
    ``np.zeros`` or a gigapixel decode loop.
    """
    if len(data) < 4 or data[0] != 0xFF or data[1] != _SOI:
        raise CodecError("not a JPEG stream (missing SOI)")
    pos = 2
    precision = rows = cols = None
    huff_tables: dict = {}
    sel = 1
    pt = 0
    table_id = 0
    got_sos = False
    while pos + 2 <= len(data):
        if data[pos] != 0xFF:
            raise CodecError(f"expected JPEG marker at {pos}")
        # optional fill bytes (T.81 B.1.1.2): extra 0xFF may pad any marker
        while pos + 1 < len(data) and data[pos + 1] == 0xFF:
            pos += 1
        if pos + 2 > len(data):
            raise CodecError("truncated JPEG marker segment")
        marker = data[pos + 1]
        pos += 2
        if marker == _EOI:
            break
        if pos + 2 > len(data):
            raise CodecError("truncated JPEG marker segment")
        seglen = struct.unpack_from(">H", data, pos)[0]
        seg_end = pos + seglen
        if seg_end > len(data):
            raise CodecError("truncated JPEG marker segment")
        body = data[pos + 2 : seg_end]
        if marker == _SOF3:
            if len(body) < 6:
                raise CodecError("short SOF3 segment")
            precision, rows, cols, ncomp = struct.unpack_from(">BHHB", body, 0)
            if ncomp != 1:
                raise CodecError(f"lossless JPEG: expected 1 component, got {ncomp}")
        elif marker in (0xC0, 0xC1, 0xC2, 0xC5, 0xC6, 0xC7, 0xC9, 0xCA, 0xCB):
            raise CodecError(
                f"JPEG SOF{marker - 0xC0} is not lossless process 14 (SOF3)"
            )
        elif marker == _DHT:
            b = 0
            while b < len(body):
                tc_th = body[b]
                counts = list(body[b + 1 : b + 17])
                nvals = sum(counts)
                if (
                    len(counts) < 16
                    or b + 17 + nvals > len(body)
                    or (tc_th >> 4) > 1
                    or (tc_th & 0x0F) > 3
                ):
                    # counts promising more values than the segment holds,
                    # or an out-of-range table class/id (T.81: Tc 0-1,
                    # Th 0-3; the C++ decoder rejects these — acceptance
                    # must agree across implementations)
                    raise CodecError("malformed DHT segment")
                vals = list(body[b + 17 : b + 17 + nvals])
                # key on (class, id): an AC-class table sharing a DC table's
                # destination id is legal T.81 and must not clobber it
                huff_tables[(tc_th >> 4, tc_th & 0x0F)] = _build_huffman(
                    counts, vals
                )
                b += 17 + nvals
        elif marker == _SOS:
            if len(body) < 6:  # ns(1) + 1 comp spec(2) + Ss/Se/AhAl(3)
                raise CodecError("short SOS segment")
            ns = body[0]
            if ns != 1:
                raise CodecError(f"expected 1 scan component, got {ns}")
            table_id = body[2] >> 4  # Td (DC table selects the lossless table)
            sel = body[1 + 2 * ns]  # Ss = predictor selection value
            pt = body[3 + 2 * ns] & 0x0F  # Al = point transform
            got_sos = True
            pos = seg_end
            break  # entropy-coded data follows
        pos = seg_end
    if precision is None or rows is None:
        raise CodecError("JPEG stream missing SOF3 header")
    if not got_sos:
        # without this a SOF3+DHT stream with no scan would decode trailing
        # bytes as entropy data under the default sel/table — an acceptance
        # divergence from the native decoder, which requires a scan header
        # (csrc/nm03native.cpp got_sos check)
        raise CodecError("JPEG stream missing SOS marker")
    if (0, table_id) not in huff_tables:  # lossless scans use DC-class tables
        raise CodecError(f"JPEG scan references undefined Huffman table {table_id}")
    if sel < 1 or sel > 7:
        raise CodecError(f"unsupported lossless predictor selection {sel}")
    if not (2 <= precision <= 16) or pt >= precision:
        # T.81 range; pt >= precision would make the default predictor's
        # shift count negative (a bare ValueError, not CodecError)
        raise CodecError(
            f"invalid JPEG precision/point-transform {precision}/{pt}"
        )
    if expect_shape is not None and (rows, cols) != tuple(expect_shape):
        raise CodecError(
            f"JPEG frame is ({rows}, {cols}), expected {tuple(expect_shape)}"
        )
    if rows <= 0 or cols <= 0 or rows > 32768 or cols > 32768:
        raise CodecError(f"implausible JPEG dimensions ({rows}, {cols})")

    table = huff_tables[(0, table_id)]
    reader = _BitReader(data, pos)
    out = np.zeros((rows, cols), np.int32)
    default = 1 << (precision - pt - 1)
    for y in range(rows):
        row = out[y]
        prev = out[y - 1] if y else None
        for x in range(cols):
            ssss = _huff_decode(reader, table)
            if ssss > 16:
                # DHT values are arbitrary bytes; >16 desyncs the bit
                # stream into silent garbage (C++ decoder has this guard)
                raise CodecError(f"invalid JPEG difference category {ssss}")
            diff = _extend(reader.read_bits(ssss) if 0 < ssss < 16 else 0, ssss)
            if y == 0:
                pred = default if x == 0 else row[x - 1]
            elif x == 0:
                pred = prev[0]
            elif sel == 1:
                pred = row[x - 1]
            elif sel == 2:
                pred = prev[x]
            elif sel == 3:
                pred = prev[x - 1]
            else:
                ra, rb, rc = int(row[x - 1]), int(prev[x]), int(prev[x - 1])
                if sel == 4:
                    pred = ra + rb - rc
                elif sel == 5:
                    pred = ra + ((rb - rc) >> 1)
                elif sel == 6:
                    pred = rb + ((ra - rc) >> 1)
                else:  # sel == 7
                    pred = (ra + rb) >> 1
            row[x] = (int(pred) + diff) & 0xFFFF
    return (out.astype(np.uint16) << pt)


# The encoder's one Huffman table: categories 0..16 all get 5-bit codes
# (17 <= 2^5, and the all-ones 5-bit code 0b11111 stays unused as T.81
# requires). Optimal coding is not the point — bit-exact round-trip is.
_ENC_BITS = [0, 0, 0, 0, 17] + [0] * 11
_ENC_VALUES = list(range(17))


def jpeg_lossless_encode(pixels: np.ndarray, precision: int = 16) -> bytes:
    """Encode uint16 (rows, cols) as lossless JPEG, process 14 SV1 (.70).

    Backs ``write_dicom(..., transfer_syntax=JPEG_LOSSLESS_SV1)`` and the
    importer round-trip tests; decodes bit-exactly with any T.81 process-14
    decoder (verified against our own general decoder).
    """
    if pixels.ndim != 2 or pixels.dtype != np.uint16:
        raise CodecError(f"encoder expects 2D uint16, got {pixels.dtype} {pixels.shape}")
    rows, cols = pixels.shape
    px = pixels.astype(np.int32)
    # SV1 prediction: left neighbour; first row predicts from above;
    # origin predicts the midpoint 2^(P-1)
    pred = np.empty_like(px)
    pred[:, 1:] = px[:, :-1]
    pred[1:, 0] = px[:-1, 0]
    pred[0, 0] = 1 << (precision - 1)
    diffs = (px - pred) & 0xFFFF  # modulo-2^16 difference arithmetic (T.81 H.1)

    out = bytearray(b"\xff\xd8")  # SOI
    sof = struct.pack(">BHHB", precision, rows, cols, 1) + bytes((1, 0x11, 0))
    out += b"\xff\xc3" + struct.pack(">H", len(sof) + 2) + sof
    dht = bytes((0x00,)) + bytes(_ENC_BITS) + bytes(_ENC_VALUES)
    out += b"\xff\xc4" + struct.pack(">H", len(dht) + 2) + dht
    sos = bytes((1, 1, 0x00, 1, 0, 0x00))  # 1 comp, Td=Ta=0, Ss=1(SV1), Se=0, Pt=0
    out += b"\xff\xda" + struct.pack(">H", len(sos) + 2) + sos

    acc, nacc = 0, 0
    body = bytearray()

    def put(value: int, nbits: int):
        nonlocal acc, nacc
        acc = (acc << nbits) | (value & ((1 << nbits) - 1))
        nacc += nbits
        while nacc >= 8:
            nacc -= 8
            byte = (acc >> nacc) & 0xFF
            body.append(byte)
            if byte == 0xFF:
                body.append(0x00)  # byte stuffing

    for d in diffs.ravel():
        d = int(d)
        if d >= 32768:
            d -= 65536  # back to signed [-32768, 32767]
        if d == -32768:
            put(16, 5)  # SSSS=16: diff 32768 == -32768 mod 2^16, no extra bits
            continue
        mag = abs(d)
        ssss = mag.bit_length()
        put(ssss, 5)
        if ssss:
            put(d if d > 0 else d - 1, ssss)  # negative: low bits of d-1
    if nacc:
        put(0x7F, 8 - nacc)  # final-byte padding is 1-bits (T.81 F.1.2.3)
    out += body + b"\xff\xd9"  # EOI
    return bytes(out)


# ---------------------------------------------------------------------------
# JPEG-LS (ITU-T T.87 / ISO 14495-1) — LOCO-I decoder
# ---------------------------------------------------------------------------
# Closes the round-3 importer-breadth gap for the DICOM transfer syntaxes
# 1.2.840.10008.1.2.4.80 (JPEG-LS Lossless) and .81 (near-lossless), which
# the reference reads through DCMTK (FAST_directives.hpp:30 contract).
# From-scratch implementation of the decoder: marker parse (SOF55/LSE/SOS),
# MED prediction with 365-context bias-corrected Golomb residuals, and
# run mode with run-interruption contexts. Conformance is pinned against
# CharLS-encoded streams (tests/golden/jpegls/, an independent codec), not
# against an encoder in this repo. Single component, interleave none — the
# single-frame grayscale envelope the importer serves.

_SOF55, _LSE = 0xF7, 0xF8
# run-length code order table J (T.87 A.2.1)
_JLS_J = [0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3,
          4, 4, 5, 5, 6, 6, 7, 7, 8, 9, 10, 11, 12, 13, 14, 15]


class _JlsBitReader:
    """MSB-first bit reader with T.87 marker-byte stuffing.

    After an 0xFF byte, the following byte carries only 7 data bits (its MSB
    is a stuffed 0); an 0xFF followed by a byte >= 0x80 is a marker and
    terminates the entropy segment — reading past it is a truncation error,
    never a hang.
    """

    __slots__ = ("data", "pos", "cache", "nbits", "prev_ff")

    def __init__(self, data: bytes, pos: int):
        self.data = data
        self.pos = pos
        self.cache = 0
        self.nbits = 0
        self.prev_ff = False

    def _fill(self) -> None:
        if self.pos >= len(self.data):
            raise CodecError("truncated JPEG-LS entropy stream")
        b = self.data[self.pos]
        if self.prev_ff:
            if b >= 0x80:  # marker: no more entropy data exists
                raise CodecError("truncated JPEG-LS entropy stream (marker)")
            # a stuffed byte is < 0x80 by construction, so it can never
            # itself re-arm the stuffing state
            self.pos += 1
            self.cache = (self.cache << 7) | b
            self.nbits += 7
            self.prev_ff = False
        else:
            self.pos += 1
            self.cache = (self.cache << 8) | b
            self.nbits += 8
            self.prev_ff = b == 0xFF

    def read_bit(self) -> int:
        if self.nbits == 0:
            self._fill()
        self.nbits -= 1
        bit = (self.cache >> self.nbits) & 1
        # mask the consumed bit out so run-mode streams (which only ever
        # call read_bit) can't grow the cache int without bound — an
        # unmasked cache makes each read O(stream size)
        self.cache &= (1 << self.nbits) - 1
        return bit

    def read_bits(self, n: int) -> int:
        while self.nbits < n:
            self._fill()
        self.nbits -= n
        val = (self.cache >> self.nbits) & ((1 << n) - 1)
        self.cache &= (1 << self.nbits) - 1
        return val

    def read_zero_run(self, cap: int) -> int:
        """Count 0 bits until the terminating 1 (consumed); error past cap."""
        z = 0
        while True:
            if self.read_bit():
                return z
            z += 1
            if z > cap:
                # corrupt streams must not degenerate into scanning the
                # whole buffer bit by bit
                raise CodecError("JPEG-LS Golomb prefix exceeds code limit")


def _jls_default_thresholds(maxval: int, near: int):
    """Default T1/T2/T3/RESET (T.87 C.2.4.1.1.1)."""

    def clamp(i, j):
        return j if (i > maxval or i < j) else i

    if maxval >= 128:
        factor = (min(maxval, 4095) + 128) // 256
        t1 = clamp(factor * (3 - 2) + 2 + 3 * near, near + 1)
        t2 = clamp(factor * (7 - 3) + 3 + 5 * near, t1)
        t3 = clamp(factor * (21 - 4) + 4 + 7 * near, t2)
    else:
        factor = 256 // (maxval + 1)
        t1 = clamp(max(2, 3 // factor + 3 * near), near + 1)
        t2 = clamp(max(3, 7 // factor + 5 * near), t1)
        t3 = clamp(max(4, 21 // factor + 7 * near), t2)
    return t1, t2, t3, 64


def _jls_parse_header(data: bytes):
    """Parse SOI..SOS; returns frame/coding parameters + entropy offset."""
    if len(data) < 4 or data[0] != 0xFF or data[1] != _SOI:
        raise CodecError("not a JPEG-LS stream (missing SOI)")
    pos = 2
    precision = rows = cols = None
    maxval = t1 = t2 = t3 = reset = None
    near = 0
    while pos + 2 <= len(data):
        if data[pos] != 0xFF:
            raise CodecError(f"expected JPEG-LS marker at {pos}")
        # optional fill bytes (T.81 B.1.1.2, inherited by T.87): any number
        # of extra 0xFF may pad before the marker code
        while pos + 1 < len(data) and data[pos + 1] == 0xFF:
            pos += 1
        if pos + 2 > len(data):
            raise CodecError("truncated JPEG-LS marker segment")
        marker = data[pos + 1]
        pos += 2
        if marker == _EOI:
            break
        if pos + 2 > len(data):
            raise CodecError("truncated JPEG-LS marker segment")
        seglen = struct.unpack_from(">H", data, pos)[0]
        seg_end = pos + seglen
        if seglen < 2 or seg_end > len(data):
            raise CodecError("truncated JPEG-LS marker segment")
        body = data[pos + 2 : seg_end]
        if marker == _SOF55:
            if len(body) < 6:
                raise CodecError("short SOF55 segment")
            precision, rows, cols, ncomp = struct.unpack_from(">BHHB", body, 0)
            if ncomp != 1:
                raise CodecError(
                    f"JPEG-LS: expected 1 component, got {ncomp} "
                    "(interleaved color is out of the importer envelope)"
                )
        elif marker in (0xC0, 0xC1, 0xC2, 0xC3, 0xC5, 0xC6, 0xC7, 0xC9,
                        0xCA, 0xCB):
            raise CodecError(f"SOF{marker - 0xC0} is not JPEG-LS (SOF55)")
        elif marker == _LSE:
            if len(body) < 1:
                raise CodecError("empty LSE segment")
            if body[0] == 1:
                if len(body) < 11:
                    raise CodecError("short LSE preset-parameters segment")
                maxval, t1, t2, t3, reset = struct.unpack_from(">HHHHH", body, 1)
            else:
                raise CodecError(
                    f"LSE id {body[0]} (mapping tables / oversize) unsupported"
                )
        elif marker == 0xDD:
            raise CodecError("JPEG-LS restart intervals unsupported")
        elif marker == _SOS:
            if len(body) < 6:
                raise CodecError("short JPEG-LS SOS segment")
            ns = body[0]
            if ns != 1:
                raise CodecError(f"expected 1 scan component, got {ns}")
            if body[2] != 0:
                raise CodecError("JPEG-LS mapping tables unsupported")
            near = body[1 + 2 * ns]
            ilv = body[2 + 2 * ns]
            al = body[3 + 2 * ns] & 0x0F
            if ilv != 0:
                raise CodecError(f"JPEG-LS interleave mode {ilv} unsupported")
            if al != 0:
                raise CodecError("JPEG-LS point transform unsupported")
            if precision is None:
                raise CodecError("JPEG-LS SOS before SOF55")
            return {
                "precision": precision,
                "rows": rows,
                "cols": cols,
                "near": near,
                "maxval": maxval,
                "t1": t1,
                "t2": t2,
                "t3": t3,
                "reset": reset,
                "entropy_at": seg_end,
            }
        pos = seg_end
    raise CodecError("JPEG-LS stream missing " +
                     ("SOS marker" if precision is not None else "SOF55 header"))


def jpegls_decode(data: bytes, expect_shape=None) -> np.ndarray:
    """Decode a single-component JPEG-LS (T.87) stream -> uint16 (rows, cols).

    Lossless and near-lossless (the DICOM .80/.81 syntaxes), default or
    LSE-preset coding parameters, 2-16 bit precision. ``expect_shape``
    rejects a disagreeing frame header before the output allocates, like
    jpeg_lossless_decode.
    """
    h = _jls_parse_header(data)
    precision, rows, cols = h["precision"], h["rows"], h["cols"]
    near = h["near"]
    if not (2 <= precision <= 16):
        raise CodecError(f"invalid JPEG-LS precision {precision}")
    if expect_shape is not None and (rows, cols) != tuple(expect_shape):
        raise CodecError(
            f"JPEG-LS frame is ({rows}, {cols}), expected {tuple(expect_shape)}"
        )
    if rows <= 0 or cols <= 0 or rows > 32768 or cols > 32768:
        raise CodecError(f"implausible JPEG-LS dimensions ({rows}, {cols})")

    maxval = h["maxval"] if h["maxval"] else (1 << precision) - 1
    if not (0 < maxval < (1 << precision)):
        raise CodecError(f"invalid JPEG-LS MAXVAL {maxval}")
    if near < 0 or near > min(255, maxval // 2):
        raise CodecError(f"invalid JPEG-LS NEAR {near}")
    dt1, dt2, dt3, dreset = _jls_default_thresholds(maxval, near)
    t1 = h["t1"] or dt1
    t2 = h["t2"] or dt2
    t3 = h["t3"] or dt3
    reset = h["reset"] or dreset
    if not (near + 1 <= t1 <= t2 <= t3 <= maxval):
        raise CodecError(f"invalid JPEG-LS thresholds {t1}/{t2}/{t3}")
    if not (3 <= reset <= max(255, maxval)):
        # T.87 C.2.4.1.1 range; an unbounded RESET would also let the
        # context accumulators grow past int32 in the native mirror
        raise CodecError(f"invalid JPEG-LS RESET {reset}")

    # derived coding parameters (T.87 A.2.1 / C.2.4.1)
    range_ = (maxval + 2 * near) // (2 * near + 1) + 1
    qbpp = max(1, (range_ - 1).bit_length())
    bpp = max(2, (maxval).bit_length())
    limit = 2 * (bpp + max(8, bpp))
    quant_step = 2 * near + 1
    range_step = range_ * quant_step

    # context state: 365 regular contexts + 2 run-interruption contexts
    a_init = max(2, (range_ + 32) >> 6)
    A = [a_init] * 365
    B = [0] * 365
    C = [0] * 365
    N = [1] * 365
    rA = [a_init, a_init]
    rN = [1, 1]
    rNn = [0, 0]
    run_index = 0

    def quantize(d):
        if d <= -t3:
            return -4
        if d <= -t2:
            return -3
        if d <= -t1:
            return -2
        if d < -near:
            return -1
        if d <= near:
            return 0
        if d < t1:
            return 1
        if d < t2:
            return 2
        if d < t3:
            return 3
        return 4

    reader = _JlsBitReader(data, h["entropy_at"])

    def decode_value(k, lim):
        z = reader.read_zero_run(lim)
        if z >= lim - qbpp - 1:
            return reader.read_bits(qbpp) + 1
        if k == 0:
            return z
        return (z << k) | reader.read_bits(k)

    def fix_reconstructed(v):
        # wrap into [-NEAR, MAXVAL+NEAR] then clamp (T.87 A.4.5 decoder side)
        if v < -near:
            v += range_step
        elif v > maxval + near:
            v -= range_step
        return 0 if v < 0 else (maxval if v > maxval else v)

    def decode_run_interruption_error(ctx):
        temp = rA[ctx] + ((rN[ctx] >> 1) if ctx else 0)
        n = rN[ctx]
        k = 0
        while (n << k) < temp:
            k += 1
            if k > 32:
                raise CodecError("JPEG-LS run-interruption k overflow")
        em = decode_value(k, limit - _JLS_J[run_index] - 1)
        # unmap (inverse of T.87 A.7.2.1 mapping; ctx == RItype): the error
        # is negative exactly when the map bit agrees with the sign
        # predictor (k != 0 or run of negatives dominating)
        tv = em + ctx
        map_bit = tv & 1
        eabs = (tv + map_bit) >> 1
        predict_neg = k != 0 or 2 * rNn[ctx] >= n
        err = -eabs if predict_neg == bool(map_bit) else eabs
        if err < 0:
            rNn[ctx] += 1
        rA[ctx] += (em + 1 - ctx) >> 1
        if rN[ctx] == reset:
            rA[ctx] >>= 1
            rN[ctx] >>= 1
            rNn[ctx] >>= 1
        rN[ctx] += 1
        return err

    out = np.zeros((rows, cols), np.int32)
    # rows padded with a virtual left/right edge (1-indexed real samples)
    prev = [0] * (cols + 2)
    cur = [0] * (cols + 2)
    for y in range(rows):
        # edge initialization: left virtual sample = sample above; the
        # previous row's right edge duplicates its last sample
        prev[cols + 1] = prev[cols]
        cur[0] = prev[1]
        x = 1
        while x <= cols:
            ra = cur[x - 1]
            rb = prev[x]
            rc = prev[x - 1]
            rd = prev[x + 1]
            q1 = quantize(rd - rb)
            q2 = quantize(rb - rc)
            q3 = quantize(rc - ra)
            if q1 == 0 and q2 == 0 and q3 == 0:
                # ---- run mode (T.87 A.7) ----
                remaining = cols - x + 1
                count = 0
                broke_on_zero = True
                while True:
                    if count == remaining:
                        broke_on_zero = False
                        break
                    if not reader.read_bit():
                        break
                    seg = 1 << _JLS_J[run_index]
                    take = min(seg, remaining - count)
                    count += take
                    if take == seg and run_index < 31:
                        run_index += 1
                    if count == remaining:
                        broke_on_zero = False
                        break
                if broke_on_zero:
                    j = _JLS_J[run_index]
                    if j:
                        count += reader.read_bits(j)
                    if count >= remaining:
                        raise CodecError("JPEG-LS run overruns the line")
                for i in range(count):
                    cur[x + i] = ra
                x += count
                if not broke_on_zero:
                    continue  # run reached end of line; no interruption sample
                # run-interruption sample (T.87 A.7.2)
                rb = prev[x]
                ritype = 1 if abs(ra - rb) <= near else 0
                err = decode_run_interruption_error(ritype)
                if ritype:
                    rx = fix_reconstructed(ra + err * quant_step)
                else:
                    sign = -1 if rb < ra else 1
                    rx = fix_reconstructed(rb + sign * err * quant_step)
                cur[x] = rx
                x += 1
                if run_index > 0:
                    run_index -= 1
                continue
            # ---- regular mode (T.87 A.4-A.6) ----
            qs = 81 * q1 + 9 * q2 + q3
            if qs < 0:
                sign = -1
                qi = -qs
            else:
                sign = 1
                qi = qs
            # MED predictor + bias correction
            if rc >= max(ra, rb):
                px = min(ra, rb)
            elif rc <= min(ra, rb):
                px = max(ra, rb)
            else:
                px = ra + rb - rc
            px += C[qi] if sign > 0 else -C[qi]
            px = 0 if px < 0 else (maxval if px > maxval else px)
            a = A[qi]
            n = N[qi]
            k = 0
            while (n << k) < a:
                k += 1
                if k > 32:
                    raise CodecError("JPEG-LS Golomb k overflow")
            m = decode_value(k, limit)
            err = (m >> 1) if (m & 1) == 0 else -((m + 1) >> 1)
            if k == 0 and near == 0 and 2 * B[qi] <= -n:
                err = -err - 1  # bias-inverted mapping (T.87 A.5.2)
            # context update with the quantized error (A.6)
            B[qi] += err * quant_step
            A[qi] += err if err >= 0 else -err
            if n == reset:
                A[qi] >>= 1
                B[qi] = B[qi] >> 1
                N[qi] = n >> 1
            N[qi] += 1
            n = N[qi]
            if B[qi] + n <= 0:
                B[qi] += n
                if B[qi] <= -n:
                    B[qi] = -n + 1
                if C[qi] > -128:
                    C[qi] -= 1
            elif B[qi] > 0:
                B[qi] -= n
                if B[qi] > 0:
                    B[qi] = 0
                if C[qi] < 127:
                    C[qi] += 1
            cur[x] = fix_reconstructed(px + sign * err * quant_step)
            x += 1
        out[y] = cur[1 : cols + 1]
        prev, cur = cur, prev
    # the scan must terminate with EOI (acceptance agreement with CharLS and
    # the native decoder); unread bits of the current byte are padding, and
    # fill 0xFF bytes may pad before the marker (T.81 B.1.1.2)
    p = reader.pos
    if reader.prev_ff and p < len(data) and data[p] < 0x80:
        # the byte stuffed after a final 0xFF data byte may carry only
        # padding bits the scan never consumed (our encoder and CharLS
        # both emit it); step over it before expecting the marker
        p += 1
    if not reader.prev_ff and (p >= len(data) or data[p] != 0xFF):
        raise CodecError("JPEG-LS stream missing EOI after scan")
    while p < len(data) and data[p] == 0xFF:
        p += 1
    if p >= len(data) or data[p] != _EOI:
        raise CodecError("JPEG-LS stream missing EOI after scan")
    return out.astype(np.uint16)


class _JlsBitWriter:
    """MSB-first bit writer with T.87 marker-byte stuffing (the encoder
    mirror of :class:`_JlsBitReader`): after an emitted 0xFF byte the next
    byte carries only 7 data bits, its MSB a stuffed 0."""

    __slots__ = ("out", "cur", "room", "width")

    def __init__(self):
        self.out = bytearray()
        self.cur = 0
        self.room = 8
        self.width = 8

    def put_bit(self, b: int) -> None:
        self.cur = (self.cur << 1) | b
        self.room -= 1
        if self.room == 0:
            self.out.append(self.cur)
            self.width = 7 if self.cur == 0xFF else 8
            self.cur = 0
            self.room = self.width

    def put_bits(self, val: int, n: int) -> None:
        for i in range(n - 1, -1, -1):
            self.put_bit((val >> i) & 1)

    def put_zeros(self, n: int) -> None:
        for _ in range(n):
            self.put_bit(0)

    def flush(self) -> bytes:
        if self.room < self.width:  # partial byte: pad with 0 bits
            self.out.append(self.cur << self.room)
        if self.out and self.out[-1] == 0xFF:
            # a trailing 0xFF data byte must be followed by its stuffed
            # byte even when it carries only padding — CharLS's decoder
            # refuses the marker in that position (its bit reader fills
            # ahead), and T.87's stuffing makes the 0x00 unambiguous
            self.out.append(0x00)
        return bytes(self.out)


def jpegls_encode(
    image: np.ndarray, precision: int | None = None, near: int = 0
) -> bytes:
    """Encode a 2D uint8/uint16 array as JPEG-LS (ITU-T T.87).

    The encoder mirror of :func:`jpegls_decode` — single component, default
    thresholds, no interleave/point-transform, the exact envelope both
    in-tree readers (and CharLS) accept; used by
    ``write_dicom(..., transfer_syntax=JPEG_LS_LOSSLESS / JPEG_LS_NEAR)``.
    ``near=0`` (lossless) round trips bit-exactly through
    :func:`jpegls_decode`, the native reader and CharLS; ``near>0``
    (near-lossless, the DICOM .81 syntax) reconstructs within ±near of the
    source, and all three decoders produce the IDENTICAL reconstruction
    (pinned in tests/test_jpegls.py) — the encoder tracks the reconstructed
    plane, not the source, exactly as T.87 requires.

    ``precision``: sample precision P (2-16); default derives the minimum
    from the data. DICOM callers must pass their BitsStored (PS3.5 A.4.3
    requires the codestream precision to match it — see write_dicom).
    """
    img = np.asarray(image)
    if img.ndim != 2:
        raise ValueError(f"expected 2D image, got {img.shape}")
    if img.dtype not in (np.uint8, np.uint16):
        raise ValueError(f"expected uint8/uint16, got {img.dtype}")
    rows, cols = img.shape
    if rows == 0 or cols == 0 or rows > 32768 or cols > 32768:
        raise ValueError(f"bad JPEG-LS dimensions ({rows}, {cols})")
    vmax = int(img.max())
    if precision is None:
        precision = max(2, vmax.bit_length())
    elif not (2 <= precision <= 16) or vmax >= (1 << precision):
        raise ValueError(
            f"precision {precision} invalid or too small for max {vmax}"
        )
    maxval = (1 << precision) - 1
    if not 0 <= near <= min(255, maxval // 2):
        raise ValueError(f"NEAR {near} outside [0, min(255, maxval//2)]")

    t1, t2, t3, reset = _jls_default_thresholds(maxval, near)
    quant_step = 2 * near + 1
    range_ = (maxval + 2 * near) // quant_step + 1
    range_step = range_ * quant_step
    qbpp = max(1, (range_ - 1).bit_length())
    bpp = max(2, maxval.bit_length())
    limit = 2 * (bpp + max(8, bpp))
    half_range = (range_ + 1) >> 1

    def fix_reconstructed(v):
        # wrap into [-NEAR, MAXVAL+NEAR] then clamp — the decoder's A.4.5
        if v < -near:
            v += range_step
        elif v > maxval + near:
            v -= range_step
        return 0 if v < 0 else (maxval if v > maxval else v)

    def quantize_err(e):
        # A.4.4: quantize the prediction error to the near-lossless grid
        if e > 0:
            return (near + e) // quant_step
        return -((near - e) // quant_step)

    # header: SOI, SOF55, SOS (defaults need no LSE)
    head = bytearray()
    head += b"\xff" + bytes([_SOI])
    head += b"\xff" + bytes([_SOF55])
    head += struct.pack(">HBHHB", 2 + 1 + 2 + 2 + 1 + 3, precision, rows,
                        cols, 1)
    head += bytes([1, 0x11, 0])  # component 1, 1x1 sampling, no Tq
    head += b"\xff" + bytes([_SOS])
    head += struct.pack(">HB", 2 + 1 + 2 + 3, 1)
    head += bytes([1, 0])  # component 1, no mapping table
    head += bytes([near, 0, 0])  # NEAR, ILV=0, Al/Ah=0

    # context state — identical initialization to the decoder
    a_init = max(2, (range_ + 32) >> 6)
    A = [a_init] * 365
    B = [0] * 365
    C = [0] * 365
    N = [1] * 365
    rA = [a_init, a_init]
    rN = [1, 1]
    rNn = [0, 0]
    run_index = 0

    def quantize(d):
        if d <= -t3:
            return -4
        if d <= -t2:
            return -3
        if d <= -t1:
            return -2
        if d < -near:
            return -1
        if d <= near:
            return 0
        if d < t1:
            return 1
        if d < t2:
            return 2
        if d < t3:
            return 3
        return 4

    w = _JlsBitWriter()

    def encode_value(m, k, lim):
        # inverse of the decoder's decode_value: Golomb prefix + remainder,
        # escape to qbpp raw bits past the length limit
        hi = m >> k
        if hi < lim - qbpp - 1:
            w.put_zeros(hi)
            w.put_bit(1)
            if k:
                w.put_bits(m & ((1 << k) - 1), k)
        else:
            w.put_zeros(lim - qbpp - 1)
            w.put_bit(1)
            w.put_bits(m - 1, qbpp)

    def encode_run_interruption(ritype, ix, ra, rb):
        # T.87 A.7.2; returns the RECONSTRUCTED sample value
        if ritype:
            err = ix - ra
            sign = 1
        else:
            sign = -1 if rb < ra else 1
            err = (ix - rb) * sign
        err = quantize_err(err)
        if err < 0:
            err += range_
        if err >= half_range:
            err -= range_
        temp = rA[ritype] + ((rN[ritype] >> 1) if ritype else 0)
        n = rN[ritype]
        k = 0
        while (n << k) < temp:
            k += 1
        # A.7.2.1 error mapping
        if k == 0 and err > 0 and 2 * rNn[ritype] < n:
            emap = 1
        elif err < 0 and 2 * rNn[ritype] >= n:
            emap = 1
        elif err < 0 and k != 0:
            emap = 1
        else:
            emap = 0
        em = 2 * abs(err) - ritype - emap
        encode_value(em, k, limit - _JLS_J[run_index] - 1)
        if err < 0:
            rNn[ritype] += 1
        rA[ritype] += (em + 1 - ritype) >> 1
        if rN[ritype] == reset:
            rA[ritype] >>= 1
            rN[ritype] >>= 1
            rNn[ritype] >>= 1
        rN[ritype] += 1
        if ritype:
            return fix_reconstructed(ra + err * quant_step)
        return fix_reconstructed(rb + sign * err * quant_step)

    src = img.astype(np.int32)
    prev = [0] * (cols + 2)
    cur = [0] * (cols + 2)
    for y in range(rows):
        prev[cols + 1] = prev[cols]
        cur[0] = prev[1]
        line = src[y].tolist()
        # `cur` holds the RECONSTRUCTED row, built incrementally — at
        # near=0 it equals the source; at near>0 context modeling and run
        # detection must see what the decoder will see
        x = 1
        while x <= cols:
            ra = cur[x - 1]
            rb = prev[x]
            rc = prev[x - 1]
            rd = prev[x + 1]
            q1 = quantize(rd - rb)
            q2 = quantize(rb - rc)
            q3 = quantize(rc - ra)
            if q1 == 0 and q2 == 0 and q3 == 0:
                # ---- run mode (T.87 A.7.1) ----
                remaining = cols - x + 1
                run_len = 0
                while (
                    run_len < remaining
                    and abs(line[x + run_len - 1] - ra) <= near
                ):
                    cur[x + run_len] = ra  # run samples reconstruct to Ra
                    run_len += 1
                hit_eol = run_len == remaining
                count = run_len  # the segment loop consumes this copy
                while count >= (1 << _JLS_J[run_index]):
                    w.put_bit(1)
                    count -= 1 << _JLS_J[run_index]
                    if run_index < 31:
                        run_index += 1
                if hit_eol:
                    if count > 0:
                        w.put_bit(1)
                    x += run_len
                    continue
                w.put_bit(0)
                j = _JLS_J[run_index]
                if j:
                    w.put_bits(count, j)
                x += run_len
                # run-interruption sample (the one that broke the run)
                ra = cur[x - 1]
                rb = prev[x]
                ritype = 1 if abs(ra - rb) <= near else 0
                cur[x] = encode_run_interruption(ritype, line[x - 1], ra, rb)
                x += 1
                if run_index > 0:
                    run_index -= 1
                continue
            # ---- regular mode (T.87 A.4-A.6) ----
            qs = 81 * q1 + 9 * q2 + q3
            if qs < 0:
                sign = -1
                qi = -qs
            else:
                sign = 1
                qi = qs
            if rc >= max(ra, rb):
                px = min(ra, rb)
            elif rc <= min(ra, rb):
                px = max(ra, rb)
            else:
                px = ra + rb - rc
            px += C[qi] if sign > 0 else -C[qi]
            px = 0 if px < 0 else (maxval if px > maxval else px)
            err = line[x - 1] - px
            if sign < 0:
                err = -err
            err = quantize_err(err)
            # modulo reduction (A.4.5): the decoder's fix_reconstructed
            # undoes the wrap
            if err < 0:
                err += range_
            if err >= half_range:
                err -= range_
            a = A[qi]
            n = N[qi]
            k = 0
            while (n << k) < a:
                k += 1
            # bias-inverted mapping is its own inverse (A.5.2/A.5.3);
            # lossless-only, exactly like the decoder's condition
            e = (
                (-err - 1)
                if (k == 0 and near == 0 and 2 * B[qi] <= -n)
                else err
            )
            m = 2 * e if e >= 0 else -2 * e - 1
            encode_value(m, k, limit)
            # context update with the REAL error — identical to the decoder
            B[qi] += err * quant_step
            A[qi] += err if err >= 0 else -err
            if n == reset:
                A[qi] >>= 1
                B[qi] = B[qi] >> 1
                N[qi] = n >> 1
            N[qi] += 1
            n = N[qi]
            if B[qi] + n <= 0:
                B[qi] += n
                if B[qi] <= -n:
                    B[qi] = -n + 1
                if C[qi] > -128:
                    C[qi] -= 1
            elif B[qi] > 0:
                B[qi] -= n
                if B[qi] > 0:
                    B[qi] = 0
                if C[qi] < 127:
                    C[qi] += 1
            cur[x] = fix_reconstructed(px + sign * err * quant_step)
            x += 1
        prev, cur = cur, prev
    body = w.flush()
    return bytes(head) + body + b"\xff" + bytes([_EOI])
