"""Compressed-pixel codecs for the DICOM importer (host-side, pure Python).

Closes the round-2 breadth gap vs the reference importer: FAST sits on DCMTK
(reference src/include/FAST/FAST_directives.hpp:30 via ``DICOMFileImporter``)
and reads compressed transfer syntaxes; dicomlite previously rejected them
all with transcode instructions. This module implements the two lossless
families that dominate medical archives — both bit-exact, so the decoded
float32 slice is identical to the uncompressed path:

* **RLE Lossless** (1.2.840.10008.1.2.5): the DICOM PackBits variant,
  PS3.5 §8.2.2 + Annex G — a 64-byte segment-offset header, one
  byte-plane segment per sample byte (MSB plane first), each PackBits
  run-length coded. Encoder + decoder (the encoder backs the writer's
  round-trip tests and ``write_dicom(..., transfer_syntax=RLE_LOSSLESS)``).

* **JPEG Lossless, Non-Hierarchical** (1.2.840.10008.1.2.4.57 and the
  first-order-prediction .70 that DCMTK emits by default): ITU-T T.81
  process 14, SOF3 — Huffman-coded prediction residuals, any selection
  value 1-7, point transform, 2-16 bit precision, single component.
  Decoder is general; the encoder emits selection value 1 (SV1), the .70
  profile.

Baseline 8-bit JPEG (1.2.840.10008.1.2.4.50, lossy) is handled in
dicomlite via PIL — re-implementing a lossy DCT decoder buys no exactness
and PIL ships in the image.

These run on the host IO path (decode feeds the host->HBM prefetch queue),
not on the TPU: entropy decoding is branchy byte-chasing, the exact shape
of work a systolic array cannot express. NumPy vectorization keeps the
byte-plane recomposition and prediction sweeps array-shaped.
"""

from __future__ import annotations

import struct

import numpy as np


class CodecError(ValueError):
    """Raised when a compressed pixel stream is malformed."""


# ---------------------------------------------------------------------------
# RLE Lossless (PS3.5 Annex G)
# ---------------------------------------------------------------------------


def packbits_decode(seg: bytes, expected: int) -> bytes:
    """Decode one PackBits-coded RLE segment to exactly ``expected`` bytes."""
    out = bytearray()
    i, n = 0, len(seg)
    while i < n and len(out) < expected:
        ctrl = seg[i]
        i += 1
        if ctrl < 128:  # literal run: copy next ctrl+1 bytes
            j = i + ctrl + 1
            if j > n:
                raise CodecError("RLE literal run overruns segment")
            out += seg[i:j]
            i = j
        elif ctrl > 128:  # replicate run: next byte repeated 257-ctrl times
            if i >= n:
                raise CodecError("RLE replicate run missing its byte")
            out += seg[i : i + 1] * (257 - ctrl)
            i += 1
        # ctrl == 128: no-op (spec: reserved, skip)
    if len(out) < expected:
        raise CodecError(f"RLE segment decoded {len(out)} bytes, expected {expected}")
    return bytes(out[:expected])


def packbits_encode(seg: bytes) -> bytes:
    """PackBits-encode one byte plane (replicate runs >= 3, literals else)."""
    out = bytearray()
    i, n = 0, len(seg)
    while i < n:
        run = 1
        while i + run < n and run < 128 and seg[i + run] == seg[i]:
            run += 1
        if run >= 3:
            out += bytes((257 - run, seg[i]))
            i += run
            continue
        # literal: extend until a >=3 replicate run starts (or 128 bytes)
        j = i + run
        while j < n and j - i < 128:
            r = 1
            while j + r < n and r < 3 and seg[j + r] == seg[j]:
                r += 1
            if r >= 3:
                break
            j += r
        j = min(j, i + 128)
        out += bytes((j - i - 1,)) + seg[i:j]
        i = j
    if len(out) % 2:
        out.append(0)  # segments are padded to even length (Annex G.3.1)
    return bytes(out)


def rle_decode_frame(frame: bytes, rows: int, cols: int, itemsize: int) -> np.ndarray:
    """Decode one RLE frame -> uint8/uint16 (rows, cols) array.

    Segments are byte planes of the composite pixel code, most-significant
    plane first (Annex G.2), so a 16-bit image recomposes as
    ``(plane0 << 8) | plane1``.
    """
    if len(frame) < 64:
        raise CodecError("RLE frame shorter than its 64-byte header")
    header = struct.unpack_from("<16I", frame, 0)
    nseg = header[0]
    if nseg != itemsize:
        raise CodecError(
            f"RLE frame has {nseg} segments, expected {itemsize} "
            "(one byte plane per sample byte, monochrome)"
        )
    offsets = list(header[1 : 1 + nseg])
    if any(o < 64 or o > len(frame) for o in offsets) or sorted(offsets) != offsets:
        raise CodecError(f"RLE segment offsets invalid: {offsets}")
    npix = rows * cols
    planes = []
    for i, off in enumerate(offsets):
        end = offsets[i + 1] if i + 1 < nseg else len(frame)
        planes.append(
            np.frombuffer(packbits_decode(frame[off:end], npix), np.uint8)
        )
    if itemsize == 1:
        return planes[0].reshape(rows, cols).copy()
    return (
        (planes[0].astype(np.uint16) << 8) | planes[1].astype(np.uint16)
    ).reshape(rows, cols)


def rle_encode_frame(pixels: np.ndarray) -> bytes:
    """Encode a uint8/uint16 (rows, cols) array as one RLE frame."""
    if pixels.dtype == np.uint16:
        flat = pixels.ravel()
        planes = [(flat >> 8).astype(np.uint8).tobytes(), (flat & 0xFF).astype(np.uint8).tobytes()]
    elif pixels.dtype == np.uint8:
        planes = [pixels.ravel().tobytes()]
    else:
        raise CodecError(f"RLE encoder expects uint8/uint16, got {pixels.dtype}")
    segs = [packbits_encode(p) for p in planes]
    offsets, pos = [], 64
    for s in segs:
        offsets.append(pos)
        pos += len(s)
    header = struct.pack(
        "<16I", len(segs), *offsets, *([0] * (15 - len(segs)))
    )
    return header + b"".join(segs)


# ---------------------------------------------------------------------------
# JPEG Lossless (ITU-T T.81 process 14, SOF3)
# ---------------------------------------------------------------------------

_SOI, _EOI, _SOF3, _DHT, _SOS = 0xD8, 0xD9, 0xC3, 0xC4, 0xDA


class _BitReader:
    """MSB-first bit reader over entropy-coded data with FF00 byte stuffing."""

    def __init__(self, buf: bytes, pos: int):
        self.buf = buf
        self.pos = pos
        self.bits = 0
        self.nbits = 0

    def read_bit(self) -> int:
        if self.nbits == 0:
            if self.pos >= len(self.buf):
                raise CodecError("JPEG entropy data truncated")
            b = self.buf[self.pos]
            self.pos += 1
            if b == 0xFF:
                if self.pos >= len(self.buf):
                    raise CodecError("JPEG entropy data truncated at FF")
                nxt = self.buf[self.pos]
                if nxt == 0x00:
                    self.pos += 1  # stuffed byte
                else:
                    # a real marker mid-scan (e.g. premature EOI)
                    raise CodecError(f"unexpected JPEG marker FF{nxt:02x} in scan")
            self.bits = b
            self.nbits = 8
        self.nbits -= 1
        return (self.bits >> self.nbits) & 1

    def read_bits(self, n: int) -> int:
        v = 0
        for _ in range(n):
            v = (v << 1) | self.read_bit()
        return v


def _build_huffman(bits_counts, values):
    """Canonical Huffman -> {(length, code): value} (T.81 Annex C)."""
    table = {}
    code = 0
    k = 0
    for length in range(1, 17):
        for _ in range(bits_counts[length - 1]):
            table[(length, code)] = values[k]
            code += 1
            k += 1
        code <<= 1
    return table


def _huff_decode(reader: _BitReader, table) -> int:
    code, length = 0, 0
    while length < 16:
        code = (code << 1) | reader.read_bit()
        length += 1
        v = table.get((length, code))
        if v is not None:
            return v
    raise CodecError("invalid JPEG Huffman code")


def _extend(bits: int, ssss: int) -> int:
    """T.81 F.2.2.1: map SSSS magnitude bits to a signed difference."""
    if ssss == 0:
        return 0
    if ssss == 16:
        return 32768  # no magnitude bits follow (lossless-mode special case)
    if bits < (1 << (ssss - 1)):
        return bits - (1 << ssss) + 1
    return bits


def jpeg_lossless_decode(data: bytes, expect_shape=None) -> np.ndarray:
    """Decode a single-component lossless JPEG (SOF3) stream.

    Supports any predictor selection value 1-7, point transform, 2-16 bit
    precision; restart intervals are not supported (DCMTK does not emit them
    for single-frame medical images). Returns uint16 (rows, cols).

    ``expect_shape``: when the caller knows the frame dimensions (the DICOM
    header's Rows/Columns), a disagreeing SOF3 is rejected BEFORE the
    output allocates — a corrupt header must not drive a multi-GB
    ``np.zeros`` or a gigapixel decode loop.
    """
    if len(data) < 4 or data[0] != 0xFF or data[1] != _SOI:
        raise CodecError("not a JPEG stream (missing SOI)")
    pos = 2
    precision = rows = cols = None
    huff_tables: dict = {}
    sel = 1
    pt = 0
    table_id = 0
    got_sos = False
    while pos + 4 <= len(data):
        if data[pos] != 0xFF:
            raise CodecError(f"expected JPEG marker at {pos}")
        marker = data[pos + 1]
        pos += 2
        if marker == _EOI:
            break
        seglen = struct.unpack_from(">H", data, pos)[0]
        seg_end = pos + seglen
        if seg_end > len(data):
            raise CodecError("truncated JPEG marker segment")
        body = data[pos + 2 : seg_end]
        if marker == _SOF3:
            if len(body) < 6:
                raise CodecError("short SOF3 segment")
            precision, rows, cols, ncomp = struct.unpack_from(">BHHB", body, 0)
            if ncomp != 1:
                raise CodecError(f"lossless JPEG: expected 1 component, got {ncomp}")
        elif marker in (0xC0, 0xC1, 0xC2, 0xC5, 0xC6, 0xC7, 0xC9, 0xCA, 0xCB):
            raise CodecError(
                f"JPEG SOF{marker - 0xC0} is not lossless process 14 (SOF3)"
            )
        elif marker == _DHT:
            b = 0
            while b < len(body):
                tc_th = body[b]
                counts = list(body[b + 1 : b + 17])
                nvals = sum(counts)
                if (
                    len(counts) < 16
                    or b + 17 + nvals > len(body)
                    or (tc_th >> 4) > 1
                    or (tc_th & 0x0F) > 3
                ):
                    # counts promising more values than the segment holds,
                    # or an out-of-range table class/id (T.81: Tc 0-1,
                    # Th 0-3; the C++ decoder rejects these — acceptance
                    # must agree across implementations)
                    raise CodecError("malformed DHT segment")
                vals = list(body[b + 17 : b + 17 + nvals])
                # key on (class, id): an AC-class table sharing a DC table's
                # destination id is legal T.81 and must not clobber it
                huff_tables[(tc_th >> 4, tc_th & 0x0F)] = _build_huffman(
                    counts, vals
                )
                b += 17 + nvals
        elif marker == _SOS:
            if len(body) < 6:  # ns(1) + 1 comp spec(2) + Ss/Se/AhAl(3)
                raise CodecError("short SOS segment")
            ns = body[0]
            if ns != 1:
                raise CodecError(f"expected 1 scan component, got {ns}")
            table_id = body[2] >> 4  # Td (DC table selects the lossless table)
            sel = body[1 + 2 * ns]  # Ss = predictor selection value
            pt = body[3 + 2 * ns] & 0x0F  # Al = point transform
            got_sos = True
            pos = seg_end
            break  # entropy-coded data follows
        pos = seg_end
    if precision is None or rows is None:
        raise CodecError("JPEG stream missing SOF3 header")
    if not got_sos:
        # without this a SOF3+DHT stream with no scan would decode trailing
        # bytes as entropy data under the default sel/table — an acceptance
        # divergence from the native decoder, which requires a scan header
        # (csrc/nm03native.cpp got_sos check)
        raise CodecError("JPEG stream missing SOS marker")
    if (0, table_id) not in huff_tables:  # lossless scans use DC-class tables
        raise CodecError(f"JPEG scan references undefined Huffman table {table_id}")
    if sel < 1 or sel > 7:
        raise CodecError(f"unsupported lossless predictor selection {sel}")
    if not (2 <= precision <= 16) or pt >= precision:
        # T.81 range; pt >= precision would make the default predictor's
        # shift count negative (a bare ValueError, not CodecError)
        raise CodecError(
            f"invalid JPEG precision/point-transform {precision}/{pt}"
        )
    if expect_shape is not None and (rows, cols) != tuple(expect_shape):
        raise CodecError(
            f"JPEG frame is ({rows}, {cols}), expected {tuple(expect_shape)}"
        )
    if rows <= 0 or cols <= 0 or rows > 32768 or cols > 32768:
        raise CodecError(f"implausible JPEG dimensions ({rows}, {cols})")

    table = huff_tables[(0, table_id)]
    reader = _BitReader(data, pos)
    out = np.zeros((rows, cols), np.int32)
    default = 1 << (precision - pt - 1)
    for y in range(rows):
        row = out[y]
        prev = out[y - 1] if y else None
        for x in range(cols):
            ssss = _huff_decode(reader, table)
            if ssss > 16:
                # DHT values are arbitrary bytes; >16 desyncs the bit
                # stream into silent garbage (C++ decoder has this guard)
                raise CodecError(f"invalid JPEG difference category {ssss}")
            diff = _extend(reader.read_bits(ssss) if 0 < ssss < 16 else 0, ssss)
            if y == 0:
                pred = default if x == 0 else row[x - 1]
            elif x == 0:
                pred = prev[0]
            elif sel == 1:
                pred = row[x - 1]
            elif sel == 2:
                pred = prev[x]
            elif sel == 3:
                pred = prev[x - 1]
            else:
                ra, rb, rc = int(row[x - 1]), int(prev[x]), int(prev[x - 1])
                if sel == 4:
                    pred = ra + rb - rc
                elif sel == 5:
                    pred = ra + ((rb - rc) >> 1)
                elif sel == 6:
                    pred = rb + ((ra - rc) >> 1)
                else:  # sel == 7
                    pred = (ra + rb) >> 1
            row[x] = (int(pred) + diff) & 0xFFFF
    return (out.astype(np.uint16) << pt)


# The encoder's one Huffman table: categories 0..16 all get 5-bit codes
# (17 <= 2^5, and the all-ones 5-bit code 0b11111 stays unused as T.81
# requires). Optimal coding is not the point — bit-exact round-trip is.
_ENC_BITS = [0, 0, 0, 0, 17] + [0] * 11
_ENC_VALUES = list(range(17))


def jpeg_lossless_encode(pixels: np.ndarray, precision: int = 16) -> bytes:
    """Encode uint16 (rows, cols) as lossless JPEG, process 14 SV1 (.70).

    Backs ``write_dicom(..., transfer_syntax=JPEG_LOSSLESS_SV1)`` and the
    importer round-trip tests; decodes bit-exactly with any T.81 process-14
    decoder (verified against our own general decoder).
    """
    if pixels.ndim != 2 or pixels.dtype != np.uint16:
        raise CodecError(f"encoder expects 2D uint16, got {pixels.dtype} {pixels.shape}")
    rows, cols = pixels.shape
    px = pixels.astype(np.int32)
    # SV1 prediction: left neighbour; first row predicts from above;
    # origin predicts the midpoint 2^(P-1)
    pred = np.empty_like(px)
    pred[:, 1:] = px[:, :-1]
    pred[1:, 0] = px[:-1, 0]
    pred[0, 0] = 1 << (precision - 1)
    diffs = (px - pred) & 0xFFFF  # modulo-2^16 difference arithmetic (T.81 H.1)

    out = bytearray(b"\xff\xd8")  # SOI
    sof = struct.pack(">BHHB", precision, rows, cols, 1) + bytes((1, 0x11, 0))
    out += b"\xff\xc3" + struct.pack(">H", len(sof) + 2) + sof
    dht = bytes((0x00,)) + bytes(_ENC_BITS) + bytes(_ENC_VALUES)
    out += b"\xff\xc4" + struct.pack(">H", len(dht) + 2) + dht
    sos = bytes((1, 1, 0x00, 1, 0, 0x00))  # 1 comp, Td=Ta=0, Ss=1(SV1), Se=0, Pt=0
    out += b"\xff\xda" + struct.pack(">H", len(sos) + 2) + sos

    acc, nacc = 0, 0
    body = bytearray()

    def put(value: int, nbits: int):
        nonlocal acc, nacc
        acc = (acc << nbits) | (value & ((1 << nbits) - 1))
        nacc += nbits
        while nacc >= 8:
            nacc -= 8
            byte = (acc >> nacc) & 0xFF
            body.append(byte)
            if byte == 0xFF:
                body.append(0x00)  # byte stuffing

    for d in diffs.ravel():
        d = int(d)
        if d >= 32768:
            d -= 65536  # back to signed [-32768, 32767]
        if d == -32768:
            put(16, 5)  # SSSS=16: diff 32768 == -32768 mod 2^16, no extra bits
            continue
        mag = abs(d)
        ssss = mag.bit_length()
        put(ssss, 5)
        if ssss:
            put(d if d > 0 else d - 1, ssss)  # negative: low bits of d-1
    if nacc:
        put(0x7F, 8 - nacc)  # final-byte padding is 1-bits (T.81 F.1.2.3)
    out += body + b"\xff\xd9"  # EOI
    return bytes(out)
