"""Dataset discovery: the cohort layout contract.

Re-implements the reference's filesystem contract (SURVEY.md section 2.1
"Dataset discovery"; src/sequential/main_sequential.cpp:93-168, duplicated in
main_parallel.cpp:233-308 — here it exists once):

* patients are directories named ``PGBM-*`` directly under the cohort root,
  processed in sorted order;
* each patient holds series subdirectories; the *first* series is used
  (sorted order here — the reference takes filesystem iteration order, which
  is unspecified; sorting makes runs reproducible);
* slices are the ``.dcm`` files in that series, ordered by the integer
  between the last ``-`` and the ``.dcm`` suffix (``1-14.dcm`` -> 14); names
  that don't parse sort with key 1000 (the reference's sentinel,
  main_sequential.cpp:18-30).
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import List

PATIENT_PREFIX = "PGBM-"
PARSE_FAILURE_KEY = 1000  # reference sentinel for unparseable names


def extract_file_number(filename: str) -> int:
    """Sort key for slice filenames, mirroring extractFileNumber.

    The integer between the final '-' and the '.dcm' extension; 1000 when the
    name doesn't follow the pattern (reference main_sequential.cpp:18-30).
    """
    m = re.match(r".*-(\d+)\.dcm$", filename)
    if m is None:
        return PARSE_FAILURE_KEY
    try:
        return int(m.group(1))
    except ValueError:  # pragma: no cover - \d+ always parses
        return PARSE_FAILURE_KEY


def find_patient_dirs(base_path: str | os.PathLike) -> List[str]:
    """Sorted patient IDs (directory names starting with ``PGBM-``)."""
    base = Path(base_path)
    if not base.is_dir():
        raise FileNotFoundError(f"cohort root does not exist: {base}")
    return sorted(
        p.name for p in base.iterdir() if p.is_dir() and p.name.startswith(PATIENT_PREFIX)
    )


def load_dicom_files_for_patient(
    base_path: str | os.PathLike, patient_id: str
) -> List[Path]:
    """Slice paths for one patient: first series dir, numerically sorted."""
    patient = Path(base_path) / patient_id
    series_dirs = sorted(p for p in patient.iterdir() if p.is_dir())
    if not series_dirs:
        raise FileNotFoundError(f"no series directories found for patient: {patient_id}")
    series = series_dirs[0]
    files = [p for p in series.iterdir() if p.suffix == ".dcm"]
    files.sort(key=lambda p: (extract_file_number(p.name), p.name))
    return files
