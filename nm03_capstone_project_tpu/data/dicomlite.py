"""Minimal DICOM reader/writer (no external DICOM dependency).

TPU-native replacement for the import side of FAST's ``DICOMFileImporter``
(reference src/test/test_pipeline.cpp:33-42 — note ``setLoadSeries(false)``:
one 2D slice per file, never a 3D volume). The reference delegates parsing to
FAST/DCMTK; this framework ships its own single-file implementation of the
subset the pipeline needs:

Support envelope (parity note vs the reference: FAST sits on DCMTK; the
T1+C Brain-Tumor-Progression cohort the reference processes is uncompressed
explicit-VR little endian, and the compressed syntaxes below cover the
archive formats DCMTK additionally reads — VERDICT r2 missing #3):

* Part-10 files (128-byte preamble + ``DICM``) and bare data sets.
* Explicit and implicit VR little endian transfer syntaxes
  (1.2.840.10008.1.2.1 / 1.2.840.10008.1.2), uncompressed pixel data,
  the retired explicit VR big endian (1.2.840.10008.1.2.2), and the
  zlib-deflated dataset form (1.2.840.10008.1.2.1.99).
* Compressed/encapsulated transfer syntaxes (data/codecs.py):
  **RLE Lossless** (1.2.840.10008.1.2.5) and **JPEG Lossless** processes
  14 / 14-SV1 (1.2.840.10008.1.2.4.57 / .70) decode bit-exactly; baseline
  8-bit JPEG (1.2.840.10008.1.2.4.50) decodes via PIL (lossy by nature).
* Monochrome 8/16-bit pixel data, signed or unsigned, with
  RescaleSlope/Intercept applied — yielding float32 intensities.
* Sequence (SQ) elements are skipped structurally (defined and undefined
  length), so real-world headers parse even though their content is unused.

NOT supported — every rejection raises :class:`DicomParseError` with a
message naming the remedy (tests/test_data.py covers each branch):

* JPEG 2000 (1.2.840.10008.1.2.4.9x) when the optional GDCM fallback shim
  (data/gdcm_fallback.py) is unavailable — transcode to explicit VR little
  endian first (``gdcmconv --raw`` or DCMTK ``dcmdjpeg``/``dcmconv +te``);
* encapsulated PixelData under an *uncompressed* transfer-syntax UID
  (malformed), color images (SamplesPerPixel != 1), BitsAllocated outside
  {8, 16}.

The writer emits valid explicit-VR-LE Part-10 files and exists so tests and
the ``--synthetic`` CLI mode can materialize cohorts that round-trip through
the same reader the real data would use. A native C++ parser
(csrc/nm03native.cpp) mirrors this logic for the threaded prefetch loader.
"""

from __future__ import annotations

import dataclasses
import os
import struct
from typing import Dict, Optional, Tuple

import numpy as np

EXPLICIT_VR_LE = "1.2.840.10008.1.2.1"
IMPLICIT_VR_LE = "1.2.840.10008.1.2"
EXPLICIT_VR_BE = "1.2.840.10008.1.2.2"  # retired, still in archives
DEFLATED_EXPLICIT_VR_LE = "1.2.840.10008.1.2.1.99"  # zlib-deflated dataset
RLE_LOSSLESS = "1.2.840.10008.1.2.5"
JPEG_BASELINE = "1.2.840.10008.1.2.4.50"  # 8-bit lossy (process 1)
JPEG_LOSSLESS = "1.2.840.10008.1.2.4.57"  # process 14, any predictor
JPEG_LOSSLESS_SV1 = "1.2.840.10008.1.2.4.70"  # process 14 SV1 (DCMTK default)
JPEG_LS_LOSSLESS = "1.2.840.10008.1.2.4.80"  # ITU-T T.87 lossless
JPEG_LS_NEAR = "1.2.840.10008.1.2.4.81"  # T.87 near-lossless

# encapsulated syntaxes this reader decodes (always explicit VR LE headers)
_DECODABLE_ENCAPSULATED = {
    RLE_LOSSLESS,
    JPEG_BASELINE,
    JPEG_LOSSLESS,
    JPEG_LOSSLESS_SV1,
    JPEG_LS_LOSSLESS,
    JPEG_LS_NEAR,
}

# JPEG 2000 family: decoded via the optional GDCM fallback shim when the
# system provides it, rejected with a transcode remedy otherwise (single
# source of truth for the UID set lives beside the shim)
from nm03_capstone_project_tpu.data import gdcm_fallback  # noqa: E402

_J2K_SYNTAXES = gdcm_fallback.J2K_SYNTAXES

# VRs whose explicit encoding uses a 2-byte reserved field + 4-byte length
_LONG_VRS = {b"OB", b"OW", b"OF", b"OD", b"OL", b"SQ", b"UC", b"UR", b"UT", b"UN"}

_ITEM = (0xFFFE, 0xE000)
_ITEM_DELIM = (0xFFFE, 0xE00D)
_SEQ_DELIM = (0xFFFE, 0xE0DD)


class DicomParseError(ValueError):
    """Raised when a file is not parseable as DICOM."""


def _photometric(meta) -> str:
    """PhotometricInterpretation (0028,0004); rejects PALETTE COLOR (its
    stored values are LUT indexes, not intensities)."""
    pi = (
        (meta.get((0x0028, 0x0004)) or b"")
        .decode("ascii", "replace")
        .strip("\x00 ")
    )
    if pi == "PALETTE COLOR":
        raise DicomParseError(
            "PALETTE COLOR images are out of envelope; convert to "
            "grayscale before import (gdcmconv or dcmconv)"
        )
    return pi


def _inversion_base(signed: bool, bits_stored: int) -> int:
    """MONOCHROME1 -> MONOCHROME2 stored-value inversion constant: lo + hi
    of the stored range (PS3.3 C.7.6.3.1.2 via DCMTK's DicomImage):
    unsigned [0, 2^b-1] -> 2^b - 1; signed [-2^(b-1), 2^(b-1)-1] -> -1."""
    return -1 if signed else (1 << bits_stored) - 1


def _check_frame_bounds(rows, cols, itemsize: int) -> None:
    """Plausibility bound shared by every decode path (native caps: 32768
    per axis, 2^28 output bytes) — applied BEFORE any decoder allocates."""
    if rows is None or cols is None:
        raise DicomParseError("missing Rows/Columns")
    if not (0 < rows <= 32768 and 0 < cols <= 32768) or (
        rows * cols * itemsize > 1 << 28
    ):
        raise DicomParseError(
            f"implausible compressed-frame dimensions ({rows}, {cols}) at "
            f"{itemsize * 8}-bit"
        )


@dataclasses.dataclass
class DicomSlice:
    """One decoded 2D slice."""

    pixels: np.ndarray  # float32 (rows, cols), rescale applied
    rows: int
    cols: int
    raw_dtype: np.dtype
    rescale_slope: float
    rescale_intercept: float
    meta: Dict[Tuple[int, int], bytes]

    def meta_str(self, tag: Tuple[int, int]) -> Optional[str]:
        v = self.meta.get(tag)
        return v.decode("ascii", "replace").strip("\x00 ") if v is not None else None

    @property
    def num_frames(self) -> int:
        """NumberOfFrames (0028,0008); 1 for ordinary single-frame slices.

        The same strict IS parse read_dicom's frame-range check uses, so
        ``range(s.num_frames)`` is always a valid frame iteration."""
        return max(1, _meta_int_str(self.meta, (0x0028, 0x0008), 1) or 1)

    @property
    def window(self) -> Optional[Tuple[float, float]]:
        """(WindowCenter, WindowWidth) when the archive carries them."""
        c = self.meta_str((0x0028, 0x1050))
        w = self.meta_str((0x0028, 0x1051))
        try:
            # multi-valued DS (PS3.5: backslash-separated) -> first pair
            return (
                (float(c.split("\\")[0]), float(w.split("\\")[0]))
                if c and w
                else None
            )
        except ValueError:
            return None


class _Reader:
    def __init__(self, buf: bytes, explicit: bool, big: bool = False):
        self.buf = buf
        self.pos = 0
        self.explicit = explicit
        self._h = ">H" if big else "<H"
        self._i = ">I" if big else "<I"

    def u16(self) -> int:
        v = struct.unpack_from(self._h, self.buf, self.pos)[0]
        self.pos += 2
        return v

    def u32(self) -> int:
        v = struct.unpack_from(self._i, self.buf, self.pos)[0]
        self.pos += 4
        return v

    def atend(self) -> bool:
        return self.pos + 8 > len(self.buf)

    def element(self):
        """Decode one data element header; returns (group, elem, vr, length)."""
        group = self.u16()
        elem = self.u16()
        if (group, elem) in (_ITEM, _ITEM_DELIM, _SEQ_DELIM):
            return group, elem, b"", self.u32()
        if self.explicit and group != 0xFFFE:
            vr = self.buf[self.pos : self.pos + 2]
            self.pos += 2
            if vr in _LONG_VRS:
                self.pos += 2  # reserved
                length = self.u32()
            else:
                length = self.u16()
        else:
            vr = b""
            length = self.u32()
        return group, elem, vr, length

    def skip_sequence(self):
        """Skip an undefined-length sequence body (until sequence delimiter)."""
        while not self.atend():
            group, elem, _, length = self.element()
            if (group, elem) == _SEQ_DELIM:
                return
            if (group, elem) == _ITEM:
                if length == 0xFFFFFFFF:
                    self._skip_item_undefined()
                else:
                    self.pos += length
            else:  # malformed; bail out of the sequence
                self.pos += 0 if length == 0xFFFFFFFF else length
                return

    def _skip_item_undefined(self):
        """Skip an undefined-length item (may contain nested sequences)."""
        while not self.atend():
            group, elem, _vr, length = self.element()
            if (group, elem) == _ITEM_DELIM:
                return
            if length == 0xFFFFFFFF:
                self.skip_sequence()  # nested undefined-length sequence
            else:
                self.pos += length


class _Fragments(list):
    """Encapsulated PixelData fragments + frame-boundary metadata.

    A plain list of fragment byte strings (so every existing isinstance and
    indexing contract holds), annotated with the Basic Offset Table entries
    and each fragment's item-tag offset — both measured, per PS3.5 §A.4,
    from the first byte of the first item FOLLOWING the BOT item — so
    :func:`_frame_payload` can use the BOT as the authoritative frame
    delimiter instead of guessing from SOI markers.
    """

    def __init__(self, frags, bot, offsets):
        super().__init__(frags)
        self.bot = list(bot)  # [] when the BOT item is empty
        self.offsets = list(offsets)  # per-fragment item-tag offsets


def _read_fragments(r: "_Reader") -> "_Fragments":
    """Encapsulated PixelData: Basic Offset Table item, then one item per
    fragment, closed by a sequence delimiter (PS3.5 §A.4). Returns the
    fragment byte strings with the BOT preserved (frame-boundary source)."""
    fragments: list = []
    bot: list = []
    offsets: list = []
    first = True
    base = 0
    while not r.atend():
        tag_pos = r.pos
        group, elem, _vr, length = r.element()
        if (group, elem) == _SEQ_DELIM:
            return _Fragments(fragments, bot, offsets)
        if (group, elem) != _ITEM or length == 0xFFFFFFFF:
            raise DicomParseError(
                f"malformed encapsulated PixelData item ({group:04x},{elem:04x})"
            )
        if length > len(r.buf) - r.pos:
            raise DicomParseError("encapsulated fragment overruns file")
        if first:  # the first item is the Basic Offset Table
            # a non-multiple-of-4 BOT is malformed but must not reject the
            # file: pre-BOT-support the table was discarded unconditionally,
            # and single-frame files never need it — treat it as empty so
            # multi-frame grouping falls back to SOI scanning
            if length % 4 == 0 and length:
                bot = list(struct.unpack_from(f"<{length // 4}I", r.buf, r.pos))
            base = r.pos + length  # offsets count from the byte after the BOT
        else:
            offsets.append(tag_pos - base)
            fragments.append(r.buf[r.pos : r.pos + length])
        first = False
        r.pos += length
    raise DicomParseError("encapsulated PixelData missing sequence delimiter")


def _parse_dataset(
    buf: bytes, explicit: bool, want_pixels: bool, encapsulated: bool = False,
    big: bool = False,
) -> Tuple[Dict[Tuple[int, int], bytes], Optional[bytes]]:
    """Returns (meta, pixel_data); pixel_data is ``bytes`` for native
    PixelData, a ``list`` of fragment byte strings when encapsulated."""
    r = _Reader(buf, explicit, big)
    meta: Dict[Tuple[int, int], bytes] = {}
    pixel_data = None
    while not r.atend():
        group, elem, vr, length = r.element()
        if (group, elem) == (0x7FE0, 0x0010):
            if length == 0xFFFFFFFF:
                if not encapsulated:
                    raise DicomParseError(
                        "encapsulated PixelData under an uncompressed "
                        "transfer-syntax UID (malformed file); transcode to "
                        "explicit VR little endian (gdcmconv --raw, or "
                        "dcmdjpeg/dcmconv +te)"
                    )
                frags = _read_fragments(r)
                pixel_data = frags if want_pixels else None
                continue
            pixel_data = r.buf[r.pos : r.pos + length] if want_pixels else None
            r.pos += length
            continue
        if length == 0xFFFFFFFF:
            r.skip_sequence()
            continue
        if vr == b"SQ":
            r.pos += length
            continue
        if group == 0xFFFE:
            r.pos += length
            continue
        if length > len(r.buf) - r.pos:
            raise DicomParseError(
                f"element ({group:04x},{elem:04x}) length {length} overruns file"
            )
        meta[(group, elem)] = r.buf[r.pos : r.pos + length]
        r.pos += length
    return meta, pixel_data


def _meta_int(meta, tag, default=None, big: bool = False) -> Optional[int]:
    v = meta.get(tag)
    if v is None:
        return default
    if len(v) == 2:
        return struct.unpack(">H" if big else "<H", v)[0]
    if len(v) == 4:
        return struct.unpack(">I" if big else "<I", v)[0]
    try:
        return int(v.decode("ascii").strip("\x00 "))
    except (UnicodeDecodeError, ValueError):
        return default


def _meta_int_str(meta, tag, default: Optional[int] = None) -> Optional[int]:
    """Integer-String (IS) tag value. NOT _meta_int: a 2-byte IS like b"3 "
    would satisfy its len==2 branch and misparse as a binary uint16.
    Strictly [+-]?digits after pad stripping — int()'s extra tolerance
    (embedded newlines, unicode digits) would diverge from the native
    reader's stol on corrupt values, and the differential fuzz holds the
    two readers to byte-identical acceptance."""
    v = meta.get(tag)
    if v is None:
        return default
    try:
        s = v.decode("ascii").strip("\x00 ")
    except UnicodeDecodeError:
        return default
    body = s[1:] if s[:1] in ("+", "-") else s
    if not body.isdigit():  # exactly one optional sign, then digits
        return default
    return int(s)


def _meta_float(meta, tag, default: float) -> float:
    v = meta.get(tag)
    if v is None:
        return default
    try:
        return float(v.decode("ascii").strip("\x00 "))
    except (UnicodeDecodeError, ValueError):
        return default


def _frame_payload(fragments: list, frame: int, nframes: int) -> bytes:
    """One frame's concatenated JPEG-family codestream.

    Single-frame: all fragments join (a frame may span fragments).
    Multi-frame: when the file carries a non-empty Basic Offset Table, the
    BOT is the AUTHORITATIVE frame-boundary source (PS3.5 §A.4: one offset
    per frame, pointing at the item tag of the frame's first fragment) —
    SOI-marker scanning is only the fallback for an empty BOT, because a
    fragment boundary can coincidentally land on bytes that look like an
    SOI (e.g. inside a COM/APPn segment), mis-splitting the stream.
    """
    if nframes <= 1:
        return b"".join(fragments)
    bot = getattr(fragments, "bot", None)
    offsets = getattr(fragments, "offsets", None)
    if bot:
        if len(bot) != nframes:
            raise DicomParseError(
                f"Basic Offset Table has {len(bot)} entries for "
                f"NumberOfFrames={nframes}"
            )
        starts: list = []
        for off in bot:
            try:
                starts.append(offsets.index(off))
            except ValueError:
                raise DicomParseError(
                    f"Basic Offset Table offset {off} does not fall on a "
                    "fragment boundary"
                ) from None
        if starts[0] != 0 or any(
            b <= a for a, b in zip(starts, starts[1:])
        ):
            raise DicomParseError(
                "Basic Offset Table offsets are not strictly increasing "
                "from the first fragment"
            )
        bounds = starts + [len(fragments)]
        return b"".join(fragments[bounds[frame] : bounds[frame + 1]])
    groups: list = []
    for frag in fragments:
        if frag[:2] == b"\xff\xd8" or not groups:
            groups.append([frag])
        else:
            groups[-1].append(frag)
    if len(groups) != nframes:
        raise DicomParseError(
            f"found {len(groups)} JPEG codestreams for "
            f"NumberOfFrames={nframes}"
        )
    return b"".join(groups[frame])


def _decode_compressed(
    transfer_syntax: str, fragments: list, rows: int, cols: int,
    dtype: np.dtype, frame: int = 0, nframes: int = 1,
) -> np.ndarray:
    """Decode one frame of encapsulated PixelData -> (rows, cols) ``dtype``.

    Single-frame files follow the reference importer's one-slice contract
    (setLoadSeries(false)); multi-frame files (real-archive shape) select
    ``frame`` of ``nframes``. RLE uses exactly one fragment per frame
    (PS3.5 §A.4.2); a JPEG/JPEG-LS frame may span fragments, so frames are
    delimited by their SOI markers and each frame's fragments concatenate.
    """
    from nm03_capstone_project_tpu.data import codecs

    if not fragments:
        raise DicomParseError("encapsulated PixelData has no fragments")
    # a hostile file declaring 65535x65535 must fail here, not after
    # rle_decode_frame's replicate pass expands fragments into a multi-GB
    # host buffer
    _check_frame_bounds(rows, cols, dtype.itemsize)
    try:
        if transfer_syntax == RLE_LOSSLESS:
            if len(fragments) != nframes:
                raise DicomParseError(
                    f"{len(fragments)} RLE fragments for NumberOfFrames="
                    f"{nframes}: PS3.5 A.4.2 requires exactly one per frame"
                )
            arr = codecs.rle_decode_frame(
                fragments[frame], rows, cols, dtype.itemsize
            )
        elif transfer_syntax in (JPEG_LOSSLESS, JPEG_LOSSLESS_SV1,
                                 JPEG_LS_LOSSLESS, JPEG_LS_NEAR):
            jls = transfer_syntax in (JPEG_LS_LOSSLESS, JPEG_LS_NEAR)
            decode = codecs.jpegls_decode if jls else codecs.jpeg_lossless_decode
            payload = _frame_payload(fragments, frame, nframes)
            arr = decode(payload, expect_shape=(rows, cols))
            if dtype.itemsize == 1:
                if arr.max(initial=0) > 0xFF:
                    raise DicomParseError(
                        ("JPEG-LS" if jls else "lossless JPEG")
                        + " precision exceeds BitsAllocated=8"
                    )
                arr = arr.astype(np.uint8)
        else:  # JPEG_BASELINE — lossy 8-bit, decoded by PIL
            import io

            from PIL import Image

            if dtype.itemsize != 1:
                raise DicomParseError(
                    "baseline JPEG (1.2.840.10008.1.2.4.50) is 8-bit only, "
                    f"but BitsAllocated={dtype.itemsize * 8}"
                )
            payload = _frame_payload(fragments, frame, nframes)
            try:
                img = Image.open(io.BytesIO(payload))
                arr = np.asarray(img.convert("L"), np.uint8)
            except (OSError, ValueError, Image.DecompressionBombError) as e:
                # PIL raises UnidentifiedImageError (an OSError) on corrupt
                # streams and DecompressionBombError (a bare Exception
                # subclass) on hostile declared dimensions; the importer
                # contract is DicomParseError only
                raise DicomParseError(f"baseline JPEG decode failed: {e}") from e
    except codecs.CodecError as e:
        raise DicomParseError(f"compressed PixelData decode failed: {e}") from e
    if arr.shape != (rows, cols):
        raise DicomParseError(
            f"compressed frame is {arr.shape}, header says ({rows}, {cols})"
        )
    # signed data: the decoded planes carry the raw two's-complement bits
    return arr.view(dtype) if dtype.itemsize == arr.dtype.itemsize else arr.astype(dtype)


def read_dicom(path: str | os.PathLike, frame: int = 0) -> DicomSlice:
    """Read one 2D DICOM slice, returning float32 rescaled intensities.

    Mirrors the reference importer's contract: exactly one 2D image per file
    (DICOMFileImporter with setLoadSeries(false), test_pipeline.cpp:38-41).
    Real archives also carry multi-frame files (NumberOfFrames > 1):
    ``frame`` selects which 2D frame decodes — the default 0 keeps the
    one-slice contract while letting multi-frame archives import instead of
    rejecting. The slice's ``num_frames`` property reports the count; use
    :func:`read_dicom_frames` to materialize a whole stack without
    re-parsing the file per frame.
    """
    with open(path, "rb") as f:
        raw = f.read()
    return read_dicom_bytes(raw, frame, path=path)


def read_dicom_bytes(raw: bytes, frame: int = 0, path="<bytes>") -> DicomSlice:
    """:func:`read_dicom` from an in-memory byte string.

    The fault-injection layer (resilience.faultinject) decodes
    deterministically corrupted file images through this entry point so the
    REAL parser's rejection path is what the chaos tests exercise; also
    useful anywhere the caller already holds the file bytes. ``path`` is a
    provenance hint — it must be the real on-disk path for the J2K shim
    route (the GDCM fallback re-reads the file itself).
    """
    ctx = _open_dataset(raw, path)
    if isinstance(ctx, DicomSlice):  # J2K shim path (single-frame)
        if frame != 0:
            raise DicomParseError(
                f"frame {frame} out of range (NumberOfFrames=1)"
            )
        return ctx
    return _materialize_frame(ctx, frame)


def read_dicom_frames(path: str | os.PathLike, strict: bool = True) -> list:
    """Every frame of a (possibly multi-frame) file, parsed ONCE.

    Single-frame files return a one-element list; archives that store a
    whole series as a single multi-frame file expand into their z-stack
    (the volume driver consumes this). ``strict=False`` substitutes the
    DicomParseError for frames whose decode fails instead of raising —
    per-frame containment for drivers that skip-and-continue, with the
    failure reason preserved.
    """
    with open(path, "rb") as f:
        raw = f.read()
    ctx = _open_dataset(raw, path)
    if isinstance(ctx, DicomSlice):
        return [ctx]
    out = []
    for k in range(ctx["nframes"]):
        try:
            out.append(_materialize_frame(ctx, k))
        except DicomParseError as e:
            if strict:
                raise
            # the EXCEPTION stands in for the frame so skip-and-continue
            # callers can still report WHY a frame was dropped
            out.append(e)
    return out


def _open_dataset(raw: bytes, path) -> "dict | DicomSlice":
    """Parse preamble/meta/dataset once; the frame-independent half of
    :func:`read_dicom`. Returns the decode context, or a finished
    DicomSlice for the GDCM-shimmed J2K path (which decodes whole)."""
    # Part-10 preamble, or a bare dataset
    body = raw
    transfer_syntax = EXPLICIT_VR_LE
    if len(raw) >= 132 and raw[128:132] == b"DICM":
        # file meta group is always explicit VR LE
        r = _Reader(raw, explicit=True)
        r.pos = 132
        meta_end = len(raw)
        first = True
        while r.pos < meta_end and not r.atend():
            mark = r.pos
            try:
                group, elem, vr, length = r.element()
            except struct.error as e:
                # a file truncated inside a meta element header must reject
                # cleanly, like the dataset-side parse below
                raise DicomParseError(f"truncated file meta group: {e}") from e
            if group != 0x0002:
                r.pos = mark
                break
            value = r.buf[r.pos : r.pos + length]
            r.pos += length
            if first and (group, elem) == (0x0002, 0x0000) and len(value) == 4:
                meta_end = r.pos + struct.unpack("<I", value)[0]
            if (group, elem) == (0x0002, 0x0010):
                # errors="replace": corrupt bytes yield a UID that matches no
                # known syntax and is rejected cleanly, instead of a
                # UnicodeDecodeError escaping the DicomParseError contract
                transfer_syntax = value.decode("ascii", "replace").strip("\x00 ")
            first = False
        body = raw[r.pos :]
    elif raw[:4] == b"DICM":
        body = raw[4:]
    if transfer_syntax == DEFLATED_EXPLICIT_VR_LE:
        # PS3.5 A.5: everything after the file meta group is one raw
        # (headerless) zlib-deflate stream of an explicit VR LE dataset.
        # Bounded inflate: a crafted bomb must hit the same ~2^28 envelope
        # cap as every other path, as a clean DicomParseError, not an OOM.
        import zlib

        limit = (1 << 28) + (1 << 20)  # pixel envelope + header slack
        d = zlib.decompressobj(wbits=-15)
        try:
            body = d.decompress(body, limit)
        except zlib.error as e:
            raise DicomParseError(f"deflated dataset inflate failed: {e}") from e
        if d.unconsumed_tail:
            raise DicomParseError(
                "deflated dataset exceeds the importer size bound"
            )
        transfer_syntax = EXPLICIT_VR_LE
    encapsulated = transfer_syntax in _DECODABLE_ENCAPSULATED
    big = transfer_syntax == EXPLICIT_VR_BE
    if transfer_syntax in _J2K_SYNTAXES:
        # JPEG 2000: the one family without an in-tree codec. Routed through
        # the optional GDCM shim (data/gdcm_fallback.py) when the system has
        # it — the same sit-on-a-system-library judgment the reference makes
        # with DCMTK — else rejected with the transcode remedy below.
        from nm03_capstone_project_tpu.data import gdcm_fallback

        if gdcm_fallback.available():
            try:
                meta, _ = _parse_dataset(
                    body, explicit=True, want_pixels=False, encapsulated=True
                )
            except struct.error as e:
                raise DicomParseError(
                    f"truncated DICOM element structure: {e}"
                ) from e
            rows = _meta_int(meta, (0x0028, 0x0010))
            cols = _meta_int(meta, (0x0028, 0x0011))
            _check_frame_bounds(rows, cols, 2)
            pi = _photometric(meta)
            if (_meta_int_str(meta, (0x0028, 0x0008), 1) or 1) > 1:
                # the shim decodes whole files; serving frame 0 of a
                # multi-frame J2K would silently drop planes (and
                # num_frames would lie about the iteration range)
                raise DicomParseError(
                    "multi-frame JPEG 2000 is out of envelope; transcode "
                    "with gdcmconv --raw first"
                )
            try:
                pixels, raw_dtype = gdcm_fallback.read_j2k(path, rows, cols)
            except ValueError as e:
                raise DicomParseError(str(e)) from e
            slope = _meta_float(meta, (0x0028, 0x1053), 1.0)
            intercept = _meta_float(meta, (0x0028, 0x1052), 0.0)
            if pi == "MONOCHROME1":
                # the shim already applied rescale, so invert in rescaled
                # space: (base - raw)*s + i == base*s + 2i - (raw*s + i)
                j2k_bits = _meta_int(meta, (0x0028, 0x0100), 16)
                bits_stored = _meta_int(meta, (0x0028, 0x0101), j2k_bits)
                if not (1 <= bits_stored <= j2k_bits <= 16):
                    raise DicomParseError(
                        f"BitsStored {bits_stored} outside "
                        f"[1, BitsAllocated={j2k_bits}]"
                    )
                j2k_signed = _meta_int(meta, (0x0028, 0x0103), 0) == 1
                base = _inversion_base(j2k_signed, bits_stored)
                pixels = np.float32(base * slope + 2 * intercept) - pixels
            return DicomSlice(
                pixels=pixels,
                rows=rows,
                cols=cols,
                raw_dtype=raw_dtype,
                rescale_slope=slope,
                rescale_intercept=intercept,
                meta=meta,
            )
    if (
        transfer_syntax not in (EXPLICIT_VR_LE, IMPLICIT_VR_LE, EXPLICIT_VR_BE)
        and not encapsulated
    ):
        kind = (
            "compressed"
            if transfer_syntax.startswith("1.2.840.10008.1.2.4")
            else "unrecognized"
        )
        raise DicomParseError(
            f"unsupported ({kind}) transfer syntax {transfer_syntax}: "
            "supported are uncompressed little/big endian "
            f"({EXPLICIT_VR_LE} / {IMPLICIT_VR_LE} / {EXPLICIT_VR_BE}), "
            f"RLE ({RLE_LOSSLESS}), "
            f"JPEG lossless ({JPEG_LOSSLESS} / {JPEG_LOSSLESS_SV1}), "
            f"JPEG-LS ({JPEG_LS_LOSSLESS} / {JPEG_LS_NEAR}) and "
            f"baseline JPEG ({JPEG_BASELINE}); transcode first "
            "(gdcmconv --raw, or DCMTK dcmdjpeg/dcmconv +te)"
        )

    explicit = transfer_syntax != IMPLICIT_VR_LE
    try:
        meta, pixel_data = _parse_dataset(
            body, explicit, want_pixels=True, encapsulated=encapsulated,
            big=big,
        )
    except struct.error as e:
        raise DicomParseError(f"truncated DICOM element structure: {e}") from e

    rows = _meta_int(meta, (0x0028, 0x0010), big=big)
    cols = _meta_int(meta, (0x0028, 0x0011), big=big)
    if rows is None or cols is None or pixel_data is None:
        raise DicomParseError("missing Rows/Columns/PixelData")
    if encapsulated and not isinstance(pixel_data, list):
        raise DicomParseError(
            f"transfer syntax {transfer_syntax} declares compressed pixels "
            "but PixelData is native/uncompressed (malformed file)"
        )
    bits = _meta_int(meta, (0x0028, 0x0100), 16, big=big)
    signed = _meta_int(meta, (0x0028, 0x0103), 0, big=big) == 1
    samples = _meta_int(meta, (0x0028, 0x0002), 1, big=big)
    if samples != 1:
        raise DicomParseError(
            f"only monochrome supported, SamplesPerPixel={samples}; convert "
            "color/multi-sample images to grayscale before import"
        )
    pi = _photometric(meta)
    if bits == 16:
        order = ">" if big else "<"
        dtype = np.dtype(order + ("i2" if signed else "u2"))
    elif bits == 8:
        dtype = np.dtype("i1") if signed else np.dtype("u1")
    else:
        raise DicomParseError(f"unsupported BitsAllocated={bits}")

    nframes = _meta_int_str(meta, (0x0028, 0x0008), 1)
    if nframes is None or nframes < 1:
        nframes = 1
    return {
        "transfer_syntax": transfer_syntax,
        "meta": meta,
        "pixel_data": pixel_data,
        "rows": rows,
        "cols": cols,
        "bits": bits,
        "signed": signed,
        "pi": pi,
        "dtype": dtype,
        "big": big,
        "nframes": nframes,
    }


def _materialize_frame(ctx: dict, frame: int) -> DicomSlice:
    """Decode + post-process ONE frame from an :func:`_open_dataset` context."""
    transfer_syntax = ctx["transfer_syntax"]
    meta = ctx["meta"]
    pixel_data = ctx["pixel_data"]
    rows, cols = ctx["rows"], ctx["cols"]
    bits, signed, pi = ctx["bits"], ctx["signed"], ctx["pi"]
    dtype, big, nframes = ctx["dtype"], ctx["big"], ctx["nframes"]
    if not 0 <= frame < nframes:
        raise DicomParseError(
            f"frame {frame} out of range (NumberOfFrames={nframes})"
        )
    if isinstance(pixel_data, list):  # encapsulated fragments
        pixels = _decode_compressed(
            transfer_syntax, pixel_data, rows, cols, dtype,
            frame=frame, nframes=nframes,
        )
    else:
        fsize = rows * cols * dtype.itemsize
        expected = fsize * nframes
        if len(pixel_data) < expected:
            raise DicomParseError(
                f"PixelData has {len(pixel_data)} bytes, expected {expected}"
                + (f" ({nframes} frames)" if nframes > 1 else "")
            )
        pixels = np.frombuffer(
            pixel_data[frame * fsize : (frame + 1) * fsize], dtype=dtype
        ).reshape(rows, cols)

    slope = _meta_float(meta, (0x0028, 0x1053), 1.0)
    intercept = _meta_float(meta, (0x0028, 0x1052), 0.0)
    bits_stored = _meta_int(meta, (0x0028, 0x0101), bits, big=big)
    if not (1 <= bits_stored <= bits):
        raise DicomParseError(
            f"BitsStored {bits_stored} outside [1, BitsAllocated={bits}]"
        )
    high_bit = _meta_int(meta, (0x0028, 0x0102), bits_stored - 1, big=big)
    if high_bit != bits_stored - 1:
        # standard layout only (PS3.5 8.1.1: HighBit = BitsStored-1);
        # exotic packings would silently misread, so reject with a remedy
        raise DicomParseError(
            f"HighBit {high_bit} != BitsStored-1 ({bits_stored - 1}); "
            "repack with gdcmconv/dcmconv before import"
        )
    if bits_stored < bits:
        # bits above BitsStored are overlay planes / garbage in historical
        # files: mask them off (unsigned) or sign-extend from the stored
        # sign bit (signed), as DCMTK's DicomImage does
        v = pixels.astype(np.int64) & ((1 << bits_stored) - 1)
        if signed:
            sign = 1 << (bits_stored - 1)
            v = (v ^ sign) - sign
        pixels = v
    if pi == "MONOCHROME1":
        # inverted grayscale (PS3.3 C.7.6.3.1.2: lowest stored value =
        # white): normalize to MONOCHROME2 semantics on the STORED values,
        # before rescale, so intensity thresholds mean the same thing on
        # every file (DCMTK's DicomImage applies the same inversion)
        pixels = _inversion_base(signed, bits_stored) - pixels.astype(np.int64)
    out = pixels.astype(np.float32) * np.float32(slope) + np.float32(intercept)
    return DicomSlice(
        pixels=out,
        rows=rows,
        cols=cols,
        raw_dtype=dtype,
        rescale_slope=slope,
        rescale_intercept=intercept,
        meta=meta,
    )


# ---------------------------------------------------------------------------
# Writer (explicit VR little endian)
# ---------------------------------------------------------------------------


def _element(group: int, elem: int, vr: bytes, value: bytes) -> bytes:
    if len(value) % 2 == 1:
        value += b" " if vr in (b"UI", b"DS", b"IS", b"CS", b"LO", b"PN", b"SH") else b"\x00"
    head = struct.pack("<HH", group, elem) + vr
    if vr in _LONG_VRS:
        return head + b"\x00\x00" + struct.pack("<I", len(value)) + value
    return head + struct.pack("<H", len(value)) + value


def _encapsulate(frame: bytes) -> bytes:
    """Encapsulated PixelData value: empty Basic Offset Table item, one
    fragment item (even-padded), sequence delimiter (PS3.5 §A.4)."""
    if len(frame) % 2:
        frame += b"\x00"
    return (
        struct.pack("<HHI", *_ITEM, 0)
        + struct.pack("<HHI", *_ITEM, len(frame))
        + frame
        + struct.pack("<HHI", *_SEQ_DELIM, 0)
    )


def write_dicom(
    path: str | os.PathLike,
    pixels: np.ndarray,
    *,
    patient_id: str = "ANON",
    series_uid: str = "1.2.826.0.1.3680043.9999.1",
    instance_number: int = 1,
    rescale_slope: float = 1.0,
    rescale_intercept: float = 0.0,
    transfer_syntax: str = EXPLICIT_VR_LE,
    jpegls_near: int = 2,
) -> None:
    """Write a monochrome uint16 slice as a Part-10 file.

    ``transfer_syntax`` may be EXPLICIT_VR_LE (native pixels), RLE_LOSSLESS,
    JPEG_LOSSLESS_SV1 or JPEG_LS_LOSSLESS (encapsulated, bit-exact round
    trip through data/codecs.py — the importer-parity test data for the
    compressed envelope), or JPEG_LS_NEAR (near-lossless: stored values
    reconstruct within ±``jpegls_near`` of the input, identically in every
    conformant decoder)."""
    if pixels.ndim != 2:
        raise ValueError(f"expected 2D pixels, got {pixels.shape}")
    if transfer_syntax not in (EXPLICIT_VR_LE, RLE_LOSSLESS,
                               JPEG_LOSSLESS_SV1, JPEG_LS_LOSSLESS,
                               JPEG_LS_NEAR):
        raise ValueError(f"writer does not support transfer syntax {transfer_syntax}")
    if transfer_syntax == JPEG_LS_NEAR and jpegls_near < 1:
        raise ValueError("JPEG_LS_NEAR requires jpegls_near >= 1 (use "
                         "JPEG_LS_LOSSLESS for exact storage)")
    data = np.ascontiguousarray(pixels.astype("<u2"))
    rows, cols = data.shape

    sop_uid = f"{series_uid}.{instance_number}"
    meta_elems = _element(0x0002, 0x0010, b"UI", transfer_syntax.encode())
    meta_group = (
        _element(0x0002, 0x0000, b"UL", struct.pack("<I", len(meta_elems)))
        + meta_elems
    )

    if transfer_syntax == RLE_LOSSLESS:
        from nm03_capstone_project_tpu.data import codecs

        pix_elem = (
            struct.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + struct.pack("<I", 0xFFFFFFFF)
            + _encapsulate(codecs.rle_encode_frame(data))
        )
    elif transfer_syntax == JPEG_LOSSLESS_SV1:
        from nm03_capstone_project_tpu.data import codecs

        pix_elem = (
            struct.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + struct.pack("<I", 0xFFFFFFFF)
            + _encapsulate(codecs.jpeg_lossless_encode(data))
        )
    elif transfer_syntax in (JPEG_LS_LOSSLESS, JPEG_LS_NEAR):
        from nm03_capstone_project_tpu.data import codecs

        near = jpegls_near if transfer_syntax == JPEG_LS_NEAR else 0
        pix_elem = (
            struct.pack("<HH", 0x7FE0, 0x0010)
            + b"OB\x00\x00"
            + struct.pack("<I", 0xFFFFFFFF)
            # precision pinned to BitsStored=16 (PS3.5 A.4.3: codestream
            # precision must match the dataset's Bits Stored)
            + _encapsulate(codecs.jpegls_encode(data, precision=16, near=near))
        )
    else:
        pix_elem = _element(0x7FE0, 0x0010, b"OW", data.tobytes())
    ds = b"".join(
        [
            _element(0x0008, 0x0016, b"UI", b"1.2.840.10008.5.1.4.1.1.4"),  # MR
            _element(0x0008, 0x0018, b"UI", sop_uid.encode()),
            _element(0x0010, 0x0020, b"LO", patient_id.encode()),
            _element(0x0020, 0x000E, b"UI", series_uid.encode()),
            _element(0x0020, 0x0013, b"IS", str(instance_number).encode()),
            _element(0x0028, 0x0002, b"US", struct.pack("<H", 1)),
            _element(0x0028, 0x0004, b"CS", b"MONOCHROME2"),
            _element(0x0028, 0x0010, b"US", struct.pack("<H", rows)),
            _element(0x0028, 0x0011, b"US", struct.pack("<H", cols)),
            _element(0x0028, 0x0100, b"US", struct.pack("<H", 16)),
            _element(0x0028, 0x0101, b"US", struct.pack("<H", 16)),
            _element(0x0028, 0x0102, b"US", struct.pack("<H", 15)),
            _element(0x0028, 0x0103, b"US", struct.pack("<H", 0)),
            _element(0x0028, 0x1052, b"DS", f"{rescale_intercept:g}".encode()),
            _element(0x0028, 0x1053, b"DS", f"{rescale_slope:g}".encode()),
            # near-lossless storage is LOSSY: PS3.3 C.7.6.1.1.5 mandates
            # declaring it, or a later transcode to a lossless syntax would
            # launder the ±near error into data claimed exact
            (
                _element(0x0028, 0x2110, b"CS", b"01")
                + _element(0x0028, 0x2114, b"CS", b"ISO_14495_1 ")
                if transfer_syntax == JPEG_LS_NEAR
                else b""
            ),
            pix_elem,
        ]
    )

    # atomic (NM351): synthetic cohorts are cached on disk and reused by
    # later runs (resolve_base_path skips regeneration for a non-empty
    # tree) — a torn .dcm from a killed generator would poison every rerun
    from nm03_capstone_project_tpu.utils.atomicio import atomic_write_bytes

    atomic_write_bytes(path, b"\x00" * 128 + b"DICM" + meta_group + ds)
