"""Optional GDCM-backed fallback for JPEG 2000 transfer syntaxes.

The in-tree importer owns every syntax the cohort uses (uncompressed LE/BE,
RLE, JPEG lossless, JPEG-LS, baseline JPEG — all with externally-produced
conformance vectors). JPEG 2000 (1.2.840.10008.1.2.4.90/.91/.92/.93) is the
one family this repo deliberately does not reimplement: its EBCOT arithmetic
coder is a multi-thousand-line codec where a from-scratch build buys no
exactness over the system libraries — the same judgment the reference makes
by sitting on DCMTK for its entire importer (FAST_directives.hpp:30).

When the system has the gdcm-3.0 development headers + libraries (as GKE
images with python3-gdcm do), ``csrc/nm03gdcm.cpp`` is compiled on demand
(same atomic-publish scheme as the main native layer) and ``read_dicom``
routes J2K files through it. Without GDCM the importer keeps its existing
behavior: a DicomParseError naming the transcode remedy.

``NM03_NO_GDCM=1`` disables the fallback explicitly (tests use it to pin
the rejection path on hosts where GDCM exists).

12-bit JPEG Extended (1.2.840.10008.1.2.4.51) was evaluated for the same
routing and deliberately EXCLUDED: GDCM does not round-trip its own .51
encode (every sample comes back +32768 — a signed-bias quirk in its 12-bit
DCT path), and this environment has no independent implementation to
arbitrate whether the fault is encoder- or decoder-side. A clean rejection
with a transcode remedy is safer than possibly-biased intensities.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_log = logging.getLogger("nm03_tpu.gdcm")

_SRC = Path(__file__).resolve().parents[2] / "csrc" / "nm03gdcm.cpp"
_BUILD_DIR = _SRC.parent / "build"
_GDCM_INCLUDE = Path("/usr/include/gdcm-3.0")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

# J2K family: lossless, lossy, and the Part-2 multi-component variants
J2K_SYNTAXES = {
    "1.2.840.10008.1.2.4.90",
    "1.2.840.10008.1.2.4.91",
    "1.2.840.10008.1.2.4.92",
    "1.2.840.10008.1.2.4.93",
}


def _compile() -> Optional[Path]:
    try:
        if not _GDCM_INCLUDE.is_dir():
            return None  # no gdcm dev files on this host
    except OSError:
        return None
    from nm03_capstone_project_tpu.native.buildlib import build_shared_library

    return build_shared_library(
        _SRC, _BUILD_DIR, "nm03gdcm",
        [f"-I{_GDCM_INCLUDE}", "-lgdcmMSFF", "-lgdcmDSED", "-lgdcmCommon"],
        _log,
        failure_level=logging.INFO,  # the shim is optional by design
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    with _lock:
        if _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("NM03_NO_GDCM") == "1":
            return None
        path = _compile()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(str(path))
        except OSError as e:
            _log.info("gdcm fallback load failed: %s", e)
            return None
        lib.nm03_gdcm_last_error.restype = ctypes.c_char_p
        lib.nm03_gdcm_read.restype = ctypes.c_int
        lib.nm03_gdcm_read.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_int),
        ]
        _lib = lib
        _log.info("gdcm J2K fallback loaded (%s)", path.name)
        return _lib


def available() -> bool:
    """True when the GDCM shim compiled + loaded on this host."""
    return _load() is not None


# scalar-type codes the shim reports (nm03gdcm.cpp) -> numpy raw dtypes
_SCALAR_DTYPES = {
    0: np.dtype("u1"),
    1: np.dtype("i1"),
    2: np.dtype("<u2"),
    3: np.dtype("<i2"),
}


def read_j2k(path: str | os.PathLike, rows: int, cols: int):
    """Decode a JPEG 2000 DICOM file via GDCM.

    ``rows``/``cols`` come from the caller's own header parse, so the
    destination buffer is exactly sized (no fixed 64 MiB scratch) and a
    frame disagreeing with its header is rejected by the shim's cap check.
    Returns (float32 (rows, cols) rescaled pixels, raw numpy dtype).
    Raises RuntimeError when the fallback is unavailable, ValueError when
    GDCM rejects the file (both mapped to DicomParseError by the caller).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("gdcm fallback unavailable")
    buf = np.empty(rows * cols, np.float32)
    r = ctypes.c_long(0)
    c = ctypes.c_long(0)
    st = ctypes.c_int(-1)
    rc = lib.nm03_gdcm_read(
        os.fspath(path).encode(),
        buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        buf.size,
        ctypes.byref(r),
        ctypes.byref(c),
        ctypes.byref(st),
    )
    if rc != 0:
        err = lib.nm03_gdcm_last_error().decode("utf-8", "replace")
        raise ValueError(f"gdcm J2K decode failed: {err}")
    if (r.value, c.value) != (rows, cols):
        raise ValueError(
            f"gdcm frame is ({r.value}, {c.value}), header says ({rows}, {cols})"
        )
    dtype = _SCALAR_DTYPES.get(st.value, np.dtype("<u2"))
    return buf.reshape(rows, cols), dtype
