"""Generic image + MetaImage IO.

TPU-native equivalents of the importer/exporter surface the reference
declares but never instantiates (carried as optional components per
SURVEY.md section 2.2): ``ImageFileImporter`` (FAST_directives.hpp:31) →
:func:`read_image`, ``ImageExporter`` (FAST_directives.hpp:27) →
:func:`write_image`, ``MetaImageExporter`` (FAST_directives.hpp:29) →
:func:`write_metaimage` / :func:`read_metaimage`.

MetaImage (.mhd + .raw/.zraw) is the ITK/FAST interchange format for
volumes: a small text header next to a raw little-endian pixel blob,
optionally zlib-compressed. Only the element types FAST images use are
supported.
"""

from __future__ import annotations

import os
import zlib
from pathlib import Path
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

_MET_TO_DTYPE = {
    "MET_UCHAR": np.uint8,
    "MET_CHAR": np.int8,
    "MET_USHORT": np.uint16,
    "MET_SHORT": np.int16,
    "MET_UINT": np.uint32,
    "MET_INT": np.int32,
    "MET_FLOAT": np.float32,
    "MET_DOUBLE": np.float64,
}
_DTYPE_TO_MET = {np.dtype(v): k for k, v in _MET_TO_DTYPE.items()}


def write_image(image: np.ndarray, path: str | os.PathLike) -> None:
    """Write a uint8 grayscale (H, W) or RGB (H, W, 3) array; format by suffix.

    The generic exporter (PNG, BMP, TIFF, JPEG — whatever PIL maps the
    suffix to), as opposed to :func:`render.export.save_jpeg` which is the
    batch drivers' JPEG-only contract path.
    """
    arr = np.asarray(image)
    if arr.dtype != np.uint8:
        raise ValueError(f"expected uint8 image, got {arr.dtype}")
    if arr.ndim not in (2, 3) or (arr.ndim == 3 and arr.shape[-1] != 3):
        raise ValueError(f"expected (H, W) or (H, W, 3), got {arr.shape}")
    from PIL import Image

    Path(path).parent.mkdir(parents=True, exist_ok=True)
    Image.fromarray(arr).save(path)


def read_image(path: str | os.PathLike) -> np.ndarray:
    """Read any PIL-supported image as float32 grayscale (H, W).

    The generic importer; color inputs are luminance-converted, so a slice
    exported with :func:`write_image` round-trips (JPEG: to within
    compression noise).
    """
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("F"), dtype=np.float32)


def write_metaimage(
    image: np.ndarray,
    path: str | os.PathLike,
    spacing: Optional[Sequence[float]] = None,
    compressed: bool = False,
) -> None:
    """Write a 2D/3D array as MetaImage: ``<path>.mhd`` header + data blob.

    ``path`` names the header (.mhd appended when not already the suffix;
    dotted basenames like ``subject.01`` are preserved, not collapsed); the
    pixel data lands next to it as ``.raw`` (or ``.zraw`` zlib-compressed).
    Array axes are (z, y, x) / (y, x); DimSize is written fastest-first
    (x y z) per the MetaIO convention.
    """
    arr = np.ascontiguousarray(image)
    if arr.ndim not in (2, 3):
        raise ValueError(f"MetaImage supports 2D/3D, got shape {arr.shape}")
    met = _DTYPE_TO_MET.get(arr.dtype)
    if met is None:
        raise ValueError(f"unsupported dtype for MetaImage: {arr.dtype}")
    ndims = arr.ndim
    if spacing is None:
        spacing = (1.0,) * ndims
    if len(spacing) != ndims:
        raise ValueError(f"spacing must have {ndims} entries, got {len(spacing)}")

    p = Path(path)
    mhd = p if p.suffix == ".mhd" else p.with_name(p.name + ".mhd")
    data_name = mhd.name[: -len(".mhd")] + (".zraw" if compressed else ".raw")
    payload = arr.tobytes()  # C order; fastest-varying axis is the last (x)
    if compressed:
        payload = zlib.compress(payload)

    dim_size = " ".join(str(s) for s in arr.shape[::-1])
    spacing_str = " ".join(f"{s:g}" for s in spacing[::-1])
    lines = [
        "ObjectType = Image",
        f"NDims = {ndims}",
        f"DimSize = {dim_size}",
        f"ElementSpacing = {spacing_str}",
        f"ElementType = {met}",
        "ElementByteOrderMSB = False",
        f"CompressedData = {'True' if compressed else 'False'}",
        f"ElementDataFile = {data_name}",
    ]
    mhd.parent.mkdir(parents=True, exist_ok=True)
    # tmp+rename (NM351) with BOTH tmps staged before either rename, blob
    # first: each file is individually complete-or-absent, and the only
    # torn state is old-header/new-blob across two adjacent renames. On a
    # re-export that changes dims/dtype that state fails the reader's
    # blob-size-vs-header validation (ValueError, not garbage); the
    # fixed ``<stem>.raw`` naming is the MetaIO convention external tools
    # and the tests rely on, so a content-keyed blob name is not an option
    data_tmp = mhd.parent / (data_name + ".tmp")
    mhd_tmp = mhd.with_name(mhd.name + ".tmp")
    data_tmp.write_bytes(payload)
    mhd_tmp.write_text("\n".join(lines) + "\n")
    os.replace(data_tmp, mhd.parent / data_name)
    os.replace(mhd_tmp, mhd)


def read_metaimage(path: str | os.PathLike) -> Tuple[np.ndarray, Tuple[float, ...]]:
    """Read a .mhd MetaImage; returns (array in (z, y, x)/(y, x) order, spacing).

    Spacing is returned in the same axis order as the array. Raises
    ValueError on malformed headers, unsupported element types, or a data
    blob whose size disagrees with the header.
    """
    mhd = Path(path)
    fields: Dict[str, str] = {}
    # errors="replace": corrupt header bytes garble fields, which then fail
    # the checks below as ValueError — never a UnicodeDecodeError escape
    for line in mhd.read_text(errors="replace").splitlines():
        if "=" in line:
            key, _, val = line.partition("=")
            fields[key.strip()] = val.strip()
    try:
        ndims = int(fields["NDims"])
        shape_xyz = tuple(int(s) for s in fields["DimSize"].split())
        met = fields["ElementType"]
        data_file = fields["ElementDataFile"]
    except KeyError as e:
        raise ValueError(f"{mhd}: missing MetaImage header field {e}") from e
    if len(shape_xyz) != ndims:
        raise ValueError(f"{mhd}: DimSize has {len(shape_xyz)} entries, NDims={ndims}")
    dtype = _MET_TO_DTYPE.get(met)
    if dtype is None:
        raise ValueError(f"{mhd}: unsupported ElementType {met}")
    if fields.get("ElementByteOrderMSB", "False").lower() == "true":
        raise ValueError(f"{mhd}: big-endian MetaImage not supported")
    if data_file == "LOCAL":
        raise ValueError(f"{mhd}: inline (LOCAL) data not supported")
    if data_file == "LIST" or "%" in data_file:
        raise ValueError(
            f"{mhd}: multi-file MetaImage (LIST / pattern data files) not supported"
        )

    try:
        payload = (mhd.parent / data_file).read_bytes()
    except OSError as e:
        # missing/unreadable data file (or a corrupt name resolving to a
        # directory) is a malformed pair per this reader's contract
        raise ValueError(f"{mhd}: cannot read data file {data_file!r}: {e}") from e
    if fields.get("CompressedData", "False").lower() == "true":
        try:
            payload = zlib.decompress(payload)
        except zlib.error as e:
            raise ValueError(f"{mhd}: corrupt compressed data: {e}") from e
    shape = shape_xyz[::-1]  # header is x y z; numpy wants z y x
    expected = int(np.prod(shape)) * np.dtype(dtype).itemsize
    if len(payload) != expected:
        raise ValueError(
            f"{mhd}: data file holds {len(payload)} bytes, header implies {expected}"
        )
    arr = np.frombuffer(payload, dtype=dtype).reshape(shape)
    spacing_field = fields.get("ElementSpacing")
    spacing = (
        tuple(float(s) for s in spacing_field.split())[::-1]
        if spacing_field
        else (1.0,) * ndims
    )
    return arr.copy(), spacing
