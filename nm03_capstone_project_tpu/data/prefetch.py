"""Host -> HBM prefetch: keep the chip fed while the host decodes.

The reference's OpenMP driver keeps its (CPU) compute units busy by forking
threads over a shared heap (src/parallel/main_parallel.cpp:336); a TPU is fed
across PCIe instead, so the equivalent discipline is a *transfer pipeline*:
``jax.device_put`` is asynchronous, so enqueuing the next batch's H2D copy
while the current batch computes hides the transfer entirely (double
buffering, SURVEY.md section 7 step 4 "hard part #2").

Composes with the decode thread pool in :mod:`..cli.runner`: IO workers
decode DICOMs ahead -> :func:`prefetch_to_device` stages them in HBM ahead ->
the jitted program consumes device-resident arrays with zero upload stall.
"""

from __future__ import annotations

import collections
import itertools
from typing import Any, Callable, Iterable, Iterator, Optional, TypeVar

import jax

T = TypeVar("T")


def prefetch_to_device(
    iterator: Iterable[T],
    depth: int = 2,
    device: Optional[Any] = None,
    to_device: Optional[Callable[[Any], Any]] = None,
) -> Iterator[T]:
    """Yield items from ``iterator`` with arrays staged on device ``depth`` ahead.

    Each item is a pytree; its array leaves are moved with ``jax.device_put``
    (asynchronous — the copy overlaps whatever the device is running).
    Non-array leaves (strings, metadata) pass through untouched.

    Args:
      iterator: source of pytree batches.
      depth: how many batches to keep in flight (2 = double buffering).
      device: target `jax.Device` or `Sharding` (default backend's device 0).
      to_device: override the per-item transfer (e.g. to apply a
        NamedSharding to some leaves only).
    """
    it = iter(iterator)
    if to_device is None:
        tgt = device if device is not None else jax.devices()[0]

        def to_device(item):
            return jax.tree.map(
                lambda x: jax.device_put(x, tgt) if hasattr(x, "shape") else x,
                item,
            )

    queue: collections.deque = collections.deque()

    def enqueue(n: int) -> None:
        for item in itertools.islice(it, n):
            queue.append(to_device(item))

    enqueue(max(depth, 1))
    while queue:
        out = queue.popleft()
        enqueue(1)
        yield out
