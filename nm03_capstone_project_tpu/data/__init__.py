"""Data layer: discovery, DICOM-lite IO, synthetic cohorts."""

from nm03_capstone_project_tpu.data.dicomlite import (  # noqa: F401
    DicomParseError,
    DicomSlice,
    read_dicom,
    read_dicom_frames,
    write_dicom,
)
from nm03_capstone_project_tpu.data.imageio import (  # noqa: F401
    read_image,
    read_metaimage,
    write_image,
    write_metaimage,
)
from nm03_capstone_project_tpu.data.discovery import (  # noqa: F401
    extract_file_number,
    find_patient_dirs,
    load_dicom_files_for_patient,
)
from nm03_capstone_project_tpu.data.synthetic import (  # noqa: F401
    phantom_series,
    phantom_slice,
    phantom_volume,
    write_synthetic_cohort,
)
