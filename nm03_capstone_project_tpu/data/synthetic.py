"""Synthetic brain-MRI phantoms and cohorts.

The reference is exercised against the TCIA Brain-Tumor-Progression T1+C
cohort (README.md:98-100), which cannot ship with a test suite. This module
generates deterministic phantoms with the same *contrast structure* the
pipeline's hard-coded thresholds assume:

* raw intensities on the reference's [0, 10000] normalization window,
* brain tissue below the segmentation band, a central hyperintense lesion
  whose normalized intensity lands inside the region-growing band
  [0.74, 0.91] (i.e. raw ~1200-2050 after the [0.5, 2.5] window maps back),
* a bright skull rim above the band,

so seeded region growing segments the lesion exactly as it would a real
T1+C tumor slice. Used by tests, benchmarks, and the CLI's --synthetic mode.
"""

from __future__ import annotations

import numpy as np


def phantom_slice(
    height: int = 256,
    width: int = 256,
    lesion_radius: float = 0.16,
    seed: int = 0,
    noise: float = 40.0,
) -> np.ndarray:
    """One synthetic T1+C-like slice, float32 (height, width), raw intensities.

    Layout (fractions of min(h, w)): elliptical head of tissue ~800 raw,
    skull rim ~6000 raw, central lesion ~1600 raw (inside the band after
    normalization), smooth low-amplitude noise everywhere.
    """
    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:height, 0:width].astype(np.float32)
    cy, cx = (height - 1) / 2.0, (width - 1) / 2.0
    r = min(height, width)

    # normalized elliptical radius of the head
    head = ((yy - cy) / (0.46 * height)) ** 2 + ((xx - cx) / (0.40 * width)) ** 2

    img = np.zeros((height, width), np.float32)
    tissue = head < 1.0
    img[tissue] = 800.0
    rim = (head >= 1.0) & (head < 1.21)
    img[rim] = 6000.0

    # ventricles: two dark lobes slightly above center
    for sx in (-1.0, 1.0):
        vent = ((yy - (cy - 0.08 * r)) / (0.10 * r)) ** 2 + (
            (xx - (cx + sx * 0.09 * r)) / (0.05 * r)
        ) ** 2
        img[(vent < 1.0) & tissue] = 350.0

    # the lesion: centered so the reference's central seeds hit it
    lesion = ((yy - cy) / (lesion_radius * r)) ** 2 + (
        (xx - cx) / (lesion_radius * r)
    ) ** 2
    img[(lesion < 1.0) & tissue] = 1600.0

    # smooth noise that stays well inside each class's margin
    if noise > 0:
        low = rng.normal(0.0, 1.0, (height // 8 + 1, width // 8 + 1))
        coarse = np.kron(low, np.ones((8, 8)))[:height, :width]
        img = img + noise * coarse.astype(np.float32) * (img > 0)

    return np.clip(img, 0.0, 10000.0).astype(np.float32)


def phantom_series(
    n_slices: int = 22,
    height: int = 256,
    width: int = 256,
    seed: int = 0,
) -> list[np.ndarray]:
    """A patient series: the lesion waxes and wanes across slices."""
    out = []
    for i in range(n_slices):
        # lesion radius sweeps 0 -> max -> 0 across the stack
        t = i / max(n_slices - 1, 1)
        radius = 0.16 * float(np.sin(np.pi * t))
        out.append(
            phantom_slice(
                height,
                width,
                lesion_radius=max(radius, 1e-3),
                seed=seed * 1000 + i,
            )
        )
    return out


def phantom_volume(
    n_slices: int = 16, height: int = 128, width: int = 128, seed: int = 0
) -> np.ndarray:
    """(D, H, W) float32 stack for the 3D volumetric pipeline."""
    return np.stack(phantom_series(n_slices, height, width, seed))


def write_synthetic_cohort(
    root,
    n_patients: int = 3,
    n_slices: int = 8,
    height: int = 256,
    width: int = 256,
    seed: int = 0,
) -> list[str]:
    """Materialize a phantom cohort with the reference's directory layout.

    Creates ``<root>/PGBM-000i/<series>/1-<j>.dcm`` mirroring the TCIA
    Brain-Tumor-Progression layout the discovery contract expects
    (main_sequential.cpp:93-168); returns the patient IDs. The written files
    round-trip through :mod:`.dicomlite`, so the whole data path — discovery,
    DICOM decode, padding, pipeline — runs exactly as it would on real data.
    """
    from pathlib import Path

    from nm03_capstone_project_tpu.data.dicomlite import write_dicom

    root = Path(root)
    patient_ids = []
    for p in range(n_patients):
        pid = f"PGBM-{p + 1:04d}"
        patient_ids.append(pid)
        series_dir = (
            root / pid / f"01-01-2000-MR-BRAIN-{p + 1:03d}"
        )
        series_dir.mkdir(parents=True, exist_ok=True)
        series = phantom_series(n_slices, height, width, seed=seed * 100 + p)
        for j, img in enumerate(series):
            write_dicom(
                series_dir / f"1-{j + 1:02d}.dcm",
                np.clip(img, 0, 65535).astype(np.uint16),
                patient_id=pid,
                series_uid=f"1.2.826.0.1.3680043.9999.{p + 1}",
                instance_number=j + 1,
            )
    return patient_ids
