"""Single-slice visual test driver.

Entry point mirroring the reference's ``test_pipeline``
(src/test/test_pipeline.cpp:29-182): one 2D slice through every stage, each
intermediate exported as a JPEG to ``out-test/`` (the reference's
golden-eyeball testing surface). The reference hard-codes one PGBM-017 slice
and blocks on a 5-pane Qt window; here the input is a flag (``--input``,
or a generated phantom by default), the "window" is the set of exported stage
images (original, preprocessed, segmentation, erosion, dilation — the same 5
panes, test_pipeline.cpp:148-158), and nothing blocks, so it runs headless.
"""

from __future__ import annotations

import argparse
import sys

from nm03_capstone_project_tpu.cli import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nm03-test-pipeline", description=__doc__.strip().splitlines()[0])
    p.add_argument("--input", default=None, help=".dcm slice to process (default: synthetic phantom)")
    p.add_argument("--output", default="out-test", help="stage-image output directory")
    p.add_argument(
        "--device", choices=["auto", "tpu", "cpu"], default="auto", help="compute backend"
    )
    p.add_argument(
        "--show",
        action="store_true",
        help="display the 5 stage panes in a blocking window (the reference's "
        "MultiViewWindow::run(), test_pipeline.cpp:148-158); requires a display",
    )
    p.add_argument("--verbose", action="store_true")
    common.add_pipeline_args(p)
    return p


def show_panel(exports: dict) -> bool:
    """Blocking 5-pane viewer mirroring MultiViewWindow (test_pipeline.cpp:148-158).

    One matplotlib window, 5 panes side by side on a black background (the
    reference's 2300x450 layout, Color::Black()); ``run()``-style blocking
    until the user closes it. Returns False (with a warning) when no GUI
    backend is usable, so headless runs degrade to the exported panel JPEG.
    """
    import os

    try:
        # only Linux signals a display via these vars; macOS/Windows GUI
        # backends work without them — there, let matplotlib try
        if sys.platform.startswith("linux") and not (
            os.environ.get("DISPLAY") or os.environ.get("WAYLAND_DISPLAY")
        ):
            raise RuntimeError("no display available")
        import matplotlib.pyplot as plt

        fig, axes = plt.subplots(
            1, len(exports), figsize=(23, 4.5), facecolor="black"
        )
        for ax, (name, img) in zip(axes, exports.items()):
            ax.imshow(img, cmap="gray" if img.ndim == 2 else None)
            ax.set_title(name, color="white", fontsize=9)
            ax.set_facecolor("black")
            ax.axis("off")
        fig.tight_layout()
        plt.show()  # blocking, like multiWindow->run()
        plt.close(fig)
        return True
    except Exception as e:  # noqa: BLE001 — headless/backend failure
        print(f"--show unavailable ({e!r}); see the exported pipeline_panel.jpg",
              file=sys.stderr)
        return False


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    common.apply_device_env(args.device)
    try:
        return run(args)
    except Exception as e:  # noqa: BLE001
        print(f"Fatal error: {e}", file=sys.stderr)
        return 1


def stage_renders(padded, dims, cfg) -> dict:
    """The 5 exported stage renders, keyed by the reference's export names.

    The single home of the test driver's golden-image contract
    (test_pipeline.cpp:162-179: original + preprocessed as grayscale renders,
    segmentation / erosion / dilation as white-label renders, all through the
    512x512 letterbox). The golden regression suite (tests/test_golden.py)
    pins these exact pixels.
    """
    import numpy as np

    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice_stages
    from nm03_capstone_project_tpu.render.render import (
        render_gray,
        render_segmentation,
    )

    stages = process_slice_stages(padded, dims, cfg)
    if not bool(np.asarray(stages["grow_converged"])):
        print(
            "WARNING: region growing hit its iteration cap; the segmentation "
            "under-covers (raise --grow-max-iters)"
        )

    def seg_render(m):
        return render_segmentation(
            m, dims, cfg.render_size, cfg.overlay_opacity,
            cfg.overlay_border_opacity, cfg.overlay_border_radius,
        )

    return {
        name: np.asarray(img)  # one device->host transfer per stage
        for name, img in {
            "original_image": render_gray(
                stages["original_image"], dims, cfg.render_size
            ),
            "preprocessed_image": render_gray(
                stages["preprocessed_image"], dims, cfg.render_size
            ),
            "segmentation": seg_render(stages["segmentation"]),
            "erosion_result": seg_render(stages["erosion_result"]),
            "final_dilated_result": seg_render(stages["final_dilated_result"]),
        }.items()
    }


def run(args: argparse.Namespace) -> int:
    import numpy as np

    from nm03_capstone_project_tpu.data.synthetic import phantom_slice
    from nm03_capstone_project_tpu.render.export import clean_directory, save_jpeg
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting

    configure_reporting(verbose=args.verbose)
    common.enable_compile_cache()
    common.apply_native_flag(args)
    cfg = common.pipeline_config_from_args(args)

    if args.input:
        from nm03_capstone_project_tpu.data.dicomlite import read_dicom

        pixels = read_dicom(args.input).pixels
    else:
        pixels = phantom_slice(256, 256, seed=17)

    h, w = pixels.shape
    if h > cfg.canvas or w > cfg.canvas:
        raise ValueError(f"slice {w}x{h} exceeds canvas {cfg.canvas}; raise --canvas")
    padded = np.zeros((cfg.canvas, cfg.canvas), np.float32)
    padded[:h, :w] = pixels
    dims = np.asarray([h, w], np.int32)

    # the reference clean-recreates out-test (test_pipeline.cpp:13-14)
    clean_directory(args.output)

    exports = stage_renders(padded, dims, cfg)
    for name, img in exports.items():
        save_jpeg(img, f"{args.output}/{name}.jpg")
        print(f"exported {args.output}/{name}.jpg")

    # the 5-pane window (MultiViewWindow, test_pipeline.cpp:148-158), as a
    # composed strip a headless run can still eyeball
    from nm03_capstone_project_tpu.render.contact_sheet import contact_sheet

    sheet = contact_sheet(list(exports.values()), labels=list(exports))
    save_jpeg(sheet, f"{args.output}/pipeline_panel.jpg")
    print(f"exported {args.output}/pipeline_panel.jpg")

    if args.show:
        show_panel(exports)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
