"""Parallel batch driver.

Entry point mirroring the reference's ``img_processing_parallel``
(src/parallel/main_parallel.cpp:389-411). The reference parallelizes with 16
OpenMP threads over a <=25-slice batch and serializes exports through one
shared Qt render target; here the batch is a vmapped leading axis of ONE
compiled XLA program (decode on an IO thread pool, JPEG encode overlapped
with the next batch's device compute) — same contract, no threads to guard,
bit-identical to the sequential driver by construction.

Observability (``--metrics-out`` / ``--log-json`` / ``--heartbeat-s``,
docs/OBSERVABILITY.md) rides through the shared :func:`sequential.run`:
outcome counters fire from the IO-pool threads (the registry is
thread-safe) and every patient gets one terminal ``patient_outcome`` event.
"""

from __future__ import annotations

import argparse

from nm03_capstone_project_tpu.cli import common
from nm03_capstone_project_tpu.cli.sequential import run


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-parallel", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument("--output", default="out-parallel", help="output root directory")
    common.add_common_args(p)
    common.add_pipeline_args(p)
    common.add_batch_args(p)
    common.add_ingest_args(p)
    common.add_render_stage_arg(p)
    common.add_model_arg(p)
    common.add_resilience_args(p)
    common.add_distributed_args(
        p,
        "Patients are round-robin sharded across processes, each on its "
        "local devices; only the final summary crosses hosts.",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    common.apply_device_env(args.device)
    return run(args, mode="parallel")


if __name__ == "__main__":
    raise SystemExit(main())
