"""Distillation training driver for the learned model family.

No reference counterpart exists — the reference is inference-only
(SURVEY.md section 5 lists training/checkpointing as absent) — so this
driver rounds out the framework: it reads a cohort exactly like the batch
drivers (same discovery contract, same synthetic option), labels it by
running the classical pipeline as teacher, trains the U-Net student, reports
student-vs-teacher IoU, and writes an orbax checkpoint a later run can
``--restore`` to fine-tune or ``--eval-only`` to score.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from nm03_capstone_project_tpu.cli import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-train", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument("--output", default="out-train", help="checkpoint/results root")
    # the batch drivers' flags minus the ones training has no use for
    # (--resume is the manifest's concept, --no-native the decode path's)
    p.add_argument(
        "--base-path",
        default=None,
        help="cohort root (defaults to $NM03_DATA_PATH/"
        f"{common.DEFAULT_COHORT_SUBPATH}); ignored with --synthetic",
    )
    p.add_argument(
        "--synthetic", type=int, default=0, metavar="N",
        help="generate an N-patient synthetic cohort instead of reading real data",
    )
    p.add_argument(
        "--synthetic-slices", type=int, default=8, help="slices per synthetic patient"
    )
    p.add_argument(
        "--device", choices=["auto", "tpu", "cpu"], default="auto",
        help="compute backend (cpu uses the host XLA backend)",
    )
    p.add_argument("--verbose", action="store_true", help="enable INFO logging")
    p.add_argument(
        "--results-json", default=None, help="write a training-results JSON"
    )
    p.add_argument(
        "--profile-dir", default=None,
        help="capture a jax.profiler trace of the training loop here",
    )
    common.add_observability_args(p)
    common.add_pipeline_args(p)
    common.add_distributed_args(
        p,
        "Training shards slices across processes (teacher distillation "
        "scales linearly); gradients psum over the global data axis every "
        "step; rank 0 writes the checkpoint. 2D student only.",
    )
    t = p.add_argument_group("training")
    t.add_argument("--steps", type=int, default=300)
    t.add_argument("--lr", type=float, default=3e-3)
    t.add_argument("--base-channels", type=int, default=16)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument(
        "--max-slices", type=int, default=256, help="cap on training slices loaded"
    )
    t.add_argument("--restore", default=None, help="checkpoint to continue from")
    t.add_argument(
        "--model-3d",
        action="store_true",
        help="train the volumetric U-Net (models/unet3d.py) against the 3D "
        "pipeline teacher instead of the per-slice 2D student",
    )
    t.add_argument(
        "--volume-depth", type=int, default=8,
        help="slices per training volume with --model-3d (divisible by 4; "
        "patients with fewer usable slices are skipped)",
    )
    t.add_argument(
        "--eval-only",
        action="store_true",
        help="skip training; just score --restore against the teacher",
    )
    t.add_argument(
        "--bf16", action="store_true", help="bfloat16 compute (TPU-native precision)"
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    common.apply_device_env(args.device)
    try:
        return run(args)
    except Exception as e:  # noqa: BLE001
        print(f"Fatal error: {e}", file=sys.stderr)
        return 1


def _load_cohort(args, cfg, rank=0, world=1):
    """(pixels, dims) float32/int32 host arrays, padded to the canvas."""
    import numpy as np

    from nm03_capstone_project_tpu.cli.runner import decode_and_guard
    from nm03_capstone_project_tpu.data.discovery import (
        find_patient_dirs,
        load_dicom_files_for_patient,
    )

    base = common.resolve_base_path_sync(args, rank, world, tmp_root=Path(args.output))
    pixels, dims = [], []
    for patient_id in find_patient_dirs(base):
        for f in load_dicom_files_for_patient(base, patient_id):
            if len(pixels) >= args.max_slices:
                break
            # the batch drivers' shared containment contract: broad catch on
            # decode + min-dim + canvas-fit guards, skip-and-continue
            px = decode_and_guard(f, cfg)
            if px is None:
                continue
            h, w = px.shape
            canvas = np.zeros((cfg.canvas, cfg.canvas), np.float32)
            canvas[:h, :w] = px
            pixels.append(canvas)
            dims.append((h, w))
    if not pixels:
        raise SystemExit(f"no usable slices under {base}")
    return np.stack(pixels), np.asarray(dims, np.int32)


def _load_cohort_volumes(args, cfg, rank=0, world=1):
    """(volumes, dims): (P, depth, canvas, canvas) float32 + (P, 2) int32.

    One training volume per patient: the first ``--volume-depth`` usable
    slices in anatomical order, assembled by the volume driver's own loader
    (one home for the decode/series-uniformity/canvas contract) and
    truncated to the common depth. Patients with fewer usable slices are
    skipped and counted, mirroring the batch drivers' accounting.
    """
    import numpy as np

    from nm03_capstone_project_tpu.cli.volume import _load_volume
    from nm03_capstone_project_tpu.data.discovery import find_patient_dirs

    base = common.resolve_base_path_sync(args, rank, world, tmp_root=Path(args.output))
    depth = args.volume_depth
    vols, dims, skipped = [], [], 0
    for patient_id in find_patient_dirs(base):
        if len(vols) * depth >= args.max_slices:
            break
        try:
            vol, pdims, _stems, _skips = _load_volume(base, patient_id, cfg)
        except ValueError:
            skipped += 1
            continue
        if vol.shape[0] < depth:
            skipped += 1
            continue
        vols.append(vol[:depth])
        dims.append(pdims)
    if skipped:
        print(f"skipped {skipped} patients with < {depth} usable slices")
    if not vols:
        raise SystemExit(f"no patient under {base} has {depth} usable slices")
    return np.stack(vols), np.asarray(dims, np.int32)


def run(args: argparse.Namespace) -> int:
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting

    configure_reporting(verbose=args.verbose)
    rank, world = common.init_distributed(args)
    run_ctx = common.make_run_context(args, "train", rank=rank)
    try:
        rc = _train(args, rank, world, run_ctx)
        run_ctx.close(status="ok" if rc == 0 else "error")
        return rc
    except BaseException as e:  # SystemExit validation paths included
        run_ctx.close(status="error", error_class=type(e).__name__)
        raise


def _train(args: argparse.Namespace, rank: int, world: int, run_ctx) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.models import (
        apply_unet3d,
        distill_batch,
        distill_volume,
        fit,
        init_unet,
        init_unet3d,
        predict_mask,
        predict_mask3d,
        prepare_student_inputs,
    )
    from nm03_capstone_project_tpu.models.checkpoint import load_params, save_params
    from nm03_capstone_project_tpu.utils.timing import write_results_json

    from nm03_capstone_project_tpu.core.image import valid_mask
    from nm03_capstone_project_tpu.utils.profiling import profile_trace

    spans = run_ctx.spans
    common.enable_compile_cache()
    cfg = common.pipeline_config_from_args(args)
    if world > 1 and args.model_3d:
        raise SystemExit("--distributed training supports the 2D student only")
    if cfg.canvas % 4:
        raise SystemExit("--canvas must be divisible by 4 (two U-Net poolings)")
    if args.eval_only and not args.restore:
        raise SystemExit("--eval-only needs --restore (nothing to score otherwise)")
    if args.model_3d and (args.volume_depth <= 0 or args.volume_depth % 4):
        raise SystemExit(
            "--volume-depth must be positive and divisible by 4 (two 3D poolings)"
        )
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32

    # restore (and model-dimension sanity) BEFORE the expensive cohort load +
    # teacher distillation: a mismatched checkpoint must fail in milliseconds
    if args.restore:
        params, meta = load_params(args.restore)
        print(f"restored checkpoint {args.restore} (meta: {meta})")
        meta = meta or {}
        if bool(meta.get("model_3d", False)) != args.model_3d:
            raise SystemExit(
                f"checkpoint {args.restore} holds a "
                f"{'3D' if meta.get('model_3d') else '2D'} model; pass "
                f"{'--model-3d' if meta.get('model_3d') else 'no --model-3d'}"
            )
    elif args.model_3d:
        params = init_unet3d(jax.random.PRNGKey(args.seed), base=args.base_channels)
    else:
        params = init_unet(jax.random.PRNGKey(args.seed), base=args.base_channels)

    if args.model_3d:
        with spans.span("load_cohort"):
            volumes, dims = _load_cohort_volumes(args, cfg, rank, world)
        print(
            f"cohort: {volumes.shape[0]} volumes of {args.volume_depth} x "
            f"{cfg.canvas}x{cfg.canvas}"
        )
        px = jnp.asarray(volumes)
        dm = jnp.asarray(dims)
        print("distilling teacher labels (volumetric pipeline)...")
        # per-volume teacher: 6-connected 3D growing + 3D morphology
        with spans.span("distill"):
            labels = jnp.stack(
                [distill_volume(v, d, cfg) for v, d in zip(px, dm)]
            )
    else:
        with spans.span("load_cohort"):
            pixels, dims = _load_cohort(args, cfg, rank, world)
        print(f"cohort: {pixels.shape[0]} slices at {cfg.canvas}x{cfg.canvas}")
        if world > 1:
            # every rank loaded the identical cohort, so this check is
            # UNIFORM — raising on only the empty-shard ranks would strand
            # the others at the next collective until the heartbeat timeout
            if pixels.shape[0] < world:
                raise SystemExit(
                    f"cohort has {pixels.shape[0]} usable slices < "
                    f"{world} processes — shrink the job or grow the cohort"
                )
            # shard slices BEFORE distillation: teacher labeling is the
            # expensive part and scales linearly with hosts this way
            pixels, dims = pixels[rank::world], dims[rank::world]
            print(f"process {rank}/{world}: {pixels.shape[0]} slices assigned")
        px = jnp.asarray(pixels)
        dm = jnp.asarray(dims)
        print("distilling teacher labels (classical pipeline)...")
        with spans.span("distill"):
            labels = distill_batch(px, dm, cfg)
    x = prepare_student_inputs(px, cfg)

    apply_fn = apply_unet3d if args.model_3d else None  # None = 2D default
    losses = []
    if not args.eval_only:
        n_dev = len(jax.devices())
        with profile_trace(args.profile_dir), spans.span("train"):
            if world > 1:
                # multi-host data parallelism: every host contributes its
                # local shard to one global batch; gradients psum over the
                # global data axis (tp stays 1 — tensor parallelism across
                # DCN would put an all-reduce on the slow links)
                from jax.experimental import multihost_utils

                from nm03_capstone_project_tpu.models import (
                    fit_distributed,
                    pad_local_shard,
                )

                ldev = len(jax.local_devices())
                counts = np.asarray(
                    multihost_utils.process_allgather(
                        np.asarray([x.shape[0]], np.int32)
                    )
                )
                per_rank = -(-int(counts.max()) // ldev) * ldev
                x_l, lb_l, dm_l = pad_local_shard(
                    np.asarray(x), np.asarray(labels), np.asarray(dm), per_rank
                )
                print(
                    f"training {args.steps} steps at lr={args.lr} over "
                    f"{world} hosts x {ldev} devices "
                    f"(global batch {world * per_rank})..."
                )
                params, losses = fit_distributed(
                    params, x_l, lb_l, dm_l,
                    steps=args.steps, lr=args.lr, compute_dtype=dtype,
                )
            elif n_dev > 1 and not args.model_3d:
                # dp x tp over every visible device: batch on 'data',
                # parameters split on output channels over 'model' (the
                # sharded step the multi-chip dryrun validates). The 3D
                # student stays single-device for now.
                from nm03_capstone_project_tpu.models import fit_sharded
                from nm03_capstone_project_tpu.parallel import make_mesh

                tp = 2 if n_dev % 2 == 0 else 1
                mesh = make_mesh(
                    n_dev,
                    axis_names=("data", "model"),
                    axis_sizes=(n_dev // tp, tp),
                )
                print(
                    f"training {args.steps} steps at lr={args.lr} on "
                    f"{n_dev} devices (dp={n_dev // tp} x tp={tp})..."
                )
                params, losses = fit_sharded(
                    params, x, labels, dm, mesh,
                    steps=args.steps, lr=args.lr, compute_dtype=dtype,
                )
            else:
                print(f"training {args.steps} steps at lr={args.lr}...")
                params, losses = fit(
                    params, x, labels, dm, steps=args.steps, lr=args.lr,
                    compute_dtype=dtype, apply_fn=apply_fn,
                )
        if losses:
            print(f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")

    # score only where the loss trained the student: canvas padding holds
    # untrained logits and must not pollute the metric
    vmask = np.asarray(valid_mask(dm, cfg.canvas_hw)).astype(bool)
    if args.model_3d:
        vmask = np.broadcast_to(vmask[:, None, :, :], px.shape)
        pred = np.asarray(predict_mask3d(params, x, dtype)).astype(bool) & vmask
    else:
        pred = np.asarray(predict_mask(params, x, dtype)).astype(bool) & vmask
    truth = np.asarray(labels).astype(bool) & vmask
    inter = int((pred & truth).sum())
    union = int((pred | truth).sum())
    n_scored = int(pred.shape[0])
    if world > 1:
        # each rank scored its own (unpadded) shard; one allgather gives the
        # cohort-wide IoU every rank agrees on
        agg = common.allgather_cluster_counts(
            {"inter": inter, "union": union, "n": n_scored}, world
        )
        inter, union, n_scored = agg["inter"], agg["union"], agg["n"]
    iou = inter / union if union else 1.0
    unit = "volumes" if args.model_3d else "slices"
    if rank == 0:
        print(f"student-vs-teacher IoU over {n_scored} {unit}: {iou:.3f}")
    from nm03_capstone_project_tpu.obs.metrics import (
        TRAIN_FINAL_LOSS,
        TRAIN_IOU_VS_TEACHER,
    )

    run_ctx.registry.gauge(
        TRAIN_IOU_VS_TEACHER, help="student-vs-teacher IoU"
    ).set(iou)
    if losses:
        run_ctx.registry.gauge(
            TRAIN_FINAL_LOSS, help="last training-step loss"
        ).set(float(losses[-1]))
    run_ctx.events.emit(
        "train_scored", iou_vs_teacher=iou, n_scored=n_scored, unit=unit
    )

    ckpt = Path(args.output) / "checkpoint"
    if not args.eval_only:
        # every rank enters the save together: orbax checkpointing is a
        # collective in a multiprocess job (its internal barrier would hang
        # rank 0 if the others had already exited); the write itself lands
        # once (params are replicated)
        save_params(
            ckpt,
            params,
            meta={
                "base_channels": args.base_channels,
                "steps": args.steps,
                "lr": args.lr,
                "canvas": cfg.canvas,
                # the student's input space: deployment must reproduce the
                # exact normalize+clip the network was trained behind
                "norm": [
                    cfg.norm_low,
                    cfg.norm_high,
                    cfg.norm_intensity_min,
                    cfg.norm_intensity_max,
                ],
                "clip": [cfg.clip_low, cfg.clip_high],
                "model_3d": args.model_3d,
                "iou_vs_teacher": iou,
            }
            if rank == 0
            else None,
        )
        if rank == 0:
            print(f"checkpoint written to {ckpt}")
    if rank != 0:
        return 0
    if args.results_json:
        write_results_json(
            args.results_json,
            {
                unit: n_scored,
                "model": "unet3d" if args.model_3d else "unet2d",
                "steps": 0 if args.eval_only else args.steps,
                "final_loss": losses[-1] if losses else None,
                "iou_vs_teacher": iou,
                "metrics": run_ctx.metrics_snapshot(),
            },
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
