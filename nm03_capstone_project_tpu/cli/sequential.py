"""Sequential batch driver.

Entry point mirroring the reference's ``img_processing_sequential``
(src/sequential/main_sequential.cpp:346-363): all patients, one slice at a
time, per-slice JPEG pair export, catch-and-continue fault tolerance, success
accounting — plus what the reference lacks: ``--device``, flags for every
constant, ``--resume``, ``--synthetic`` cohorts, and an in-tree results JSON.
"""

from __future__ import annotations

import argparse
import sys

from nm03_capstone_project_tpu.cli import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-sequential", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument("--output", default="out-sequential", help="output root directory")
    common.add_common_args(p)
    common.add_pipeline_args(p)
    common.add_ingest_args(p)
    common.add_render_stage_arg(p)
    common.add_model_arg(p)
    common.add_resilience_args(p)
    # run() already handles world>1 (patient shard + collective accounting);
    # without this the advertised `nm03-sequential --distributed` died at
    # argparse (ADVICE r2)
    common.add_distributed_args(p)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    common.apply_device_env(args.device)
    return run(args, mode="sequential")


def run(args: argparse.Namespace, mode: str) -> int:
    # jax-importing modules stay inside run() so --device can pin the backend
    from pathlib import Path

    from nm03_capstone_project_tpu.cli.runner import CohortProcessor
    from nm03_capstone_project_tpu.config import BatchConfig
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting
    from nm03_capstone_project_tpu.utils.timing import write_results_json

    configure_reporting(verbose=args.verbose)
    common.apply_native_flag(args)
    common.enable_compile_cache()
    cfg = common.pipeline_config_from_args(args)
    batch_cfg = BatchConfig(
        batch_size=getattr(args, "batch_size", BatchConfig.batch_size),
        io_workers=getattr(args, "io_workers", BatchConfig.io_workers),
        prefetch_depth=getattr(args, "prefetch_depth", BatchConfig.prefetch_depth),
        ingest_depth=getattr(args, "ingest_depth", BatchConfig.ingest_depth),
        ingest_decode_workers=getattr(
            args, "ingest_decode_workers", BatchConfig.ingest_decode_workers
        ),
        use_native=not getattr(args, "no_native", False),
        render_stage=getattr(args, "render_stage", BatchConfig.render_stage),
    )
    from nm03_capstone_project_tpu.utils.profiling import profile_trace

    run_ctx = None
    try:
        rank, world = common.init_distributed(args)
        run_ctx = common.make_run_context(args, mode, rank=rank)
        base = common.resolve_base_path_sync(
            args, rank, world, tmp_root=Path(args.output)
        )
        model_params = common.load_model_checkpoint(args, cfg)
        proc = CohortProcessor(
            base,
            args.output,
            cfg=cfg,
            batch_cfg=batch_cfg,
            mode=mode,
            resume=args.resume,
            process_rank=rank,
            process_count=world,
            model_params=model_params,
            obs=run_ctx,
            resilience=common.resilience_config_from_args(args),
        )
        import time

        t0 = time.perf_counter()
        with profile_trace(getattr(args, "profile_dir", None)):
            summary = proc.process_all_patients()
        wall_s = time.perf_counter() - t0

        cluster = None
        if world > 1:
            # the one DCN crossing of the whole run (a collective: if a rank
            # died earlier the others block here until the coordinator's
            # missed-heartbeat handling fails the job — the standard SPMD
            # failure mode, preferred over reporting partial totals as global)
            cluster = common.allgather_cluster_counts(
                {
                    "patients_ok": summary.patients_ok,
                    "patients_total": len(summary.patients),
                    "slices_ok": summary.succeeded_slices,
                    "slices_total": summary.total_slices,
                },
                world,
            )
            if rank == 0:
                print(
                    f"\nCluster totals: {cluster['patients_ok']}/"
                    f"{cluster['patients_total']} patients, "
                    f"{cluster['slices_ok']}/{cluster['slices_total']} slices "
                    f"across {world} processes."
                )

        from nm03_capstone_project_tpu.obs.metrics import (
            PIPELINE_FEED_STALL_RATIO,
            RUN_WALL_SECONDS,
        )

        run_ctx.registry.gauge(
            RUN_WALL_SECONDS, help="end-to-end driver wall clock"
        ).set(wall_s)
        # feed-stall accounting (ISSUE 10): the fraction of wall the device
        # sat starved by the serial decode->stage->dispatch->fetch feed —
        # the before/after number ROADMAP item 3's streaming ingest lands
        # on. Both drivers run through here; the report also rides the
        # event stream and (when a device batch ran at all) the gauge.

        feed_stall = proc.feed.report()
        if feed_stall["feed_stall_ratio"] is not None:
            run_ctx.registry.gauge(
                PIPELINE_FEED_STALL_RATIO,
                help="fraction of wall time no device dispatch was in "
                "flight — serial-feed starvation (obs.saturation; a lower "
                "bound: the dispatch interval is enqueue->fetch complete)",
            ).set(feed_stall["feed_stall_ratio"])
        run_ctx.events.emit("feed_stall", mode=mode, **feed_stall)
        # streaming-ingest drain (ISSUE 11): refresh the ingest_* gauges
        # from the run-level aggregate (so the final --metrics-out carries
        # ring occupancy / decode lookahead / upload overlap) and put the
        # same numbers on the event stream next to the feed_stall they
        # exist to explain
        ingest_rep = proc.publish_ingest()
        if ingest_rep is not None:
            run_ctx.events.emit("ingest_drained", mode=mode, **ingest_rep)
        if args.results_json and rank == 0:
            import jax

            # backend honesty (bench-evidence contract): requested is the
            # --device flag, actual is what the run finished on — a PR-3
            # one-way degradation means the tail of the cohort ran on the
            # CPU fallback, and the record must say so rather than let a
            # degraded run masquerade as a chip number
            platform = jax.devices()[0].platform
            degraded = proc.dispatch.degraded
            record = {
                "mode": mode,
                "backend": platform,  # legacy alias of backend_actual
                "backend_requested": args.device,
                "backend_actual": "cpu" if degraded else platform,
                "backend_degraded": bool(degraded),
                **(
                    {"backend_degraded_cause": proc.dispatch.degraded_cause}
                    if degraded
                    else {}
                ),
                "summary": summary.as_dict(),
                # wall_s is the number to compare across drivers/modes:
                # in the parallel driver device compute overlaps the
                # export wait, so per-section times don't partition it
                "wall_s": round(wall_s, 3),
                "timing_s": proc.timer.report(),
                # the feed_stall report (docs/OBSERVABILITY.md): per-phase
                # busy unions + the device-starvation headline
                "feed_stall": feed_stall,
                # the streaming-ingest aggregate (ring occupancy, decode
                # lookahead, upload overlap — docs/OBSERVABILITY.md)
                "ingest": ingest_rep,
                # the full observability snapshot rides in the results JSON
                # too, so one artifact carries outcome counters + stage
                # latency distributions next to the wall-clock headline
                "metrics": run_ctx.metrics_snapshot(),
            }
            if cluster is not None:
                record["cluster"] = cluster  # rank 0's summary/timing above
                record["process_count"] = world
            write_results_json(args.results_json, record)
        run_ctx.close(status="ok", wall_s=round(wall_s, 3))
        return 0
    except Exception as e:  # noqa: BLE001 - reference: fatal-error catch in main
        print(f"Fatal error: {e}", file=sys.stderr)
        if run_ctx is not None:
            run_ctx.close(status="error", error_class=type(e).__name__)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
