"""Sequential batch driver.

Entry point mirroring the reference's ``img_processing_sequential``
(src/sequential/main_sequential.cpp:346-363): all patients, one slice at a
time, per-slice JPEG pair export, catch-and-continue fault tolerance, success
accounting — plus what the reference lacks: ``--device``, flags for every
constant, ``--resume``, ``--synthetic`` cohorts, and an in-tree results JSON.
"""

from __future__ import annotations

import argparse
import sys

from nm03_capstone_project_tpu.cli import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-sequential", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument("--output", default="out-sequential", help="output root directory")
    common.add_common_args(p)
    common.add_pipeline_args(p)
    common.add_render_stage_arg(p)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    common.apply_device_env(args.device)
    return run(args, mode="sequential")


def run(args: argparse.Namespace, mode: str) -> int:
    # jax-importing modules stay inside run() so --device can pin the backend
    from pathlib import Path

    from nm03_capstone_project_tpu.cli.runner import CohortProcessor
    from nm03_capstone_project_tpu.config import BatchConfig
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting
    from nm03_capstone_project_tpu.utils.timing import write_results_json

    configure_reporting(verbose=args.verbose)
    common.apply_native_flag(args)
    common.enable_compile_cache()
    cfg = common.pipeline_config_from_args(args)
    batch_cfg = BatchConfig(
        batch_size=getattr(args, "batch_size", BatchConfig.batch_size),
        io_workers=getattr(args, "io_workers", BatchConfig.io_workers),
        prefetch_depth=getattr(args, "prefetch_depth", BatchConfig.prefetch_depth),
        use_native=not getattr(args, "no_native", False),
        render_stage=getattr(args, "render_stage", BatchConfig.render_stage),
    )
    from nm03_capstone_project_tpu.utils.profiling import profile_trace

    try:
        rank, world = 0, 1
        if getattr(args, "distributed", False):
            from nm03_capstone_project_tpu.parallel import distributed

            distributed.initialize(
                coordinator_address=getattr(args, "coordinator_address", None),
                num_processes=getattr(args, "num_processes", None),
                process_id=getattr(args, "process_id", None),
            )
            info = distributed.process_info()
            rank, world = info["process_index"], info["process_count"]
            want = getattr(args, "num_processes", None)
            if want and want > 1 and world == 1:
                # an explicitly requested multi-process job that joined
                # nothing must not silently have every worker process the
                # whole cohort into the same tree
                raise RuntimeError(
                    f"--distributed --num-processes {want} requested but this "
                    "process joined no cluster (world=1); check the "
                    "coordinator address / process ids"
                )
            if world == 1:
                print(
                    "--distributed: no cluster detected; running single-process",
                    file=sys.stderr,
                )

        if world > 1 and args.synthetic > 0:
            # only rank 0 generates the shared synthetic cohort; a barrier
            # keeps other ranks from listing a half-written tree
            from jax.experimental import multihost_utils

            if rank == 0:
                base = common.resolve_base_path(args, tmp_root=Path(args.output))
            multihost_utils.sync_global_devices("nm03 synthetic cohort ready")
            if rank != 0:
                base = common.resolve_base_path(args, tmp_root=Path(args.output))
        else:
            base = common.resolve_base_path(args, tmp_root=Path(args.output))
        proc = CohortProcessor(
            base,
            args.output,
            cfg=cfg,
            batch_cfg=batch_cfg,
            mode=mode,
            resume=args.resume,
            process_rank=rank,
            process_count=world,
        )
        import time

        t0 = time.perf_counter()
        with profile_trace(getattr(args, "profile_dir", None)):
            summary = proc.process_all_patients()
        wall_s = time.perf_counter() - t0

        cluster = None
        if world > 1:
            # the one DCN crossing of the whole run: allgather each rank's
            # success counters so rank 0 can report the cohort-wide totals
            # (the reference's end-of-run accounting, main_parallel.cpp:349).
            # If a rank died before reaching this collective the others block
            # here until the coordinator's missed-heartbeat handling fails
            # the job — the standard SPMD failure mode, preferred over
            # skipping the aggregate and reporting partial totals as global.
            import numpy as np
            from jax.experimental import multihost_utils

            counts = np.asarray(
                [
                    summary.patients_ok,
                    len(summary.patients),
                    summary.succeeded_slices,
                    summary.total_slices,
                ],
                np.int32,
            )
            gathered = np.asarray(
                multihost_utils.process_allgather(counts)
            ).reshape(world, 4)
            cluster = {
                "patients_ok": int(gathered[:, 0].sum()),
                "patients_total": int(gathered[:, 1].sum()),
                "slices_ok": int(gathered[:, 2].sum()),
                "slices_total": int(gathered[:, 3].sum()),
                "per_process": {
                    str(r): {
                        "patients_ok": int(gathered[r, 0]),
                        "patients_total": int(gathered[r, 1]),
                        "slices_ok": int(gathered[r, 2]),
                        "slices_total": int(gathered[r, 3]),
                    }
                    for r in range(world)
                },
            }
            if rank == 0:
                print(
                    f"\nCluster totals: {cluster['patients_ok']}/"
                    f"{cluster['patients_total']} patients, "
                    f"{cluster['slices_ok']}/{cluster['slices_total']} slices "
                    f"across {world} processes."
                )

        if args.results_json and rank == 0:
            import jax

            record = {
                "mode": mode,
                "backend": jax.devices()[0].platform,  # provenance
                "summary": summary.as_dict(),
                # wall_s is the number to compare across drivers/modes:
                # in the parallel driver device compute overlaps the
                # export wait, so per-section times don't partition it
                "wall_s": round(wall_s, 3),
                "timing_s": proc.timer.report(),
            }
            if cluster is not None:
                record["cluster"] = cluster  # rank 0's summary/timing above
                record["process_count"] = world
            write_results_json(args.results_json, record)
        return 0
    except Exception as e:  # noqa: BLE001 - reference: fatal-error catch in main
        print(f"Fatal error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
