"""Volumetric batch driver: one 3D segmentation per patient series.

No reference counterpart — the reference forces 2D everywhere
(``setLoadSeries(false)``, src/test/test_pipeline.cpp:41) and its nearest
scale axis is slices-per-patient. This driver is BASELINE.json config 4:
each patient's series stacks into a (D, H, W) volume, preprocessing runs
vmapped per slice, and region growing + morphology run with true 3D
connectivity (one 6-connected lesion body across slices). With several
devices and ``--z-shard`` the same pipeline runs split along z over a
``Mesh('z')`` with ppermute halo exchange per step.

Outputs keep the batch drivers' contract (per-slice original/processed JPEG
pairs, success counters, catch-and-continue per patient) plus optional
``--export-mhd`` MetaImage mask volumes for ITK-family viewers.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from nm03_capstone_project_tpu.cli import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-volume", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument("--output", default="out-volume", help="output root directory")
    common.add_common_args(p)
    common.add_pipeline_args(p)
    p.add_argument(
        "--z-shard",
        action="store_true",
        help="shard each volume along z across all devices (halo-exchange mesh)",
    )
    p.add_argument(
        "--export-mhd",
        action="store_true",
        help="also write each patient's 3D mask as MetaImage (<patient>/mask.mhd)",
    )
    p.add_argument(
        "--mhd-compressed",
        action="store_true",
        help="zlib-compress the MetaImage pixel payload (.zraw); binary masks "
        "compress ~100x",
    )
    common.add_render_stage_arg(p)
    common.add_model_arg(p)
    common.add_distributed_args(
        p,
        "Without --z-shard, patients are round-robin sharded across "
        "processes on their local devices. WITH --z-shard, every process "
        "cooperates on every volume: the z axis spans the GLOBAL device set "
        "and the halo exchange rides DCN between hosts (the long-sequence "
        "mode); rank 0 exports.",
    )
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    common.apply_device_env(args.device)
    try:
        return run(args)
    except Exception as e:  # noqa: BLE001
        print(f"Fatal error: {e}", file=sys.stderr)
        return 1


def _file_sources(f, cfg):
    """[(stem, guarded-pixels-or-None)] for one series file, frames expanded.

    Real archives store series both ways — one file per slice AND
    multi-frame files (NumberOfFrames > 1) whose frames are z-planes; a
    series may even mix them. Every file expands through the parse-once
    :func:`dicomlite.read_dicom_frames`: a single-frame file yields its one
    slice under the plain file stem (the decode happens once — this IS the
    per-file path), a multi-frame file yields ``<stem>_fNNN`` per frame,
    and per-frame decode failures contain to that frame (strict=False).
    """
    from nm03_capstone_project_tpu.cli.runner import guard_pixels, log
    from nm03_capstone_project_tpu.data.dicomlite import read_dicom_frames

    try:
        slices = read_dicom_frames(f, strict=False)
    except Exception as e:  # noqa: BLE001 - per-file containment
        log.warning("failed to read %s: %s", f.name, e)
        return [(f.stem, None)]
    if len(slices) == 1:
        s = slices[0]
        if isinstance(s, Exception):
            log.warning("failed to read %s: %s", f.name, s)
            return [(f.stem, None)]
        return [(f.stem, guard_pixels(s.pixels, f.name, cfg))]
    out = []
    for k, s in enumerate(slices):
        stem = f"{f.stem}_f{k:03d}"
        if isinstance(s, Exception):
            log.warning("skipping frame %d of %s: %s", k, f.name, s)
            out.append((stem, None))
        else:
            out.append((stem, guard_pixels(s.pixels, stem, cfg)))
    return out


def _load_volume(base, patient_id, cfg):
    """Stack one patient's series onto the canvas; (volume, dims, stems).

    Containment mirrors runner.decode_and_guard (shared guards via
    guard_pixels); the volume driver adds only the series-uniformity check —
    a volume needs all slices at one in-plane size. Multi-frame files
    expand into their frames (see :func:`_file_sources`).
    """
    import numpy as np

    from nm03_capstone_project_tpu.data.discovery import load_dicom_files_for_patient

    files = load_dicom_files_for_patient(base, patient_id)
    # generator: stream one file's frames at a time — materializing the
    # whole decoded series AND the canvas stack would double peak memory
    sources = (sf for f in files for sf in _file_sources(f, cfg))

    planes, stems, skipped, hw = [], [], [], None
    for stem, px in sources:
        if px is None:
            skipped.append(stem)
            continue
        h, w = px.shape
        if hw is None:
            hw = (h, w)
        elif (h, w) != hw:
            print(
                f"  skipping {stem}: {w}x{h} != series {hw[1]}x{hw[0]}",
                file=sys.stderr,
            )
            skipped.append(stem)
            continue
        canvas = np.zeros((cfg.canvas, cfg.canvas), np.float32)
        canvas[:h, :w] = px
        planes.append(canvas)
        stems.append(stem)
    if not planes:
        raise ValueError(f"no usable slices for {patient_id}")
    return np.stack(planes), np.asarray(hw, np.int32), stems, skipped


def _compiled_volume_fn(cfg):
    """Volume pipeline + vmapped renders (compile-hub program).

    One program per (cfg, depth) shape: (vol, dims) -> (mask, gray stack,
    segmentation stack) — compute and render fused, one dispatch per patient.
    """
    from nm03_capstone_project_tpu.compilehub import programs

    return programs.volume_pipeline(cfg, "render")


def _make_student_volume_fn(model_params, cfg):
    """Jitted 3D-student stand-in for the volume pipeline.

    Depth pads to the U-Net's pooling multiple inside the jit (static per
    compiled shape, same caching behavior as the classical volume fn);
    compute is bf16 on TPU, f32 elsewhere (threshold output)."""
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.compilehub import hub_jit
    from nm03_capstone_project_tpu.core.backend import is_tpu_backend
    from nm03_capstone_project_tpu.core.image import valid_mask
    from nm03_capstone_project_tpu.models import predict_mask3d, prepare_student_inputs

    params = jax.device_put(model_params)  # nm03-lint: disable=NM401 one-time model-weight placement, not the batch data path the ingest pipeline owns
    dtype = jnp.bfloat16 if is_tpu_backend() else jnp.float32
    pool_multiple = 2 ** len(model_params["enc"])  # one halving per level

    @hub_jit
    def f(vol, dims):
        depth = vol.shape[0]
        pad = (-depth) % pool_multiple
        vp = jnp.pad(vol, ((0, pad), (0, 0), (0, 0)))
        x = prepare_student_inputs(vp, cfg)
        mask = predict_mask3d(params, x[None], dtype)[0][:depth]
        return mask * valid_mask(dims, vol.shape[-2:]).astype(mask.dtype)

    return f


def _compiled_volume_mask_fn(cfg):
    """Mask-only volume pipeline: the host-render path fetches 65 KB/plane
    instead of two rendered canvases (~1.5 MB/plane) through the link."""
    from nm03_capstone_project_tpu.compilehub import programs

    return programs.volume_pipeline(cfg, "mask")


def _compiled_render_fn(cfg):
    """The deferred vmapped render program for the z-sharded path (whose
    compute runs through parallel.process_volume_zsharded separately)."""
    from nm03_capstone_project_tpu.compilehub import programs

    return programs.volume_pipeline(cfg, "render_only")


def run(args: argparse.Namespace) -> int:
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting

    configure_reporting(verbose=args.verbose)
    common.enable_compile_cache()
    common.apply_native_flag(args)
    cfg = common.pipeline_config_from_args(args)
    rank, world = common.init_distributed(args)
    run_ctx = common.make_run_context(args, "volume", rank=rank)
    try:
        return _run_inner(args, cfg, rank, world, run_ctx)
    except Exception as e:
        run_ctx.close(status="error", error_class=type(e).__name__)
        raise


def _run_inner(args, cfg, rank, world, run_ctx) -> int:
    """The volume cohort loop, observability-wired (run_ctx owns the spans,
    per-patient outcome events, and truncation counter; ``run`` closes the
    context on the fatal-error path, this function on success)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.data.discovery import find_patient_dirs
    from nm03_capstone_project_tpu.render.export import clean_directory, export_pairs
    from nm03_capstone_project_tpu.utils.manifest import (
        STATUS_DONE,
        STATUS_FAILED,
        STATUS_TRUNCATED,
        Manifest,
    )
    from nm03_capstone_project_tpu.utils.profiling import profile_trace
    from nm03_capstone_project_tpu.utils.timing import write_results_json

    base = common.resolve_base_path_sync(args, rank, world, tmp_root=Path(args.output))
    out_root = Path(args.output)
    model_params = common.load_model_checkpoint(args, cfg, want_3d=True)
    if model_params is not None and args.z_shard:
        raise SystemExit(
            "--model with --z-shard is unsupported: the 3D student runs "
            "whole volumes (drop --z-shard; --distributed patient sharding "
            "still applies)"
        )
    student_fn = (
        _make_student_volume_fn(model_params, cfg)
        if model_params is not None
        else None
    )

    # two multi-process layouts (see --distributed help): with --z-shard the
    # whole job cooperates volume-by-volume over the GLOBAL device set (rank
    # 0 exports and keeps the manifest); without it, patients shard across
    # ranks, each on its local devices, like the batch drivers
    global_zshard = args.z_shard and world > 1
    patient_sharded = world > 1 and not global_zshard
    i_export = rank == 0 or patient_sharded

    manifest_name = (
        f"manifest.rank{rank}.json" if patient_sharded else "manifest.json"
    )
    if args.resume and rank == 0:
        # rank 0 only: all ranks see the same shared out_root, and one
        # warning in the merged job log is enough
        common.warn_resume_topology(
            out_root, world if patient_sharded else 1, lambda m, *a: print(
                "warning: " + (m % a), file=sys.stderr
            )
        )
    manifest = (
        Manifest.load_or_create(out_root, manifest_name)
        if args.resume
        else Manifest(out_root, manifest_name)
    )

    devices = jax.devices() if global_zshard else jax.local_devices()
    n_dev = len(devices)
    zshard = args.z_shard and n_dev > 1
    if args.z_shard and n_dev == 1:
        print("--z-shard ignored: single device", file=sys.stderr)
    if cfg.grow_algorithm != "dilate" and zshard:
        # the z-sharded decomposition implements only the halo-exchange
        # dilation fixpoint — don't let a user benchmark "jump" timings that
        # were secretly dilate (single-device volumes honor the flag)
        print(
            "warning: --grow-algorithm jump applies to single-device volumes; "
            "the z-sharded path always runs the halo-exchange dilation fixpoint",
            file=sys.stderr,
        )
    mesh = None
    if zshard:
        from nm03_capstone_project_tpu.parallel import make_mesh

        mesh = make_mesh(n_dev, axis_names=("z",), devices=devices)
        print(
            f"z-sharding volumes over {n_dev} "
            f"{'global' if global_zshard else 'local'} devices"
        )

    # the context's span recorder: same report() the results JSON always
    # carried, now also feeding stage latency histograms (stage label =
    # first path component, so per-patient keys stay bounded-cardinality)
    timer = run_ctx.spans

    def emit_outcome(pid, status, **fields):
        """Guarded terminal telemetry (runner._emit_outcome's contract): a
        telemetry failure must never reclassify or fail a patient."""
        try:
            if not run_ctx.has_outcome(pid):
                run_ctx.patient_outcome(pid, status, **fields)
        except Exception as e:  # noqa: BLE001 — telemetry never costs a run
            print(
                f"warning: patient {pid}: outcome telemetry failed: {e}",
                file=sys.stderr,
            )
    patients = find_patient_dirs(base)
    if patient_sharded:
        patients = common.shard_patients(patients, rank, world)
    print(f"=== Volumetric processing: {len(patients)} patients ===")

    def _bcast_flag(flag: bool) -> bool:
        """Collective: rank 0's decision, everywhere."""
        from jax.experimental import multihost_utils

        return bool(
            np.asarray(
                multihost_utils.broadcast_one_to_all(np.asarray([flag], np.int32))
            )[0]
        )

    def _all_ranks_ok(ok: bool) -> bool:
        """Collective: True iff every rank reports ok."""
        from jax.experimental import multihost_utils

        return bool(
            np.asarray(
                multihost_utils.process_allgather(np.asarray([ok], np.int32))
            ).all()
        )

    ok_patients, results = 0, {}
    truncated_patients: list = []
    with profile_trace(args.profile_dir):
        for pid in patients:
            try:
                # In global z-shard mode every branch below must be taken
                # IDENTICALLY on every rank — a rank that skips a patient
                # while another enters its collectives deadlocks the job. So
                # the resume decision is rank 0's, broadcast (per-rank
                # manifests may differ if out_root is not truly shared), and
                # a load failure on ANY rank fails the patient on ALL ranks.
                skip = False
                if args.resume:
                    if rank == 0 or not global_zshard:
                        # stems come from the listing alone — no decode
                        # needed to decide a patient is fully visited. In
                        # global mode the listing is inside its own guard:
                        # an exception here on rank 0 must not skip the
                        # broadcast below, or every later collective would
                        # pair with the wrong patient
                        try:
                            from nm03_capstone_project_tpu.data.discovery import (
                                load_dicom_files_for_patient,
                            )

                            listed = [
                                f.stem
                                for f in load_dicom_files_for_patient(base, pid)
                            ]
                            skip = bool(
                                listed and manifest.patient_accounted(pid, listed)
                            )
                        except Exception:  # noqa: BLE001
                            if not global_zshard:
                                raise
                            # fall through with skip=False: the load step
                            # below will fail collectively and uniformly
                    if global_zshard:
                        skip = _bcast_flag(skip)
                if skip:
                    print(f"Patient {pid}: already complete, skipping")
                    ok_patients += 1
                    emit_outcome(pid, "ok", skipped=True)
                    continue

                load_error = None
                try:
                    with timer.section(f"load/{pid}"):
                        vol, dims, stems, skipped = _load_volume(base, pid, cfg)
                except Exception as e:  # noqa: BLE001 — judged collectively
                    load_error = e
                if global_zshard and not _all_ranks_ok(load_error is None):
                    raise load_error or RuntimeError(
                        f"{pid}: load failed on another rank"
                    )
                if load_error is not None:
                    raise load_error
                for stem in skipped:
                    # record load-time rejects so --resume can account for them
                    manifest.record(pid, stem, STATUS_FAILED)
                depth = vol.shape[0]
                host_render = (
                    getattr(args, "render_stage", "host") == "host"
                )
                with timer.section(f"compute/{pid}"):
                    # The compute section holds only work every rank takes
                    # identically (incl. the cooperative collectives). The
                    # exporting rank's device render is DEFERRED into the
                    # guarded region below: a rank-0-only failure there must
                    # funnel into the export-outcome collective, or the other
                    # ranks' collectives pair off-by-one for the rest of the
                    # run (code-review r3).
                    gray = seg = None
                    conv = None  # None = path without a growing fixpoint
                    if student_fn is not None:
                        volj, dimsj = jnp.asarray(vol), jnp.asarray(dims)
                        maskj = student_fn(volj, dimsj)
                        mask = np.asarray(maskj)
                    elif zshard:
                        from nm03_capstone_project_tpu.parallel import (
                            process_volume_zsharded,
                        )

                        pad = (-depth) % mesh.shape["z"]
                        if pad:
                            # zero filler planes: normalize(0)->0.5, clip->0.68,
                            # outside the grow band, so they segment empty
                            vol = np.concatenate(
                                [vol, np.zeros((pad,) + vol.shape[1:], vol.dtype)]
                            )
                        out = process_volume_zsharded(
                            jnp.asarray(vol), jnp.asarray(dims), cfg, mesh
                        )
                        vol = vol[:depth]
                        if global_zshard:
                            # the mask is a GLOBAL array (shards on every
                            # host); gather it — a direct np.asarray of a
                            # non-addressable array would fail
                            from jax.experimental import multihost_utils

                            mask = np.asarray(
                                multihost_utils.process_allgather(
                                    out["mask"], tiled=True
                                )
                            )[:depth]
                            maskj = jnp.asarray(mask)
                        else:
                            maskj = out["mask"][:depth]
                            mask = np.asarray(maskj)
                        # replicated scalar: addressable on every rank
                        conv = out["grow_converged"]
                    elif host_render:
                        maskj, conv = _compiled_volume_mask_fn(cfg)(
                            jnp.asarray(vol), jnp.asarray(dims)
                        )
                        mask = np.asarray(maskj)
                    else:
                        # single program computes mask + renders in one jit;
                        # this branch never runs under z-shard (zshard takes
                        # precedence), so materializing here cannot desync
                        maskj, grayj, segj, conv = _compiled_volume_fn(cfg)(
                            jnp.asarray(vol), jnp.asarray(dims)
                        )
                        mask = np.asarray(maskj)
                        if not host_render and i_export:
                            gray = np.asarray(grayj)
                            seg = np.asarray(segj)
                if conv is not None and not bool(np.asarray(conv)):
                    truncated_patients.append(pid)
                    print(
                        f"WARNING: patient {pid}: region growing hit its "
                        "iteration cap; the 3D mask under-covers "
                        "(raise --grow-max-iters)",
                        file=sys.stderr,
                    )
                    # grow_converged=False surfaced structurally, not just on
                    # stderr: WARNING event + pipeline_grow_truncated_total
                    # (count=1: the whole volume's fixpoint truncated)
                    try:
                        run_ctx.grow_truncated(pid, count=1, scope="volume")
                    except Exception as e:  # noqa: BLE001
                        print(
                            f"warning: patient {pid}: truncation telemetry "
                            f"failed: {e}",
                            file=sys.stderr,
                        )
                if not i_export:
                    # global z-shard, rank != 0: compute was cooperative but
                    # rank 0 owns the export/manifest. Learn its outcome
                    # (collective, mirroring the load step) before counting,
                    # so ok_patients — and the exit code — agree on every
                    # rank (ADVICE r2)
                    export_ok = _all_ranks_ok(True)
                    results[pid] = {"slices": depth, "mask_voxels": int(mask.sum())}
                    if export_ok:
                        ok_patients += 1
                    else:
                        print(
                            f"Patient {pid}: export failed on the exporting rank",
                            file=sys.stderr,
                        )
                    emit_outcome(
                        pid,
                        "ok" if export_ok else "failed",
                        slices_total=depth,
                        grow_truncated=pid in truncated_patients,
                        error_class=None if export_ok else "RemoteExportError",
                    )
                    continue
                export_error, missing = None, []
                try:
                    if not host_render and gray is None:
                        # deferred rank-local render (student / z-shard
                        # modes): per-rank local math, only the exporting
                        # rank pays it — and inside this guard so a failure
                        # reaches the outcome collective below
                        with timer.section(f"render/{pid}"):
                            grayj, segj = _compiled_render_fn(cfg)(
                                jnp.asarray(vol), maskj, jnp.asarray(dims)
                            )
                            gray = np.asarray(grayj)
                            seg = np.asarray(segj)
                    with timer.section(f"export/{pid}"):
                        if not args.resume:
                            clean_directory(out_root / pid)
                        if host_render:
                            from nm03_capstone_project_tpu.render.export import (
                                render_export_pairs,
                            )

                            done = render_export_pairs(
                                [
                                    (stems[i], vol[i], mask[i], dims)
                                    for i in range(depth)
                                ],
                                out_root / pid,
                                cfg,
                            )
                        else:
                            done = export_pairs(
                                [(stems[i], gray[i], seg[i]) for i in range(depth)],
                                out_root / pid,
                            )
                        # a cap-truncated volume's pairs exist but the 3D
                        # mask under-covers: record TRUNCATED so --resume
                        # with a raised cap recomputes this patient
                        status = (
                            STATUS_TRUNCATED
                            if pid in truncated_patients
                            else STATUS_DONE
                        )
                        for stem in done:
                            manifest.record(pid, stem, status)
                        manifest.flush()
                        if args.export_mhd:
                            from nm03_capstone_project_tpu.data.imageio import (
                                write_metaimage,
                            )

                            write_metaimage(
                                mask,
                                out_root / pid / "mask.mhd",
                                compressed=getattr(args, "mhd_compressed", False),
                            )
                    missing = sorted(set(stems) - set(done))
                    for stem in missing:
                        manifest.record(pid, stem, STATUS_FAILED)
                    if missing:
                        manifest.flush()
                except Exception as e:  # noqa: BLE001 — judged collectively
                    # an export crash must still reach the outcome collective
                    # below, or the waiting ranks would deadlock
                    export_error = e
                if global_zshard:
                    _all_ranks_ok(export_error is None and not missing)
                if export_error is not None:
                    raise export_error
                if missing:
                    # success is "the JPEG pair exists" (runner contract)
                    print(
                        f"Patient {pid}: {len(missing)} slices failed to export",
                        file=sys.stderr,
                    )
                else:
                    ok_patients += 1
                # results first, telemetry second: the run's own artifacts
                # must be complete before (and regardless of) any outcome
                # emission
                results[pid] = {
                    "slices": depth,
                    "exported": len(done),
                    "mask_voxels": int(mask.sum()),
                    "grow_truncated": pid in truncated_patients,
                }
                emit_outcome(
                    pid,
                    "ok" if not missing else "failed",
                    slices_total=depth + len(skipped),
                    slices_ok=len(done),
                    slices_failed=len(missing) + len(skipped),
                    slices_truncated=(
                        len(done) if pid in truncated_patients else 0
                    ),
                    grow_truncated=pid in truncated_patients,
                )
                print(f"Patient {pid}: {depth} slices, mask {int(mask.sum())} voxels")
            except Exception as e:  # noqa: BLE001 - per-patient containment
                print(f"Patient {pid} failed: {e}", file=sys.stderr)
                emit_outcome(pid, "failed", error_class=type(e).__name__)
    print("\n=== All Processing Completed ===\n")
    print(f"Successfully processed {ok_patients}/{len(patients)} patients.")
    cluster = None
    if patient_sharded:
        # same single DCN crossing as the batch drivers: cohort-wide totals
        cluster = common.allgather_cluster_counts(
            {"patients_ok": ok_patients, "patients_total": len(patients)}, world
        )
        if rank == 0:
            print(
                f"Cluster totals: {cluster['patients_ok']}/"
                f"{cluster['patients_total']} patients across {world} processes."
            )
    if args.results_json and rank == 0:
        platform = jax.devices()[0].platform
        record = {
            "mode": "volume",
            "grow_truncated_patients": truncated_patients,
            "backend": platform,  # legacy alias of backend_actual
            # backend honesty (bench-evidence contract): a --device tpu
            # request that initialized on cpu is visible as requested !=
            # actual, not silently recorded as a chip run
            "backend_requested": args.device,
            "backend_actual": platform,
            "z_sharded": bool(zshard),
            "z_global": bool(global_zshard),
            "patients": results,
            "timings_s": timer.report(),
            "metrics": run_ctx.metrics_snapshot(),
        }
        if cluster is not None:
            record["cluster"] = cluster
            record["process_count"] = world
        write_results_json(args.results_json, record)
    all_ok = ok_patients == len(patients)
    run_ctx.close(status="ok" if all_ok else "error")
    return 0 if all_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
