"""Volumetric batch driver: one 3D segmentation per patient series.

No reference counterpart — the reference forces 2D everywhere
(``setLoadSeries(false)``, src/test/test_pipeline.cpp:41) and its nearest
scale axis is slices-per-patient. This driver is BASELINE.json config 4:
each patient's series stacks into a (D, H, W) volume, preprocessing runs
vmapped per slice, and region growing + morphology run with true 3D
connectivity (one 6-connected lesion body across slices). With several
devices and ``--z-shard`` the same pipeline runs split along z over a
``Mesh('z')`` with ppermute halo exchange per step.

Outputs keep the batch drivers' contract (per-slice original/processed JPEG
pairs, success counters, catch-and-continue per patient) plus optional
``--export-mhd`` MetaImage mask volumes for ITK-family viewers.
"""

from __future__ import annotations

import argparse
import functools
import sys
from pathlib import Path

from nm03_capstone_project_tpu.cli import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nm03-volume", description=__doc__.strip().splitlines()[0]
    )
    p.add_argument("--output", default="out-volume", help="output root directory")
    common.add_common_args(p)
    common.add_pipeline_args(p)
    p.add_argument(
        "--z-shard",
        action="store_true",
        help="shard each volume along z across all devices (halo-exchange mesh)",
    )
    p.add_argument(
        "--export-mhd",
        action="store_true",
        help="also write each patient's 3D mask as MetaImage (<patient>/mask.mhd)",
    )
    common.add_render_stage_arg(p)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    common.apply_device_env(args.device)
    try:
        return run(args)
    except Exception as e:  # noqa: BLE001
        print(f"Fatal error: {e}", file=sys.stderr)
        return 1


def _load_volume(base, patient_id, cfg):
    """Stack one patient's series onto the canvas; (volume, dims, stems).

    Per-slice containment lives in runner.decode_and_guard (shared with the
    batch drivers); the volume driver adds only the series-uniformity check —
    a volume needs all slices at one in-plane size.
    """
    import numpy as np

    from nm03_capstone_project_tpu.cli.runner import decode_and_guard
    from nm03_capstone_project_tpu.data.discovery import load_dicom_files_for_patient

    planes, stems, skipped, hw = [], [], [], None
    for f in load_dicom_files_for_patient(base, patient_id):
        px = decode_and_guard(f, cfg)
        if px is None:
            skipped.append(f.stem)
            continue
        h, w = px.shape
        if hw is None:
            hw = (h, w)
        elif (h, w) != hw:
            print(
                f"  skipping {f.name}: {w}x{h} != series {hw[1]}x{hw[0]}",
                file=sys.stderr,
            )
            skipped.append(f.stem)
            continue
        canvas = np.zeros((cfg.canvas, cfg.canvas), np.float32)
        canvas[:h, :w] = px
        planes.append(canvas)
        stems.append(f.stem)
    if not planes:
        raise ValueError(f"no usable slices for {patient_id}")
    return np.stack(planes), np.asarray(hw, np.int32), stems, skipped


@functools.lru_cache(maxsize=4)
def _compiled_volume_fn(cfg):
    """jit-compiled volume pipeline + vmapped renders, cached per config.

    One program per (cfg, depth) shape: (vol, dims) -> (mask, gray stack,
    segmentation stack) — compute and render fused, one dispatch per patient.
    """
    import jax

    from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume
    from nm03_capstone_project_tpu.render.render import render_pair

    def f(vol, dims):
        out = process_volume(vol, dims, cfg)
        gray, seg = jax.vmap(lambda p, m: render_pair(p, m, dims, cfg))(
            vol, out["mask"]
        )
        return out["mask"], gray, seg

    return jax.jit(f)


@functools.lru_cache(maxsize=4)
def _compiled_volume_mask_fn(cfg):
    """Mask-only volume pipeline: the host-render path fetches 65 KB/plane
    instead of two rendered canvases (~1.5 MB/plane) through the link."""
    import jax

    from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

    return jax.jit(lambda vol, dims: process_volume(vol, dims, cfg)["mask"])


@functools.lru_cache(maxsize=4)
def _compiled_render_fn(cfg):
    """Cached vmapped render program for the z-sharded path (whose compute
    runs through parallel.process_volume_zsharded separately)."""
    import jax

    from nm03_capstone_project_tpu.render.render import render_pair

    def f(vol, mask, dims):
        return jax.vmap(lambda p, m: render_pair(p, m, dims, cfg))(vol, mask)

    return jax.jit(f)


def run(args: argparse.Namespace) -> int:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.data.discovery import find_patient_dirs
    from nm03_capstone_project_tpu.render.export import clean_directory, export_pairs
    from nm03_capstone_project_tpu.utils.manifest import (
        STATUS_DONE,
        STATUS_FAILED,
        Manifest,
    )
    from nm03_capstone_project_tpu.utils.profiling import profile_trace
    from nm03_capstone_project_tpu.utils.reporter import configure_reporting
    from nm03_capstone_project_tpu.utils.timing import Timer, write_results_json

    configure_reporting(verbose=args.verbose)
    common.enable_compile_cache()
    common.apply_native_flag(args)
    cfg = common.pipeline_config_from_args(args)
    base = common.resolve_base_path(args, tmp_root=Path(args.output))
    out_root = Path(args.output)
    manifest = Manifest.load_or_create(out_root) if args.resume else Manifest(out_root)

    n_dev = len(jax.devices())
    zshard = args.z_shard and n_dev > 1
    if args.z_shard and n_dev == 1:
        print("--z-shard ignored: single device", file=sys.stderr)
    if cfg.grow_algorithm != "dilate" and zshard:
        # the z-sharded decomposition implements only the halo-exchange
        # dilation fixpoint — don't let a user benchmark "jump" timings that
        # were secretly dilate (single-device volumes honor the flag)
        print(
            "warning: --grow-algorithm jump applies to single-device volumes; "
            "the z-sharded path always runs the halo-exchange dilation fixpoint",
            file=sys.stderr,
        )
    mesh = None
    if zshard:
        from nm03_capstone_project_tpu.parallel import make_mesh

        mesh = make_mesh(n_dev, axis_names=("z",))
        print(f"z-sharding volumes over {n_dev} devices")

    timer = Timer()
    patients = find_patient_dirs(base)
    print(f"=== Volumetric processing: {len(patients)} patients ===")
    ok_patients, results = 0, {}
    with profile_trace(args.profile_dir):
        for pid in patients:
            try:
                if args.resume:
                    # stems come from the listing alone — no decode needed to
                    # decide a patient is fully visited (done or recorded bad)
                    from nm03_capstone_project_tpu.data.discovery import (
                        load_dicom_files_for_patient,
                    )

                    listed = [f.stem for f in load_dicom_files_for_patient(base, pid)]
                    if listed and manifest.patient_accounted(pid, listed):
                        print(f"Patient {pid}: already complete, skipping")
                        ok_patients += 1
                        continue
                with timer.section(f"load/{pid}"):
                    vol, dims, stems, skipped = _load_volume(base, pid, cfg)
                for stem in skipped:
                    # record load-time rejects so --resume can account for them
                    manifest.record(pid, stem, STATUS_FAILED)
                depth = vol.shape[0]
                host_render = (
                    getattr(args, "render_stage", "host") == "host"
                )
                with timer.section(f"compute/{pid}"):
                    gray = seg = None
                    if zshard:
                        from nm03_capstone_project_tpu.parallel import (
                            process_volume_zsharded,
                        )

                        pad = (-depth) % mesh.shape["z"]
                        if pad:
                            # zero filler planes: normalize(0)->0.5, clip->0.68,
                            # outside the grow band, so they segment empty
                            vol = np.concatenate(
                                [vol, np.zeros((pad,) + vol.shape[1:], vol.dtype)]
                            )
                        out = process_volume_zsharded(
                            jnp.asarray(vol), jnp.asarray(dims), cfg, mesh
                        )
                        vol = vol[:depth]
                        maskj = out["mask"][:depth]
                        if not host_render:
                            grayj, segj = _compiled_render_fn(cfg)(
                                jnp.asarray(vol), maskj, jnp.asarray(dims)
                            )
                    elif host_render:
                        maskj = _compiled_volume_mask_fn(cfg)(
                            jnp.asarray(vol), jnp.asarray(dims)
                        )
                    else:
                        maskj, grayj, segj = _compiled_volume_fn(cfg)(
                            jnp.asarray(vol), jnp.asarray(dims)
                        )
                    mask = np.asarray(maskj)
                    if not host_render:
                        gray = np.asarray(grayj)
                        seg = np.asarray(segj)
                with timer.section(f"export/{pid}"):
                    if not args.resume:
                        clean_directory(out_root / pid)
                    if host_render:
                        from nm03_capstone_project_tpu.render.export import (
                            render_export_pairs,
                        )

                        done = render_export_pairs(
                            [
                                (stems[i], vol[i], mask[i], dims)
                                for i in range(depth)
                            ],
                            out_root / pid,
                            cfg,
                        )
                    else:
                        done = export_pairs(
                            [(stems[i], gray[i], seg[i]) for i in range(depth)],
                            out_root / pid,
                        )
                    for stem in done:
                        manifest.record(pid, stem, STATUS_DONE)
                    manifest.flush()
                    if args.export_mhd:
                        from nm03_capstone_project_tpu.data.imageio import (
                            write_metaimage,
                        )

                        write_metaimage(mask, out_root / pid / "mask.mhd")
                missing = sorted(set(stems) - set(done))
                for stem in missing:
                    manifest.record(pid, stem, STATUS_FAILED)
                if missing:
                    manifest.flush()
                    # success is "the JPEG pair exists" (runner contract)
                    print(
                        f"Patient {pid}: {len(missing)} slices failed to export",
                        file=sys.stderr,
                    )
                else:
                    ok_patients += 1
                results[pid] = {
                    "slices": depth,
                    "exported": len(done),
                    "mask_voxels": int(mask.sum()),
                }
                print(f"Patient {pid}: {depth} slices, mask {int(mask.sum())} voxels")
            except Exception as e:  # noqa: BLE001 - per-patient containment
                print(f"Patient {pid} failed: {e}", file=sys.stderr)
    print("\n=== All Processing Completed ===\n")
    print(f"Successfully processed {ok_patients}/{len(patients)} patients.")
    if args.results_json:
        import jax

        write_results_json(
            args.results_json,
            {
                "mode": "volume",
                "backend": jax.devices()[0].platform,  # provenance
                "z_sharded": bool(zshard),
                "patients": results,
                "timings_s": timer.report(),
            },
        )
    return 0 if ok_patients == len(patients) else 1


if __name__ == "__main__":
    raise SystemExit(main())
