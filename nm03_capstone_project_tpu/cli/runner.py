"""Cohort orchestration: the batch-driver layer, once.

The reference implements its orchestration twice — SequentialImageProcessor
(main_sequential.cpp:9-344) and OptimizedParallelProcessor
(main_parallel.cpp:19-387) — duplicating discovery, per-patient looping and
fault tolerance. Here a single :class:`CohortProcessor` owns the loop and the
two execution strategies differ only in how a patient's slices are executed:

* ``sequential`` — one slice at a time through the jitted pipeline, export
  interleaved per image (the reference's sequential contract).
* ``parallel`` — slices decoded by an IO thread pool, stacked into device
  batches, processed + rendered by ONE jitted vmapped program, JPEG-encoded
  by a host thread pool that overlaps the next batch's compute. This is the
  TPU-native replacement for the OpenMP parallel-for + serial-export split
  (main_parallel.cpp:330-347): the "thread-safety" problem disappears
  because rendering is pure device math.

Fault tolerance mirrors the reference at both granularities
(SURVEY.md section 5): per-slice catch-and-continue with success counting
(main_sequential.cpp:267-271,288-294) and per-patient catch-and-continue
(main_sequential.cpp:301-305); plus what the reference lacks — a manifest for
``--resume`` instead of the destructive ``rm -rf`` rerun.
"""

from __future__ import annotations

import concurrent.futures as cf
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig
from nm03_capstone_project_tpu.data.dicomlite import read_dicom
from nm03_capstone_project_tpu.data.discovery import (
    find_patient_dirs,
    load_dicom_files_for_patient,
)
from nm03_capstone_project_tpu.ingest import (
    IngestFailure,
    IngestPipeline,
    stage_batch,
)
from nm03_capstone_project_tpu.obs import (
    RESILIENCE_RETRIES_TOTAL,
    PhaseAccountant,
    RunContext,
)
from nm03_capstone_project_tpu.render.export import (
    clean_directory,
    export_pairs,
    render_export_pairs,
)
from nm03_capstone_project_tpu.resilience import (
    DispatchSupervisor,
    FaultPlan,
    InjectedExportError,
    InjectedTransientError,
    PatientJournal,
    ResilienceConfig,
    corrupt_bytes,
    deliver_sigterm,
    execute_hang,
)
from nm03_capstone_project_tpu.utils import sanitize
from nm03_capstone_project_tpu.utils.manifest import (
    STATUS_DONE,
    STATUS_FAILED,
    STATUS_TRUNCATED,
    Manifest,
)
from nm03_capstone_project_tpu.utils.reporter import get_logger

log = get_logger("runner")


def guard_pixels(
    pixels: np.ndarray, name: str, cfg: PipelineConfig
) -> Optional[np.ndarray]:
    """Dimension guards for one decoded slice; None signals rejection.

    The min-dimension guard (main_sequential.cpp:189-192) and the
    canvas-fit guard, shared by the per-file path and the multi-frame
    expansion (where each frame guards individually)."""
    h, w = pixels.shape
    if h < cfg.min_dim or w < cfg.min_dim:
        # reference: "Image dimensions too small" (main_sequential.cpp:189-192)
        log.warning("image dimensions too small: %dx%d (%s)", w, h, name)
        return None
    if h > cfg.canvas or w > cfg.canvas:
        log.warning(
            "slice %s (%dx%d) exceeds canvas %d; raise --canvas",
            name, w, h, cfg.canvas,
        )
        return None
    return pixels


def decode_and_guard(path: Path, cfg: PipelineConfig) -> Optional[np.ndarray]:
    """Decode + guard one slice; None signals failure (null-ptr analog).

    The single home of the per-slice containment contract shared by every
    driver: broad catch on decode (the reference skips unreadable images and
    continues, main_sequential.cpp:288-294) plus :func:`guard_pixels`.
    """
    try:
        s = read_dicom(path)
    except Exception as e:  # noqa: BLE001 - per-slice containment
        log.warning("failed to read %s: %s", path.name, e)
        return None
    return guard_pixels(s.pixels, path.name, cfg)


def _native_available() -> bool:
    from nm03_capstone_project_tpu import native

    return native.available()


def _compiled_slice_fn(cfg: PipelineConfig):
    """Pipeline + on-device render for one slice (compile-hub program)."""
    from nm03_capstone_project_tpu.compilehub import programs

    return programs.slice_pipeline(cfg, render=True)


def _compiled_slice_mask_fn(cfg: PipelineConfig):
    """The pipeline alone: only the mask crosses back to the host."""
    from nm03_capstone_project_tpu.compilehub import programs

    return programs.slice_pipeline(cfg, render=False)


def _compiled_batch_mask_fn(cfg: PipelineConfig):
    """Vmapped mask-only pipeline (host-render export path).

    The device copy of the pixel stack is dead after the pipeline reads it
    (the host keeps its own copy for rendering) — the hub program donates
    its HBM.
    """
    from nm03_capstone_project_tpu.compilehub import programs

    return programs.batch_pipeline(cfg, render=False)


def _student_batch_mask(params, pixels, dims, cfg):
    """The distilled U-Net standing in for everything downstream of
    normalize+clip (models/train.py prepare_student_inputs): (B, H, W)
    pixels -> (B, H, W) uint8 mask, canvas padding zeroed (the student's
    logits there are untrained). Compute runs bf16 on TPU (the model's
    mixed-precision design — the output is a >0 threshold, insensitive to
    the mantissa) and f32 elsewhere."""
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.core.backend import is_tpu_backend
    from nm03_capstone_project_tpu.core.image import valid_mask
    from nm03_capstone_project_tpu.models import predict_mask, prepare_student_inputs

    dtype = jnp.bfloat16 if is_tpu_backend() else jnp.float32
    x = prepare_student_inputs(pixels, cfg)
    mask = predict_mask(params, x, dtype)
    return mask * valid_mask(dims, pixels.shape[-2:]).astype(mask.dtype)


def _compiled_batch_fn(cfg: PipelineConfig):
    """Vmapped pipeline + render over a fixed-size slice stack.

    The hub program donates the pixel stack: the raw canvas batch is dead
    after the pipeline reads it, so XLA may reuse its HBM for
    intermediates (the render output is a different shape, but fusion
    scratch benefits).
    """
    from nm03_capstone_project_tpu.compilehub import programs

    return programs.batch_pipeline(cfg, render=True)


@dataclass
class PatientResult:
    patient_id: str
    total: int
    succeeded: int
    failed_slices: List[str] = field(default_factory=list)
    # slices whose region-growing fixpoint hit its iteration cap: the mask
    # was exported but under-covers the true connected set (FAST's BFS
    # always completes, so this is a divergence the record must carry —
    # VERDICT r4 item 4). Distinct from failed_slices: the pair exists.
    truncated_slices: List[str] = field(default_factory=list)


@dataclass
class RunSummary:
    patients: List[PatientResult] = field(default_factory=list)
    patients_ok: int = 0

    @property
    def total_slices(self) -> int:
        return sum(p.total for p in self.patients)

    @property
    def succeeded_slices(self) -> int:
        return sum(p.succeeded for p in self.patients)

    @property
    def truncated_slices(self) -> int:
        return sum(len(p.truncated_slices) for p in self.patients)

    def as_dict(self) -> dict:
        return {
            "patients_ok": self.patients_ok,
            "patients_total": len(self.patients),
            "slices_ok": self.succeeded_slices,
            "slices_total": self.total_slices,
            "slices_truncated": self.truncated_slices,
            "per_patient": {
                p.patient_id: {
                    "ok": p.succeeded,
                    "total": p.total,
                    "truncated": len(p.truncated_slices),
                }
                for p in self.patients
            },
        }


class CohortProcessor:
    """Drives the full cohort with either execution strategy."""

    def __init__(
        self,
        base_path,
        out_root,
        cfg: PipelineConfig = PipelineConfig(),
        batch_cfg: BatchConfig = BatchConfig(),
        mode: str = "sequential",
        resume: bool = False,
        process_rank: int = 0,
        process_count: int = 1,
        model_params=None,
        mask_sink=None,
        obs: RunContext = None,
        resilience: ResilienceConfig = None,
    ):
        if mode not in ("sequential", "parallel"):
            raise ValueError(f"unknown mode: {mode}")
        if not 0 <= process_rank < process_count:
            raise ValueError(
                f"process_rank {process_rank} outside [0, {process_count})"
            )
        self.base_path = Path(base_path)
        self.out_root = Path(out_root)
        self.cfg = cfg
        self.batch_cfg = batch_cfg
        self.mode = mode
        self.resume = resume
        # multi-process job: this process owns patients[rank::count] and its
        # own manifest file (shared out_root assumed to be a shared fs)
        self.process_rank = process_rank
        self.process_count = process_count
        # a trained student checkpoint (2D U-Net host pytree) replaces the
        # classical pipeline's compute when given (--model)
        self.model_params = model_params
        # metrics hook: called (patient_id, stem, mask_2d) for every slice
        # whose mask reaches the host, i.e. in host-render mode (the
        # default) — scripts/student_eval.py consumes this for cohort-scale
        # teacher-vs-student IoU without decoding exported JPEGs. In
        # parallel mode it fires on IO-pool threads: the sink must be
        # thread-safe.
        self.mask_sink = mask_sink
        self._student_fns: dict = {}
        # observability: drivers pass their flag-configured RunContext; a
        # library caller gets a sink-less one (metrics/events accumulate in
        # memory, nothing touches disk). `timer` IS the context's span
        # recorder, so every section also feeds the per-stage latency
        # histograms. Counters fire from IO-pool threads in parallel mode;
        # the registry is thread-safe by design.
        self.obs = obs if obs is not None else RunContext.create(driver=mode)
        self.timer = self.obs.spans
        # feed-phase accounting (ISSUE 10): both execution strategies
        # record decode/stage/dispatch/fetch/export busy intervals so the
        # drivers' results carry a `feed_stall` report — the fraction of
        # wall the device sat starved by the serial feed, the number
        # ROADMAP item 3's streaming ingest must drive toward zero. The
        # recorded "dispatch" interval spans enqueue -> fetch completion
        # (an upper bound on device busy, so the reported stall is a LOWER
        # bound: every second of it is real starvation).
        self.feed = PhaseAccountant()
        # streaming ingest (ISSUE 11): both execution strategies feed the
        # device through an ingest/ IngestPipeline (decode pool -> bounded
        # staging ring -> upload-ahead stager); one drained stats snapshot
        # is kept per patient pipeline so the run can report aggregate
        # ring occupancy / decode lookahead / upload overlap next to the
        # feed_stall record it erases
        self._ingest_reports: List[dict] = []
        # resilience: retry/deadline policies, CPU degradation, chaos layer
        # (docs/RESILIENCE.md). Defaults are behavior-preserving: no dispatch
        # deadline, no fault plan (unless NM03_FAULT_PLAN activates one).
        self.res = resilience if resilience is not None else ResilienceConfig()
        plan = self.res.fault_plan
        self.fault_plan = (
            FaultPlan.from_spec(plan) if plan is not None else FaultPlan.from_env()
        )
        self.retry = self.res.make_retry_policy(
            seed=self.fault_plan.seed if self.fault_plan is not None else 0
        )
        self.retry.obs = self.obs
        self.dispatch = DispatchSupervisor(self.res, retry=self.retry, obs=self.obs)
        self._fallback_fns: dict = {}
        self.out_root.mkdir(parents=True, exist_ok=True)
        manifest_name = (
            "manifest.json"
            if process_count == 1
            else f"manifest.rank{process_rank}.json"
        )
        if resume:
            from nm03_capstone_project_tpu.cli.common import warn_resume_topology

            warn_resume_topology(self.out_root, process_count, log.warning)
        self.manifest = (
            Manifest.load_or_create(self.out_root, manifest_name)
            if resume
            else Manifest(self.out_root, manifest_name)
        )

    # -- data loading ------------------------------------------------------

    def _read_slice(
        self, path: Path, patient: Optional[str] = None, index: Optional[int] = None
    ) -> Optional[np.ndarray]:
        """Decode + guard one slice; None signals failure (null-ptr analog).

        The decode-site chaos hook lives here: an ``error`` rule fails the
        slice before decode; a ``corrupt`` rule feeds the REAL parser
        deterministically corrupted file bytes, exercising the actual
        rejection path rather than a mock.
        """
        plan = self.fault_plan
        if plan is not None and plan.has_site("decode"):
            rule = plan.fire(
                "decode", obs=self.obs, patient=patient, stem=path.stem, index=index
            )
            if rule is not None:
                if rule.kind == "error":
                    log.warning(
                        "failed to read %s: injected decode fault", path.name
                    )
                    return None
                # kind == "corrupt"
                from nm03_capstone_project_tpu.data.dicomlite import (
                    read_dicom_bytes,
                )

                try:
                    raw = corrupt_bytes(path.read_bytes(), plan.seed, path.stem)
                    s = read_dicom_bytes(raw)
                except Exception as e:  # noqa: BLE001 - per-slice containment
                    log.warning("failed to read %s: %s", path.name, e)
                    return None
                return guard_pixels(s.pixels, path.name, self.cfg)
        return decode_and_guard(path, self.cfg)

    # -- resilience hooks --------------------------------------------------

    def _dispatch_pre(self, patient_id: str, index: int):
        """Dispatch-site fault hook for the supervisor (None when off)."""
        plan = self.fault_plan
        if plan is None or not plan.has_site("dispatch"):
            return None

        def pre(cancel):
            rule = plan.fire(
                "dispatch", obs=self.obs, patient=patient_id, index=index
            )
            if rule is None:
                return
            if rule.kind == "hang":
                execute_hang(rule, cancel)
            else:  # transient
                raise InjectedTransientError(
                    f"injected transient device error "
                    f"(patient {patient_id}, dispatch {index})"
                )

        return pre

    def _export_fault_hook(self, patient_id: str):
        """Export-site fault hook threaded into the export layer."""
        plan = self.fault_plan
        if plan is None or not plan.has_site("export"):
            return None

        def hook(stem):
            rule = plan.fire("export", obs=self.obs, patient=patient_id, stem=stem)
            if rule is None:
                return
            if rule.kind == "sigterm":
                deliver_sigterm()
            raise InjectedExportError(f"injected export fault for {stem}")

        return hook

    def _fallback_call(self, batched: bool, host_render: bool):
        """The CPU degradation target: same outputs as the primary pipeline
        fn, computed on the CPU backend through the XLA path (Pallas is
        excluded by construction — the wedge being escaped may BE the
        accelerator). Takes host arrays only: fetching a device array here
        could hang on the very wedge that triggered degradation. Built and
        compiled lazily on first degradation, cached per shape-of-use."""
        key = (batched, host_render)
        if key in self._fallback_fns:
            return self._fallback_fns[key]
        import dataclasses

        import jax

        cpu = jax.local_devices(backend="cpu")[0]
        cfg = (
            dataclasses.replace(self.cfg, use_pallas=False)
            if self.cfg.use_pallas
            else self.cfg
        )
        if self.model_params is not None:
            inner = self._student_fn(
                batched=batched, mesh=None, host_render=host_render, device=cpu
            )
        elif batched:
            inner = (
                _compiled_batch_mask_fn(cfg) if host_render else _compiled_batch_fn(cfg)
            )
        else:
            inner = (
                _compiled_slice_mask_fn(cfg) if host_render else _compiled_slice_fn(cfg)
            )

        def call(px, dm):
            with jax.default_device(cpu):
                # commit the inputs to the CPU device explicitly: the batched
                # fns donate their pixel arg, and donation of an uncommitted
                # numpy arg is a no-op that warns on every fallback batch
                out = inner(
                    jax.device_put(np.asarray(px), cpu),  # nm03-lint: disable=NM401 CPU-degradation target: committing host arrays to the FALLBACK device is the escape from the wedged one — routing through ingest would touch the very device path being escaped
                    jax.device_put(np.asarray(dm), cpu),  # nm03-lint: disable=NM401 CPU-degradation target: committing host arrays to the FALLBACK device is the escape from the wedged one — routing through ingest would touch the very device path being escaped
                )
            return tuple(np.asarray(a) for a in out)

        self._fallback_fns[key] = call
        return call

    # -- student deployment ------------------------------------------------

    def _student_fn(self, batched: bool, mesh, host_render: bool, device=None):
        """Jitted student-model stand-in for the pipeline fns, cached per
        (shape-of-use) so each compiles once per processor. ``device`` pins
        the params to a specific device — the CPU-degradation fallback path
        (resilience) uses it to keep a second, accelerator-free copy."""
        key = (batched, mesh is not None, host_render, str(device))
        if key in self._student_fns:
            return self._student_fns[key]
        import jax

        cfg = self.cfg
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            # nm03-lint: disable=NM401 one-time model-weight placement, not the batch data path the ingest pipeline owns
            params = jax.device_put(
                self.model_params, NamedSharding(mesh, PartitionSpec())
            )
        elif device is not None:
            params = jax.device_put(self.model_params, device)  # nm03-lint: disable=NM401 one-time model-weight placement, not the batch data path the ingest pipeline owns
        else:
            params = jax.device_put(self.model_params)  # nm03-lint: disable=NM401 one-time model-weight placement, not the batch data path the ingest pipeline owns

        import jax.numpy as jnp

        # the student has no growing fixpoint, so its "convergence" is a
        # constant True per slice — emitted anyway so every pipeline fn
        # shares one output contract with the classical paths
        if host_render:

            def core(px, dm):
                mask = _student_batch_mask(params, px, dm, cfg)
                return mask, jnp.ones(mask.shape[:1], jnp.bool_)

        else:
            from nm03_capstone_project_tpu.render.render import render_pair

            def core(px, dm):
                mask = _student_batch_mask(params, px, dm, cfg)
                gray, seg = jax.vmap(lambda p, m, d: render_pair(p, m, d, cfg))(
                    px, mask, dm
                )
                return gray, seg, jnp.ones(mask.shape[:1], jnp.bool_)

        from nm03_capstone_project_tpu.compilehub import hub_jit

        if batched:
            # host-render keeps its own pixel copy on the host, so the
            # device stack is dead after the student reads it — donate,
            # matching the classical batched fns (the render path still
            # reads px after the mask, so it cannot donate)
            fn = hub_jit(core, donate_argnums=(0,) if host_render else ())
        else:
            fn = hub_jit(lambda px, dm: jax.tree.map(
                lambda a: a[0], core(px[None], dm[None])
            ))
        self._student_fns[key] = fn
        return fn

    # -- patient processing ------------------------------------------------

    def process_patient(self, patient_id: str) -> PatientResult:
        print(f"\n=== Processing Patient: {patient_id} ===\n")
        out_dir = self.out_root / patient_id
        if not self.resume:
            clean_directory(out_dir)
        files = load_dicom_files_for_patient(self.base_path, patient_id)
        print(f"Found {len(files)} DICOM files for patient {patient_id}")

        # slice-grain crash-safe resume: the journal records each completed
        # slice the moment its pair is on disk (the manifest flushes only per
        # patient), so a kill mid-patient loses at most the slice in flight.
        # On --resume, fold the journal of the interrupted patient back into
        # the manifest before computing the todo list.
        journal = PatientJournal(out_dir)
        if self.resume:
            seen = self.manifest.data.get(patient_id, {})
            for stem, status in journal.entries().items():
                if stem not in seen:
                    self.manifest.record(patient_id, stem, status)

        todo = []
        already = 0
        for f in files:
            stem = f.stem
            if self.resume and self.manifest.is_done(patient_id, stem):
                already += 1
            else:
                todo.append(f)

        if self.mode == "sequential":
            ok, failed, truncated = self._run_sequential(
                patient_id, out_dir, todo, journal
            )
        else:
            ok, failed, truncated = self._run_parallel(
                patient_id, out_dir, todo, journal
            )
        journal.close()

        result = PatientResult(
            patient_id=patient_id,
            total=len(files),
            succeeded=ok + already,
            failed_slices=failed,
            truncated_slices=truncated,
        )
        if truncated:
            log.warning(
                "patient %s: %d slice(s) hit the region-growing iteration "
                "cap; masks under-cover (raise --grow-max-iters): %s",
                patient_id, len(truncated), ", ".join(truncated[:8]),
            )
            # structured surfacing of grow_converged=False: WARNING event +
            # pipeline_grow_truncated_total counter, not just a log line.
            # Guarded: a telemetry failure here would otherwise mark a
            # fully-exported patient as failed (sink I/O errors are already
            # contained in EventLog, but the run's results take no chances)
            try:
                self.obs.grow_truncated(
                    patient_id, count=len(truncated), slices=truncated[:16]
                )
            except Exception as e:  # noqa: BLE001 — telemetry never costs a run
                log.warning(
                    "patient %s: truncation telemetry failed: %s", patient_id, e
                )
        self.manifest.flush()
        print(
            f"\nPatient {patient_id} completed. Successfully processed "
            f"{result.succeeded}/{result.total} images."
        )
        return result

    def _run_sequential(
        self, patient_id: str, out_dir: Path, files: List[Path], journal=None
    ) -> Tuple[int, List[str], List[str]]:
        host_render = self.batch_cfg.render_stage == "host"
        if self.model_params is not None:
            fn = self._student_fn(batched=False, mesh=None, host_render=host_render)
        elif host_render:
            fn = _compiled_slice_mask_fn(self.cfg)
        else:
            fn = _compiled_slice_fn(self.cfg)
        ok, failed, truncated = 0, [], []
        export_fault = self._export_fault_hook(patient_id)

        # One-slice-at-a-time with ONE dispatch in flight: slice N+1's
        # compute is enqueued (async dispatch) before slice N's results are
        # fetched and exported, hiding one direction of the per-slice
        # device round trip (~66 ms each way through the tunnel) that
        # dominated this driver's wall. Processing and export remain
        # strictly in slice order with per-slice containment — the
        # reference's sequential contract (main_sequential.cpp:170-272) is
        # about ORDER and interleaving, not about stalling the device
        # between slices (its local GPU has no such round trip to hide).
        # The timer's "compute" section therefore measures enqueue; the
        # device wait lands in the fetch inside "export".
        #
        # Student fns are batched even in sequential mode: their converged
        # flag is (1,); the classical slice fns emit a scalar — np.all
        # eats both.
        def resolve(p) -> None:
            nonlocal ok
            stem = p["stem"]
            try:
                if "error" in p:
                    raise p["error"]
                # the blocking device fetch counts toward "export": that is
                # where the per-slice device wait lands in this driver's
                # timing report (the enqueue-only "compute" section cannot
                # carry it)
                if host_render:
                    with self.timer.section("export"), self.feed.busy("fetch"):
                        # nm03-lint: disable=NM321 deliberate: this driver charges the per-slice device wait to "export" (see comment above); the sync IS the measurement
                        mask = np.asarray(p["mask_dev"])  # device sync
                    if p.get("t_disp0") is not None:
                        # device-in-flight interval: enqueue -> fetch done
                        self.feed.record(
                            "dispatch", p["t_disp0"], time.monotonic()
                        )
                    if self.mask_sink is not None:
                        self.mask_sink(patient_id, stem, mask)
                    with self.timer.section("export"), self.feed.busy("export"):
                        written = render_export_pairs(
                            [(stem, p["padded"], mask, p["dims"])],
                            out_dir,
                            self.cfg,
                            max_workers=1,
                            fault_hook=export_fault,
                            retry=self.retry,
                        )
                else:
                    with self.timer.section("export"):
                        with self.feed.busy("fetch"):
                            # nm03-lint: disable=NM321 deliberate: device wait charged to "export" by design, as on the host_render path above
                            orig = np.asarray(p["orig_dev"])
                            proc = np.asarray(p["proc_dev"])  # nm03-lint: disable=NM321 see above
                        if p.get("t_disp0") is not None:
                            self.feed.record(
                                "dispatch", p["t_disp0"], time.monotonic()
                            )
                        with self.feed.busy("export"):
                            written = export_pairs(
                                [(stem, orig, proc)],
                                out_dir,
                                max_workers=1,
                                fault_hook=export_fault,
                                retry=self.retry,
                            )
                if stem not in written:
                    raise IOError("JPEG export failed")
                # after the export check: truncated means "the pair exists
                # but the mask under-covers" — a failed slice is only
                # failed. Truncated gets its own manifest status so a
                # --resume rerun with a raised cap recomputes it.
                if not bool(np.all(np.asarray(p["conv"]))):
                    truncated.append(stem)
                    status = STATUS_TRUNCATED
                else:
                    status = STATUS_DONE
                self.manifest.record(patient_id, stem, status)
                if journal is not None:
                    journal.record(stem, status)
                ok += 1
            except Exception as e:  # noqa: BLE001 - reference: don't throw
                log.warning("error processing file %s: %s", stem, e)
                self.manifest.record(patient_id, stem, STATUS_FAILED)
                if journal is not None:
                    journal.record(stem, STATUS_FAILED)
                failed.append(stem)

        # Supervised dispatch (resilience): with a --dispatch-timeout-s the
        # primary fetches its results INSIDE the deadline (a wedged fetch is
        # the same wedge as a wedged dispatch), trading the one-in-flight
        # enqueue overlap for wedge immunity. Unsupervised (the default) the
        # call is inline and async exactly as before — the supervisor only
        # adds the transient-error retry policy around it.
        supervised = self.dispatch.supervised

        def run_dispatch(pixels_dev, dims_dev, pixels_host, dims_host, index):
            # dispatch consumes the ingest-staged device arrays; the CPU
            # degradation fallback recomputes from the HOST copies the
            # stager preserved (a fetch from the wedged device is the
            # wedge). --sanitize: inputs were staged, so an implicit h2d
            # inside this window is a hidden re-stage and raises.
            if supervised:
                primary = lambda: tuple(  # noqa: E731
                    np.asarray(a) for a in fn(pixels_dev, dims_dev)
                )
            else:
                primary = lambda: fn(pixels_dev, dims_dev)  # noqa: E731
            fallback = lambda: self._fallback_call(  # noqa: E731
                batched=False, host_render=host_render
            )(pixels_host, dims_host)
            with sanitize.guard_dispatch():
                return self.dispatch.run(
                    primary,
                    fallback=fallback,
                    pre=self._dispatch_pre(patient_id, index),
                    staged_inputs=True,
                )

        # streaming ingest (ISSUE 11): the decode pool runs slices ahead,
        # the stager uploads slice N+1 while slice N computes, and the
        # bounded ring caps how far decode may outrun the chip. Processing
        # and export remain strictly in slice order with per-slice
        # containment — the reference's sequential contract
        # (main_sequential.cpp:170-272) is about ORDER and interleaving,
        # not about stalling the device between slices.
        def decode_one(job):
            di, f = job
            pixels = self._read_slice(f, patient=patient_id, index=di)
            if pixels is None:
                raise ValueError("decode/guard failed")
            padded, dims = self._pad_one(pixels)
            return {"stem": f.stem, "index": di, "pixels": padded, "dims": dims}

        def stage_one(item):
            # degraded run keeps the slice on the host (host_only —
            # rationale in staging.stage_batch)
            return stage_batch(item, host_only=self.dispatch.degraded)

        pending = None
        pipe = self._ingest_pipeline(
            list(enumerate(files)), decode_one, stage_one, patient_id
        )
        with pipe:
            for rec in pipe:
                if isinstance(rec, IngestFailure):
                    # decode failure contained as a record: resolve() logs
                    # and counts it AFTER the previous slice completes —
                    # failure handling stays in slice order
                    _, f = rec.item
                    cur = {"stem": f.stem, "error": rec.error}
                else:
                    stem = rec["stem"]
                    try:
                        with self.timer.section("compute"):
                            t_disp0 = time.monotonic()
                            if host_render:
                                mask_dev, conv = run_dispatch(
                                    rec["pixels"], rec["dims"],
                                    rec["pixels_host"], rec["dims_host"],
                                    rec["index"],
                                )
                                cur = {
                                    "stem": stem, "mask_dev": mask_dev,
                                    "conv": conv,
                                    "padded": rec["pixels_host"],
                                    "dims": rec["dims_host"],
                                    "t_disp0": t_disp0,
                                }
                            else:
                                orig_dev, proc_dev, conv = run_dispatch(
                                    rec["pixels"], rec["dims"],
                                    rec["pixels_host"], rec["dims_host"],
                                    rec["index"],
                                )
                                cur = {
                                    "stem": stem, "orig_dev": orig_dev,
                                    "proc_dev": proc_dev, "conv": conv,
                                    "t_disp0": t_disp0,
                                }
                    except Exception as e:  # noqa: BLE001 - reference: don't throw
                        cur = {"stem": stem, "error": e}
                if pending is not None:
                    resolve(pending)
                pending = cur
            if pending is not None:
                resolve(pending)
        self._note_ingest(pipe)
        return ok, failed, truncated

    def _run_parallel(
        self, patient_id: str, out_dir: Path, files: List[Path], journal=None
    ) -> Tuple[int, List[str], List[str]]:
        import jax

        host_render = self.batch_cfg.render_stage == "host"
        # Every LOCAL device joins a ('data',) mesh and the batch axis is
        # sharded across it — the pod-scale form of the reference's OpenMP
        # batch loop (SURVEY.md section 2.3 DP row). One device degenerates
        # to the plain vmapped program. Local, not global: in a multi-process
        # job each rank owns disjoint patients, so its programs touch only
        # its own chips and nothing rides DCN except the final summary.
        local = jax.local_devices()
        n_dev = len(local)
        mesh = None
        if n_dev > 1:
            from nm03_capstone_project_tpu.parallel import make_mesh

            mesh = make_mesh(axis_names=("data",), devices=local)

        if self.model_params is not None:
            fn = self._student_fn(batched=True, mesh=mesh, host_render=host_render)
        elif mesh is not None:
            from nm03_capstone_project_tpu.parallel.dp import process_batch_sharded

            if host_render:

                def fn(px, dm):
                    out = process_batch_sharded(
                        px, dm, self.cfg, mesh, mask_only=True
                    )
                    return out["mask"], out["grow_converged"]

            else:

                def fn(px, dm):
                    out = process_batch_sharded(
                        px, dm, self.cfg, mesh, with_render=True
                    )
                    return out["original"], out["mask"], out["grow_converged"]

        else:
            fn = (
                _compiled_batch_mask_fn(self.cfg)
                if host_render
                else _compiled_batch_fn(self.cfg)
            )
        bs = self.batch_cfg.batch_size
        if mesh is not None:
            # slice batches at a mesh-aligned size: full batches then pad to
            # exactly themselves (zero dead lanes), and every batch divides
            # the data axis
            import math

            m = math.lcm(8, n_dev)
            bs = max(m, (bs // m) * m)
        ok, failed = 0, []
        # written from IO-pool threads (dict ops are atomic under the GIL);
        # resolved against `written` at the end so a slice whose export
        # fails is counted failed, never truncated
        conv_by_stem: Dict[str, bool] = {}
        batches = [files[i : i + bs] for i in range(0, len(files), bs)]

        def pad_target(n: int) -> int:
            # Lane-friendly bucketing: pad each batch up to the next multiple
            # of 8 (capped at batch_size) instead of always to batch_size.
            # A cohort of 8-slice patients under the reference's bs=25 would
            # otherwise compute 3x dead lanes; buckets keep recompiles
            # bounded (at most bs/8 shapes) while never padding past 7 lanes.
            # With a mesh the bucket is lcm(8, n_dev), so every padded batch
            # divides the data axis; the cap at bs stays correct in both
            # cases because mesh-mode bs is itself a multiple of the bucket.
            bucket = 8 if mesh is None else math.lcm(8, n_dev)
            return min(bs, ((n + bucket - 1) // bucket) * bucket)
        export_futures = []
        expected_stems: List[str] = []
        use_native = self.batch_cfg.use_native and _native_available()
        # decode concurrency: up to `ingest_decode_workers` batches in
        # flight on the ingest pool; the per-batch slice decode then
        # splits the io_workers budget so a small cohort (few batches)
        # still decodes its slices in parallel while a deep one pipelines
        # across batches (_decode_thread_split is the one formula)
        inner_threads = self._decode_thread_split(len(batches))

        def decode_batch(job):
            """One ingest work item: (batch index, files) -> decoded host
            batch (the pipeline accounts it as the feed's decode phase)."""
            bi, batch_files = job
            if use_native:
                # the C++ thread pool decodes + pads the whole batch
                # (csrc nm03_load_batch); same batch-count-clamped thread
                # split as the Python path below, so a one-batch cohort
                # keeps the full io_workers budget
                return self._decode_batch_native(
                    batch_files,
                    pad_target(len(batch_files)),
                    patient_id,
                    bi * bs,
                    threads=inner_threads,
                )
            idx0 = bi * bs
            if inner_threads > 1 and len(batch_files) > 1:
                with cf.ThreadPoolExecutor(inner_threads) as slice_pool:
                    decoded = list(
                        slice_pool.map(
                            lambda jf: self._read_slice(
                                jf[1], patient_id, idx0 + jf[0]
                            ),
                            enumerate(batch_files),
                        )
                    )
            else:
                decoded = [
                    self._read_slice(f, patient_id, idx0 + j)
                    for j, f in enumerate(batch_files)
                ]
            stems = [f.stem for f in batch_files]
            bad = [s for s, p in zip(stems, decoded) if p is None]
            good = [(s, p) for s, p in zip(stems, decoded) if p is not None]
            if not good:
                return {"stems": [], "bad": bad, "pixels": None, "dims": None}
            padded, dims = self._pad_stack(
                [p for _, p in good], pad_to=pad_target(len(batch_files))
            )
            return {
                "stems": [s for s, _ in good],
                "bad": bad,
                "pixels": padded,
                "dims": dims,
            }

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            batch_sharding = NamedSharding(mesh, PartitionSpec("data"))
        else:
            batch_sharding = None

        def stage(item):
            # move only the compute inputs; the host copy of the pixel
            # stack stays behind (as <key>_host) for the host-render
            # export path and the CPU fallback. With a mesh the
            # host->device copy is already batch-sharded, so each device
            # receives only its shard. A degraded run keeps the batch on
            # the host (host_only — rationale in staging.stage_batch).
            if item.get("pixels") is None:
                return item
            return stage_batch(
                item,
                placement=batch_sharding,
                host_only=self.dispatch.degraded,
            )

        export_fault = self._export_fault_hook(patient_id)
        supervised = self.dispatch.supervised

        def journal_slice(stem):
            # slice-grain crash record the moment the pair is on disk
            # (fires per slice from the export pool threads, so a kill
            # mid-batch loses at most the slice in flight; the journal
            # is thread-safe). conv_by_stem is populated before the
            # batch's export writes begin in both render paths.
            if journal is not None:
                journal.record(
                    stem,
                    STATUS_DONE
                    if conv_by_stem.get(stem, True)
                    else STATUS_TRUNCATED,
                )

        # streaming ingest (ISSUE 11): the decode pool runs `workers`
        # batches ahead into the bounded staging ring; the stager enqueues
        # batch N+1's (async) device_put while batch N computes; result
        # fetch + export stream back on the same pool. Backpressure: a
        # full ring blocks the feeder, so decode can never outrun HBM.
        pipe = self._ingest_pipeline(
            list(enumerate(batches)), decode_batch, stage, patient_id
        )
        with pipe:
            for bi, batch in enumerate(pipe):
                if isinstance(batch, IngestFailure):
                    # whole-batch decode failure (injected ingest fault or
                    # an unexpected decode-layer error): every slice of the
                    # batch is counted failed — contained, never propagated
                    _, batch_files = batch.item
                    log.warning(
                        "ingest decode failed for batch %d: %s",
                        batch.index, batch.error,
                    )
                    for f in batch_files:
                        failed.append(f.stem)
                        self.manifest.record(patient_id, f.stem, STATUS_FAILED)
                        if journal is not None:
                            journal.record(f.stem, STATUS_FAILED)
                    continue
                for s in batch["bad"]:
                    failed.append(s)
                    self.manifest.record(patient_id, s, STATUS_FAILED)
                    if journal is not None:
                        journal.record(s, STATUS_FAILED)
                if not batch["stems"]:
                    continue
                pix, dm = batch["pixels"], batch["dims"]
                pxh, dmh = batch["pixels_host"], batch["dims_host"]
                pre = self._dispatch_pre(patient_id, bi)
                # degradation target: the same batch recomputed on the CPU
                # backend from the HOST copies (never the device arrays — a
                # fetch from the wedged device is the wedge)
                fallback = lambda pxh=pxh, dmh=dmh: self._fallback_call(  # noqa: E731
                    batched=True, host_render=host_render
                )(pxh, dmh)
                if host_render:
                    # 'dispatch', not 'compute': unsupervised this enqueues
                    # only — the 65 KB/slice mask fetch happens on the IO
                    # worker, overlapped with the next batch's device compute
                    # (the device stream is FIFO, so the worker's device_get
                    # also serves as the batch sync). Device time is
                    # therefore absorbed by the 'export' wait; compare
                    # drivers on the results JSON's wall_s, not per-section
                    # times. SUPERVISED (--dispatch-timeout-s), the fetch
                    # moves inside the deadline — a wedged fetch is the same
                    # wedge as a wedged dispatch — trading that overlap for
                    # wedge immunity.
                    if supervised:
                        primary = lambda pix=pix, dm=dm: tuple(  # noqa: E731
                            np.asarray(a) for a in fn(pix, dm)
                        )
                    else:
                        primary = lambda pix=pix, dm=dm: fn(pix, dm)  # noqa: E731
                    t_disp0 = time.monotonic()
                    with self.timer.section("dispatch"):
                        # --sanitize (upload-only guard): inputs were staged
                        # by the ingest stager, so an implicit h2d inside
                        # this window is a hidden re-stage; the primary's
                        # d2h fetch is sanctioned (inside the deadline)
                        with sanitize.guard_dispatch():
                            mask_dev, conv_dev = self.dispatch.run(
                                primary,
                                fallback=fallback,
                                pre=pre,
                                staged_inputs=True,
                            )

                    def fetch_render_export(
                        mask_dev=mask_dev, conv_dev=conv_dev, batch=batch,
                        t_disp0=t_disp0,
                    ):
                        with self.feed.busy("fetch"):
                            mask_b = np.asarray(mask_dev)
                            conv_b = np.asarray(conv_dev)
                        # device-in-flight interval for the feed report:
                        # enqueue -> fetch complete (an upper bound on
                        # device busy; the reported stall is a lower bound)
                        self.feed.record("dispatch", t_disp0, time.monotonic())
                        for i, s in enumerate(batch["stems"]):
                            conv_by_stem[s] = bool(conv_b[i])
                        if self.mask_sink is not None:
                            for i, s in enumerate(batch["stems"]):
                                self.mask_sink(patient_id, s, mask_b[i])
                        items = [
                            (
                                s,
                                batch["pixels_host"][i],
                                mask_b[i],
                                batch["dims_host"][i],
                            )
                            for i, s in enumerate(batch["stems"])
                        ]
                        with self.feed.busy("export"):
                            return render_export_pairs(
                                items,
                                out_dir,
                                self.cfg,
                                4,
                                fault_hook=export_fault,
                                retry=self.retry,
                                success_hook=journal_slice,
                            )

                    # hand fetch+render+export to the ingest pool: the mask
                    # streams back while the next batch computes
                    export_futures.append(pipe.submit(fetch_render_export))
                else:
                    with self.timer.section("compute"), self.feed.busy(
                        "dispatch"
                    ):
                        with sanitize.guard_dispatch():
                            orig_b, proc_b, conv_b = self.dispatch.run(
                                lambda pix=pix, dm=dm: tuple(
                                    np.asarray(a) for a in fn(pix, dm)
                                ),
                                fallback=fallback,
                                pre=pre,
                                staged_inputs=True,
                            )
                    for i, s in enumerate(batch["stems"]):
                        conv_by_stem[s] = bool(conv_b[i])
                    items = [
                        (s, orig_b[i], proc_b[i]) for i, s in enumerate(batch["stems"])
                    ]

                    # hand encoding to the IO pool; overlap with next batch
                    # compute (wrapped so the export phase lands in the
                    # feed report from the worker thread too)
                    def encode_export(items=items):
                        with self.feed.busy("export"):
                            return export_pairs(
                                items,
                                out_dir,
                                4,
                                fault_hook=export_fault,
                                retry=self.retry,
                                success_hook=journal_slice,
                            )

                    export_futures.append(pipe.submit(encode_export))
                expected_stems.extend(batch["stems"])
            with self.timer.section("export"):
                written = set()
                for fut in export_futures:
                    written.update(fut.result())
        self._note_ingest(pipe)
        # success is "the JPEG pair exists", not "compute finished"
        truncated: List[str] = []
        for s in expected_stems:
            if s in written:
                ok += 1
                if not conv_by_stem.get(s, True):
                    truncated.append(s)
                    self.manifest.record(patient_id, s, STATUS_TRUNCATED)
                else:
                    self.manifest.record(patient_id, s, STATUS_DONE)
            else:
                log.warning("export failed for slice %s", s)
                self.manifest.record(patient_id, s, STATUS_FAILED)
                if journal is not None:
                    journal.record(s, STATUS_FAILED)
                failed.append(s)
        return ok, failed, truncated

    def _decode_batch_native(
        self,
        batch_files: List[Path],
        pad_to: int,
        patient_id: Optional[str] = None,
        base_index: int = 0,
        threads: Optional[int] = None,
    ) -> dict:
        """Decode one batch via the C++ thread-pool loader.

        Same output contract as the Python path in ``staged()``: good slices
        compacted into the leading rows of a fixed (pad_to, canvas, canvas)
        stack, failed stems listed in ``bad``. ``threads`` is the per-call
        C++ pool size — _run_parallel passes its batch-count-clamped split
        of the io_workers budget (a one-batch cohort gets the whole
        budget, a deep one pipelines across batches instead).
        """
        from nm03_capstone_project_tpu import native

        if threads is None:
            # direct callers (tests) decode one batch in isolation: the
            # same formula, clamped to a single batch in flight
            threads = self._decode_thread_split(1)
        pixels, dims, okf, errs = native.load_batch_native(
            batch_files,
            canvas=self.cfg.canvas,
            min_dim=self.cfg.min_dim,
            threads=threads,
        )
        # parse failures fall back through the Python reader: its envelope
        # is a superset of the C++ parser's (the C++ side decodes
        # uncompressed LE, RLE Lossless, JPEG Lossless and JPEG-LS;
        # baseline JPEG decodes via PIL in the Python reader only), so a
        # compressed cohort still flows through the native fast path with
        # per-slice fallback instead of failing wholesale. The fallbacks
        # run on their own small pool: a fully-baseline-JPEG batch would
        # otherwise decode serially on this one thread. Accounted through
        # the resilience retry counter (cause="native_parse") but not
        # budget-gated: this is a deterministic alternate-decoder path, not
        # a transient failure, so a large compressed cohort must never
        # exhaust a budget and start failing slices it used to decode.
        retry_idx = [
            i for i, (o, e) in enumerate(zip(okf, errs))
            if not o and int(e) == 2  # "DICOM parse failed"
        ]
        if retry_idx:
            self.obs.registry.counter(
                RESILIENCE_RETRIES_TOTAL,
                help="supervised retries by cause (resilience.RetryPolicy)",
                cause="native_parse",
            ).inc(len(retry_idx))
            with cf.ThreadPoolExecutor(min(threads, len(retry_idx))) as pool:
                retried = pool.map(
                    lambda i: decode_and_guard(batch_files[i], self.cfg),
                    retry_idx,
                )
            for i, px in zip(retry_idx, retried):
                if px is not None:
                    h, w = px.shape
                    pixels[i] = 0.0  # slot may hold a partial native write
                    pixels[i, :h, :w] = px
                    dims[i] = (h, w)
                    okf[i] = True
        # chaos routing: files a decode-site fault rule selects re-decode
        # through the Python path, where injection actually happens (the
        # selector probe is side-effect free; fire() runs in _read_slice)
        injected_bad: set = set()
        plan = self.fault_plan
        if plan is not None and plan.has_site("decode"):
            for i, f in enumerate(batch_files):
                if plan.routes_decode(
                    patient=patient_id, stem=f.stem, index=base_index + i
                ):
                    px = self._read_slice(
                        f, patient=patient_id, index=base_index + i
                    )
                    if px is None:
                        okf[i] = False
                        injected_bad.add(f.stem)
                    else:
                        h, w = px.shape
                        pixels[i] = 0.0
                        pixels[i, :h, :w] = px
                        dims[i] = (h, w)
                        okf[i] = True
        stems = [f.stem for f in batch_files]
        bad = [s for s, o in zip(stems, okf) if not o]
        for f, o, e in zip(batch_files, okf, errs):
            if not o and f.stem not in injected_bad:  # _read_slice logged those
                log.warning(
                    "failed to decode %s: %s",
                    f.name,
                    native.BATCH_ERRORS.get(int(e), f"error {e}"),
                )
        idx = np.flatnonzero(okf)
        if idx.size == 0:
            return {"stems": [], "bad": bad, "pixels": None, "dims": None}
        if idx.size == pad_to:  # full all-ok batch: arena is already in shape
            return {"stems": stems, "bad": [], "pixels": pixels, "dims": dims}
        out = np.zeros((pad_to, self.cfg.canvas, self.cfg.canvas), np.float32)
        out_dims = np.full((pad_to, 2), self.cfg.min_dim, np.int32)
        out[: idx.size] = pixels[idx]
        out_dims[: idx.size] = dims[idx]
        return {
            "stems": [stems[i] for i in idx],
            "bad": bad,
            "pixels": out,
            "dims": out_dims,
        }

    # -- padding helpers ---------------------------------------------------

    def _pad_one(self, pixels: np.ndarray):
        c = self.cfg.canvas
        out = np.zeros((c, c), np.float32)
        out[: pixels.shape[0], : pixels.shape[1]] = pixels
        return out, np.asarray(pixels.shape, np.int32)

    def _pad_stack(self, arrays: List[np.ndarray], pad_to: int):
        """Stack to a FIXED batch size so one compiled program serves all
        batches (ragged final batches are padded with blank slices whose
        outputs are simply not exported)."""
        c = self.cfg.canvas
        out = np.zeros((pad_to, c, c), np.float32)
        dims = np.full((pad_to, 2), self.cfg.min_dim, np.int32)
        for i, a in enumerate(arrays):
            out[i, : a.shape[0], : a.shape[1]] = a
            dims[i] = a.shape
        return out, dims

    # -- streaming ingest --------------------------------------------------

    def _decode_thread_split(self, n_batches: int) -> int:
        """Per-batch decode thread budget: io_workers divided by how many
        batches can actually decode concurrently (the ingest pool's bound,
        clamped by the cohort's batch count) — a one-batch cohort keeps
        the whole budget, a deep one pipelines across batches. THE one
        formula for both the Python slice pool and the C++ native loader."""
        workers = max(
            1, self.batch_cfg.ingest_decode_workers or self.batch_cfg.io_workers
        )
        concurrent = max(1, min(workers, max(n_batches, 1)))
        return max(1, self.batch_cfg.io_workers // concurrent)

    def _ingest_pipeline(
        self, source, decode, stage, patient_id: str
    ) -> IngestPipeline:
        """One host→HBM pipeline per patient run (docs/OPERATIONS.md
        "Feeding the chip"): ring depth and decode pool from BatchConfig,
        feed/span/fault plumbing shared with the rest of the driver."""
        workers = self.batch_cfg.ingest_decode_workers or self.batch_cfg.io_workers
        return IngestPipeline(
            source=source,
            decode=decode,
            stage=stage,
            depth=max(self.batch_cfg.ingest_depth, 1),
            decode_workers=max(workers, 1),
            staged_depth=max(self.batch_cfg.prefetch_depth, 1),
            feed=self.feed,
            spans=self.timer,
            obs=self.obs,
            fault_plan=self.fault_plan,
            fault_patient=patient_id,
        )

    def _note_ingest(self, pipe: IngestPipeline) -> None:
        """Collect one pipeline's drained snapshot + refresh the live
        ``ingest_*`` gauges. Telemetry never costs a run."""
        try:
            self._ingest_reports.append(pipe.publish(self.obs.registry))
        except Exception as e:  # noqa: BLE001 — telemetry never costs a run
            log.warning("ingest telemetry failed: %s", e)

    def ingest_report(self) -> Optional[dict]:
        """Run-level aggregate of the per-patient pipeline snapshots
        (the ``ingest`` record in the drivers' --results-json)."""
        reps = self._ingest_reports
        if not reps:
            return None
        counts: Dict[str, int] = {}
        for r in reps:
            for k, v in r["counts"].items():
                counts[k] = counts.get(k, 0) + v
        weighted = [
            r for r in reps
            if r["upload_overlap_ratio"] is not None and r["upload_s"] > 0
        ]
        up_s = sum(r["upload_s"] for r in weighted)
        overlap = (
            round(
                sum(r["upload_overlap_ratio"] * r["upload_s"] for r in weighted)
                / up_s,
                4,
            )
            if up_s > 0
            else None
        )
        return {
            "patients": len(reps),
            "ring_capacity": reps[-1]["ring"]["capacity"],
            "ring_peak": max(r["ring"]["peak"] for r in reps),
            "ring_occupancy_ratio": round(
                sum(r["ring"]["occupancy_ratio"] for r in reps) / len(reps), 4
            ),
            "decode_queue_peak": max(r["decode_queue_peak"] for r in reps),
            "upload_s": round(sum(r["upload_s"] for r in reps), 4),
            "upload_overlap_ratio": overlap,
            "counts": counts,
        }

    def publish_ingest(self) -> Optional[dict]:
        """The drained-at-exit gauge refresh (drivers call this right
        before the final --metrics-out snapshot): occupancy = mean over
        patient pipelines, queue depth = the run's decode-lookahead
        high-water mark, overlap = upload-weighted mean."""
        rep = self.ingest_report()
        if rep is None:
            return None
        from nm03_capstone_project_tpu.ingest.pipeline import publish_gauges

        publish_gauges(
            self.obs.registry,
            occupancy=rep["ring_occupancy_ratio"],
            queue_depth=rep["decode_queue_peak"],
            overlap=rep["upload_overlap_ratio"],
        )
        return rep

    # -- cohort loop -------------------------------------------------------

    def _emit_outcome(self, pid: str, status: str, **fields) -> None:
        """Terminal patient telemetry; never raises into the cohort loop
        (a duplicate pid from a pathological listing, or any emit failure,
        is logged — telemetry must not alter the run's actual results)."""
        try:
            if not self.obs.has_outcome(pid):
                self.obs.patient_outcome(pid, status, **fields)
        except Exception as e:  # noqa: BLE001 — telemetry never costs a run
            log.warning("patient %s: outcome telemetry failed: %s", pid, e)

    def process_all_patients(self) -> RunSummary:
        mode_name = self.mode.capitalize()
        print(f"\n=== Starting {mode_name} Processing for All Patients ===\n")
        patients = find_patient_dirs(self.base_path)
        print(f"Found {len(patients)} patient directories.")
        from nm03_capstone_project_tpu.cli.common import shard_patients

        patients = shard_patients(patients, self.process_rank, self.process_count)
        summary = RunSummary()
        if not patients:
            print("No patient directories found. Exiting.")
            return summary
        for pid in patients:
            try:
                result = self.process_patient(pid)
            except Exception as e:  # noqa: BLE001 - reference: move to next patient
                log.warning("failed to process patient %s: %s", pid, e)
                summary.patients.append(PatientResult(pid, 0, 0))
                self._emit_outcome(pid, "failed", error_class=type(e).__name__)
                continue
            summary.patients.append(result)
            summary.patients_ok += 1
            # the ONE terminal telemetry record of this patient's run —
            # OUTSIDE the containment try: a telemetry failure must never
            # double-count the patient in the cohort summary
            self._emit_outcome(
                pid,
                "ok",
                slices_total=result.total,
                slices_ok=result.succeeded,
                slices_failed=len(result.failed_slices),
                slices_truncated=len(result.truncated_slices),
            )
        print("\n=== All Processing Completed ===\n")
        print(
            f"Successfully processed {summary.patients_ok}/{len(patients)} patients."
        )
        return summary
