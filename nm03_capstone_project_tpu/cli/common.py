"""Shared CLI plumbing.

The reference hard-codes every knob (SURVEY.md section 5 "Config / flag
system": dataset root via Config::getTestDataPath() + fixed subpath, output
dirs as ctor defaults, batch size / thread count / all pipeline parameters
inlined). Here every constant in the PipelineConfig is a flag, and device
selection is explicit (``--device tpu|cpu|auto``).

Device selection must happen before jax initializes, so CLI mains keep jax
imports *inside* functions and call :func:`apply_device_env` first.
"""

from __future__ import annotations

import argparse
import os
from pathlib import Path

from nm03_capstone_project_tpu.config import BatchConfig, PipelineConfig

# The reference resolves its cohort as Config::getTestDataPath() +
# "Brain-Tumor-Progression/T1-Post-Combined-P001-P020/"
# (main_sequential.cpp:83-84). The env var is this framework's equivalent of
# FAST's configured test-data path.
DATA_PATH_ENV = "NM03_DATA_PATH"
DEFAULT_COHORT_SUBPATH = "Brain-Tumor-Progression/T1-Post-Combined-P001-P020"


def add_common_args(parser: argparse.ArgumentParser) -> None:
    parser.add_argument(
        "--base-path",
        default=None,
        help="cohort root (defaults to $NM03_DATA_PATH/"
        f"{DEFAULT_COHORT_SUBPATH}); ignored with --synthetic",
    )
    parser.add_argument(
        "--synthetic",
        type=int,
        default=0,
        metavar="N",
        help="generate an N-patient synthetic cohort instead of reading real data",
    )
    parser.add_argument(
        "--synthetic-slices", type=int, default=8, help="slices per synthetic patient"
    )
    parser.add_argument(
        "--device",
        choices=["auto", "tpu", "cpu"],
        default="auto",
        help="compute backend (cpu uses the host XLA backend)",
    )
    parser.add_argument("--resume", action="store_true", help="skip slices already in the manifest")
    parser.add_argument("--verbose", action="store_true", help="enable INFO logging")
    parser.add_argument(
        "--no-native",
        action="store_true",
        help="force the pure-Python decode/encode path even when the C++ "
        "runtime (csrc/) is buildable",
    )
    parser.add_argument(
        "--results-json",
        default=None,
        help="write a timing/success results JSON (in-tree replacement for the "
        "reference's out-of-tree hyperfine artifacts)",
    )
    parser.add_argument(
        "--profile-dir",
        default=None,
        help="capture a jax.profiler trace here (view with tensorboard or "
        "Perfetto; in-tree replacement for the reference's perf/Hotspot use)",
    )
    add_observability_args(parser)


def add_observability_args(parser: argparse.ArgumentParser) -> None:
    """--metrics-out / --log-json / --heartbeat-s (docs/OBSERVABILITY.md).

    Shared by every driver INCLUDING the ones that skip add_common_args
    (train, bench), so the telemetry surface is uniform across entry points.
    """
    g = parser.add_argument_group(
        "observability", "structured run telemetry (docs/OBSERVABILITY.md)"
    )
    g.add_argument(
        "--metrics-out",
        default=None,
        metavar="JSON",
        help="write the run's metrics snapshot here (counters, gauges, "
        "per-stage latency histograms; schema nm03.metrics.v1)",
    )
    g.add_argument(
        "--log-json",
        default=None,
        metavar="JSONL",
        help="write structured JSON-lines events here (run id + git SHA on "
        "every record, one terminal outcome event per patient; schema "
        "nm03.events.v1; one run per file — truncated at start)",
    )
    g.add_argument(
        "--heartbeat-s",
        type=float,
        default=30.0,
        metavar="SEC",
        help="heartbeat event period for --log-json streams (uptime + live "
        "counter totals; 0 disables)",
    )
    g.add_argument(
        "--sanitize",
        action="store_true",
        help="enable the runtime twins of the nm03-lint static rules "
        "(docs/STATIC_ANALYSIS.md): jax_debug_nans, a transfer guard "
        "around staged-batch dispatch, and a recompile watchdog feeding "
        "pipeline_recompiles_total. Debugging/CI mode: correctness "
        "checks cost throughput",
    )


def add_resilience_args(parser: argparse.ArgumentParser) -> None:
    """--retry-max / --retry-backoff-s / --dispatch-timeout-s /
    --fallback-cpu / --fault-plan (docs/RESILIENCE.md).

    For the batch drivers that dispatch device work per cohort (sequential /
    parallel). Defaults preserve the unsupervised behavior: no deadline, no
    fault plan, retries only where a transient device error was previously
    a hard failure.
    """
    from nm03_capstone_project_tpu.resilience import ResilienceConfig

    d = ResilienceConfig()
    g = parser.add_argument_group(
        "resilience", "supervised execution + chaos testing (docs/RESILIENCE.md)"
    )
    g.add_argument(
        "--retry-max",
        type=int,
        default=d.retry_max,
        help="retries per transient device/export error (0 disables; a "
        "per-cause run budget caps the total)",
    )
    g.add_argument(
        "--retry-backoff-s",
        type=float,
        default=d.retry_backoff_s,
        help="initial retry backoff; doubles per attempt with deterministic "
        "jitter",
    )
    g.add_argument(
        "--dispatch-timeout-s",
        type=float,
        default=d.dispatch_timeout_s,
        metavar="SEC",
        help="wall-clock deadline per device dispatch batch (0 disables "
        "supervision). On expiry the dispatch is abandoned and the run "
        "degrades per --fallback-cpu — the escape hatch for the tunnel "
        "wedges documented in docs/OPERATIONS.md. Supervision moves the "
        "result fetch inside the deadline, trading the fetch/compute "
        "overlap for wedge immunity",
    )
    g.add_argument(
        "--fallback-cpu",
        action=argparse.BooleanOptionalAction,
        default=d.fallback_cpu,
        help="on dispatch deadline expiry or device loss, finish the "
        "remaining work on the CPU backend (XLA path, Pallas excluded) "
        "instead of failing it; --no-fallback-cpu fails fast instead — "
        "either way the run terminates, never wedges",
    )
    g.add_argument(
        "--fault-plan",
        default=None,
        metavar="SPEC",
        help="seeded deterministic fault plan: a JSON file path or inline "
        "JSON (see resilience.faultinject). Also honored from "
        "$NM03_FAULT_PLAN when the flag is unset. Chaos testing only — "
        "injects decode/dispatch/export faults at the planned sites",
    )


def resilience_config_from_args(args: argparse.Namespace):
    from nm03_capstone_project_tpu.resilience import FaultPlan, ResilienceConfig

    d = ResilienceConfig()
    return ResilienceConfig(
        retry_max=getattr(args, "retry_max", d.retry_max),
        retry_backoff_s=getattr(args, "retry_backoff_s", d.retry_backoff_s),
        dispatch_timeout_s=getattr(
            args, "dispatch_timeout_s", d.dispatch_timeout_s
        ),
        fallback_cpu=getattr(args, "fallback_cpu", d.fallback_cpu),
        fault_plan=FaultPlan.from_spec(getattr(args, "fault_plan", None)),
    )


def make_run_context(
    args: argparse.Namespace, driver: str, rank: int = 0, argv=None
):
    """The driver's RunContext from its parsed flags.

    Only rank 0 gets the file sinks: in a multi-process job every rank would
    otherwise append to the same ``--log-json`` path (interleaved streams
    fail the one-run_id-per-stream schema), so the artifacts describe rank
    0's shard and the collective summary it prints. Non-zero ranks still
    accumulate metrics in memory for their own results reporting.
    """
    from nm03_capstone_project_tpu.obs import RunContext

    sink = rank == 0
    ctx = RunContext.create(
        driver,
        metrics_out=getattr(args, "metrics_out", None) if sink else None,
        log_json=getattr(args, "log_json", None) if sink else None,
        heartbeat_s=getattr(args, "heartbeat_s", 0.0) or 0.0,
        argv=argv,
    )
    if getattr(args, "sanitize", False):
        # the runtime twins of nm03-lint (docs/STATIC_ANALYSIS.md); must
        # run after apply_device_env (jax config follows the pinned
        # backend) — drivers call make_run_context inside run(), so that
        # ordering holds by construction
        from nm03_capstone_project_tpu.utils import sanitize

        sanitize.enable(ctx.registry)
    if hasattr(args, "median_impl"):
        # snapshot which median/render paths this run will ACTUALLY use,
        # plus the comparator counts behind the median network (jax-free
        # module). A --use-pallas request on a non-TPU backend silently
        # degrades to the XLA path in every dispatcher, so the recorded
        # label must resolve the backend the same way — a CPU run must
        # never be attributed to the Pallas kernels.
        from nm03_capstone_project_tpu.ops.selection_network import (
            comparator_counts,
        )

        use_pallas = getattr(args, "use_pallas", False)
        if use_pallas:
            from nm03_capstone_project_tpu.ops.pallas_median import (
                pallas_backend_supported,
            )

            use_pallas = pallas_backend_supported()
        ctx.record_pipeline_paths(
            median_impl=args.median_impl,
            render_fused=not getattr(args, "no_render_fuse", False),
            fuse_preprocess=not getattr(args, "no_preprocess_fuse", False),
            use_pallas=use_pallas,
            comparators=comparator_counts(args.median_window),
        )
    return ctx


def add_pipeline_args(parser: argparse.ArgumentParser) -> None:
    d = PipelineConfig()
    g = parser.add_argument_group("pipeline", "every constant the reference hard-codes")
    g.add_argument("--norm-low", type=float, default=d.norm_low)
    g.add_argument("--norm-high", type=float, default=d.norm_high)
    g.add_argument("--norm-min", type=float, default=d.norm_intensity_min)
    g.add_argument("--norm-max", type=float, default=d.norm_intensity_max)
    g.add_argument("--clip-low", type=float, default=d.clip_low)
    g.add_argument("--clip-high", type=float, default=d.clip_high)
    g.add_argument("--median-window", type=int, default=d.median_window)
    g.add_argument("--sharpen-gain", type=float, default=d.sharpen_gain)
    g.add_argument("--sharpen-sigma", type=float, default=d.sharpen_sigma)
    g.add_argument("--sharpen-kernel", type=int, default=d.sharpen_kernel)
    g.add_argument("--grow-low", type=float, default=d.grow_low)
    g.add_argument("--grow-high", type=float, default=d.grow_high)
    g.add_argument("--morph-size", type=int, default=d.morph_size)
    g.add_argument("--min-dim", type=int, default=d.min_dim)
    g.add_argument("--render-size", type=int, default=d.render_size)
    g.add_argument("--canvas", type=int, default=d.canvas)
    g.add_argument(
        "--use-pallas",
        action="store_true",
        help="route hot ops through the Pallas TPU kernels",
    )
    g.add_argument(
        "--median-impl",
        choices=["pruned", "merge", "sort"],
        default=d.median_impl,
        help="XLA median implementation: pruned selection network (fast "
        "default), full odd-even merge baseline, or the sort oracle — all "
        "bit-identical (ops.selection_network)",
    )
    g.add_argument(
        "--no-preprocess-fuse",
        action="store_true",
        help="with --use-pallas on TPU, run median/growing as separate "
        "Pallas kernels instead of the fused normalize->clip->median->"
        "sharpen preprocessing kernel",
    )
    g.add_argument(
        "--no-render-fuse",
        action="store_true",
        help="render the export pair as two independent device passes "
        "instead of the fused shared-geometry pass (pixel-identical; the "
        "unfused path is the comparison baseline bench.py times against)",
    )
    g.add_argument(
        "--grow-algorithm",
        choices=["dilate", "jump"],
        default=d.grow_algorithm,
        help="2D region-growing convergence schedule: one-ring dilation "
        "fixpoint or O(log) pointer-jumping label merge (identical masks "
        "whenever dilate converges within its iteration cap; not combinable "
        "with --use-pallas; 2D drivers only)",
    )
    g.add_argument(
        "--grow-block-iters", type=int, default=d.grow_block_iters,
        help="dilation steps per region-growing convergence check",
    )
    g.add_argument(
        "--grow-max-iters", type=int, default=d.grow_max_iters,
        help="hard cap on region growth, expressed as a RADIUS in pixels "
        "(dilate steps) for every --grow-algorithm: the dilate schedule "
        "runs up to this many one-ring steps, while the jump schedule "
        "derives its pointer-jumping round cap as ceil(log2(N))+2 so the "
        "same flag value bounds the same growth either way; a capped "
        "slice is counted as truncated in the summary and warned per "
        "patient",
    )


def pipeline_config_from_args(args: argparse.Namespace) -> PipelineConfig:
    return PipelineConfig(
        norm_low=args.norm_low,
        norm_high=args.norm_high,
        norm_intensity_min=args.norm_min,
        norm_intensity_max=args.norm_max,
        clip_low=args.clip_low,
        clip_high=args.clip_high,
        median_window=args.median_window,
        sharpen_gain=args.sharpen_gain,
        sharpen_sigma=args.sharpen_sigma,
        sharpen_kernel=args.sharpen_kernel,
        grow_low=args.grow_low,
        grow_high=args.grow_high,
        morph_size=args.morph_size,
        min_dim=args.min_dim,
        render_size=args.render_size,
        canvas=args.canvas,
        use_pallas=args.use_pallas,
        median_impl=args.median_impl,
        fuse_preprocess=not args.no_preprocess_fuse,
        render_fused=not args.no_render_fuse,
        grow_algorithm=args.grow_algorithm,
        grow_block_iters=args.grow_block_iters,
        grow_max_iters=args.grow_max_iters,
    )


def add_render_stage_arg(parser: argparse.ArgumentParser) -> None:
    """--render-stage, for the drivers that export JPEG pairs
    (sequential / parallel / volume).

    Deliberately NOT in add_common_args: the train driver doesn't go through
    the pair-export path, and an advertised-but-ignored flag is worse than an
    absent one — any driver adding this flag must honor it.
    """
    parser.add_argument(
        "--render-stage",
        choices=["host", "device"],
        default=BatchConfig.render_stage,
        help="where the 512x512 export renders are computed: 'host' fetches "
        "only the mask from the device and renders in the IO pool (default; "
        "~24x less host<->device traffic per slice), 'device' renders inside "
        "the jit (the canonical render.render_pair path)",
    )


def add_model_arg(parser: argparse.ArgumentParser) -> None:
    """--model, for the 2D batch drivers that can deploy the student."""
    parser.add_argument(
        "--model",
        default=None,
        metavar="CKPT",
        help="run the distilled 2D U-Net student from this checkpoint "
        "(written by nm03-train) instead of the classical pipeline — the "
        "deployment the distillation exists for: the network replaces "
        "everything downstream of normalize+clip",
    )


def load_model_checkpoint(args: argparse.Namespace, cfg, want_3d: bool = False):
    """Load + validate the --model checkpoint; None when the flag is unset."""
    if not getattr(args, "model", None):
        return None
    from nm03_capstone_project_tpu.models.checkpoint import load_params

    params, meta = load_params(args.model)
    meta = meta or {}
    if bool(meta.get("model_3d")) != want_3d:
        have = "3D" if meta.get("model_3d") else "2D"
        need = "3D" if want_3d else "2D"
        raise SystemExit(
            f"--model {args.model} holds the {have} student; this driver "
            f"deploys the {need} one"
        )
    ck = meta.get("canvas")
    if ck and int(ck) != cfg.canvas:
        raise SystemExit(
            f"--model was trained at canvas {ck}; pass --canvas {ck}"
        )
    # the student only works on the input distribution it was trained on:
    # normalize+clip constants are part of the model, not free flags
    want_norm = [cfg.norm_low, cfg.norm_high, cfg.norm_intensity_min, cfg.norm_intensity_max]
    want_clip = [cfg.clip_low, cfg.clip_high]
    for key, want in (("norm", want_norm), ("clip", want_clip)):
        got = meta.get(key)
        if got is not None and [float(v) for v in got] != [float(v) for v in want]:
            raise SystemExit(
                f"--model was trained with {key} constants {got}; this run "
                f"uses {want} — the student's input space must match its "
                "training (drop the conflicting flags or retrain)"
            )
    return params


def add_batch_args(parser: argparse.ArgumentParser) -> None:
    d = BatchConfig()
    parser.add_argument(
        "--batch-size",
        type=int,
        default=d.batch_size,
        help="slices per device batch (reference DEFAULT_BATCH_SIZE=25, "
        "main_parallel.cpp:31-33)",
    )
    parser.add_argument("--io-workers", type=int, default=d.io_workers)
    parser.add_argument("--prefetch-depth", type=int, default=d.prefetch_depth)


def add_ingest_args(parser: argparse.ArgumentParser) -> None:
    """The streaming-ingest knobs (ingest/; docs/OPERATIONS.md "Feeding
    the chip"). Both batch drivers feed the device through the ingest
    pipeline, so both take these."""
    d = BatchConfig()
    g = parser.add_argument_group(
        "ingest", "host->HBM streaming pipeline (docs/OPERATIONS.md)"
    )
    g.add_argument(
        "--ingest-depth",
        type=int,
        default=d.ingest_depth,
        help="staging-ring capacity: host batches decoded ahead of the "
        "chip. The backpressure bound — decode blocks when the ring is "
        "full, so host memory for staged batches is capped at roughly "
        "(ingest-depth + decode workers + prefetch-depth) batches",
    )
    g.add_argument(
        "--ingest-decode-workers",
        type=int,
        default=d.ingest_decode_workers,
        help="decode pool size for the ingest pipeline (0 = --io-workers). "
        "The same pool streams result fetch/export back while the next "
        "batch computes",
    )


def add_distributed_args(parser: argparse.ArgumentParser, extra_help: str = "") -> None:
    """The multi-host job flags (drivers that support --distributed)."""
    d = parser.add_argument_group(
        "distributed",
        "multi-host cohort processing: one process per host. " + extra_help,
    )
    d.add_argument(
        "--distributed",
        action="store_true",
        help="join a jax.distributed job (autodetects the coordinator on TPU "
        "pods/SLURM/GKE; pass the explicit flags elsewhere)",
    )
    d.add_argument("--coordinator-address", default=None, metavar="HOST:PORT")
    d.add_argument("--num-processes", type=int, default=None)
    d.add_argument("--process-id", type=int, default=None)


def init_distributed(args: argparse.Namespace) -> tuple[int, int]:
    """Join the cluster per the --distributed flags; (rank, world).

    An explicitly requested multi-process job that joined nothing is a hard
    error — every worker silently processing the whole cohort into the same
    tree is the worst failure mode a cluster launcher can hand back.
    """
    if not getattr(args, "distributed", False):
        return 0, 1
    import sys

    from nm03_capstone_project_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=getattr(args, "coordinator_address", None),
        num_processes=getattr(args, "num_processes", None),
        process_id=getattr(args, "process_id", None),
    )
    info = distributed.process_info()
    rank, world = info["process_index"], info["process_count"]
    want = getattr(args, "num_processes", None)
    if want and want > 1 and world == 1:
        raise RuntimeError(
            f"--distributed --num-processes {want} requested but this process "
            "joined no cluster (world=1); check the coordinator address / "
            "process ids"
        )
    if world == 1:
        print(
            "--distributed: no cluster detected; running single-process",
            file=sys.stderr,
        )
    return rank, world


def resolve_base_path_sync(
    args: argparse.Namespace, rank: int, world: int, tmp_root: Path | None = None
) -> Path:
    """resolve_base_path, with rank 0 generating any synthetic cohort behind
    a barrier so other ranks never list a half-written tree."""
    if world > 1 and args.synthetic > 0:
        from jax.experimental import multihost_utils

        base = None
        if rank == 0:
            base = resolve_base_path(args, tmp_root=tmp_root)
        multihost_utils.sync_global_devices("nm03 synthetic cohort ready")
        if rank != 0:
            base = resolve_base_path(args, tmp_root=tmp_root)
        return base
    return resolve_base_path(args, tmp_root=tmp_root)


def shard_patients(patients: list, rank: int, world: int) -> list:
    """Deterministic round-robin patient shard (discovery sorts the list, so
    every rank computes the same split with no communication)."""
    if world <= 1:
        return patients
    mine = patients[rank::world]
    print(f"process {rank}/{world}: {len(mine)} patients assigned")
    return mine


def allgather_cluster_counts(counts: "dict[str, int]", world: int) -> dict:
    """Allgather each rank's counters; cluster totals + per-process rows.

    The one DCN crossing of a patient-sharded multi-host run (the
    reference's end-of-run accounting, main_parallel.cpp:349). All ranks
    must call this (it is a collective).
    """
    import numpy as np
    from jax.experimental import multihost_utils

    keys = sorted(counts)
    # Voxel-level counters (train.py passes per-slice inter/union sums, up
    # to 65,536 per 256x256 slice) overflow int32 past ~33k slices/rank
    # (ADVICE r2) — and an int64 array does NOT survive the collective,
    # because jax canonicalizes it back to int32 when x64 is off (always,
    # here). Transport as two sub-2^31 halves per counter (good to 2^61)
    # and recombine in int64 on the host.
    vals = np.asarray([counts[k] for k in keys], np.int64)
    if (vals < 0).any():
        raise ValueError(f"counters must be non-negative, got {counts}")
    halves = np.stack([vals >> 30, vals & ((1 << 30) - 1)]).astype(np.int32)
    gathered = np.asarray(
        multihost_utils.process_allgather(halves), np.int64
    ).reshape(world, 2, len(keys))
    per_rank = (gathered[:, 0] << 30) | gathered[:, 1]
    out = {k: int(per_rank[:, i].sum()) for i, k in enumerate(keys)}
    out["per_process"] = {
        str(r): {k: int(per_rank[r, i]) for i, k in enumerate(keys)}
        for r in range(world)
    }
    return out


def warn_resume_topology(out_root: Path, process_count: int, warn) -> None:
    """Warn when --resume runs under a different process count than the
    manifests on disk: the round-robin shard reassigns patients to ranks
    whose manifests never saw them, so done work is redone (correctness is
    unaffected)."""
    prior_ranks = len(list(Path(out_root).glob("manifest.rank*.json")))
    prior_single = (Path(out_root) / "manifest.json").exists()
    if process_count > 1 and (prior_single or prior_ranks not in (0, process_count)):
        warn(
            "resuming with %d processes but prior manifests suggest a "
            "different topology (%s) — patients may be reprocessed",
            process_count,
            f"{prior_ranks} rank manifests" if prior_ranks else "single-process run",
        )
    elif process_count == 1 and prior_ranks:
        warn(
            "resuming single-process over a %d-rank output tree — prior rank "
            "manifests are ignored and patients will be reprocessed",
            prior_ranks,
        )


def apply_native_flag(args: argparse.Namespace) -> None:
    """--no-native disables the whole C++ layer (decode AND JPEG encode)."""
    if getattr(args, "no_native", False):
        os.environ["NM03_NO_NATIVE"] = "1"


def apply_device_env(device: str) -> None:
    """Pin the JAX platform before jax is imported.

    'cpu' forces the host backend (and skips any accelerator plugin handshake
    via PALLAS_AXON_POOL_IPS removal on this image); 'tpu'/'auto' leave the
    environment's default backend in charge.
    """
    if device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)


def enable_compile_cache() -> None:
    """Point jax at a persistent compilation cache — OPT-IN via
    NM03_COMPILE_CACHE=<dir>.

    The fused pipeline costs seconds to compile, so a cache warm-starts
    repeat CLI runs — but it is opt-in because both accelerator and CPU
    backends misbehaved with it on this infrastructure: asking the tunneled
    remote-TPU backend to serialize executables wedged the tunnel (first jit
    compile never returned, hung claim blocked the chip), and XLA:CPU AOT
    cache entries reloaded under a different detected feature set warn of
    possible SIGILL. Set NM03_COMPILE_CACHE=<dir> to enable deliberately.
    """
    cache = os.environ.get("NM03_COMPILE_CACHE", "")
    if not cache or cache == "0":
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def resolve_base_path(args: argparse.Namespace, tmp_root: Path | None = None) -> Path:
    """Cohort root: --synthetic generates one; else --base-path or env."""
    if args.synthetic > 0:
        from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

        # key the directory by its parameters so changing --synthetic /
        # --synthetic-slices / --canvas regenerates instead of reusing a
        # stale cohort. Slices are sized to fit the canvas: the generator's
        # 256px default under a smaller --canvas would fail the size guard
        # for every slice, a silently empty run.
        size = min(256, int(getattr(args, "canvas", 256)))
        name = f"synthetic-cohort-{args.synthetic}x{args.synthetic_slices}-{size}"
        root = (tmp_root or Path(args.output)) / name
        if not (root.exists() and any(root.iterdir())):
            write_synthetic_cohort(
                root,
                n_patients=args.synthetic,
                n_slices=args.synthetic_slices,
                height=size,
                width=size,
            )
        return root
    if args.base_path:
        return Path(args.base_path)
    env = os.environ.get(DATA_PATH_ENV)
    if env:
        return Path(env) / DEFAULT_COHORT_SUBPATH
    raise SystemExit(
        "no data: pass --base-path, set $NM03_DATA_PATH, or use --synthetic N"
    )
