"""Multi-host initialization: the distributed communication backend.

The reference is strictly single-process shared memory + OpenMP (SURVEY.md
section 2.3: no NCCL/MPI/Gloo anywhere). The TPU-native equivalent of a
multi-node backend is ``jax.distributed`` — one Python process per host,
all chips joined into one global device set, XLA collectives riding ICI
within a slice and DCN across hosts. Nothing else in this framework changes
for multi-host: the same ``Mesh``-based code runs over
``jax.devices()`` whether that is 1 chip or a pod slice; only the mesh
construction distinguishes local from global devices.

Usage on each host of a multi-host job::

    from nm03_capstone_project_tpu.parallel import distributed
    distributed.initialize()          # no-op single-host, env-driven multi-host
    mesh = distributed.global_mesh(("data",))
    # ... identical pjit/shard_map code as single-host ...

On TPU pods the coordinator address / process count / process id come from
the TPU runtime and ``initialize()`` needs no arguments; elsewhere they can
be passed explicitly or via JAX's standard environment variables.
"""

from __future__ import annotations

from typing import Optional, Sequence

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join this process into the multi-host job; returns True if it did.

    Single-process runs (num_processes absent or 1, no coordinator found)
    are a no-op returning False — so drivers can call this unconditionally.
    Safe to call twice (second call is a no-op).
    """
    global _initialized
    if _initialized:
        return True
    import jax

    try:
        # With no arguments jax runs its cluster autodetection (TPU-pod
        # metadata, SLURM, GKE, JAX_COORDINATOR_ADDRESS env...); pre-guarding
        # on env vars here would defeat it. On a plain single host detection
        # finds nothing and raises — that is the no-op path.
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        if coordinator_address is not None or num_processes is not None:
            raise  # an explicit multi-host request must not fail silently
        msg = str(e).lower()
        if "must be called before" in msg:
            # backends already initialized (e.g. a long-lived session calling
            # this late) — multi-host init is impossible now; warn, don't die
            from nm03_capstone_project_tpu.utils.reporter import get_logger

            get_logger("distributed").warning(
                "jax backends already initialized; distributed init skipped"
            )
            return False
        # "nothing to join": jax complains about the undefined coordinator /
        # process count. Anything else (unreachable coordinator, barrier
        # timeout, mismatched counts) is a DETECTED cluster failing to join —
        # silently degrading to single-host would run duplicate workloads.
        if "coordinator_address" in msg or "num_processes" in msg or "process_id" in msg:
            return False
        raise
    _initialized = True
    return True


def global_mesh(axis_names: Sequence[str] = ("data",), axis_sizes=None):
    """Mesh over EVERY device in the job (all hosts), not just local ones.

    Mirrors :func:`nm03_capstone_project_tpu.parallel.make_mesh` but over the
    global device set, laid out so the trailing mesh axis varies fastest
    within a host — keeping intra-host neighbors on ICI and crossing DCN only
    along the leading (typically ``data``) axis.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()  # global across processes after initialize()
    n = len(devices)
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    if int(np.prod(axis_sizes)) != n:
        raise ValueError(f"axis_sizes {axis_sizes} != global device count {n}")
    return Mesh(np.asarray(devices).reshape(axis_sizes), tuple(axis_names))


def process_info() -> dict:
    """{'process_index', 'process_count', 'local_devices', 'global_devices'}."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
