"""Multi-host initialization: the distributed communication backend.

The reference is strictly single-process shared memory + OpenMP (SURVEY.md
section 2.3: no NCCL/MPI/Gloo anywhere). The TPU-native equivalent of a
multi-node backend is ``jax.distributed`` — one Python process per host,
all chips joined into one global device set, XLA collectives riding ICI
within a slice and DCN across hosts. Nothing else in this framework changes
for multi-host: the same ``Mesh``-based code runs over
``jax.devices()`` whether that is 1 chip or a pod slice; only the mesh
construction distinguishes local from global devices.

Usage on each host of a multi-host job::

    from nm03_capstone_project_tpu.parallel import distributed
    distributed.initialize()          # no-op single-host, env-driven multi-host
    mesh = distributed.global_mesh(("data",))
    # ... identical pjit/shard_map code as single-host ...

On TPU pods the coordinator address / process count / process id come from
the TPU runtime and ``initialize()`` needs no arguments; elsewhere they can
be passed explicitly or via JAX's standard environment variables.
"""

from __future__ import annotations

from typing import Optional, Sequence

_initialized = False

# Environment signals that a multi-process cluster surrounds this process.
# Fast path only: jax's own autodetection covers MORE than these (notably
# GceTpuCluster, which queries the GCE metadata server with no env var at
# all), so a miss here must still fall through to jax's detectors — it must
# NOT short-circuit to "single host".
_CLUSTER_ENV_SIGNALS = (
    "JAX_COORDINATOR_ADDRESS",  # jax's own override
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",  # multi-slice TPU
    "TPU_WORKER_HOSTNAMES",  # GKE TPU-pod env
    "SLURM_STEP_NODELIST",  # SLURM multi-node
    "OMPI_MCA_orte_hnp_uri",  # Open MPI
)


def _cluster_detected() -> Optional[bool]:
    """Structural cluster detection; None = could not determine.

    First the env fast path, then jax's own cluster framework (the same
    detectors ``jax.distributed.initialize()`` consults — including the GCE
    TPU-pod metadata probe that involves no env var). The private-API access
    is fenced: if a future jax moves it, we return None and the caller falls
    back to calling initialize() and classifying its outcome.
    """
    import os

    if any(os.environ.get(k) for k in _CLUSTER_ENV_SIGNALS):
        return True
    try:
        from jax._src.clusters.cluster import ClusterEnv

        env_present = any(
            cluster.is_env_present() for cluster in ClusterEnv._cluster_types
        )
        if not env_present:
            return False
        # a detector fired; only trust "multi-process cluster" if it can
        # actually name more than one process
        for cluster in ClusterEnv._cluster_types:
            if cluster.is_env_present():
                try:
                    return (cluster.get_process_count() or 1) > 1
                except Exception:  # noqa: BLE001 — detector quirk
                    return True  # detected but unsized: let jax try to join
        return False
    except Exception:  # noqa: BLE001 — private API moved; undetermined
        return None


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join this process into the multi-host job; returns True if it did.

    Single-process runs (no explicit arguments and no cluster environment
    detected) are a no-op returning False — so drivers can call this
    unconditionally. Safe to call twice (second call is a no-op).
    """
    global _initialized
    if _initialized:
        return True
    import jax

    from nm03_capstone_project_tpu.compilehub import distributed_is_initialized

    if distributed_is_initialized():  # someone else already joined us
        _initialized = True
        return True
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    detected = None if explicit else _cluster_detected()
    if not explicit and detected is False:
        # structurally nothing to join: no arguments, no cluster env signal,
        # and jax's own detectors (incl. the GCE TPU-pod metadata probe,
        # which uses no env var) found no multi-process cluster
        return False

    try:
        # joining a real multi-process job: make sure the CPU backend can
        # actually run cross-process collectives on this jaxlib (gloo; a
        # no-op where jax auto-selects or an operator already chose)
        from nm03_capstone_project_tpu.compilehub import (
            ensure_cpu_multiprocess_collectives,
        )

        ensure_cpu_multiprocess_collectives()
        # jax runs its cluster autodetection (TPU-pod metadata, SLURM, GKE,
        # JAX_COORDINATOR_ADDRESS env...) for any argument left as None.
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as e:
        # Explicit requests, and any failure of a DETECTED cluster to join
        # (unreachable coordinator, barrier timeout, mismatched counts),
        # must raise: silently degrading to single-host would run duplicate
        # work. The message checks below are a FALLBACK for the detected /
        # undetermined cases only (jax rewording them degrades to raising —
        # loud, never silently wrong).
        msg = str(e).lower()
        if not explicit and "must be called before" in msg:
            # backends already created (a long-lived session calling this
            # late) — multi-host init is impossible now; warn, don't die
            from nm03_capstone_project_tpu.utils.reporter import get_logger

            get_logger("distributed").warning(
                "jax backends already initialized; distributed init skipped"
            )
            return False
        if detected is None and (
            "coordinator_address" in msg
            or "num_processes" in msg
            or "process_id" in msg
        ):
            # detection was undetermined and jax says it has nothing to
            # join (undefined coordinator/process count) — single-host no-op
            return False
        raise
    _initialized = True
    return True


def global_mesh(axis_names: Sequence[str] = ("data",), axis_sizes=None):
    """Mesh over EVERY device in the job (all hosts), not just local ones.

    Delegates to :func:`nm03_capstone_project_tpu.parallel.make_mesh`, whose
    default device pool is already ``jax.devices()`` — the global set after
    :func:`initialize` — laid out so the trailing mesh axis varies fastest
    within a host: intra-host neighbors stay on ICI and only the leading
    (typically ``data``) axis crosses DCN.
    """
    from nm03_capstone_project_tpu.parallel.mesh import make_mesh

    return make_mesh(axis_names=axis_names, axis_sizes=axis_sizes)


def process_info() -> dict:
    """{'process_index', 'process_count', 'local_devices', 'global_devices'}."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
