"""Multi-device execution: meshes, data parallelism, z-sharded volumes.

The reference's entire parallel story is one OpenMP pragma over a slice batch
(src/parallel/main_parallel.cpp:336) plus the mutex discipline around its
non-thread-safe export path. Here parallelism is declarative: a
`jax.sharding.Mesh` with named axes, `NamedSharding` annotations, and XLA
inserting the collectives —

* :mod:`.mesh` — mesh construction, batch shardings, batch padding.
* :mod:`.dp`   — slice/patient data parallelism (zero-communication SPMD).
* :mod:`.zshard` — sequence-parallel analog: volumes sharded along z with
  ring halo exchange (`ppermute`) per growth step and `psum` convergence.
* :mod:`.distributed` — multi-host backend: `jax.distributed` init + a
  global mesh over every host's chips (ICI within a slice, DCN across).
"""

from nm03_capstone_project_tpu.parallel import distributed  # noqa: F401

from nm03_capstone_project_tpu.parallel.dp import process_batch_sharded  # noqa: F401
from nm03_capstone_project_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    pad_to_multiple,
    replicated,
)
from nm03_capstone_project_tpu.parallel.zshard import (  # noqa: F401
    process_volume_batch_zsharded,
    process_volume_zsharded,
)
