"""Device-mesh construction and sharding helpers.

The reference's parallelism is 16 OpenMP threads over a ≤25-slice batch on one
shared-memory node (src/parallel/main_parallel.cpp:336,401). The TPU-native
replacement is a `jax.sharding.Mesh` over chips with named axes:

* ``data`` — batch/data parallelism: slices (and whole patients) spread
  across devices, no cross-device communication inside the pipeline.
* ``z``   — volume sharding: a (D, H, W) series split along z, stencils and
  region growing communicating one halo plane per step over ICI
  (see :mod:`.zshard`).

A mesh is cheap to build and purely declarative; XLA inserts the collectives.
On a single host the same code runs over `xla_force_host_platform_device_count`
virtual devices, which is how the test suite exercises every collective path
without TPU hardware (SURVEY.md section 7 step 8).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Sequence[str] = ("data",),
    axis_sizes: Optional[Sequence[int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh over the first ``n_devices`` of ``devices``.

    Args:
      n_devices: number of devices to use (default: all in ``devices``).
      axis_names: mesh axis names, e.g. ("data",) or ("data", "z").
      axis_sizes: sizes per axis; must multiply to n_devices. Defaults to all
        devices on the first axis.
      devices: the device pool (default ``jax.devices()``; pass
        ``jax.local_devices()`` for a per-process mesh in a multi-host job).
    """
    devices = list(jax.devices() if devices is None else devices)
    n = len(devices) if n_devices is None else n_devices
    if n > len(devices):
        raise ValueError(f"requested {n} devices, only {len(devices)} available")
    if axis_sizes is None:
        axis_sizes = [n] + [1] * (len(axis_names) - 1)
    if int(np.prod(axis_sizes)) != n:
        raise ValueError(f"axis_sizes {axis_sizes} != n_devices {n}")
    dev_array = np.asarray(devices[:n]).reshape(axis_sizes)
    return Mesh(dev_array, tuple(axis_names))


def batch_sharding(mesh: Mesh, ndim: int, axis: str = "data") -> NamedSharding:
    """Sharding that splits axis 0 of an ndim-array across ``axis``."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(
    pixels: np.ndarray, dims: np.ndarray, multiple: int
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Pad a (B, H, W) host batch along B so it divides the mesh evenly.

    Filler slices get dims (1, 1): they fail the reference's min-dimension
    guard (main_sequential.cpp:189-192) by construction, so callers that
    count successes never see them, and their valid-region is a single pixel
    so the padded lanes converge immediately in the region-growing fixpoint.

    Returns (pixels, dims, real_count).
    """
    b = pixels.shape[0]
    rem = (-b) % multiple
    if rem == 0:
        return pixels, dims, b
    pad_px = np.zeros((rem,) + pixels.shape[1:], pixels.dtype)
    pad_dims = np.ones((rem, 2), dims.dtype)
    return (
        np.concatenate([pixels, pad_px]),
        np.concatenate([dims, pad_dims]),
        b,
    )
