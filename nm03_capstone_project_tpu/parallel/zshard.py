"""Z-sharded volumetric pipeline: shard_map + halo exchange over ICI.

The framework's sequence-parallel analog (task: "ring attention or all-to-all
sequence/context parallelism for long sequences"): a long (D, H, W) series is
split along z across the mesh's ``z`` axis, and the 3D stencil ops communicate
exactly one boundary plane per growth step with `jax.lax.ppermute` — a ring
halo exchange that rides ICI, never the host.

Decomposition per shard (depth D/n):

* 2D per-slice preprocessing — embarrassingly parallel, zero communication
  (each slice's normalize/clip/median/sharpen never crosses z).
* 3D seeded region growing — each fixpoint step dilates the local block with
  a 1-plane halo received from both z-neighbors (`ppermute` shifts; edge
  shards receive zeros = the constant-pad boundary of the unsharded op), and
  the convergence test is a `psum` of local popcounts, so every shard exits
  the `while_loop` on the same iteration.
* final 3D dilation — one more halo exchange.

This is bit-identical to :func:`..pipeline.volume_pipeline.process_volume` on
one device (the property tests assert it), the way the reference's
parallel/sequential drivers are only *believed* identical by diffing output
directories (SURVEY.md section 4).
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from nm03_capstone_project_tpu.config import DEFAULT_CONFIG, PipelineConfig
from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.ops.elementwise import cast_uint8
from nm03_capstone_project_tpu.ops.seeds import seed_mask
from nm03_capstone_project_tpu.ops.volume import dilate3d
from nm03_capstone_project_tpu.pipeline.slice_pipeline import preprocess

AXIS = "z"


def _halo_pad(r: jax.Array, n_shards: int, halo: int = 1) -> jax.Array:
    """Pad a local (d, H, W) block with ``halo`` planes from each z-neighbor.

    Shard i receives the last ``halo`` planes of shard i-1 below and the
    first ``halo`` planes of shard i+1 above; ring ends receive zeros
    (ppermute's semantics for devices with no source), which reproduces the
    constant background padding of the unsharded 3D ops. Correct for a single
    stencil of z-radius ``halo`` as long as ``halo <= d_local`` (enforced at
    dispatch in :func:`process_volume_zsharded`) — a deeper stencil would
    need planes from the neighbor's neighbor.
    """
    from_prev = jax.lax.ppermute(
        r[-halo:], AXIS, [(i, i + 1) for i in range(n_shards - 1)]
    )
    from_next = jax.lax.ppermute(
        r[:halo], AXIS, [(i + 1, i) for i in range(n_shards - 1)]
    )
    return jnp.concatenate([from_prev, r, from_next], axis=0)


def _region_grow_local(
    pre: jax.Array,
    seeds: jax.Array,
    band_mask: jax.Array,
    n_shards: int,
    block_iters: int,
    max_iters: int,
) -> jax.Array:
    """Distributed fixpoint flood fill on one shard's (d, H, W) block."""

    def grow_block(region):
        def step(_, r):
            padded = _halo_pad(r, n_shards)
            return dilate3d(padded, 3, "cross")[1:-1] & band_mask

        return jax.lax.fori_loop(0, block_iters, step, region)

    def global_count(region):
        return jax.lax.psum(region.sum(), AXIS)

    # the state carries the CURRENT region's count so each convergence
    # check costs one psum, not two (cond used to recompute the popcount
    # + collective the body had just evaluated)
    def cond(state):
        _, prev_count, count, iters = state
        return (count != prev_count) & (iters < max_iters)

    def body(state):
        region, _, count, iters = state
        new_region = grow_block(region)
        return new_region, count, global_count(new_region), iters + block_iters

    region0 = seeds & band_mask
    region1 = grow_block(region0)
    region, _, _, _ = jax.lax.while_loop(
        cond,
        body,
        (
            region1,
            global_count(region0),
            global_count(region1),
            jnp.int32(block_iters),
        ),
    )
    return region


@functools.lru_cache(maxsize=8)
def _compiled_zsharded(mesh: Mesh, cfg: PipelineConfig):
    n_shards = mesh.shape[AXIS]
    spec_v = P(AXIS, None, None)

    def run(vol_local: jax.Array, dims: jax.Array) -> Dict[str, jax.Array]:
        d_local = vol_local.shape[0]
        canvas_hw = vol_local.shape[-2:]

        pre = jax.vmap(lambda p: preprocess(p, dims, cfg))(vol_local)

        seeds2d = seed_mask(dims, canvas_hw)
        valid2d = valid_mask(dims, canvas_hw)
        seeds = jnp.broadcast_to(seeds2d, (d_local,) + seeds2d.shape)
        valid = jnp.broadcast_to(valid2d, (d_local,) + valid2d.shape)

        band = (pre >= cfg.grow_low) & (pre <= cfg.grow_high) & valid
        region = _region_grow_local(
            pre, seeds, band, n_shards, cfg.grow_block_iters, cfg.grow_max_iters
        )

        seg = cast_uint8(region)
        # the final dilation has z-radius morph_size//2: exchange that many
        # halo planes (VERDICT r1 weak #6 — one plane is silently wrong for
        # morph_size >= 5 at shard boundaries). morph_size=1 has radius 0:
        # no exchange, and no [0:-0] slicing (that would be empty).
        halo = cfg.morph_size // 2
        if halo:
            mask = dilate3d(_halo_pad(seg, n_shards, halo), cfg.morph_size)[
                halo:-halo
            ]
        else:
            mask = dilate3d(seg, cfg.morph_size)
        mask = mask * valid.astype(mask.dtype)
        return {"original": vol_local, "mask": mask}

    sharded = jax.shard_map(
        run,
        mesh=mesh,
        in_specs=(spec_v, P()),
        out_specs={"original": spec_v, "mask": spec_v},
        check_vma=False,
    )
    return jax.jit(sharded)


def process_volume_zsharded(
    volume: jax.Array,
    dims: jax.Array,
    cfg: PipelineConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
) -> Dict[str, jax.Array]:
    """Run the volumetric pipeline with z sharded across the mesh.

    Args:
      volume: (D, H, W) raw canvas volume; D must divide the mesh's ``z``
        axis size evenly.
      dims: int32 (2,) true in-plane (height, width).
      mesh: mesh with a ``z`` axis (default: all devices on one ``z`` axis).
    """
    if mesh is None:
        from nm03_capstone_project_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(axis_names=(AXIS,))
    if volume.shape[0] % mesh.shape[AXIS] != 0:
        raise ValueError(
            f"depth {volume.shape[0]} not divisible by z-axis size "
            f"{mesh.shape[AXIS]}; pad the stack first"
        )
    d_local = volume.shape[0] // mesh.shape[AXIS]
    halo = cfg.morph_size // 2
    if d_local < halo:
        raise ValueError(
            f"local shard depth {d_local} < dilation z-radius {halo} "
            f"(morph_size={cfg.morph_size}): the single-neighbor halo "
            "exchange would be incomplete; use fewer z-shards or a deeper "
            "volume"
        )
    return _compiled_zsharded(mesh, cfg)(volume, dims)
