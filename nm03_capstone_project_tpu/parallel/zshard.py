"""Z-sharded volumetric pipeline: shard_map + halo exchange over ICI.

The framework's sequence-parallel analog (task: "ring attention or all-to-all
sequence/context parallelism for long sequences"): a long (D, H, W) series is
split along z across the mesh's ``z`` axis, and the 3D stencil ops communicate
exactly one boundary plane per growth step with `jax.lax.ppermute` — a ring
halo exchange that rides ICI, never the host.

Decomposition per shard (depth D/n):

* 2D per-slice preprocessing — embarrassingly parallel, zero communication
  (each slice's normalize/clip/median/sharpen never crosses z).
* 3D seeded region growing — each fixpoint step dilates the local block with
  a 1-plane halo received from both z-neighbors (`ppermute` shifts; edge
  shards receive zeros = the constant-pad boundary of the unsharded op), and
  the convergence test is a `psum` of local popcounts, so every shard exits
  the `while_loop` on the same iteration.
* final 3D dilation — one more halo exchange.

This is bit-identical to :func:`..pipeline.volume_pipeline.process_volume` on
one device (the property tests assert it), the way the reference's
parallel/sequential drivers are only *believed* identical by diffing output
directories (SURVEY.md section 4).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from nm03_capstone_project_tpu.compilehub import (
    CompileSpec,
    get_hub,
    hub_jit,
    shard_map,
)
from nm03_capstone_project_tpu.config import DEFAULT_CONFIG, PipelineConfig
from nm03_capstone_project_tpu.core.image import valid_mask
from nm03_capstone_project_tpu.ops.elementwise import cast_uint8
from nm03_capstone_project_tpu.ops.seeds import seed_mask
from nm03_capstone_project_tpu.ops.volume import dilate3d
from nm03_capstone_project_tpu.pipeline.slice_pipeline import preprocess

AXIS = "z"


def _halo_pad(r: jax.Array, n_shards: int, halo: int = 1) -> jax.Array:
    """Pad a local (d, H, W) block with ``halo`` planes from each z-neighbor.

    Shard i receives the last ``halo`` planes of shard i-1 below and the
    first ``halo`` planes of shard i+1 above; ring ends receive zeros
    (ppermute's semantics for devices with no source), which reproduces the
    constant background padding of the unsharded 3D ops. Correct for a single
    stencil of z-radius ``halo`` as long as ``halo <= d_local`` (enforced at
    dispatch in :func:`process_volume_zsharded`) — a deeper stencil would
    need planes from the neighbor's neighbor.
    """
    from_prev = jax.lax.ppermute(
        r[-halo:], AXIS, [(i, i + 1) for i in range(n_shards - 1)]
    )
    from_next = jax.lax.ppermute(
        r[:halo], AXIS, [(i + 1, i) for i in range(n_shards - 1)]
    )
    return jnp.concatenate([from_prev, r, from_next], axis=0)


def _region_grow_local(
    pre: jax.Array,
    seeds: jax.Array,
    band_mask: jax.Array,
    n_shards: int,
    block_iters: int,
    max_iters: int,
) -> tuple[jax.Array, jax.Array]:
    """Distributed fixpoint flood fill on one shard's (d, H, W) block.

    Returns ``(region, converged)``; ``converged`` is a replicated scalar
    bool, False when ``max_iters`` truncated the global fixpoint (VERDICT r4
    item 4).
    """

    def grow_block(region):
        def step(_, r):
            padded = _halo_pad(r, n_shards)
            return dilate3d(padded, 3, "cross")[1:-1] & band_mask

        return jax.lax.fori_loop(0, block_iters, step, region)

    def global_count(region):
        return jax.lax.psum(region.sum(), AXIS)

    # the state carries the CURRENT region's count so each convergence
    # check costs one psum, not two (cond used to recompute the popcount
    # + collective the body had just evaluated)
    def cond(state):
        _, prev_count, count, iters = state
        return (count != prev_count) & (iters < max_iters)

    def body(state):
        region, _, count, iters = state
        new_region = grow_block(region)
        return new_region, count, global_count(new_region), iters + block_iters

    region0 = seeds & band_mask
    region1 = grow_block(region0)
    region, prev_count, count, _ = jax.lax.while_loop(
        cond,
        body,
        (
            region1,
            global_count(region0),
            global_count(region1),
            jnp.int32(block_iters),
        ),
    )
    # popcount stable at exit == converged (cap-hit mid-growth otherwise);
    # both counts are psums, so the flag is replicated across shards
    return region, count == prev_count


def _pre_and_band(vol_local: jax.Array, dims: jax.Array, cfg: PipelineConfig):
    """Pure per-volume front half: preprocess + seed/valid/band planes.

    Shared verbatim by the single-volume path and (under vmap) the
    ('data', 'z') batched path — no collectives, so it batches freely.
    """
    d_local = vol_local.shape[0]
    canvas_hw = vol_local.shape[-2:]
    pre = jax.vmap(lambda p: preprocess(p, dims, cfg))(vol_local)
    seeds2d = seed_mask(dims, canvas_hw)
    valid2d = valid_mask(dims, canvas_hw)
    seeds = jnp.broadcast_to(seeds2d, (d_local,) + seeds2d.shape)
    valid = jnp.broadcast_to(valid2d, (d_local,) + valid2d.shape)
    band = (pre >= cfg.grow_low) & (pre <= cfg.grow_high) & valid
    return pre, seeds, valid, band


def _post_mask(
    region: jax.Array, valid: jax.Array, cfg: PipelineConfig, n_shards: int
) -> jax.Array:
    """Per-volume back half: cast + halo-exchanged final dilation + re-mask.

    The final dilation has z-radius morph_size//2: exchange that many halo
    planes (VERDICT r1 weak #6 — one plane is silently wrong for
    morph_size >= 5 at shard boundaries). morph_size=1 has radius 0: no
    exchange, and no [0:-0] slicing (that would be empty). One ppermute
    pair regardless of data, so it batches cleanly under vmap too.
    """
    seg = cast_uint8(region)
    halo = cfg.morph_size // 2
    if halo:
        mask = dilate3d(_halo_pad(seg, n_shards, halo), cfg.morph_size)[
            halo:-halo
        ]
    else:
        mask = dilate3d(seg, cfg.morph_size)
    return mask * valid.astype(mask.dtype)


def zshard_volume_callable(mesh: Mesh, cfg: PipelineConfig):
    """The shard_map'd z-sharded volume program, un-jitted.

    The single shared definition of the halo-exchanged region-growing
    program: :func:`_compiled_zsharded` wraps it in a deferred ``hub_jit``
    (the batch driver's path) and the serving volume gang AOT-compiles it
    per depth bucket through :func:`compilehub.programs.serve_volume`
    (ISSUE 15) — one program text, so the served mask is bit-identical to
    a directly-driven ``nm03-volume --z-shard`` run by construction.
    """
    n_shards = mesh.shape[AXIS]
    spec_v = P(AXIS, None, None)

    def run(vol_local: jax.Array, dims: jax.Array) -> Dict[str, jax.Array]:
        pre, seeds, valid, band = _pre_and_band(vol_local, dims, cfg)
        region, converged = _region_grow_local(
            pre, seeds, band, n_shards,
            cfg.grow_block_iters, cfg.grow_max_iters,
        )
        return {
            "original": vol_local,
            "mask": _post_mask(region, valid, cfg, n_shards),
            "grow_converged": converged,
        }

    return shard_map(
        run,
        mesh=mesh,
        in_specs=(spec_v, P()),
        out_specs={
            "original": spec_v,
            "mask": spec_v,
            "grow_converged": P(),
        },
        check_vma=False,
    )


def _compiled_zsharded(mesh: Mesh, cfg: PipelineConfig):
    """The z-sharded volume program, compiled and cached by the hub.

    ``shard_map`` comes from the compilehub compat shim — the seed's
    direct ``jax.shard_map`` reference is exactly the version drift that
    failed these paths on jaxlibs shipping only the experimental entry
    point (ISSUE 6 satellite; pinned by tests/test_parallel.py).
    """

    def build(spec: CompileSpec):
        return hub_jit(zshard_volume_callable(spec.mesh, spec.cfg))

    return get_hub().get(
        CompileSpec(name="zshard_volume", cfg=cfg, mesh=mesh), build
    )


def _region_grow_local_batch(
    pre: jax.Array,
    seeds: jax.Array,
    band: jax.Array,
    n_shards: int,
    block_iters: int,
    max_iters: int,
) -> tuple[jax.Array, jax.Array]:
    """Fixpoint flood fill over a LOCAL BATCH of (b, d, H, W) z-shard blocks.

    Not vmap-of-the-single-volume-loop: a while_loop containing collectives
    must run the SAME trip count on every device, but different volumes
    converge at different counts, so lanes on different 'data' shards would
    execute different numbers of z-ring ppermutes/psums — mismatched
    collectives that abort (or deadlock) the runtime. Instead ONE loop
    carries the whole local batch and continues while ANY volume on ANY
    'data' shard is still growing (the continue bit is psummed over 'data');
    extra iterations on already-converged volumes are fixpoint no-ops, and
    each volume's ``converged`` flag is its own popcount stability, not the
    loop exit reason.
    """

    def grow_block(region):
        def step(_, r):
            # per-volume halo exchange + dilate: uniform collective count
            # across lanes (one ppermute pair per step regardless of data)
            return jax.vmap(
                lambda rr, bb: dilate3d(_halo_pad(rr, n_shards), 3, "cross")[
                    1:-1
                ]
                & bb
            )(r, band)

        return jax.lax.fori_loop(0, block_iters, step, region)

    def counts(region):
        # (b,) global per-volume popcount: sum the local block, psum over z
        return jax.lax.psum(region.sum(axis=(1, 2, 3)), AXIS)

    def go_bit(prev, cur):
        local_any = jnp.any(cur != prev).astype(jnp.int32)
        return jax.lax.psum(local_any, "data") > 0

    def cond(state):
        _, _, _, go, it = state
        return go & (it < max_iters)

    def body(state):
        region, _, cur, _, it = state
        new = grow_block(region)
        newc = counts(new)
        return new, cur, newc, go_bit(cur, newc), it + block_iters

    region0 = seeds & band
    region1 = grow_block(region0)
    c0, c1 = counts(region0), counts(region1)
    region, prev, cur, _, _ = jax.lax.while_loop(
        cond, body, (region1, c0, c1, go_bit(c0, c1), jnp.int32(block_iters))
    )
    return region, cur == prev


def _compiled_batch_zsharded(mesh: Mesh, cfg: PipelineConfig):
    """Batched twin over a ('data', 'z') 2D mesh: a COHORT of long series at
    once — volumes sharded over 'data', each volume's planes over 'z'. The
    halo ppermutes ride the 'z' rings only; the 'data' axis communicates
    exactly one scalar per convergence check (the loop-uniformity bit, see
    :func:`_region_grow_local_batch`), which is exactly the layout a 2D
    torus wants. Compiled and cached through the hub like every other
    mesh program."""

    def build(spec: CompileSpec):
        n_shards = spec.mesh.shape[AXIS]
        spec_v = P("data", AXIS, None, None)
        cfg = spec.cfg

        def run(vol_local: jax.Array, dims_local: jax.Array) -> Dict[str, jax.Array]:
            # vol_local: (b_local, d_local, H, W). The pure front/back halves
            # are the single-volume helpers under vmap; only the growing loop
            # is batch-aware (see _region_grow_local_batch for why it cannot
            # simply be vmapped).
            pre, seeds, valid, band = jax.vmap(
                lambda v, d: _pre_and_band(v, d, cfg)
            )(vol_local, dims_local)
            region, converged = _region_grow_local_batch(
                pre, seeds, band, n_shards,
                cfg.grow_block_iters, cfg.grow_max_iters,
            )
            mask = jax.vmap(lambda r, v: _post_mask(r, v, cfg, n_shards))(
                region, valid
            )
            return {
                "original": vol_local,
                "mask": mask,
                "grow_converged": converged,
            }

        sharded = shard_map(
            run,
            mesh=spec.mesh,
            in_specs=(spec_v, P("data", None)),
            out_specs={
                "original": spec_v,
                "mask": spec_v,
                "grow_converged": P("data"),
            },
            check_vma=False,
        )
        return hub_jit(sharded)

    return get_hub().get(
        CompileSpec(name="zshard_volume_batch", cfg=cfg, mesh=mesh), build
    )


def process_volume_zsharded(
    volume: jax.Array,
    dims: jax.Array,
    cfg: PipelineConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
) -> Dict[str, jax.Array]:
    """Run the volumetric pipeline with z sharded across the mesh.

    Args:
      volume: (D, H, W) raw canvas volume; D must divide the mesh's ``z``
        axis size evenly.
      dims: int32 (2,) true in-plane (height, width).
      mesh: mesh with a ``z`` axis (default: all devices on one ``z`` axis).
    """
    if mesh is None:
        from nm03_capstone_project_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(axis_names=(AXIS,))
    if volume.shape[0] % mesh.shape[AXIS] != 0:
        raise ValueError(
            f"depth {volume.shape[0]} not divisible by z-axis size "
            f"{mesh.shape[AXIS]}; pad the stack first"
        )
    d_local = volume.shape[0] // mesh.shape[AXIS]
    halo = cfg.morph_size // 2
    if d_local < halo:
        raise ValueError(
            f"local shard depth {d_local} < dilation z-radius {halo} "
            f"(morph_size={cfg.morph_size}): the single-neighbor halo "
            "exchange would be incomplete; use fewer z-shards or a deeper "
            "volume"
        )
    return _compiled_zsharded(mesh, cfg)(volume, dims)

def process_volume_batch_zsharded(
    volumes: jax.Array,
    dims: jax.Array,
    cfg: PipelineConfig = DEFAULT_CONFIG,
    mesh: Mesh | None = None,
) -> Dict[str, jax.Array]:
    """Run a (B, D, H, W) cohort of volumes over a ('data', 'z') 2D mesh.

    The combined form of the two parallel axes (SURVEY.md section 2.3): B
    volumes sharded over 'data' (independent, zero communication) while each
    volume's D planes shard over 'z' (ppermute halo exchange + psum
    convergence). The 'data'-axis size must divide B and the 'z'-axis size
    must divide D.

    Returns {'original', 'mask', 'grow_converged'}; ``grow_converged`` is
    (B,) — per-volume, since each volume's fixpoint is independent.
    """
    if mesh is None:
        from nm03_capstone_project_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(axis_names=("data", AXIS))
    if volumes.ndim != 4:
        raise ValueError(f"expected (B, D, H, W) volumes, got {volumes.shape}")
    if volumes.shape[0] % mesh.shape["data"] != 0:
        raise ValueError(
            f"batch {volumes.shape[0]} not divisible by data-axis size "
            f"{mesh.shape['data']}; pad the cohort first"
        )
    if volumes.shape[1] % mesh.shape[AXIS] != 0:
        raise ValueError(
            f"depth {volumes.shape[1]} not divisible by z-axis size "
            f"{mesh.shape[AXIS]}; pad the stacks first"
        )
    d_local = volumes.shape[1] // mesh.shape[AXIS]
    halo = cfg.morph_size // 2
    if d_local < halo:
        raise ValueError(
            f"local shard depth {d_local} < dilation z-radius {halo} "
            f"(morph_size={cfg.morph_size}); use fewer z-shards"
        )
    return _compiled_batch_zsharded(mesh, cfg)(volumes, dims)
