"""Data-parallel cohort execution over a device mesh.

TPU-native replacement for the reference's OpenMP batch loop
(src/parallel/main_parallel.cpp:330-347): where the reference forks 16
threads over a ≤25-slice batch, here the batch axis is sharded across chips
with `NamedSharding` and the vmapped pipeline runs as ONE compiled SPMD
program — no threads, no mutexes, no serial-export bottleneck, and
bit-identical results to the sequential path by construction.

There is no cross-device communication in this path (each slice is
independent), so scaling is embarrassingly linear over ICI-connected chips;
the only collective XLA inserts is for the vmapped region-growing
convergence test, which reduces over the *slice*, not the mesh.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nm03_capstone_project_tpu.compilehub import CompileSpec, get_hub, hub_jit
from nm03_capstone_project_tpu.config import DEFAULT_CONFIG, PipelineConfig
from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice


def _compiled_sharded_batch(
    mesh: Mesh, cfg: PipelineConfig, with_render: bool, mask_only: bool = False
):
    """The vmapped pipeline with batch-axis in/out shardings, via the hub."""

    def build(spec: CompileSpec):
        mesh, cfg = spec.mesh, spec.cfg
        shard3 = NamedSharding(mesh, P("data", None, None))
        shard2 = NamedSharding(mesh, P("data", None))
        shard1 = NamedSharding(mesh, P("data"))

        if spec.variant == "mask_only":
            # the host-render drivers fetch nothing but the mask (plus the
            # per-slice convergence flag, 1 byte/slice): don't emit the
            # original-canvas passthrough as a program output, and donate the
            # input stack's HBM (the host keeps its own copy for rendering)
            def mask_fn(pixels, dims):
                out = process_slice(pixels, dims, cfg)
                return {"mask": out["mask"], "grow_converged": out["grow_converged"]}

            return hub_jit(
                jax.vmap(mask_fn),
                in_shardings=(shard3, shard2),
                out_shardings={"mask": shard3, "grow_converged": shard1},
                donate_argnums=(0,),
            )

        if spec.variant == "render":
            from nm03_capstone_project_tpu.render.render import (
                render_gray,
                render_segmentation,
            )

            def one(pixels, dims):
                out = process_slice(pixels, dims, cfg)
                orig = render_gray(out["original"], dims, cfg.render_size)
                proc = render_segmentation(
                    out["mask"],
                    dims,
                    cfg.render_size,
                    cfg.overlay_opacity,
                    cfg.overlay_border_opacity,
                    cfg.overlay_border_radius,
                )
                return {
                    "original": orig,
                    "mask": proc,
                    "grow_converged": out["grow_converged"],
                }

        else:

            def one(pixels, dims):
                return process_slice(pixels, dims, cfg)

        return hub_jit(
            jax.vmap(one),
            in_shardings=(shard3, shard2),
            out_shardings={
                "original": shard3,
                "mask": shard3,
                "grow_converged": shard1,
            },
        )

    variant = "mask_only" if mask_only else ("render" if with_render else "")
    return get_hub().get(
        CompileSpec(
            name="dp_batch",
            cfg=cfg,
            mesh=mesh,
            donate=mask_only,
            variant=variant,
        ),
        build,
    )


def process_batch_sharded(
    pixels: jax.Array,
    dims: jax.Array,
    cfg: PipelineConfig = DEFAULT_CONFIG,
    mesh: Optional[Mesh] = None,
    with_render: bool = False,
    mask_only: bool = False,
) -> Dict[str, jax.Array]:
    """Run a (B, H, W) slice batch data-parallel across the mesh.

    B must divide the mesh's ``data`` axis evenly — use
    :func:`.mesh.pad_to_multiple` on the host batch first.

    Args:
      pixels: (B, H, W) float canvas batch.
      dims: (B, 2) true dims.
      mesh: a mesh with a ``data`` axis (default: all devices).
      with_render: additionally produce the 512x512 rendered pair on-device
        (the reference's export stage, main_sequential.cpp:254-265).
      mask_only: return {'mask', 'grow_converged'} only, with the pixel
        stack DONATED — the host-render export path; mutually exclusive
        with ``with_render``.

    Every mode's output carries ``grow_converged``: a (B,) bool, False for
    slices whose growing fixpoint hit its iteration cap (VERDICT r4 item 4).
    """
    if mask_only and with_render:
        raise ValueError("mask_only and with_render are mutually exclusive")
    if mesh is None:
        from nm03_capstone_project_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
    compiled = _compiled_sharded_batch(mesh, cfg, with_render, mask_only)
    return compiled(pixels, dims)
