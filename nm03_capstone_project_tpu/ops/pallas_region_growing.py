"""Pallas TPU kernel for seeded region growing.

The segmentation fixpoint (FAST ``SeededRegionGrowing::create(0.74f, 0.91f,
seeds)``, src/test/test_pipeline.cpp:98-108) is the pipeline's other hot op
besides the median. The portable XLA version (:mod:`.region_growing`) runs
each dilate-and-mask step as its own fused HBM pass; this kernel instead
keeps the whole slice resident in VMEM and iterates there — a (H+2, W+2)
scratch pad holds the region with a zero halo, each step reads the four
(or eight) neighbor windows as static slices of the pad, maxes them, masks
with the intensity band, and writes back, so a 256x256 slice pays one HBM
read (band + seeds) and one write (mask) for the entire fixpoint instead of
one round-trip per step.

Convergence matches the XLA implementation exactly: ``block_iters`` steps
per popcount check (the region only grows, so popcount equality is set
equality), hard-capped at ``max_iters``. The XLA path is the oracle; tests
assert bit-identical masks in interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _grow_kernel(
    band_ref,
    seed_ref,
    out_ref,
    conv_ref,
    scr,
    *,
    h: int,
    w: int,
    connectivity: int,
    block_iters: int,
    max_iters: int,
):
    """Fixpoint for one slice; ``scr`` is the (h+2, w+2) haloed region pad."""
    band = band_ref[0]
    scr[:, :] = jnp.zeros((h + 2, w + 2), jnp.float32)
    scr[1 : h + 1, 1 : w + 1] = seed_ref[0] * band

    def run_block(_):
        def step(_, carry):
            c = scr[1 : h + 1, 1 : w + 1]
            up = scr[0:h, 1 : w + 1]
            dn = scr[2 : h + 2, 1 : w + 1]
            lf = scr[1 : h + 1, 0:w]
            rt = scr[1 : h + 1, 2 : w + 2]
            grown = jnp.maximum(
                jnp.maximum(jnp.maximum(up, dn), jnp.maximum(lf, rt)), c
            )
            if connectivity == 8:
                ul = scr[0:h, 0:w]
                ur = scr[0:h, 2 : w + 2]
                dl = scr[2 : h + 2, 0:w]
                dr = scr[2 : h + 2, 2 : w + 2]
                grown = jnp.maximum(
                    grown, jnp.maximum(jnp.maximum(ul, ur), jnp.maximum(dl, dr))
                )
            scr[1 : h + 1, 1 : w + 1] = grown * band
            return carry

        jax.lax.fori_loop(0, block_iters, step, 0)
        return jnp.sum(scr[1 : h + 1, 1 : w + 1])

    def cond(state):
        prev, cur, iters = state
        return (cur != prev) & (iters < max_iters)

    def body(state):
        _, cur, iters = state
        return cur, run_block(0), iters + block_iters

    # mirror region_growing.region_grow: one unconditional block, then
    # iterate until the popcount stops changing
    c0 = jnp.sum(scr[1 : h + 1, 1 : w + 1])
    c1 = run_block(0)
    prev, cur, _ = jax.lax.while_loop(
        cond, body, (c0, c1, jnp.int32(block_iters))
    )
    out_ref[0] = scr[1 : h + 1, 1 : w + 1]
    # popcount stable at exit == converged; cap-hit mid-growth otherwise
    # (same definition as region_growing.region_grow, VERDICT r4 item 4)
    conv_ref[0] = (cur == prev).astype(jnp.int32)


@functools.partial(
    # nm03-lint: disable=NM361 Pallas kernel wrapper: the jit IS the kernel's dispatch envelope (static kernel params pin the pallas_call grid), not a pipeline compile site the hub should own
    jax.jit,
    static_argnames=("connectivity", "block_iters", "max_iters", "interpret"),
)
def _grow_pallas_batched(
    band: jax.Array,
    seeds: jax.Array,
    connectivity: int,
    block_iters: int,
    max_iters: int,
    interpret: bool,
) -> tuple[jax.Array, jax.Array]:
    b, h, w = band.shape
    kernel = functools.partial(
        _grow_kernel,
        h=h,
        w=w,
        connectivity=connectivity,
        block_iters=block_iters,
        max_iters=max_iters,
    )
    spec = pl.BlockSpec((1, h, w), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)
    conv_spec = pl.BlockSpec((1,), lambda i: (i,), memory_space=pltpu.SMEM)
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=[spec, spec],
        out_specs=(spec, conv_spec),
        out_shape=(
            jax.ShapeDtypeStruct((b, h, w), jnp.float32),
            jax.ShapeDtypeStruct((b,), jnp.int32),
        ),
        scratch_shapes=[pltpu.VMEM((h + 2, w + 2), jnp.float32)],
        interpret=interpret,
    )(band, seeds)


def region_grow_pallas(
    image: jax.Array,
    seeds: jax.Array,
    low: float = 0.74,
    high: float = 0.91,
    valid: jax.Array | None = None,
    connectivity: int = 4,
    block_iters: int = 16,
    max_iters: int = 1024,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """Drop-in Pallas variant of :func:`.region_growing.region_grow`.

    Returns ``(mask, converged)`` with the same convergence definition.
    """
    if connectivity not in (4, 8):
        raise ValueError(f"connectivity must be 4 or 8, got {connectivity}")
    h, w = image.shape[-2:]
    # The fixpoint needs the whole slice resident (band + seeds + out + the
    # haloed scratch, ~5 slice-sized f32 buffers incl. compiler temps —
    # measured 20 MB scoped at 1024²). A banded variant makes no sense for
    # a globally-propagating fixpoint, so slices past the ~16 MB VMEM
    # budget take the XLA path instead of failing at Mosaic compile time.
    # Estimate on TILE-PADDED dims (8-row sublanes x 128 lanes): a tall
    # (5600, 129) slice really costs its (5600, 256) padded footprint.
    hp = -(-h // 8) * 8
    wp = -(-w // 128) * 128
    if not interpret and 5 * hp * wp * 4 > (14 << 20):
        import logging

        from nm03_capstone_project_tpu.ops.region_growing import region_grow

        # fires at trace time (once per compiled shape), so it cannot spam;
        # without it a bench of the "pallas path" would silently time XLA
        logging.getLogger("nm03_tpu.pallas").info(
            "pallas grow: %dx%d slice exceeds the VMEM budget; XLA path", h, w
        )
        return region_grow(
            image, seeds, low, high, valid=valid, connectivity=connectivity,
            block_iters=block_iters, max_iters=max_iters,
        )
    band = (image >= low) & (image <= high)
    if valid is not None:
        band = band & valid
    orig_shape = band.shape
    bandb = band.reshape((-1,) + band.shape[-2:]).astype(jnp.float32)
    seedb = (
        seeds.astype(bool).reshape((-1,) + seeds.shape[-2:]).astype(jnp.float32)
    )
    out, conv = _grow_pallas_batched(
        bandb, seedb, connectivity, block_iters, max_iters, interpret
    )
    # scalar converged over the whole call, matching the XLA path's global
    # popcount loop (per-slice granularity comes from vmapping the caller)
    return out.reshape(orig_shape).astype(jnp.uint8), jnp.all(conv == 1)


def grow_dispatch(
    image,
    seeds,
    low,
    high,
    valid=None,
    connectivity: int = 4,
    block_iters: int = 16,
    max_iters: int = 1024,
    use_pallas: bool = False,
    algorithm: str = "dilate",
):
    """Route between the Pallas kernel and the portable XLA implementations.

    Same dispatch contract as :func:`.pallas_median.median_filter`: off-TPU
    the Pallas request degrades to the XLA path (identical results).
    ``algorithm`` selects the XLA convergence schedule — "dilate" (one-ring
    fixpoint) or "jump" (pointer-jumping label merge, O(log) rounds);
    identical masks whenever both converge within their caps, see
    :mod:`.region_growing`. PipelineConfig rejects jump+use_pallas (the
    Pallas kernel implements the dilate schedule and would silently win
    here).
    """
    from nm03_capstone_project_tpu.ops.pallas_median import pallas_backend_supported

    if use_pallas and pallas_backend_supported():
        return region_grow_pallas(
            image, seeds, low, high, valid, connectivity, block_iters, max_iters
        )
    if algorithm == "jump":
        import math

        from nm03_capstone_project_tpu.ops.region_growing import region_grow_jump

        # ONE flag, one growth budget (ADVICE r5): ``max_iters`` is a growth
        # RADIUS in pixels — the dilate schedule's unit. Pointer jumping
        # doubles its reach every round, so the equivalent round cap is
        # ceil(log2(max_iters)) plus a small margin absorbing the rounds
        # boundary effects cost without doubling reach. Passing max_iters
        # straight through (the old behavior) silently gave the jump path a
        # ~2^max_iters growth budget under the same flag value.
        max_rounds = math.ceil(math.log2(max(max_iters, 2))) + 2
        return region_grow_jump(
            image, seeds, low, high, valid=valid, connectivity=connectivity,
            max_rounds=max_rounds,
        )
    from nm03_capstone_project_tpu.ops.region_growing import region_grow

    return region_grow(
        image,
        seeds,
        low,
        high,
        valid=valid,
        connectivity=connectivity,
        block_iters=block_iters,
        max_iters=max_iters,
    )
