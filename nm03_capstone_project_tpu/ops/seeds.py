"""Adaptive seed-point generation.

The reference builds its SeededRegionGrowing seed list from the image
dimensions with C++ integer arithmetic (src/test/test_pipeline.cpp:79-106,
src/sequential/main_sequential.cpp:213-241, src/parallel/main_parallel.cpp:118-148):

* a central seed (w/2, h/2),
* four offset seeds at (w/2 +- w/8, h/2) and (w/2, h/2 +- h/8),
* a grid over the central half: x in [w/4, 3*w/4) step w/10,
  y in [h/4, 3*h/4) step h/10.

Here the seed *list* becomes a seed *mask image* computed elementwise from
broadcasted iotas — a pure function of traced (h, w), so one compiled program
adapts its seeds to every slice size, and the whole thing vmaps over a batch.
All divisions floor, matching C++ integer division on the positive operands
involved.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def seed_mask(dims: jax.Array, canvas_hw: Tuple[int, int]) -> jax.Array:
    """Boolean (..., H, W) mask marking the reference's adaptive seed points.

    Args:
      dims: int32 array (..., 2) of true (height, width) per slice.
      canvas_hw: static padded canvas shape.
    """
    hh, ww = canvas_hw
    rows = jax.lax.broadcasted_iota(jnp.int32, (hh, ww), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (hh, ww), 1)

    h = dims[..., 0:1, None].astype(jnp.int32)  # (..., 1, 1)
    w = dims[..., 1:2, None].astype(jnp.int32)

    cx = w // 2
    cy = h // 2
    off_x = w // 8
    off_y = h // 8

    # The five explicit seeds: center plus axis-aligned offsets
    # (test_pipeline.cpp:86-95).
    fixed = (
        ((cols == cx) & (rows == cy))
        | ((cols == cx + off_x) & (rows == cy))
        | ((cols == cx - off_x) & (rows == cy))
        | ((cols == cx) & (rows == cy + off_y))
        | ((cols == cx) & (rows == cy - off_y))
    )

    # The central-half grid (test_pipeline.cpp:102-106). Guard step >= 1 so
    # degenerate tiny images (below the reference's own 100px guard) don't
    # divide by zero.
    step_x = jnp.maximum(w // 10, 1)
    step_y = jnp.maximum(h // 10, 1)
    x0 = w // 4
    y0 = h // 4
    grid = (
        (cols >= x0)
        & (cols < (3 * w) // 4)
        & ((cols - x0) % step_x == 0)
        & (rows >= y0)
        & (rows < (3 * h) // 4)
        & ((rows - y0) % step_y == 0)
    )

    inside = (rows < h) & (cols < w) & (rows >= 0) & (cols >= 0)
    return (fixed | grid) & inside
