"""Elementwise intensity ops.

These are the cheap stages XLA fuses into neighbours for free; they exist as
named functions so the pipeline reads like the reference's operator chain.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def normalize(
    x: jax.Array,
    low: float = 0.5,
    high: float = 2.5,
    intensity_min: float = 0.0,
    intensity_max: float = 10000.0,
) -> jax.Array:
    """Linear intensity rescale from [intensity_min, intensity_max] to [low, high].

    TPU-native equivalent of FAST ``IntensityNormalization::create(0.5f, 2.5f,
    0.0f, 10000.0f)`` (reference src/test/test_pipeline.cpp:55,
    src/sequential/main_sequential.cpp:195-196): intensities are mapped
    affinely so the source window [intensity_min, intensity_max] lands on
    [low, high]. Values outside the source window extrapolate linearly (no
    clamping — clamping is the job of :func:`clip_intensity`, the next stage).
    """
    scale = (high - low) / (intensity_max - intensity_min)
    return (x - intensity_min) * scale + low


def clip_intensity(x: jax.Array, low: float = 0.68, high: float = 4000.0) -> jax.Array:
    """Clamp intensities to [low, high].

    TPU-native equivalent of FAST ``IntensityClipping::create(0.68f, 4000.0f)``
    (reference src/test/test_pipeline.cpp:60, main_sequential.cpp:200).
    """
    return jnp.clip(x, low, high)


def cast_uint8(x: jax.Array) -> jax.Array:
    """Cast to uint8.

    TPU-native equivalent of FAST ``ImageCaster::create(TYPE_UINT8)``
    (reference src/test/test_pipeline.cpp:114, main_sequential.cpp:246), used
    to move the float segmentation labels into the dtype the morphology stage
    expects.
    """
    return x.astype(jnp.uint8)


def binary_threshold(x: jax.Array, low: float, high: float) -> jax.Array:
    """1 where low <= x <= high else 0 (uint8).

    Optional op: declared in the reference's API surface
    (FAST_directives.hpp:13 ``BinaryThresholding``) but never instantiated.
    """
    return ((x >= low) & (x <= high)).astype(jnp.uint8)
