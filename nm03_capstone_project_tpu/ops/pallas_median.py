"""Pallas TPU kernel for the 7x7 vector median filter.

The hot stencil of the pipeline (FAST ``VectorMedianFilter::create(7)``,
src/test/test_pipeline.cpp:65-66) as a VMEM-resident selection-network
kernel:

* The padded slice (edge-replicated, matching the OpenCL clamp-to-edge
  sampler the reference inherits) lives in VMEM once per program; each grid
  step produces one row band of output, so the working set — the k sorted
  row views plus the in-flight merge values — stays comfortably under the
  ~16 MB VMEM budget at any canvas size.
* Selection runs the same column-presorted Batcher merge network as the XLA
  path (:mod:`.median`, whose pair-generation and +inf-folding machinery is
  reused verbatim): the k vertical neighbors are sorted once per column (a
  16-CE network for k=7, shared by the k horizontal windows reading that
  column), the k sorted runs are merged with odd-even merge networks, and
  the rank-k²//2 element is the median — a few hundred VPU min/max ops per
  pixel band, no data-dependent control flow. (An earlier revision selected
  by all-pairs rank counting: k²(k²-1)/2 = 1176 compares plus two integer
  adds each — about 7x the work for the same result.)

The portable XLA implementation (:func:`.median.vector_median_filter`) is the
oracle; the test suite asserts bit-identical outputs in interpret mode, and
the wrapper transparently falls back to it off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_tile(
    h: int, w: int = 256, r: int = 3, itemsize: int = 4, preferred: int = 64
):
    """Row-band size, or None when no band fits the VMEM budget.

    The band no longer has to divide ``h`` — the wrapper pads the row
    dimension up to the next band multiple and slices the output back, so a
    prime ``h`` gets the same wide bands as a friendly one instead of
    degenerating to a per-row grid (VERDICT r3 item 3: the old divisor
    search returned tile=1 for prime heights).

    The budget keeps the kernel's scoped VMEM stack inside the ~16 MB
    Mosaic limit: the presort + merge temporaries cost ~9(2r+1) full-width
    row copies per band row (calibrated against the measured 17.07 MB
    scoped allocation at k=7, band rows 70, w 1030 — the 1024² OOM; the
    model scales with window size and element width rather than
    hard-coding that point). When even the minimum legal band (8 rows, or
    ``h`` when h < 8) exceeds the budget — short-but-very-wide canvases —
    the caller falls back to the XLA path instead of OOMing on chip.
    """
    # estimate on the LANE-padded width (Mosaic pads the last dim to 128):
    # a 129-wide band really costs its 256-lane footprint
    wp = -(-(w + 2 * r) // 128) * 128
    per_band_row = wp * itemsize * 9 * (2 * r + 1)
    budget_rows = (10 << 20) // per_band_row - 2 * r
    if h < 8:
        return h if budget_rows >= h else None
    # Mosaic requires the row block be a multiple of the 8-row sublane tile
    t = (min(preferred, h, budget_rows) // 8) * 8
    return t if t >= 8 else None


def _median_band_kernel(in_ref, out_ref, *, k: int, tile: int, w: int):
    """One (tile, w) output band of the k x k median (Batcher selection)."""
    from nm03_capstone_project_tpu.ops.median import (
        _merge_runs_take_median,
        _sort_network,
    )

    r = k // 2
    t = pl.program_id(1)
    # (tile + 2r, w + 2r) band of the padded slice, dynamically positioned
    band = in_ref[0, pl.ds(t * tile, tile + 2 * r), :]
    # vertical presort over full-width rows: shared by all k horizontal
    # windows that read each column
    sorted_rows = _sort_network([band[dr : dr + tile, :] for dr in range(k)])
    out_ref[0] = _merge_runs_take_median(
        sorted_rows, k, lambda a, j: a[:, j : j + w]
    )


@functools.partial(jax.jit, static_argnames=("size", "interpret"))
def vector_median_filter_pallas(
    x: jax.Array, size: int = 7, interpret: bool = False
) -> jax.Array:
    """Pallas k x k median over (..., H, W); clamp-to-edge boundaries.

    Bit-identical to :func:`.median.vector_median_filter`. ``interpret=True``
    runs the kernel in the Pallas interpreter (CPU testing).
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    orig_shape = x.shape
    xb = x.reshape((-1,) + x.shape[-2:]) if x.ndim != 2 else x[None]
    b, h, w = xb.shape
    r = size // 2
    tile = _pick_tile(h, w, r, x.dtype.itemsize)
    if tile is None:
        # no legal band fits the VMEM budget (short-but-very-wide canvas,
        # or a large window/dtype): the XLA path computes the identical
        # result without the scoped-stack constraint
        from nm03_capstone_project_tpu.ops.median import vector_median_filter

        return vector_median_filter(x, size)
    # pad rows to a band multiple (edge mode, same replication as the halo):
    # the extra bands read only replicated bottom rows and their output is
    # sliced off, so results stay bit-identical to the XLA oracle while a
    # prime h keeps full-width bands instead of a per-row grid
    h_pad = (-h) % tile
    xp = jnp.pad(xb, ((0, 0), (r, r + h_pad), (r, r)), mode="edge")
    kernel = functools.partial(_median_band_kernel, k=size, tile=tile, w=w)
    out = pl.pallas_call(
        kernel,
        grid=(b, (h + h_pad) // tile),
        in_specs=[
            pl.BlockSpec(
                (1, h + h_pad + 2 * r, w + 2 * r),
                lambda i, t: (i, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, tile, w), lambda i, t: (i, t, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, h + h_pad, w), x.dtype),
        interpret=interpret,
    )(xp)
    return out[:, :h, :].reshape(orig_shape)


def pallas_backend_supported() -> bool:
    """True iff the default backend can lower ``pltpu`` kernels.

    Only real TPUs qualify (core.backend holds the single platform
    allowlist). A GPU (or any other) backend must take the XLA path —
    attempting Mosaic lowering there crashes at compile time.
    """
    from nm03_capstone_project_tpu.core.backend import is_tpu_backend

    return is_tpu_backend()


def median_filter(x: jax.Array, size: int = 7, use_pallas: bool = False) -> jax.Array:
    """Dispatch between the Pallas TPU kernel and the portable XLA path.

    On non-TPU backends the Pallas request transparently degrades to the XLA
    implementation (same results), so one PipelineConfig serves tests,
    CPU fallback and TPU runs.
    """
    if use_pallas and pallas_backend_supported():
        return vector_median_filter_pallas(x, size)
    from nm03_capstone_project_tpu.ops.median import vector_median_filter

    return vector_median_filter(x, size)
