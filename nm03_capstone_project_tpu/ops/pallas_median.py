"""Pallas TPU kernels for the 7x7 vector median filter and the fused
normalize -> clip -> median -> sharpen preprocessing stage.

The hot stencil of the pipeline (FAST ``VectorMedianFilter::create(7)``,
src/test/test_pipeline.cpp:65-66) as a VMEM-resident selection-network
kernel:

* The padded slice (edge-replicated, matching the OpenCL clamp-to-edge
  sampler the reference inherits) lives in VMEM once per program; each grid
  step produces one row band of output, so the working set — the k sorted
  row views plus the in-flight merge values — stays comfortably under the
  ~16 MB VMEM budget at any canvas size.
* Selection runs the **shared pruned plan** of
  :mod:`.selection_network`: the k vertical neighbors are sorted once per
  column (a 16-CE network for k=7, shared by the k horizontal windows
  reading that column), canonical subtree merges are built once and
  referenced at lane shifts across the overlapping windows, the final
  merge is replaced by a rank-k²//2 selection, and dead ops are pruned —
  262 VPU min/max ops per pixel at k=7 where the odd-even merge tree of
  earlier revisions cost 566. On VMEM-resident values the op count IS the
  cost, which is why the kernel takes the shared variant while the XLA
  path takes the unshared one (see selection_network's docstring for the
  measured fusion rationale).

:func:`fused_preprocess_pallas` extends the same banding to the whole
preprocessing chain: one kernel reads each input band from HBM once,
normalizes + clips in registers, runs the median plan, and applies the
unsharp sharpen (separable gaussian, identical tap order to
:mod:`.sharpen`) before writing the single f32 output band — one HBM
read/write of the image instead of four round trips through the four
stage boundaries. Canvas-boundary halos replicate the *median output*
edge rows/cols in-kernel (a jnp.where against the row index plus an edge
concat), reproducing the unfused path's pad-per-stage semantics exactly.

Exactness contract: the median band kernel is **bit-identical** to the
XLA path (pure min/max — no arithmetic to re-associate). The fused
preprocess kernel is exact in its windowing/halo semantics but its
normalize/sharpen *arithmetic* may differ from the unfused composition by
a few ulp (measured <= 4 across 90 random canvases vs the JITTED
composition — the thing the pipeline actually runs; the eager evaluation
of the same code differs from its own jit by up to 8 ulp, i.e. more than
the kernel does): separately compiled programs contract ``a*b+c`` into
fma (single rounding) or not depending on the fusion shape — the 1-ulp
blur variance is then amplified by the unsharp update's cancellation.
Unobservable from JAX, and the same class of divergence the render
module documents for its matmul-vs-gather samplers. The test suite pins
an 8-ulp bound; the bench's checksum gate (mask equality) remains the
end-to-end guard.

The portable XLA implementations are the oracle; the wrappers
transparently fall back to them off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nm03_capstone_project_tpu.ops.selection_network import median_merge_plan


def _pick_tile(
    h: int, w: int = 256, r: int = 3, itemsize: int = 4, preferred: int = 64
):
    """Row-band size, or None when no band fits the VMEM budget.

    The band no longer has to divide ``h`` — the wrapper pads the row
    dimension up to the next band multiple and slices the output back, so a
    prime ``h`` gets the same wide bands as a friendly one instead of
    degenerating to a per-row grid (VERDICT r3 item 3: the old divisor
    search returned tile=1 for prime heights).

    The budget keeps the kernel's scoped VMEM stack inside the ~16 MB
    Mosaic limit: the presort + merge temporaries cost ~9(2r+1) full-width
    row copies per band row (calibrated against the measured 17.07 MB
    scoped allocation at k=7, band rows 70, w 1030 — the 1024² OOM; the
    model scales with window size and element width rather than
    hard-coding that point). The fused preprocess kernel passes the summed
    halo radius (median + sharpen), which the same model covers: its extra
    blur temporaries ride inside the 9x factor's slack. When even the
    minimum legal band (8 rows, or ``h`` when h < 8) exceeds the budget —
    short-but-very-wide canvases — the caller falls back to the XLA path
    instead of OOMing on chip.
    """
    # estimate on the LANE-padded width (Mosaic pads the last dim to 128):
    # a 129-wide band really costs its 256-lane footprint
    wp = -(-(w + 2 * r) // 128) * 128
    per_band_row = wp * itemsize * 9 * (2 * r + 1)
    budget_rows = (10 << 20) // per_band_row - 2 * r
    if h < 8:
        return h if budget_rows >= h else None
    # Mosaic requires the row block be a multiple of the 8-row sublane tile
    t = (min(preferred, h, budget_rows) // 8) * 8
    return t if t >= 8 else None


def _median_band_kernel(in_ref, out_ref, *, k: int, tile: int, w: int):
    """One (tile, w) output band of the k x k median (pruned selection)."""
    from nm03_capstone_project_tpu.ops.median import _execute_plan, _sort_network

    r = k // 2
    t = pl.program_id(1)
    # (tile + 2r, w + 2r) band of the padded slice, dynamically positioned
    band = in_ref[0, pl.ds(t * tile, tile + 2 * r), :]
    # vertical presort over full-width rows: shared by all k horizontal
    # windows that read each column
    sorted_rows = _sort_network([band[dr : dr + tile, :] for dr in range(k)])
    out_ref[0] = _execute_plan(median_merge_plan(k, share=True), sorted_rows, w)


# nm03-lint: disable=NM361 Pallas kernel wrapper: the jit IS the kernel's dispatch envelope (static size/interpret pin the pallas_call grid), not a pipeline compile site the hub should own
@functools.partial(jax.jit, static_argnames=("size", "interpret"))
def vector_median_filter_pallas(
    x: jax.Array, size: int = 7, interpret: bool = False
) -> jax.Array:
    """Pallas k x k median over (..., H, W); clamp-to-edge boundaries.

    Bit-identical to :func:`.median.vector_median_filter`. ``interpret=True``
    runs the kernel in the Pallas interpreter (CPU testing).
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    orig_shape = x.shape
    xb = x.reshape((-1,) + x.shape[-2:]) if x.ndim != 2 else x[None]
    b, h, w = xb.shape
    r = size // 2
    tile = _pick_tile(h, w, r, x.dtype.itemsize)
    if tile is None:
        # no legal band fits the VMEM budget (short-but-very-wide canvas,
        # or a large window/dtype): the XLA path computes the identical
        # result without the scoped-stack constraint
        from nm03_capstone_project_tpu.ops.median import vector_median_filter

        return vector_median_filter(x, size)
    # pad rows to a band multiple (edge mode, same replication as the halo):
    # the extra bands read only replicated bottom rows and their output is
    # sliced off, so results stay bit-identical to the XLA oracle while a
    # prime h keeps full-width bands instead of a per-row grid
    h_pad = (-h) % tile
    xp = jnp.pad(xb, ((0, 0), (r, r + h_pad), (r, r)), mode="edge")
    kernel = functools.partial(_median_band_kernel, k=size, tile=tile, w=w)
    out = pl.pallas_call(
        kernel,
        grid=(b, (h + h_pad) // tile),
        in_specs=[
            pl.BlockSpec(
                (1, h + h_pad + 2 * r, w + 2 * r),
                lambda i, t: (i, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, tile, w), lambda i, t: (i, t, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, h + h_pad, w), x.dtype),
        interpret=interpret,
    )(xp)
    return out[:, :h, :].reshape(orig_shape)


def _fused_band_kernel(
    in_ref,
    out_ref,
    *,
    k: int,
    tile: int,
    w: int,
    h: int,
    taps: tuple,
    norm_scale: float,
    norm_low: float,
    norm_min: float,
    clip_low: float,
    clip_high: float,
    gain: float,
):
    """One (tile, w) band of normalize -> clip -> median -> sharpen.

    The input band carries a (rm + rs)-row and rm-col halo (rm = median
    radius, rs = sharpen radius). The median is computed for the band's
    rows plus a ±rs halo; rows/cols of that halo falling outside the true
    canvas are replaced by the median's own edge rows/cols (a where()
    against the global row index, and an edge concat for columns), exactly
    reproducing the unfused path where sharpen edge-pads the median
    OUTPUT — median of replicated input rows is NOT the replicated median
    row, so computing into the overhang and fixing up is the only band
    decomposition that stays bit-identical.
    """
    from nm03_capstone_project_tpu.ops.median import _execute_plan, _sort_network

    rm = k // 2
    ks = len(taps)
    rs = ks // 2
    t = pl.program_id(1)
    rows_m = tile + 2 * rs  # median output rows this band produces
    band = in_ref[0, pl.ds(t * tile, rows_m + 2 * rm), :]
    # normalize + clip, elementwise in registers (same expressions as
    # ops.elementwise so results are bitwise equal)
    xn = jnp.clip(
        (band - norm_min) * norm_scale + norm_low, clip_low, clip_high
    )
    # median over the band: presort + the shared pruned selection plan
    sorted_rows = _sort_network([xn[dr : dr + rows_m, :] for dr in range(k)])
    m = _execute_plan(median_merge_plan(k, share=True), sorted_rows, w)
    # --- canvas-boundary row fixup -------------------------------------
    # global median row of band row i is t*tile - rs + i; rows outside
    # [0, h) must hold the edge median row (the unfused path's pad).
    row_g = t * tile - rs + jax.lax.broadcasted_iota(jnp.int32, (rows_m, 1), 0)
    m = jnp.where(row_g < 0, m[rs : rs + 1, :], m)  # only band 0 clamps low
    t_last = (h - 1) // tile  # static: h and tile are Python ints
    idx_a = (h - 1) - (t_last * tile - rs)
    if t_last >= 1 and (h - 1) % tile < rs:
        # the band BEFORE the one holding row h-1 also overhangs: its copy
        # of row h-1 sits one tile higher in band coordinates
        idx_b = idx_a + tile
        bot = jnp.where(t == t_last, m[idx_a : idx_a + 1, :], m[idx_b : idx_b + 1, :])
    else:
        bot = m[idx_a : idx_a + 1, :]
    m = jnp.where(row_g > h - 1, bot, m)
    # --- sharpen: edge col halo + separable gaussian (exact tap order) --
    m_wide = jnp.concatenate(
        [jnp.repeat(m[:, :1], rs, axis=1), m, jnp.repeat(m[:, -1:], rs, axis=1)],
        axis=1,
    )
    acc = None
    for i in range(ks):
        term = jnp.float32(taps[i]) * m_wide[i : i + tile, :]
        acc = term if acc is None else acc + term
    blur = None
    for i in range(ks):
        term = jnp.float32(taps[i]) * acc[:, i : i + w]
        blur = term if blur is None else blur + term
    center = m_wide[rs : rs + tile, rs : rs + w]
    out_ref[0] = center + gain * (center - blur)


@functools.partial(
    # nm03-lint: disable=NM361 Pallas kernel wrapper: the jit IS the fused kernel's dispatch envelope (static stage params pin the pallas_call grid), not a pipeline compile site the hub should own
    jax.jit,
    static_argnames=(
        "norm_low",
        "norm_high",
        "norm_min",
        "norm_max",
        "clip_low",
        "clip_high",
        "median_window",
        "sharpen_gain",
        "sharpen_sigma",
        "sharpen_kernel",
        "interpret",
    ),
)
def fused_preprocess_pallas(
    x: jax.Array,
    *,
    norm_low: float = 0.5,
    norm_high: float = 2.5,
    norm_min: float = 0.0,
    norm_max: float = 10000.0,
    clip_low: float = 0.68,
    clip_high: float = 4000.0,
    median_window: int = 7,
    sharpen_gain: float = 2.0,
    sharpen_sigma: float = 0.5,
    sharpen_kernel: int = 9,
    interpret: bool = False,
) -> jax.Array:
    """normalize -> clip -> k x k median -> unsharp sharpen, one kernel.

    ``x`` is the (..., H, W) f32 canvas (already edge-extended for true
    dims by the pipeline); returns the preprocessed canvas — same
    windowing/halo semantics as the unfused XLA composition, arithmetic
    within a few ulp of its jitted form (fma-contraction variance; see
    the module docstring).
    Each band is read from HBM once and written once — the four-stage
    chain's intermediate round trips disappear into VMEM. Falls back to
    the XLA composition when no band fits the VMEM budget.
    """
    from nm03_capstone_project_tpu.ops.sharpen import gaussian_kernel_1d

    if median_window % 2 != 1:
        raise ValueError(f"median window must be odd, got {median_window}")
    k = median_window
    rm = k // 2
    rs = sharpen_kernel // 2
    orig_shape = x.shape
    xb = x.reshape((-1,) + x.shape[-2:]) if x.ndim != 2 else x[None]
    b, h, w = xb.shape
    tile = _pick_tile(h, w, rm + rs, x.dtype.itemsize)
    if tile is None or h <= rs or tile < rs:
        # no VMEM-legal band, a canvas so short the row-fixup's band
        # arithmetic degenerates, or a band SMALLER than the sharpen halo
        # (tile < rs: interior bands would then overhang the canvas and
        # the two-candidate boundary fixup no longer covers them —
        # reachable with large sharpen kernels on narrow VMEM budgets):
        # compose the stages in XLA instead — identical math, just with
        # materialized stage boundaries
        return _fused_preprocess_xla(
            x,
            norm_low=norm_low,
            norm_high=norm_high,
            norm_min=norm_min,
            norm_max=norm_max,
            clip_low=clip_low,
            clip_high=clip_high,
            median_window=median_window,
            sharpen_gain=sharpen_gain,
            sharpen_sigma=sharpen_sigma,
            sharpen_kernel=sharpen_kernel,
        )
    h_pad = (-h) % tile
    halo = rm + rs
    xp = jnp.pad(xb, ((0, 0), (halo, halo + h_pad), (rm, rm)), mode="edge")
    taps = tuple(float(v) for v in gaussian_kernel_1d(sharpen_sigma, sharpen_kernel))
    scale = (norm_high - norm_low) / (norm_max - norm_min)
    kernel = functools.partial(
        _fused_band_kernel,
        k=k,
        tile=tile,
        w=w,
        h=h,
        taps=taps,
        norm_scale=scale,
        norm_low=norm_low,
        norm_min=norm_min,
        clip_low=clip_low,
        clip_high=clip_high,
        gain=sharpen_gain,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b, (h + h_pad) // tile),
        in_specs=[
            pl.BlockSpec(
                (1, h + h_pad + 2 * halo, w + 2 * rm),
                lambda i, t: (i, 0, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=pl.BlockSpec(
            (1, tile, w), lambda i, t: (i, t, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((b, h + h_pad, w), x.dtype),
        interpret=interpret,
    )(xp)
    return out[:, :h, :].reshape(orig_shape)


def _fused_preprocess_xla(
    x: jax.Array,
    *,
    norm_low,
    norm_high,
    norm_min,
    norm_max,
    clip_low,
    clip_high,
    median_window,
    sharpen_gain,
    sharpen_sigma,
    sharpen_kernel,
) -> jax.Array:
    """The portable composition of the four stages (XLA fuses what it can)."""
    from nm03_capstone_project_tpu.ops.elementwise import clip_intensity, normalize
    from nm03_capstone_project_tpu.ops.median import vector_median_filter
    from nm03_capstone_project_tpu.ops.sharpen import sharpen

    out = normalize(x, norm_low, norm_high, norm_min, norm_max)
    out = clip_intensity(out, clip_low, clip_high)
    out = vector_median_filter(out, median_window)
    return sharpen(out, sharpen_gain, sharpen_sigma, sharpen_kernel)


def pallas_backend_supported() -> bool:
    """True iff the default backend can lower ``pltpu`` kernels.

    Only real TPUs qualify (core.backend holds the single platform
    allowlist). A GPU (or any other) backend must take the XLA path —
    attempting Mosaic lowering there crashes at compile time.
    """
    from nm03_capstone_project_tpu.core.backend import is_tpu_backend

    return is_tpu_backend()


def median_filter(
    x: jax.Array, size: int = 7, use_pallas: bool = False, impl: str = "pruned"
) -> jax.Array:
    """Dispatch between the Pallas TPU kernel and the portable XLA paths.

    ``impl`` selects the XLA implementation: 'pruned' (the selection
    network, the default fast path), 'merge' (the full odd-even merge
    baseline), or 'sort' (the materialize-and-sort oracle) — all
    bit-identical; the non-default paths exist for comparison timing and
    debugging (``PipelineConfig.median_impl``). On non-TPU backends a
    Pallas request transparently degrades to the selected XLA
    implementation, so one PipelineConfig serves tests, CPU fallback and
    TPU runs.
    """
    if use_pallas and pallas_backend_supported():
        return vector_median_filter_pallas(x, size)
    from nm03_capstone_project_tpu.ops.median import (
        vector_median_filter,
        vector_median_filter_merge,
        vector_median_filter_sort,
    )

    if impl == "merge":
        return vector_median_filter_merge(x, size)
    if impl == "sort":
        return vector_median_filter_sort(x, size)
    if impl != "pruned":
        raise ValueError(f"unknown median impl: {impl!r}")
    return vector_median_filter(x, size)
