"""Unsharp-mask sharpening.

TPU-native equivalent of FAST ``ImageSharpening::create(2.0f, 0.5f, 9)``
(reference src/test/test_pipeline.cpp:71, main_sequential.cpp:208): gaussian
blur (sigma, odd kernel size) followed by the unsharp update

    out = x + gain * (x - blur(x))

The blur is a separable pair of SHIFTED-ADD sweeps: each axis applies
``sum_i k[i] * shift_i(x)`` as ``size`` fused multiply-adds over the whole
image — pure VPU streaming that XLA fuses into one loop per axis. This
replaced a ``lax.conv_general_dilated`` lowering that measured ~32x slower
on the CPU backend (1-wide separable kernels also tile the MXU poorly, so
the elementwise form is the right shape on TPU too; all arithmetic is true
f32 by construction — the earlier conv needed precision='highest' to avoid
a ~2e-3 bf16 error that the downstream [0.74, 0.91] segmentation band would
amplify into flipped pixels). Clamp-to-edge boundary handling matches the
OpenCL sampler behavior the reference inherits.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def gaussian_kernel_1d(sigma: float, size: int) -> np.ndarray:
    """Normalized 1D gaussian taps; host-side constant folded into the jit."""
    if size % 2 != 1:
        raise ValueError(f"kernel size must be odd, got {size}")
    r = size // 2
    # nm03-lint: disable=NM341 deliberate: taps are computed once on the host at full precision, then cast — the f32 cast below is the pipeline boundary and the folded constant is identical across backends
    xs = np.arange(-r, r + 1, dtype=np.float64)
    k = np.exp(-(xs**2) / (2.0 * sigma * sigma))
    return (k / k.sum()).astype(np.float32)


def gaussian_blur(x: jax.Array, sigma: float, size: int) -> jax.Array:
    """Separable gaussian blur over the last two axes, clamp-to-edge."""
    k = gaussian_kernel_1d(sigma, size)
    r = size // 2
    for axis in (-2, -1):
        pad = [(0, 0)] * x.ndim
        pad[x.ndim + axis] = (r, r)
        xp = jnp.pad(x, pad, mode="edge")
        acc = None
        for i in range(size):
            term = jnp.float32(k[i]) * jax.lax.slice_in_dim(
                xp, i, i + x.shape[axis], axis=axis
            )
            acc = term if acc is None else acc + term
        x = acc
    return x


def sharpen(
    x: jax.Array, gain: float = 2.0, sigma: float = 0.5, size: int = 9
) -> jax.Array:
    """Unsharp mask with the reference's default (gain=2, sigma=0.5, size=9)."""
    return x + gain * (x - gaussian_blur(x, sigma, size))
