"""Unsharp-mask sharpening.

TPU-native equivalent of FAST ``ImageSharpening::create(2.0f, 0.5f, 9)``
(reference src/test/test_pipeline.cpp:71, main_sequential.cpp:208): gaussian
blur (sigma, odd kernel size) followed by the unsharp update

    out = x + gain * (x - blur(x))

The blur is a separable 1D convolution pair lowered through
``lax.conv_general_dilated`` (XLA maps it onto the MXU/VPU and fuses the
elementwise tail). Clamp-to-edge boundary handling matches the OpenCL
sampler behavior the reference inherits.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=None)
def gaussian_kernel_1d(sigma: float, size: int) -> np.ndarray:
    """Normalized 1D gaussian taps; host-side constant folded into the jit."""
    if size % 2 != 1:
        raise ValueError(f"kernel size must be odd, got {size}")
    r = size // 2
    xs = np.arange(-r, r + 1, dtype=np.float64)
    k = np.exp(-(xs**2) / (2.0 * sigma * sigma))
    return (k / k.sum()).astype(np.float32)


def gaussian_blur(x: jax.Array, sigma: float, size: int) -> jax.Array:
    """Separable gaussian blur over the last two axes, clamp-to-edge."""
    k = jnp.asarray(gaussian_kernel_1d(sigma, size))
    r = size // 2
    lead = x.shape[:-2]
    h, w = x.shape[-2], x.shape[-1]
    xb = x.reshape((-1, 1, h, w))  # NCHW
    xb = jnp.pad(
        xb, [(0, 0), (0, 0), (r, r), (r, r)], mode="edge"
    )
    dn = jax.lax.conv_dimension_numbers(xb.shape, (1, 1, size, 1), ("NCHW", "OIHW", "NCHW"))
    # precision='highest' keeps the taps in true f32: the default bf16 matmul
    # path costs ~2e-3 absolute error, which the downstream [0.74, 0.91]
    # segmentation band would amplify into flipped pixels.
    xb = jax.lax.conv_general_dilated(
        xb, k.reshape(1, 1, size, 1), (1, 1), "VALID",
        dimension_numbers=dn, precision="highest",
    )
    xb = jax.lax.conv_general_dilated(
        xb, k.reshape(1, 1, 1, size), (1, 1), "VALID",
        dimension_numbers=dn, precision="highest",
    )
    return xb.reshape(lead + (h, w))


def sharpen(
    x: jax.Array, gain: float = 2.0, sigma: float = 0.5, size: int = 9
) -> jax.Array:
    """Unsharp mask with the reference's default (gain=2, sigma=0.5, size=9)."""
    return x + gain * (x - gaussian_blur(x, sigma, size))
