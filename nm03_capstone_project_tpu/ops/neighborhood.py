"""Shared neighborhood machinery for the stencil ops.

The reference's per-pixel kernels run under OpenCL samplers with
clamp-to-edge addressing; on a padded static canvas the equivalent is
(a) replicating each slice's true edge into the padding region
(:func:`extend_edges`) so stencils never mix padding zeros into real pixels,
and (b) expressing small windows as stacks of shifted views
(:func:`shifted_stack`), which XLA fuses into tight VPU loops.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp


def extend_edges(x: jax.Array, dims: jax.Array) -> jax.Array:
    """Replicate each slice's true boundary into the canvas padding.

    ``x`` is (..., H, W); ``dims`` is (..., 2) true (height, width). Every
    pixel at (r, c) becomes x[min(r, h-1), min(c, w-1)], i.e. clamp-to-edge
    addressing applied to the whole canvas, jit-friendly for traced dims.

    Formulated as two single-index gathers (the edge row/column) plus
    broadcast selects rather than a full-canvas ``take_along_axis`` pair: a
    dynamic 2D gather along the lane dimension costs ~57 ms per 32x256x256
    batch on TPU — 16x the select form — and was 63% of round 2's measured
    pipeline device time before this rewrite.
    """
    h_canvas, w_canvas = x.shape[-2], x.shape[-1]
    rows = jax.lax.broadcasted_iota(jnp.int32, (h_canvas, 1), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (1, w_canvas), 1)
    h = dims[..., 0:1, None]
    w = dims[..., 1:2, None]
    edge = jnp.broadcast_to(h - 1, (*x.shape[:-2], 1, 1))
    row_edge = jnp.take_along_axis(x, edge, axis=-2)  # x[..., h-1, :]
    x = jnp.where(rows >= h, row_edge, x)
    edge = jnp.broadcast_to(w - 1, (*x.shape[:-2], 1, 1))
    col_edge = jnp.take_along_axis(x, edge, axis=-1)  # x[..., :, w-1]
    return jnp.where(cols >= w, col_edge, x)


def shifted_stack(
    x: jax.Array,
    offsets: List[Tuple[int, int]],
    pad_mode: str = "edge",
    constant_values=0,
) -> jax.Array:
    """Stack shifted views of ``x`` along a new leading axis.

    For each (dr, dc) in ``offsets`` the result holds x shifted so that entry
    [k, ..., r, c] == x_padded[..., r + dr + R, c + dc + C] where R, C are the
    max absolute offsets. Used to materialize k*k windows for median /
    morphology / convolution-style ops; XLA fuses the stack away.
    ``constant_values`` applies only with ``pad_mode='constant'`` (e.g. a
    +inf/maxval border for min-propagation).
    """
    max_r = max(abs(dr) for dr, _ in offsets)
    max_c = max(abs(dc) for _, dc in offsets)
    pad_widths = [(0, 0)] * (x.ndim - 2) + [(max_r, max_r), (max_c, max_c)]
    if pad_mode == "constant":
        xp = jnp.pad(x, pad_widths, mode="constant", constant_values=constant_values)
    else:
        xp = jnp.pad(x, pad_widths, mode=pad_mode)
    h, w = x.shape[-2], x.shape[-1]
    views = [
        jax.lax.dynamic_slice_in_dim(
            jax.lax.dynamic_slice_in_dim(xp, max_r + dr, h, axis=-2),
            max_c + dc,
            w,
            axis=-1,
        )
        for dr, dc in offsets
    ]
    return jnp.stack(views, axis=0)


def window_offsets(size: int) -> List[Tuple[int, int]]:
    """All (dr, dc) offsets of a size x size window centered at 0."""
    r = size // 2
    return [(dr, dc) for dr in range(-r, size - r) for dc in range(-r, size - r)]


def footprint_offsets(size: int, shape: str) -> List[Tuple[int, int]]:
    """Offsets of a structuring element.

    shape: 'box' (full window), 'cross' (city-block radius size//2, the
    4-connected element for size 3), or 'disk' (euclidean radius size/2).
    """
    r = size // 2
    offs = []
    for dr in range(-r, r + 1):
        for dc in range(-r, r + 1):
            if shape == "box":
                offs.append((dr, dc))
            elif shape == "cross":
                if abs(dr) + abs(dc) <= r:
                    offs.append((dr, dc))
            elif shape == "disk":
                if dr * dr + dc * dc <= (size / 2.0) ** 2:
                    offs.append((dr, dc))
            else:
                raise ValueError(f"unknown footprint shape: {shape}")
    return offs
