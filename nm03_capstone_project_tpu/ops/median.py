"""Vector median filter.

TPU-native equivalent of FAST ``VectorMedianFilter::create(7)`` (reference
src/test/test_pipeline.cpp:65-66, main_sequential.cpp:204), the
edge-preserving denoise stage and one of the two hot per-pixel kernels.

The vector median of a window is the sample minimizing the summed L1 distance
to all other samples; for single-channel data that minimizer is exactly the
scalar median sample, so the scalar path computes a median-of-k^2. The
implementations share the contract:

* :func:`vector_median_filter` — the default XLA path: **column-presorted
  pruned selection network**. The k vertical neighbors are sorted ONCE per
  column with a sorting network (shared by all k horizontal windows that
  read that column), then the plan from :mod:`.selection_network` merges
  the sorted columns, replaces the final merge with a rank-k²//2
  selection, and backward-liveness-prunes every op the median cannot see
  — 1.64x fewer min/max ops traced than the full odd-even merge tree at
  k=7 (566 -> 346). The Pallas kernel runs the *shared* variant of the
  same plan (subtree merges built once and referenced at lane shifts
  across the k overlapping windows in x — 566 -> 262, 2.16x fewer; see
  selection_network for why sharing is a Pallas-only win).
* :func:`vector_median_filter_merge` — the previous default, kept as the
  comparison baseline: full Batcher odd-even merge of the presorted runs,
  rank k²//2 read at the end. Selected by ``PipelineConfig``'s
  ``median_impl='merge'``.
* :func:`vector_median_filter_sort` — the straightforward sort-the-window
  implementation; the readable in-repo oracle (SciPy is the external one).
* ``ops.pallas_median`` (Pallas TPU kernel, VMEM-resident tiles) — runs
  the same pruned plan per row band; selected via
  ``PipelineConfig.use_pallas``.

All are bit-identical on real data: the pruned plan is value-equivalent to
the full network by construction (rank selection is an identity on values,
liveness only removes dead ops). (Pathological caveat shared with any
min/max network: NaNs are unordered and -0.0/+0.0 compare equal, so
windows containing those may differ bitwise from a total-order sort; the
pipeline's median consumes clipped intensities in [0.68, 4000], where
neither occurs.)

Boundary handling is clamp-to-edge, matching the OpenCL sampler addressing
the reference inherits.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.neighborhood import shifted_stack, window_offsets
from nm03_capstone_project_tpu.ops.selection_network import (
    MedianPlan,
    median_merge_plan,
    next_pow2 as _next_pow2,  # noqa: F401 — re-exported for callers/tests
    oddeven_merge_pairs,
    oddeven_sort_pairs,
)

_PAD = None  # Python-level +inf sentinel; folded before any op is emitted


def _oddeven_merge_pairs(lo: int, n: int, r: int, pairs: List[Tuple[int, int]]):
    """Batcher odd-even merge pair generation (see ops.selection_network)."""
    oddeven_merge_pairs(lo, n, r, pairs)


def _oddeven_sort_pairs(lo: int, n: int, pairs: List[Tuple[int, int]]):
    """Batcher odd-even sort pair generation (see ops.selection_network)."""
    oddeven_sort_pairs(lo, n, pairs)


def _apply_pairs(vals: List[Optional[jax.Array]], pairs) -> None:
    """Run compare-exchanges in place, folding the +inf sentinel in Python.

    CE(a, b) -> (min, max). With b = +inf it is a no-op; with a = +inf it is
    a pure swap; only real-real pairs emit jnp.minimum/jnp.maximum.
    """
    for i, j in pairs:
        a, b = vals[i], vals[j]
        if b is _PAD:
            continue
        if a is _PAD:
            vals[i], vals[j] = b, _PAD
            continue
        vals[i] = jnp.minimum(a, b)
        vals[j] = jnp.maximum(a, b)


def _sort_network(vals: List[jax.Array]) -> List[jax.Array]:
    """Sort a small list of arrays elementwise with a Batcher network."""
    n = len(vals)
    p = _next_pow2(n)
    padded: List[Optional[jax.Array]] = list(vals) + [_PAD] * (p - n)
    pairs: List[Tuple[int, int]] = []
    _oddeven_sort_pairs(0, p, pairs)
    _apply_pairs(padded, pairs)
    assert all(v is not _PAD for v in padded[:n])
    return padded[:n]  # ascending; pads sorted to the tail


def _execute_plan(
    plan: MedianPlan, padded_rows: List[jax.Array], w_out: int
) -> jax.Array:
    """Run a selection-network plan over k presorted full-width rows.

    ``padded_rows`` are the ascending vertical-sort outputs, each padded by
    r = k//2 lanes of edge replication on both sides (the clamp-to-edge
    window columns), so lane domain [-r, w_out + r) exists for every input.
    Each plan node is computed ONCE on the lane interval its consumers
    reach it at (the cross-window sharing: a node referenced at several
    shifts becomes one slightly wider array, not several re-merges); static
    slices feed the operands, so XLA sees a pure min/max DAG.
    """
    r = plan.k // 2
    # backward pass: the union of lane shifts each value is consumed at
    need: Dict[int, set] = {plan.out[0]: {plan.out[1]}}
    for kind, out, a, ash, b, bsh in reversed(plan.ops):
        for s in need.get(out, ()):
            need.setdefault(a, set()).add(s + ash)
            need.setdefault(b, set()).add(s + bsh)
    dom = {i: (min(ss), max(ss)) for i, ss in need.items()}
    arrs: Dict[int, jax.Array] = {}
    los: Dict[int, int] = {}
    for i in range(plan.k):
        lo, hi = dom.get(i, (0, 0))
        arrs[i] = padded_rows[i][..., lo + r : hi + r + w_out]
        los[i] = lo
    for kind, out, a, ash, b, bsh in plan.ops:
        if out not in dom:  # dead op of an unpruned plan
            continue
        lo, hi = dom[out]
        wn = w_out + hi - lo
        sa = lo + ash - los[a]
        sb = lo + bsh - los[b]
        av = arrs[a][..., sa : sa + wn]
        bv = arrs[b][..., sb : sb + wn]
        arrs[out] = jnp.minimum(av, bv) if kind == "min" else jnp.maximum(av, bv)
        los[out] = lo
    oi, osh = plan.out
    s = osh - los[oi]
    return arrs[oi][..., s : s + w_out]


def _merge_runs_take_median(sorted_rows: List[jax.Array], k: int, colslice):
    """Rank-k²//2 of the k*k window given k vertically-sorted row arrays —
    the FULL odd-even merge baseline (``median_impl='merge'``).

    ``colslice(a, j)`` extracts the j-th (0-based) horizontal window column
    from a sorted row array. Runs are +inf-padded to powers of two (folded
    in Python by :func:`_apply_pairs`) and merged with a Batcher odd-even
    merge tree; XLA dead-code-eliminates the pairs that cannot reach the
    median output. Kept verbatim as the comparison baseline the pruned
    plan is counted (and benchmarked) against.
    """
    p_run = _next_pow2(k)  # slots per run, +inf padded
    n_runs = _next_pow2(k)  # number of runs, all-+inf runs appended
    vals: List[Optional[jax.Array]] = []
    for j in range(k):
        vals.extend(colslice(a, j) for a in sorted_rows)
        vals.extend([_PAD] * (p_run - k))
    vals.extend([_PAD] * ((n_runs - k) * p_run))

    width = p_run
    total = p_run * n_runs
    while width < total:
        pairs: List[Tuple[int, int]] = []
        for lo in range(0, total, 2 * width):
            _oddeven_merge_pairs(lo, 2 * width, 1, pairs)
        _apply_pairs(vals, pairs)
        width *= 2
    med = vals[(k * k) // 2]
    assert med is not _PAD
    return med


def _presorted_rows(x: jax.Array, k: int) -> List[jax.Array]:
    """The k ascending vertical neighbors per column (clamp-to-edge),
    shared across the k horizontal windows that read each column."""
    r = k // 2
    rows = shifted_stack(x, [(dr, 0) for dr in range(-r, k - r)], pad_mode="edge")
    return _sort_network([rows[i] for i in range(k)])


def vector_median_filter(x: jax.Array, size: int = 7) -> jax.Array:
    """Median over a size x size clamp-to-edge window (fast XLA path).

    ``x`` is (..., H, W) float; returns the same shape/dtype. The median of
    an odd k*k window equals the vector median (L1) for scalar samples.
    Column presort + the pruned selection network of
    :func:`.selection_network.median_merge_plan`.
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    if size == 1:
        return x
    k = size
    r = k // 2
    sorted_rows = _presorted_rows(x, k)
    pw = [(0, 0)] * (x.ndim - 1) + [(r, r)]
    padded = [jnp.pad(a, pw, mode="edge") for a in sorted_rows]
    # unshared plan: shifts only on the k input rows, so the whole merge
    # stays one elementwise DAG XLA fuses into a register-resident loop
    # (the shared plan belongs to the Pallas kernel — see selection_network)
    return _execute_plan(median_merge_plan(k, share=False), padded, x.shape[-1])


def vector_median_filter_merge(x: jax.Array, size: int = 7) -> jax.Array:
    """Median via the full odd-even merge network (the pre-pruning default).

    Bit-identical to :func:`vector_median_filter`; kept as the baseline the
    comparator-count reduction and the bench stage delta are measured
    against (``median_impl='merge'``).
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    if size == 1:
        return x
    k = size
    r = k // 2
    sorted_rows = _presorted_rows(x, k)

    def colslice(a: jax.Array, j: int) -> jax.Array:
        pw = [(0, 0)] * (a.ndim - 1) + [(r, r)]
        ap = jnp.pad(a, pw, mode="edge")
        return jax.lax.dynamic_slice_in_dim(ap, j, a.shape[-1], axis=-1)

    return _merge_runs_take_median(sorted_rows, k, colslice)


def vector_median_filter_sort(x: jax.Array, size: int = 7) -> jax.Array:
    """Median via materialize-and-sort (the readable in-repo oracle)."""
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    stack = shifted_stack(x, window_offsets(size), pad_mode="edge")
    # sort over the window axis and take the middle sample
    n = stack.shape[0]
    return jnp.sort(stack, axis=0)[n // 2]


def vector_median_filter_multichannel(x: jax.Array, size: int = 7) -> jax.Array:
    """True vector median for multi-channel data (..., C, H, W).

    Picks, per pixel, the window *sample vector* minimizing the sum of L1
    distances to the other samples — the general contract FAST's
    VectorMedianFilter implements for color/vector images.
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    offs = window_offsets(size)
    stack = shifted_stack(x, offs, pad_mode="edge")  # (K, ..., C, H, W)
    # pairwise L1 distances between window samples, summed over channels
    diff = jnp.abs(stack[:, None] - stack[None, :]).sum(axis=-3)  # (K, K, ..., H, W)
    cost = diff.sum(axis=1)  # (K, ..., H, W)
    best = jnp.argmin(cost, axis=0)  # (..., H, W)
    return _select_sample(stack, best)


def _select_sample(stack: jax.Array, best: jax.Array) -> jax.Array:
    """Gather stack[best[..., h, w], ..., :, h, w] -> (..., C, H, W)."""
    k = stack.shape[0]
    onehot = jax.nn.one_hot(best, k, axis=0, dtype=stack.dtype)  # (K, ..., H, W)
    return (stack * onehot[:, ..., None, :, :]).sum(axis=0)
