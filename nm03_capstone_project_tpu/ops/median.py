"""Vector median filter.

TPU-native equivalent of FAST ``VectorMedianFilter::create(7)`` (reference
src/test/test_pipeline.cpp:65-66, main_sequential.cpp:204), the
edge-preserving denoise stage and one of the two hot per-pixel kernels.

The vector median of a window is the sample minimizing the summed L1 distance
to all other samples; for single-channel data that minimizer is exactly the
scalar median sample, so the scalar path computes a median-of-k^2. Three
implementations share the contract:

* :func:`vector_median_filter` — the default XLA path: **column-presorted
  Batcher merge network**. The k vertical neighbors are sorted ONCE per
  column with a sorting network (shared by all k horizontal windows that
  read that column — the classic amortization of fast 2D median filters),
  then the k sorted runs are merged with Batcher odd-even merge networks
  and the rank-k²//2 element is taken. Runs are padded to powers of two
  with +inf sentinels that are folded away in Python (a compare-exchange
  against +inf is a no-op or a swap), so the emitted XLA graph contains
  only real min/max pairs — several-fold fewer than sorting the full k²
  window stack, and XLA dead-code-eliminates the pairs that cannot reach
  the median output.
* :func:`vector_median_filter_sort` — the straightforward sort-the-window
  implementation; kept as the readable in-repo oracle (SciPy is the
  external one).
* ``ops.pallas_median`` (Pallas TPU kernel, pairwise rank selection,
  VMEM-resident tiles) — selected via ``PipelineConfig.use_pallas``.

All three are bit-identical on real data. (Pathological caveat shared with
any min/max network: NaNs are unordered and -0.0/+0.0 compare equal, so
windows containing those may differ bitwise from a total-order sort; the
pipeline's median consumes clipped intensities in [0.68, 4000], where
neither occurs.)

Boundary handling is clamp-to-edge, matching the OpenCL sampler addressing
the reference inherits.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.neighborhood import shifted_stack, window_offsets

_PAD = None  # Python-level +inf sentinel; folded before any op is emitted


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _oddeven_merge_pairs(lo: int, n: int, r: int, pairs: List[Tuple[int, int]]):
    """Batcher odd-even merge: positions [lo, lo+n) hold two sorted halves."""
    step = 2 * r
    if step < n:
        _oddeven_merge_pairs(lo, n, step, pairs)
        _oddeven_merge_pairs(lo + r, n, step, pairs)
        for i in range(lo + r, lo + n - r, step):
            pairs.append((i, i + r))
    else:
        pairs.append((lo, lo + r))


def _oddeven_sort_pairs(lo: int, n: int, pairs: List[Tuple[int, int]]):
    """Batcher odd-even mergesort network for positions [lo, lo+n), n = 2^m."""
    if n > 1:
        m = n // 2
        _oddeven_sort_pairs(lo, m, pairs)
        _oddeven_sort_pairs(lo + m, m, pairs)
        _oddeven_merge_pairs(lo, n, 1, pairs)


def _apply_pairs(vals: List[Optional[jax.Array]], pairs) -> None:
    """Run compare-exchanges in place, folding the +inf sentinel in Python.

    CE(a, b) -> (min, max). With b = +inf it is a no-op; with a = +inf it is
    a pure swap; only real-real pairs emit jnp.minimum/jnp.maximum.
    """
    for i, j in pairs:
        a, b = vals[i], vals[j]
        if b is _PAD:
            continue
        if a is _PAD:
            vals[i], vals[j] = b, _PAD
            continue
        vals[i] = jnp.minimum(a, b)
        vals[j] = jnp.maximum(a, b)


def _sort_network(vals: List[jax.Array]) -> List[jax.Array]:
    """Sort a small list of arrays elementwise with a Batcher network."""
    n = len(vals)
    p = _next_pow2(n)
    padded: List[Optional[jax.Array]] = list(vals) + [_PAD] * (p - n)
    pairs: List[Tuple[int, int]] = []
    _oddeven_sort_pairs(0, p, pairs)
    _apply_pairs(padded, pairs)
    assert all(v is not _PAD for v in padded[:n])
    return padded[:n]  # ascending; pads sorted to the tail


def _merge_runs_take_median(sorted_rows: List[jax.Array], k: int, colslice):
    """Rank-k²//2 of the k*k window given k vertically-sorted row arrays.

    ``colslice(a, j)`` extracts the j-th (0-based) horizontal window column
    from a sorted row array — the only step that differs between the XLA
    path (edge-padded dynamic slice) and the Pallas kernel (static slice of
    the already-padded VMEM band). Shared so the two paths cannot drift
    apart: runs are +inf-padded to powers of two (folded in Python by
    :func:`_apply_pairs`) and merged with a Batcher odd-even merge tree.
    """
    p_run = _next_pow2(k)  # slots per run, +inf padded
    n_runs = _next_pow2(k)  # number of runs, all-+inf runs appended
    vals: List[Optional[jax.Array]] = []
    for j in range(k):
        vals.extend(colslice(a, j) for a in sorted_rows)
        vals.extend([_PAD] * (p_run - k))
    vals.extend([_PAD] * ((n_runs - k) * p_run))

    width = p_run
    total = p_run * n_runs
    while width < total:
        pairs: List[Tuple[int, int]] = []
        for lo in range(0, total, 2 * width):
            _oddeven_merge_pairs(lo, 2 * width, 1, pairs)
        _apply_pairs(vals, pairs)
        width *= 2
    med = vals[(k * k) // 2]
    assert med is not _PAD
    return med


def vector_median_filter(x: jax.Array, size: int = 7) -> jax.Array:
    """Median over a size x size clamp-to-edge window (fast XLA path).

    ``x`` is (..., H, W) float; returns the same shape/dtype. The median of
    an odd k*k window equals the vector median (L1) for scalar samples.
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    if size == 1:
        return x
    k = size
    r = k // 2

    # vertical sort, shared across the k horizontal windows per column:
    # row-shifted full-width views -> k sorted arrays (16 CEs for k=7)
    rows = shifted_stack(x, [(dr, 0) for dr in range(-r, k - r)], pad_mode="edge")
    sorted_rows = _sort_network([rows[i] for i in range(k)])

    def colslice(a: jax.Array, j: int) -> jax.Array:
        pw = [(0, 0)] * (a.ndim - 1) + [(r, r)]
        ap = jnp.pad(a, pw, mode="edge")
        return jax.lax.dynamic_slice_in_dim(ap, j, a.shape[-1], axis=-1)

    return _merge_runs_take_median(sorted_rows, k, colslice)


def vector_median_filter_sort(x: jax.Array, size: int = 7) -> jax.Array:
    """Median via materialize-and-sort (the readable in-repo oracle)."""
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    stack = shifted_stack(x, window_offsets(size), pad_mode="edge")
    # sort over the window axis and take the middle sample
    n = stack.shape[0]
    return jnp.sort(stack, axis=0)[n // 2]


def vector_median_filter_multichannel(x: jax.Array, size: int = 7) -> jax.Array:
    """True vector median for multi-channel data (..., C, H, W).

    Picks, per pixel, the window *sample vector* minimizing the sum of L1
    distances to the other samples — the general contract FAST's
    VectorMedianFilter implements for color/vector images.
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    offs = window_offsets(size)
    stack = shifted_stack(x, offs, pad_mode="edge")  # (K, ..., C, H, W)
    # pairwise L1 distances between window samples, summed over channels
    diff = jnp.abs(stack[:, None] - stack[None, :]).sum(axis=-3)  # (K, K, ..., H, W)
    cost = diff.sum(axis=1)  # (K, ..., H, W)
    best = jnp.argmin(cost, axis=0)  # (..., H, W)
    return _select_sample(stack, best)


def _select_sample(stack: jax.Array, best: jax.Array) -> jax.Array:
    """Gather stack[best[..., h, w], ..., :, h, w] -> (..., C, H, W)."""
    k = stack.shape[0]
    onehot = jax.nn.one_hot(best, k, axis=0, dtype=stack.dtype)  # (K, ..., H, W)
    return (stack * onehot[:, ..., None, :, :]).sum(axis=0)
