"""Vector median filter.

TPU-native equivalent of FAST ``VectorMedianFilter::create(7)`` (reference
src/test/test_pipeline.cpp:65-66, main_sequential.cpp:204), the
edge-preserving denoise stage and one of the two hot per-pixel kernels.

The vector median of a window is the sample minimizing the summed L1 distance
to all other samples; for single-channel data that minimizer is exactly the
scalar median sample, so the scalar path computes a median-of-k^2. Two
implementations share the contract:

* :func:`vector_median_filter` — portable XLA version (sort over the
  materialized window stack), used on CPU and as the oracle.
* ``ops.pallas_median`` (Pallas TPU kernel, rank-selection without a sort,
  VMEM-resident tiles) — selected via ``PipelineConfig.use_pallas``.

Boundary handling is clamp-to-edge, matching the OpenCL sampler addressing
the reference inherits.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.neighborhood import shifted_stack, window_offsets


def vector_median_filter(x: jax.Array, size: int = 7) -> jax.Array:
    """Median over a size x size clamp-to-edge window (XLA reference path).

    ``x`` is (..., H, W) float; returns the same shape/dtype. The median of an
    odd k*k window equals the vector median (L1) for scalar samples.
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    stack = shifted_stack(x, window_offsets(size), pad_mode="edge")
    # sort over the window axis and take the middle sample
    n = stack.shape[0]
    return jnp.sort(stack, axis=0)[n // 2]


def vector_median_filter_multichannel(x: jax.Array, size: int = 7) -> jax.Array:
    """True vector median for multi-channel data (..., C, H, W).

    Picks, per pixel, the window *sample vector* minimizing the sum of L1
    distances to the other samples — the general contract FAST's
    VectorMedianFilter implements for color/vector images.
    """
    if size % 2 != 1:
        raise ValueError(f"median window must be odd, got {size}")
    offs = window_offsets(size)
    stack = shifted_stack(x, offs, pad_mode="edge")  # (K, ..., C, H, W)
    # pairwise L1 distances between window samples, summed over channels
    diff = jnp.abs(stack[:, None] - stack[None, :]).sum(axis=-3)  # (K, K, ..., H, W)
    cost = diff.sum(axis=1)  # (K, ..., H, W)
    best = jnp.argmin(cost, axis=0)  # (..., H, W)
    return _select_sample(stack, best)


def _select_sample(stack: jax.Array, best: jax.Array) -> jax.Array:
    """Gather stack[best[..., h, w], ..., :, h, w] -> (..., C, H, W)."""
    k = stack.shape[0]
    onehot = jax.nn.one_hot(best, k, axis=0, dtype=stack.dtype)  # (K, ..., H, W)
    return (stack * onehot[:, ..., None, :, :]).sum(axis=0)
