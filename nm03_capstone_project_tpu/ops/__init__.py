"""The operator set.

TPU-native re-implementations of every FAST ProcessObject the reference
instantiates (SURVEY.md section 2.2), as pure jit-friendly functions:

=====================  =============================================  =========================
Reference operator     This package                                   Reference instantiation
=====================  =============================================  =========================
IntensityNormalization :func:`elementwise.normalize`                  create(0.5, 2.5, 0, 10000)
IntensityClipping      :func:`elementwise.clip_intensity`             create(0.68, 4000)
VectorMedianFilter     :func:`median.vector_median_filter`            create(7)
ImageSharpening        :func:`sharpen.sharpen`                        create(2.0, 0.5, 9)
SeededRegionGrowing    :func:`region_growing.region_grow`             create(0.74, 0.91, seeds)
ImageCaster            :func:`elementwise.cast_uint8`                 create(TYPE_UINT8)
Dilation               :func:`morphology.dilate`                      create(3)
Erosion                :func:`morphology.erode`                       create(3)
(seed-point logic)     :func:`seeds.seed_mask`                        test_pipeline.cpp:79-106
=====================  =============================================  =========================

Also carried as optional ops (declared in the reference's header but never
instantiated): :func:`elementwise.binary_threshold` (BinaryThresholding,
FAST_directives.hpp:13) and :mod:`regionprops` (RegionProperties,
FAST_directives.hpp:24).
"""

from nm03_capstone_project_tpu.ops.elementwise import (  # noqa: F401
    binary_threshold,
    cast_uint8,
    clip_intensity,
    normalize,
)
from nm03_capstone_project_tpu.ops.median import (  # noqa: F401
    vector_median_filter,
    vector_median_filter_merge,
    vector_median_filter_multichannel,
    vector_median_filter_sort,
)
from nm03_capstone_project_tpu.ops.selection_network import (  # noqa: F401
    comparator_counts,
    median_merge_plan,
)
from nm03_capstone_project_tpu.ops.morphology import dilate, erode  # noqa: F401
from nm03_capstone_project_tpu.ops.neighborhood import extend_edges  # noqa: F401
from nm03_capstone_project_tpu.ops.pallas_median import (  # noqa: F401
    median_filter,
)
from nm03_capstone_project_tpu.ops.pallas_region_growing import (  # noqa: F401
    grow_dispatch,
    region_grow_pallas,
)
from nm03_capstone_project_tpu.ops.region_growing import (  # noqa: F401
    region_grow,
    region_grow_jump,
)
from nm03_capstone_project_tpu.ops.regionprops import (  # noqa: F401
    bounding_box,
    connected_components,
    region_properties,
)
from nm03_capstone_project_tpu.ops.seeds import seed_mask  # noqa: F401
from nm03_capstone_project_tpu.ops.sharpen import gaussian_blur, sharpen  # noqa: F401
from nm03_capstone_project_tpu.ops.volume import (  # noqa: F401
    dilate3d,
    erode3d,
    region_grow_3d,
    region_grow_jump_3d,
)
