"""Morphological dilation / erosion.

TPU-native equivalents of FAST ``Dilation::create(3)`` / ``Erosion::create(3)``
(reference src/test/test_pipeline.cpp:119-125, src/sequential/main_sequential.cpp:250-252),
the post-processing cleanup on the uint8 segmentation mask.

Outside-image pixels count as background (0), matching flood-fill-style
morphology on label masks: dilation pads with the minimum, erosion erodes at
the image border.

Implementation: min/max over the structuring element, with the element
decomposed where the algebra allows — decompositions are exact because
erosion/dilation by ``B1 ⊕ B2`` (Minkowski sum) equals the two-stage
erosion/dilation by B1 then B2, and the constant-0 border commutes through
the stages (0 is absorbing for the min and the identity for the max on the
non-negative mask dtypes these ops serve):

* ``box k`` — separable: a (k,1) then a (1,k) ``lax.reduce_window``. One
  native windowed pass per axis instead of a k²-1 op fold.
* ``disk 5`` — exactly ``box3 ⊕ cross3`` (every offset with dr²+dc² <=
  6.25 is a sum of a box3 and a cross3 offset and the corners (±2,±2) are
  unreachable), so: separable box3 reduce_window, then a 5-offset cross
  fold. This is the render overlay's border element; the decomposition
  (plus reduce_window acting as a fusion boundary that stops XLA:CPU from
  re-computing the upstream resample into each shifted read) took the
  render segmentation leg from 226 to 66 ms/batch on the bench host.
* everything else — a folded accumulation over shifted views (no
  materialized (|offsets|, ..., H, W) stack; min/max are commutative and
  associative, so the fold is bit-identical to the old stack reduction).
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.neighborhood import footprint_offsets


def _extreme_identity(dtype, is_max: bool):
    """The neutral element for max (resp. absorbing-free init for min)."""
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(-jnp.inf if is_max else jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.array(info.min if is_max else info.max, dtype)


def _fold(x: jax.Array, offs: List[Tuple[int, int]], is_max: bool) -> jax.Array:
    """min/max over shifted views, constant-0 border, no materialized stack."""
    max_r = max(abs(dr) for dr, _ in offs)
    max_c = max(abs(dc) for _, dc in offs)
    pad_widths = [(0, 0)] * (x.ndim - 2) + [(max_r, max_r), (max_c, max_c)]
    xp = jnp.pad(x, pad_widths, mode="constant")
    h, w = x.shape[-2], x.shape[-1]
    op = jnp.maximum if is_max else jnp.minimum
    out = None
    for dr, dc in offs:
        view = jax.lax.slice_in_dim(
            jax.lax.slice_in_dim(xp, max_r + dr, max_r + dr + h, axis=-2),
            max_c + dc,
            max_c + dc + w,
            axis=-1,
        )
        out = view if out is None else op(out, view)
    return out


def _box_reduce_window(x: jax.Array, size: int, is_max: bool) -> jax.Array:
    """Separable k x k box min/max: (k,1) then (1,k) reduce_window over the
    constant-0-padded canvas (VALID padding — the explicit pad carries the
    background semantics; reduce_window's own padding would inject the
    init value instead of 0)."""
    r = size // 2
    pad_widths = [(0, 0)] * (x.ndim - 2) + [(r, r), (r, r)]
    xp = jnp.pad(x, pad_widths, mode="constant")
    init = _extreme_identity(x.dtype, is_max)
    op = jax.lax.max if is_max else jax.lax.min
    ones = (1,) * x.ndim
    out = jax.lax.reduce_window(
        xp, init, op, (1,) * (x.ndim - 2) + (size, 1), ones, "VALID"
    )
    return jax.lax.reduce_window(
        out, init, op, (1,) * (x.ndim - 2) + (1, size), ones, "VALID"
    )


def _morph(x: jax.Array, size: int, shape: str, is_max: bool) -> jax.Array:
    orig_dtype = x.dtype
    work = x.astype(jnp.uint8) if orig_dtype == jnp.bool_ else x
    if size == 1:
        return x
    if shape == "box":
        out = _box_reduce_window(work, size, is_max)
    elif shape == "disk" and size == 5:
        # disk5 == box3 ⊕ cross3: separable box pass, then the cross fold
        out = _fold(
            _box_reduce_window(work, 3, is_max),
            footprint_offsets(3, "cross"),
            is_max,
        )
    else:
        out = _fold(work, footprint_offsets(size, shape), is_max)
    return out.astype(orig_dtype)


def dilate(x: jax.Array, size: int = 3, shape: str = "cross") -> jax.Array:
    """Grayscale/binary dilation with a size x size structuring element.

    Default element is 'cross' (city-block radius 1 for size 3, i.e.
    4-connectivity), matching the compact cleanup the reference applies; 'box'
    and 'disk' are available where FAST-parity experiments want them.
    """
    return _morph(x, size, shape, is_max=True)


def erode(x: jax.Array, size: int = 3, shape: str = "cross") -> jax.Array:
    """Grayscale/binary erosion with a size x size structuring element."""
    return _morph(x, size, shape, is_max=False)
