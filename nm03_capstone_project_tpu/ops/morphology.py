"""Morphological dilation / erosion.

TPU-native equivalents of FAST ``Dilation::create(3)`` / ``Erosion::create(3)``
(reference src/test/test_pipeline.cpp:119-125, src/sequential/main_sequential.cpp:250-252),
the post-processing cleanup on the uint8 segmentation mask. Implemented as
max/min over a structuring element expressed as shifted views — for the tiny
3x3 elements involved this fuses into a single VPU pass, and the same code
path serves bool, uint8 and float inputs.

Outside-image pixels count as background (0), matching flood-fill-style
morphology on label masks: dilation pads with the minimum, erosion erodes at
the image border.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.neighborhood import (
    footprint_offsets,
    shifted_stack,
)


def _morph(x: jax.Array, size: int, shape: str, is_max: bool) -> jax.Array:
    offs = footprint_offsets(size, shape)
    orig_dtype = x.dtype
    work = x.astype(jnp.uint8) if orig_dtype == jnp.bool_ else x
    # constant (background) padding: dilation can't spill in from outside,
    # erosion removes border-touching foreground
    stack = shifted_stack(work, offs, pad_mode="constant")
    out = stack.max(axis=0) if is_max else stack.min(axis=0)
    return out.astype(orig_dtype)


def dilate(x: jax.Array, size: int = 3, shape: str = "cross") -> jax.Array:
    """Grayscale/binary dilation with a size x size structuring element.

    Default element is 'cross' (city-block radius 1 for size 3, i.e.
    4-connectivity), matching the compact cleanup the reference applies; 'box'
    and 'disk' are available where FAST-parity experiments want them.
    """
    return _morph(x, size, shape, is_max=True)


def erode(x: jax.Array, size: int = 3, shape: str = "cross") -> jax.Array:
    """Grayscale/binary erosion with a size x size structuring element."""
    return _morph(x, size, shape, is_max=False)
