"""Seeded region growing.

TPU-native equivalent of FAST ``SeededRegionGrowing::create(0.74f, 0.91f,
seeds)`` (reference src/test/test_pipeline.cpp:98-108,
main_sequential.cpp:232-243) — the segmentation stage and the reference's
hardest kernel: a data-dependent flood fill from ~30 adaptive seeds accepting
pixels whose intensity lies in [low, high].

A sequential BFS queue is the wrong shape for a TPU. Here the fill is a
*fixpoint of masked label dilation*: the region mask grows by one
4-connected ring per step via a 3x3 cross max, intersected with the intensity
band, until nothing changes. Control flow is `lax.while_loop` over a
`lax.fori_loop` block of ``block_iters`` steps — the inner block amortizes the
convergence check (a device-wide reduction) over many cheap VPU steps, and
everything stays inside one compiled program (no host round-trips, vmappable
over a batch).

Worst-case step count is the longest 4-connected path inside the band
(bounded by H*W, practically by the region diameter); ``max_iters`` caps it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.morphology import dilate


def region_grow(
    image: jax.Array,
    seeds: jax.Array,
    low: float = 0.74,
    high: float = 0.91,
    valid: jax.Array | None = None,
    connectivity: int = 4,
    block_iters: int = 16,
    max_iters: int = 1024,
) -> jax.Array:
    """Flood-fill segmentation; returns a uint8 {0,1} mask shaped like image.

    Args:
      image: (..., H, W) float intensities.
      seeds: (..., H, W) bool seed mask (see ops.seeds.seed_mask).
      low/high: inclusive intensity band a pixel must lie in to join the
        region (reference band [0.74, 0.91]).
      valid: optional (..., H, W) bool mask of true-image pixels; padding
        never joins the region.
      connectivity: 4 (reference/FAST behavior) or 8.
      block_iters: dilation steps per convergence check.
      max_iters: hard cap on total steps (safety for pathological bands).
    """
    band = (image >= low) & (image <= high)
    if valid is not None:
        band = band & valid
    shape = "cross" if connectivity == 4 else "box"
    region0 = seeds & band

    def grow_block(region):
        def step(_, r):
            return dilate(r, 3, shape) & band
        return jax.lax.fori_loop(0, block_iters, step, region)

    def cond(state):
        region, prev_count, iters = state
        return (region.sum() != prev_count) & (iters < max_iters)

    def body(state):
        region, _, iters = state
        count = region.sum()
        return grow_block(region), count, iters + block_iters

    # Run at least one block, then iterate until the popcount stops changing.
    # (popcount equality == set equality here because the region only grows.)
    region, _, _ = jax.lax.while_loop(
        cond, body, (grow_block(region0), region0.sum(), jnp.int32(block_iters))
    )
    return region.astype(jnp.uint8)
