"""Seeded region growing.

TPU-native equivalent of FAST ``SeededRegionGrowing::create(0.74f, 0.91f,
seeds)`` (reference src/test/test_pipeline.cpp:98-108,
main_sequential.cpp:232-243) — the segmentation stage and the reference's
hardest kernel: a data-dependent flood fill from ~30 adaptive seeds accepting
pixels whose intensity lies in [low, high].

A sequential BFS queue is the wrong shape for a TPU. Two jit-native
formulations share the exact set semantics (pixels of the intensity band
connected to a seed) and produce bit-identical masks whenever both converge
within their iteration caps (the dilate path truncates a region whose
longest band path exceeds ``max_iters``; the jump path, converging in
O(log) rounds, effectively never truncates):

* :func:`region_grow` — *fixpoint of masked label dilation*: the region mask
  grows by one 4-connected ring per step via a 3x3 cross max, intersected
  with the intensity band, until nothing changes. Control flow is
  `lax.while_loop` over a `lax.fori_loop` block of ``block_iters`` steps —
  the inner block amortizes the convergence check over many cheap VPU steps.
  Sequential depth = the longest band path (the region diameter).
* :func:`region_grow_jump` — *pointer-jumping connected components*:
  min-label propagation with pointer-doubling gathers, O(log diameter)
  rounds instead of O(diameter) — the latency-optimal shape when the
  sequential depth of the dilation fixpoint, not its per-step VPU cost,
  bounds the stage (PipelineConfig.grow_algorithm selects it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.morphology import dilate


def region_grow(
    image: jax.Array,
    seeds: jax.Array,
    low: float = 0.74,
    high: float = 0.91,
    valid: jax.Array | None = None,
    connectivity: int = 4,
    block_iters: int = 16,
    max_iters: int = 1024,
) -> tuple[jax.Array, jax.Array]:
    """Flood-fill segmentation; returns ``(mask, converged)``.

    ``mask`` is a uint8 {0,1} array shaped like ``image``; ``converged`` is
    a scalar bool — False means the iteration cap truncated a still-growing
    region and the mask under-covers the true connected set. FAST's BFS
    always completes (main_sequential.cpp:232-243), so a capped mask is a
    divergence the caller must be able to see: drivers count and log it per
    patient like any other per-slice failure (VERDICT r4 item 4).

    Args:
      image: (..., H, W) float intensities.
      seeds: (..., H, W) bool seed mask (see ops.seeds.seed_mask).
      low/high: inclusive intensity band a pixel must lie in to join the
        region (reference band [0.74, 0.91]).
      valid: optional (..., H, W) bool mask of true-image pixels; padding
        never joins the region.
      connectivity: 4 (reference/FAST behavior) or 8.
      block_iters: dilation steps per convergence check.
      max_iters: hard cap on total steps (safety for pathological bands).
    """
    band = (image >= low) & (image <= high)
    if valid is not None:
        band = band & valid
    shape = "cross" if connectivity == 4 else "box"
    region0 = seeds & band

    def grow_block(region):
        def step(_, r):
            return dilate(r, 3, shape) & band
        return jax.lax.fori_loop(0, block_iters, step, region)

    # the state carries the CURRENT region's popcount so each convergence
    # check costs one reduction, not two (cond used to recompute the sum
    # the body had just evaluated — same shape as zshard's psum loop), and
    # the converged flag falls out of the carried counts for free
    def cond(state):
        _, prev_count, count, iters = state
        return (count != prev_count) & (iters < max_iters)

    def body(state):
        region, _, count, iters = state
        new_region = grow_block(region)
        return new_region, count, new_region.sum(), iters + block_iters

    # Run at least one block, then iterate until the popcount stops changing.
    # (popcount equality == set equality here because the region only grows.)
    region1 = grow_block(region0)
    region, prev_count, count, _ = jax.lax.while_loop(
        cond, body,
        (region1, region0.sum(), region1.sum(), jnp.int32(block_iters)),
    )
    # the loop exits either because the popcount went stable (converged) or
    # because the cap hit mid-growth; the carried counts distinguish the two
    converged = count == prev_count
    return region.astype(jnp.uint8), converged


def _neighbor_min(labels: jax.Array, band: jax.Array, sentinel, connectivity: int):
    """Min label over each pixel's in-band neighbors (and itself)."""
    h, w = labels.shape
    pad = jnp.full_like(labels[:1], sentinel)
    padc = jnp.full_like(labels[:, :1], sentinel)
    up = jnp.concatenate([labels[1:], pad], axis=0)
    down = jnp.concatenate([pad, labels[:-1]], axis=0)
    left = jnp.concatenate([labels[:, 1:], padc], axis=1)
    right = jnp.concatenate([padc, labels[:, :-1]], axis=1)
    m = jnp.minimum(jnp.minimum(up, down), jnp.minimum(left, right))
    if connectivity == 8:
        ul = jnp.concatenate([up[:, 1:], padc], axis=1)
        ur = jnp.concatenate([padc, up[:, :-1]], axis=1)
        dl = jnp.concatenate([down[:, 1:], padc], axis=1)
        dr = jnp.concatenate([padc, down[:, :-1]], axis=1)
        m = jnp.minimum(m, jnp.minimum(jnp.minimum(ul, ur), jnp.minimum(dl, dr)))
    m = jnp.minimum(m, labels)
    return jnp.where(band, m, sentinel)


def region_grow_jump(
    image: jax.Array,
    seeds: jax.Array,
    low: float = 0.74,
    high: float = 0.91,
    valid: jax.Array | None = None,
    connectivity: int = 4,
    max_rounds: int = 256,
    jumps_per_round: int = 2,
) -> tuple[jax.Array, jax.Array]:
    """Flood fill in O(log diameter) rounds via pointer-jumping label merge.

    Returns ``(mask, converged)`` like :func:`region_grow`; ``converged`` is
    False only when ``max_rounds`` cut the label fixpoint short (with the
    default 256 on O(log diameter) rounds, effectively never).

    Same set semantics as :func:`region_grow` — pixels of the intensity band
    4/8-connected to a seed — so the outputs are bit-identical; only the
    convergence schedule differs. Where the dilation fixpoint advances the
    frontier ONE ring per step (sequential depth = region diameter, the
    latency-bound worst case on an accelerator), this is connected-component
    labeling by min-label propagation with pointer doubling:

    * each round takes the min label over in-band neighbors (one VPU stencil),
    * then compresses pointer chains with ``label <- label_of[label]``
      gathers (``jumps_per_round`` times) — halving label-tree depth per
      jump, which is what turns O(diameter) into O(log),

    and stops at the first round that changes nothing (a fixpoint of
    neighbor-min, i.e. every component carries its min pixel-id). A pixel
    then joins the region iff its component label is one a seed carries —
    one scatter + one gather.

    2D only (the batch drivers vmap over slices; use
    :func:`ops.volume.region_grow_3d` for volumes).
    """
    if image.ndim != 2:
        raise ValueError(
            f"region_grow_jump is per-slice (2D); got shape {image.shape} — "
            "vmap over leading axes instead"
        )
    band = (image >= low) & (image <= high)
    if valid is not None:
        band = band & valid
    h, w = image.shape
    n = h * w
    sentinel = jnp.int32(n)  # out-of-band marker; also the "no label" slot
    ids = jnp.arange(n, dtype=jnp.int32).reshape(h, w)
    labels0 = jnp.where(band, ids, sentinel)

    def jump(labels):
        flat = jnp.concatenate([labels.ravel(), jnp.array([n], jnp.int32)])
        return jnp.where(band, flat[labels], sentinel)

    def round_(labels):
        labels = _neighbor_min(labels, band, sentinel, connectivity)
        for _ in range(jumps_per_round):
            labels = jump(labels)
        return labels

    def cond(state):
        prev, cur, it = state
        return jnp.any(prev != cur) & (it < max_rounds)

    def body(state):
        _, cur, it = state
        return cur, round_(cur), it + 1

    prev, labels, _ = jax.lax.while_loop(
        cond, body, (labels0, round_(labels0), jnp.int32(1))
    )
    converged = jnp.all(prev == labels)

    # components whose min-id a seed carries are the grown region
    seed_labels = jnp.where(seeds.astype(bool) & band, labels, sentinel)
    marked = (
        jnp.zeros((n + 1,), jnp.bool_)
        .at[seed_labels.ravel()]
        .set(True, mode="drop")
        .at[n]
        .set(False)
    )
    region = band & marked[labels]
    return region.astype(jnp.uint8), converged
