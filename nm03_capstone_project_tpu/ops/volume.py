"""3D volumetric operators.

The reference is strictly 2D — ``setLoadSeries(false)`` everywhere
(src/test/test_pipeline.cpp:41) — and its "scale" axis is slices-per-patient.
The TPU-native framework's volumetric capability (BASELINE.json config 4)
stacks a patient's T1+C series into a (D, H, W) volume and runs seeded region
growing / morphology with true 3D connectivity, so a lesion is segmented as
one connected body instead of D independent 2D islands.

All ops operate on the last three axes and vmap over any leading batch axes.
The 'cross' footprint at size 3 is the 6-connected structuring element — the
3D analog of the reference's 4-connected flood fill; 'box' gives
26-connectivity.
"""

from __future__ import annotations

from typing import List, Tuple

import jax
import jax.numpy as jnp


def footprint_offsets_3d(size: int, shape: str = "cross") -> List[Tuple[int, int, int]]:
    """Offsets (dz, dr, dc) of a 3D structuring element.

    shape: 'box' (full cube), 'cross' (city-block radius size//2 — the
    6-connected element for size 3), or 'ball' (euclidean radius size/2).
    """
    r = size // 2
    offs = []
    for dz in range(-r, r + 1):
        for dr in range(-r, r + 1):
            for dc in range(-r, r + 1):
                if shape == "box":
                    offs.append((dz, dr, dc))
                elif shape == "cross":
                    if abs(dz) + abs(dr) + abs(dc) <= r:
                        offs.append((dz, dr, dc))
                elif shape == "ball":
                    if dz * dz + dr * dr + dc * dc <= (size / 2.0) ** 2:
                        offs.append((dz, dr, dc))
                else:
                    raise ValueError(f"unknown footprint shape: {shape}")
    return offs


def shifted_stack_3d(
    x: jax.Array,
    offsets: List[Tuple[int, int, int]],
    pad_mode: str = "constant",
) -> jax.Array:
    """Stack 3D-shifted views of ``x`` (..., D, H, W) along a new leading axis.

    The volumetric counterpart of :func:`ops.neighborhood.shifted_stack`; XLA
    fuses the stack into the consuming reduction.
    """
    max_z = max(abs(dz) for dz, _, _ in offsets)
    max_r = max(abs(dr) for _, dr, _ in offsets)
    max_c = max(abs(dc) for _, _, dc in offsets)
    pad_widths = [(0, 0)] * (x.ndim - 3) + [
        (max_z, max_z),
        (max_r, max_r),
        (max_c, max_c),
    ]
    xp = jnp.pad(x, pad_widths, mode=pad_mode)
    d, h, w = x.shape[-3], x.shape[-2], x.shape[-1]
    views = [
        jax.lax.dynamic_slice_in_dim(
            jax.lax.dynamic_slice_in_dim(
                jax.lax.dynamic_slice_in_dim(xp, max_z + dz, d, axis=-3),
                max_r + dr,
                h,
                axis=-2,
            ),
            max_c + dc,
            w,
            axis=-1,
        )
        for dz, dr, dc in offsets
    ]
    return jnp.stack(views, axis=0)


def _morph3d(x: jax.Array, size: int, shape: str, is_max: bool) -> jax.Array:
    offs = footprint_offsets_3d(size, shape)
    orig_dtype = x.dtype
    work = x.astype(jnp.uint8) if orig_dtype == jnp.bool_ else x
    stack = shifted_stack_3d(work, offs, pad_mode="constant")
    out = stack.max(axis=0) if is_max else stack.min(axis=0)
    return out.astype(orig_dtype)


def dilate3d(x: jax.Array, size: int = 3, shape: str = "cross") -> jax.Array:
    """3D dilation over (..., D, H, W); outside-volume counts as background.

    Volumetric extension of FAST ``Dilation::create(3)``
    (src/sequential/main_sequential.cpp:250) with 6-connectivity by default.
    """
    return _morph3d(x, size, shape, is_max=True)


def erode3d(x: jax.Array, size: int = 3, shape: str = "cross") -> jax.Array:
    """3D erosion over (..., D, H, W); foreground erodes at volume borders."""
    return _morph3d(x, size, shape, is_max=False)


def region_grow_3d(
    volume: jax.Array,
    seeds: jax.Array,
    low: float = 0.74,
    high: float = 0.91,
    valid: jax.Array | None = None,
    connectivity: int = 6,
    block_iters: int = 16,
    max_iters: int = 4096,
) -> tuple[jax.Array, jax.Array]:
    """3D seeded region growing; returns ``(mask, converged)``.

    ``mask`` is a uint8 {0,1} array shaped like ``volume``; ``converged`` is
    a scalar bool, False when ``max_iters`` truncated a still-growing region
    (VERDICT r4 item 4 — FAST's BFS always completes, so truncation must be
    visible to callers).

    The volumetric extension of the reference's SeededRegionGrowing
    (src/sequential/main_sequential.cpp:232-243): the flood fill is a fixpoint
    of masked 3D label dilation — grow one 6-connected (or 26-connected)
    shell per step, intersect with the intensity band [low, high], repeat
    until the popcount stops changing (region only grows, so popcount
    equality is set equality).

    Args:
      volume: (..., D, H, W) float intensities (already preprocessed).
      seeds: (..., D, H, W) bool seed mask.
      valid: optional bool mask of true-volume voxels; padding never joins.
      connectivity: 6 (face neighbors) or 26 (full cube).
      block_iters: dilation steps per convergence check (amortizes the
        device-wide reduction over many cheap VPU steps).
      max_iters: hard cap on total steps.
    """
    band = (volume >= low) & (volume <= high)
    if valid is not None:
        band = band & valid
    shape = "cross" if connectivity == 6 else "box"
    region0 = seeds & band

    def grow_block(region):
        def step(_, r):
            return dilate3d(r, 3, shape) & band

        return jax.lax.fori_loop(0, block_iters, step, region)

    # carried-count state: one popcount per check, converged for free (the
    # same loop shape as the 2D op and zshard's psum loop)
    def cond(state):
        _, prev_count, count, iters = state
        return (count != prev_count) & (iters < max_iters)

    def body(state):
        region, _, count, iters = state
        new_region = grow_block(region)
        return new_region, count, new_region.sum(), iters + block_iters

    region1 = grow_block(region0)
    region, prev_count, count, _ = jax.lax.while_loop(
        cond, body,
        (region1, region0.sum(), region1.sum(), jnp.int32(block_iters)),
    )
    converged = count == prev_count
    return region.astype(jnp.uint8), converged


def _shift3d(a: jax.Array, off, fill) -> jax.Array:
    """``a`` shifted by (dz, dy, dx); vacated voxels take ``fill``."""
    out = a
    for axis, d in zip((-3, -2, -1), off):
        if d == 0:
            continue
        pad = [(0, 0)] * a.ndim
        pad[axis] = (max(-d, 0), max(d, 0))
        out = jnp.pad(out, pad, mode="constant", constant_values=fill)
        out = jax.lax.slice_in_dim(
            out, max(d, 0), max(d, 0) + a.shape[axis], axis=axis
        )
    return out


def region_grow_jump_3d(
    volume: jax.Array,
    seeds: jax.Array,
    low: float = 0.74,
    high: float = 0.91,
    valid: jax.Array | None = None,
    connectivity: int = 6,
    max_rounds: int = 256,
    jumps_per_round: int = 2,
) -> tuple[jax.Array, jax.Array]:
    """3D flood fill in O(log diameter) rounds via pointer-jumping label merge.

    Returns ``(mask, converged)`` like :func:`region_grow_3d`.

    Volumetric twin of :func:`ops.region_growing.region_grow_jump` — same set
    semantics as :func:`region_grow_3d` (identical masks whenever the dilate
    schedule converges within its cap), with O(log) sequential depth instead
    of one 6/26-connected shell per step. One (D, H, W) volume; vmap for
    batches.
    """
    if volume.ndim != 3:
        raise ValueError(
            f"region_grow_jump_3d is per-volume (3D); got shape {volume.shape}"
            " — vmap over leading axes instead"
        )
    band = (volume >= low) & (volume <= high)
    if valid is not None:
        band = band & valid
    d, h, w = volume.shape
    n = d * h * w
    sentinel = jnp.int32(n)
    ids = jnp.arange(n, dtype=jnp.int32).reshape(d, h, w)
    labels0 = jnp.where(band, ids, sentinel)

    if connectivity == 6:
        offsets = [
            (1, 0, 0), (-1, 0, 0), (0, 1, 0), (0, -1, 0), (0, 0, 1), (0, 0, -1),
        ]
    elif connectivity == 26:
        offsets = [
            (dz, dy, dx)
            for dz in (-1, 0, 1)
            for dy in (-1, 0, 1)
            for dx in (-1, 0, 1)
            if (dz, dy, dx) != (0, 0, 0)
        ]
    else:
        raise ValueError(f"connectivity must be 6 or 26, got {connectivity}")

    def neighbor_min(labels):
        m = labels
        for off in offsets:
            m = jnp.minimum(m, _shift3d(labels, off, n))
        return jnp.where(band, m, sentinel)

    def jump(labels):
        flat = jnp.concatenate([labels.ravel(), jnp.array([n], jnp.int32)])
        return jnp.where(band, flat[labels], sentinel)

    def round_(labels):
        labels = neighbor_min(labels)
        for _ in range(jumps_per_round):
            labels = jump(labels)
        return labels

    def cond(state):
        prev, cur, it = state
        return jnp.any(prev != cur) & (it < max_rounds)

    def body(state):
        _, cur, it = state
        return cur, round_(cur), it + 1

    prev, labels, _ = jax.lax.while_loop(
        cond, body, (labels0, round_(labels0), jnp.int32(1))
    )
    converged = jnp.all(prev == labels)

    seed_labels = jnp.where(seeds.astype(bool) & band, labels, sentinel)
    marked = (
        jnp.zeros((n + 1,), jnp.bool_)
        .at[seed_labels.ravel()]
        .set(True)
        .at[n]
        .set(False)
    )
    region = band & marked[labels]
    return region.astype(jnp.uint8), converged
