"""Comparator-network construction and trace-time pruning for the median.

Pure Python, deliberately **jax-free**: the network is a compile-time
artifact (a DAG of min/max ops over window samples), so its construction,
pruning and counting must be importable from processes that never touch
jax — bench.py's orchestrator records comparator metadata in the metrics
snapshot, and the obs registry is stdlib-only by contract.

The planner turns "median of a k x k window given k column-presorted rows"
into a DAG of min/max ops over *lane-shifted* array references, applying
three work-elimination ideas the full odd-even merge tree leaves on the
table:

* **Merge sharing across overlapping windows.** Adjacent output pixels
  share k-1 of their k sorted columns, so the merge of columns (x, x+1)
  is the merge of columns (x+2, x+3) shifted two lanes. Subtree merges
  are built once in canonical form and *referenced* at different shifts
  (each op in the plan carries per-operand lane shifts); the executor
  computes every node a single time on a slightly widened domain instead
  of re-merging per window position.
* **Rank selection instead of a final merge.** The filter needs rank
  k²//2, not a sort: the last (largest) merge level is replaced by the
  order-statistic identity

      rank_p(A ∪ B) = max_{i+j=p} min(A_i, B_j)      (+inf past the ends)

  (verified exhaustively against brute force, duplicates included, in the
  test suite) — ~40 ops where the odd-even final merge costs hundreds.
* **Backward liveness** from the single median output then removes every
  op that cannot reach it (dead sorted positions, and the dead half of
  compare-exchanges only one of whose outputs is consumed).

For k=7 the full odd-even merge tree emits 566 min/max ops per pixel; the
pruned plan emits 346 (1.64x fewer), and with cross-window sharing 262 —
2.16x fewer (3.14x at k=5, 3.90x at k=9; presort excluded: its outputs
all stay live and every path shares it; exact numbers per k come from
:func:`comparator_counts`, asserted in tests). The XLA path runs the
unshared pruned plan (sharing requires shifted reads of intermediates,
which XLA's producer-duplicating fusion turns into recompute — measured
~10x slower on XLA:CPU); the Pallas kernel runs the shared plan on
VMEM-resident values, where the op count is the cost. Every pruned-plan op
computes the same value as the full network (the rank identity is an
equality on values, not an approximation), so the median is bit-identical
on any input free of NaNs — the caveat all min/max networks share; the
pipeline's median consumes clipped finite data.
"""

from __future__ import annotations

import functools
from typing import Dict, List, NamedTuple, Optional, Tuple

_PAD = None  # +inf sentinel slot; folded in Python before any op is planned

Ref = Tuple[int, int]  # (value id, lane shift relative to the consumer)


def next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def oddeven_merge_pairs(lo: int, n: int, r: int, pairs: List[Tuple[int, int]]):
    """Batcher odd-even merge: positions [lo, lo+n) hold two sorted halves."""
    step = 2 * r
    if step < n:
        oddeven_merge_pairs(lo, n, step, pairs)
        oddeven_merge_pairs(lo + r, n, step, pairs)
        for i in range(lo + r, lo + n - r, step):
            pairs.append((i, i + r))
    else:
        pairs.append((lo, lo + r))


def oddeven_sort_pairs(lo: int, n: int, pairs: List[Tuple[int, int]]):
    """Batcher odd-even mergesort network for positions [lo, lo+n), n = 2^m."""
    if n > 1:
        m = n // 2
        oddeven_sort_pairs(lo, m, pairs)
        oddeven_sort_pairs(lo + m, m, pairs)
        oddeven_merge_pairs(lo, n, 1, pairs)


class MedianPlan(NamedTuple):
    """Executable min/max DAG for the merge phase of a k x k median.

    Value ids [0, k) are the k column-presorted rows (ascending: id a is
    the a-th smallest of the k vertical neighbors, as a full-width array);
    every other id is defined by one op. ``ops`` is topologically ordered:
    ``(kind, out_id, a_id, a_shift, b_id, b_shift)`` defines ``out_id`` as
    ``kind(a@a_shift, b@b_shift)`` where ``v@s`` reads value ``v`` at lane
    ``x + s`` for output lane ``x``. ``out`` is ``(id, shift)`` of the
    median. Shifts stay within [-(k//2), k//2].
    """

    k: int
    ops: Tuple[Tuple[str, int, int, int, int, int], ...]
    out: Ref


class _Builder:
    """Min/max DAG under construction; input ids are [0, n_in)."""

    def __init__(self, n_in: int):
        self.n_in = n_in
        self.nodes: Dict[int, Tuple[str, Ref, Ref]] = {}
        self._next = n_in

    def emit(self, kind: str, a: Ref, b: Ref) -> int:
        i = self._next
        self._next += 1
        self.nodes[i] = (kind, a, b)
        return i


def _merge_sorted_refs(
    bld: _Builder,
    a: List[Ref],
    b: List[Ref],
    memo: Optional[Dict],
) -> List[Ref]:
    """Odd-even merge of two ascending ref lists into one; returns the
    merged list. With ``memo``, structurally identical merges (same ref
    ids and *relative* shifts) are canonicalized, built once, and
    re-referenced at the caller's base shift — the cross-window sharing.
    Without ``memo`` no canonicalization happens, so intermediate nodes
    are only ever referenced at shift 0 (shifts appear exclusively on the
    k input rows) — the shape XLA fuses into one register-resident loop.
    """
    if not a:
        return list(b)
    if not b:
        return list(a)
    if memo is None:
        base = 0
        an, bn = tuple(a), tuple(b)
    else:
        base = min(s for _, s in a + b)
        an = tuple((i, s - base) for i, s in a)
        bn = tuple((i, s - base) for i, s in b)
    key = (an, bn)
    if memo is not None and key in memo:
        merged = memo[key]
    else:
        half = next_pow2(max(len(an), len(bn)))
        pos: List = list(an) + [_PAD] * (half - len(an))
        pos += list(bn) + [_PAD] * (half - len(bn))
        pairs: List[Tuple[int, int]] = []
        oddeven_merge_pairs(0, 2 * half, 1, pairs)
        for i, j in pairs:
            x, y = pos[i], pos[j]
            if y is _PAD:
                continue
            if x is _PAD:
                pos[i], pos[j] = y, _PAD
                continue
            pos[i] = (bld.emit("min", x, y), 0)
            pos[j] = (bld.emit("max", x, y), 0)
        merged = tuple(p for p in pos if p is not _PAD)
        assert len(merged) == len(an) + len(bn)
        if memo is not None:
            memo[key] = merged
    return [(i, s + base) for i, s in merged]


def _rank_select(bld: _Builder, a: List[Ref], b: List[Ref], rho: int) -> Ref:
    """rank_rho(a ∪ b) for ascending ref lists via max_{i+j=rho} min(a_i, b_j).

    Out-of-range positions are +inf: a term with one side past the end
    collapses to the other side's element alone, and consecutive collapsed
    terms are dominated by their largest (the lists are sorted), so each
    boundary contributes at most one bare term. The max accumulation is a
    balanced tree (min/max are commutative and associative, so shape is
    free; a tree keeps the dependency depth logarithmic for the VPU).
    """
    terms: List[Ref] = []
    if rho >= len(b):  # a-side terms whose b-side is exhausted
        terms.append(a[rho - len(b)])
    if rho >= len(a):
        terms.append(b[rho - len(a)])
    for i in range(max(0, rho - len(b) + 1), min(rho + 1, len(a))):
        terms.append((bld.emit("min", a[i], b[rho - i]), 0))
    while len(terms) > 1:
        nxt = [
            (bld.emit("max", terms[t], terms[t + 1]), 0)
            for t in range(0, len(terms) - 1, 2)
        ]
        if len(terms) % 2:
            nxt.append(terms[-1])
        terms = nxt
    return terms[0]


def _build(k: int, prune: bool, share: bool) -> MedianPlan:
    r = k // 2
    n_runs = next_pow2(k)
    bld = _Builder(k)
    memo: Optional[Dict] = {} if share else None

    def subtree(q: int, span: int) -> List[Ref]:
        """Ascending merged refs of runs [q, q+span) (runs >= k are empty)."""
        if span == 1:
            if q >= k:
                return []
            return [(a, q - r) for a in range(k)]
        left = subtree(q, span // 2)
        right = subtree(q + span // 2, span // 2)
        return _merge_sorted_refs(bld, left, right, memo)

    left = subtree(0, n_runs // 2)
    right = subtree(n_runs // 2, n_runs // 2)
    if prune:
        out = _rank_select(bld, left, right, (k * k) // 2)
        live = set()
        stack = [out[0]]
        while stack:
            v = stack.pop()
            if v < k or v in live:
                continue
            live.add(v)
            _, (ai, _), (bi, _) = bld.nodes[v]
            stack.extend((ai, bi))
    else:
        out = _merge_sorted_refs(bld, left, right, memo)[(k * k) // 2]
        live = set(bld.nodes)
    ops = tuple(
        (kind, i, a[0], a[1], b[0], b[1])
        for i, (kind, a, b) in sorted(bld.nodes.items())
        if i in live
    )
    return MedianPlan(k=k, ops=ops, out=out)


@functools.lru_cache(maxsize=None)
def median_merge_plan(
    k: int, prune: bool = True, share: bool = False
) -> MedianPlan:
    """The merge-phase plan for a k x k median over k presorted rows.

    ``prune=False, share=False`` is the odd-even merge baseline: the full
    per-window merge tree, every compare-exchange emitting both outputs —
    the network this repo's median has always traced. ``prune=True`` adds
    rank-k²//2 selection in place of the final merge plus backward
    liveness; ``share=True`` additionally canonicalizes subtree merges so
    repeated structures are built once and referenced at lane shifts.

    The two fast variants serve different executors:

    * ``share=False`` (346 ops at k=7) keeps every intermediate at shift
      0, so the XLA path stays one pure elementwise DAG over input slices
      — the shape XLA fuses into a register-resident loop. (Measured on
      XLA:CPU: the shared plan's shifted intermediate reads defeat fusion
      and run ~10x slower despite fewer ops; XLA's producer-duplicating
      fusion recomputes sliced intermediates per consumer.)
    * ``share=True`` (262 ops at k=7) is for the Pallas kernel, where ops
      execute one-by-one on VMEM-resident values: there a node referenced
      at three shifts really is computed once, and the op count is the
      cost.

    All variants compute the same value on NaN-free inputs.
    """
    if k < 1 or k % 2 == 0:
        raise ValueError(f"median window must be odd and >= 1, got {k}")
    if k == 1:
        return MedianPlan(k=1, ops=(), out=(0, 0))
    return _build(k, prune, share)


def presort_minmax_count(k: int) -> int:
    """min/max ops of the column presort (a k-wide Batcher sort network).

    Every presorted output feeds the merge phase, so the presort never
    prunes; counted separately for the stage-table attribution.
    """
    p = next_pow2(k)
    pairs: List[Tuple[int, int]] = []
    oddeven_sort_pairs(0, p, pairs)
    pos: List = list(range(k)) + [_PAD] * (p - k)
    n_ce = 0
    for i, j in pairs:
        a, b = pos[i], pos[j]
        if b is _PAD:
            continue
        if a is _PAD:
            pos[i], pos[j] = b, _PAD
            continue
        n_ce += 1
        pos[i] = pos[j] = -1  # real nodes; ids irrelevant for counting
    return 2 * n_ce


def full_merge_minmax_count(k: int) -> int:
    """min/max ops of the historical odd-even merge baseline.

    Counts the exact network :func:`median.vector_median_filter_merge`
    traces: k runs padded to ``p = next_pow2(k)`` +inf slots, ``p`` runs
    total, the staged width-doubling merge run to a full sort, rank k²//2
    read at the end — every fold-surviving compare-exchange emitting both
    outputs, every window re-merged (no cross-window sharing). This is the
    denominator of the pruning claim, so it must count the baseline as
    traced, not as the planner would restructure it.
    """
    if k == 1:
        return 0
    p_run = next_pow2(k)
    total = p_run * p_run
    pos: List = []
    for j in range(k):
        pos.extend([j] * k)
        pos.extend([_PAD] * (p_run - k))
    pos.extend([_PAD] * ((p_run - k) * p_run))
    n_ce = 0
    width = p_run
    while width < total:
        pairs: List[Tuple[int, int]] = []
        for lo in range(0, total, 2 * width):
            oddeven_merge_pairs(lo, 2 * width, 1, pairs)
        for i, j in pairs:
            a, b = pos[i], pos[j]
            if b is _PAD:
                continue
            if a is _PAD:
                pos[i], pos[j] = b, _PAD
                continue
            n_ce += 1
        width *= 2
    return 2 * n_ce


@functools.lru_cache(maxsize=None)
def comparator_counts(k: int) -> Dict[str, int]:
    """min/max op counts of the k x k median's merge phase, full vs pruned.

    ``merge_minmax_full`` is the odd-even merge baseline (every
    compare-exchange emits both outputs, every window re-merged);
    ``merge_minmax_pruned`` the liveness-pruned selection network the XLA
    path traces; ``merge_minmax_pruned_shared`` the additionally
    cross-window-shared plan the Pallas kernel runs. ``presort_minmax``
    is the per-column vertical sort all paths share. Counts are the ops
    the respective program executes per pixel.
    """
    pruned = median_merge_plan(k, prune=True, share=False)
    shared = median_merge_plan(k, prune=True, share=True)
    return {
        "window": k,
        "presort_minmax": presort_minmax_count(k),
        "merge_minmax_full": full_merge_minmax_count(k),
        "merge_minmax_pruned": len(pruned.ops),
        "merge_minmax_pruned_shared": len(shared.ops),
    }
