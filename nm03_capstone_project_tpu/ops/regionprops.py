"""Connected components + region properties.

TPU-native equivalent of FAST ``RegionProperties`` — declared in the
reference's API surface (FAST_directives.hpp:24) but never instantiated, so
carried here as an optional op per SURVEY.md section 2.2.

Connected-component labeling is a poor fit for sequential union-find; on TPU
it is a *fixpoint of min-label propagation*: every foreground pixel starts
with its linear index as label, each step takes the minimum over its
(4- or 8-connected) neighborhood, and the fixpoint assigns every component
the smallest linear index it contains. Same lax.while_loop-of-fori_loop
shape as ops.region_growing (amortized convergence checks), fully jittable
and vmappable.

Per-region statistics are masked reductions into fixed-size slots
(jit-friendly static shapes): ``region_properties`` ranks components by area
and returns the top ``max_regions``.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.ops.neighborhood import (
    footprint_offsets,
    shifted_stack,
)


def _neighbor_min(lab: jax.Array, connectivity: int) -> jax.Array:
    """Min label over the 3x3 cross (4-conn) or full 3x3 (8-conn) window."""
    big = jnp.iinfo(lab.dtype).max
    offs = footprint_offsets(3, "cross" if connectivity == 4 else "box")
    # maxval border: the out-of-canvas padding never wins the min, so
    # opposite edges cannot connect
    return shifted_stack(
        lab, offs, pad_mode="constant", constant_values=big
    ).min(axis=0)


def _minmax_box(m: jax.Array, ys: jax.Array, xs: jax.Array) -> jax.Array:
    """(y0, x0, y1, x1) of True pixels via masked min/max reductions over the
    trailing two axes; garbage (inf-derived) where ``m`` is all-False —
    callers mask that case out."""
    return jnp.stack(
        [
            jnp.min(jnp.where(m, ys, jnp.inf), axis=(-2, -1)),
            jnp.min(jnp.where(m, xs, jnp.inf), axis=(-2, -1)),
            jnp.max(jnp.where(m, ys, -jnp.inf), axis=(-2, -1)),
            jnp.max(jnp.where(m, xs, -jnp.inf), axis=(-2, -1)),
        ],
        axis=-1,
    )


def bounding_box(mask: jax.Array) -> jax.Array:
    """(y0, x0, y1, x1) inclusive bounds of ALL foreground pixels; -1s if empty.

    TPU-native equivalent of FAST ``BoundingBox`` (declared in the
    reference's API surface, FAST_directives.hpp:2, never instantiated) —
    the whole-mask box, as opposed to :func:`region_properties` which boxes
    each component separately. jit/vmap-friendly (static output shape).
    """
    m = mask.astype(bool)
    h, w = m.shape[-2], m.shape[-1]
    ys = jnp.arange(h, dtype=jnp.float32)[:, None]
    xs = jnp.arange(w, dtype=jnp.float32)[None, :]
    any_fg = jnp.any(m, axis=(-2, -1))
    box = _minmax_box(m, ys, xs)
    return jnp.where(any_fg[..., None], box, -1.0).astype(jnp.int32)


def connected_components(
    mask: jax.Array,
    connectivity: int = 4,
    block_iters: int = 16,
    max_iters: int | None = None,
) -> jax.Array:
    """Label connected components of a boolean mask.

    Returns int32 labels shaped like ``mask``: 0 for background, and for
    each component the (1-based) smallest linear index it contains. Labels
    are unique per component but not consecutive; see
    :func:`region_properties` for ranked per-region statistics.

    ``max_iters`` defaults to h*w — an upper bound on any propagation path
    (e.g. a serpentine component), so the fixpoint always converges unless
    explicitly capped lower.
    """
    if connectivity not in (4, 8):
        raise ValueError(f"connectivity must be 4 or 8, got {connectivity}")
    m = mask.astype(bool)
    h, w = m.shape[-2], m.shape[-1]
    if max_iters is None:
        max_iters = h * w
    big = jnp.iinfo(jnp.int32).max
    idx = (jnp.arange(h * w, dtype=jnp.int32) + 1).reshape(h, w)
    idx = jnp.broadcast_to(idx, m.shape)
    lab = jnp.where(m, idx, big)

    def block(lab):
        def step(_, l):
            prop = _neighbor_min(l, connectivity)
            return jnp.where(m, prop, big)

        return jax.lax.fori_loop(0, block_iters, step, lab)

    def cond(state):
        lab, prev, it = state
        return (it < max_iters) & jnp.any(lab != prev)

    def body(state):
        lab, _, it = state
        return block(lab), lab, it + block_iters

    lab, _, _ = jax.lax.while_loop(cond, body, (block(lab), lab, 0))
    return jnp.where(m, lab, 0).astype(jnp.int32)


def region_properties(
    mask: jax.Array,
    connectivity: int = 4,
    max_regions: int = 8,
) -> Dict[str, jax.Array]:
    """Area / centroid / bbox of the ``max_regions`` largest components.

    All outputs have static shapes (jit/vmap-friendly). Slots beyond the
    number of actual components have area 0 and -1 elsewhere.

    Returns dict of arrays, each with leading dim ``max_regions``:
      area      — pixel count, int32, descending
      centroid  — (y, x) float32 mean position
      bbox      — (y0, x0, y1, x1) int32 inclusive bounds
      label     — the component's label in :func:`connected_components`
    """
    if mask.ndim != 2:
        raise ValueError(
            f"region_properties expects a single (H, W) mask, got "
            f"{mask.shape}; use jax.vmap for batches"
        )
    labels = connected_components(mask, connectivity)
    h, w = labels.shape[-2], labels.shape[-1]
    flat = labels.reshape(-1)

    # rank distinct labels by area: count occurrences of every linear-index
    # label via a length-(h*w+1) bincount (static shape), then top-k
    counts = jnp.zeros(h * w + 1, jnp.int32).at[flat].add(1)
    counts = counts.at[0].set(0)  # background doesn't rank
    k = min(max_regions, h * w + 1)  # top_k caps at the candidate count
    area, top_labels = jax.lax.top_k(counts, k)
    if k < max_regions:
        area = jnp.pad(area, (0, max_regions - k))
        top_labels = jnp.pad(top_labels, (0, max_regions - k))
    valid = area > 0
    top_labels = jnp.where(valid, top_labels, -1)

    ys = jnp.arange(h, dtype=jnp.float32)[:, None]
    xs = jnp.arange(w, dtype=jnp.float32)[None, :]

    def props(label, a, v):
        m = labels == label
        af = jnp.maximum(a, 1).astype(jnp.float32)
        cy = jnp.sum(jnp.where(m, ys, 0.0)) / af
        cx = jnp.sum(jnp.where(m, xs, 0.0)) / af
        bbox = _minmax_box(m, ys, xs).astype(jnp.int32)
        centroid = jnp.stack([cy, cx])
        return (
            jnp.where(v, centroid, -1.0),
            jnp.where(v, bbox, -1),
        )

    centroid, bbox = jax.vmap(props)(top_labels, area, valid)
    return {
        "area": area,
        "centroid": centroid,
        "bbox": bbox,
        "label": top_labels,
    }
