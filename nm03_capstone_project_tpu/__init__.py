"""nm03_capstone_project_tpu — a TPU-native medical-image-processing framework.

A brand-new JAX / XLA / Pallas implementation of the capabilities of the
reference system calebhabesh/NM03-Capstone-Project ("Optimizing Medical Image
Processing: A Hybrid Approach with the FAST Framework and OpenMP"): a
fault-tolerant brain-tumor segmentation pipeline over DICOM cohorts —

    import DICOM -> intensity normalization -> intensity clipping
    -> 7x7 vector median filter -> unsharp sharpening
    -> seeded region growing (adaptive seed grid)
    -> uint8 cast -> morphology (dilation / erosion)
    -> 512x512 overlay JPEG export

re-designed TPU-first:

* The reference's FAST/OpenCL ProcessObjects (lazy DAG + eager per-stage
  ``update()``, reference ``src/test/test_pipeline.cpp:53-125``) become pure
  functions fused under a single ``jax.jit``.
* The reference's OpenMP batch loop (``src/parallel/main_parallel.cpp:336``)
  becomes ``jax.vmap`` over a padded slice stack plus a
  ``jax.sharding.Mesh`` over TPU chips.
* The hot per-pixel kernels (vector median filter, seeded region growing)
  have Pallas TPU implementations alongside portable XLA reference
  implementations.
* DICOM decode feeds an async host->HBM prefetch queue so compute never
  stalls on I/O; a native C++ loader backs the queue.

Subpackage map (mirrors SURVEY.md section 7):

* :mod:`~nm03_capstone_project_tpu.core`     — image containers, padding/dtype policy
* :mod:`~nm03_capstone_project_tpu.ops`      — the operator set (elementwise, median, sharpen, morphology, region growing, seeds)
* :mod:`~nm03_capstone_project_tpu.pipeline` — fused slice/volume pipelines
* :mod:`~nm03_capstone_project_tpu.data`     — dataset discovery, DICOM-lite IO, synthetic cohorts, prefetch
* :mod:`~nm03_capstone_project_tpu.render`   — 512x512 letterbox render + overlay + JPEG export
* :mod:`~nm03_capstone_project_tpu.parallel` — device mesh, batch sharding, z-axis halo exchange
* :mod:`~nm03_capstone_project_tpu.models`   — model families built on the op set
* :mod:`~nm03_capstone_project_tpu.utils`    — reporter/logging, timing, manifest/resume, profiling
* :mod:`~nm03_capstone_project_tpu.cli`      — the three entry points (test-pipeline, sequential, parallel)
"""

__version__ = "0.1.0"

from nm03_capstone_project_tpu.config import PipelineConfig  # noqa: F401
