"""Deadline-guarded dispatch with one-way CPU degradation.

The tunnel failure mode docs/OPERATIONS.md documents — a device dispatch
that never returns, holding the chip claim for hours — cannot be handled by
containment (there is no exception to catch) or by retry (the call never
comes back). The :class:`DispatchSupervisor` handles it the only way a
client can: run the dispatch on an expendable worker thread, give it a
wall-clock :class:`~.policy.Deadline`, and when the deadline expires,
*abandon* the thread (daemonized, cancel-signalled) and flip the rest of
the run to the CPU backend so the cohort finishes instead of wedging.

The degradation ladder, in order:

1. dispatch succeeds — the normal path;
2. dispatch raises a retryable (transient/XLA-runtime) error — retried
   under the :class:`~.policy.RetryPolicy` within the same deadline;
3. retries exhausted, or the deadline expires — the supervisor marks the
   run degraded (``pipeline_degraded_total`` + a WARNING ``degraded``
   event, once per run) and reruns the work through the caller-supplied
   CPU fallback; every later dispatch goes straight to the fallback;
4. with ``--no-fallback-cpu``, step 3 raises :class:`DeadlineExceeded`
   into the per-patient containment instead — the run still finishes, by
   failing fast rather than by degrading.

With ``dispatch_timeout_s == 0`` (the default) no worker threads exist and
dispatches run inline on the caller's thread — the legacy path, except that
transient device errors now retry under the policy instead of failing the
slice/batch outright.
"""

from __future__ import annotations

import threading
import time
from contextlib import nullcontext
from typing import Callable, Optional

from nm03_capstone_project_tpu.resilience.policy import (
    Deadline,
    DeadlineExceeded,
    ResilienceConfig,
    RetryPolicy,
    is_retryable,
)
from nm03_capstone_project_tpu.utils.sanitize import guard_dispatch


class DispatchSupervisor:
    """Supervises every device-touching step of one driver run."""

    def __init__(
        self,
        cfg: ResilienceConfig,
        retry: Optional[RetryPolicy] = None,
        obs=None,
        emit_degraded: bool = True,
    ):
        """``emit_degraded=False`` makes the degradation transition QUIET:
        the one-way flag still flips (and ``run`` still raises / falls
        back identically), but no ``degraded`` event, no
        ``pipeline_degraded_total`` increment, and no flight-recorder
        auto-dump fire. The serving executor runs one supervisor per
        replica lane in this mode — a single lane expiring its deadline
        is a lane *quarantine* (serving/lanes.py owns that telemetry),
        not a process-wide degradation; the process-level event fires
        only when the last healthy lane goes."""
        self.cfg = cfg
        self.retry = retry or cfg.make_retry_policy()
        self.obs = obs
        self.emit_degraded = bool(emit_degraded)
        self._lock = threading.Lock()
        self.degraded = False
        self.degraded_cause: Optional[str] = None

    @property
    def supervised(self) -> bool:
        return self.cfg.dispatch_timeout_s > 0

    # -- the one entry point -----------------------------------------------

    def run(
        self,
        primary: Callable[[], object],
        fallback: Optional[Callable[[], object]] = None,
        pre: Optional[Callable[[Optional[threading.Event]], None]] = None,
        label: str = "dispatch",
        staged_inputs: bool = False,
    ):
        """Run ``primary()`` under supervision; degrade to ``fallback()``.

        ``primary`` must perform the dispatch AND the device fetch, returning
        host-side results — the fetch is as wedgeable as the dispatch, so it
        must live inside the deadline. ``fallback`` recomputes the same
        result on the CPU backend from host-side inputs (never from device
        arrays: fetching those could hang on the very wedge being escaped).
        ``pre`` is the fault-injection hook; it receives the attempt's
        cancel event so an injected hang dies with the abandoned thread.

        ``staged_inputs`` declares that the primary's inputs were already
        device_put — under ``--sanitize`` the supervised worker thread then
        re-arms the (thread-local) upload guard around the primary, so a
        hidden per-dispatch re-stage raises even in the supervised
        configuration. Both batch drivers stage through the ingest
        pipeline and pass True; callers whose primaries upload host
        arrays by design (the serving executor) leave it False.
        """
        if self.degraded:
            if fallback is not None and self.cfg.fallback_cpu:
                return fallback()
            raise DeadlineExceeded(
                f"device path degraded ({self.degraded_cause}) and CPU "
                "fallback is disabled"
            )
        if not self.supervised:
            # inline path: no threads, no deadline — the retry policy sits
            # between a transient device error and failure, and exhausted
            # retries still degrade to the CPU fallback (device-lost
            # without a deadline is still device-lost)
            def attempt():
                if pre is not None:
                    pre(None)
                return primary()

            try:
                return self.retry.call(attempt, cause=label, obs=self.obs)
            except BaseException as e:  # noqa: BLE001 — classified below
                if is_retryable(e):
                    return self._degrade(
                        label, "device_lost", fallback, timeout_s=0.0, error=e
                    )
                raise

        deadline = Deadline.start(self.cfg.dispatch_timeout_s)
        attempt = 0
        while True:
            status, value = self._attempt(
                primary, pre, deadline, staged_inputs=staged_inputs
            )
            if status == "ok":
                return value
            if status == "timeout":
                return self._degrade(
                    label, "deadline", fallback, timeout_s=deadline.budget_s
                )
            err = value  # status == "err"
            if not is_retryable(err):
                raise err  # deterministic failure: per-slice containment's job
            attempt += 1
            delay = self.retry.delay_s(label, attempt)
            if (
                attempt > self.retry.retry_max
                or not self.retry.try_acquire(label)
                or delay >= deadline.remaining()
            ):
                return self._degrade(
                    label,
                    "device_lost",
                    fallback,
                    timeout_s=deadline.budget_s,
                    error=err,
                )
            if self.obs is not None:
                self.obs.retry(
                    cause=label,
                    attempt=attempt,
                    error_class=type(err).__name__,
                    backoff_s=round(delay, 4),
                )
            time.sleep(delay)

    # -- internals ---------------------------------------------------------

    def _attempt(self, primary, pre, deadline: Deadline, staged_inputs=False):
        box: dict = {}
        cancel = threading.Event()

        def work():
            try:
                # --sanitize: the transfer guard is thread-local, so a
                # caller-side guard_dispatch() does not reach this worker
                # thread — re-arm it here (only for staged-input callers)
                # or the supervised configuration silently skips the
                # check. No-op (and jax-free) when sanitize is off.
                guard = guard_dispatch() if staged_inputs else nullcontext()
                with guard:
                    if pre is not None:
                        pre(cancel)
                    box["out"] = primary()
            except BaseException as e:  # noqa: BLE001 — crosses the thread
                box["err"] = e

        t = threading.Thread(target=work, daemon=True, name="nm03-dispatch")
        t.start()
        t.join(timeout=max(deadline.remaining(), 0.0))
        if t.is_alive():
            # abandon, never kill: killing a client mid-TPU-op can wedge the
            # tunnel for the next user (docs/OPERATIONS.md). The daemon
            # thread dies with the process; injected hangs honor `cancel`.
            cancel.set()
            return ("timeout", None)
        if "err" in box:
            return ("err", box["err"])
        return ("ok", box.get("out"))

    def _degrade(self, label, cause, fallback, timeout_s: float, error=None):
        first = False
        with self._lock:
            if not self.degraded:
                self.degraded = True
                self.degraded_cause = cause
                first = True
        if first and not self.emit_degraded:
            first = False  # quiet mode: the caller owns transition telemetry
        if first and self.obs is not None:
            try:
                self.obs.degraded(
                    cause=cause,
                    site=label,
                    timeout_s=timeout_s,
                    error_class=type(error).__name__ if error else None,
                )
            except Exception:  # noqa: BLE001 — telemetry never costs the run
                pass
        if first:
            # the degradation transition IS the post-mortem moment: dump
            # the flight-recorder rings (every thread's recent events and
            # spans, in-flight trace ids included) while the evidence is
            # still in memory. Inert unless a dump dir is configured
            # (nm03-serve --flight-dir / NM03_FLIGHTREC_DIR); obs.flightrec
            # is stdlib-only, so this import keeps resilience jax-free.
            try:
                from nm03_capstone_project_tpu.obs import flightrec

                flightrec.auto_dump(reason=f"degraded_{cause}")
            except Exception:  # noqa: BLE001 — capture is best-effort
                pass
        if fallback is not None and self.cfg.fallback_cpu:
            return fallback()
        if error is not None:
            raise error
        raise DeadlineExceeded(
            f"{label} exceeded its {timeout_s:.1f}s deadline and CPU "
            "fallback is disabled"
        )
