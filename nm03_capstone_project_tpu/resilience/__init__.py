"""Resilience subsystem: supervised execution for the batch drivers.

Four pillars (docs/RESILIENCE.md has the failure-taxonomy → policy → flag →
metric table):

* :mod:`~nm03_capstone_project_tpu.resilience.policy` — :class:`RetryPolicy`
  (exponential backoff, deterministic jitter, per-cause run budgets) and
  :class:`Deadline` (wall-clock budget per device dispatch batch);
* :mod:`~nm03_capstone_project_tpu.resilience.supervisor` —
  :class:`DispatchSupervisor`, which abandons a wedged dispatch at its
  deadline and flips the run to the CPU backend (graceful degradation);
* :mod:`~nm03_capstone_project_tpu.resilience.faultinject` —
  :class:`FaultPlan`, the seedable deterministic chaos layer that makes
  every containment claim a test;
* :mod:`~nm03_capstone_project_tpu.resilience.journal` —
  :class:`PatientJournal`, slice-grain crash-safe resume.

jax-free at import time: bench.py's orchestrator (which must never import
jax) and pure-host tooling can use the policy objects directly.
"""

from nm03_capstone_project_tpu.resilience.faultinject import (  # noqa: F401
    ENV_VAR as FAULT_PLAN_ENV,
    FaultAbandoned,
    FaultPlan,
    FaultRule,
    InjectedDecodeError,
    InjectedExportError,
    InjectedTransientError,
    corrupt_bytes,
    deliver_sigterm,
    execute_hang,
)
from nm03_capstone_project_tpu.resilience.journal import (  # noqa: F401
    JOURNAL_NAME,
    PatientJournal,
)
from nm03_capstone_project_tpu.resilience.policy import (  # noqa: F401
    Deadline,
    DeadlineExceeded,
    ResilienceConfig,
    RetryPolicy,
    TransientDeviceError,
    is_retryable,
)
from nm03_capstone_project_tpu.resilience.supervisor import (  # noqa: F401
    DispatchSupervisor,
)
