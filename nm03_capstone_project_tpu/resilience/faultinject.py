"""Seedable, deterministic fault injection for the batch drivers.

Every containment claim in this repo ("a corrupt slice never kills a cohort
run", "an export failure is counted, not propagated", "a wedged dispatch
degrades to CPU") was previously testable only by monkeypatching internals.
A :class:`FaultPlan` makes each claim a *chaos test*: a JSON plan names the
site, the kind of fault, and the exact slice/patient/batch it hits, and the
drivers consult the plan at their injection points. Zero overhead when off —
the drivers hold ``None`` and never call in.

Activation (either):

* ``--fault-plan SPEC`` on the batch drivers (CLI flag), or
* ``NM03_FAULT_PLAN=SPEC`` in the environment (reaches subprocess workers,
  e.g. bench.py's, without flag plumbing).

``SPEC`` is inline JSON (starts with ``{``) or a path to a JSON file::

    {"seed": 7, "faults": [
      {"site": "decode",   "kind": "error",    "stem": "1-02"},
      {"site": "decode",   "kind": "corrupt",  "stem": "1-03"},
      {"site": "dispatch", "kind": "hang",     "index": 0, "hang_s": 120},
      {"site": "dispatch", "kind": "transient","count": 2},
      {"site": "export",   "kind": "io_error", "stem": "1-04"},
      {"site": "export",   "kind": "sigterm",  "after": 4}
    ]}

Selectors (``patient``, ``stem``, ``index``, ``lane`` — the last for the
serving fleet's dispatch site, so a chaos drill can deterministically wedge
one chosen replica lane) restrict where a rule fires;
``after`` skips the first N-1 matching checks (1-based ordinal), ``count``
caps total fires (default unlimited), and ``rate`` fires probabilistically —
with the draw derived from (plan seed, rule, site, selector values), so the
same plan against the same cohort injects the same faults regardless of
thread scheduling or run-to-run ordering.

Kinds by site:

* ``decode``:   ``error`` (raise before decode), ``corrupt`` (feed the real
  parser deterministically corrupted file bytes — exercises the actual
  rejection path, not a mock);
* ``dispatch``: ``transient`` (a retryable :class:`TransientDeviceError`),
  ``hang`` (block ``hang_s`` seconds, the tunnel-wedge simulation the
  dispatch deadline exists for);
* ``export``:   ``io_error`` (raise before the JPEG pair writes),
  ``sigterm`` (deliver SIGTERM to this process — the crash-safe-resume
  drill);
* ``cache``:    ``io_error`` (abort a persistent compile-cache entry write
  — the next start recompiles instead of loading; ``stem`` selects the
  entry filename);
* ``ingest``:   ``decode_error`` (fail one work item on the streaming
  ingest's decode pool — contained, counted, never propagated),
  ``stall`` (wedge the stager ``hang_s`` seconds — the backpressure
  drill for the staging ring);
* ``fleet``:    ``replica_unreachable`` (the router's health poll for one
  chosen replica behaves as connection-refused; ``stem`` = the replica's
  host:port — the deterministic ejection drill), ``proxy_io_error``
  (abort one proxied request mid-flight; ``index`` = proxied-request
  ordinal — the deterministic failover drill).

Injected faults are observable: every fire increments
``resilience_faults_injected_total{site,kind}`` and emits a
``fault_injected`` event when the caller passes its RunContext.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from nm03_capstone_project_tpu.resilience.policy import TransientDeviceError

ENV_VAR = "NM03_FAULT_PLAN"

SITES = ("decode", "dispatch", "export", "cache", "ingest", "fleet", "volume")
KINDS_BY_SITE = {
    "decode": ("error", "corrupt"),
    "dispatch": ("transient", "hang"),
    "export": ("io_error", "sigterm"),
    # the cache site covers both cache tiers and disambiguates with
    # fire()'s `kinds` filter, like the fleet site's pair. io_error is
    # the persistent COMPILE cache's store path (compilehub/persist.py):
    # it aborts the entry write, proving a failed persist degrades to a
    # plain recompile on the next start — never a torn entry (the write
    # itself is atomic; `stem` selects the entry filename). corrupt_entry
    # is the RESULT tier's read path (ISSUE 19, cache/store.py
    # verify-on-read): the lookup sees one flipped byte, the digest check
    # evicts the entry and reports a miss — a corrupt entry costs one
    # recompute, never a wrong mask (`stem` selects the result-key digest)
    "cache": ("io_error", "corrupt_entry"),
    # the streaming-ingest pipeline (ingest/, ISSUE 11): `decode_error`
    # fails one work item on the decode pool (contained as an
    # IngestFailure record the driver counts); `stall` wedges the stager
    # for hang_s — the drill proving ring backpressure holds (decode
    # blocks, nothing reorders, the run completes late, never wrong).
    # `index` selects the work item (batch index for the parallel driver,
    # slice index for the sequential one).
    "ingest": ("decode_error", "stall"),
    # the replica-fleet front-end (fleet/, ISSUE 13): `replica_unreachable`
    # makes the router's health poll for one chosen replica behave as
    # connection-refused (`stem` selects the replica's host:port label) —
    # the deterministic ejection drill; `proxy_io_error` aborts one
    # proxied request mid-flight on its way to a replica (`index` selects
    # the proxied-request ordinal) — the deterministic failover drill.
    # The router's two injection points share this site and disambiguate
    # with fire()'s `kinds` filter, so one kind's rules never consume the
    # other's after/count budget.
    "fleet": ("replica_unreachable", "proxy_io_error"),
    # the whole-volume gang lane (serving/volumes.py, ISSUE 15):
    # `dispatch_error` fails one supervised mesh-wide dispatch as a
    # retryable device error — with a `lane` selector the gang treats it
    # as that lane's death, quarantines it, and re-meshes the retry onto
    # the survivors (the lane-death-mid-volume drill); without `lane` the
    # failure is unattributable and the gang sheds honestly with
    # Retry-After rather than guess. `index` selects the volume-request
    # ordinal.
    "volume": ("dispatch_error",),
}


class InjectedDecodeError(RuntimeError):
    """An injected per-slice decode failure (contained like a real one)."""


class InjectedExportError(OSError):
    """An injected export I/O failure (contained like a real one)."""


class InjectedTransientError(TransientDeviceError):
    """An injected retryable device error."""


class FaultAbandoned(RuntimeError):
    """Raised inside an abandoned (deadline-expired) hang so the orphaned
    worker thread dies instead of proceeding to the real dispatch."""


@dataclass
class FaultRule:
    site: str
    kind: str
    patient: Optional[str] = None
    stem: Optional[str] = None
    index: Optional[int] = None
    # replica-lane selector (dispatch site, serving fleet): a chaos drill
    # can deterministically wedge ONE chosen lane of a multi-chip replica
    # ({"site": "dispatch", "kind": "hang", "lane": 2}); checks that carry
    # no lane (the batch drivers) never match a lane-selected rule
    lane: Optional[int] = None
    after: Optional[int] = None  # fire from the Nth matching check (1-based)
    count: Optional[int] = None  # max fires; None = unlimited
    rate: Optional[float] = None  # per-check probability (seeded draw)
    hang_s: float = 60.0
    # mutable bookkeeping (guarded by the plan's lock)
    _seen: int = field(default=0, repr=False)
    _fired: int = field(default=0, repr=False)

    def validate(self) -> "FaultRule":
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r} (want {SITES})")
        if self.kind not in KINDS_BY_SITE[self.site]:
            raise ValueError(
                f"kind {self.kind!r} invalid for site {self.site!r} "
                f"(want one of {KINDS_BY_SITE[self.site]})"
            )
        if self.rate is not None and not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {self.rate}")
        if self.hang_s < 0:
            raise ValueError(f"hang_s must be >= 0, got {self.hang_s}")
        return self

    def selectors_match(self, patient=None, stem=None, index=None, lane=None) -> bool:
        """Selector-only match (no ordinal/count/rate state consulted)."""
        if self.patient is not None and self.patient != patient:
            return False
        if self.stem is not None and self.stem != stem:
            return False
        if self.index is not None and self.index != index:
            return False
        if self.lane is not None and self.lane != lane:
            return False
        return True


class FaultPlan:
    """A parsed, thread-safe fault plan; drivers hold ``None`` when off."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.rules = [r.validate() for r in rules]
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._sites = frozenset(r.site for r in self.rules)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_spec(cls, spec) -> Optional["FaultPlan"]:
        """Build from a dict, inline-JSON string, or path; None stays None."""
        if spec is None:
            return None
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            text = spec if spec.lstrip().startswith("{") else None
            if text is None:
                with open(spec) as f:
                    text = f.read()
            try:
                spec = json.loads(text)
            except json.JSONDecodeError as e:
                raise ValueError(f"fault plan is not valid JSON: {e}") from e
        if not isinstance(spec, dict):
            raise ValueError(f"fault plan must be a JSON object, got {type(spec)}")
        known = {"site", "kind", "patient", "stem", "index", "lane", "after",
                 "count", "rate", "hang_s"}
        rules = []
        for i, entry in enumerate(spec.get("faults", [])):
            if not isinstance(entry, dict):
                raise ValueError(f"faults[{i}] is not an object")
            unknown = set(entry) - known
            if unknown:
                raise ValueError(f"faults[{i}] has unknown keys {sorted(unknown)}")
            rules.append(FaultRule(**entry))
        return cls(rules, seed=spec.get("seed", 0))

    @classmethod
    def from_env(cls, environ=os.environ) -> Optional["FaultPlan"]:
        return cls.from_spec(environ.get(ENV_VAR) or None)

    # -- matching ----------------------------------------------------------

    def has_site(self, site: str) -> bool:
        return site in self._sites

    def routes_decode(self, patient=None, stem=None, index=None) -> bool:
        """Selector-only decode-site probe, side-effect free.

        The native batch loader uses this to route fault-matched files
        through the Python decode path (where injection actually happens)
        without consuming the rule's ordinal/count state.
        """
        return any(
            r.site == "decode" and r.selectors_match(patient, stem, index)
            for r in self.rules
        )

    def _draw(
        self, rule_idx: int, rule: FaultRule, patient, stem, index, lane
    ) -> bool:
        # keyed, not sequential: the draw depends only on the plan seed and
        # the check's identity (lane included — a serving fleet's lane
        # thread scheduling must not change which dispatches a rate rule
        # hits), so thread interleaving cannot change the injection set
        rng = random.Random(
            f"{self.seed}:{rule_idx}:{rule.site}:{patient}:{stem}:{index}:{lane}"
        )
        return rng.random() < rule.rate

    def fire(
        self, site: str, obs=None, patient=None, stem=None, index=None,
        lane=None, lane_only=False, kinds=None,
    ):
        """Return the first rule firing at this check site, else None.

        ``lane_only`` restricts the check to rules that EXPLICITLY select a
        lane — rules without a ``lane`` selector are skipped entirely
        (their ordinal/budget state untouched). The serving probation
        probes use it: an off-request-path canary must keep failing on a
        deliberately-wedged chip, but must never consume a generic
        dispatch rule's ``count``/``after`` budget meant for request
        traffic.

        ``kinds`` restricts the check to rules of the listed kinds, with
        the same budget-untouched skip semantics. It exists for sites
        whose kinds live at DIFFERENT call points (the fleet router's
        health poll vs its proxy path): without it, a ``proxy_io_error``
        rule would match — and consume its ``count`` budget at — every
        health-poll check it was never meant for.

        Consumes ordinal (``after``) and budget (``count``) state; emits the
        ``resilience_faults_injected_total`` counter + ``fault_injected``
        event through ``obs`` when given. The caller maps rule.kind to the
        actual fault (raise / hang / corrupt / SIGTERM).
        """
        if site not in self._sites:
            return None
        hit = None
        with self._lock:
            for i, r in enumerate(self.rules):
                if lane_only and r.lane is None:
                    continue
                if kinds is not None and r.kind not in kinds:
                    continue
                if r.site != site or not r.selectors_match(
                    patient, stem, index, lane
                ):
                    continue
                r._seen += 1
                if r.after is not None and r._seen < r.after:
                    continue
                if r.count is not None and r._fired >= r.count:
                    continue
                if r.rate is not None and not self._draw(
                    i, r, patient, stem, index, lane
                ):
                    continue
                r._fired += 1
                hit = r
                break
        if hit is not None and obs is not None:
            try:
                obs.fault_injected(
                    site=site, kind=hit.kind,
                    patient=patient, stem=stem, index=index, lane=lane,
                )
            except Exception:  # noqa: BLE001 — telemetry never blocks a fault
                pass
        return hit

    def fired_total(self) -> int:
        with self._lock:
            return sum(r._fired for r in self.rules)


# -- fault actions ----------------------------------------------------------


def corrupt_bytes(raw: bytes, seed: int, key: str = "") -> bytes:
    """Deterministically corrupt a DICOM file image in memory.

    Overwrites a 64-byte window over the Part-10 magic and the start of the
    file meta group with seeded garbage AND truncates the tail (so even a
    parse that realigns onto valid elements hits a PixelData length
    overrun) — the *real* parser exercises its rejection path on every
    input, without touching the file on disk.
    """
    rng = random.Random(f"{seed}:corrupt:{key}")
    start = min(128, max(0, len(raw) - 1))
    garbage = bytes(rng.randrange(1, 255) for _ in range(64))
    out = raw[:start] + garbage + raw[start + len(garbage):]
    return out[: max(192, len(out) // 2)]


def execute_hang(rule: FaultRule, cancel: Optional[threading.Event] = None) -> None:
    """Simulate a wedged dispatch: block for ``rule.hang_s`` seconds.

    When the supervisor abandons the dispatch (deadline expiry) it sets
    ``cancel``; this raises :class:`FaultAbandoned` so the orphaned worker
    thread exits promptly instead of sleeping out the hang and then running
    the real dispatch whose results nobody will read.
    """
    t_end = time.monotonic() + rule.hang_s
    while time.monotonic() < t_end:
        if cancel is not None:
            if cancel.wait(timeout=0.05):
                raise FaultAbandoned("hang abandoned by dispatch supervisor")
        else:
            time.sleep(min(0.05, max(t_end - time.monotonic(), 0.0)))


def deliver_sigterm() -> None:
    """The crash drill: deliver SIGTERM to this process and wait to die.

    The sleep guarantees the injection point is a hard interruption (the
    default SIGTERM disposition terminates the process before the sleep
    ends); if a test harness traps SIGTERM instead, the fault degrades to a
    raised :class:`InjectedExportError` so the run cannot sail on.
    """
    import signal

    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(10.0)
    raise InjectedExportError("SIGTERM fault delivered but process survived")
