"""Retry and deadline policy objects.

The reference's fault tolerance is containment only: catch, log, move to the
next slice/patient (main_sequential.cpp:267-305). Containment handles
*deterministic* failures (a corrupt file stays corrupt), but the failure
modes this repo actually hits (docs/OPERATIONS.md) are *transient* or
*unbounded*: a device dispatch that errors once and would succeed on retry,
or a tunnel wedge where the dispatch never returns at all. These two policy
objects give those failure modes first-class semantics:

* :class:`RetryPolicy` — bounded exponential backoff with deterministic
  jitter and per-cause run-level retry budgets, so one flapping cause
  cannot spend the whole cohort's wall clock retrying;
* :class:`Deadline` — a wall-clock budget for one device dispatch batch,
  the unit the :class:`~.supervisor.DispatchSupervisor` abandons and
  degrades on when it expires.

This module is jax-free and numpy-free by design: bench.py's orchestrator
(which must never import jax, docs/OPERATIONS.md "Tunnel behavior") and the
unit tests can import it without touching a backend.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Dict, Optional, Tuple, Type


class TransientDeviceError(RuntimeError):
    """A device-side failure worth retrying (and, exhausted, degrading on).

    Raised by the fault-injection layer's ``transient`` kind; real backends
    surface their equivalent as ``XlaRuntimeError``, which the supervisor
    classifies via :func:`is_retryable`.
    """


class DeadlineExceeded(TimeoutError):
    """A supervised dispatch outlived its wall-clock budget."""


def is_retryable(exc: BaseException, extra: Tuple[Type[BaseException], ...] = ()) -> bool:
    """Transient-or-device-runtime classification for dispatch errors.

    Matches :class:`TransientDeviceError` (and subclasses), any class in
    ``extra``, and — by name, so this module stays jax-free — the XLA/PJRT
    runtime error types a lost or wedged backend raises.
    """
    if isinstance(exc, (TransientDeviceError, *extra)):
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
            return True
    return False


@dataclasses.dataclass(frozen=True)
class Deadline:
    """Wall-clock budget for one supervised operation (monotonic clock).

    ``budget_s <= 0`` means *no deadline* (remaining is infinite) so callers
    can thread one object unconditionally.
    """

    budget_s: float
    started_mono: float

    @classmethod
    def start(cls, budget_s: float) -> "Deadline":
        return cls(budget_s=float(budget_s), started_mono=time.monotonic())

    @property
    def enabled(self) -> bool:
        return self.budget_s > 0

    def elapsed(self) -> float:
        return time.monotonic() - self.started_mono

    def remaining(self) -> float:
        if not self.enabled:
            return float("inf")
        return self.budget_s - self.elapsed()

    def expired(self) -> bool:
        return self.enabled and self.remaining() <= 0

    def check(self, what: str = "operation") -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"{what} exceeded its {self.budget_s:.1f}s deadline"
            )


class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter + cause budgets.

    ``retry_max`` is the number of *retries* after the first attempt (0
    disables retrying). ``budget_per_cause`` caps total retries per cause
    string across the whole run — a cohort of thousands of slices must not
    multiply a persistent failure into thousands of backoff waits.

    Jitter is deterministic: the delay for (cause, attempt) is derived from
    ``seed`` alone, so two runs of the same seeded chaos test sleep the same
    schedule (the fault-injection layer's reproducibility contract extends
    to the recovery path).

    Thread-safe: the parallel driver retries from IO-pool threads.
    """

    def __init__(
        self,
        retry_max: int = 2,
        backoff_s: float = 0.05,
        multiplier: float = 2.0,
        max_backoff_s: float = 5.0,
        jitter: float = 0.5,
        budget_per_cause: int = 64,
        seed: int = 0,
        obs=None,
    ):
        if retry_max < 0:
            raise ValueError(f"retry_max must be >= 0, got {retry_max}")
        if backoff_s < 0 or max_backoff_s < 0:
            raise ValueError("backoff times must be >= 0")
        if multiplier < 1.0:
            raise ValueError(f"multiplier must be >= 1, got {multiplier}")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1], got {jitter}")
        self.retry_max = int(retry_max)
        self.backoff_s = float(backoff_s)
        self.multiplier = float(multiplier)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self.budget_per_cause = int(budget_per_cause)
        self.seed = int(seed)
        # default telemetry target for call(): set once by the owning driver
        # so deep callees (the export layer) need not thread a RunContext
        self.obs = obs
        self._lock = threading.Lock()
        self._spent: Dict[str, int] = {}

    # -- schedule ----------------------------------------------------------

    def delay_s(self, cause: str, attempt: int) -> float:
        """Backoff before retry ``attempt`` (1-based) of ``cause``."""
        base = min(
            self.backoff_s * (self.multiplier ** max(attempt - 1, 0)),
            self.max_backoff_s,
        )
        if self.jitter == 0.0 or base == 0.0:
            return base
        rng = random.Random(f"{self.seed}:{cause}:{attempt}")
        # full-jitter fraction: delay in [base*(1-j), base]
        return base * (1.0 - self.jitter * rng.random())

    # -- budget accounting -------------------------------------------------

    def spent(self, cause: str) -> int:
        with self._lock:
            return self._spent.get(cause, 0)

    def try_acquire(self, cause: str) -> bool:
        """Reserve one retry from ``cause``'s run-level budget."""
        with self._lock:
            if self._spent.get(cause, 0) >= self.budget_per_cause:
                return False
            self._spent[cause] = self._spent.get(cause, 0) + 1
            return True

    # -- execution ---------------------------------------------------------

    def call(
        self,
        fn: Callable,
        *args,
        cause: str,
        retryable: Tuple[Type[BaseException], ...] = (),
        obs=None,
        deadline: Optional[Deadline] = None,
        sleep: Callable[[float], None] = time.sleep,
        **kwargs,
    ):
        """Run ``fn(*args, **kwargs)`` under this policy.

        Retries only exceptions :func:`is_retryable` classifies (plus any in
        ``retryable``); everything else propagates on first raise — a
        deterministic failure must stay a contained per-slice failure, not
        spend the backoff schedule. ``obs`` (a RunContext) receives one
        ``retry`` record per actual retry. A ``deadline`` caps the whole
        attempt sequence: no retry is launched past its expiry.
        """
        if obs is None:
            obs = self.obs
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — classified below
                attempt += 1
                if not is_retryable(e, extra=retryable):
                    raise
                if attempt > self.retry_max:
                    raise
                if deadline is not None and deadline.expired():
                    raise
                if not self.try_acquire(cause):
                    raise
                delay = self.delay_s(cause, attempt)
                if deadline is not None and delay >= deadline.remaining():
                    raise
                if obs is not None:
                    obs.retry(
                        cause=cause,
                        attempt=attempt,
                        error_class=type(e).__name__,
                        backoff_s=round(delay, 4),
                    )
                sleep(delay)


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """The driver-facing bundle: one object carries every resilience knob.

    Defaults preserve the pre-resilience behavior exactly: no dispatch
    deadline (0 disables supervision threads entirely), no fault plan, and
    retries only where a transient device error would previously have been
    a hard per-slice/per-patient failure.
    """

    retry_max: int = 2
    retry_backoff_s: float = 0.05
    dispatch_timeout_s: float = 0.0  # 0 = unsupervised (legacy path)
    fallback_cpu: bool = True
    fault_plan: object = None  # Optional[FaultPlan]; object keeps this jax/json-light

    def make_retry_policy(self, seed: int = 0) -> RetryPolicy:
        return RetryPolicy(
            retry_max=self.retry_max,
            backoff_s=self.retry_backoff_s,
            seed=seed,
        )
