"""Per-patient journal of completed stems: crash-safe resume at slice grain.

The manifest (utils/manifest.py) flushes once per *patient*, so a SIGTERM /
kill / wedge mid-patient forgets every slice the interrupted patient already
exported and ``--resume`` redoes them. The journal closes that window: one
append-only JSON-lines file per patient directory, one line per completed
slice, written (and flushed to the OS) the moment the slice's JPEG pair is
verified on disk. On ``--resume`` the driver folds the journal back into the
manifest before computing the todo list.

Crash-safety properties:

* append-only writes of single short lines — a crash can at worst tear the
  FINAL line, which :meth:`entries` skips (every completed line is intact);
* lives inside the patient's output directory, so the fresh-run
  ``clean_directory`` wipe resets it together with the outputs it indexes;
* thread-safe — the parallel driver journals from IO-pool export threads.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Dict

JOURNAL_NAME = "slices.journal"


class PatientJournal:
    """Append-only ``{stem, status}`` JSONL record for one patient dir."""

    def __init__(self, patient_dir: str | os.PathLike):
        self.path = Path(patient_dir) / JOURNAL_NAME
        self._lock = threading.Lock()
        self._fh = None

    def record(self, stem: str, status: str) -> None:
        """Append one completion record and flush it to the OS."""
        line = json.dumps({"stem": str(stem), "status": str(status)}) + "\n"
        with self._lock:
            if self._fh is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                self._fh = open(self.path, "a")
            self._fh.write(line)
            self._fh.flush()

    def record_many(self, stems, status_by_stem: Dict[str, str], default: str) -> None:
        for s in stems:
            self.record(s, status_by_stem.get(s, default))

    def entries(self) -> Dict[str, str]:
        """Replay the journal: {stem: last status}. Torn/corrupt lines (the
        one a crash can leave unfinished) are skipped, not fatal."""
        out: Dict[str, str] = {}
        try:
            with open(self.path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail line from a crash mid-write
                    if isinstance(rec, dict) and "stem" in rec and "status" in rec:
                        out[str(rec["stem"])] = str(rec["status"])
        except OSError:
            return {}
        return out

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self) -> "PatientJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
