"""Benchmark: DICOM slices/sec/chip through the fused segmentation pipeline.

Prints ONE JSON line:
    {"metric": "slices_per_sec_per_chip", "value": N, "unit": "slices/s",
     "vs_baseline": R}

``value`` is the throughput of the full 7-op pipeline (normalize → clip →
7x7 vector median → sharpen → seeded region growing → cast → dilate,
the reference's batch-driver contract, src/sequential/main_sequential.cpp:170-272)
vmapped over a 256x256 slice batch on ONE device of the default jax backend
(the TPU chip under the driver).

``vs_baseline`` is the speedup over the same program executed on the CPU
backend — the stand-in for the reference's OpenMP-parallel CPU driver
(src/parallel/main_parallel.cpp:336; XLA:CPU also uses the host's cores, so
this is parallel-CPU vs one TPU chip, the north-star ratio in BASELINE.json).

Timing methodology: the output is reduced to a scalar checksum ON DEVICE and
the scalar is fetched to host — a device_get is the only synchronization that
is trustworthy on every platform (on the tunneled TPU backend,
``block_until_ready`` returns before execution finishes and a bare sync costs
~66 ms of round-trip latency). ``REPS`` executions are enqueued back-to-back
and synced once; single-device PjRt streams execute FIFO, so fetching each
result after the loop charges the full compute of all reps to the measured
window while amortizing the tunnel latency across them.

All progress chatter goes to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import json
import sys
import time

BATCH = 32
CANVAS = 256
TPU_REPS = 10
CPU_REPS = 2


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _make_batch():
    import numpy as np

    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    pixels = np.stack(
        [
            phantom_slice(CANVAS, CANVAS, seed=i, lesion_radius=0.12 + 0.002 * i)
            for i in range(BATCH)
        ]
    ).astype(np.float32)
    dims = np.full((BATCH, 2), CANVAS, np.int32)
    return pixels, dims


def _bench_on(device, pixels, dims, reps, use_pallas=False) -> float:
    """Slices/sec of the jitted vmapped pipeline on one device.

    ``use_pallas`` routes the hot ops (7x7 median, region growing) through
    the Pallas TPU kernels; lowering failures propagate — the caller decides
    the fallback.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = PipelineConfig(use_pallas=use_pallas)

    def f(px, dm):
        # Scalar checksum: forces the whole pipeline to run, and fetching it
        # is a 4-byte transfer — honest sync without paying a 2 MB pull
        # through the TPU tunnel per rep.
        mask = process_batch(px, dm, cfg)["mask"]
        return mask.astype(jnp.int32).sum()

    px = jax.device_put(jnp.asarray(pixels), device)
    dm = jax.device_put(jnp.asarray(dims), device)
    fn = jax.jit(f)

    t0 = time.perf_counter()
    checksum = int(fn(px, dm))  # device_get = real synchronization
    _log(
        f"{device.platform}{' (pallas)' if use_pallas else ''}: "
        f"compile+first run {time.perf_counter() - t0:.1f}s"
    )
    if checksum <= 0:
        _log("WARNING: pipeline segmented nothing — benchmark suspect")

    t0 = time.perf_counter()
    results = [fn(px, dm) for _ in range(reps)]  # enqueue, FIFO stream
    int(results[-1])  # one sync: FIFO order implies all earlier reps finished
    elapsed = time.perf_counter() - t0
    return BATCH * reps / elapsed, checksum


def main() -> None:
    import jax

    pixels, dims = _make_batch()

    devices = jax.devices()
    main_dev = devices[0]
    # pltpu kernels lower only on TPU hardware ("axon" = TPU via tunnel);
    # never attempt them on GPU/other non-CPU backends
    on_tpu = main_dev.platform in ("tpu", "axon")
    _log(f"default backend: {main_dev.platform} ({len(devices)} devices)")
    pallas_tput = pallas_sum = None
    if on_tpu:
        try:
            pallas_tput, pallas_sum = _bench_on(
                main_dev, pixels, dims, TPU_REPS, use_pallas=True
            )
            _log(f"tpu pallas throughput: {pallas_tput:.2f} slices/s")
        except Exception as e:  # noqa: BLE001 — pallas lowering failure
            _log(f"pallas path failed, using XLA ops only: {e!r:.500}")
    tput, xla_sum = _bench_on(main_dev, pixels, dims, TPU_REPS, use_pallas=False)
    if pallas_tput is not None:
        # only a result-identical pallas run may win the headline number —
        # a miscompiled kernel must not corrupt the benchmark record
        if pallas_sum == xla_sum:
            tput = max(tput, pallas_tput)
        else:
            _log(
                f"pallas checksum {pallas_sum} != xla checksum {xla_sum}; "
                "ignoring pallas throughput"
            )
    _log(f"{main_dev.platform} throughput: {tput:.2f} slices/s")

    vs_baseline = 1.0
    if main_dev.platform != "cpu":
        try:
            cpu_dev = jax.devices("cpu")[0]
            cpu_tput = _bench_on(cpu_dev, pixels, dims, CPU_REPS)
            _log(f"cpu baseline throughput: {cpu_tput:.2f} slices/s")
            vs_baseline = tput / cpu_tput
        except Exception as e:  # no cpu backend reachable — report raw value
            _log(f"cpu baseline unavailable: {e}")

    print(
        json.dumps(
            {
                "metric": "slices_per_sec_per_chip",
                "value": round(tput, 2),
                "unit": "slices/s",
                "vs_baseline": round(vs_baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
