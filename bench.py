"""Benchmark: DICOM slices/sec/chip through the fused segmentation pipeline.

Prints ONE JSON line:
    {"metric": "slices_per_sec_per_chip", "value": N, "unit": "slices/s",
     "vs_baseline": R, "backend": "...", "stages": {...}, ...}

``value`` is the throughput of the full 7-op pipeline (normalize → clip →
7x7 vector median → sharpen → seeded region growing → cast → dilate,
the reference's batch-driver contract, src/sequential/main_sequential.cpp:170-272)
vmapped over a 256x256 slice batch on ONE device of the default jax backend
(the TPU chip under the driver).

``vs_baseline`` is the speedup over the same program executed on the CPU
backend — the stand-in for the reference's OpenMP-parallel CPU driver
(src/parallel/main_parallel.cpp:336; XLA:CPU also uses the host's cores, so
this is parallel-CPU vs one TPU chip, the north-star ratio in BASELINE.json).
The accelerator sweeps batch sizes (ACCEL_BATCH_SWEEP) and the best
slices/s wins; the CPU baseline then runs at the SAME winning batch so the
ratio stays program-for-program.

Robustness architecture (the round-1 lesson, plus the round-2 discovery that
killing a worker mid-TPU-claim wedges the tunnel for everyone after): the
orchestrator process never imports jax. Each measurement runs in a
subprocess with a hard timeout —

* a cheap PROBE worker (devices + tiny jit) gates the expensive run: the
  orchestrator retries the probe with backoff until the tunnel answers, so
  the heavy worker's long timeout is only ever spent on real work, and a
  wedged tunnel costs a few short probe kills (harmless — a hung
  ``jax.devices()`` holds no chip claim yet), not a mid-compile kill;
* the accelerator worker inherits the environment (so the tunneled TPU
  backend registers), gets ONE long-timeout attempt, and appends each
  completed section (xla / pallas / stages) to a results file as it goes —
  a timeout loses only the unfinished section, never the headline;
* the CPU-baseline worker runs with JAX_PLATFORMS=cpu and the TPU tunnel
  env scrubbed, so it can never dial (or hang on) the accelerator;
* whatever happens, the orchestrator emits the JSON line, with a
  ``backend`` field saying what was actually measured and an ``error``
  field when a path was lost.

Timing methodology (inside the workers): the output is reduced to a scalar
checksum ON DEVICE and the scalar is fetched to host — a device_get is the
only synchronization that is trustworthy on every platform (on the tunneled
TPU backend, ``block_until_ready`` returns before execution finishes and a
bare sync costs ~66 ms of round-trip latency). ``reps`` executions are
enqueued back-to-back and synced once; single-device PjRt streams execute
FIFO, so fetching the last result charges the full compute of all reps to
the measured window while amortizing the tunnel latency across them.

The ``stages`` block is the per-stage device-time breakdown (VERDICT round 1
item 7): each pipeline stage jitted and timed in isolation with the same
enqueue-then-sync methodology, plus a qualitative bound classification.

All progress chatter goes to stderr; stdout carries only the JSON line
(workers mark their result line with a sentinel the orchestrator strips).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import signal
import subprocess
import sys
import time

BATCH = 32
# the accelerator worker sweeps these and reports the best slices/s — batch
# size is free to choose when the metric is throughput, and bigger batches
# amortize dispatch/sync better on the chip; the CPU baseline then reruns
# at the winning size so vs_baseline stays a same-program ratio
ACCEL_BATCH_SWEEP = (32, 128, 256)
SCAN_CHUNK = 8  # batches per dispatch in the scan-amortized leg
CANVAS = 256
TPU_REPS = 40
CPU_REPS = 2
STAGE_REPS = 48

PROBE_TIMEOUT_S = 90
PROBE_ATTEMPTS = 6
PROBE_BACKOFF_S = 45

# bench stage names -> the serving ledger's stage vocabulary
# (obs.ledger.STAGES): the device_time_pie record and the perf baseline
# speak the SAME stage names as serving_device_time_share, so check_perf
# can compare a drill's pie against a bench-derived baseline key-by-key
BENCH_STAGE_TO_LEDGER = {
    "normalize_clip": "normalize",
    "median7": "median7",
    "sharpen": "sharpen",
    "region_grow": "grow",
    "cast_dilate": "morph",
    "render": "render",
}
# Vigil probe backoff (r05 lesson: vigil probe 4 burned its full 90 s
# timeout and the zshard section was then skipped for budget): each
# consecutive vigil-probe TIMEOUT halves the next probe's timeout down to
# this floor — a wedged tunnel fails fast, a recovering one still gets a
# real probe (a healthy backend answers a probe in seconds), and a
# late-recovery success resets to the full timeout. No hard retry cap: the
# r03 lesson is that a recovery in the final minutes still wins the round,
# and with 20 s probes the whole vigil tail costs less than one old probe.
VIGIL_PROBE_MIN_TIMEOUT_S = 20
# Wall reserved so the (tunnel-independent) zshard scaling section still
# runs after a fruitless vigil — r05 skipped it entirely.
ZSHARD_RESERVE_S = 150.0
ACCEL_TIMEOUT_S = 900  # ONE attempt; killing mid-compile wedges the tunnel
CPU_TIMEOUT_S = 420
# When the initial probe round finds the tunnel wedged, the orchestrator runs
# the (tunnel-independent) CPU baseline immediately, then keeps re-probing the
# accelerator at this spacing until the overall budget is spent — the round-2
# lesson was that giving up after a 3-minute window forfeited the whole
# round's TPU record while the orchestrator then idled 7 minutes on CPU work.
# Base vigil re-probe cadence: 2x the probe timeout (so probing's wall
# share stays ~1/3 as the backoff shrinks probes), floored at 60 s; at the
# full 90 s probe timeout that is the historical 180 s spacing.
PROBE_VIGIL_SPACING_S = 180  # == 2 * PROBE_TIMEOUT_S; see _accel_vigil
VIGIL_BUDGET_ENV = "NM03_BENCH_VIGIL_BUDGET_S"
# Total wall budget for the WHOLE orchestrator run — probe round, accel
# attempt, CPU baseline, vigil, emit. MUST stay under the driver's 1800 s
# kill with slack: round 3's record was rc=124/parsed:null precisely because
# the old 2400 s default let the wedge vigil outlive the external timeout
# (VERDICT r3 weak item 1). Longer manual vigils: NM03_BENCH_VIGIL_BUDGET_S.
VIGIL_BUDGET_DEFAULT_S = 1500.0
# Wall reserved at the tail of the budget for section merging + composing +
# printing the final JSON line (pure host work, but leave real slack).
EMIT_RESERVE_S = 45.0
# Wall reserved for the CPU-baseline worker when capping the accel attempt:
# without a baseline the record's vs_baseline degrades to 1.0 + error.
CPU_RESERVE_S = 150.0
# Accel-attempt shedding tiers (VERDICT r3 item 1: "shed the batch sweep /
# stage matrix first when the budget runs short"). Below FULL, the attempt
# drops the sweep, stage matrix, student and Pallas legs and measures one
# headline batch; below REDUCED there is no time for compile+measure at all.
# FULL is sized at >4x the observed healthy-tunnel full program (~110 s
# wall, 2026-07-31 chip run) — a deadline-capped attempt can still be
# timeout-killed mid-claim if the run needs the pathological end of
# ACCEL_TIMEOUT_S, but in that regime the tunnel is already sick and the
# alternative is the external driver's own kill, which wedges just as hard
# and loses the record besides.
MIN_ACCEL_FULL_S = 480.0
MIN_ACCEL_REDUCED_S = 150.0
MIN_CPU_ATTEMPT_S = 60.0

_SENTINEL = "@@BENCH_RESULT@@"


def _hub_jit(fn, **kwargs):
    """The compile hub's tracked jit (docs: compilehub). Lazy import: the
    orchestrator never imports jax, and the hub package is jax-free at
    import time, but routing measurement compiles through one helper keeps
    bench inside the NM361 compile-home contract."""
    from nm03_capstone_project_tpu.compilehub import hub_jit

    return hub_jit(fn, **kwargs)

# Observability (--metrics-out / --log-json): the orchestrator's RunContext.
# Module-level because the SIGTERM/SIGALRM emit path shares it with main();
# the obs package is deliberately jax-free, so wiring it here keeps the
# orchestrator's never-imports-jax invariant intact.
_OBS_CTX = None


def _obs_event(event: str, **fields) -> None:
    if _OBS_CTX is not None:
        with contextlib.suppress(Exception):  # telemetry never costs a record
            _OBS_CTX.events.emit(event, **fields)


def _obs_span(name: str):
    if _OBS_CTX is not None:
        return _OBS_CTX.spans.span(name)
    return contextlib.nullcontext()

# Qualitative bound per stage, justified by the measured ms next to it:
# elementwise/render stream HBM with trivial FLOPs/byte (memory-bound on the
# VPU); the 7x7 vector median does a 49-candidate rank-select per pixel
# (compute-bound on the VPU); region growing is an iterative fixpoint whose
# cost is sequential sweeps, not bytes (iteration/latency-bound).
_STAGE_BOUND = {
    "normalize_clip": "memory (VPU elementwise, HBM-limited)",
    "median7": "compute (VPU pruned selection network, column presort)",
    "sharpen": "memory (9-tap shifted-add sweeps, HBM-limited)",
    "region_grow": "iteration (sequential one-ring fixpoint sweeps)",
    "cast_dilate": "memory (VPU reduce-window, HBM-limited)",
    "render": "memory (fused letterbox resample + integer overlay)",
}
# The `jump` growing schedule is out of the stage matrix (round 3): with the
# pipeline's adaptive seed grid the band path length is bounded by seed
# spacing and the dilate schedule wins at every canvas size measured
# (512/1024/2048: 57/312/1532 ms vs 91/497/4265 ms on XLA:CPU). Its real win
# region is sparse/single seeds with canvas-length paths, where it is 2-3x
# faster AND converges while the dilate schedule hits max_iters — measured
# and documented in docs/PERF.md; the op stays available via
# --grow-algorithm jump.

# Minimum algorithmic HBM traffic per stage in bytes, f(batch, canvas,
# render_size): the data each stage MUST read + write (f32 in/out for the
# float stages; the cast stage writes u8; render reads f32+u8 and writes two
# u8 render canvases). Intra-stage temporaries that XLA keeps in
# registers/VMEM are deliberately excluded — this is the lower bound that
# makes achieved-GB/s an upper-bound-honest roofline figure (VERDICT r2
# weak item 3). The iteration-bound growing stages have no static traffic
# model (sweep count is data-dependent) and carry no roofline entry.
_STAGE_MIN_BYTES = {
    "normalize_clip": lambda b, n, r: 2 * b * n * n * 4,
    "median7": lambda b, n, r: 2 * b * n * n * 4,
    "sharpen": lambda b, n, r: 2 * b * n * n * 4,
    "cast_dilate": lambda b, n, r: b * n * n * (4 + 1),
    "render": lambda b, n, r: b * (n * n * (4 + 1) + 2 * r * r),
}
RENDER_SIZE = 512
# the small batch of the two-point fit that separates per-dispatch overhead
# (constant vs batch) from true device time (linear in batch)
STAGE_SMALL_BATCH = 8

# Peak HBM bandwidth (GB/s) by jax device_kind, public spec-sheet numbers;
# NM03_HBM_PEAK_GBPS overrides. pct_of_hbm_peak is only emitted when the
# kind is known (or overridden) — never against a guessed denominator.
_HBM_PEAK_GBPS = {
    "TPU v2": 700.0,
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v4i": 614.0,
    "TPU v5e": 819.0,
    "TPU v5 lite": 819.0,
    "TPU v5p": 2765.0,
    "TPU v5": 2765.0,
    "TPU v6e": 1640.0,
    "TPU v6 lite": 1640.0,
}


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# worker mode — the only code paths that import jax
# --------------------------------------------------------------------------


def _make_batch(batch: int | None = None):
    import numpy as np

    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    if batch is None:
        batch = BATCH  # resolved at call time: tests monkeypatch BATCH
    pixels = np.stack(
        [
            # i % 32, NOT i: radius growing with the raw index made larger
            # batches carry systematically larger lesions, and the batched
            # growing fixpoint runs until the LARGEST lesion converges —
            # xla_by_batch then measured lesion scaling, not batch scaling
            # (the round-4 "inversion", VERDICT r4 weak #5; the same fall
            # shows in the tunnel-free CPU record, refuting enqueue). The
            # modulus keeps every batch's radius DISTRIBUTION identical —
            # and batch 32 identical to all prior rounds' headline batch.
            phantom_slice(
                CANVAS, CANVAS, seed=i, lesion_radius=0.12 + 0.002 * (i % 32)
            )
            for i in range(batch)
        ]
    ).astype(np.float32)
    dims = np.full((batch, 2), CANVAS, np.int32)
    return pixels, dims


def _batch_scaling_note(by_batch, best_batch, canvas):
    """One-sentence attribution when a LARGER batch measures slower than the
    sweep winner (ISSUE 2 satellite: the r05 record showed 111.61 at batch
    256 vs 116.09 at 128 with nothing in the output saying why).

    The cause was measured in round 5 (docs/PERF.md): radius distributions
    are batch-invariant since the r05 generator fix, and the residual fall
    tracks the working set — a 256-slice f32 canvas batch is 64 MB, past
    any LLC on this host class — so it is cache footprint, not the grow
    loop, and not worth chasing. The sweep already picks the best batch
    for the headline automatically; the note makes the record
    self-explaining. Returns None when no larger batch fell >3% below the
    winner.
    """
    if not by_batch or best_batch is None:
        return None
    best = by_batch.get(str(best_batch))
    if not best:
        return None
    worse = {
        int(b): v
        for b, v in by_batch.items()
        if int(b) > int(best_batch) and v < 0.97 * best
    }
    if not worse:
        return None
    b = max(worse)
    mb = b * canvas * canvas * 4 / 1e6
    pct = round(100.0 * (1 - worse[b] / best), 1)
    return (
        f"batch {b} measures {pct}% below the batch-{best_batch} best: a "
        f"{b}-slice f32 canvas batch is {mb:.0f} MB — past any LLC on this "
        "host class, so the fall is cache footprint, not the grow loop "
        "(lesion radii are batch-invariant since the r05 generator fix); "
        "the sweep picks the best batch for the headline automatically"
    )


def _bench_on(device, pixels, dims, reps, use_pallas=False):
    """(slices/sec, checksum) of the jitted vmapped pipeline on one device.

    ``use_pallas`` routes the hot ops (7x7 median, region growing) through
    the Pallas TPU kernels; lowering failures propagate — the caller decides
    the fallback.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = PipelineConfig(use_pallas=use_pallas)

    def f(px, dm):
        # Scalar checksum: forces the whole pipeline to run, and fetching it
        # is a 4-byte transfer — honest sync without paying a 2 MB pull
        # through the TPU tunnel per rep.
        mask = process_batch(px, dm, cfg)["mask"]
        return mask.astype(jnp.int32).sum()

    px = jax.device_put(jnp.asarray(pixels), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    dm = jax.device_put(jnp.asarray(dims), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    fn = _hub_jit(f)

    t0 = time.perf_counter()
    checksum = int(fn(px, dm))  # device_get = real synchronization
    _log(
        f"{device.platform}{' (pallas)' if use_pallas else ''}: "
        f"compile+first run {time.perf_counter() - t0:.1f}s"
    )
    if checksum <= 0:
        _log("WARNING: pipeline segmented nothing — benchmark suspect")

    from nm03_capstone_project_tpu.utils import sanitize

    t0 = time.perf_counter()
    # --sanitize: the (upload-only) guard proves the steady-state loop
    # performs zero implicit host->device transfers — inputs were
    # committed above, so anything the guard catches is a hidden re-stage.
    # The d2h scalar sync is deliberately sanctioned. No-op otherwise.
    with sanitize.guard_dispatch():
        results = [fn(px, dm) for _ in range(reps)]  # enqueue, FIFO stream
        int(results[-1])  # one sync: FIFO implies all earlier reps finished
    elapsed = time.perf_counter() - t0
    return pixels.shape[0] * reps / elapsed, checksum


def _bench_scan_chunk(device, batch, reps, chunk=8):
    """(slices/sec, checksum) with ``chunk`` batches per SINGLE dispatch.

    The per-dispatch path (_bench_on) pays the tunnel enqueue per rep even
    with enqueue-then-sync; here a `lax.scan` runs ``chunk`` DISTINCT
    batches inside one compiled program, so the measured rate is the pure
    device rate with the dispatch floor amortized to nothing — the
    latency-bound-vs-device-bound split made explicit (VERDICT r4 weak
    #5's prescription). Distinct per-iteration inputs stop XLA hoisting
    the body out of the loop as loop-invariant.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    import numpy as np

    cfg = PipelineConfig()
    # one generation, `chunk` rolled copies: every scan iteration sees a
    # genuinely different batch (stops loop-invariant hoisting) with the
    # identical radius distribution — and identical TOTAL checksum, which
    # the caller validates against chunk x the per-dispatch checksum
    px, dm = _make_batch(batch)
    xs_px = jnp.asarray(np.stack([np.roll(px, c, axis=0) for c in range(chunk)]))
    xs_dm = jnp.asarray(np.stack([np.roll(dm, c, axis=0) for c in range(chunk)]))

    def step(carry, xd):
        px, dm = xd
        mask = process_batch(px, dm, cfg)["mask"]
        return carry + mask.astype(jnp.int32).sum(), None

    fn = _hub_jit(
        lambda xp, xm: jax.lax.scan(step, jnp.int32(0), (xp, xm))[0]
    )
    xs_px = jax.device_put(xs_px, device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    xs_dm = jax.device_put(xs_dm, device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    checksum = int(fn(xs_px, xs_dm))  # compile + warm sync
    t0 = time.perf_counter()
    outs = [fn(xs_px, xs_dm) for _ in range(reps)]
    int(outs[-1])
    elapsed = time.perf_counter() - t0
    return batch * chunk * reps / elapsed, checksum


def _bench_student(device, pixels, dims, reps):
    """slices/s of the deployed 2D student (cli.runner._student_batch_mask)
    with train-default architecture, same enqueue-then-sync methodology."""
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.cli.runner import _student_batch_mask
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.models import init_unet

    cfg = PipelineConfig()
    params = jax.device_put(init_unet(jax.random.PRNGKey(0), base=16), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    px = jax.device_put(jnp.asarray(pixels), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    dm = jax.device_put(jnp.asarray(dims), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    fn = _hub_jit(
        lambda p, d: _student_batch_mask(params, p, d, cfg).astype(jnp.int32).sum()
    )
    int(fn(px, dm))  # compile + warm-up sync
    t0 = time.perf_counter()
    outs = [fn(px, dm) for _ in range(reps)]
    int(outs[-1])
    return pixels.shape[0] * reps / (time.perf_counter() - t0)


VOLUME_DEPTH = 22
VOLUME_REPS = 8
ZSHARD_DEPTH = 16
ZSHARD_CANVAS = 128


def _make_volume(depth, canvas):
    """One synthetic series stacked into a (depth, canvas, canvas) volume
    with a waxing/waning lesion, mirroring the cohort generator's shape
    (BASELINE.json config 4: ~22 slices of 256²)."""
    import numpy as np

    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    rad = [0.10 + 0.08 * (1 - abs(2 * i / (depth - 1) - 1)) for i in range(depth)]
    vol = np.stack(
        [phantom_slice(canvas, canvas, seed=7, lesion_radius=r) for r in rad]
    ).astype(np.float32)
    dims = np.asarray([canvas, canvas], np.int32)
    return vol, dims


def _bench_volume(device, reps):
    """Per-volume wall for the 3D pipeline (grow3d + morphology), same
    enqueue-then-sync methodology as the 2D path (VERDICT r3 item 5)."""
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

    cfg = PipelineConfig()
    vol, dims = _make_volume(VOLUME_DEPTH, CANVAS)
    v = jax.device_put(jnp.asarray(vol), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    d = jax.device_put(jnp.asarray(dims), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
    fn = _hub_jit(
        lambda vv, dd: process_volume(vv, dd, cfg)["mask"].astype(jnp.int32).sum()
    )
    t0 = time.perf_counter()
    checksum = int(fn(v, d))
    _log(f"volume: compile+first run {time.perf_counter() - t0:.1f}s "
         f"(checksum {checksum})")
    t0 = time.perf_counter()
    outs = [fn(v, d) for _ in range(reps)]
    int(outs[-1])
    per_volume = (time.perf_counter() - t0) / reps
    return {
        "ms_per_volume": round(per_volume * 1e3, 2),
        "depth": VOLUME_DEPTH,
        "canvas": CANVAS,
        "mvoxels_per_s": round(
            VOLUME_DEPTH * CANVAS * CANVAS / per_volume / 1e6, 2
        ),
        "checksum": checksum,
    }


def zshard_scaling() -> None:
    """Multi-chip measurement on the 8-virtual-device mesh: z-sharded
    volume AND data-parallel 2D batch scaling curves at 1/2/4/8 shards
    (checksum-equality asserted across every width), plus the serving
    fleet's replica-lane throughput — per-chip compile-hub executables
    dispatched concurrently across 1/2/4/8 lanes, the number BENCH_r06's
    multi-chip column reports.

    Runs under JAX_PLATFORMS=cpu with xla_force_host_platform_device_count=8
    (the parent sets the env), so it is tunnel-independent; on real
    multi-chip hardware the same code paths ride ICI instead.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.parallel.dp import process_batch_sharded
    from nm03_capstone_project_tpu.parallel.mesh import make_mesh
    from nm03_capstone_project_tpu.parallel.zshard import process_volume_zsharded

    cfg = PipelineConfig()
    vol, dims = _make_volume(ZSHARD_DEPTH, ZSHARD_CANVAS)
    v = jnp.asarray(vol)
    d = jnp.asarray(dims)
    # dp input: the same stack treated as a 2D batch, dims per slice
    bd = jnp.broadcast_to(d, (ZSHARD_DEPTH, 2))
    devices = jax.devices()
    out: dict = {
        "depth": ZSHARD_DEPTH,
        "canvas": ZSHARD_CANVAS,
        "mesh_shape": [len(devices)],
        "lanes": len(devices),
        "ms": {},
        "dp_ms": {},
        "serve_lane_tput": {},
        # label the leg's evidentiary value INSIDE the record (VERDICT r4
        # weak #4): on this host the mesh is 8 virtual devices on ONE core,
        # so the curves prove collective-lockstep correctness, not speedup
        "note": (
            "virtual CPU mesh on a 1-core host: checksum/lockstep "
            "correctness evidence; wall times are NOT a scaling curve"
        ),
    }
    bases: dict = {}
    for shards in (1, 2, 4, 8):
        if shards > len(devices):
            break
        sub = devices[:shards]
        zmesh = make_mesh(axis_names=("z",), devices=sub)
        dmesh = make_mesh(axis_names=("data",), devices=sub)
        zfn = _hub_jit(
            lambda vv, dd, m=zmesh: process_volume_zsharded(vv, dd, cfg, m)[
                "mask"
            ].astype(jnp.int32).sum()
        )
        # mask_only would DONATE the pixel stack, invalidating it for the
        # next rep — use the non-donating default path
        dfn = _hub_jit(
            lambda vv, dd, m=dmesh: process_batch_sharded(vv, dd, cfg, m)[
                "mask"
            ].astype(jnp.int32).sum()
        )
        reps = 4
        for key, fn, args in (("ms", zfn, (v, d)), ("dp_ms", dfn, (v, bd))):
            checksum = int(fn(*args))  # compile + warm
            agree = checksum == bases.setdefault(key, checksum)
            t0 = time.perf_counter()
            outs = [fn(*args) for _ in range(reps)]
            int(outs[-1])
            ms = (time.perf_counter() - t0) / reps * 1e3
            out[key][str(shards)] = round(ms, 2)
            out.setdefault("checksum_ok", True)
            out["checksum_ok"] = out["checksum_ok"] and agree
            _log(f"{key} {shards}: {ms:.1f} ms (checksum {checksum})")

    # Serving fleet: per-lane warm executables (compile hub, pinned per
    # device) dispatched concurrently — the path nm03-serve's batcher fans
    # coalesced batches over. Enqueue every lane's bucket then sync: the
    # same async-dispatch overlap the service gets from its lane threads.
    import numpy as np

    from nm03_capstone_project_tpu.compilehub import programs as hub_programs

    bucket = 8
    # serving contract: slices ride the cfg.canvas stack, true dims aside
    # (the batcher's pad_batch layout)
    px8 = np.zeros((bucket, cfg.canvas, cfg.canvas), np.float32)
    px8[:, :ZSHARD_CANVAS, :ZSHARD_CANVAS] = np.asarray(vol[:bucket], np.float32)
    dm8 = np.broadcast_to(np.asarray(dims, np.int32), (bucket, 2)).copy()
    lane_checks: dict = {}
    for lanes in (1, 2, 4, 8):
        if lanes > len(devices):
            break
        devs = hub_programs.lane_devices(lanes)
        exes = [
            hub_programs.serve_mask(cfg, bucket=bucket, device=dv)
            for dv in devs
        ]
        outs = [ex(px8, dm8) for ex in exes]  # compile+warm every lane
        checks = {int(np.asarray(m).astype(np.int64).sum()) for m, _ in outs}
        lane_checks[lanes] = checks
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            outs = [ex(px8, dm8) for ex in exes]  # enqueue all lanes
        for m, _ in outs:  # sync the last wave, every lane
            np.asarray(m)
        elapsed = time.perf_counter() - t0
        tput = lanes * bucket * reps / elapsed
        out["serve_lane_tput"][str(lanes)] = round(tput, 2)
        _log(f"serve lanes {lanes}: {tput:.1f} slices/s (checksums {checks})")
    all_checks = set().union(*lane_checks.values()) if lane_checks else set()
    out["serve_lane_checksum_ok"] = len(all_checks) == 1

    # The whole-volume SERVING number (ISSUE 15) — the budget-reserved
    # zshard slot's missing record: one study through the gang lane
    # (POST /v1/segment-volume's in-process path — gang acquire, mesh
    # staging, AOT z-sharded dispatch, gather) vs the same study driven
    # directly the way nm03-volume --z-shard dispatches it. Checksum-
    # gated like the Pallas/cold-start legs: the throughput claims are
    # null unless the served mask is BIT-IDENTICAL to the direct one.
    try:
        out["volume_serve"] = _volume_serve_record(vol, dims)
    except Exception as e:  # noqa: BLE001 — the section's other legs stand
        out["volume_serve_error"] = f"{e!r:.500}"
        _log(f"volume_serve leg failed: {e!r:.500}")
    # the fleet's compile-cost columns (ISSUE 7): what warming every
    # per-lane serve_mask executable cost, with the XLA cost/memory
    # analysis where exposed — the denominators the serve_lane_tput
    # numbers were missing
    from nm03_capstone_project_tpu.compilehub import get_hub

    hub = get_hub()
    out["compile_cost"] = {
        "total_compile_seconds": hub.stats()["total_compile_seconds"],
        "specs": [e for e in hub.cost_report() if e["name"] == "serve_mask"],
    }
    print(_SENTINEL + json.dumps(out), flush=True)


def _volume_serve_record(vol, dims) -> dict:
    """Served-volume vs direct z-shard throughput (ISSUE 15), one record.

    An in-process ServingApp (4 lanes, one slice bucket, one volume depth
    bucket) serves the synthetic study through the FULL gang path; the
    direct leg dispatches the same study through
    ``process_volume_zsharded`` on an identical mesh. ``slices_per_s``
    fields are null unless every served mask equalled the direct mask
    byte-for-byte. CPU-container honesty (PERF.md): 4 virtual devices
    share the host cores, so the record proves serve-path overhead and
    correctness, not multi-chip speedup — the TPU window re-measures.
    """
    import base64

    import numpy as np

    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.parallel.mesh import make_mesh
    from nm03_capstone_project_tpu.parallel.zshard import (
        process_volume_zsharded,
    )
    from nm03_capstone_project_tpu.serving.server import ServingApp

    lanes = min(4, len(jax.devices()))
    depth = int(vol.shape[0])
    canvas = int(vol.shape[1])
    cfg = PipelineConfig(canvas=canvas)
    app = ServingApp(
        cfg=cfg, buckets=(1,), lanes=lanes,
        volume_serving=True, volume_depth_buckets=(depth,),
    )
    t0 = time.perf_counter()
    app.start()
    warm_s = time.perf_counter() - t0
    rec: dict = {
        "depth": depth, "canvas": canvas, "z_shards": lanes,
        "warmup_s": round(warm_s, 2),
        "note": (
            "virtual CPU mesh on a shared-core host: serve-path overhead "
            "+ bit-identity evidence, not a scaling claim"
        ),
    }
    try:
        vol_np = np.asarray(vol, np.float32)
        dims_np = np.asarray(dims, np.int32)
        reps = 3
        payloads = []
        t0 = time.perf_counter()
        for _ in range(reps):
            payloads.append(app.segment_volume(vol_np))
        served_s = (time.perf_counter() - t0) / reps
        rec["gang_wait_s_max"] = max(p["gang_wait_s"] for p in payloads)
        served_masks = [
            np.frombuffer(base64.b64decode(p["mask_b64"]), np.uint8).reshape(
                depth, canvas, canvas
            )
            for p in payloads
        ]
        # the direct leg: the driver's own dispatch on an identical mesh
        mesh = make_mesh(lanes, axis_names=("z",), devices=jax.devices()[:lanes])
        dfn = lambda: process_volume_zsharded(  # noqa: E731
            jnp.asarray(vol_np), jnp.asarray(dims_np), cfg, mesh
        )["mask"]
        direct = np.asarray(dfn())  # compile + warm
        t0 = time.perf_counter()
        for _ in range(reps):
            last = dfn()
        np.asarray(last)
        direct_s = (time.perf_counter() - t0) / reps
        checksum_ok = all(np.array_equal(m, direct) for m in served_masks)
        rec["checksum_ok"] = bool(checksum_ok)
        if checksum_ok:
            rec["served_slices_per_s"] = round(depth / served_s, 2)
            rec["direct_slices_per_s"] = round(depth / direct_s, 2)
            rec["serve_overhead_ratio"] = round(served_s / direct_s, 3)
        else:
            rec["served_slices_per_s"] = None
            rec["direct_slices_per_s"] = None
            rec["serve_overhead_ratio"] = None
        _log(
            f"volume_serve: served {rec['served_slices_per_s']} vs direct "
            f"{rec['direct_slices_per_s']} slices/s (checksum {checksum_ok})"
        )
    finally:
        app.begin_drain(reason="bench_done")
        app.close()
    return rec


def _time_stage(fn, args, reps):
    """Seconds per call: jit, warm up, enqueue ``reps``, one checksum sync."""
    import jax
    import jax.numpy as jnp

    def with_checksum(*a):
        out = fn(*a)
        leaves = jax.tree_util.tree_leaves(out)
        # nm03-lint: disable=NM311 leaves are traced values already inside this trace; asarray is a dtype-view cast here, not per-trace construction
        return sum(jnp.asarray(leaf).astype(jnp.float32).sum() for leaf in leaves)

    jitted = _hub_jit(with_checksum)
    float(jitted(*args))  # compile + warm-up, device_get sync
    t0 = time.perf_counter()
    outs = [jitted(*args) for _ in range(reps)]
    float(outs[-1])  # FIFO stream: last result implies all reps done
    return (time.perf_counter() - t0) / reps


def _stage_times(device, reps):
    """Per-stage breakdown (ms per BATCH-slice batch), stages jitted alone.

    The fused pipeline is faster than the sum (XLA melts the elementwise
    stages into neighbours); this is the attribution breakdown, not a second
    throughput claim.

    Each stage is timed at two batch sizes (STAGE_SMALL_BATCH and BATCH) and
    the constant term is fitted out: the round-2 TPU record showed every
    stage floored at 1.5-2.4 ms/batch regardless of work — per-dispatch
    tunnel overhead, not device time (VERDICT r2 weak item 3). ``device_ms``
    is the batch-linear component (true device time at the reference batch),
    ``dispatch_floor_ms`` the constant; memory-bound stages additionally get
    achieved GB/s against their minimum algorithmic traffic and, when the
    chip's device_kind has a known spec peak, pct_of_hbm_peak.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.ops.elementwise import (
        cast_uint8,
        clip_intensity,
        normalize,
    )
    from nm03_capstone_project_tpu.ops.morphology import dilate
    from nm03_capstone_project_tpu.ops.neighborhood import extend_edges
    from nm03_capstone_project_tpu.ops.pallas_median import median_filter
    from nm03_capstone_project_tpu.ops.sharpen import sharpen
    from nm03_capstone_project_tpu.core.image import valid_mask
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import segment
    from nm03_capstone_project_tpu.render.render import render_pair

    cfg = PipelineConfig()

    def vm(f):
        return jax.vmap(f)

    f_norm = vm(
        lambda p, d: clip_intensity(
            normalize(
                extend_edges(p, d),
                cfg.norm_low,
                cfg.norm_high,
                cfg.norm_intensity_min,
                cfg.norm_intensity_max,
            ),
            cfg.clip_low,
            cfg.clip_high,
        )
    )
    f_med = vm(lambda p: median_filter(p, cfg.median_window, impl=cfg.median_impl))
    f_sharp = vm(
        lambda p: sharpen(p, cfg.sharpen_gain, cfg.sharpen_sigma, cfg.sharpen_kernel)
    )
    # [0]: segment returns (mask, converged); the stage clock times the mask
    # (the flag is a byproduct of the same fixpoint loop)
    f_grow = vm(lambda p, d: segment(p, d, cfg)[0])
    f_post = vm(
        lambda s, d: dilate(cast_uint8(s), cfg.morph_size)
        * valid_mask(d, s.shape[-2:]).astype(jnp.uint8)
    )
    f_render = vm(lambda p, m, d: render_pair(p, m, d, cfg))

    def stage_args(batch):
        """Materialize each stage's input on device, off the clock."""
        pixels, dims = _make_batch(batch)
        px = jax.device_put(jnp.asarray(pixels), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
        dm = jax.device_put(jnp.asarray(dims), device)  # nm03-lint: disable=NM401 bench measurement harness: staging this leg's inputs on device, off the measured clock, is the leg's own setup — not batch feeding
        normed = _hub_jit(f_norm)(px, dm)
        med = _hub_jit(f_med)(normed)
        pre = _hub_jit(f_sharp)(med)
        seg = _hub_jit(f_grow)(pre, dm)
        mask = _hub_jit(f_post)(seg, dm)
        return {
            "normalize_clip": (px, dm),
            "median7": (normed,),
            "sharpen": (med,),
            "region_grow": (pre, dm),
            "cast_dilate": (seg, dm),
            "render": (px, mask, dm),
        }

    big = stage_args(BATCH)
    small = stage_args(STAGE_SMALL_BATCH)
    kind = getattr(device, "device_kind", "unknown")
    peak_env = os.environ.get("NM03_HBM_PEAK_GBPS")
    peak = float(peak_env) if peak_env else _HBM_PEAK_GBPS.get(kind)

    fns = {
        "normalize_clip": f_norm,
        "median7": f_med,
        "sharpen": f_sharp,
        "region_grow": f_grow,
        "cast_dilate": f_post,
        "render": f_render,
    }
    stages = {}
    for name, fn in fns.items():
        ms = _time_stage(fn, big[name], reps) * 1e3
        ms_small = _time_stage(fn, small[name], reps) * 1e3
        slope = (ms - ms_small) / (BATCH - STAGE_SMALL_BATCH)
        device_ms = min(max(slope * BATCH, 0.0), ms)
        entry = {
            "ms_per_batch": round(ms, 3),
            "bound": _STAGE_BOUND[name],
            "device_ms": round(device_ms, 3),
            "dispatch_floor_ms": round(ms - device_ms, 3),
        }
        bytes_fn = _STAGE_MIN_BYTES.get(name)
        if bytes_fn and device_ms > 0:
            gbps = bytes_fn(BATCH, CANVAS, RENDER_SIZE) / 1e9 / (device_ms / 1e3)
            # 3 decimals: tiny test shapes measure fractions of a GB/s, and
            # rounding those to 0.0 made the figure (and its test) vanish
            entry["achieved_gbps"] = round(gbps, 3)
            if peak:
                entry["pct_of_hbm_peak"] = round(100.0 * gbps / peak, 1)
        stages[name] = entry
        _log(
            f"stage {name}: {ms:.2f} ms/batch (device {device_ms:.2f} + "
            f"floor {ms - device_ms:.2f}) ({_STAGE_BOUND[name]})"
            + (f" {entry['achieved_gbps']} GB/s" if "achieved_gbps" in entry else "")
        )
    # attribution extras for the two rebuilt stages (PR 2): the comparator
    # counts behind the median's pruned selection network, and each fast
    # path timed against the baseline it replaced — measured at the
    # reference batch only, so the delta is one extra timing per stage
    import dataclasses

    from nm03_capstone_project_tpu.ops.selection_network import comparator_counts

    stages["median7"]["comparators"] = comparator_counts(cfg.median_window)
    f_med_merge = vm(
        lambda p: median_filter(p, cfg.median_window, impl="merge")
    )
    merge_ms = _time_stage(f_med_merge, big["median7"], reps) * 1e3
    stages["median7"]["merge_baseline_ms_per_batch"] = round(merge_ms, 3)
    if stages["median7"]["ms_per_batch"] > 0:
        stages["median7"]["pruned_vs_merge_speedup"] = round(
            merge_ms / stages["median7"]["ms_per_batch"], 3
        )
    cfg_unfused = dataclasses.replace(cfg, render_fused=False)
    f_render_unf = vm(lambda p, m, d: render_pair(p, m, d, cfg_unfused))
    unf_ms = _time_stage(f_render_unf, big["render"], reps) * 1e3
    stages["render"]["unfused_ms_per_batch"] = round(unf_ms, 3)
    if stages["render"]["ms_per_batch"] > 0:
        stages["render"]["fused_vs_unfused_speedup"] = round(
            unf_ms / stages["render"]["ms_per_batch"], 3
        )
    _log(
        "median7 pruned vs merge baseline: "
        f"{stages['median7']['ms_per_batch']} vs {merge_ms:.2f} ms; "
        f"render fused vs unfused: {stages['render']['ms_per_batch']} vs "
        f"{unf_ms:.2f} ms"
    )
    total = sum(s["ms_per_batch"] for s in stages.values())
    for s in stages.values():
        if total:
            s["share"] = round(s["ms_per_batch"] / total, 3)
    return {
        "device_kind": kind,
        "hbm_peak_gbps": peak,
        "stages": stages,
    }


def _device_time_pie(prof: dict) -> dict:
    """The serving ledger's pie, bench-side (ISSUE 16).

    Normalizes each stage's batch-linear ``device_ms`` from the stage
    matrix into a share under the ledger's stage names
    (:data:`BENCH_STAGE_TO_LEDGER`) — the record-side twin of
    ``serving_device_time_share``, so a round's artifact carries the same
    pie nm03-top renders and check_perf gates. Checksum-gated like every
    derived leg: the shares only count when the staged composition's mask
    is bit-identical to the fused pipeline's mask on the same inputs (an
    attribution of a *different* program is no attribution). Gated fields
    are null on mismatch; ``checksum_ok`` is always present.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.core.image import valid_mask
    from nm03_capstone_project_tpu.ops.elementwise import (
        cast_uint8,
        clip_intensity,
        normalize,
    )
    from nm03_capstone_project_tpu.ops.morphology import dilate
    from nm03_capstone_project_tpu.ops.neighborhood import extend_edges
    from nm03_capstone_project_tpu.ops.pallas_median import median_filter
    from nm03_capstone_project_tpu.ops.sharpen import sharpen
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import (
        process_batch,
        segment,
    )

    cfg = PipelineConfig()
    pixels, dims = _make_batch(STAGE_SMALL_BATCH)

    def staged(px, dm):
        # the stage matrix's exact per-stage compositions, chained — what
        # the pie attributes must be the program the pipeline serves
        normed = jax.vmap(
            lambda p, d: clip_intensity(
                normalize(
                    extend_edges(p, d),
                    cfg.norm_low,
                    cfg.norm_high,
                    cfg.norm_intensity_min,
                    cfg.norm_intensity_max,
                ),
                cfg.clip_low,
                cfg.clip_high,
            )
        )(px, dm)
        med = jax.vmap(
            lambda p: median_filter(p, cfg.median_window, impl=cfg.median_impl)
        )(normed)
        pre = jax.vmap(
            lambda p: sharpen(
                p, cfg.sharpen_gain, cfg.sharpen_sigma, cfg.sharpen_kernel
            )
        )(med)
        seg = jax.vmap(lambda p, d: segment(p, d, cfg)[0])(pre, dm)
        return jax.vmap(
            lambda s, d: dilate(cast_uint8(s), cfg.morph_size)
            * valid_mask(d, s.shape[-2:]).astype(jnp.uint8)
        )(seg, dm)

    staged_sum = int(
        np.asarray(_hub_jit(staged)(pixels, dims)).astype(np.int64).sum()
    )
    fused_sum = int(
        np.asarray(
            _hub_jit(lambda px, dm: process_batch(px, dm, cfg)["mask"])(
                pixels, dims
            )
        ).astype(np.int64).sum()
    )
    checksum_ok = staged_sum == fused_sum

    device_ms = {
        name: float((prof["stages"].get(name) or {}).get("device_ms") or 0.0)
        for name in BENCH_STAGE_TO_LEDGER
    }
    total = sum(device_ms.values())
    shares = (
        {
            BENCH_STAGE_TO_LEDGER[k]: round(v / total, 4)
            for k, v in device_ms.items()
        }
        if total > 0
        else None
    )
    return {
        "batch": BATCH,
        "checksum_ok": checksum_ok,
        "stage_share": shares if checksum_ok else None,
        "device_seconds_per_slice": (
            round(total / 1e3 / BATCH, 9)
            if checksum_ok and total > 0
            else None
        ),
    }


def write_perf_baseline(
    path: str, platform: str | None = None, reps: int = STAGE_REPS
) -> int:
    """Measure the stage matrix in-process and write a perf baseline.

    The ``--write-perf-baseline`` mode: produces the committed
    ``PERF_BASELINE.json`` that ``scripts/check_perf.py`` gates serving
    drills against (schema ``nm03.perf_baseline.v1``). The bands are
    deliberately wide — the tripwire exists to catch a stage silently
    doubling or the per-request cost jumping an order of magnitude, not
    to flake on run-to-run jitter of a shared CI host.
    """
    _pin_platform(platform)
    import jax

    dev = jax.devices()[0]
    prof = _stage_times(dev, reps)
    pie = _device_time_pie(prof)
    if not pie["checksum_ok"]:
        print(
            "write-perf-baseline: staged/fused checksum MISMATCH — a "
            "baseline of the wrong program gates nothing; refusing to write",
            file=sys.stderr,
        )
        return 1
    baseline = {
        "schema": "nm03.perf_baseline.v1",
        "device_kind": prof["device_kind"],
        "batch": pie["batch"],
        "device_seconds_per_slice": pie["device_seconds_per_slice"],
        "stage_shares": pie["stage_share"],
        "tolerance": {"device_seconds_rel": 4.0, "stage_share_abs": 0.25},
        "min_share": 0.05,
    }
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(baseline, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    print(f"wrote {path}", file=sys.stderr)
    return 0


def _pin_platform(platform: str | None):
    """Pin the backend before jax initializes (belt and braces: env is set by
    the parent, but a PJRT plugin loaded via sitecustomize may have re-pinned
    jax.config at interpreter startup — see tests/conftest.py)."""
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        import jax

        jax.config.update("jax_platforms", platform)


def _compile_cost_record(batch: int) -> dict:
    """AOT compile cost + XLA cost analysis of the mask program at ``batch``.

    The roofline denominators ISSUE 7 adds to the perf trajectory: what the
    executable costs to BUILD (compile wall) and to RUN (flops, bytes
    accessed, HBM residency) next to the measured slices/s — the numbers
    the AOT-serialization plan (ROADMAP item 2) needs a baseline for.
    Fields beyond ``compile_s`` exist only where jaxlib exposes
    ``cost_analysis()``/``memory_analysis()`` on this backend.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.compilehub import executable_cost
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = PipelineConfig()
    fn = _hub_jit(lambda px, dm: process_batch(px, dm, cfg)["mask"])
    t0 = time.perf_counter()
    compiled = fn.lower(
        jax.ShapeDtypeStruct((batch, CANVAS, CANVAS), jnp.float32),
        jax.ShapeDtypeStruct((batch, 2), jnp.int32),
    ).compile()
    out = {"batch": batch, "compile_s": round(time.perf_counter() - t0, 3)}
    cost = executable_cost(compiled)
    out.update({k: cost[k] for k in sorted(cost)})
    if cost.get("flops") and cost.get("bytes_accessed"):
        out["intensity_flops_per_byte"] = round(
            cost["flops"] / cost["bytes_accessed"], 4
        )
        out["flops_per_slice"] = round(cost["flops"] / batch, 1)
    return out


def _cold_start_record(batch: int) -> dict:
    """Two successive in-process warmups of the AOT mask program: cache-cold
    (trace+lower+compile, then persist) vs cache-warm (deserialize from the
    persistent executable cache — compilehub/persist.py, ISSUE 9).

    The first non-kernel win the trajectory can carry: ``speedup`` is what
    every replica restart / bench run / driver process stops paying once a
    ``--compile-cache-dir`` is in play. Gated like the Pallas leg: the
    record only counts if the loaded executable's masks are BIT-identical
    to the freshly compiled one's.
    """
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nm03_capstone_project_tpu.compilehub.hub import (
        CompileHub,
        CompileSpec,
        aot_compile,
    )
    from nm03_capstone_project_tpu.compilehub.persist import ExecutableCache
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = PipelineConfig()
    spec = CompileSpec(
        name="bench_mask", cfg=cfg, shape=(batch, CANVAS, CANVAS),
        variant="cold_start",
    )

    def build(s):
        fn = _hub_jit(lambda px, dm: process_batch(px, dm, s.cfg)["mask"])
        return aot_compile(
            fn,
            jax.ShapeDtypeStruct((batch, CANVAS, CANVAS), jnp.float32),
            jax.ShapeDtypeStruct((batch, 2), jnp.int32),
        )

    pixels, dims = _make_batch(batch)
    with tempfile.TemporaryDirectory() as cache_dir:
        # two PRIVATE hubs against one cache dir = two process starts,
        # without the subprocess tax: the second hub's registry is empty,
        # so its only warm path is the on-disk entry the first one wrote.
        # Each warmup is timed THROUGH its first execute — on backends
        # where only the jax-export fallback serializes, the "warm" start
        # still pays an XLA compile at first call, and that cost must
        # land in compile_seconds_warm, not vanish
        cold_hub = CompileHub()
        cold_hub.attach_cache(ExecutableCache(cache_dir))
        t0 = time.perf_counter()
        fn_cold = cold_hub.get(spec, build)
        m_cold = np.asarray(fn_cold(pixels, dims))
        cold_s = time.perf_counter() - t0
        warm_hub = CompileHub()
        warm_hub.attach_cache(ExecutableCache(cache_dir))
        t0 = time.perf_counter()
        fn_warm = warm_hub.get(spec, build)
        m_warm = np.asarray(fn_warm(pixels, dims))
        warm_s = time.perf_counter() - t0
        warm_stats = warm_hub.stats()
    checksum_ok = bool(np.array_equal(m_cold, m_warm))
    return {
        "batch": batch,
        "compile_seconds_cold": round(cold_s, 3),
        "compile_seconds_warm": round(warm_s, 3),
        # same gate as the Pallas leg: only a result-identical load may
        # claim the speedup — a deserialized executable that computes
        # different masks must not put a cache "win" in the record
        "speedup": (
            round(cold_s / warm_s, 1) if checksum_ok and warm_s > 0 else None
        ),
        # cache_hit False = the warm start actually recompiled (e.g. the
        # backend cannot serialize executables) — speedup is then ~1 and
        # honest about it, never silently mislabeled as a cache win
        "cache_hit": warm_stats["builds"] == 0
        and warm_stats["cache_loads"] == 1,
        "checksum_ok": checksum_ok,
        "cache_bytes": int(warm_stats.get("cache_bytes", 0)),
    }


def _result_cache_record() -> dict:
    """Cold-vs-warm replay through the content-addressed result tier
    (ISSUE 19): one study POSTed twice through a real HTTP round trip.

    The cold request computes and fills (``X-Nm03-Cache: fill``); the
    warm repeats are served from the store without touching the batcher.
    Gated like the Pallas/cold-start legs: ``speedup_on_repeat`` is null
    unless the cached payload is BIT-identical to a recomputed one —
    proven by evicting the entry, recomputing, and requiring the
    content ETag (sha256 of the stored bytes) to come back unchanged.
    CPU-container honesty (PERF.md): the cold leg's latency is a
    shared-core CPU compute time, so the ratio is an overhead floor for
    the hit path, not a chip-relative claim — the TPU window re-measures.
    """
    import urllib.request

    import numpy as np

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.serving.server import (
        ServingApp,
        serve_in_thread,
    )

    canvas = CANVAS
    app = ServingApp(
        cfg=PipelineConfig(canvas=canvas), buckets=(1,), lanes=1,
        max_wait_s=0.005, result_cache_bytes=64 * 1024 * 1024,
    )
    httpd, _t, port = serve_in_thread(app)  # starts the app's lanes too
    rec: dict = {"canvas": canvas, "warm_requests": 8}
    try:
        rng = np.random.default_rng(20260807)
        body = rng.random((canvas, canvas), np.float32).astype("<f4").tobytes()
        headers = {
            "Content-Type": "application/octet-stream",
            "X-Nm03-Height": str(canvas), "X-Nm03-Width": str(canvas),
        }

        def post(extra=None):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/segment?output=mask",
                data=body, headers={**headers, **(extra or {})},
                method="POST",
            )
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=300) as resp:
                payload = json.loads(resp.read())
                return (
                    time.perf_counter() - t0,
                    resp.headers.get("X-Nm03-Cache"),
                    resp.headers.get("ETag"),
                    payload,
                )

        post()  # warm the executor off the clock (compile + first dispatch)
        app.result_store.evict()
        cold_s, cold_state, etag_cold, p_cold = post()
        warm = [post() for _ in range(rec["warm_requests"])]
        warm_lat = sorted(w[0] for w in warm)
        # recompute leg: drop the entry, compute again, compare content
        # ETags — sha256 over the stored bytes, so equality IS bit-identity
        # between the cached payload and a fresh compute of the same study
        app.result_store.evict()
        _, refill_state, etag_refill, p_refill = post()
        checksum_ok = bool(
            cold_state == "fill" and refill_state == "fill"
            and etag_cold is not None and etag_cold == etag_refill
            and all(w[1] == "hit" and w[2] == etag_cold for w in warm)
            and all(
                w[3]["mask_sha256"] == p_cold["mask_sha256"] for w in warm
            )
            and p_refill["mask_sha256"] == p_cold["mask_sha256"]
        )
        warm_p50_s = warm_lat[len(warm_lat) // 2]
        rec.update({
            "cold_ms": round(cold_s * 1e3, 2),
            "warm_hit_p50_ms": round(warm_p50_s * 1e3, 2),
            "warm_hit_max_ms": round(warm_lat[-1] * 1e3, 2),
            "checksum_ok": checksum_ok,
            # same gate as the Pallas/cold-start legs: only bit-identical
            # cached bytes may claim the win
            "speedup_on_repeat": (
                round(cold_s / warm_p50_s, 1)
                if checksum_ok and warm_p50_s > 0 else None
            ),
            "store": {
                k: app.result_store.stats()[k]
                for k in ("hits", "misses", "fills", "evictions", "bytes")
            },
            "note": (
                "cold leg is shared-core CPU compute when no accelerator "
                "is attached: the ratio bounds hit-path overhead, it is "
                "not a chip-relative claim"
            ),
        })
    finally:
        app.begin_drain(reason="bench_done")
        httpd.shutdown()
        httpd.server_close()
        app.close()
    return rec


def _feed_stall_record(batch: int, reps: int) -> dict:
    """The serial decode→stage→dispatch→fetch feed, accounted (ISSUE 10).

    Re-runs the batch drivers' per-batch feed shape — synthesize (decode
    stand-in), device_put (stage), execute the AOT mask program
    (dispatch), pull the mask (fetch), strictly serially — while a
    PhaseAccountant records each phase's busy intervals. ``feed_stall_
    ratio`` is the fraction of wall the device sat idle waiting on the
    feed: the pinned before/after number the streaming-ingest work
    (ROADMAP item 3) must drive toward zero. Checksum-gated like the
    Pallas and cold-start legs: the ratio only counts when every fetched
    mask's checksum equals an independently-computed reference — a feed
    loop that computed the wrong masks reports null, never a number.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.obs.saturation import PhaseAccountant
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = PipelineConfig()
    fn = _hub_jit(lambda px, dm: process_batch(px, dm, cfg)["mask"])
    compiled = fn.lower(
        jax.ShapeDtypeStruct((batch, CANVAS, CANVAS), jnp.float32),
        jax.ShapeDtypeStruct((batch, 2), jnp.int32),
    ).compile()
    dev = jax.devices()[0]
    # independent reference checksum: the SAME program via the deferred
    # path, off the feed clock (compile time must not ride the report)
    ref_pixels, ref_dims = _make_batch(batch)
    ref = int(np.asarray(fn(ref_pixels, ref_dims)).astype(np.int64).sum())

    feed = PhaseAccountant()
    sums = []
    for _ in range(reps):
        with feed.busy("decode"):
            pixels, dims = _make_batch(batch)  # synthetic decode stand-in
        with feed.busy("stage"):
            px = jax.device_put(pixels, dev)  # nm03-lint: disable=NM401 the serial-feed BEFORE leg: this upload IS the thing being measured (the streamed AFTER leg routes through ingest)
            dm = jax.device_put(dims, dev)  # nm03-lint: disable=NM401 the serial-feed BEFORE leg: this upload IS the thing being measured (the streamed AFTER leg routes through ingest)
        with feed.busy("dispatch"):
            mask = compiled(px, dm)
            # the serial contract under measurement: the driver waits for
            # THIS batch before feeding the next
            jax.block_until_ready(mask)
        with feed.busy("fetch"):
            host = np.asarray(mask)
        sums.append(int(host.astype(np.int64).sum()))
    rep = feed.report()
    checksum_ok = bool(sums) and all(s == ref for s in sums)
    return {
        "batch": batch,
        "reps": reps,
        "wall_s": rep["wall_s"],
        "busy_s": rep["busy_s"],
        "busy_fraction": rep["busy_fraction"],
        # the gated headline: null unless the masks were bit-equivalent
        "feed_stall_ratio": (
            rep["feed_stall_ratio"] if checksum_ok else None
        ),
        "stall_s": rep["stall_s"] if checksum_ok else None,
        "checksum_ok": checksum_ok,
    }


def _streamed_feed_record(
    batch: int,
    reps: int,
    serial_rec: dict | None = None,
    depth: int = 3,
    workers: int = 2,
) -> dict:
    """The streamed AFTER leg next to :func:`_feed_stall_record`'s serial
    BEFORE (ISSUE 11): the SAME AOT mask program, fed through the
    ingest/ pipeline — decode pool ahead, staging ring, upload overlapped
    with compute, mask fetch streaming back on the pool — instead of the
    drivers' old serial turn-taking. Checksum-gated identically (every
    fetched mask must equal the independently-computed reference, else
    the ratio/throughput report null), so the pair
    ``feed_stall.feed_stall_ratio`` → ``feed_streamed.feed_stall_ratio``
    is a like-for-like before/after on one program, one batch shape, one
    backend. ``speedup_vs_serial`` is the end-to-end feed throughput
    ratio, only reported when BOTH legs' checksums held.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.ingest import IngestPipeline
    from nm03_capstone_project_tpu.ingest.staging import stage_batch
    from nm03_capstone_project_tpu.obs.saturation import PhaseAccountant
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch
    from nm03_capstone_project_tpu.utils import sanitize

    cfg = PipelineConfig()
    fn = _hub_jit(lambda px, dm: process_batch(px, dm, cfg)["mask"])
    compiled = fn.lower(
        jax.ShapeDtypeStruct((batch, CANVAS, CANVAS), jnp.float32),
        jax.ShapeDtypeStruct((batch, 2), jnp.int32),
    ).compile()
    dev = jax.devices()[0]
    # independent reference checksum, off the feed clock (as in the leg
    # this one mirrors)
    ref_pixels, ref_dims = _make_batch(batch)
    ref = int(np.asarray(fn(ref_pixels, ref_dims)).astype(np.int64).sum())

    feed = PhaseAccountant()

    def decode(_):
        pixels, dims = _make_batch(batch)  # synthetic decode stand-in
        return {"pixels": pixels, "dims": dims}

    def stage(item):
        # the pipeline's stager: async device_put one batch ahead of
        # compute (no host refs kept — this leg never renders host-side)
        return stage_batch(item, placement=dev, keep_host=False)

    def fetch(mask, t0):
        with feed.busy("fetch"):
            host = np.asarray(mask)
        # device-in-flight interval, enqueue -> fetch complete: the same
        # lower-bound dispatch definition the drivers report
        feed.record("dispatch", t0, time.monotonic())
        return int(host.astype(np.int64).sum())

    fetches = []
    t_wall0 = time.perf_counter()
    with IngestPipeline(
        source=range(reps),
        decode=decode,
        stage=stage,
        depth=depth,
        decode_workers=workers,
        feed=feed,
    ) as pipe:
        for item in pipe:
            t0 = time.monotonic()
            # --sanitize twin: staged inputs, so an implicit h2d here is a
            # hidden re-stage and raises under the guard
            with sanitize.guard_dispatch():
                mask = compiled(item["pixels"], item["dims"])
            fetches.append(pipe.submit(fetch, mask, t0))
        sums = [f.result() for f in fetches]
        stats = pipe.stats()
    wall = time.perf_counter() - t_wall0
    rep = feed.report()
    checksum_ok = bool(sums) and all(s == ref for s in sums)
    tput = (batch * reps / wall) if wall > 0 else None
    out = {
        "batch": batch,
        "reps": reps,
        "wall_s": rep["wall_s"],
        "busy_s": rep["busy_s"],
        "busy_fraction": rep["busy_fraction"],
        # the gated headline pair: null unless the masks were bit-equivalent
        "feed_stall_ratio": rep["feed_stall_ratio"] if checksum_ok else None,
        "stall_s": rep["stall_s"] if checksum_ok else None,
        "slices_per_s": (
            round(tput, 2) if checksum_ok and tput is not None else None
        ),
        "checksum_ok": checksum_ok,
        "ingest": {
            "ring_occupancy_ratio": stats["ring"]["occupancy_ratio"],
            "ring_peak": stats["ring"]["peak"],
            "decode_queue_peak": stats["decode_queue_peak"],
            "upload_overlap_ratio": stats["upload_overlap_ratio"],
        },
    }
    if (
        serial_rec is not None
        and checksum_ok
        and serial_rec.get("checksum_ok")
        and serial_rec.get("wall_s")
        and tput is not None
    ):
        serial_tput = (
            serial_rec["batch"] * serial_rec["reps"] / serial_rec["wall_s"]
        )
        if serial_tput > 0:
            out["serial_slices_per_s"] = round(serial_tput, 2)
            out["speedup_vs_serial"] = round(tput / serial_tput, 2)
    return out


def probe(platform: str | None) -> None:
    """Tunnel health check: devices + a tiny jit round trip, nothing more."""
    _pin_platform(platform)
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    x = jax.device_put(jnp.ones((128, 128), jnp.float32), dev)  # nm03-lint: disable=NM401 tunnel health probe: one tiny round trip, no batch feed exists yet
    val = float(_hub_jit(lambda a: (a @ a).sum())(x))
    assert val == 128.0 * 128 * 128
    print(_SENTINEL + json.dumps({"backend": dev.platform}), flush=True)


def worker(
    platform: str | None,
    reps: int,
    want_pallas: bool,
    want_stages: bool,
    out_path: str | None,
    batches: tuple | None = None,
    want_volume: bool = False,
    want_scan: bool = False,
    sanitize_on: bool = False,
):
    """Measure on this process's backend.

    ``batches`` is swept on the XLA path and the best slices/s wins (batch
    size is a free choice when the metric is throughput); the Pallas path
    and its checksum comparison run at the winning batch. Each completed
    section is appended to ``out_path`` immediately (one JSON line per
    section), so a parent-side timeout loses only the section in flight.
    The merged result also goes to stdout behind a sentinel.
    """
    if batches is None:
        batches = (BATCH,)  # resolved at call time: tests monkeypatch BATCH
    _pin_platform(platform)
    sanitize_state = None
    if sanitize_on:
        # the runtime twins (docs/STATIC_ANALYSIS.md): debug_nans +
        # recompile watchdog here; the transfer guard arms the
        # guard_dispatch() window inside _bench_on automatically
        from nm03_capstone_project_tpu.utils import sanitize as _sanitize

        sanitize_state = _sanitize.enable()
    import jax

    def emit(update: dict):
        result.update(update)
        if out_path:
            with open(out_path, "a") as f:
                f.write(json.dumps(update) + "\n")

    devices = jax.devices()
    dev = devices[0]
    from nm03_capstone_project_tpu.core.backend import _TPU_PLATFORMS
    from nm03_capstone_project_tpu.utils.profiling import profile_trace

    on_tpu = dev.platform in _TPU_PLATFORMS
    _log(f"worker backend: {dev.platform} ({len(devices)} devices)")

    # NM03_BENCH_PROFILE_DIR: capture a jax.profiler trace (the roofline
    # evidence VERDICT r2 asked for — true device timelines, not just wall
    # deltas). The traced rep-block runs AFTER the sweep at the winning
    # batch and is excluded from the measured numbers, because tracing
    # perturbs them; the record marks that a trace was captured.
    profile_dir = os.environ.get("NM03_BENCH_PROFILE_DIR")

    result: dict = {}
    emit({"backend": dev.platform})
    by_batch: dict = {}
    best = None  # (tput, batch, checksum, pixels, dims)
    for b in batches:
        pixels, dims = _make_batch(b)
        tput, xla_sum = _bench_on(dev, pixels, dims, reps, use_pallas=False)
        by_batch[str(b)] = round(tput, 2)
        _log(f"{dev.platform} XLA throughput @batch={b}: {tput:.2f} slices/s")
        if best is None or tput > best[0]:
            best = (tput, b, xla_sum, pixels, dims)
        # checkpoint progress after every batch size — a timeout keeps the
        # sizes measured so far
        emit(
            {
                "xla_tput": best[0],
                "xla_batch": best[1],
                "checksum": best[2],
                "xla_by_batch": dict(by_batch),
            }
        )
    tput, batch, xla_sum, pixels, dims = best
    if len(batches) > 1:
        note = _batch_scaling_note(by_batch, batch, CANVAS)
        if note:
            emit({"batch_note": note})
            _log(f"batch scaling: {note}")
    if profile_dir:
        # dedicated traced rep-block at the winning batch, off the clock
        _log(f"capturing profiler trace at batch {batch} into {profile_dir}")
        with profile_trace(profile_dir):
            _bench_on(dev, pixels, dims, min(reps, 8), use_pallas=False)
        emit({"profile_dir": profile_dir})
    # honest fused-pipeline roofline anchor: the mask program's minimum HBM
    # traffic is one f32 read + one u8 write per pixel; at the measured
    # slices/s that is the achieved end-to-end bandwidth (the pipeline is
    # compute-dominated by the median network, so expect this far below the
    # HBM peak — the utilization statement VERDICT r2 asked to make explicit)
    emit(
        {
            "fused_min_traffic_gbps": round(
                tput * CANVAS * CANVAS * (4 + 1) / 1e9, 2
            )
        }
    )
    # the feed legs run FIRST among the optional sections: they are the
    # newest acceptance evidence (ISSUE 11's before/after pair), and a
    # deadline-capped attempt sheds sections from the tail — the streamed
    # feed's gate must not be the first thing a slow host loses
    try:
        # feed-stall leg (ISSUE 10): the serial per-batch feed accounted —
        # the idle fraction ROADMAP item 3's streaming ingest must erase,
        # pinned next to the throughput it caps
        fs = _feed_stall_record(batch, reps=min(reps, 8))
        emit({"feed_stall": fs})
        _log(
            f"feed stall @batch={batch}: {fs['feed_stall_ratio']} of wall "
            f"starved (busy {fs['busy_fraction']}, checksum "
            f"{'matches' if fs['checksum_ok'] else 'MISMATCH'})"
        )
    except Exception as e:  # noqa: BLE001 — never lose the headline
        fs = None
        _log(f"feed-stall leg skipped: {e!r:.500}")
    try:
        # streamed-feed leg (ISSUE 11): the AFTER number — the same AOT
        # mask program fed through the ingest/ pipeline; checksum-gated
        # like the serial leg, with speedup_vs_serial only when both
        # legs' checksums held
        fs2 = _streamed_feed_record(batch, reps=min(reps, 8), serial_rec=fs)
        emit({"feed_streamed": fs2})
        _log(
            f"streamed feed @batch={batch}: stall "
            f"{fs2['feed_stall_ratio']} (was {fs['feed_stall_ratio'] if fs else '?'}), "
            f"{fs2['slices_per_s']} slices/s"
            + (
                f" = {fs2['speedup_vs_serial']}x the serial feed"
                if "speedup_vs_serial" in fs2
                else ""
            )
        )
        # the fused-preprocess layout re-measure under the new feed
        # (ISSUE 11 satellite): the serial sweep's batch_note pinned a
        # batch-256 cache-footprint fall — sweep the STREAMED feed over
        # the same batches to see whether the fall moves when decode and
        # upload no longer serialize with compute. Its OWN containment:
        # a failed satellite sweep must not mislabel the already-emitted
        # main feed_streamed record as skipped.
        try:
            if len(batches) > 1:
                streamed_by_batch = {}
                for b in batches:
                    if b == batch:
                        streamed_by_batch[str(b)] = fs2["slices_per_s"]
                        continue
                    r = _streamed_feed_record(b, reps=min(reps, 4))
                    streamed_by_batch[str(b)] = r["slices_per_s"]
                emit({"feed_streamed_by_batch": streamed_by_batch})
                measured = {
                    k: v for k, v in streamed_by_batch.items() if v is not None
                }
                if measured:
                    best_b = max(measured, key=lambda k: measured[k])
                    note = _batch_scaling_note(measured, int(best_b), CANVAS)
                    if note:
                        emit({"streamed_batch_note": f"streamed feed: {note}"})
                        _log(f"streamed batch scaling: {note}")
        except Exception as e:  # noqa: BLE001 — never lose the main leg
            _log(f"streamed by-batch sweep skipped: {e!r:.500}")
    except Exception as e:  # noqa: BLE001 — never lose the headline
        emit({"feed_streamed_error": f"{e!r:.500}"})
        _log(f"streamed-feed leg skipped: {e!r:.500}")

    try:
        # compile-cost / roofline columns (ISSUE 7): AOT-compiled mask
        # program at the winning batch — compile wall + flops/bytes/HBM
        cost = _compile_cost_record(batch)
        emit({"compile_cost": cost})
        _log(f"compile cost @batch={batch}: {cost}")
    except Exception as e:  # noqa: BLE001 — never lose the headline
        _log(f"compile-cost leg skipped: {e}")
    try:
        # cold-start leg (ISSUE 9): cache-cold vs cache-warm warmup of the
        # same AOT mask program — the restart cost the persistent
        # executable cache deletes, measured next to the throughput it
        # protects
        cold = _cold_start_record(batch)
        emit({"cold_start": cold})
        _log(
            f"cold start @batch={batch}: compile {cold['compile_seconds_cold']}s "
            f"-> load {cold['compile_seconds_warm']}s "
            f"({cold['speedup']}x, checksum "
            f"{'matches' if cold['checksum_ok'] else 'MISMATCH'})"
        )
    except Exception as e:  # noqa: BLE001 — never lose the headline
        emit({"cold_start_error": f"{e!r:.500}"})
        _log(f"cold-start leg skipped: {e!r:.500}")
    try:
        # result-tier leg (ISSUE 19): cold-vs-warm replay of one study
        # through the content-addressed result store, ETag-gated — the
        # repeat-read cost the memoization tier deletes, measured next to
        # the compute it memoizes
        rc = _result_cache_record()
        emit({"result_cache": rc})
        _log(
            f"result cache: cold {rc['cold_ms']}ms -> hit "
            f"{rc['warm_hit_p50_ms']}ms p50 "
            f"({rc['speedup_on_repeat']}x on repeat, checksum "
            f"{'matches' if rc['checksum_ok'] else 'MISMATCH'})"
        )
    except Exception as e:  # noqa: BLE001 — never lose the headline
        emit({"result_cache_error": f"{e!r:.500}"})
        _log(f"result-cache leg skipped: {e!r:.500}")
    if want_scan:
        try:
            # dispatch-amortized device rate: `chunk` distinct batches per
            # ONE dispatch via lax.scan — the gap between this and xla_tput
            # IS the per-dispatch (tunnel) cost enqueueing could not hide
            s_tput, s_sum = _bench_scan_chunk(
                dev, batch, max(1, reps // SCAN_CHUNK), chunk=SCAN_CHUNK
            )
            # rolled copies => the scan total must equal chunk x the
            # per-dispatch checksum; a miscompiled/hoisted loop must not
            # put a wrong rate in the record (same gate as the Pallas leg)
            agrees = s_sum == SCAN_CHUNK * xla_sum
            emit({
                "xla_scan_tput": round(s_tput, 2),
                "scan_chunk": SCAN_CHUNK,
                "scan_checksum_ok": agrees,
            })
            _log(
                f"{dev.platform} scan-chunked ({SCAN_CHUNK} batches/dispatch): "
                f"{s_tput:.2f} slices/s (per-dispatch path: {tput:.2f}; "
                f"checksum {'matches' if agrees else 'MISMATCH'})"
            )
        except Exception as e:  # noqa: BLE001 — never lose the headline
            emit({"scan_error": f"{e!r:.500}"})
            _log(f"scan-chunk timing failed: {e!r:.500}")

    if want_pallas and on_tpu:
        try:
            p_tput, p_sum = _bench_on(dev, pixels, dims, reps, use_pallas=True)
            agrees = p_sum == xla_sum
            emit({"pallas_tput": p_tput, "pallas_checksum_ok": agrees})
            _log(
                f"tpu pallas throughput @batch={batch}: {p_tput:.2f} slices/s "
                f"(checksum {'matches' if agrees else 'MISMATCH — discarded'})"
            )
        except Exception as e:  # noqa: BLE001 — pallas lowering failure
            emit({"pallas_error": f"{e!r:.500}"})
            _log(f"pallas path failed, XLA ops only: {e!r:.500}")

    if want_stages:
        try:
            # stage attribution stays at the reference batch (32) so the
            # breakdown is comparable across rounds
            prof = _stage_times(dev, STAGE_REPS)
            emit(
                {
                    "stages": prof["stages"],
                    "device_kind": prof["device_kind"],
                    "hbm_peak_gbps": prof["hbm_peak_gbps"],
                }
            )
            # the ledger pie (ISSUE 16): the stage matrix renormalized
            # under the serving stage names, checksum-gated — its OWN
            # containment so a failed gate leg cannot mislabel the
            # already-emitted stage matrix as skipped
            try:
                pie = _device_time_pie(prof)
                emit({"device_time_pie": pie})
                _log(
                    f"device-time pie: {pie['stage_share']} "
                    f"({pie['device_seconds_per_slice']} device-s/slice, "
                    f"checksum "
                    f"{'matches' if pie['checksum_ok'] else 'MISMATCH'})"
                )
            except Exception as e:  # noqa: BLE001
                emit({"device_time_pie_error": f"{e!r:.500}"})
                _log(f"device-time pie leg failed: {e!r:.500}")
        except Exception as e:  # noqa: BLE001 — never lose the headline number
            emit({"stages_error": f"{e!r:.500}"})
            _log(f"stage timing failed: {e!r:.500}")
        try:
            # the deployment path (--model): distilled-student throughput at
            # the winning batch. Weights don't affect speed, so a fresh init
            # measures the real path without shipping a checkpoint.
            s_tput = _bench_student(dev, pixels, dims, reps)
            emit({"student_tput": round(s_tput, 2)})
            _log(f"{dev.platform} student throughput: {s_tput:.2f} slices/s")
        except Exception as e:  # noqa: BLE001
            emit({"student_error": f"{e!r:.500}"})
            _log(f"student timing failed: {e!r:.500}")

    if want_volume:
        try:
            # the 3D path's first perf leg (VERDICT r3 item 5)
            vol = _bench_volume(dev, VOLUME_REPS)
            emit({"volume": vol})
            _log(f"{dev.platform} volume: {vol['ms_per_volume']} ms/volume "
                 f"({vol['mvoxels_per_s']} Mvoxel/s)")
        except Exception as e:  # noqa: BLE001
            emit({"volume_error": f"{e!r:.500}"})
            _log(f"volume timing failed: {e!r:.500}")

    if sanitize_state is not None:
        # the jax-free orchestrator folds this into pipeline_recompiles_total
        emit({"sanitize_recompiles": sanitize_state.recompiles})
        _log(f"sanitize: {sanitize_state.recompiles} compilations observed")
    print(_SENTINEL + json.dumps(result), flush=True)


# --------------------------------------------------------------------------
# orchestrator — no jax; subprocess workers with hard timeouts
# --------------------------------------------------------------------------


# The currently-running worker child, if any — the SIGTERM best-so-far
# handler must kill it (a hung client HOLDS the chip claim until it dies;
# orphaning it would wedge the tunnel for whatever runs after us).
_CURRENT_CHILD: list = []


def _spawn(label, extra_args, env_overrides, timeout_s):
    """Run this file in a subprocess; (rc, stdout, stderr), rc=None on timeout."""
    env = os.environ.copy()
    for key, val in env_overrides.items():
        if val is None:
            env.pop(key, None)
        else:
            env[key] = val
    cmd = [sys.executable, os.path.abspath(__file__), *extra_args]
    _log(f"{label}: spawning (timeout {timeout_s}s)")
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env
    )
    _CURRENT_CHILD.append(proc)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        _, stderr = proc.communicate()
        _log(f"{label}: timed out after {timeout_s}s")
        if stderr:
            _log(f"{label}: stderr before kill: {stderr[-800:]}")
        return None, "", stderr or ""
    finally:
        if proc in _CURRENT_CHILD:
            _CURRENT_CHILD.remove(proc)
    for line in stderr.splitlines():
        print(line, file=sys.stderr, flush=True)
    if proc.returncode != 0:
        _log(f"{label}: rc={proc.returncode}; stderr tail: {stderr[-800:]}")
    return proc.returncode, stdout, stderr


def _git_sha() -> str:
    """Short SHA of HEAD (+ dirty marker) so every benchmark record names the
    exact code it measured — the round-2 chip artifact went stale against
    HEAD with nothing in the file to prove it (VERDICT r2 weak item 5).

    Deliberately duplicates utils/timing.py:git_sha: importing the package
    (even `utils.timing` alone) triggers the package __init__, which imports
    jax — and the orchestrator process must NEVER import jax, or a wedged
    tunnel can hang the orchestrator itself at interpreter startup."""
    cwd = os.path.dirname(os.path.abspath(__file__))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10, cwd=cwd,
        ).stdout.strip()
        # exclude the bench's own output artifacts: a run that only WROTE
        # results must not stamp itself dirty (round-3's chip record carried
        # "-dirty" purely because its stdout redirect pre-created the file)
        dirty = subprocess.run(
            ["git", "status", "--porcelain", "--",
             ".", ":(exclude)results", ":(exclude)bench_stderr.log"],
            capture_output=True, text=True, timeout=10, cwd=cwd,
        ).stdout.strip()
        return sha + ("-dirty" if dirty else "") if sha else "unknown"
    except Exception:  # noqa: BLE001 — never let stamping break the bench
        return "unknown"


def _tunnel_tcp_probe() -> dict:
    """TCP-level check of the tunnel relay endpoints (stdlib, ~instant).

    Distinguishes the two wedge modes a jax-level probe cannot: 'refused'
    (the relay process is not even listening — restart-side problem) vs
    'open' (listening but the claim/compile path is hung). Round 3 observed
    the former: during the 13h+ wedge nothing listened on any relay port.
    """
    import socket

    ips = [
        ip.strip()
        for ip in os.environ.get("PALLAS_AXON_POOL_IPS", "").split(",")
        if ip.strip()
    ]
    import errno

    out = {}
    for ip in ips[:4]:
        for port in (8081, 8082, 8083):  # axon claim/serve ports
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            # 0.5s: refused-vs-listening is one RTT on these loopback/pool
            # addresses, and the worst case (filtered port -> full timeout
            # on every socket) must not eat the vigil's re-probe budget
            s.settimeout(0.5)
            try:
                rc = s.connect_ex((ip, port))
                if rc == 0:
                    out[f"{ip}:{port}"] = "open"
                elif rc in (errno.EAGAIN, errno.EWOULDBLOCK, errno.EINPROGRESS):
                    # connect_ex reports an expired settimeout as EAGAIN —
                    # filtered/blackholed, NOT refused (different remediation)
                    out[f"{ip}:{port}"] = "timeout"
                else:
                    out[f"{ip}:{port}"] = f"closed({rc})"
            except OSError as e:
                out[f"{ip}:{port}"] = f"error({e})"
            finally:
                s.close()
    return out


def _claim_holder_snapshot() -> str:
    """Best-effort list of processes that could be wedging the tunnel (a hung
    client HOLDS the chip claim until it dies) — recorded on probe timeout so
    a lost round is at least diagnosable (VERDICT r2 weak item 2)."""
    try:
        ps = subprocess.run(
            ["ps", "-eo", "pid,etime,args"], capture_output=True, text=True,
            timeout=10,
        ).stdout
        mine = str(os.getpid())
        lines = [
            l for l in ps.splitlines()
            if any(k in l for k in ("jax", "axon", "bench", "python"))
            and l.strip().split()[0] != mine
            and "ps -eo" not in l
        ]
        return "\n".join(lines[:20])
    except Exception:  # noqa: BLE001
        return "unavailable"


def _parse_sentinel(stdout: str):
    for line in stdout.splitlines():
        if line.startswith(_SENTINEL):
            return json.loads(line[len(_SENTINEL) :])
    return None


# Per-attempt probe diagnostics for the emitted JSON: two rounds of headline
# numbers were lost to an environment failure the artifacts couldn't diagnose
# (VERDICT r2 weak item 2). Reset by main(); appended by _probe_once.
_PROBE_HISTORY: list = []


def _probe_once(env_overrides, label, t0, timeout_s=PROBE_TIMEOUT_S) -> bool:
    """One probe attempt, recorded in _PROBE_HISTORY with rc / duration /
    stderr tail (and, on a timeout, a snapshot of candidate claim-holders).
    ``timeout_s`` lets the vigil shrink probe work as timeouts repeat."""
    start = time.monotonic()
    rc, stdout, stderr = _spawn(label, ["--probe"], env_overrides, timeout_s)
    entry = {
        "t_offset_s": round(start - t0, 1),
        "rc": rc,
        "duration_s": round(time.monotonic() - start, 1),
        "timeout_s": timeout_s,
    }
    res = _parse_sentinel(stdout) if rc == 0 else None
    if res is not None:
        entry["backend"] = res["backend"]
        entry["wedge_state"] = "healthy"
    else:
        entry["stderr_tail"] = (stderr or "")[-400:]
        if rc is None:  # timeout = wedge; record who might hold the claim
            # the explicit wedge-state tag the evidence chain reads: a
            # probe TIMEOUT is the tunnel-wedge signature (a fast error is
            # the backend at least answering) — VERDICT r5 evidence gap
            entry["wedge_state"] = "wedged"
            entry["claim_holders"] = _claim_holder_snapshot()
            entry["tunnel_tcp"] = _tunnel_tcp_probe()
        else:
            entry["wedge_state"] = "error"
    _PROBE_HISTORY.append(entry)
    return res is not None


def _probe_until_healthy(env_overrides, label, t0=None, deadline=None) -> bool:
    """Short probe attempts with backoff until the backend answers.

    A hung probe holds no chip claim (it never gets past device init), so
    killing it on timeout cannot wedge the tunnel the way killing a
    mid-compile heavy worker does. Two failure modes get different budgets:
    a FAST error (rc != 0, e.g. "Unable to initialize backend") is often
    transient and worth the full retry schedule, but a probe TIMEOUT means
    the tunnel is wedged — observed to persist for hours — so two
    consecutive timeouts end this INITIAL round quickly. Main() then runs the
    CPU baseline (tunnel-independent) and hands the remaining budget to
    _accel_vigil rather than giving up on the round (VERDICT r2 item 1).

    ``deadline``: the orchestrator's wall budget. The retry schedule must
    never be the thing that eats the round — a probe (or its backoff) that
    would overrun the budget minus the CPU-baseline + emit reserve is
    skipped and the round falls through to the wedge path.
    """
    if t0 is None:
        t0 = time.monotonic()
    consecutive_timeouts = 0
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        if deadline is not None and (
            deadline - time.monotonic()
            < PROBE_TIMEOUT_S + MIN_ACCEL_REDUCED_S + EMIT_RESERVE_S
        ):
            # a success here could not be measured anyway (the attempt needs
            # MIN_ACCEL_REDUCED_S past the emit reserve even with the CPU
            # baseline sacrificed) — don't burn a probe on an unmeasurable
            # recovery; fall through to the wedge path so the CPU baseline
            # still lands
            _log(f"{label}: budget too low for probe+attempt; wedge path")
            return False
        ok = _probe_once(
            env_overrides, f"{label} probe {attempt}/{PROBE_ATTEMPTS}", t0
        )
        if ok:
            _log(f"{label} probe ok: backend {_PROBE_HISTORY[-1]['backend']}")
            return True
        rc = _PROBE_HISTORY[-1]["rc"]
        consecutive_timeouts = consecutive_timeouts + 1 if rc is None else 0
        if consecutive_timeouts >= 2:
            _log(f"{label}: two probe timeouts — tunnel wedged; "
                 "deferring to post-baseline vigil")
            return False
        if attempt < PROBE_ATTEMPTS:
            _log(f"{label} probe failed; backing off {PROBE_BACKOFF_S}s")
            time.sleep(PROBE_BACKOFF_S)
    return False


TCP_VIGIL_SPACING_S = 20


def _accel_vigil(env_overrides, t0, deadline) -> bool:
    """Re-probes until the tunnel answers or the budget is spent.

    Runs AFTER the CPU baseline is banked, so every minute here is a minute
    that could still win the round's accelerator record — the round-2 bench
    forfeited its window 3 minutes in and then idled through 7 minutes of
    CPU work with no re-probe (VERDICT r2 weak item 1).

    Two-tier cadence: the instant TCP relay check runs every 20s, and the
    expensive jax probe fires when a relay port opens — so a recovery is
    caught within seconds — or on the 3-minute schedule regardless, as a
    safety net against the port assumption being wrong.

    Probe work backs off as timeouts repeat (the r05 lesson: vigil probe 4
    burned a full 90 s timeout with the budget nearly spent, and the
    zshard section was then skipped): every consecutive probe TIMEOUT
    halves the next probe's timeout down to VIGIL_PROBE_MIN_TIMEOUT_S — a
    fast error (rc != 0) resets the backoff, since the tunnel is at least
    answering, and a healthy backend answers a probe in seconds, so the
    shrunken timeout still catches a real recovery. The caller's deadline
    additionally reserves the zshard section's slot (main()).
    """
    attempt = 0
    last_full_probe = -float("inf")
    probe_timeout = PROBE_TIMEOUT_S
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            _log("vigil: budget exhausted; emitting with what we have")
            return False
        tcp = _tunnel_tcp_probe()
        since_last = time.monotonic() - last_full_probe
        # rate-limit the relay-up trigger: a port that is open while the
        # claim path is hung must not turn the vigil into a 90s-timeout
        # probe hammer (stamped AFTER the probe so its own duration does
        # not count toward the interval)
        relay_up = any(v == "open" for v in tcp.values()) and since_last >= 60
        # spacing scales with the backed-off probe cost: a full 90 s probe
        # keeps the PROBE_VIGIL_SPACING_S (3-minute) cadence, a
        # halved-down 20 s probe re-probes every minute — the wall share
        # of probing stays ~1/3 while a late recovery is caught minutes
        # sooner (and the r05 failure mode of a single probe eating the
        # tail of the budget cannot recur)
        spacing = max(probe_timeout * PROBE_VIGIL_SPACING_S // PROBE_TIMEOUT_S, 60)
        due = since_last >= spacing
        if relay_up or due:
            if remaining < probe_timeout + MIN_ACCEL_REDUCED_S + EMIT_RESERVE_S:
                # a probe launched now either overshoots the wall budget or
                # recovers a tunnel there is no time left to measure on —
                # both are wasted wall; stop cleanly instead
                _log("vigil: budget too low for another probe+attempt; emitting")
                return False
            if relay_up:
                _log(f"vigil: relay TCP open ({tcp}); probing now")
            attempt += 1
            ok = _probe_once(
                env_overrides, f"vigil probe {attempt}", t0, probe_timeout
            )
            last_full_probe = time.monotonic()
            if ok:
                _log(f"vigil: tunnel recovered on re-probe {attempt}")
                return True
            if _PROBE_HISTORY and _PROBE_HISTORY[-1]["rc"] is None:
                probe_timeout = max(probe_timeout // 2, VIGIL_PROBE_MIN_TIMEOUT_S)
                _log(f"vigil: probe timed out; next probe capped at {probe_timeout}s")
            else:
                probe_timeout = PROBE_TIMEOUT_S
        time.sleep(min(TCP_VIGIL_SPACING_S, max(deadline - time.monotonic(), 1)))


# (label, sections-path) of the in-flight worker, so the SIGTERM handler can
# recover sections the worker banked before an external kill.
_CURRENT_SECTIONS: list = []


def _merge_sections(out_path, label) -> dict:
    """Fold a worker's per-section checkpoint file into one record."""
    merged: dict = {}
    try:
        with open(out_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    merged.update(json.loads(line))
                except json.JSONDecodeError:
                    # a timeout kill can land mid-write; drop the torn line
                    _log(f"{label}: dropping torn section line ({len(line)}B)")
    except OSError:
        pass
    return merged


def _run_measurement(label, worker_args, env_overrides, timeout_s):
    """One heavy-worker attempt; returns merged partial sections (or None).

    The worker appends each completed section to a temp file, so even a
    timeout kill returns everything measured up to the kill.
    """
    import tempfile

    fd, out_path = tempfile.mkstemp(prefix="bench_sections_", suffix=".jsonl")
    os.close(fd)
    _CURRENT_SECTIONS.append((label, out_path))
    sanitize_args = ["--sanitize"] if _SANITIZE else []
    try:
        rc, stdout = _spawn(
            label,
            ["--worker", *worker_args, *sanitize_args, "--out", out_path],
            env_overrides,
            timeout_s,
        )[:2]
        full = _parse_sentinel(stdout) if rc == 0 else None
        if full is not None:
            return full
        merged = _merge_sections(out_path, label)
        if merged:
            _log(f"{label}: recovered partial sections {sorted(merged)}")
        return merged or None
    finally:
        _CURRENT_SECTIONS[:] = [s for s in _CURRENT_SECTIONS if s[1] != out_path]
        os.unlink(out_path)


def _copy_optional(out: dict, rec: dict) -> None:
    """Carry a measurement record's optional sections into the emitted JSON."""
    for key in ("stages", "device_kind", "hbm_peak_gbps",
                "fused_min_traffic_gbps", "profile_dir", "student_tput",
                "volume", "xla_scan_tput", "scan_chunk",
                "scan_checksum_ok", "batch_note", "compile_cost",
                "cold_start", "result_cache", "feed_stall", "feed_streamed",
                "feed_streamed_by_batch", "streamed_batch_note",
                "device_time_pie"):
        if key in rec:
            out[key] = rec[key]


def _compose(accel, cpu, meta) -> dict:
    """Fold the accel/cpu worker records into the one emitted JSON object.

    Backend honesty (VERDICT r5 evidence-chain gap): the record always
    carries ``backend_requested`` (what this bench run was trying to
    measure — the environment's accelerator) next to ``backend_actual``
    (what the winning worker actually ran on), so a CPU-fallback record
    can never masquerade as a chip number even if a reader only keeps the
    headline fields. ``backend`` remains as the legacy alias of
    ``backend_actual``. ``wedge_observed`` summarizes the probe history's
    per-entry ``wedge_state`` tags.
    """
    out = {
        "metric": "slices_per_sec_per_chip",
        "value": 0.0,
        "unit": "slices/s",
        "vs_baseline": 0.0,
        # the orchestrator always *requests* the accelerator; only the
        # actually-measured backend may differ
        "backend_requested": "accelerator",
        # topology honesty next to the backend pair: the headline is a
        # single-chip number by definition; the multi-chip evidence lives
        # in the zshard_scaling section (its own mesh_shape/lanes +
        # serve_lane_tput — the replica-lane serving fleet measurement)
        "mesh_shape": [1],
        "lanes": 1,
    }
    out.update(meta)
    history = meta.get("probe_history") or []
    out["wedge_observed"] = any(
        e.get("wedge_state") == "wedged" for e in history
    )
    if accel is not None:
        tput = accel["xla_tput"]
        # only a result-identical pallas run may win the headline number —
        # a miscompiled kernel must not corrupt the benchmark record
        if accel.get("pallas_checksum_ok") and accel.get("pallas_tput", 0) > tput:
            tput = accel["pallas_tput"]
            out["winning_path"] = "pallas"
        else:
            out["winning_path"] = "xla"
        out["value"] = round(tput, 2)
        out["backend"] = out["backend_actual"] = accel["backend"]
        if "xla_batch" in accel:
            out["batch"] = accel["xla_batch"]
        if "xla_by_batch" in accel:
            out["xla_by_batch"] = accel["xla_by_batch"]
        if "pallas_tput" in accel:
            out["pallas_tput"] = round(accel["pallas_tput"], 2)
            out["pallas_checksum_ok"] = accel["pallas_checksum_ok"]
        _copy_optional(out, accel)
        if accel["backend"] == "cpu":
            out["vs_baseline"] = 1.0
            out["error"] = "no accelerator backend available; measured cpu only"
        elif cpu is not None:
            # same-program ratio: prefer the CPU measurement at the batch
            # size that won the accelerator sweep (the wedge-first CPU
            # baseline sweeps all of ACCEL_BATCH_SWEEP up front)
            base = cpu.get("xla_by_batch", {}).get(str(out.get("batch")))
            base = base if base else cpu["xla_tput"]
            out["cpu_baseline_tput"] = round(base, 2)
            out["vs_baseline"] = round(tput / base, 2)
            # sections the wedge-first CPU baseline measured but a shed
            # late-recovery accel attempt didn't: carry them under a
            # DISTINCT key — cpu-measured stage/volume numbers must never
            # masquerade as the record's (accelerator) sections
            diag = {
                k: cpu[k]
                for k in ("stages", "volume")
                if k in cpu and k not in out
            }
            if diag:
                out["cpu_diagnostics"] = diag
        else:
            out["vs_baseline"] = 1.0
            out["error"] = "cpu baseline worker failed; vs_baseline unknown"
    elif cpu is not None:
        out["value"] = round(cpu["xla_tput"], 2)
        out["backend"] = out["backend_actual"] = "cpu"
        out["vs_baseline"] = 1.0
        if "xla_batch" in cpu:
            out["batch"] = cpu["xla_batch"]
        if "xla_by_batch" in cpu:
            out["xla_by_batch"] = cpu["xla_by_batch"]
        _copy_optional(out, cpu)
        out["error"] = "accelerator worker failed; cpu fallback measured"
    else:
        out["backend"] = out["backend_actual"] = "none"
        out["error"] = "all measurement workers failed; see stderr"
    return out


def _measure_accel(deadline=None, cpu_banked=False):
    """One long-timeout accelerator attempt; None if the headline is lost.

    ``deadline``-aware (VERDICT r3 item 1): the attempt's timeout is capped
    so the orchestrator can still run the CPU baseline and emit inside the
    wall budget. When the cap leaves too little for the full program, the
    batch sweep / stage matrix / Pallas / student legs are shed first and a
    single headline batch is measured; when even that cannot fit, the
    attempt is skipped (an un-measurable recovery is not worth a mid-compile
    kill, which wedges the tunnel for whoever runs next).

    ``cpu_banked``: True on the vigil path, where the CPU baseline already
    ran and NO cpu work follows this attempt — reserving CPU_RESERVE_S
    there would double-count it and shed (or skip) late recoveries that
    genuinely fit, forfeiting the round's accelerator record.
    """
    timeout_s = ACCEL_TIMEOUT_S
    args = [
        "--reps",
        str(TPU_REPS),
        "--pallas",
        "--stages",
        "--volume",
        "--scan",
        "--batches",
        ",".join(str(b) for b in ACCEL_BATCH_SWEEP),
    ]
    if deadline is not None:
        reserve = EMIT_RESERVE_S + (0.0 if cpu_banked else CPU_RESERVE_S)
        remaining = deadline - time.monotonic() - reserve
        if remaining < MIN_ACCEL_REDUCED_S and not cpu_banked:
            # tight budget: a TPU headline with vs_baseline unknown beats a
            # CPU-only record — sacrifice the CPU-baseline reserve (the
            # emitted JSON carries the degradation in its error field)
            reserve = EMIT_RESERVE_S
            remaining = deadline - time.monotonic() - reserve
            if remaining - MIN_CPU_ATTEMPT_S >= MIN_ACCEL_REDUCED_S:
                # keep a minimal baseline viable when the attempt still fits
                # beside it — a timeout-killed attempt then degrades to the
                # CPU record instead of "all measurement workers failed"
                remaining -= MIN_CPU_ATTEMPT_S
            _log("accel: sacrificing the CPU-baseline reserve for the attempt")
        if remaining < MIN_ACCEL_REDUCED_S:
            _log(f"accel: {remaining:.0f}s left — no room for an attempt; skipping")
            return None
        if remaining < MIN_ACCEL_FULL_S:
            _log(
                f"accel: {remaining:.0f}s left — shedding sweep/stages/"
                "pallas/student; headline batch only"
            )
            args = ["--reps", str(TPU_REPS), "--batches", str(BATCH)]
        timeout_s = min(ACCEL_TIMEOUT_S, remaining)
    accel = _run_measurement("accel measurement", args, {}, timeout_s)
    # a partial record without the headline number is useless — treat as lost
    if accel is not None and "xla_tput" not in accel:
        _log(f"accel sections incomplete ({sorted(accel)}); discarding")
        accel = None
    return accel


_CPU_ENV = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": None}

ZSHARD_TIMEOUT_S = 240


def _measure_zshard(deadline):
    """Spawn the z-shard scaling worker on an 8-virtual-device CPU mesh;
    returns its record or None (skipped under budget pressure / failure)."""
    remaining = deadline - time.monotonic() - EMIT_RESERVE_S
    if remaining < 90:
        _log("zshard scaling: budget too low; skipping")
        return None
    env = dict(_CPU_ENV)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    env["XLA_FLAGS"] = flags
    rc, stdout, _ = _spawn(
        "zshard scaling", ["--zshard-scaling"], env,
        min(ZSHARD_TIMEOUT_S, remaining),
    )
    return _parse_sentinel(stdout) if rc == 0 else None


# abspath: a bare-filename override would give _bank_partial an empty
# dirname, whose makedirs('') OSError is silently swallowed — and the
# SIGKILL-proof banked record would never be written
_PARTIAL_PATH = os.path.abspath(
    os.environ.get("NM03_BENCH_PARTIAL_PATH")
    or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", "bench_partial.json"
    )
)


def _bank_partial(state) -> None:
    """Write the would-be JSON to results/bench_partial.json: SIGKILL-proof
    on-disk evidence of everything measured so far (stdout still carries
    exactly one line, at the end). Written atomically — a kill mid-write
    must not destroy the previously banked record."""
    try:
        os.makedirs(os.path.dirname(_PARTIAL_PATH), exist_ok=True)
        tmp = _PARTIAL_PATH + f".{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(_compose(state["accel"], state["cpu"], state["meta"]), f)
        os.replace(tmp, _PARTIAL_PATH)
    except OSError:
        pass


# PIPE_BUF-safe budget for the final line: a single write of <= 4096 bytes
# to a pipe is atomic (POSIX), so a merged (2>&1) stream cannot interleave
# stderr chatter INSIDE the record; 4000 leaves room for the framing
# newlines
_FINAL_LINE_CAP = 4000
# True only when bench.py runs as the orchestrator script — the emit path
# then parks fd 2 on /dev/null after the record so nothing (interpreter
# teardown noise included) can land after the final line. In-process test
# callers keep their streams.
_AS_SCRIPT = False
# --sanitize: thread the runtime-twin flag to every measurement worker and
# fold their reported compile counts into pipeline_recompiles_total
# (docs/STATIC_ANALYSIS.md; the orchestrator itself never imports jax)
_SANITIZE = False
# fields the final line always keeps, whatever the shedding pressure
# (backend_requested/actual are the honesty pair: the slim line must never
# shed the evidence that a number was NOT measured on the chip)
_SLIM_REQUIRED = ("metric", "value", "unit", "vs_baseline", "backend",
                  "backend_requested", "backend_actual", "wedge_observed",
                  "mesh_shape", "lanes", "error", "detail",
                  # the ledger pie rides the slim line (ISSUE 16): small
                  # (~6 shares + one scalar), checksum-gated, and the
                  # record-side anchor check_perf baselines come from
                  "device_time_pie")


def _slim_record(record: dict) -> dict:
    """The stdout copy of the record: headline + small fixed fields only.

    The driver reads bench through ``2>&1 | tail -N`` and json-parses the
    last line, so that line must be small and tear-proof (VERDICT r4 item
    1). Unbounded diagnostics — probe history with its ps/TCP snapshots —
    live exclusively in the banked file; the line points at it via
    ``detail``. If the slim record still exceeds the cap, optional sections
    are shed largest-first until it fits; the headline fields and the
    pointer always survive.
    """
    slim = {k: v for k, v in record.items() if k != "probe_history"}
    slim["detail"] = _PARTIAL_PATH
    while len(json.dumps(slim)) > _FINAL_LINE_CAP:
        droppable = [k for k in slim if k not in _SLIM_REQUIRED]
        if not droppable:
            break
        slim.pop(max(droppable, key=lambda k: len(json.dumps(slim[k]))))
    return slim


def _record_path_metrics(record) -> None:
    """Mirror which median/render path the measured pipeline ran (and its
    comparator counts) into the metrics registry, so a ``--metrics-out``
    snapshot is self-describing (ISSUE 2 satellite). Delegates to
    ``RunContext.record_pipeline_paths`` — the single owner of these
    series — with every value derived from the worker's record (plain
    dict reads; the orchestrator never imports jax): the stage matrix
    measures the default PipelineConfig, i.e. the pruned XLA median and
    the fused render, and a checksum-gated Pallas headline win means the
    Pallas (shared-plan) path is what the record's number ran.
    """
    if _OBS_CTX is None or not record:
        return
    with contextlib.suppress(Exception):  # telemetry never costs a record
        stages = record.get("stages") or {}
        winning = str(record.get("winning_path", "xla"))
        _OBS_CTX.record_pipeline_paths(
            median_impl="pruned",  # PipelineConfig default the worker measures
            render_fused="fused_vs_unfused_speedup" in (stages.get("render") or {}),
            # the pallas leg measures PipelineConfig(use_pallas=True), whose
            # fuse_preprocess default routes the fused kernel on chip
            fuse_preprocess=winning == "pallas",
            use_pallas=winning == "pallas",
            comparators=(stages.get("median7") or {}).get("comparators"),
            winning_path=winning,
        )


def _emit_final(state) -> None:
    """Bank the full record, then put exactly ONE short JSON line on stdout.

    The line is framed by newlines and written through a just-flushed
    stream, so the whole thing reaches the pipe as one <= PIPE_BUF write:
    atomic, untearable, and — thanks to the LEADING newline — immune to a
    dangling partial stderr line earlier in a merged (2>&1) stream. In
    script mode stderr is then parked on /dev/null so no late chatter can
    land after the record.
    """
    if _OBS_CTX is not None:
        # the banked record embeds the metrics snapshot (phase latency
        # histograms, phase counters) next to the measured numbers; the
        # slim stdout line sheds it under size pressure like any optional
        # section. close() also writes --metrics-out / run_finished.
        _record_path_metrics(state.get("accel") or state.get("cpu"))
        if _SANITIZE:
            # one coherent counter across the sanitized workers: created at
            # 0 even when every worker was lost, so a --sanitize snapshot
            # always carries the series
            with contextlib.suppress(Exception):
                from nm03_capstone_project_tpu.utils import sanitize as _san

                total = sum(
                    int(r.get("sanitize_recompiles", 0))
                    for r in (state.get("accel"), state.get("cpu"))
                    if r
                )
                _san.record_external_recompiles(_OBS_CTX.registry, total)
        with contextlib.suppress(Exception):
            state["meta"]["metrics"] = _OBS_CTX.metrics_snapshot()
            _OBS_CTX.close(
                status="ok" if state.get("accel") or state.get("cpu") else "error"
            )
    _bank_partial(state)  # the on-disk copy carries the full diagnostics
    record = _compose(state["accel"], state["cpu"], state["meta"])
    line = json.dumps(_slim_record(record))
    sys.stderr.flush()
    sys.stdout.flush()
    sys.stdout.write("\n" + line + "\n")
    sys.stdout.flush()
    if _AS_SCRIPT:
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, 2)
            os.close(devnull)
        except OSError:
            pass


def main(metrics_out: str | None = None, log_json: str | None = None) -> None:
    # Flow (VERDICT r2 item 1): quick accel probe round; on success, one
    # long-timeout accel attempt. If the tunnel is wedged (or the attempt
    # lost), bank the tunnel-independent CPU baseline IMMEDIATELY, then keep
    # re-probing at PROBE_VIGIL_SPACING_S until the overall wall budget
    # (NM03_BENCH_VIGIL_BUDGET_S, default 25 min — strictly inside the
    # driver's 30 min kill) is spent — only then emit. EVERY phase is capped
    # against the deadline (VERDICT r3 item 1): probe retries, the accel
    # attempt (shedding sweep/stages first), the CPU baseline, the vigil.
    # The orchestrator never imports jax; all measurement is in subprocess
    # workers with hard timeouts, and probe diagnostics land in the JSON.
    t0 = time.monotonic()
    budget_s = float(os.environ.get(VIGIL_BUDGET_ENV, VIGIL_BUDGET_DEFAULT_S))
    deadline = t0 + budget_s
    global _OBS_CTX
    if _OBS_CTX is None and (metrics_out or log_json):
        from nm03_capstone_project_tpu.obs import RunContext

        # heartbeat keeps the event stream alive through the (silent) wedge
        # vigil, so a tail -f can tell "waiting on the tunnel" from "hung"
        _OBS_CTX = RunContext.create(
            "bench",
            metrics_out=metrics_out,
            log_json=log_json,
            heartbeat_s=60.0,
        )
    _PROBE_HISTORY.clear()
    try:
        # a stale banked record from a previous run must not masquerade as
        # this run's if we are killed before the first bank
        os.unlink(_PARTIAL_PATH)
    except OSError:
        pass
    state = {
        "accel": None,
        "cpu": None,
        "meta": {"git_sha": _git_sha(), "probe_history": _PROBE_HISTORY},
    }

    def _on_term(signum, frame):
        # an external kill (driver timeout) mid-vigil must not cost the
        # round its record: emit best-so-far and go down with rc 0. The
        # in-flight worker is killed too — a hung client HOLDS the chip
        # claim, so orphaning it would wedge the tunnel for whoever runs
        # after us — and the sections it banked before the kill are
        # recovered so a mid-measurement kill still keeps its headline.
        for proc in list(_CURRENT_CHILD):
            try:
                proc.kill()
            except OSError:
                pass
        for label, path in list(_CURRENT_SECTIONS):
            merged = _merge_sections(path, label)
            key = "accel" if "accel" in label else "cpu"
            if merged.get("xla_tput") and state[key] is None:
                state[key] = merged
        state["meta"]["terminated"] = "signal mid-run; emitted best-so-far"
        state["meta"]["elapsed_s"] = round(time.monotonic() - t0, 1)
        _emit_final(state)
        os._exit(0)

    old_term = signal.signal(signal.SIGTERM, _on_term)
    # SIGALRM backstop: if any phase wedges past its cap (e.g. an unkillable
    # worker blocking communicate()), the alarm forces the best-so-far emit
    # well before the external driver's kill. Cancelled before the normal
    # emit so the record can never be printed twice.
    old_alrm = signal.signal(signal.SIGALRM, _on_term)
    signal.alarm(int(budget_s + EMIT_RESERVE_S))

    def _measure_cpu(batch_args):
        """Deadline-capped CPU-baseline attempt; None when lost or skipped."""
        timeout_s = min(CPU_TIMEOUT_S, deadline - time.monotonic() - EMIT_RESERVE_S)
        if timeout_s < MIN_CPU_ATTEMPT_S:
            _log("cpu baseline: budget too low; skipping")
            return None
        cpu = _run_measurement(
            "cpu baseline",
            ["--platform", "cpu", "--reps", str(CPU_REPS), *batch_args],
            _CPU_ENV,
            timeout_s,
        )
        return cpu if cpu and "xla_tput" in cpu else None

    # state is the single source of truth for what has been measured — the
    # SIGTERM handler and the banked on-disk record both read it
    if _probe_until_healthy({}, "accel", t0, deadline):
        _obs_event("bench_phase", phase="accel_attempt")
        with _obs_span("accel"):
            state["accel"] = _measure_accel(deadline)
        # bank before the CPU baseline: a kill during that phase must not
        # cost the already-measured accelerator record
        _bank_partial(state)

    if state["accel"] is None:
        # tunnel wedged or attempt lost — bank the CPU baseline first (it
        # cannot touch the tunnel), sweeping every accel batch size so the
        # ratio stays same-program whatever batch later wins on the chip,
        # and carrying the stage breakdown + volume leg for diagnosability
        # (sections checkpoint incrementally: if the volume leg overruns
        # the worker timeout, only it is lost, never the headline). The
        # extra legs cost ~90 s of LOCAL compute against the vigil budget —
        # accepted: they are bounded (no tunnel involvement, nothing to
        # hang on) and a wedged round's record is exactly where the
        # diagnostics matter most.
        _obs_event("bench_phase", phase="cpu_baseline", accel_lost=True)
        with _obs_span("cpu_baseline"):
            state["cpu"] = _measure_cpu(
                ["--batches", ",".join(str(b) for b in ACCEL_BATCH_SWEEP),
                 "--stages", "--volume"]
            )
        # bank the best-so-far record to a file before entering the vigil:
        # stdout still carries exactly ONE line at the end, but if an
        # external supervisor hard-kills (SIGKILL) mid-vigil — which no
        # handler can catch — the round's measurement survives on disk
        _bank_partial(state)
        # now spend whatever budget remains waiting for the tunnel; a late
        # recovery gets a deadline-capped (possibly shed) attempt with no
        # CPU reserve — the baseline above is the only cpu work this path
        # does. The vigil's own deadline additionally reserves the zshard
        # slot (r05 skipped that section entirely after the vigil ate the
        # tail of the budget); a recovered tunnel's ACCEL attempt still
        # gets the full deadline — an accelerator record outranks the
        # virtual-mesh curve.
        _obs_event("bench_phase", phase="vigil")
        if _accel_vigil({}, t0, deadline - ZSHARD_RESERVE_S):
            _obs_event("bench_phase", phase="accel_attempt", late_recovery=True)
            with _obs_span("accel"):
                state["accel"] = _measure_accel(deadline, cpu_banked=True)
            _bank_partial(state)
    elif state["accel"]["backend"] != "cpu":
        # accel record in hand: CPU baseline at exactly the winning batch
        _obs_event("bench_phase", phase="cpu_baseline")
        with _obs_span("cpu_baseline"):
            state["cpu"] = _measure_cpu(
                ["--batches", str(state["accel"].get("xla_batch", BATCH))]
            )

    # z-shard scaling curve: tunnel-independent (virtual CPU mesh), cheap,
    # and the 3D path's only multi-device perf signal (VERDICT r3 item 5)
    _obs_event("bench_phase", phase="zshard_scaling")
    with _obs_span("zshard_scaling"):
        z = _measure_zshard(deadline)
    if z is not None:
        state["meta"]["zshard_scaling"] = z

    state["meta"]["elapsed_s"] = round(time.monotonic() - t0, 1)
    # nothing left but pure host bank+compose+write: the alarm's job is
    # done, and cancelling it first means the record can never hit stdout
    # twice
    signal.alarm(0)
    _emit_final(state)
    # only restore AFTER the record is on stdout — restoring first would
    # reopen the very lost-record window the handler exists to close
    signal.signal(signal.SIGTERM, old_term)
    signal.signal(signal.SIGALRM, old_alrm)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--probe", action="store_true")
    parser.add_argument("--zshard-scaling", action="store_true")
    parser.add_argument("--platform", default=None)
    parser.add_argument("--reps", type=int, default=TPU_REPS)
    parser.add_argument("--pallas", action="store_true")
    parser.add_argument("--stages", action="store_true")
    parser.add_argument("--volume", action="store_true")
    parser.add_argument("--scan", action="store_true")
    parser.add_argument("--out", default=None)
    parser.add_argument("--batches", default=str(BATCH), help="comma list to sweep")
    parser.add_argument(
        "--sanitize", action="store_true",
        help="runtime twins of the nm03-lint static rules "
        "(docs/STATIC_ANALYSIS.md): jax_debug_nans + transfer guard around "
        "the dispatch loop + recompile watchdog in every worker; compile "
        "counts land in pipeline_recompiles_total in the --metrics-out "
        "snapshot. Debug/CI mode — numbers measured under it are not "
        "comparable to unsanitized rounds",
    )
    parser.add_argument(
        "--synthetic", action="store_true",
        help="measure on synthetic phantom slices (always the case: bench "
        "generates its inputs; the flag exists for driver-parity in CI "
        "recipes)",
    )
    parser.add_argument(
        "--metrics-out", default=None,
        help="write the orchestrator's metrics snapshot here "
        "(schema nm03.metrics.v1, docs/OBSERVABILITY.md)",
    )
    parser.add_argument(
        "--write-perf-baseline", default=None, metavar="PATH",
        help="measure the stage matrix in-process and write the perf "
        "baseline scripts/check_perf.py gates against (schema "
        "nm03.perf_baseline.v1; refuses to write on a staged/fused "
        "checksum mismatch); standalone mode — no orchestrator run",
    )
    parser.add_argument(
        "--log-json", default=None,
        help="write structured orchestrator events here (bench phases, "
        "60 s heartbeat through the vigil; schema nm03.events.v1; one run "
        "per file — truncated at start)",
    )
    ns = parser.parse_args()
    _AS_SCRIPT = True
    _SANITIZE = ns.sanitize
    if ns.write_perf_baseline:
        raise SystemExit(
            write_perf_baseline(
                ns.write_perf_baseline, ns.platform, ns.reps
            )
        )
    if ns.probe:
        probe(ns.platform)
    elif ns.zshard_scaling:
        zshard_scaling()
    elif ns.worker:
        worker(
            ns.platform,
            ns.reps,
            ns.pallas,
            ns.stages,
            ns.out,
            tuple(int(b) for b in ns.batches.split(",")),
            want_volume=ns.volume,
            want_scan=ns.scan,
            sanitize_on=ns.sanitize,
        )
    else:
        main(metrics_out=ns.metrics_out, log_json=ns.log_json)
