"""Benchmark: DICOM slices/sec/chip through the fused segmentation pipeline.

Prints ONE JSON line:
    {"metric": "slices_per_sec_per_chip", "value": N, "unit": "slices/s",
     "vs_baseline": R, "backend": "...", "stages": {...}, ...}

``value`` is the throughput of the full 7-op pipeline (normalize → clip →
7x7 vector median → sharpen → seeded region growing → cast → dilate,
the reference's batch-driver contract, src/sequential/main_sequential.cpp:170-272)
vmapped over a 256x256 slice batch on ONE device of the default jax backend
(the TPU chip under the driver).

``vs_baseline`` is the speedup over the same program executed on the CPU
backend — the stand-in for the reference's OpenMP-parallel CPU driver
(src/parallel/main_parallel.cpp:336; XLA:CPU also uses the host's cores, so
this is parallel-CPU vs one TPU chip, the north-star ratio in BASELINE.json).
The accelerator sweeps batch sizes (ACCEL_BATCH_SWEEP) and the best
slices/s wins; the CPU baseline then runs at the SAME winning batch so the
ratio stays program-for-program.

Robustness architecture (the round-1 lesson, plus the round-2 discovery that
killing a worker mid-TPU-claim wedges the tunnel for everyone after): the
orchestrator process never imports jax. Each measurement runs in a
subprocess with a hard timeout —

* a cheap PROBE worker (devices + tiny jit) gates the expensive run: the
  orchestrator retries the probe with backoff until the tunnel answers, so
  the heavy worker's long timeout is only ever spent on real work, and a
  wedged tunnel costs a few short probe kills (harmless — a hung
  ``jax.devices()`` holds no chip claim yet), not a mid-compile kill;
* the accelerator worker inherits the environment (so the tunneled TPU
  backend registers), gets ONE long-timeout attempt, and appends each
  completed section (xla / pallas / stages) to a results file as it goes —
  a timeout loses only the unfinished section, never the headline;
* the CPU-baseline worker runs with JAX_PLATFORMS=cpu and the TPU tunnel
  env scrubbed, so it can never dial (or hang on) the accelerator;
* whatever happens, the orchestrator emits the JSON line, with a
  ``backend`` field saying what was actually measured and an ``error``
  field when a path was lost.

Timing methodology (inside the workers): the output is reduced to a scalar
checksum ON DEVICE and the scalar is fetched to host — a device_get is the
only synchronization that is trustworthy on every platform (on the tunneled
TPU backend, ``block_until_ready`` returns before execution finishes and a
bare sync costs ~66 ms of round-trip latency). ``reps`` executions are
enqueued back-to-back and synced once; single-device PjRt streams execute
FIFO, so fetching the last result charges the full compute of all reps to
the measured window while amortizing the tunnel latency across them.

The ``stages`` block is the per-stage device-time breakdown (VERDICT round 1
item 7): each pipeline stage jitted and timed in isolation with the same
enqueue-then-sync methodology, plus a qualitative bound classification.

All progress chatter goes to stderr; stdout carries only the JSON line
(workers mark their result line with a sentinel the orchestrator strips).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

BATCH = 32
# the accelerator worker sweeps these and reports the best slices/s — batch
# size is free to choose when the metric is throughput, and bigger batches
# amortize dispatch/sync better on the chip; the CPU baseline then reruns
# at the winning size so vs_baseline stays a same-program ratio
ACCEL_BATCH_SWEEP = (32, 128)
CANVAS = 256
TPU_REPS = 40
CPU_REPS = 2
STAGE_REPS = 48

PROBE_TIMEOUT_S = 90
PROBE_ATTEMPTS = 6
PROBE_BACKOFF_S = 45
ACCEL_TIMEOUT_S = 900  # ONE attempt; killing mid-compile wedges the tunnel
CPU_TIMEOUT_S = 420

_SENTINEL = "@@BENCH_RESULT@@"

# Qualitative bound per stage, justified by the measured ms next to it:
# elementwise/render stream HBM with trivial FLOPs/byte (memory-bound on the
# VPU); the 7x7 vector median does a 49-candidate rank-select per pixel
# (compute-bound on the VPU); region growing is an iterative fixpoint whose
# cost is sequential sweeps, not bytes (iteration/latency-bound).
_STAGE_BOUND = {
    "normalize_clip": "memory (VPU elementwise, HBM-limited)",
    "median7": "compute (VPU Batcher-merge network, column presort)",
    "sharpen": "memory (9-tap shifted-add sweeps, HBM-limited)",
    "region_grow": "iteration (sequential one-ring fixpoint sweeps)",
    "region_grow_jump": "iteration (O(log) pointer-jumping schedule)",
    "cast_dilate": "memory (VPU reduce-window, HBM-limited)",
    "render": "memory (gather + compositing, HBM-limited)",
}


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


# --------------------------------------------------------------------------
# worker mode — the only code paths that import jax
# --------------------------------------------------------------------------


def _make_batch(batch: int | None = None):
    import numpy as np

    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    if batch is None:
        batch = BATCH  # resolved at call time: tests monkeypatch BATCH
    pixels = np.stack(
        [
            phantom_slice(CANVAS, CANVAS, seed=i, lesion_radius=0.12 + 0.002 * i)
            for i in range(batch)
        ]
    ).astype(np.float32)
    dims = np.full((batch, 2), CANVAS, np.int32)
    return pixels, dims


def _bench_on(device, pixels, dims, reps, use_pallas=False):
    """(slices/sec, checksum) of the jitted vmapped pipeline on one device.

    ``use_pallas`` routes the hot ops (7x7 median, region growing) through
    the Pallas TPU kernels; lowering failures propagate — the caller decides
    the fallback.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch

    cfg = PipelineConfig(use_pallas=use_pallas)

    def f(px, dm):
        # Scalar checksum: forces the whole pipeline to run, and fetching it
        # is a 4-byte transfer — honest sync without paying a 2 MB pull
        # through the TPU tunnel per rep.
        mask = process_batch(px, dm, cfg)["mask"]
        return mask.astype(jnp.int32).sum()

    px = jax.device_put(jnp.asarray(pixels), device)
    dm = jax.device_put(jnp.asarray(dims), device)
    fn = jax.jit(f)

    t0 = time.perf_counter()
    checksum = int(fn(px, dm))  # device_get = real synchronization
    _log(
        f"{device.platform}{' (pallas)' if use_pallas else ''}: "
        f"compile+first run {time.perf_counter() - t0:.1f}s"
    )
    if checksum <= 0:
        _log("WARNING: pipeline segmented nothing — benchmark suspect")

    t0 = time.perf_counter()
    results = [fn(px, dm) for _ in range(reps)]  # enqueue, FIFO stream
    int(results[-1])  # one sync: FIFO order implies all earlier reps finished
    elapsed = time.perf_counter() - t0
    return pixels.shape[0] * reps / elapsed, checksum


def _bench_student(device, pixels, dims, reps):
    """slices/s of the deployed 2D student (cli.runner._student_batch_mask)
    with train-default architecture, same enqueue-then-sync methodology."""
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.cli.runner import _student_batch_mask
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.models import init_unet

    cfg = PipelineConfig()
    params = jax.device_put(init_unet(jax.random.PRNGKey(0), base=16), device)
    px = jax.device_put(jnp.asarray(pixels), device)
    dm = jax.device_put(jnp.asarray(dims), device)
    fn = jax.jit(
        lambda p, d: _student_batch_mask(params, p, d, cfg).astype(jnp.int32).sum()
    )
    int(fn(px, dm))  # compile + warm-up sync
    t0 = time.perf_counter()
    outs = [fn(px, dm) for _ in range(reps)]
    int(outs[-1])
    return pixels.shape[0] * reps / (time.perf_counter() - t0)


def _time_stage(fn, args, reps):
    """Seconds per call: jit, warm up, enqueue ``reps``, one checksum sync."""
    import jax
    import jax.numpy as jnp

    def with_checksum(*a):
        out = fn(*a)
        leaves = jax.tree_util.tree_leaves(out)
        return sum(jnp.asarray(leaf).astype(jnp.float32).sum() for leaf in leaves)

    jitted = jax.jit(with_checksum)
    float(jitted(*args))  # compile + warm-up, device_get sync
    t0 = time.perf_counter()
    outs = [jitted(*args) for _ in range(reps)]
    float(outs[-1])  # FIFO stream: last result implies all reps done
    return (time.perf_counter() - t0) / reps


def _stage_times(device, pixels, dims, reps):
    """Per-stage device time (ms per 32-slice batch), stages jitted alone.

    The fused pipeline is faster than the sum (XLA melts the elementwise
    stages into neighbours); this is the attribution breakdown, not a second
    throughput claim.
    """
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.ops.elementwise import (
        cast_uint8,
        clip_intensity,
        normalize,
    )
    from nm03_capstone_project_tpu.ops.morphology import dilate
    from nm03_capstone_project_tpu.ops.neighborhood import extend_edges
    from nm03_capstone_project_tpu.ops.pallas_median import median_filter
    from nm03_capstone_project_tpu.ops.sharpen import sharpen
    from nm03_capstone_project_tpu.core.image import valid_mask
    from nm03_capstone_project_tpu.pipeline.slice_pipeline import segment
    from nm03_capstone_project_tpu.render.render import render_pair

    import dataclasses

    cfg = PipelineConfig()
    cfg_jump = dataclasses.replace(cfg, grow_algorithm="jump")
    px = jax.device_put(jnp.asarray(pixels), device)
    dm = jax.device_put(jnp.asarray(dims), device)

    def vm(f):
        return jax.vmap(f)

    f_norm = vm(
        lambda p, d: clip_intensity(
            normalize(
                extend_edges(p, d),
                cfg.norm_low,
                cfg.norm_high,
                cfg.norm_intensity_min,
                cfg.norm_intensity_max,
            ),
            cfg.clip_low,
            cfg.clip_high,
        )
    )
    f_med = vm(lambda p: median_filter(p, cfg.median_window))
    f_sharp = vm(
        lambda p: sharpen(p, cfg.sharpen_gain, cfg.sharpen_sigma, cfg.sharpen_kernel)
    )
    f_grow = vm(lambda p, d: segment(p, d, cfg))
    f_grow_jump = vm(lambda p, d: segment(p, d, cfg_jump))
    f_post = vm(
        lambda s, d: dilate(cast_uint8(s), cfg.morph_size)
        * valid_mask(d, s.shape[-2:]).astype(jnp.uint8)
    )
    f_render = vm(lambda p, m, d: render_pair(p, m, d, cfg))

    # materialize each stage's input once (device-resident, off the clock)
    normed = jax.jit(f_norm)(px, dm)
    med = jax.jit(f_med)(normed)
    pre = jax.jit(f_sharp)(med)
    seg = jax.jit(f_grow)(pre, dm)
    mask = jax.jit(f_post)(seg, dm)

    stages = {}
    for name, fn, args in (
        ("normalize_clip", f_norm, (px, dm)),
        ("median7", f_med, (normed,)),
        ("sharpen", f_sharp, (med,)),
        ("region_grow", f_grow, (pre, dm)),
        ("region_grow_jump", f_grow_jump, (pre, dm)),
        ("cast_dilate", f_post, (seg, dm)),
        ("render", f_render, (px, mask, dm)),
    ):
        ms = _time_stage(fn, args, reps) * 1e3
        stages[name] = {"ms_per_batch": round(ms, 3), "bound": _STAGE_BOUND[name]}
        _log(f"stage {name}: {ms:.2f} ms/batch ({_STAGE_BOUND[name]})")
    # region_grow_jump is an ALTERNATIVE schedule for the region_grow stage,
    # not an additional pipeline stage — keep it out of the share denominator
    total = sum(
        s["ms_per_batch"] for n, s in stages.items() if n != "region_grow_jump"
    )
    for name, s in stages.items():
        if total and name != "region_grow_jump":
            s["share"] = round(s["ms_per_batch"] / total, 3)
    return stages


def _pin_platform(platform: str | None):
    """Pin the backend before jax initializes (belt and braces: env is set by
    the parent, but a PJRT plugin loaded via sitecustomize may have re-pinned
    jax.config at interpreter startup — see tests/conftest.py)."""
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
        import jax

        jax.config.update("jax_platforms", platform)


def probe(platform: str | None) -> None:
    """Tunnel health check: devices + a tiny jit round trip, nothing more."""
    _pin_platform(platform)
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    x = jax.device_put(jnp.ones((128, 128), jnp.float32), dev)
    val = float(jax.jit(lambda a: (a @ a).sum())(x))
    assert val == 128.0 * 128 * 128
    print(_SENTINEL + json.dumps({"backend": dev.platform}), flush=True)


def worker(
    platform: str | None,
    reps: int,
    want_pallas: bool,
    want_stages: bool,
    out_path: str | None,
    batches: tuple | None = None,
):
    """Measure on this process's backend.

    ``batches`` is swept on the XLA path and the best slices/s wins (batch
    size is a free choice when the metric is throughput); the Pallas path
    and its checksum comparison run at the winning batch. Each completed
    section is appended to ``out_path`` immediately (one JSON line per
    section), so a parent-side timeout loses only the section in flight.
    The merged result also goes to stdout behind a sentinel.
    """
    if batches is None:
        batches = (BATCH,)  # resolved at call time: tests monkeypatch BATCH
    _pin_platform(platform)
    import jax

    def emit(update: dict):
        result.update(update)
        if out_path:
            with open(out_path, "a") as f:
                f.write(json.dumps(update) + "\n")

    devices = jax.devices()
    dev = devices[0]
    from nm03_capstone_project_tpu.core.backend import _TPU_PLATFORMS

    on_tpu = dev.platform in _TPU_PLATFORMS
    _log(f"worker backend: {dev.platform} ({len(devices)} devices)")

    result: dict = {}
    emit({"backend": dev.platform})
    by_batch: dict = {}
    best = None  # (tput, batch, checksum, pixels, dims)
    for b in batches:
        pixels, dims = _make_batch(b)
        tput, xla_sum = _bench_on(dev, pixels, dims, reps, use_pallas=False)
        by_batch[str(b)] = round(tput, 2)
        _log(f"{dev.platform} XLA throughput @batch={b}: {tput:.2f} slices/s")
        if best is None or tput > best[0]:
            best = (tput, b, xla_sum, pixels, dims)
        # checkpoint progress after every batch size — a timeout keeps the
        # sizes measured so far
        emit(
            {
                "xla_tput": best[0],
                "xla_batch": best[1],
                "checksum": best[2],
                "xla_by_batch": dict(by_batch),
            }
        )
    tput, batch, xla_sum, pixels, dims = best

    if want_pallas and on_tpu:
        try:
            p_tput, p_sum = _bench_on(dev, pixels, dims, reps, use_pallas=True)
            agrees = p_sum == xla_sum
            emit({"pallas_tput": p_tput, "pallas_checksum_ok": agrees})
            _log(
                f"tpu pallas throughput @batch={batch}: {p_tput:.2f} slices/s "
                f"(checksum {'matches' if agrees else 'MISMATCH — discarded'})"
            )
        except Exception as e:  # noqa: BLE001 — pallas lowering failure
            emit({"pallas_error": f"{e!r:.500}"})
            _log(f"pallas path failed, XLA ops only: {e!r:.500}")

    if want_stages:
        try:
            # stage attribution stays at the reference batch (32) so the
            # breakdown is comparable across rounds
            s_pixels, s_dims = _make_batch(BATCH)
            emit({"stages": _stage_times(dev, s_pixels, s_dims, STAGE_REPS)})
        except Exception as e:  # noqa: BLE001 — never lose the headline number
            emit({"stages_error": f"{e!r:.500}"})
            _log(f"stage timing failed: {e!r:.500}")
        try:
            # the deployment path (--model): distilled-student throughput at
            # the winning batch. Weights don't affect speed, so a fresh init
            # measures the real path without shipping a checkpoint.
            s_tput = _bench_student(dev, pixels, dims, reps)
            emit({"student_tput": round(s_tput, 2)})
            _log(f"{dev.platform} student throughput: {s_tput:.2f} slices/s")
        except Exception as e:  # noqa: BLE001
            emit({"student_error": f"{e!r:.500}"})
            _log(f"student timing failed: {e!r:.500}")

    print(_SENTINEL + json.dumps(result), flush=True)


# --------------------------------------------------------------------------
# orchestrator — no jax; subprocess workers with hard timeouts
# --------------------------------------------------------------------------


def _spawn(label, extra_args, env_overrides, timeout_s):
    """Run this file in a subprocess; (rc, stdout) with rc=None on timeout."""
    env = os.environ.copy()
    for key, val in env_overrides.items():
        if val is None:
            env.pop(key, None)
        else:
            env[key] = val
    cmd = [sys.executable, os.path.abspath(__file__), *extra_args]
    _log(f"{label}: spawning (timeout {timeout_s}s)")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s, env=env
        )
    except subprocess.TimeoutExpired as e:
        _log(f"{label}: timed out after {timeout_s}s")
        partial = e.stderr or b""
        if partial:
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            _log(f"{label}: stderr before kill: {partial[-800:]}")
        return None, ""
    for line in proc.stderr.splitlines():
        print(line, file=sys.stderr, flush=True)
    if proc.returncode != 0:
        _log(f"{label}: rc={proc.returncode}; stderr tail: {proc.stderr[-800:]}")
    return proc.returncode, proc.stdout


def _parse_sentinel(stdout: str):
    for line in stdout.splitlines():
        if line.startswith(_SENTINEL):
            return json.loads(line[len(_SENTINEL) :])
    return None


def _probe_until_healthy(env_overrides, label) -> bool:
    """Short probe attempts with backoff until the backend answers.

    A hung probe holds no chip claim (it never gets past device init), so
    killing it on timeout cannot wedge the tunnel the way killing a
    mid-compile heavy worker does. Two failure modes get different budgets:
    a FAST error (rc != 0, e.g. "Unable to initialize backend") is often
    transient and worth the full retry schedule, but a probe TIMEOUT means
    the tunnel is wedged — observed to persist for hours — so two
    consecutive timeouts end the vigil instead of burning the whole
    benchmark window on a dead tunnel.
    """
    consecutive_timeouts = 0
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        rc, stdout = _spawn(
            f"{label} probe {attempt}/{PROBE_ATTEMPTS}",
            ["--probe"],
            env_overrides,
            PROBE_TIMEOUT_S,
        )
        res = _parse_sentinel(stdout) if rc == 0 else None
        if res is not None:
            _log(f"{label} probe ok: backend {res['backend']}")
            return True
        consecutive_timeouts = consecutive_timeouts + 1 if rc is None else 0
        if consecutive_timeouts >= 2:
            _log(f"{label}: two probe timeouts — tunnel wedged, giving up")
            return False
        if attempt < PROBE_ATTEMPTS:
            _log(f"{label} probe failed; backing off {PROBE_BACKOFF_S}s")
            time.sleep(PROBE_BACKOFF_S)
    return False


def _run_measurement(label, worker_args, env_overrides, timeout_s):
    """One heavy-worker attempt; returns merged partial sections (or None).

    The worker appends each completed section to a temp file, so even a
    timeout kill returns everything measured up to the kill.
    """
    import tempfile

    fd, out_path = tempfile.mkstemp(prefix="bench_sections_", suffix=".jsonl")
    os.close(fd)
    try:
        rc, stdout = _spawn(
            label, ["--worker", *worker_args, "--out", out_path], env_overrides, timeout_s
        )
        full = _parse_sentinel(stdout) if rc == 0 else None
        if full is not None:
            return full
        merged: dict = {}
        with open(out_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    merged.update(json.loads(line))
                except json.JSONDecodeError:
                    # a timeout kill can land mid-write; drop the torn line
                    _log(f"{label}: dropping torn section line ({len(line)}B)")
        if merged:
            _log(f"{label}: recovered partial sections {sorted(merged)}")
        return merged or None
    finally:
        os.unlink(out_path)


def main() -> None:
    # accelerator path: inherit env so the TPU tunnel registers. Gate the one
    # long-timeout heavy attempt behind cheap probes — never burn the heavy
    # attempt (or wedge the tunnel by killing it mid-claim) on a dead tunnel.
    accel = None
    if _probe_until_healthy({}, "accel"):
        accel = _run_measurement(
            "accel measurement",
            [
                "--reps",
                str(TPU_REPS),
                "--pallas",
                "--stages",
                "--batches",
                ",".join(str(b) for b in ACCEL_BATCH_SWEEP),
            ],
            {},
            ACCEL_TIMEOUT_S,
        )
    # a partial record without the headline number is useless — treat as lost
    if accel is not None and "xla_tput" not in accel:
        _log(f"accel sections incomplete ({sorted(accel)}); discarding")
        accel = None

    # CPU baseline in a scrubbed environment: the baseline process must never
    # dial (or hang on) the accelerator tunnel. It runs at the SAME batch
    # size that won the accelerator sweep so vs_baseline stays a
    # same-program ratio.
    cpu = None
    if accel is None or accel["backend"] != "cpu":
        # when the accelerator record is lost, let the fallback at least
        # carry the per-stage breakdown so the round's JSON stays diagnosable
        extra = ["--stages"] if accel is None else []
        cpu_batch = accel.get("xla_batch", BATCH) if accel else BATCH
        cpu = _run_measurement(
            "cpu baseline",
            [
                "--platform",
                "cpu",
                "--reps",
                str(CPU_REPS),
                "--batches",
                str(cpu_batch),
                *extra,
            ],
            {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": None},
            CPU_TIMEOUT_S,
        )
        if cpu is not None and "xla_tput" not in cpu:
            cpu = None

    out = {
        "metric": "slices_per_sec_per_chip",
        "value": 0.0,
        "unit": "slices/s",
        "vs_baseline": 0.0,
    }
    if accel is not None:
        tput = accel["xla_tput"]
        # only a result-identical pallas run may win the headline number —
        # a miscompiled kernel must not corrupt the benchmark record
        if accel.get("pallas_checksum_ok") and accel.get("pallas_tput", 0) > tput:
            tput = accel["pallas_tput"]
            out["winning_path"] = "pallas"
        else:
            out["winning_path"] = "xla"
        out["value"] = round(tput, 2)
        out["backend"] = accel["backend"]
        if "xla_batch" in accel:
            out["batch"] = accel["xla_batch"]
        if "xla_by_batch" in accel:
            out["xla_by_batch"] = accel["xla_by_batch"]
        if "pallas_tput" in accel:
            out["pallas_tput"] = round(accel["pallas_tput"], 2)
            out["pallas_checksum_ok"] = accel["pallas_checksum_ok"]
        if "stages" in accel:
            out["stages"] = accel["stages"]
        if "student_tput" in accel:
            out["student_tput"] = accel["student_tput"]
        if accel["backend"] == "cpu":
            out["vs_baseline"] = 1.0
            out["error"] = "no accelerator backend available; measured cpu only"
        elif cpu is not None:
            out["cpu_baseline_tput"] = round(cpu["xla_tput"], 2)
            out["vs_baseline"] = round(tput / cpu["xla_tput"], 2)
        else:
            out["vs_baseline"] = 1.0
            out["error"] = "cpu baseline worker failed; vs_baseline unknown"
    elif cpu is not None:
        out["value"] = round(cpu["xla_tput"], 2)
        out["backend"] = "cpu"
        out["vs_baseline"] = 1.0
        if "stages" in cpu:
            out["stages"] = cpu["stages"]
        if "student_tput" in cpu:
            out["student_tput"] = cpu["student_tput"]
        out["error"] = "accelerator worker failed; cpu fallback measured"
    else:
        out["backend"] = "none"
        out["error"] = "all measurement workers failed; see stderr"

    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--worker", action="store_true")
    parser.add_argument("--probe", action="store_true")
    parser.add_argument("--platform", default=None)
    parser.add_argument("--reps", type=int, default=TPU_REPS)
    parser.add_argument("--pallas", action="store_true")
    parser.add_argument("--stages", action="store_true")
    parser.add_argument("--out", default=None)
    parser.add_argument("--batches", default=str(BATCH), help="comma list to sweep")
    ns = parser.parse_args()
    if ns.probe:
        probe(ns.platform)
    elif ns.worker:
        worker(
            ns.platform,
            ns.reps,
            ns.pallas,
            ns.stages,
            ns.out,
            tuple(int(b) for b in ns.batches.split(",")),
        )
    else:
        main()
