// Native runtime layer for the TPU framework.
//
// The reference (calebhabesh/NM03-Capstone-Project) is a C++17 system: its
// import path (FAST DICOMFileImporter, src/test/test_pipeline.cpp:33-42), its
// batch parallelism (OpenMP parallel-for, src/parallel/main_parallel.cpp:336)
// and its export path (Qt/FAST ImageFileExporter,
// src/sequential/main_sequential.cpp:61-73) are all native code. This file is
// the TPU-native counterpart of that host-side runtime — everything that is
// NOT device math: DICOM decode, threaded batch staging for the HBM prefetch
// queue, and JPEG encoding. Device compute stays in JAX/XLA/Pallas.
//
// Exposed as a C ABI (ctypes-friendly, no pybind11):
//   nm03_dicom_read         — decode one 2D slice to float32 (rescale applied)
//   nm03_load_batch         — thread-pool decode of N files into a padded
//                             canvas arena + dims + per-file ok flags
//   nm03_jpeg_encode_gray   — baseline JPEG (grayscale) encoder
//   nm03_last_error         — thread-local error string
//
// Contracts mirror the Python implementations in
// nm03_capstone_project_tpu/data/dicomlite.py (parser) and
// nm03_capstone_project_tpu/render/export.py (encoder); tests/test_native.py
// checks native == Python on round-trips.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#if defined(_WIN32)
#define NM03_EXPORT extern "C" __declspec(dllexport)
#else
#define NM03_EXPORT extern "C" __attribute__((visibility("default")))
#endif

namespace {

thread_local std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

// ---------------------------------------------------------------------------
// DICOM-lite parser (explicit/implicit VR little endian, uncompressed mono)
// ---------------------------------------------------------------------------

struct ByteReader {
  const uint8_t* buf;
  size_t len;
  size_t pos = 0;
  bool explicit_vr;
  bool ok = true;
  bool big = false;  // explicit VR big endian (1.2.840.10008.1.2.2)

  uint16_t u16() {
    if (pos + 2 > len) { ok = false; return 0; }
    uint16_t v = big ? (uint16_t)((buf[pos] << 8) | buf[pos + 1])
                     : (uint16_t)(buf[pos] | (buf[pos + 1] << 8));
    pos += 2;
    return v;
  }
  uint32_t u32() {
    if (pos + 4 > len) { ok = false; return 0; }
    uint32_t v = big ? (((uint32_t)buf[pos] << 24) | ((uint32_t)buf[pos + 1] << 16) |
                        ((uint32_t)buf[pos + 2] << 8) | (uint32_t)buf[pos + 3])
                     : ((uint32_t)buf[pos] | ((uint32_t)buf[pos + 1] << 8) |
                        ((uint32_t)buf[pos + 2] << 16) | ((uint32_t)buf[pos + 3] << 24));
    pos += 4;
    return v;
  }
  bool atend() const { return pos + 8 > len; }
};

constexpr uint32_t kUndefined = 0xFFFFFFFFu;

bool is_long_vr(const char vr[2]) {
  static const char* kLong[] = {"OB", "OW", "OF", "OD", "OL",
                                "SQ", "UC", "UR", "UT", "UN"};
  for (const char* s : kLong)
    if (vr[0] == s[0] && vr[1] == s[1]) return true;
  return false;
}

struct Element {
  uint16_t group, elem;
  char vr[2];
  uint32_t length;
};

// Decode one data element header (mirrors _Reader.element in dicomlite.py).
Element read_element(ByteReader& r) {
  Element e{};
  e.group = r.u16();
  e.elem = r.u16();
  bool delim = e.group == 0xFFFE &&
               (e.elem == 0xE000 || e.elem == 0xE00D || e.elem == 0xE0DD);
  if (delim) {
    e.length = r.u32();
    return e;
  }
  if (r.explicit_vr && e.group != 0xFFFE) {
    if (r.pos + 2 > r.len) { r.ok = false; return e; }
    e.vr[0] = (char)r.buf[r.pos];
    e.vr[1] = (char)r.buf[r.pos + 1];
    r.pos += 2;
    if (is_long_vr(e.vr)) {
      r.pos += 2;  // reserved
      e.length = r.u32();
    } else {
      e.length = r.u16();
    }
  } else {
    e.length = r.u32();
  }
  return e;
}

void skip_item_undefined(ByteReader& r);

// Skip an undefined-length sequence body (until sequence delimiter).
void skip_sequence(ByteReader& r) {
  while (!r.atend() && r.ok) {
    Element e = read_element(r);
    if (e.group == 0xFFFE && e.elem == 0xE0DD) return;  // seq delimiter
    if (e.group == 0xFFFE && e.elem == 0xE000) {        // item
      if (e.length == kUndefined)
        skip_item_undefined(r);
      else
        r.pos += e.length;
    } else {  // malformed; bail out of the sequence
      if (e.length != kUndefined) r.pos += e.length;
      return;
    }
  }
}

void skip_item_undefined(ByteReader& r) {
  while (!r.atend() && r.ok) {
    Element e = read_element(r);
    if (e.group == 0xFFFE && e.elem == 0xE00D) return;  // item delimiter
    if (e.length == kUndefined)
      skip_sequence(r);  // nested undefined-length sequence
    else
      r.pos += e.length;
  }
}

using Tag = uint32_t;
constexpr Tag tag(uint16_t g, uint16_t e) { return ((Tag)g << 16) | e; }

struct DataSet {
  std::map<Tag, std::vector<uint8_t>> meta;
  const uint8_t* pixel_data = nullptr;
  size_t pixel_len = 0;
  // encapsulated PixelData fragments (byte spans into the file buffer)
  std::vector<std::pair<const uint8_t*, size_t>> fragments;
};

// Encapsulated PixelData: Basic Offset Table item, then one item per
// fragment, closed by a sequence delimiter (PS3.5 A.4; mirrors
// _read_fragments in dicomlite.py).
bool read_fragments(ByteReader& r, DataSet* out) {
  bool first = true;
  while (!r.atend() && r.ok) {
    Element e = read_element(r);
    if (e.group == 0xFFFE && e.elem == 0xE0DD) return true;  // seq delimiter
    if (e.group != 0xFFFE || e.elem != 0xE000 || e.length == kUndefined) {
      set_error("malformed encapsulated PixelData item");
      return false;
    }
    if (e.length > r.len - r.pos) {
      set_error("encapsulated fragment overruns file");
      return false;
    }
    if (!first)  // the first item is the Basic Offset Table
      out->fragments.emplace_back(r.buf + r.pos, (size_t)e.length);
    first = false;
    r.pos += e.length;
  }
  set_error("encapsulated PixelData missing sequence delimiter");
  return false;
}

bool parse_dataset(const uint8_t* buf, size_t len, bool explicit_vr,
                   DataSet* out, bool encapsulated = false, bool big = false) {
  ByteReader r{buf, len, 0, explicit_vr, true, big};
  while (!r.atend()) {
    Element e = read_element(r);
    if (!r.ok) { set_error("truncated DICOM element structure"); return false; }
    if (e.group == 0x7FE0 && e.elem == 0x0010) {
      if (e.length == kUndefined) {
        if (!encapsulated) {
          set_error("encapsulated PixelData under an uncompressed transfer syntax");
          return false;
        }
        if (!read_fragments(r, out)) return false;
        continue;
      }
      // clamp a declared length that overruns the file (Python's slice
      // semantics in dicomlite.py:142); the rows*cols sufficiency check
      // below decides whether the slice is still decodable
      size_t avail = len - r.pos;
      out->pixel_data = buf + r.pos;
      out->pixel_len = e.length < avail ? e.length : avail;
      r.pos += out->pixel_len;
      continue;
    }
    if (e.length == kUndefined) { skip_sequence(r); continue; }
    if (e.vr[0] == 'S' && e.vr[1] == 'Q') { r.pos += e.length; continue; }
    if (e.group == 0xFFFE) { r.pos += e.length; continue; }
    if (e.length > len - r.pos) {
      char msg[96];
      std::snprintf(msg, sizeof msg, "element (%04x,%04x) length %u overruns file",
                    e.group, e.elem, e.length);
      set_error(msg);
      return false;
    }
    out->meta[tag(e.group, e.elem)].assign(buf + r.pos, buf + r.pos + e.length);
    r.pos += e.length;
  }
  return true;
}

std::string ascii_value(const std::vector<uint8_t>& v) {
  std::string s(v.begin(), v.end());
  while (!s.empty() && (s.back() == '\0' || s.back() == ' ')) s.pop_back();
  size_t i = 0;
  while (i < s.size() && (s[i] == '\0' || s[i] == ' ')) ++i;
  return s.substr(i);
}

bool meta_int(const DataSet& ds, Tag t, long* out, bool big = false) {
  auto it = ds.meta.find(t);
  if (it == ds.meta.end()) return false;
  const auto& v = it->second;
  if (v.size() == 2) {
    *out = big ? ((v[0] << 8) | v[1]) : (v[0] | (v[1] << 8));
    return true;
  }
  if (v.size() == 4) {
    *out = big ? (long)(((uint32_t)v[0] << 24) | ((uint32_t)v[1] << 16) |
                        ((uint32_t)v[2] << 8) | (uint32_t)v[3])
               : (long)((uint32_t)v[0] | ((uint32_t)v[1] << 8) |
                        ((uint32_t)v[2] << 16) | ((uint32_t)v[3] << 24));
    return true;
  }
  try {
    *out = std::stol(ascii_value(v));
    return true;
  } catch (...) { return false; }
}

double meta_float(const DataSet& ds, Tag t, double dflt) {
  auto it = ds.meta.find(t);
  if (it == ds.meta.end()) return dflt;
  try { return std::stod(ascii_value(it->second)); } catch (...) { return dflt; }
}

// ---------------------------------------------------------------------------
// RLE Lossless (PS3.5 Annex G) — mirrors data/codecs.py:rle_decode_frame.
// Decodes one frame into little-endian sample bytes (the layout the pixel
// conversion loops below already read), recomposed from the MSB-first
// byte-plane segments.
// ---------------------------------------------------------------------------

uint32_t le32_at(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

bool packbits_decode(const uint8_t* seg, size_t seg_len, uint8_t* out,
                     size_t expected) {
  size_t i = 0, got = 0;
  while (i < seg_len && got < expected) {
    uint8_t ctrl = seg[i++];
    if (ctrl < 128) {  // literal run: copy next ctrl+1 bytes
      size_t count = (size_t)ctrl + 1;
      if (i + count > seg_len) { set_error("RLE literal run overruns segment"); return false; }
      if (got + count > expected) count = expected - got;
      std::memcpy(out + got, seg + i, count);
      i += (size_t)ctrl + 1;
      got += count;
    } else if (ctrl > 128) {  // replicate: next byte repeated 257-ctrl times
      if (i >= seg_len) { set_error("RLE replicate run missing its byte"); return false; }
      size_t count = 257 - ctrl;
      if (got + count > expected) count = expected - got;
      std::memset(out + got, seg[i], count);
      ++i;
      got += count;
    }
    // ctrl == 128: no-op (reserved)
  }
  if (got < expected) { set_error("RLE segment decoded short"); return false; }
  return true;
}

bool rle_decode_frame(const uint8_t* frame, size_t flen, size_t rows,
                      size_t cols, int itemsize, std::vector<uint8_t>* out) {
  if (flen < 64) { set_error("RLE frame shorter than its 64-byte header"); return false; }
  uint32_t nseg = le32_at(frame);
  if ((int)nseg != itemsize) { set_error("RLE segment count mismatch"); return false; }
  uint32_t offsets[15];
  for (uint32_t s = 0; s < nseg; ++s) {
    offsets[s] = le32_at(frame + 4 + 4 * s);
    if (offsets[s] < 64 || offsets[s] > flen ||
        (s && offsets[s] < offsets[s - 1])) {
      set_error("RLE segment offsets invalid");
      return false;
    }
  }
  size_t npix = rows * cols;
  out->resize(npix * itemsize);
  std::vector<uint8_t> plane(npix);
  for (uint32_t s = 0; s < nseg; ++s) {
    size_t start = offsets[s];
    size_t end = (s + 1 < nseg) ? offsets[s + 1] : flen;
    if (!packbits_decode(frame + start, end - start, plane.data(), npix))
      return false;
    // segment order is MSB plane first; emit little-endian sample bytes
    size_t byte_index = (size_t)(itemsize - 1 - (int)s);
    for (size_t i = 0; i < npix; ++i)
      (*out)[i * itemsize + byte_index] = plane[i];
  }
  return true;
}

// ---------------------------------------------------------------------------
// JPEG Lossless (ITU-T T.81 process 14, SOF3) — mirrors
// data/codecs.py:jpeg_lossless_decode. Any predictor selection 1-7, point
// transform, 2-16 bit precision, single component, no restart intervals.
// The Python decoder is the reference implementation; this one keeps
// JPEG-lossless cohorts on the threaded native fast path (the pure-Python
// per-pixel Huffman loop costs ~0.5 s per 256x256 slice).
// ---------------------------------------------------------------------------

struct JBitReader {
  const uint8_t* buf;
  size_t len, pos;
  uint32_t acc = 0;
  int nacc = 0;
  bool ok = true;

  int read_bit() {
    if (nacc == 0) {
      if (pos >= len) { ok = false; return 0; }
      uint8_t b = buf[pos++];
      if (b == 0xFF) {
        if (pos >= len) { ok = false; return 0; }
        if (buf[pos] == 0x00) ++pos;  // stuffed byte
        else { ok = false; return 0; }  // real marker mid-scan
      }
      acc = b;
      nacc = 8;
    }
    --nacc;
    return (acc >> nacc) & 1;
  }
  uint32_t read_bits(int n) {
    uint32_t v = 0;
    for (int i = 0; i < n; ++i) v = (v << 1) | (uint32_t)read_bit();
    return v;
  }
};

// Canonical Huffman (T.81 Annex C): codes of each length are consecutive.
struct JHuffTable {
  uint32_t first_code[17];  // smallest code of each length
  int first_index[17];      // index into values of that code
  int count[17];            // codes of each length
  std::vector<uint8_t> values;
  bool present = false;
};

void build_huffman(const uint8_t* counts, const uint8_t* vals, int nvals,
                   JHuffTable* t) {
  t->values.assign(vals, vals + nvals);
  uint32_t code = 0;
  int index = 0;
  for (int length = 1; length <= 16; ++length) {
    t->first_code[length] = code;
    t->first_index[length] = index;
    t->count[length] = counts[length - 1];
    code = (code + counts[length - 1]) << 1;
    index += counts[length - 1];
  }
  t->present = true;
}

int huff_decode(JBitReader& r, const JHuffTable& t) {
  uint32_t code = 0;
  for (int length = 1; length <= 16; ++length) {
    code = (code << 1) | (uint32_t)r.read_bit();
    if (!r.ok) return -1;
    if (t.count[length] &&
        code < t.first_code[length] + (uint32_t)t.count[length]) {
      return t.values[t.first_index[length] + (code - t.first_code[length])];
    }
  }
  return -1;
}

// T.81 F.2.2.1: map SSSS magnitude bits to a signed difference.
int32_t jpeg_extend(uint32_t bits, int ssss) {
  if (ssss == 0) return 0;
  if (ssss == 16) return 32768;  // no magnitude bits (lossless special case)
  if (bits < (1u << (ssss - 1))) return (int32_t)bits - (1 << ssss) + 1;
  return (int32_t)bits;
}

// expect_rows/expect_cols: the DICOM header's dimensions — checked right
// after SOF3 parses, BEFORE sizing the output, so a hostile embedded JPEG
// claiming 32768x32768 cannot drive a ~2 GiB allocation + gigapixel decode
// that the caller's post-hoc dimension check would only catch afterwards.
bool jpeg_lossless_decode(const uint8_t* data, size_t len, long expect_rows,
                          long expect_cols, std::vector<uint16_t>* out,
                          long* rows_out, long* cols_out) {
  if (len < 4 || data[0] != 0xFF || data[1] != 0xD8) {
    set_error("not a JPEG stream (missing SOI)");
    return false;
  }
  size_t pos = 2;
  int precision = -1;
  long rows = 0, cols = 0;
  JHuffTable tables[2][4];  // [class][id]; lossless scans use class 0
  int sel = 1, pt = 0, table_id = 0;
  bool got_sos = false;
  while (pos + 2 <= len) {
    if (data[pos] != 0xFF) { set_error("expected JPEG marker"); return false; }
    // optional fill bytes (T.81 B.1.1.2): extra 0xFF may pad any marker
    while (pos + 1 < len && data[pos + 1] == 0xFF) ++pos;
    if (pos + 2 > len) { set_error("truncated JPEG marker segment"); return false; }
    uint8_t marker = data[pos + 1];
    pos += 2;
    if (marker == 0xD9) break;  // EOI
    if (pos + 2 > len) { set_error("truncated JPEG marker segment"); return false; }
    size_t seglen = ((size_t)data[pos] << 8) | data[pos + 1];
    size_t seg_end = pos + seglen;
    if (seglen < 2 || seg_end > len) {
      // seglen includes its own 2 bytes; < 2 would underflow body_len
      set_error("truncated JPEG marker segment");
      return false;
    }
    const uint8_t* body = data + pos + 2;
    size_t body_len = seglen - 2;
    if (marker == 0xC3) {  // SOF3
      if (body_len < 6) { set_error("short SOF3"); return false; }
      precision = body[0];
      rows = ((long)body[1] << 8) | body[2];
      cols = ((long)body[3] << 8) | body[4];
      if (body[5] != 1) { set_error("lossless JPEG: expected 1 component"); return false; }
    } else if ((marker >= 0xC0 && marker <= 0xCB) && marker != 0xC3 &&
               marker != 0xC4 && marker != 0xC8) {
      set_error("JPEG SOF is not lossless process 14 (SOF3)");
      return false;
    } else if (marker == 0xC4) {  // DHT
      size_t b = 0;
      while (b + 17 <= body_len) {
        uint8_t tc_th = body[b];
        int tc = tc_th >> 4, th = tc_th & 0x0F;
        int nvals = 0;
        for (int i = 0; i < 16; ++i) nvals += body[b + 1 + i];
        if (b + 17 + nvals > body_len || tc > 1 || th > 3) {
          set_error("malformed DHT");
          return false;
        }
        build_huffman(body + b + 1, body + b + 17, nvals, &tables[tc][th]);
        b += 17 + (size_t)nvals;
      }
      if (b != body_len) {
        // trailing bytes too short for another table: the Python
        // reference rejects this stream; the decoders must agree
        set_error("malformed DHT");
        return false;
      }
    } else if (marker == 0xDA) {  // SOS
      if (body_len < 6 || body[0] != 1) { set_error("expected 1 scan component"); return false; }
      table_id = body[2] >> 4;  // Td
      sel = body[3];            // Ss = predictor selection value
      pt = body[5] & 0x0F;      // Al = point transform
      pos = seg_end;
      got_sos = true;
      break;  // entropy-coded data follows
    }
    pos = seg_end;
  }
  if (precision < 0 || !got_sos) { set_error("JPEG stream missing SOF3/SOS"); return false; }
  if (table_id > 3 || !tables[0][table_id].present) {
    set_error("JPEG scan references undefined Huffman table");
    return false;
  }
  if (sel < 1 || sel > 7) { set_error("unsupported lossless predictor"); return false; }
  if (rows != expect_rows || cols != expect_cols) {
    set_error("JPEG frame dimensions disagree with DICOM header");
    return false;
  }
  if (precision < 2 || precision > 16 || pt >= precision) {
    // T.81: lossless precision is 2-16; pt >= precision would make the
    // default predictor's shift count negative (UB)
    set_error("invalid JPEG precision/point-transform");
    return false;
  }

  const JHuffTable& table = tables[0][table_id];
  JBitReader r{data, len, pos};
  out->assign((size_t)rows * cols, 0);
  std::vector<int32_t> cur(cols), prev(cols);
  int32_t dflt = 1 << (precision - pt - 1);
  for (long y = 0; y < rows; ++y) {
    for (long x = 0; x < cols; ++x) {
      int ssss = huff_decode(r, table);
      if (ssss < 0 || !r.ok) { set_error("invalid JPEG Huffman code"); return false; }
      if (ssss > 16) {
        // DHT values are arbitrary bytes; >16 would be shift-count UB in
        // jpeg_extend and silent divergence from the Python reference
        set_error("invalid JPEG difference category");
        return false;
      }
      uint32_t extra = (ssss > 0 && ssss < 16) ? r.read_bits(ssss) : 0;
      if (!r.ok) { set_error("JPEG entropy data truncated"); return false; }
      int32_t diff = jpeg_extend(extra, ssss);
      int32_t pred;
      if (y == 0) {
        pred = (x == 0) ? dflt : cur[x - 1];
      } else if (x == 0) {
        pred = prev[0];
      } else {
        int32_t ra = cur[x - 1], rb = prev[x], rc = prev[x - 1];
        switch (sel) {
          case 1: pred = ra; break;
          case 2: pred = rb; break;
          case 3: pred = rc; break;
          case 4: pred = ra + rb - rc; break;
          case 5: pred = ra + ((rb - rc) >> 1); break;
          case 6: pred = rb + ((ra - rc) >> 1); break;
          default: pred = (ra + rb) >> 1; break;
        }
      }
      cur[x] = (pred + diff) & 0xFFFF;
      (*out)[(size_t)y * cols + x] = (uint16_t)(cur[x] << pt);
    }
    std::swap(cur, prev);
  }
  *rows_out = rows;
  *cols_out = cols;
  return true;
}

bool read_file(const char* path, std::vector<uint8_t>* out) {
  FILE* f = std::fopen(path, "rb");
  if (!f) { set_error(std::string("cannot open ") + path); return false; }
  std::fseek(f, 0, SEEK_END);
  long n = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (n < 0) { std::fclose(f); set_error("ftell failed"); return false; }
  out->resize((size_t)n);
  size_t got = n ? std::fread(out->data(), 1, (size_t)n, f) : 0;
  std::fclose(f);
  if (got != (size_t)n) { set_error("short read"); return false; }
  return true;
}

// ---------------------------------------------------------------------------
// JPEG-LS (ITU-T T.87) decoder — native mirror of data/codecs.py
// jpegls_decode. LOCO-I: MED prediction, 365 bias-corrected Golomb contexts,
// run mode with two run-interruption contexts. Lossless + near-lossless,
// single component, interleave none; conformance pinned against CharLS
// streams by tests/test_jpegls.py::TestNativeParity (vendored goldens +
// live three-way fuzz) alongside the Python decoder.
// ---------------------------------------------------------------------------

struct JlsBitReader {
  const uint8_t* buf;
  size_t len, pos;
  uint64_t cache = 0;
  int nbits = 0;
  bool prev_ff = false;
  bool ok = true;

  bool fill() {
    if (pos >= len) { ok = false; return false; }
    uint8_t b = buf[pos];
    if (prev_ff) {
      if (b >= 0x80) { ok = false; return false; }  // marker ends the scan
      ++pos;
      cache = (cache << 7) | b;
      nbits += 7;
      prev_ff = false;
    } else {
      ++pos;
      cache = (cache << 8) | b;
      nbits += 8;
      prev_ff = (b == 0xFF);
    }
    return true;
  }
  int read_bit() {
    if (nbits == 0 && !fill()) return 0;
    --nbits;
    return (int)((cache >> nbits) & 1);
  }
  uint32_t read_bits(int n) {
    while (nbits < n) if (!fill()) return 0;
    nbits -= n;
    uint32_t v = (uint32_t)((cache >> nbits) & ((1u << n) - 1));
    cache &= (nbits ? ((uint64_t)1 << nbits) - 1 : 0);
    return v;
  }
  int read_zero_run(int cap) {
    int z = 0;
    while (true) {
      if (read_bit()) return z;
      if (!ok) return -1;
      if (++z > cap) { ok = false; return -1; }
    }
  }
};

struct JlsRunCtx { int32_t a, n, nn; };

bool jpegls_decode(const uint8_t* data, size_t len, long expect_rows,
                   long expect_cols, std::vector<uint16_t>* out,
                   long* rows_out, long* cols_out) {
  if (len < 4 || data[0] != 0xFF || data[1] != 0xD8) {
    set_error("not a JPEG-LS stream (missing SOI)");
    return false;
  }
  size_t pos = 2;
  int precision = -1;
  long rows = 0, cols = 0;
  long maxval_hdr = 0, t1_hdr = 0, t2_hdr = 0, t3_hdr = 0, reset_hdr = 0;
  int near = 0;
  size_t entropy_at = 0;
  bool got_sos = false;
  while (pos + 2 <= len) {
    if (data[pos] != 0xFF) { set_error("expected JPEG-LS marker"); return false; }
    // optional fill bytes (T.81 B.1.1.2): extra 0xFF may pad any marker
    while (pos + 1 < len && data[pos + 1] == 0xFF) ++pos;
    if (pos + 2 > len) { set_error("truncated JPEG-LS segment"); return false; }
    uint8_t marker = data[pos + 1];
    pos += 2;
    if (marker == 0xD9) break;  // EOI before SOS
    if (pos + 2 > len) { set_error("truncated JPEG-LS segment"); return false; }
    size_t seglen = ((size_t)data[pos] << 8) | data[pos + 1];
    size_t seg_end = pos + seglen;
    if (seglen < 2 || seg_end > len) { set_error("truncated JPEG-LS segment"); return false; }
    const uint8_t* body = data + pos + 2;
    size_t body_len = seglen - 2;
    if (marker == 0xF7) {  // SOF55
      if (body_len < 6) { set_error("short SOF55"); return false; }
      precision = body[0];
      rows = ((long)body[1] << 8) | body[2];
      cols = ((long)body[3] << 8) | body[4];
      if (body[5] != 1) { set_error("JPEG-LS: expected 1 component"); return false; }
    } else if (marker >= 0xC0 && marker <= 0xCB && marker != 0xC4 && marker != 0xC8) {
      set_error("not JPEG-LS (wrong SOF)");
      return false;
    } else if (marker == 0xF8) {  // LSE
      if (body_len < 1 || body[0] != 1) { set_error("unsupported LSE segment"); return false; }
      if (body_len < 11) { set_error("short LSE preset segment"); return false; }
      maxval_hdr = ((long)body[1] << 8) | body[2];
      t1_hdr = ((long)body[3] << 8) | body[4];
      t2_hdr = ((long)body[5] << 8) | body[6];
      t3_hdr = ((long)body[7] << 8) | body[8];
      reset_hdr = ((long)body[9] << 8) | body[10];
    } else if (marker == 0xDD) {
      set_error("JPEG-LS restart intervals unsupported");
      return false;
    } else if (marker == 0xDA) {  // SOS
      if (body_len < 6) { set_error("short JPEG-LS SOS"); return false; }
      if (body[0] != 1) { set_error("expected 1 scan component"); return false; }
      if (body[2] != 0) { set_error("JPEG-LS mapping tables unsupported"); return false; }
      near = body[3];
      if (body[4] != 0) { set_error("JPEG-LS interleave unsupported"); return false; }
      if ((body[5] & 0x0F) != 0) { set_error("JPEG-LS point transform unsupported"); return false; }
      entropy_at = seg_end;
      got_sos = true;
      break;
    }
    pos = seg_end;
  }
  if (precision < 2 || precision > 16) { set_error("JPEG-LS missing/invalid SOF55"); return false; }
  if (!got_sos) { set_error("JPEG-LS stream missing SOS"); return false; }
  if (expect_rows > 0 && (rows != expect_rows || cols != expect_cols)) {
    set_error("JPEG-LS frame dimensions disagree with DICOM header");
    return false;
  }
  if (rows <= 0 || cols <= 0 || rows > 32768 || cols > 32768) {
    set_error("implausible JPEG-LS dimensions");
    return false;
  }
  long maxval = maxval_hdr ? maxval_hdr : ((1L << precision) - 1);
  if (maxval <= 0 || maxval >= (1L << precision)) { set_error("invalid JPEG-LS MAXVAL"); return false; }
  if (near < 0 || near > maxval / 2) { set_error("invalid JPEG-LS NEAR"); return false; }

  // default thresholds (T.87 C.2.4.1.1.1)
  long t1, t2, t3, reset = 64;
  {
    auto clampv = [&](long i, long j) { return (i > maxval || i < j) ? j : i; };
    if (maxval >= 128) {
      long factor = ((maxval < 4095 ? maxval : 4095) + 128) / 256;
      t1 = clampv(factor * 1 + 2 + 3 * near, near + 1);
      t2 = clampv(factor * 4 + 3 + 5 * near, t1);
      t3 = clampv(factor * 17 + 4 + 7 * near, t2);
    } else {
      long factor = 256 / (maxval + 1);
      long v1 = 3 / factor + 3 * near; if (v1 < 2) v1 = 2;
      long v2 = 7 / factor + 5 * near; if (v2 < 3) v2 = 3;
      long v3 = 21 / factor + 7 * near; if (v3 < 4) v3 = 4;
      t1 = clampv(v1, near + 1);
      t2 = clampv(v2, t1);
      t3 = clampv(v3, t2);
    }
  }
  if (t1_hdr) t1 = t1_hdr;
  if (t2_hdr) t2 = t2_hdr;
  if (t3_hdr) t3 = t3_hdr;
  if (reset_hdr) reset = reset_hdr;
  if (!(near + 1 <= t1 && t1 <= t2 && t2 <= t3 && t3 <= maxval)) {
    set_error("invalid JPEG-LS thresholds");
    return false;
  }
  // T.87 C.2.4.1.1 range; unbounded RESET would let the int32 context
  // accumulators overflow (UB) before the halving ever triggers
  if (reset < 3 || reset > (maxval > 255 ? maxval : 255)) {
    set_error("invalid JPEG-LS RESET");
    return false;
  }

  const long quant_step = 2L * near + 1;
  const long range = (maxval + 2 * near) / quant_step + 1;
  int qbpp = 1; while ((1L << qbpp) < range) ++qbpp;
  int bpp = 2; while ((1L << bpp) <= maxval) ++bpp;
  const int limit = 2 * (bpp > 8 ? 2 * bpp : bpp + 8);
  const long range_step = range * quant_step;

  static const int J[32] = {0, 0, 0, 0, 1, 1, 1, 1, 2, 2, 2, 2, 3, 3, 3, 3,
                            4, 4, 5, 5, 6, 6, 7, 7, 8, 9, 10, 11, 12, 13, 14, 15};
  const int32_t a_init = (int32_t)std::max(2L, (range + 32) >> 6);
  std::vector<int32_t> A(365, a_init), B(365, 0), C(365, 0), N(365, 1);
  JlsRunCtx rctx[2] = {{a_init, 1, 0}, {a_init, 1, 0}};
  int run_index = 0;

  auto quantize = [&](long d) -> int {
    if (d <= -t3) return -4;
    if (d <= -t2) return -3;
    if (d <= -t1) return -2;
    if (d < -near) return -1;
    if (d <= near) return 0;
    if (d < t1) return 1;
    if (d < t2) return 2;
    if (d < t3) return 3;
    return 4;
  };

  JlsBitReader r{data, len, entropy_at};

  auto decode_value = [&](int k, int lim) -> long {
    int z = r.read_zero_run(lim);
    if (z < 0) return -1;
    if (z >= lim - qbpp - 1) return (long)r.read_bits(qbpp) + 1;
    if (k == 0) return z;
    return ((long)z << k) | r.read_bits(k);
  };

  auto fix_reconstructed = [&](long v) -> long {
    if (v < -near) v += range_step;
    else if (v > maxval + near) v -= range_step;
    if (v < 0) return 0;
    if (v > maxval) return maxval;
    return v;
  };

  auto decode_run_interruption_error = [&](int ctx) -> long {
    JlsRunCtx& c = rctx[ctx];
    long temp = c.a + (ctx ? (c.n >> 1) : 0);
    int k = 0;
    while (((long)c.n << k) < temp) { if (++k > 32) { r.ok = false; return 0; } }
    long em = decode_value(k, limit - J[run_index] - 1);
    if (em < 0) { r.ok = false; return 0; }
    long tv = em + ctx;
    int map_bit = (int)(tv & 1);
    long eabs = (tv + map_bit) >> 1;
    bool cond = (k != 0) || (2 * c.nn >= c.n);
    long err = (cond == (map_bit != 0)) ? -eabs : eabs;
    if (err < 0) ++c.nn;
    c.a += (int32_t)((em + 1 - ctx) >> 1);
    if (c.n == (int32_t)reset) { c.a >>= 1; c.n >>= 1; c.nn >>= 1; }
    ++c.n;
    return err;
  };

  out->assign((size_t)rows * cols, 0);
  std::vector<long> prev((size_t)cols + 2, 0), cur((size_t)cols + 2, 0);
  for (long y = 0; y < rows; ++y) {
    prev[cols + 1] = prev[cols];
    cur[0] = prev[1];
    long x = 1;
    while (x <= cols) {
      if (!r.ok) { set_error("truncated JPEG-LS entropy stream"); return false; }
      long ra = cur[x - 1], rb = prev[x], rc = prev[x - 1], rd = prev[x + 1];
      int q1 = quantize(rd - rb), q2 = quantize(rb - rc), q3 = quantize(rc - ra);
      if (q1 == 0 && q2 == 0 && q3 == 0) {
        // run mode
        long remaining = cols - x + 1;
        long count = 0;
        bool broke_on_zero = true;
        while (true) {
          if (count == remaining) { broke_on_zero = false; break; }
          int bit = r.read_bit();
          if (!r.ok) { set_error("truncated JPEG-LS entropy stream"); return false; }
          if (!bit) break;
          long seg = 1L << J[run_index];
          long take = seg < remaining - count ? seg : remaining - count;
          count += take;
          if (take == seg && run_index < 31) ++run_index;
          if (count == remaining) { broke_on_zero = false; break; }
        }
        if (broke_on_zero) {
          int j = J[run_index];
          if (j) count += r.read_bits(j);
          if (!r.ok || count >= remaining) { set_error("JPEG-LS run overruns the line"); return false; }
        }
        for (long i = 0; i < count; ++i) cur[x + i] = ra;
        x += count;
        if (!broke_on_zero) continue;
        rb = prev[x];
        int ritype = (std::labs(ra - rb) <= near) ? 1 : 0;
        long err = decode_run_interruption_error(ritype);
        if (!r.ok) { set_error("truncated JPEG-LS entropy stream"); return false; }
        long rx;
        if (ritype) rx = fix_reconstructed(ra + err * quant_step);
        else {
          long sgn = rb < ra ? -1 : 1;
          rx = fix_reconstructed(rb + sgn * err * quant_step);
        }
        cur[x] = rx;
        ++x;
        if (run_index > 0) --run_index;
        continue;
      }
      // regular mode
      long qs = 81L * q1 + 9L * q2 + q3;
      long sign = 1;
      if (qs < 0) { sign = -1; qs = -qs; }
      long px;
      long mn = ra < rb ? ra : rb, mx = ra < rb ? rb : ra;
      if (rc >= mx) px = mn;
      else if (rc <= mn) px = mx;
      else px = ra + rb - rc;
      px += sign > 0 ? C[qs] : -C[qs];
      if (px < 0) px = 0; else if (px > maxval) px = maxval;
      int32_t a = A[qs], n = N[qs];
      int k = 0;
      while (((long)n << k) < a) { if (++k > 32) { set_error("JPEG-LS k overflow"); return false; } }
      long m = decode_value(k, limit);
      if (m < 0) { set_error("truncated JPEG-LS entropy stream"); return false; }
      long err = ((m & 1) == 0) ? (m >> 1) : -((m + 1) >> 1);
      if (k == 0 && near == 0 && 2 * B[qs] <= -n) err = -err - 1;
      B[qs] += (int32_t)(err * quant_step);
      A[qs] += (int32_t)(err >= 0 ? err : -err);
      if (n == (int32_t)reset) { A[qs] >>= 1; B[qs] >>= 1; N[qs] = n >> 1; }
      ++N[qs];
      n = N[qs];
      if (B[qs] + n <= 0) {
        B[qs] += n;
        if (B[qs] <= -n) B[qs] = -n + 1;
        if (C[qs] > -128) --C[qs];
      } else if (B[qs] > 0) {
        B[qs] -= n;
        if (B[qs] > 0) B[qs] = 0;
        if (C[qs] < 127) ++C[qs];
      }
      cur[x] = fix_reconstructed(px + sign * err * quant_step);
      ++x;
    }
    for (long i = 0; i < cols; ++i)
      (*out)[(size_t)y * cols + i] = (uint16_t)cur[i + 1];
    std::swap(prev, cur);
  }
  // scan must terminate with EOI (acceptance agreement with the Python
  // decoder and CharLS); unread bits of the current byte are padding, and
  // fill 0xFF bytes may pad before the marker (T.81 B.1.1.2)
  size_t p = r.pos;
  if (r.prev_ff && p < len && data[p] < 0x80) {
    // step over the stuffed byte a final 0xFF data byte carries even when
    // the scan consumed none of its bits (mirrors the Python decoder)
    ++p;
  }
  if (!r.prev_ff && (p >= len || data[p] != 0xFF)) {
    set_error("JPEG-LS stream missing EOI");
    return false;
  }
  while (p < len && data[p] == 0xFF) ++p;
  if (p >= len || data[p] != 0xD9) {
    set_error("JPEG-LS stream missing EOI");
    return false;
  }
  *rows_out = rows;
  *cols_out = cols;
  return true;
}

// Decode one slice into `pixels` (resized), returning rows/cols.
// Mirrors read_dicom() in dicomlite.py.
bool decode_dicom(const uint8_t* raw, size_t raw_len,
                  std::vector<float>* pixels, int* rows_out, int* cols_out) {
  const uint8_t* body = raw;
  size_t body_len = raw_len;
  std::string transfer_syntax = "1.2.840.10008.1.2.1";

  if (raw_len >= 132 && std::memcmp(raw + 128, "DICM", 4) == 0) {
    // file meta group is always explicit VR LE
    ByteReader r{raw, raw_len, 132, true};
    size_t meta_end = raw_len;
    bool first = true;
    while (r.pos < meta_end && !r.atend()) {
      size_t mark = r.pos;
      Element e = read_element(r);
      if (!r.ok) break;
      if (e.group != 0x0002) { r.pos = mark; break; }
      if (e.length > raw_len - r.pos) { set_error("file meta overruns"); return false; }
      std::vector<uint8_t> value(raw + r.pos, raw + r.pos + e.length);
      r.pos += e.length;
      if (first && e.group == 0x0002 && e.elem == 0x0000 && value.size() == 4) {
        uint32_t glen = (uint32_t)value[0] | ((uint32_t)value[1] << 8) |
                        ((uint32_t)value[2] << 16) | ((uint32_t)value[3] << 24);
        meta_end = r.pos + glen;
      }
      if (e.group == 0x0002 && e.elem == 0x0010)
        transfer_syntax = ascii_value(value);
      first = false;
    }
    body = raw + r.pos;
    body_len = raw_len - r.pos;
  } else if (raw_len >= 4 && std::memcmp(raw, "DICM", 4) == 0) {
    body = raw + 4;
    body_len = raw_len - 4;
  }

  bool explicit_vr;
  bool rle = false, jpegll = false, jls = false, big = false;
  if (transfer_syntax == "1.2.840.10008.1.2.1") explicit_vr = true;
  else if (transfer_syntax == "1.2.840.10008.1.2") explicit_vr = false;
  else if (transfer_syntax == "1.2.840.10008.1.2.2") {
    explicit_vr = true;
    big = true;
  }
  else if (transfer_syntax == "1.2.840.10008.1.2.5") {
    // RLE Lossless, JPEG Lossless and JPEG-LS decode natively; other
    // compressed syntaxes (baseline JPEG, J2K) fall back to the Python
    // reader (cli/runner.py retries parse failures there)
    explicit_vr = true;
    rle = true;
  } else if (transfer_syntax == "1.2.840.10008.1.2.4.57" ||
             transfer_syntax == "1.2.840.10008.1.2.4.70") {
    explicit_vr = true;
    jpegll = true;
  } else if (transfer_syntax == "1.2.840.10008.1.2.4.80" ||
             transfer_syntax == "1.2.840.10008.1.2.4.81") {
    explicit_vr = true;
    jls = true;
  }
  else { set_error("unsupported transfer syntax: " + transfer_syntax); return false; }

  DataSet ds;
  if (!parse_dataset(body, body_len, explicit_vr, &ds, rle || jpegll || jls,
                     big))
    return false;

  long rows = 0, cols = 0;
  if (!meta_int(ds, tag(0x0028, 0x0010), &rows, big) ||
      !meta_int(ds, tag(0x0028, 0x0011), &cols, big) ||
      (!ds.pixel_data && ds.fragments.empty())) {
    set_error("missing Rows/Columns/PixelData");
    return false;
  }
  if ((rle || jpegll || jls) && ds.pixel_data) {
    set_error("compressed transfer syntax with native PixelData (malformed file)");
    return false;
  }
  long bits = 16, pixrep = 0, samples = 1;
  meta_int(ds, tag(0x0028, 0x0100), &bits, big);
  meta_int(ds, tag(0x0028, 0x0103), &pixrep, big);
  meta_int(ds, tag(0x0028, 0x0002), &samples, big);
  if (samples != 1) { set_error("only monochrome supported"); return false; }
  if (bits != 8 && bits != 16) { set_error("unsupported BitsAllocated"); return false; }
  bool is_signed = pixrep == 1;
  // photometric interpretation (PS3.3 C.7.6.3.1.2), checked BEFORE any
  // frame decompression: PALETTE COLOR stores LUT indexes (reject);
  // MONOCHROME1 stores inverted grayscale — normalize to MONOCHROME2 on
  // the stored values with base = lo+hi of the stored range (unsigned:
  // 2^BitsStored-1; signed: -1). Mirrors dicomlite.py.
  std::string pi;
  {
    auto it = ds.meta.find(tag(0x0028, 0x0004));
    if (it != ds.meta.end()) pi = ascii_value(it->second);
  }
  if (pi == "PALETTE COLOR") {
    set_error("PALETTE COLOR images are out of envelope; convert to grayscale");
    return false;
  }
  long bits_stored = bits;
  meta_int(ds, tag(0x0028, 0x0101), &bits_stored, big);
  if (bits_stored < 1 || bits_stored > bits) {
    set_error("BitsStored outside [1, BitsAllocated]");
    return false;
  }
  long high_bit = bits_stored - 1;
  meta_int(ds, tag(0x0028, 0x0102), &high_bit, big);
  if (high_bit != bits_stored - 1) {
    // standard layout only (PS3.5 8.1.1); exotic packings would misread
    set_error("HighBit != BitsStored-1; repack with gdcmconv/dcmconv");
    return false;
  }
  bool invert = pi == "MONOCHROME1";
  long invert_base = invert ? (is_signed ? -1 : (1L << bits_stored) - 1) : 0;

  // NumberOfFrames (0028,0008), VR IS: digits or absent. Mirrors the
  // Python reader's _meta_int_str STRICTLY — exactly one optional sign
  // then ASCII digits; anything else (embedded whitespace stol would
  // skip, binary-looking bytes) means 1. A positive value too large for
  // long can never match real data (Python rejects such files at its
  // size/fragment checks), so it rejects here — acceptance-identical.
  long nframes = 1;
  {
    auto it = ds.meta.find(tag(0x0028, 0x0008));
    if (it != ds.meta.end()) {
      std::string s = ascii_value(it->second);
      std::string body = (!s.empty() && (s[0] == '+' || s[0] == '-'))
                             ? s.substr(1)
                             : s;
      bool digits = !body.empty() &&
                    body.find_first_not_of("0123456789") == std::string::npos;
      if (digits) {
        if (!s.empty() && s[0] == '-') {
          nframes = 1;  // < 1 clamps to 1, like the Python reader
        } else {
          try {
            nframes = std::max(1L, std::stol(s));
          } catch (const std::out_of_range&) {
            set_error("NumberOfFrames implausible");
            return false;
          }
        }
      }
    }
  }

  size_t expected = (size_t)rows * cols * (bits / 8);
  // Plausibility bound BEFORE any decode-side allocation: the uncompressed
  // path is implicitly bounded by the file size (pixel_len < expected
  // rejects), but RLE expands, so hostile Rows/Columns (65535 x 65535 =
  // an 8.6 GB resize) must fail gracefully here, not via std::bad_alloc
  // escaping the C ABI.
  if (rows <= 0 || cols <= 0 || rows > 32768 || cols > 32768 ||
      expected > ((size_t)1 << 28)) {
    set_error("implausible Rows/Columns");
    return false;
  }
  std::vector<uint8_t> decomp_buf;  // decoded samples as LE bytes
  if (rle) {
    // one fragment per frame (PS3.5 A.4.2); this reader serves frame 0 of
    // a multi-frame file, like the Python reader's default
    if ((long)ds.fragments.size() != nframes) {
      set_error("RLE fragment count disagrees with NumberOfFrames");
      return false;
    }
    if (!rle_decode_frame(ds.fragments[0].first, ds.fragments[0].second,
                          (size_t)rows, (size_t)cols, (int)(bits / 8),
                          &decomp_buf))
      return false;
    ds.pixel_data = decomp_buf.data();
    ds.pixel_len = decomp_buf.size();
  } else if (jpegll || jls) {
    // single fragment (the common single-frame case) decodes in place; a
    // frame spanning fragments is joined first. Multi-frame files delimit
    // frames by their SOI-starting fragments — the codestream count must
    // match NumberOfFrames and frame 0's group decodes, mirroring the
    // Python reader's _frame_payload exactly (acceptance parity).
    size_t first_begin = 0, first_end = ds.fragments.size();
    if (nframes > 1) {
      long groups = 0;
      for (size_t i = 0; i < ds.fragments.size(); ++i) {
        bool soi = ds.fragments[i].second >= 2 &&
                   ds.fragments[i].first[0] == 0xFF &&
                   ds.fragments[i].first[1] == 0xD8;
        if (soi || groups == 0) {
          ++groups;
          if (groups == 1) first_begin = i;
          if (groups == 2) first_end = i;
        }
      }
      if (groups != nframes) {
        set_error("JPEG codestream count disagrees with NumberOfFrames");
        return false;
      }
    }
    const uint8_t* stream_ptr = ds.fragments[first_begin].first;
    size_t stream_len = ds.fragments[first_begin].second;
    std::vector<uint8_t> joined;
    if (first_end - first_begin > 1) {
      for (size_t i = first_begin; i < first_end; ++i)
        joined.insert(joined.end(), ds.fragments[i].first,
                      ds.fragments[i].first + ds.fragments[i].second);
      stream_ptr = joined.data();
      stream_len = joined.size();
    }
    std::vector<uint16_t> samples;
    long jr = 0, jc = 0;
    bool ok = jls ? jpegls_decode(stream_ptr, stream_len, rows, cols,
                                  &samples, &jr, &jc)
                  : jpeg_lossless_decode(stream_ptr, stream_len, rows, cols,
                                         &samples, &jr, &jc);
    if (!ok) return false;
    decomp_buf.resize(samples.size() * (bits / 8));
    if (bits == 16) {
      for (size_t i = 0; i < samples.size(); ++i) {
        decomp_buf[2 * i] = (uint8_t)(samples[i] & 0xFF);
        decomp_buf[2 * i + 1] = (uint8_t)(samples[i] >> 8);
      }
    } else {
      for (size_t i = 0; i < samples.size(); ++i) {
        if (samples[i] > 0xFF) {
          set_error((jls ? "JPEG-LS" : "lossless JPEG") +
                    std::string(" precision exceeds BitsAllocated=8"));
          return false;
        }
        decomp_buf[i] = (uint8_t)samples[i];
      }
    }
    ds.pixel_data = decomp_buf.data();
    ds.pixel_len = decomp_buf.size();
  }
  // a multi-frame file must carry ALL its declared frames even though
  // this reader serves only frame 0 — the Python reader enforces the same
  // (a lying NumberOfFrames is a malformed file, not a short read).
  // Division, not multiplication: expected * nframes could overflow
  // size_t and bypass the check (expected >= 1 — rows/cols validated > 0).
  if (ds.pixel_len < expected ||
      (!(rle || jpegll || jls) &&
       ds.pixel_len / expected < (size_t)nframes)) {
    set_error("PixelData truncated");
    return false;
  }

  double slope = meta_float(ds, tag(0x0028, 0x1053), 1.0);
  double intercept = meta_float(ds, tag(0x0028, 0x1052), 0.0);
  float fslope = (float)slope, fintercept = (float)intercept;

  pixels->resize((size_t)rows * cols);
  const uint8_t* p = ds.pixel_data;
  float* dst = pixels->data();
  size_t n = (size_t)rows * cols;
  // decoded/compressed buffers are always little-endian sample bytes; only
  // native big-endian PixelData arrives byte-swapped
  const int lo = big ? 1 : 0, hi = big ? 0 : 1;
  // bits above BitsStored are overlay planes / garbage in historical
  // files: mask (unsigned) or sign-extend from the stored sign bit
  // (signed), as DCMTK's DicomImage does; no-op when BitsStored ==
  // BitsAllocated (the sign extension below reproduces the (int16_t) /
  // (int8_t) casts the raw loops used to apply)
  const long stored_mask = (bits_stored >= 64) ? -1L : (1L << bits_stored) - 1;
  const long sign_bit = 1L << (bits_stored - 1);
  auto store = [&](size_t i, long raw) {
    raw &= stored_mask;
    if (is_signed) raw = (raw ^ sign_bit) - sign_bit;
    if (invert) raw = invert_base - raw;
    dst[i] = (float)raw * fslope + fintercept;
  };
  if (bits == 16) {
    for (size_t i = 0; i < n; ++i)
      store(i, (long)(uint16_t)(p[2 * i + lo] | (p[2 * i + hi] << 8)));
  } else {
    for (size_t i = 0; i < n; ++i) store(i, (long)p[i]);
  }
  *rows_out = (int)rows;
  *cols_out = (int)cols;
  return true;
}

// ---------------------------------------------------------------------------
// Baseline JPEG encoder (grayscale)
// ---------------------------------------------------------------------------

const uint8_t kZigzag[64] = {
    0,  1,  8,  16, 9,  2,  3,  10, 17, 24, 32, 25, 18, 11, 4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6,  7,  14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63};

// ITU-T T.81 Table K.1 (luminance quantization)
const int kQuantLum[64] = {
    16, 11, 10, 16, 24,  40,  51,  61,  12, 12, 14, 19, 26,  58,  60,  55,
    14, 13, 16, 24, 40,  57,  69,  56,  14, 17, 22, 29, 51,  87,  80,  62,
    18, 22, 37, 56, 68,  109, 103, 77,  24, 35, 55, 64, 81,  104, 113, 92,
    49, 64, 78, 87, 103, 121, 120, 101, 72, 92, 95, 98, 112, 100, 103, 99};

// ITU-T T.81 Annex K.3 standard luminance Huffman tables
const uint8_t kDcBits[17] = {0, 0, 1, 5, 1, 1, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0};
const uint8_t kDcVals[12] = {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11};
const uint8_t kAcBits[17] = {0, 0, 2, 1, 3, 3, 2, 4, 3, 5, 5, 4, 4, 0, 0, 1, 0x7d};
const uint8_t kAcVals[162] = {
    0x01, 0x02, 0x03, 0x00, 0x04, 0x11, 0x05, 0x12, 0x21, 0x31, 0x41, 0x06,
    0x13, 0x51, 0x61, 0x07, 0x22, 0x71, 0x14, 0x32, 0x81, 0x91, 0xa1, 0x08,
    0x23, 0x42, 0xb1, 0xc1, 0x15, 0x52, 0xd1, 0xf0, 0x24, 0x33, 0x62, 0x72,
    0x82, 0x09, 0x0a, 0x16, 0x17, 0x18, 0x19, 0x1a, 0x25, 0x26, 0x27, 0x28,
    0x29, 0x2a, 0x34, 0x35, 0x36, 0x37, 0x38, 0x39, 0x3a, 0x43, 0x44, 0x45,
    0x46, 0x47, 0x48, 0x49, 0x4a, 0x53, 0x54, 0x55, 0x56, 0x57, 0x58, 0x59,
    0x5a, 0x63, 0x64, 0x65, 0x66, 0x67, 0x68, 0x69, 0x6a, 0x73, 0x74, 0x75,
    0x76, 0x77, 0x78, 0x79, 0x7a, 0x83, 0x84, 0x85, 0x86, 0x87, 0x88, 0x89,
    0x8a, 0x92, 0x93, 0x94, 0x95, 0x96, 0x97, 0x98, 0x99, 0x9a, 0xa2, 0xa3,
    0xa4, 0xa5, 0xa6, 0xa7, 0xa8, 0xa9, 0xaa, 0xb2, 0xb3, 0xb4, 0xb5, 0xb6,
    0xb7, 0xb8, 0xb9, 0xba, 0xc2, 0xc3, 0xc4, 0xc5, 0xc6, 0xc7, 0xc8, 0xc9,
    0xca, 0xd2, 0xd3, 0xd4, 0xd5, 0xd6, 0xd7, 0xd8, 0xd9, 0xda, 0xe1, 0xe2,
    0xe3, 0xe4, 0xe5, 0xe6, 0xe7, 0xe8, 0xe9, 0xea, 0xf1, 0xf2, 0xf3, 0xf4,
    0xf5, 0xf6, 0xf7, 0xf8, 0xf9, 0xfa};

struct HuffCode { uint16_t code; uint8_t len; };

// Canonical Huffman code assignment (T.81 Annex C).
void build_codes(const uint8_t bits[17], const uint8_t* vals, int nvals,
                 HuffCode table[256]) {
  int code = 0, k = 0;
  for (int len = 1; len <= 16; ++len) {
    for (int i = 0; i < bits[len]; ++i) {
      table[vals[k]] = {(uint16_t)code, (uint8_t)len};
      ++code;
      ++k;
    }
    code <<= 1;
  }
  (void)nvals;
}

struct BitWriter {
  std::vector<uint8_t>& out;
  uint32_t acc = 0;
  int nbits = 0;

  void put(uint32_t bits, int len) {
    acc = (acc << len) | (bits & ((1u << len) - 1));
    nbits += len;
    while (nbits >= 8) {
      uint8_t b = (uint8_t)(acc >> (nbits - 8));
      out.push_back(b);
      if (b == 0xFF) out.push_back(0x00);  // byte stuffing
      nbits -= 8;
    }
  }
  void flush() {
    if (nbits > 0) put(0x7F, 8 - nbits);  // pad with 1s
  }
};

void put_marker_u16(std::vector<uint8_t>& o, uint16_t v) {
  o.push_back((uint8_t)(v >> 8));
  o.push_back((uint8_t)(v & 0xFF));
}

int bit_category(int v) {
  int a = v < 0 ? -v : v;
  int n = 0;
  while (a) { ++n; a >>= 1; }
  return n;
}

// Plain separable float DCT-II with precomputed basis; clear and fast enough
// for host-side export (encoding overlaps device compute in the runner).
struct DctBasis {
  float c[8][8];
  DctBasis() {
    for (int k = 0; k < 8; ++k)
      for (int x = 0; x < 8; ++x)
        c[k][x] = std::cos((2 * x + 1) * k * 3.14159265358979323846 / 16.0) *
                  (k == 0 ? std::sqrt(0.125) : 0.5);
  }
};

long jpeg_encode_gray(const uint8_t* pix, int h, int w, int quality,
                      uint8_t* out, long cap) {
  if (h <= 0 || w <= 0 || h > 65500 || w > 65500) { set_error("bad dims"); return -1; }
  quality = std::min(100, std::max(1, quality));
  int scale = quality < 50 ? 5000 / quality : 200 - 2 * quality;
  uint8_t qt[64];
  for (int i = 0; i < 64; ++i) {
    int v = (kQuantLum[i] * scale + 50) / 100;
    qt[i] = (uint8_t)std::min(255, std::max(1, v));
  }

  // magic statics: thread-safe one-time init (encoder runs on a thread pool)
  struct HuffTables {
    HuffCode dc[256] = {}, ac[256] = {};
    HuffTables() {
      build_codes(kDcBits, kDcVals, 12, dc);
      build_codes(kAcBits, kAcVals, 162, ac);
    }
  };
  static const HuffTables huff;
  const HuffCode* dc_table = huff.dc;
  const HuffCode* ac_table = huff.ac;
  static const DctBasis basis;

  std::vector<uint8_t> o;
  o.reserve((size_t)h * w / 4 + 1024);

  // SOI, APP0/JFIF
  put_marker_u16(o, 0xFFD8);
  put_marker_u16(o, 0xFFE0);
  put_marker_u16(o, 16);
  const char jfif[] = "JFIF";
  o.insert(o.end(), jfif, jfif + 5);
  o.push_back(1); o.push_back(1);       // version 1.1
  o.push_back(0);                        // aspect-ratio units
  put_marker_u16(o, 1); put_marker_u16(o, 1);
  o.push_back(0); o.push_back(0);       // no thumbnail

  // DQT (zigzag order)
  put_marker_u16(o, 0xFFDB);
  put_marker_u16(o, 2 + 1 + 64);
  o.push_back(0x00);
  for (int i = 0; i < 64; ++i) o.push_back(qt[kZigzag[i]]);

  // SOF0: 8-bit, 1 component
  put_marker_u16(o, 0xFFC0);
  put_marker_u16(o, 2 + 6 + 3);
  o.push_back(8);
  put_marker_u16(o, (uint16_t)h);
  put_marker_u16(o, (uint16_t)w);
  o.push_back(1);
  o.push_back(1); o.push_back(0x11); o.push_back(0);

  // DHT: DC then AC
  put_marker_u16(o, 0xFFC4);
  put_marker_u16(o, (uint16_t)(2 + 1 + 16 + 12));
  o.push_back(0x00);
  for (int i = 1; i <= 16; ++i) o.push_back(kDcBits[i]);
  o.insert(o.end(), kDcVals, kDcVals + 12);
  put_marker_u16(o, 0xFFC4);
  put_marker_u16(o, (uint16_t)(2 + 1 + 16 + 162));
  o.push_back(0x10);
  for (int i = 1; i <= 16; ++i) o.push_back(kAcBits[i]);
  o.insert(o.end(), kAcVals, kAcVals + 162);

  // SOS
  put_marker_u16(o, 0xFFDA);
  put_marker_u16(o, 2 + 1 + 2 + 3);
  o.push_back(1);
  o.push_back(1); o.push_back(0x00);
  o.push_back(0); o.push_back(63); o.push_back(0);

  BitWriter bw{o};
  int prev_dc = 0;
  float block[64], tmp[64], coef[64];

  for (int by = 0; by < h; by += 8) {
    for (int bx = 0; bx < w; bx += 8) {
      // fetch 8x8 block, edge-replicated, level-shifted
      for (int y = 0; y < 8; ++y) {
        int sy = std::min(by + y, h - 1);
        for (int x = 0; x < 8; ++x) {
          int sx = std::min(bx + x, w - 1);
          block[y * 8 + x] = (float)pix[(size_t)sy * w + sx] - 128.0f;
        }
      }
      // rows then columns
      for (int y = 0; y < 8; ++y)
        for (int k = 0; k < 8; ++k) {
          float s = 0;
          for (int x = 0; x < 8; ++x) s += block[y * 8 + x] * basis.c[k][x];
          tmp[y * 8 + k] = s;
        }
      for (int k = 0; k < 8; ++k)
        for (int u = 0; u < 8; ++u) {
          float s = 0;
          for (int y = 0; y < 8; ++y) s += tmp[y * 8 + k] * basis.c[u][y];
          coef[u * 8 + k] = s;
        }

      int q[64];
      for (int i = 0; i < 64; ++i) {
        float v = coef[kZigzag[i]] / (float)qt[kZigzag[i]];
        q[i] = (int)std::lround(v);
      }

      // DC
      int diff = q[0] - prev_dc;
      prev_dc = q[0];
      int s = bit_category(diff);
      bw.put(dc_table[s].code, dc_table[s].len);
      if (s) bw.put(diff < 0 ? (uint32_t)(diff + (1 << s) - 1) : (uint32_t)diff, s);

      // AC with run-length, ZRL, EOB
      int run = 0;
      for (int i = 1; i < 64; ++i) {
        if (q[i] == 0) { ++run; continue; }
        while (run > 15) {
          bw.put(ac_table[0xF0].code, ac_table[0xF0].len);
          run -= 16;
        }
        int sz = bit_category(q[i]);
        int sym = (run << 4) | sz;
        bw.put(ac_table[sym].code, ac_table[sym].len);
        bw.put(q[i] < 0 ? (uint32_t)(q[i] + (1 << sz) - 1) : (uint32_t)q[i], sz);
        run = 0;
      }
      if (run > 0) bw.put(ac_table[0x00].code, ac_table[0x00].len);
    }
  }
  bw.flush();
  put_marker_u16(o, 0xFFD9);

  if ((long)o.size() > cap) { set_error("output buffer too small"); return -1; }
  std::memcpy(out, o.data(), o.size());
  return (long)o.size();
}

// ---------------------------------------------------------------------------
// Host-export renderer — mirrors render/host_render.py operation for
// operation (same f32 arithmetic, same association order, numpy's
// round-half-even via nearbyintf, truncating uint8 casts), so the C++ and
// NumPy paths produce IDENTICAL bytes. The library builds with
// -ffp-contract=off so the compiler cannot fuse the lerp into FMAs numpy
// does not use. Reference contract: RenderToImage(Black, 512, 512) +
// ImageRenderer / SegmentationRenderer({1: White}, 0.6, 1.0, 2)
// (main_sequential.cpp:49-78).
// ---------------------------------------------------------------------------

struct LetterboxCoords {
  std::vector<float> src_y, src_x;
  std::vector<uint8_t> in_y, in_x;
};

LetterboxCoords letterbox_coords(int h, int w, int out_size) {
  LetterboxCoords lc;
  lc.src_y.resize(out_size); lc.src_x.resize(out_size);
  lc.in_y.resize(out_size); lc.in_x.resize(out_size);
  float fh = (float)h, fw = (float)w;
  float scale = std::min((float)out_size / fh, (float)out_size / fw);
  float dest_h = fh * scale, dest_w = fw * scale;
  float off_y = ((float)out_size - dest_h) / 2.0f;
  float off_x = ((float)out_size - dest_w) / 2.0f;
  for (int o = 0; o < out_size; ++o) {
    float fo = (float)o;
    lc.src_y[o] = (fo - off_y + 0.5f) / scale - 0.5f;
    lc.src_x[o] = (fo - off_x + 0.5f) / scale - 0.5f;
    lc.in_y[o] = (fo >= std::floor(off_y)) && (fo < std::ceil(off_y + dest_h));
    lc.in_x[o] = (fo >= std::floor(off_x)) && (fo < std::ceil(off_x + dest_w));
  }
  return lc;
}

void render_gray_impl(const float* pixels, int stride, int h, int w,
                      const LetterboxCoords& lc, int out_size,
                      uint8_t* out) {
  // auto-window over the true region only
  float vmin = pixels[0], vmax = pixels[0];
  for (int y = 0; y < h; ++y)
    for (int x = 0; x < w; ++x) {
      float v = pixels[(size_t)y * stride + x];
      vmin = std::min(vmin, v);
      vmax = std::max(vmax, v);
    }
  float rng = std::max(vmax - vmin, 1e-6f);
  // per-column sample coordinates are row-invariant: compute once
  std::vector<int> x0s(out_size), x1s(out_size);
  std::vector<float> fxs(out_size);
  for (int ox = 0; ox < out_size; ++ox) {
    float sx = lc.src_x[ox];
    x0s[ox] = std::min(std::max((int)std::floor(sx), 0), w - 1);
    x1s[ox] = std::min(x0s[ox] + 1, w - 1);
    fxs[ox] = std::min(std::max(sx - (float)x0s[ox], 0.0f), 1.0f);
  }
  for (int oy = 0; oy < out_size; ++oy) {
    uint8_t* orow = out + (size_t)oy * out_size;
    if (!lc.in_y[oy]) {
      std::memset(orow, 0, out_size);
      continue;
    }
    float sy = lc.src_y[oy];
    int y0 = std::min(std::max((int)std::floor(sy), 0), h - 1);
    int y1 = std::min(y0 + 1, h - 1);
    float fy = std::min(std::max(sy - (float)y0, 0.0f), 1.0f);
    const float* r0 = pixels + (size_t)y0 * stride;
    const float* r1 = pixels + (size_t)y1 * stride;
    for (int ox = 0; ox < out_size; ++ox) {
      uint8_t px = 0;
      if (lc.in_x[ox]) {
        int x0 = x0s[ox], x1 = x1s[ox];
        float fx = fxs[ox];
        // numpy: rows = img[y0]*(1-fy) + img[y1]*fy; out = rows[x0]*(1-fx)
        //        + rows[x1]*fx — keep the exact association
        float a = r0[x0] * (1.0f - fy) + r1[x0] * fy;
        float b = r0[x1] * (1.0f - fy) + r1[x1] * fy;
        float sampled = a * (1.0f - fx) + b * fx;
        float g = (sampled - vmin) / rng * 255.0f;
        g = std::min(std::max(g, 0.0f), 255.0f);
        px = (uint8_t)g;  // truncation, like astype(uint8)
      }
      orow[ox] = px;
    }
  }
}

void render_seg_impl(const uint8_t* mask, int stride, int h, int w,
                     const LetterboxCoords& lc, int out_size, float opacity,
                     float border_opacity, int border_radius, uint8_t* out) {
  // nearest-sampled binary mask, restricted to the letterbox interior
  std::vector<uint8_t> m((size_t)out_size * out_size);
  std::vector<int> yy(out_size), xx(out_size);
  for (int o = 0; o < out_size; ++o) {
    // numpy np.round rounds half to even: nearbyintf under the default
    // FE_TONEAREST mode matches it exactly
    yy[o] = std::min(std::max((int)std::nearbyintf(lc.src_y[o]), 0), h - 1);
    xx[o] = std::min(std::max((int)std::nearbyintf(lc.src_x[o]), 0), w - 1);
  }
  for (int oy = 0; oy < out_size; ++oy)
    for (int ox = 0; ox < out_size; ++ox)
      m[(size_t)oy * out_size + ox] =
          (mask[(size_t)yy[oy] * stride + xx[ox]] > 0) && lc.in_y[oy] &&
          lc.in_x[ox];
  // binary erosion, euclidean-disk element of size 2r+1, zero padding —
  // the same offsets ops.neighborhood.footprint_offsets(size, "disk")
  // enumerates
  int size = 2 * border_radius + 1;
  int r = size / 2;
  double rad2 = (size / 2.0) * (size / 2.0);
  std::vector<std::pair<int, int>> offs;
  for (int dr = -r; dr <= r; ++dr)
    for (int dc = -r; dc <= r; ++dc)
      if ((double)(dr * dr + dc * dc) <= rad2) offs.emplace_back(dr, dc);
  const uint8_t interior_px = (uint8_t)std::min(
      std::max(opacity * 255.0f, 0.0f), 255.0f);
  const uint8_t border_px = (uint8_t)std::min(
      std::max(border_opacity * 255.0f, 0.0f), 255.0f);
  for (int oy = 0; oy < out_size; ++oy) {
    for (int ox = 0; ox < out_size; ++ox) {
      uint8_t cur = m[(size_t)oy * out_size + ox];
      if (!cur) {  // outside the mask the erosion result is irrelevant
        out[(size_t)oy * out_size + ox] = 0;
        continue;
      }
      uint8_t interior = 1;
      for (auto& od : offs) {
        int y = oy + od.first, x = ox + od.second;
        uint8_t v = (y >= 0 && y < out_size && x >= 0 && x < out_size)
                        ? m[(size_t)y * out_size + x]
                        : 0;
        if (!v) { interior = 0; break; }
      }
      out[(size_t)oy * out_size + ox] = interior ? interior_px : border_px;
    }
  }
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

NM03_EXPORT const char* nm03_last_error() { return g_error.c_str(); }

NM03_EXPORT int nm03_version() { return 1; }

// Decode one slice. `out` must hold max_elems floats; rows*cols must fit.
// Returns 0 on success.
NM03_EXPORT int nm03_dicom_read(const char* path, float* out, long max_elems,
                                int* rows, int* cols) {
  try {
    std::vector<uint8_t> raw;
    if (!read_file(path, &raw)) return 1;
    std::vector<float> pixels;
    if (!decode_dicom(raw.data(), raw.size(), &pixels, rows, cols)) return 2;
    if ((long)pixels.size() > max_elems) { set_error("output buffer too small"); return 3; }
    std::memcpy(out, pixels.data(), pixels.size() * sizeof(float));
    return 0;
  } catch (const std::exception& e) {
    // an exception must never unwind through the extern "C" boundary (UB)
    set_error(std::string("decode exception: ") + e.what());
    return 2;
  }
}

// Thread-pool batch decode into a padded canvas arena.
//
// This is the native core of the host->HBM prefetch path: the TPU-side
// replacement for the reference's OpenMP parallel-for over a slice batch
// (main_parallel.cpp:336) applied where it belongs on TPU — the host decode
// stage, so the device sees one contiguous (n, canvas_h, canvas_w) float32
// arena ready for device_put.
//
//   paths    — n C strings
//   out      — n * canvas_h * canvas_w floats, zero-padded per slot
//   dims     — n * 2 ints (rows, cols); untouched slots stay as passed in
//   ok       — n flags: 1 decoded + guards passed, 0 failed (per-slice
//              catch-and-continue, main_sequential.cpp:267-271)
//   err      — optional (may be NULL) n codes: 0 ok, 1 read failed,
//              2 parse failed, 3 below min_dim, 4 exceeds canvas
//   min_dim  — reject slices smaller than this (main_sequential.cpp:189-192)
// Returns the number of successfully decoded slices.
NM03_EXPORT int nm03_load_batch(const char** paths, int n, int canvas_h,
                                int canvas_w, int min_dim, int threads,
                                float* out, int* dims, unsigned char* ok,
                                int* err) {
  if (n <= 0) return 0;
  threads = std::max(1, std::min(threads, n));
  std::atomic<int> next(0), good(0);
  auto worker = [&]() {
    std::vector<float> pixels;
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) return;
      ok[i] = 0;
      auto fail = [&](int code) { if (err) err[i] = code; };
      int rows = 0, cols = 0;
      std::vector<uint8_t> raw;
      try {
        if (!read_file(paths[i], &raw)) { fail(1); continue; }
        if (!decode_dicom(raw.data(), raw.size(), &pixels, &rows, &cols)) {
          fail(2);
          continue;
        }
      } catch (const std::exception&) {
        // per-slice catch-and-continue: an exception escaping a std::thread
        // lambda would std::terminate the whole Python process
        fail(2);
        continue;
      }
      if (rows < min_dim || cols < min_dim) { fail(3); continue; }
      if (rows > canvas_h || cols > canvas_w) { fail(4); continue; }
      if (err) err[i] = 0;
      float* slot = out + (size_t)i * canvas_h * canvas_w;
      std::memset(slot, 0, (size_t)canvas_h * canvas_w * sizeof(float));
      for (int y = 0; y < rows; ++y)
        std::memcpy(slot + (size_t)y * canvas_w, pixels.data() + (size_t)y * cols,
                    (size_t)cols * sizeof(float));
      dims[2 * i] = rows;
      dims[2 * i + 1] = cols;
      ok[i] = 1;
      good.fetch_add(1);
    }
  };
  std::vector<std::thread> pool;
  for (int t = 0; t < threads; ++t) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
  return good.load();
}

// Baseline JPEG (grayscale). Returns bytes written, or -1 on error.
// Render the export pair for one slice: letterboxed auto-windowed grayscale
// + white-overlay segmentation render, byte-identical to the NumPy host
// renderer (render/host_render.py). pixels is the (canvas_h, canvas_w)
// padded f32 canvas; (h, w) the slice's true dims; both outputs are
// (out_size, out_size) uint8. Returns 0 on success.
NM03_EXPORT int nm03_render_pair(const float* pixels, int canvas_h,
                                 int canvas_w, const unsigned char* mask,
                                 int mask_h, int mask_w, int h, int w,
                                 int out_size, float opacity,
                                 float border_opacity, int border_radius,
                                 unsigned char* gray_out,
                                 unsigned char* seg_out) {
  try {
    if (h <= 0 || w <= 0 || h > canvas_h || w > canvas_w || h > mask_h ||
        w > mask_w || out_size <= 0 || border_radius < 0) {
      set_error("render: bad dimensions");
      return 1;
    }
    LetterboxCoords lc = letterbox_coords(h, w, out_size);
    render_gray_impl(pixels, canvas_w, h, w, lc, out_size, gray_out);
    render_seg_impl(mask, mask_w, h, w, lc, out_size, opacity,
                    border_opacity, border_radius, seg_out);
    return 0;
  } catch (const std::exception& e) {
    set_error(std::string("render exception: ") + e.what());
    return 2;
  }
}

NM03_EXPORT long nm03_jpeg_encode_gray(const unsigned char* pixels, int h,
                                       int w, int quality, unsigned char* out,
                                       long out_capacity) {
  return jpeg_encode_gray(pixels, h, w, quality, out, out_capacity);
}
