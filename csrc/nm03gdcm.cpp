// Optional GDCM-backed fallback importer for the JPEG 2000 transfer
// syntaxes (1.2.840.10008.1.2.4.90/.91 and the Part-2 variants).
//
// The in-tree importer (data/dicomlite.py + csrc/nm03native.cpp) owns every
// syntax the cohort actually uses — uncompressed LE/BE, RLE, JPEG lossless,
// JPEG-LS, baseline JPEG. JPEG 2000's EBCOT arithmetic coder is the one
// family where a from-scratch decoder buys nothing over the system
// libraries, so — exactly like the reference sits on DCMTK for its whole
// importer (FAST_directives.hpp:30) — this shim hands J2K files to the
// system GDCM when present. It is compiled on demand by
// nm03_capstone_project_tpu/data/gdcm_fallback.py only when the gdcm-3.0
// headers exist, and the importer degrades to the transcode-remedy error
// without it.
//
// Build (done by gdcm_fallback.py):
//   g++ -O2 -std=c++17 -shared -fPIC csrc/nm03gdcm.cpp \
//     -I/usr/include/gdcm-3.0 -lgdcmMSFF -lgdcmDSED -lgdcmCommon \
//     -o libnm03gdcm.so

#include <cstdint>
#include <cstring>
#include <string>

#include <gdcmImage.h>
#include <gdcmImageReader.h>
#include <gdcmPixelFormat.h>

#define NM03_EXPORT extern "C" __attribute__((visibility("default")))

namespace {
thread_local std::string g_error;
void set_error(const std::string& msg) { g_error = msg; }
}  // namespace

NM03_EXPORT const char* nm03_gdcm_last_error() { return g_error.c_str(); }

// Decode one 2D monochrome DICOM file into rescaled float32 pixels.
// Returns 0 on success; out must hold cap floats. rows/cols are outputs;
// scalar_out reports the raw sample type (0=u8, 1=i8, 2=u16, 3=i16) so the
// caller can surface an honest raw_dtype.
NM03_EXPORT int nm03_gdcm_read(const char* path, float* out, long cap,
                               long* rows_out, long* cols_out,
                               int* scalar_out) {
  try {
    gdcm::ImageReader reader;
    reader.SetFileName(path);
    if (!reader.Read()) {
      set_error("gdcm could not read the file");
      return 1;
    }
    const gdcm::Image& img = reader.GetImage();
    if (img.GetNumberOfDimensions() != 2) {
      set_error("gdcm fallback: only single-slice 2D files are in envelope");
      return 2;
    }
    const unsigned int* dims = img.GetDimensions();
    const long cols = dims[0], rows = dims[1];
    if (rows <= 0 || cols <= 0 || rows > 32768 || cols > 32768 ||
        rows * cols > cap) {
      set_error("gdcm fallback: implausible or oversized dimensions");
      return 3;
    }
    const gdcm::PixelFormat& pf = img.GetPixelFormat();
    if (pf.GetSamplesPerPixel() != 1) {
      set_error("gdcm fallback: only monochrome supported");
      return 4;
    }
    const size_t buflen = img.GetBufferLength();
    std::string buffer(buflen, '\0');
    if (!img.GetBuffer(buffer.data())) {
      set_error("gdcm fallback: pixel decode failed");
      return 5;
    }
    const double slope = img.GetSlope(), intercept = img.GetIntercept();
    const size_t n = (size_t)rows * cols;
    const auto st = pf.GetScalarType();
    if (st == gdcm::PixelFormat::UINT16 && buflen >= n * 2) {
      const uint8_t* p = (const uint8_t*)buffer.data();
      for (size_t i = 0; i < n; ++i)
        out[i] = (float)((double)(uint16_t)(p[2 * i] | (p[2 * i + 1] << 8)) *
                             slope + intercept);
      *scalar_out = 2;
    } else if (st == gdcm::PixelFormat::INT16 && buflen >= n * 2) {
      const uint8_t* p = (const uint8_t*)buffer.data();
      for (size_t i = 0; i < n; ++i)
        out[i] = (float)((double)(int16_t)(p[2 * i] | (p[2 * i + 1] << 8)) *
                             slope + intercept);
      *scalar_out = 3;
    } else if (st == gdcm::PixelFormat::UINT8 && buflen >= n) {
      const uint8_t* p = (const uint8_t*)buffer.data();
      for (size_t i = 0; i < n; ++i)
        out[i] = (float)((double)p[i] * slope + intercept);
      *scalar_out = 0;
    } else if (st == gdcm::PixelFormat::INT8 && buflen >= n) {
      const int8_t* p = (const int8_t*)buffer.data();
      for (size_t i = 0; i < n; ++i)
        out[i] = (float)((double)p[i] * slope + intercept);
      *scalar_out = 1;
    } else {
      set_error("gdcm fallback: unsupported pixel format " +
                std::string(pf.GetScalarTypeAsString()));
      return 6;
    }
    *rows_out = rows;
    *cols_out = cols;
    return 0;
  } catch (const std::exception& e) {
    set_error(std::string("gdcm fallback exception: ") + e.what());
    return 7;
  } catch (...) {
    set_error("gdcm fallback: unknown exception");
    return 7;
  }
}
