"""Learned model family: U-Net forward, distillation training, sharded step.

The multi-device test runs the SAME train step over a ('data', 'model') mesh
on the 8-virtual-device CPU backend and checks it agrees with the unsharded
step — the formalization of "sharding must not change the math".
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = [pytest.mark.slow]


from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.core import pad_to_canvas
from nm03_capstone_project_tpu.data.synthetic import phantom_series
from nm03_capstone_project_tpu.models import (
    apply_unet,
    distill_batch,
    fit,
    init_unet,
    make_optimizer,
    make_sharded_train_step,
    predict_mask,
    prepare_student_inputs,
    train_step,
)
from nm03_capstone_project_tpu.parallel import make_mesh

CFG = PipelineConfig(canvas=64, grow_block_iters=8, grow_max_iters=128)


def _batch(n=4, seed=3):
    series = phantom_series(n, 64, 64, seed=seed)
    batch = pad_to_canvas(series, CFG.canvas_hw)
    return jnp.asarray(batch.pixels), jnp.asarray(batch.dims)


def _student_batch(n=4, seed=3):
    px, dims = _batch(n, seed)
    return prepare_student_inputs(px, CFG), distill_batch(px, dims, CFG), dims


class TestForward:
    def test_logit_shapes_and_dtype(self):
        params = init_unet(jax.random.PRNGKey(0), base=8)
        px, _ = _batch(2)
        logits = apply_unet(params, px, jnp.float32)
        assert logits.shape == (2, 64, 64)
        assert logits.dtype == jnp.float32

    def test_bfloat16_compute_path_traces(self):
        params = init_unet(jax.random.PRNGKey(0), base=8)
        px, _ = _batch(2)
        logits = jax.jit(lambda p, x: apply_unet(p, x, jnp.bfloat16))(params, px)
        assert logits.dtype == jnp.float32  # logits cast back for the loss
        assert np.isfinite(np.asarray(logits)).all()

    def test_mask_contract_is_uint8(self):
        params = init_unet(jax.random.PRNGKey(0), base=8)
        px, _ = _batch(1)
        m = predict_mask(params, px, jnp.float32)
        assert m.dtype == jnp.uint8 and set(np.unique(np.asarray(m))) <= {0, 1}

    def test_rejects_unaligned_base(self):
        with pytest.raises(ValueError, match="multiple of 8"):
            init_unet(jax.random.PRNGKey(0), base=12)


class TestDistillation:
    def test_teacher_labels_come_from_pipeline(self):
        px, dims = _batch(3)
        labels = distill_batch(px, dims, CFG)
        assert labels.shape == (3, 64, 64) and labels.dtype == jnp.uint8

    def test_prepared_inputs_are_order_one(self):
        px, _ = _batch(2)
        x = np.asarray(prepare_student_inputs(px, CFG))
        assert x.min() >= CFG.clip_low - 1e-6 and x.max() <= CFG.clip_high

    def test_loss_decreases(self):
        x, labels, dims = _student_batch(4)
        params = init_unet(jax.random.PRNGKey(1), base=8)
        _, losses = fit(params, x, labels, dims, steps=30, lr=3e-3)
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, losses[::10]

    def test_student_learns_the_lesion(self):
        x, labels, dims = _student_batch(6, seed=9)
        params = init_unet(jax.random.PRNGKey(2), base=8)
        params, _ = fit(params, x, labels, dims, steps=150, lr=3e-3)
        pred = np.asarray(predict_mask(params, x, jnp.float32))
        truth = np.asarray(labels)
        inter = (pred & truth).sum()
        union = (pred | truth).sum()
        assert union > 0 and inter / union > 0.6, f"IoU {inter}/{union}"


class TestShardedTraining:
    def test_dp_tp_step_matches_unsharded(self):
        n_dev = len(jax.devices())
        if n_dev < 8:
            pytest.skip("needs the 8-virtual-device CPU mesh")
        mesh = make_mesh(8, axis_names=("data", "model"), axis_sizes=(4, 2))
        x, labels, dims = _student_batch(8)
        params = init_unet(jax.random.PRNGKey(4), base=8)
        tx = make_optimizer(1e-3)

        step_fn, place = make_sharded_train_step(
            mesh, params, tx, compute_dtype=jnp.float32
        )
        sp = place(params)
        s_opt = tx.init(sp)  # inherits the params' shardings leaf-for-leaf
        new_sp, _, loss_sharded = step_fn(sp, s_opt, x, labels, dims)

        opt0 = tx.init(params)
        new_p, _, loss_plain = train_step(
            params, opt0, x, labels, dims, tx=tx, compute_dtype=jnp.float32
        )
        assert np.allclose(float(loss_sharded), float(loss_plain), rtol=1e-5)
        flat_s = jax.tree_util.tree_leaves(new_sp)
        flat_p = jax.tree_util.tree_leaves(new_p)
        for a, b in zip(flat_s, flat_p):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


    def test_fit_sharded_wraps_odd_batch_and_learns(self):
        # the CLI's multi-device loop: batch (5) does not divide dp (4), so
        # fit_sharded wraps real slices; params come back host-resident
        n_dev = len(jax.devices())
        if n_dev < 8:
            pytest.skip("needs the 8-virtual-device CPU mesh")
        from nm03_capstone_project_tpu.models import fit_sharded

        mesh = make_mesh(8, axis_names=("data", "model"), axis_sizes=(4, 2))
        x, labels, dims = _student_batch(5, seed=7)
        params = init_unet(jax.random.PRNGKey(6), base=8)
        params, losses = fit_sharded(
            params, x, labels, dims, mesh, steps=30, lr=3e-3,
            compute_dtype=jnp.float32,
        )
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, losses[::10]
        for leaf in jax.tree_util.tree_leaves(params):
            assert isinstance(leaf, np.ndarray)  # host-resident for orbax

    def test_kernels_actually_sharded_on_model_axis(self):
        n_dev = len(jax.devices())
        if n_dev < 8:
            pytest.skip("needs the 8-virtual-device CPU mesh")
        mesh = make_mesh(8, axis_names=("data", "model"), axis_sizes=(4, 2))
        params = init_unet(jax.random.PRNGKey(5), base=8)
        from nm03_capstone_project_tpu.models import param_shardings

        shards = param_shardings(params, mesh)
        head_spec = shards["head"]["w"].spec
        assert tuple(head_spec) == (None, None, None, "model")
