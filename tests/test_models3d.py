"""3D U-Net family: volumetric forward, distillation from the 3D teacher."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = [pytest.mark.slow]


from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import phantom_volume
from nm03_capstone_project_tpu.models import (
    apply_unet3d,
    distill_volume,
    fit,
    init_unet3d,
    param_shardings,
    predict_mask3d,
    prepare_student_inputs,
)

CFG = PipelineConfig(canvas=32, grow_block_iters=8, grow_max_iters=64, min_dim=16)


def _volume_batch(b=2, d=8, hw=32, seed=0):
    vols = np.stack(
        [phantom_volume(n_slices=d, height=hw, width=hw, seed=seed + i) for i in range(b)]
    ).astype(np.float32)
    dims = np.full((b, 2), hw, np.int32)
    return jnp.asarray(vols), jnp.asarray(dims)


class TestForward3D:
    def test_logit_shapes(self):
        params = init_unet3d(jax.random.PRNGKey(0), base=8)
        vols, _ = _volume_batch()
        logits = apply_unet3d(params, vols, jnp.float32)
        assert logits.shape == vols.shape and logits.dtype == jnp.float32

    def test_mask_contract(self):
        params = init_unet3d(jax.random.PRNGKey(0), base=8)
        vols, _ = _volume_batch(b=1)
        m = predict_mask3d(params, vols, jnp.float32)
        assert m.dtype == jnp.uint8

    def test_rejects_unaligned_base(self):
        with pytest.raises(ValueError, match="multiple of 8"):
            init_unet3d(jax.random.PRNGKey(0), base=4)

    def test_params_shard_on_model_axis(self):
        from nm03_capstone_project_tpu.parallel import make_mesh

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-virtual-device CPU mesh")
        mesh = make_mesh(8, axis_names=("data", "model"), axis_sizes=(4, 2))
        shards = param_shardings(init_unet3d(jax.random.PRNGKey(1), base=8), mesh)
        assert tuple(shards["head"]["w"].spec) == (None, None, None, None, "model")


class TestDistillation3D:
    def test_teacher_labels_are_3d(self):
        vols, dims = _volume_batch(b=1)
        labels = jax.vmap(lambda v, d: distill_volume(v, d, CFG))(vols, dims)
        assert labels.shape == vols.shape and labels.dtype == jnp.uint8
        assert int(labels.sum()) > 0

    def test_volume_loss_decreases(self):
        vols, dims = _volume_batch(b=2)
        labels = jax.vmap(lambda v, d: distill_volume(v, d, CFG))(vols, dims)
        x = prepare_student_inputs(vols, CFG)
        params = init_unet3d(jax.random.PRNGKey(2), base=8)
        params, losses = fit(
            params, x, labels, dims, steps=40, lr=3e-3, apply_fn=apply_unet3d
        )
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, losses[::10]
