"""Multi-device sharding tests on the 8-virtual-device CPU mesh.

Formalizes the invariant the reference can only check by diffing output
directories (out-sequential/ vs out-parallel/, SURVEY.md section 4): the
sharded paths are bit-identical to the single-device ones. Runs entirely on
`xla_force_host_platform_device_count=8` devices (conftest), exercising the
real NamedSharding / shard_map / ppermute / psum code paths.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import phantom_slice, phantom_volume
from nm03_capstone_project_tpu.parallel import (
    make_mesh,
    pad_to_multiple,
    process_batch_sharded,
    process_volume_zsharded,
)
from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_batch
from nm03_capstone_project_tpu.pipeline.volume_pipeline import process_volume

CFG = PipelineConfig(grow_block_iters=8, grow_max_iters=512)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(8, axis_names=("data",))


@pytest.fixture(scope="module")
def meshz():
    return make_mesh(8, axis_names=("z",))


def _batch(n, hw=96):
    px = np.stack(
        [phantom_slice(hw, hw, seed=i, lesion_radius=0.12 + 0.01 * i) for i in range(n)]
    )
    dims = np.full((n, 2), hw, np.int32)
    return px, dims


class TestMesh:
    def test_make_mesh_shape(self, mesh8):
        assert mesh8.shape == {"data": 8}

    def test_two_axis_mesh(self):
        m = make_mesh(8, axis_names=("data", "z"), axis_sizes=(2, 4))
        assert m.shape == {"data": 2, "z": 4}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError):
            make_mesh(1024)

    def test_pad_to_multiple(self):
        px, dims = _batch(5, 32)
        p2, d2, real = pad_to_multiple(px, dims, 8)
        assert p2.shape[0] == 8 and real == 5
        assert (d2[5:] == 1).all()
        p3, d3, real3 = pad_to_multiple(px, dims, 5)
        assert p3.shape[0] == 5 and real3 == 5


class TestDataParallel:
    @pytest.mark.slow
    def test_sharded_equals_single_device(self, mesh8):
        px, dims = _batch(8)
        got = process_batch_sharded(jnp.asarray(px), jnp.asarray(dims), CFG, mesh8)
        want = process_batch(jnp.asarray(px), jnp.asarray(dims), CFG)
        np.testing.assert_array_equal(np.asarray(got["mask"]), np.asarray(want["mask"]))
        np.testing.assert_allclose(
            np.asarray(got["original"]), np.asarray(want["original"])
        )

    def test_output_is_sharded_over_mesh(self, mesh8):
        px, dims = _batch(8)
        got = process_batch_sharded(jnp.asarray(px), jnp.asarray(dims), CFG, mesh8)
        assert len(got["mask"].sharding.device_set) == 8

    @pytest.mark.slow
    def test_padded_lanes_do_not_disturb_real_ones(self, mesh8):
        px, dims = _batch(5)
        p2, d2, real = pad_to_multiple(px, dims, 8)
        got = process_batch_sharded(jnp.asarray(p2), jnp.asarray(d2), CFG, mesh8)
        want = process_batch(jnp.asarray(px), jnp.asarray(dims), CFG)
        np.testing.assert_array_equal(
            np.asarray(got["mask"])[:real], np.asarray(want["mask"])
        )

    @pytest.mark.slow
    def test_with_render(self, mesh8):
        px, dims = _batch(8)
        got = process_batch_sharded(
            jnp.asarray(px), jnp.asarray(dims), CFG, mesh8, with_render=True
        )
        assert got["original"].shape == (8, CFG.render_size, CFG.render_size)
        assert got["mask"].shape == (8, CFG.render_size, CFG.render_size)


class TestZShard:
    @pytest.mark.parametrize("morph_size", [1, 3, 5])
    @pytest.mark.slow
    def test_zsharded_equals_single_device(self, meshz, morph_size):
        # morph_size=5 needs a 2-plane halo exchange at shard boundaries
        # (VERDICT r1 weak #6: a fixed 1-plane halo gave silent wrong
        # answers); morph_size=1 needs none (r[-0:] slicing would be wrong)
        cfg = dataclasses.replace(CFG, morph_size=morph_size)
        vol = phantom_volume(n_slices=16, height=64, width=64, seed=3)
        dims = jnp.asarray([64, 64], jnp.int32)
        got = process_volume_zsharded(jnp.asarray(vol), dims, cfg, meshz)
        want = process_volume(jnp.asarray(vol), dims, cfg)
        np.testing.assert_array_equal(
            np.asarray(got["mask"]), np.asarray(want["mask"])
        )

    def test_shard_too_shallow_for_halo_raises(self, meshz):
        # depth 8 over 8 shards = 1 plane per shard < radius 2 for
        # morph_size=5: must reject loudly instead of truncating the halo
        cfg = dataclasses.replace(CFG, morph_size=5)
        vol = phantom_volume(n_slices=8, height=32, width=32, seed=3)
        with pytest.raises(ValueError, match="halo"):
            process_volume_zsharded(
                jnp.asarray(vol), jnp.asarray([32, 32], jnp.int32), cfg, meshz
            )

    @pytest.mark.slow
    def test_region_crosses_shard_boundaries(self, meshz):
        # a lesion spanning all 16 slices; with 8 shards of depth 2 the
        # region must cross every shard boundary via the halo exchange
        vol = phantom_volume(n_slices=16, height=64, width=64, seed=4)
        dims = jnp.asarray([64, 64], jnp.int32)
        got = np.asarray(process_volume_zsharded(jnp.asarray(vol), dims, CFG, meshz)["mask"])
        per_slice = got.reshape(16, -1).sum(axis=1)
        # center slices (max lesion) segmented; mask spans > one 2-slice shard
        assert (per_slice > 0).sum() > 2

    def test_indivisible_depth_raises(self, meshz):
        vol = jnp.zeros((10, 32, 32), jnp.float32)
        with pytest.raises(ValueError):
            process_volume_zsharded(vol, jnp.asarray([32, 32], jnp.int32), CFG, meshz)


class TestCollectiveLowering:
    def test_zshard_program_contains_collectives(self, meshz):
        """The z-sharded program really lowers to collective-permute/all-reduce."""
        from nm03_capstone_project_tpu.parallel.zshard import _compiled_zsharded

        vol = jnp.zeros((16, 32, 32), jnp.float32)
        dims = jnp.asarray([32, 32], jnp.int32)
        txt = _compiled_zsharded(meshz, CFG).lower(vol, dims).as_text()
        assert "collective_permute" in txt or "collective-permute" in txt
        assert "all_reduce" in txt or "all-reduce" in txt


class TestDistributed:
    """Multi-host wrapper: single-process behavior (multi-host needs a pod)."""

    def test_initialize_is_noop_single_process(self, monkeypatch):
        from nm03_capstone_project_tpu.parallel import distributed

        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        assert distributed.initialize() is False

    def test_no_cluster_env_never_calls_jax_initialize(self, monkeypatch):
        # the single-host no-op is structural (no cluster env signal), not
        # inferred from exception wording (ADVICE r1: message matching breaks
        # across jax versions)
        import jax

        from nm03_capstone_project_tpu.parallel import distributed

        for key in distributed._CLUSTER_ENV_SIGNALS:
            monkeypatch.delenv(key, raising=False)
        monkeypatch.setattr(distributed, "_initialized", False)

        def boom(**kwargs):
            raise AssertionError("initialize() dialed the cluster with no env")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        assert distributed.initialize() is False

    def test_detected_cluster_join_failure_raises(self, monkeypatch):
        # a DETECTED cluster failing to join must raise — silent single-host
        # degradation would run duplicate workloads
        import jax

        from nm03_capstone_project_tpu.parallel import distributed

        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "203.0.113.1:1234")
        monkeypatch.setattr(distributed, "_initialized", False)
        monkeypatch.setattr(
            jax.distributed,
            "initialize",
            lambda **kw: (_ for _ in ()).throw(RuntimeError("barrier timeout")),
        )
        with pytest.raises(RuntimeError, match="barrier timeout"):
            distributed.initialize()

    def test_late_init_with_cluster_env_warns_not_dies(self, monkeypatch):
        import jax

        from nm03_capstone_project_tpu.parallel import distributed

        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "203.0.113.1:1234")
        monkeypatch.setattr(distributed, "_initialized", False)
        monkeypatch.setattr(
            jax.distributed,
            "initialize",
            lambda **kw: (_ for _ in ()).throw(
                RuntimeError("jax.distributed.initialize must be called before "
                             "any JAX computations")
            ),
        )
        assert distributed.initialize() is False

    def test_global_mesh_covers_all_devices(self):
        import jax

        from nm03_capstone_project_tpu.parallel import distributed

        n = len(jax.devices())
        mesh = distributed.global_mesh(("data",))
        assert mesh.size == n
        if n % 2 == 0:
            mesh2 = distributed.global_mesh(("data", "model"), (n // 2, 2))
            assert mesh2.shape == {"data": n // 2, "model": 2}

    def test_global_mesh_rejects_bad_sizes(self):
        import jax
        import pytest as _pytest

        from nm03_capstone_project_tpu.parallel import distributed

        with _pytest.raises(ValueError, match="axis_sizes"):
            distributed.global_mesh(("data",), (len(jax.devices()) + 1,))

    def test_process_info_single_host(self):
        from nm03_capstone_project_tpu.parallel import distributed

        info = distributed.process_info()
        assert info["process_count"] == 1 and info["process_index"] == 0
        assert info["global_devices"] == info["local_devices"]


class TestConvergedFlagSharded:
    """VERDICT r4 item 4 on the distributed paths: the z-shard psum loop and
    the dp-sharded batch must surface cap-truncation like the local ops."""

    def test_zshard_flag_converges_and_caps(self, meshz):
        # the phantom's lesion lands in the grow band after preprocessing;
        # its radius (~0.16*32 px) needs more than 2 one-ring steps
        vol = np.asarray(phantom_volume(16, 32, 32, seed=4), np.float32)
        dims = jnp.asarray([32, 32], jnp.int32)
        out = process_volume_zsharded(jnp.asarray(vol), dims, CFG, meshz)
        assert bool(np.asarray(out["grow_converged"]))
        capped_cfg = dataclasses.replace(
            CFG, grow_block_iters=1, grow_max_iters=2
        )
        out2 = process_volume_zsharded(
            jnp.asarray(vol), dims, capped_cfg, meshz
        )
        # the uniform band spans the whole volume: 2 one-ring steps cannot
        # finish, and every shard must agree (the flag is a psum'd popcount
        # comparison, replicated across the mesh)
        assert not bool(np.asarray(out2["grow_converged"]))

    def test_dp_sharded_flag_per_slice(self, mesh8):
        px, dims = _batch(8)
        capped_cfg = dataclasses.replace(
            CFG, grow_block_iters=1, grow_max_iters=2
        )
        out = process_batch_sharded(
            jnp.asarray(px), jnp.asarray(dims), capped_cfg, mesh8
        )
        conv = np.asarray(out["grow_converged"])
        assert conv.shape == (8,)
        want = np.asarray(
            process_batch(jnp.asarray(px), jnp.asarray(dims), capped_cfg)[
                "grow_converged"
            ]
        )
        np.testing.assert_array_equal(conv, want)
        assert not conv.all()  # the tiny cap truncates the lesion slices


class TestBatchZshard:
    """('data', 'z') 2D-mesh cohort-of-volumes path: B volumes over 'data',
    planes over 'z' — bit-identical to the single-device volume pipeline."""

    @pytest.fixture(scope="class")
    def mesh2d(self):
        return make_mesh(8, axis_names=("data", "z"), axis_sizes=(2, 4))

    def test_matches_single_device(self, mesh2d):
        from nm03_capstone_project_tpu.parallel import (
            process_volume_batch_zsharded,
        )

        vols = np.stack(
            [
                np.asarray(phantom_volume(8, 48, 48, seed=s), np.float32)
                for s in (3, 7)
            ]
        )
        dims = np.full((2, 2), 48, np.int32)
        out = process_volume_batch_zsharded(
            jnp.asarray(vols), jnp.asarray(dims), CFG, mesh2d
        )
        mask = np.asarray(out["mask"])
        conv = np.asarray(out["grow_converged"])
        assert mask.shape == (2, 8, 48, 48) and conv.shape == (2,)
        assert conv.all()
        for i in range(2):
            want = process_volume(
                jnp.asarray(vols[i]), jnp.asarray(dims[i]), CFG
            )
            np.testing.assert_array_equal(mask[i], np.asarray(want["mask"]))
        assert mask.sum() > 0

    def test_per_volume_flag_under_cap(self, mesh2d):
        from nm03_capstone_project_tpu.parallel import (
            process_volume_batch_zsharded,
        )

        # volume 0 has a lesion (caps out under a tiny budget); volume 1 is
        # blank (trivially converged) — the (B,) flag must split them
        vols = np.stack(
            [
                np.asarray(phantom_volume(8, 48, 48, seed=3), np.float32),
                np.zeros((8, 48, 48), np.float32),
            ]
        )
        dims = np.full((2, 2), 48, np.int32)
        capped = dataclasses.replace(CFG, grow_block_iters=1, grow_max_iters=2)
        out = process_volume_batch_zsharded(
            jnp.asarray(vols), jnp.asarray(dims), capped, mesh2d
        )
        conv = np.asarray(out["grow_converged"])
        assert not conv[0] and conv[1]

    def test_bad_divisibility_rejected(self, mesh2d):
        from nm03_capstone_project_tpu.parallel import (
            process_volume_batch_zsharded,
        )

        with pytest.raises(ValueError, match="not divisible"):
            process_volume_batch_zsharded(
                jnp.zeros((3, 8, 32, 32)), jnp.full((3, 2), 32), CFG, mesh2d
            )
        with pytest.raises(ValueError, match="not divisible"):
            process_volume_batch_zsharded(
                jnp.zeros((2, 6, 32, 32)), jnp.full((2, 2), 32), CFG, mesh2d
            )
