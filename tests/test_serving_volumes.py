"""Whole-volume multi-chip serving tests (ISSUE 15).

Covers the gang lane end to end: depth-bucket math, the HTTP-free
``segment_volume`` path asserted BIT-IDENTICAL to the directly-dispatched
z-shard program, the ``POST /v1/segment-volume`` loopback round trip
(raw stacked + concatenated-DICOM-parts bodies, summary/mask/mhd
outputs, guard rejections), gang/slice interleaving with zero failed
slice requests, the lane-death-mid-volume fault drill (re-mesh onto
survivors vs the honest shed), the ``--distributed-init`` satellite pin,
the loadgen ``--volume`` mode, and the subprocess acceptance drill whose
served mask must equal a directly-driven ``nm03-volume --z-shard`` run
on the same study — gated post-drain by ``check_telemetry`` on the new
``serving_volume_*`` series.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import phantom_slice, phantom_volume

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")

CANVAS = 64
DEPTH = 6
BUCKET = 8


def run_checker(*argv):
    return subprocess.run(
        [sys.executable, CHECKER, *map(str, argv)],
        capture_output=True, text=True, timeout=60,
    )


def _post(url: str, body: bytes, headers: dict, timeout=120.0):
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _volume_headers(d: int, h: int, w: int) -> dict:
    return {
        "Content-Type": "application/octet-stream",
        "X-Nm03-Depth": str(d),
        "X-Nm03-Height": str(h),
        "X-Nm03-Width": str(w),
    }


def _study(depth=DEPTH, hw=CANVAS, seed=0) -> np.ndarray:
    return np.asarray(
        phantom_volume(n_slices=depth, height=hw, width=hw, seed=seed),
        np.float32,
    )


def _cfg() -> PipelineConfig:
    return PipelineConfig(canvas=CANVAS, min_dim=16)


def _direct_mask(volume: np.ndarray, devices, cfg=None) -> np.ndarray:
    """The reference: the driver's own z-shard dispatch on an identical
    mesh (divisibility-padded exactly like cli/volume.py), cropped back."""
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.parallel.mesh import make_mesh
    from nm03_capstone_project_tpu.parallel.zshard import process_volume_zsharded

    cfg = cfg if cfg is not None else _cfg()
    n = len(devices)
    mesh = make_mesh(n, axis_names=("z",), devices=list(devices))
    depth, h, w = volume.shape
    # pad to the serving depth bucket (zero filler segments empty): the
    # gang pads the same way, so shapes — and masks — line up exactly
    padded = -(-BUCKET // n) * n
    stack = np.zeros((padded, cfg.canvas, cfg.canvas), np.float32)
    stack[:depth, :h, :w] = volume
    out = process_volume_zsharded(
        jnp.asarray(stack), jnp.asarray([h, w], np.int32), cfg, mesh
    )
    return np.asarray(out["mask"])[:depth, :h, :w]


# -- depth-bucket math (no backend) -----------------------------------------


class TestGangMath:
    def _gang(self, buckets):
        from nm03_capstone_project_tpu.serving.volumes import VolumeGang

        return VolumeGang(_cfg(), executor=None, batcher=None,
                          depth_buckets=buckets)

    def test_padded_depth_rounds_to_bucket_and_shards(self):
        g = self._gang((8, 16))
        assert g.padded_depth(6, 4) == 8    # bucket 8, 4 | 8
        assert g.padded_depth(6, 3) == 9    # bucket 8 -> next multiple of 3
        assert g.padded_depth(8, 1) == 8
        assert g.padded_depth(9, 4) == 16   # next bucket
        assert g.max_depth == 16
        assert g.default_cost == 8

    def test_too_deep_raises(self):
        g = self._gang((8,))
        with pytest.raises(ValueError, match="largest volume depth bucket"):
            g.padded_depth(9, 1)

    def test_bad_buckets_rejected(self):
        from nm03_capstone_project_tpu.serving.volumes import VolumeGang

        with pytest.raises(ValueError, match="strictly increasing"):
            VolumeGang(_cfg(), None, None, depth_buckets=(8, 8))
        with pytest.raises(ValueError, match=">= 1"):
            VolumeGang(_cfg(), None, None, depth_buckets=(0, 4))

    def test_usable_shards_respects_halo(self):
        import dataclasses

        from nm03_capstone_project_tpu.serving.volumes import VolumeGang

        # morph_size 5 -> z-radius 2: a (8,)-bucket study on 8 shards has
        # d_local 1 < 2, so the gang must shrink the mesh until the halo
        # contract holds (the same guard process_volume_zsharded enforces)
        cfg5 = dataclasses.replace(_cfg(), morph_size=5)
        g = VolumeGang(cfg5, None, None, depth_buckets=(8, 32))
        n = g._usable_shards(8, 8)
        assert g.padded_depth(8, n) // n >= 2
        # the width is BUCKET-dependent under the halo constraint: a
        # 32-plane study sustains the full 8-way mesh where the 8-plane
        # bucket cannot — warmup must warm each bucket at ITS width
        # (the review-hardening regression: warmup used to pin every
        # bucket at the smallest bucket's width, so a deep request
        # compiled online while holding the gang)
        assert n < 8
        assert g._usable_shards(8, 32) == 8


# -- the served app (module-scoped: one warmup) -----------------------------


@pytest.fixture(scope="module")
def vapp(tmp_path_factory):
    """A 4-lane volume-serving app with a seq-indexed volume fault plan.

    The plan drives the two fault drills deterministically by request
    ordinal: volume seq 4 loses lane 1 mid-volume (re-mesh onto the
    survivors), seq 5 fails unattributably (the honest shed). Earlier
    seqs never match, so the happy-path tests run fault-free. Tests that
    consume seqs run in file order (pytest default) — the drill tests
    submit sentinel requests to reach their ordinals regardless.
    """
    from nm03_capstone_project_tpu.obs import flightrec
    from nm03_capstone_project_tpu.resilience import FaultPlan
    from nm03_capstone_project_tpu.serving.server import ServingApp

    # the lane-death drill's quarantine fires a flight-recorder auto-dump;
    # point it at a tmp dir so test runs never litter the repo root
    flightrec.configure(dump_dir=str(tmp_path_factory.mktemp("flight")))
    plan = FaultPlan.from_spec({
        "faults": [
            {"site": "volume", "kind": "dispatch_error", "index": 4,
             "lane": 1, "count": 1},
            {"site": "volume", "kind": "dispatch_error", "index": 5,
             "count": 1},
        ]
    })
    app = ServingApp(
        cfg=_cfg(),
        buckets=(1, 2),
        lanes=4,
        max_wait_s=0.005,
        volume_serving=True,
        volume_depth_buckets=(BUCKET,),
        fault_plan=plan,
    )
    app.start()
    yield app
    app.begin_drain(reason="test")
    app.close()


@pytest.fixture(scope="module")
def vserved(vapp):
    """The module app behind a live loopback HTTP server."""
    from nm03_capstone_project_tpu.serving.server import make_http_server

    httpd = make_http_server(vapp)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield vapp, base
    httpd.shutdown()
    httpd.server_close()


class TestSegmentVolume:
    def test_bit_identity_with_direct_zshard(self, vapp):
        """THE defining test: the served mask volume equals nm03-volume's
        z-shard dispatch on the same study, byte for byte."""
        vol = _study(seed=3)
        payload = vapp.segment_volume(vol)  # volume seq 0
        assert payload["shape"] == [DEPTH, CANVAS, CANVAS]
        assert payload["z_shards"] == 4
        assert payload["grow_converged"] is True
        assert payload["requeues"] == 0
        served = np.frombuffer(
            base64.b64decode(payload["mask_b64"]), np.uint8
        ).reshape(DEPTH, CANVAS, CANVAS)
        devices = [d for _, d in vapp.executor.healthy_lane_devices()]
        direct = _direct_mask(vol, devices)
        assert served.sum() > 0, "phantom study segmented nothing"
        assert np.array_equal(served, direct)
        reg = vapp.registry
        assert reg.get("serving_volume_requests_total", status="ok").value >= 1
        assert reg.get("serving_volume_zshards").value == 4
        assert reg.get("serving_volume_gang_wait_seconds") is not None

    def test_mhd_payload_matches_driver_contract(self, vapp):
        """?output=mhd carries the same MetaImage pair --export-mhd writes."""
        from nm03_capstone_project_tpu.data.imageio import read_metaimage

        vol = _study(seed=4)
        payload = vapp.segment_volume(vol, mhd=True)  # volume seq 1
        assert payload["mhd_data_file"] == "mask.raw"
        served = np.frombuffer(
            base64.b64decode(payload["mask_b64"]), np.uint8
        ).reshape(DEPTH, CANVAS, CANVAS)
        import tempfile
        from pathlib import Path

        with tempfile.TemporaryDirectory() as td:
            (Path(td) / "mask.mhd").write_bytes(
                base64.b64decode(payload["mhd_header_b64"])
            )
            (Path(td) / "mask.raw").write_bytes(
                base64.b64decode(payload["mhd_data_b64"])
            )
            arr, _spacing = read_metaimage(Path(td) / "mask.mhd")
        assert np.array_equal(arr, served)

    def test_guards(self, vapp):
        from nm03_capstone_project_tpu.serving.server import RequestRejected

        with pytest.raises(RequestRejected) as e:
            vapp.guard_volume(np.zeros((BUCKET + 1, CANVAS, CANVAS), np.float32))
        assert e.value.http_status == 413
        with pytest.raises(RequestRejected) as e:
            vapp.guard_volume(np.zeros((2, 8, 8), np.float32))  # < min_dim
        assert e.value.http_status == 400

    def test_volume_serving_disabled_is_404(self):
        from nm03_capstone_project_tpu.serving.server import (
            RequestRejected,
            ServingApp,
        )

        app = ServingApp(cfg=_cfg())  # never started: guards are host-only
        try:
            with pytest.raises(RequestRejected) as e:
                app.guard_volume(np.zeros((2, CANVAS, CANVAS), np.float32))
            assert e.value.http_status == 404
        finally:
            app.close()


class TestVolumeHTTP:
    def test_raw_roundtrip_and_headers(self, vserved):
        vapp, base = vserved
        vol = _study(seed=5)
        status, payload, headers = _post(
            base + "/v1/segment-volume",
            vol.astype("<f4").tobytes(),
            _volume_headers(DEPTH, CANVAS, CANVAS),
        )  # volume seq 2
        assert status == 200
        assert headers["X-Nm03-Z-Shards"] == "4"
        assert "X-Nm03-Gang-Wait-Ms" in headers
        served = np.frombuffer(
            base64.b64decode(payload["mask_b64"]), np.uint8
        ).reshape(DEPTH, CANVAS, CANVAS)
        devices = [d for _, d in vapp.executor.healthy_lane_devices()]
        assert np.array_equal(served, _direct_mask(vol, devices))

    def test_summary_output_omits_mask(self, vserved):
        _vapp, base = vserved
        vol = _study(depth=2, seed=6)
        status, payload, _ = _post(
            base + "/v1/segment-volume?output=summary",
            vol.astype("<f4").tobytes(),
            _volume_headers(2, CANVAS, CANVAS),
        )  # volume seq 3
        assert status == 200
        assert "mask_b64" not in payload
        assert payload["mask_voxels"] >= 0
        assert payload["z_shards"] == 4

    def test_rejections(self, vserved):
        _vapp, base = vserved
        # truncated raw body
        status, payload, _ = _post(
            base + "/v1/segment-volume",
            b"\x00" * 16,
            _volume_headers(DEPTH, CANVAS, CANVAS),
        )
        assert status == 400 and "bytes" in payload["error"]
        # too deep for the bucket ladder (does not reach the gang)
        deep = np.zeros((BUCKET + 1, CANVAS, CANVAS), np.float32)
        status, payload, _ = _post(
            base + "/v1/segment-volume",
            deep.astype("<f4").tobytes(),
            _volume_headers(BUCKET + 1, CANVAS, CANVAS),
        )
        assert status == 413
        # empty body
        status, payload, _ = _post(
            base + "/v1/segment-volume", b"",
            {"Content-Type": "application/octet-stream"},
        )
        assert status in (400, 411)

    def test_dicom_parts_decode(self, vapp, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import write_dicom

        vol = _study(depth=2, seed=7)
        parts = []
        for i, plane in enumerate(vol):
            p = tmp_path / f"p{i}.dcm"
            write_dicom(p, np.clip(plane, 0, 65535).astype(np.uint16))
            raw = p.read_bytes()
            parts.append(len(raw).to_bytes(4, "little") + raw)
        stacked = vapp.decode_volume_dicom(
            b"".join(parts), "application/x-nm03-dicom-parts"
        )
        assert stacked.shape == (2, CANVAS, CANVAS)
        assert stacked.dtype == np.float32
        # truncated framing is a 400, never a partial volume
        from nm03_capstone_project_tpu.serving.server import RequestRejected

        with pytest.raises(RequestRejected) as e:
            vapp.decode_volume_dicom(
                b"".join(parts)[:-10], "application/x-nm03-dicom-parts"
            )
        assert e.value.http_status == 400

    def test_single_dicom_file_body(self, vapp, tmp_path):
        from nm03_capstone_project_tpu.data.dicomlite import write_dicom

        plane = np.clip(_study(depth=1, seed=8)[0], 0, 65535).astype(np.uint16)
        p = tmp_path / "one.dcm"
        write_dicom(p, plane)
        stacked = vapp.decode_volume_dicom(p.read_bytes(), "application/dicom")
        assert stacked.shape == (1, CANVAS, CANVAS)

    def test_zero_frame_dicom_is_400(self, vapp, monkeypatch):
        """A parseable-but-empty study is a 400, never an IndexError."""
        from nm03_capstone_project_tpu.data import dicomlite
        from nm03_capstone_project_tpu.serving.server import RequestRejected

        monkeypatch.setattr(
            dicomlite, "read_dicom_frames", lambda path, strict=True: []
        )
        with pytest.raises(RequestRejected) as e:
            vapp.decode_volume_dicom(b"\x00" * 200, "application/dicom")
        assert e.value.http_status == 400
        assert "no image planes" in str(e.value)


class TestVolumeFaultDrills:
    """The vapp plan's seq-indexed rules: lane death at volume seq 4,
    an unattributable failure at seq 5 (see the fixture docstring)."""

    def _seq(self, vapp):
        # the gang's next request ordinal (peek, do not consume)
        import itertools

        seq, vapp.volumes._seq = itertools.tee(vapp.volumes._seq)
        return next(seq)

    def _advance_to(self, vapp, target_seq):
        """Burn volume seqs with tiny studies until the next is target."""
        while self._seq(vapp) < target_seq:
            vapp.segment_volume(_study(depth=2, seed=99), include_mask=False)

    def test_lane_death_mid_volume_completes_on_survivors(self, vapp):
        self._advance_to(vapp, 4)
        vol = _study(seed=10)
        payload = vapp.segment_volume(vol)  # seq 4: lane 1 dies mid-volume
        assert payload["requeues"] == 1
        assert payload["z_shards"] == 3  # the surviving mesh
        served = np.frombuffer(
            base64.b64decode(payload["mask_b64"]), np.uint8
        ).reshape(DEPTH, CANVAS, CANVAS)
        # never a wrong mask: the survivors' result equals the full-mesh
        # dispatch (the z-shard decomposition is shard-count-invariant)
        devices = [d for _, d in vapp.executor.healthy_lane_devices()][:4]
        assert np.array_equal(served, _direct_mask(vol, devices))
        reg = vapp.registry
        # the lane death was booked through the REAL quarantine machine
        # (the probation probe may legitimately have reinstated the —
        # actually healthy — lane already, so assert the monotone counter)
        assert (
            reg.get("serving_lane_quarantines_total",
                    lane="1", cause="device_lost").value >= 1
        )
        assert reg.get("serving_volume_zshards").value == 3
        assert (
            reg.get(
                "resilience_faults_injected_total",
                site="volume", kind="dispatch_error",
            ).value >= 1
        )

    def test_unattributable_failure_sheds_honestly(self, vapp):
        from nm03_capstone_project_tpu.serving.volumes import GangUnavailable

        self._advance_to(vapp, 5)
        with pytest.raises(GangUnavailable):
            vapp.segment_volume(_study(seed=11))  # seq 5: no lane to blame
        reg = vapp.registry
        assert reg.get("serving_volume_requests_total", status="shed").value >= 1
        # the shed is a 503 + Retry-After on the wire (handler mapping
        # covered by TestVolumeHTTP + the subprocess drill)

    def test_recovers_after_the_drill(self, vapp):
        payload = vapp.segment_volume(_study(seed=12), include_mask=False)
        assert payload["z_shards"] >= 3
        assert payload["requeues"] == 0


class TestGangSliceInterleaving:
    def test_mixed_traffic_zero_failed_slices(self, vserved):
        """Slice + volume traffic concurrently: every slice request
        succeeds, slice p99 stays bounded, and the gang-wait gauge is
        observed — the admission-separation contract. Runs AFTER the
        fault drills, so its volume seq is past the plan's rules."""
        from nm03_capstone_project_tpu.serving.loadgen import (
            LoadResult,
            _make_payloads,
            run_load,
        )

        vapp, base = vserved
        vol_result = {}

        def volume_worker():
            vol = _study(seed=9)
            status, payload, _ = _post(
                base + "/v1/segment-volume?output=summary",
                vol.astype("<f4").tobytes(),
                _volume_headers(DEPTH, CANVAS, CANVAS),
            )
            vol_result["status"] = status
            vol_result["payload"] = payload

        vt = threading.Thread(target=volume_worker)
        vt.start()
        payloads = _make_payloads(CANVAS, CANVAS, n_distinct=2, dicom=False)
        summary = run_load(
            base + "/v1/segment?output=mask", payloads,
            n_requests=16, concurrency=8, rate_rps=0.0, timeout_s=120.0,
            result=LoadResult(),
        )
        vt.join(timeout=120)
        assert vol_result["status"] == 200, vol_result
        assert summary["requests_ok"] == 16, summary["statuses"]
        # bounded inflation: nothing timed out against the generous
        # per-request budget, and p99 stayed far under the volume timeout
        assert summary["latency_ms"]["p99"] < 60_000
        gw = vapp.registry.get("serving_volume_gang_wait_seconds")
        assert gw is not None and gw.value >= 0.0


class TestDistributedInitSatellite:
    def test_cli_flag_wires_gang_distributed(self):
        """--distributed-init: collectives ensured, single-process start
        is a no-op, and the gang is marked to use the global device set."""
        from nm03_capstone_project_tpu.compilehub import (
            ensure_cpu_multiprocess_collectives,
        )
        from nm03_capstone_project_tpu.serving import server as srv

        assert ensure_cpu_multiprocess_collectives() in (True, False)
        args = srv.build_parser().parse_args([
            "--device", "cpu", "--volume-serving", "--distributed-init",
            "--canvas", str(CANVAS), "--min-dim", "16",
        ])
        app = srv.app_from_args(args)
        try:
            assert app.volumes is not None
            assert app.volumes.distributed is True
            assert app.status()["volumes"]["distributed"] is True
        finally:
            app.close()

    def test_distributed_pool_spans_global_devices(self, vapp, monkeypatch):
        """With distributed_is_initialized() true, the gang's mesh pool is
        jax.devices() — the replica's mesh can span processes."""
        import jax

        import nm03_capstone_project_tpu.compilehub as compilehub

        monkeypatch.setattr(vapp.volumes, "distributed", True)
        monkeypatch.setattr(
            compilehub, "distributed_is_initialized", lambda: True
        )
        pool = vapp.volumes._device_pool()
        assert [d for _, d in pool] == list(jax.devices())
        assert all(ln is None for ln, _ in pool)
        monkeypatch.setattr(
            compilehub, "distributed_is_initialized", lambda: False
        )
        # not initialized: straight back to the healthy-lane pool
        pool = vapp.volumes._device_pool()
        assert all(ln is not None for ln, _ in pool)


class TestLoadgenVolumeMode:
    def test_volume_payload_builder(self):
        from nm03_capstone_project_tpu.serving.loadgen import (
            _make_volume_payloads,
        )

        payloads = _make_volume_payloads(4, 32, 32, n_distinct=2, dicom=False)
        body, headers = payloads[0]
        assert len(body) == 4 * 32 * 32 * 4
        assert headers["X-Nm03-Depth"] == "4"
        parts = _make_volume_payloads(2, 32, 32, n_distinct=1, dicom=True)
        body, headers = parts[0]
        assert headers["Content-Type"] == "application/x-nm03-dicom-parts"
        n = int.from_bytes(body[:4], "little")
        assert body[132:136] == b"DICM" or n > 0  # framed Part-10 inside

    def test_cli_flags_parse(self):
        from nm03_capstone_project_tpu.serving.loadgen import build_parser

        args = build_parser().parse_args(["--volume", "--volume-depth", "4"])
        assert args.volume and args.volume_depth == 4

    def test_volume_mode_against_live_server(self, vserved):
        from nm03_capstone_project_tpu.serving.loadgen import (
            LoadResult,
            _make_volume_payloads,
            run_load,
        )

        _vapp, base = vserved
        payloads = _make_volume_payloads(
            2, CANVAS, CANVAS, n_distinct=2, dicom=False
        )
        summary = run_load(
            base + "/v1/segment-volume?output=summary", payloads,
            n_requests=3, concurrency=1, rate_rps=0.0, timeout_s=120.0,
            result=LoadResult(),
        )
        assert summary["requests_ok"] == 3
        vb = summary["volume"]
        assert set(vb["zshards_observed"]) <= {"3", "4"}
        assert sum(vb["zshards_observed"].values()) == 3
        assert vb["gang_wait_ms"]["max"] >= 0.0


# -- the subprocess acceptance drill ----------------------------------------


class TestAcceptanceDrill:
    @pytest.mark.slow
    def test_served_volume_bit_identical_to_driver(self, tmp_path):
        """ISSUE 15 acceptance: nm03-serve on 4 forced virtual devices
        serves a whole synthetic study; the mask equals ``nm03-volume
        --z-shard --export-mhd`` on the SAME study; a concurrent
        slice+volume run completes with zero failures; the seq-indexed
        mid-volume lane-death drill completes on the surviving mesh; and
        post-drain check_telemetry gates the serving_volume_* series."""
        from nm03_capstone_project_tpu.data.discovery import (
            find_patient_dirs,
            load_dicom_files_for_patient,
        )
        from nm03_capstone_project_tpu.data.imageio import read_metaimage
        from nm03_capstone_project_tpu.data.synthetic import (
            write_synthetic_cohort,
        )

        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
        )
        env.pop("NM03_FAULT_PLAN", None)
        cohort = tmp_path / "cohort"
        pids = write_synthetic_cohort(
            cohort, n_patients=1, n_slices=DEPTH, height=CANVAS, width=CANVAS,
        )
        out_dir = tmp_path / "driver-out"
        # the reference: the batch driver's own z-sharded run + MHD export
        res = subprocess.run(
            [
                sys.executable, "-m", "nm03_capstone_project_tpu.cli.volume",
                "--base-path", str(cohort), "--output", str(out_dir),
                "--device", "cpu", "--z-shard", "--export-mhd",
                "--canvas", str(CANVAS), "--min-dim", "16",
            ],
            capture_output=True, text=True, timeout=400, env=env, cwd=REPO,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        driver_mask, _sp = read_metaimage(out_dir / pids[0] / "mask.mhd")
        assert driver_mask.shape == (DEPTH, CANVAS, CANVAS)

        # the same study, byte-sourced from the SAME files the driver read
        base_dir = find_patient_dirs(cohort)
        files = load_dicom_files_for_patient(cohort, pids[0])
        assert base_dir and files
        parts = []
        for f in files:
            raw = f.read_bytes()
            parts.append(len(raw).to_bytes(4, "little") + raw)
        study_body = b"".join(parts)

        port_file = tmp_path / "port"
        metrics = tmp_path / "metrics.json"
        events = tmp_path / "events.jsonl"
        # seq-indexed fault: volume seq 3 (after identity seq 0 and the
        # two mixed-run volumes at seqs 1-2) loses lane 1 mid-volume
        env["NM03_FAULT_PLAN"] = json.dumps({
            "faults": [{
                "site": "volume", "kind": "dispatch_error",
                "index": 3, "lane": 1, "count": 1,
            }]
        })
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--min-dim", "16",
                "--buckets", "1,2", "--lanes", "4", "--max-wait-ms", "5",
                "--volume-serving",
                "--volume-depth-buckets", str(BUCKET),
                "--heartbeat-s", "0",
                # the lane-death drill auto-dumps the flight rings; keep
                # them in tmp, never the cwd (= the repo root here)
                "--flight-dir", str(tmp_path),
                "--metrics-out", str(metrics), "--log-json", str(events),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            deadline = time.monotonic() + 300
            while not port_file.exists() and time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail(f"server died: {proc.stdout.read()}")
                time.sleep(0.2)
            assert port_file.exists(), "server never became ready"
            base = f"http://127.0.0.1:{int(port_file.read_text())}"

            # (1) bit-identity: served mask == the driver's MHD volume
            status, payload, headers = _post(
                base + "/v1/segment-volume",
                study_body,
                {"Content-Type": "application/x-nm03-dicom-parts"},
            )
            assert status == 200, payload
            assert payload["z_shards"] == 4
            served = np.frombuffer(
                base64.b64decode(payload["mask_b64"]), np.uint8
            ).reshape(DEPTH, CANVAS, CANVAS)
            assert served.sum() > 0
            assert np.array_equal(served, driver_mask), (
                "served mask differs from nm03-volume --z-shard"
            )

            # (2) concurrent slice + volume traffic: zero failures
            errors: list = []

            def slice_worker(i):
                body = phantom_slice(CANVAS, CANVAS, seed=i).astype(
                    "<f4"
                ).tobytes()
                s, p, _ = _post(
                    base + "/v1/segment?output=mask", body,
                    {"Content-Type": "application/octet-stream",
                     "X-Nm03-Height": str(CANVAS),
                     "X-Nm03-Width": str(CANVAS)},
                )
                if s != 200:
                    errors.append((i, s, p))

            def vol_worker(seed):
                vol = _study(seed=seed)
                s, p, _ = _post(
                    base + "/v1/segment-volume?output=summary",
                    vol.astype("<f4").tobytes(),
                    _volume_headers(DEPTH, CANVAS, CANVAS),
                )
                if s != 200:
                    errors.append(("vol", s, p))

            threads = [
                threading.Thread(target=slice_worker, args=(i,))
                for i in range(12)
            ] + [
                threading.Thread(target=vol_worker, args=(s,))
                for s in (20, 21)  # volume seqs 1-2
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert not errors, errors

            # (3) the mid-volume lane-death drill (volume seq 3): the gang
            # re-meshes onto the 3 survivors and the mask is STILL the
            # driver's — never wrong, even through a lane death
            status, payload, _ = _post(
                base + "/v1/segment-volume", study_body,
                {"Content-Type": "application/x-nm03-dicom-parts"},
            )
            assert status == 200, payload
            assert payload["requeues"] == 1
            assert payload["z_shards"] == 3
            served = np.frombuffer(
                base64.b64decode(payload["mask_b64"]), np.uint8
            ).reshape(DEPTH, CANVAS, CANVAS)
            assert np.array_equal(served, driver_mask)

            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        # (4) post-drain telemetry gates on the new series
        res = run_checker(
            "--events", events, "--metrics", metrics,
            "--expect-counter", "serving_volume_requests_total{status=ok}=4",
            "--expect-gauge", "serving_volume_zshards=3",
            "--expect-gauge-range",
            "serving_volume_gang_wait_seconds=[0..60)",
            "--expect-counter", "resilience_faults_injected_total=1",
            "--expect-counter", "serving_requests_total{status=ok}=12",
        )
        assert res.returncode == 0, res.stdout + res.stderr
