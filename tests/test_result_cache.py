"""Content-addressed result tier tests (ISSUE 19).

Covers the contract at every layer: key derivation (the versioned-key
discipline extended to results), the LRU-by-bytes store with
verify-on-read, the in-flight dedup index and its idempotency-key alias
map, the replica tier end to end over loopback HTTP (fill/hit/304 and
bit-identity across evict/recompute cycles), the batcher's in-flight
dedup window, the router tier against fake replicas (including the
mixed-program-version bypass), the FaultPlan ``cache``/``corrupt_entry``
drill, and the slow subprocess acceptance drills: a zipfian fleet replay
whose p50 collapses on repeats, and a SIGKILL-mid-fleet idempotent
volume retry that returns the identical mask without a second gang
dispatch.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from nm03_capstone_project_tpu.cache import (
    InflightIndex,
    ResultStore,
    content_etag,
    digest_bytes,
    etag_matches,
    parse_bytes,
    result_key,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 128


# -- keys -------------------------------------------------------------------


class TestResultKeys:
    def test_key_is_deterministic_and_total(self):
        k1 = result_key(b"body", "segment", {"render": True}, "v1")
        k2 = result_key(b"body", "segment", {"render": True}, "v1")
        assert k1 == k2 and k1.digest() == k2.digest()
        assert len(k1.digest()) == 32
        assert len(k1.input_digest) == 64  # full sha256 hex of the body
        assert k1.input_digest == digest_bytes(b"body")

    def test_every_component_changes_the_address(self):
        base = result_key(b"body", "segment", {"a": 1}, "v1").digest()
        assert result_key(b"BODY", "segment", {"a": 1}, "v1").digest() != base
        assert (
            result_key(b"body", "segment-volume", {"a": 1}, "v1").digest()
            != base
        )
        assert result_key(b"body", "segment", {"a": 2}, "v1").digest() != base
        # the invalidation story: a new program version IS a new keyspace
        assert result_key(b"body", "segment", {"a": 1}, "v2").digest() != base

    def test_no_params_is_one_identity(self):
        assert (
            result_key(b"b", "segment", None, "v").digest()
            == result_key(b"b", "segment", {}, "v").digest()
        )


class TestEtagHelpers:
    def test_content_etag_is_quoted_and_content_only(self):
        e = content_etag(b"payload")
        assert e.startswith('"') and e.endswith('"') and len(e) == 34
        assert e == content_etag(b"payload")  # two identical results agree
        assert e != content_etag(b"payloae")

    def test_etag_matches_rfc7232(self):
        e = content_etag(b"x")
        assert not etag_matches(None, e)
        assert not etag_matches("", e)
        assert etag_matches("*", e)
        assert etag_matches(e, e)
        assert etag_matches(f'"nope", {e}', e)
        assert etag_matches(f"W/{e}", e)  # weak comparison revalidates
        assert not etag_matches('"nope"', e)

    def test_parse_bytes(self):
        assert parse_bytes("1048576") == 1 << 20
        assert parse_bytes("64m") == 64 << 20
        assert parse_bytes("2G") == 2 << 30
        assert parse_bytes("1.5k") == 1536
        with pytest.raises(ValueError):
            parse_bytes("")
        with pytest.raises(ValueError):
            parse_bytes("lots")


# -- the store --------------------------------------------------------------


class TestResultStore:
    def test_fill_lookup_roundtrip(self):
        store = ResultStore(1 << 20)
        entry, created = store.fill("d1", b"payload", "segment")
        assert created and entry.etag == content_etag(b"payload")
        got = store.lookup("d1")
        assert got is entry and got.hits == 1
        assert store.lookup("missing") is None
        st = store.stats()
        assert st["hits"] == 1 and st["misses"] == 1 and st["fills"] == 1
        assert st["hit_ratio"] == 0.5 and st["bytes"] == len(b"payload")

    def test_fill_is_idempotent_on_digest(self):
        store = ResultStore(1 << 20)
        e1, c1 = store.fill("d", b"same-bytes", "segment")
        e2, c2 = store.fill("d", b"same-bytes", "segment")
        assert c1 and not c2 and e2 is e1
        assert store.stats()["fills"] == 1 and len(store) == 1

    def test_lru_evicts_cold_end_by_bytes(self):
        evicted = []
        store = ResultStore(100, on_evict=evicted.append)
        store.fill("a", b"x" * 40, "segment")
        store.fill("b", b"y" * 40, "segment")
        store.lookup("a")  # touch: a is now hot, b cold
        store.fill("c", b"z" * 40, "segment")  # must evict b, not a
        assert store.lookup("a") is not None
        assert store.lookup("b") is None
        assert evicted == [1] and store.bytes <= 100

    def test_oversize_payload_rejected_not_stored(self):
        store = ResultStore(10)
        entry, created = store.fill("big", b"x" * 11, "segment")
        assert entry is None and not created
        st = store.stats()
        assert st["oversize_rejects"] == 1 and st["entries"] == 0
        assert st["evictions"] == 0  # nothing was sacrificed for it

    def test_explicit_evict_one_and_all(self):
        store = ResultStore(1 << 20)
        store.fill("a", b"1", "segment")
        store.fill("b", b"2", "segment")
        assert store.evict("a") == 1 and store.evict("a") == 0
        assert store.evict() == 1 and store.bytes == 0

    def test_verify_on_read_evicts_corrupt_entry(self):
        """The stale-result-is-never-an-outcome half the drill gates: a
        payload that no longer hashes to its fill-time ETag is evicted
        and reported as a miss — one recompute, never a wrong answer."""
        fire = {"on": False}
        evicted = []
        store = ResultStore(
            1 << 20,
            corrupt_hook=lambda d: fire["on"],
            on_evict=evicted.append,
        )
        store.fill("d", b"good-bytes", "segment")
        assert store.lookup("d") is not None  # clean read first
        fire["on"] = True
        assert store.lookup("d") is None  # flipped byte -> evict + miss
        fire["on"] = False
        assert store.lookup("d") is None  # really gone, not hidden
        st = store.stats()
        assert st["corrupt_evictions"] == 1 and evicted == [1]

    def test_ls_is_hot_first(self):
        store = ResultStore(1 << 20)
        store.fill("a", b"1", "segment")
        store.fill("b", b"2", "segment-volume")
        store.lookup("a")
        rows = store.ls()
        assert [r["digest"] for r in rows] == ["a", "b"]
        assert rows[0]["hits"] == 1 and rows[1]["algo"] == "segment-volume"


class TestInflightIndex:
    def test_first_register_wins(self):
        idx = InflightIndex()
        leader = object()
        rider = object()
        assert idx.register("d", leader) is leader
        assert idx.register("d", rider) is leader  # join, don't dispatch
        assert idx.claim("d") is leader
        idx.release("d")
        assert idx.claim("d") is None
        assert idx.stats()["coalesced"] == 2

    def test_alias_outlives_release(self):
        """The idempotency contract: a retry AFTER the gang finished and
        released still resolves its key to the content digest."""
        idx = InflightIndex()
        idx.register("digest-1", object(), alias="idem:K")
        idx.release("digest-1")
        assert idx.resolve("idem:K") == "digest-1"

    def test_alias_map_is_bounded_fifo(self):
        idx = InflightIndex(max_aliases=2)
        for i in range(3):
            idx.register(f"d{i}", object(), alias=f"idem:{i}")
        assert idx.resolve("idem:0") is None  # oldest dropped
        assert idx.resolve("idem:2") == "d2"
        assert idx.stats()["aliases"] == 2


# -- the FaultPlan cache site -----------------------------------------------


class TestCacheFaultSite:
    def test_corrupt_entry_is_a_registered_kind(self):
        from nm03_capstone_project_tpu.resilience.faultinject import (
            KINDS_BY_SITE,
        )

        assert "corrupt_entry" in KINDS_BY_SITE["cache"]

    def test_corrupt_entry_drill_through_the_store(self):
        """The drill end to end at the store layer: a FaultPlan-driven
        hook flips a byte, verify-on-read evicts, the next lookup is an
        honest miss (and the ISSUE 9 io_error rules stay untouched —
        kinds filtering keeps the budgets separate)."""
        from nm03_capstone_project_tpu.resilience.faultinject import FaultPlan
        from nm03_capstone_project_tpu.serving.server import (
            _result_corrupt_hook,
        )

        class _Obs:
            def fault_injected(self, **kw):
                pass

        plan = FaultPlan.from_spec({
            "seed": 1,
            "faults": [
                {"site": "cache", "kind": "corrupt_entry", "count": 1},
            ],
        })
        hook = _result_corrupt_hook(plan, _Obs())
        assert hook is not None
        store = ResultStore(1 << 20, corrupt_hook=hook)
        store.fill("d", b"payload", "segment")
        assert store.lookup("d") is None  # the one budgeted fire
        store.fill("d", b"payload", "segment")
        assert store.lookup("d") is not None  # budget spent; clean again

    def test_no_cache_rules_no_hook(self):
        from nm03_capstone_project_tpu.serving.server import (
            _result_corrupt_hook,
        )

        assert _result_corrupt_hook(None, None) is None


# -- replica tier over loopback HTTP ----------------------------------------


def _post(url, body, headers, timeout=60.0):
    req = urllib.request.Request(url, data=body, headers=headers, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read() or b"", dict(e.headers)


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _slice_body(seed=0):
    from nm03_capstone_project_tpu.data.synthetic import phantom_slice

    return phantom_slice(CANVAS, CANVAS, seed=seed).astype("<f4").tobytes()


def _raw_headers(**extra):
    return {
        "Content-Type": "application/octet-stream",
        "X-Nm03-Height": str(CANVAS),
        "X-Nm03-Width": str(CANVAS),
        **extra,
    }


def _counter_sum(registry, name, **labels):
    return sum(
        m.value for m in registry.series()
        if m.name == name
        and all(m.labels.get(k) == v for k, v in labels.items())
    )


@pytest.fixture(scope="module")
def cached_server():
    """One warmed loopback replica with the result tier on."""
    from nm03_capstone_project_tpu.config import PipelineConfig
    from nm03_capstone_project_tpu.serving.server import (
        ServingApp,
        serve_in_thread,
    )

    app = ServingApp(
        cfg=PipelineConfig(canvas=CANVAS),
        queue_capacity=32,
        buckets=(1, 4),
        max_wait_s=0.02,
        request_timeout_s=30.0,
        lanes=1,
        result_cache_bytes=8 << 20,
    )
    httpd, _, port = serve_in_thread(app)
    yield app, f"http://127.0.0.1:{port}"
    app.begin_drain(reason="test_teardown")
    httpd.shutdown()
    httpd.server_close()
    app.close()


class TestReplicaTierE2E:
    def test_fill_then_hit_then_304(self, cached_server):
        app, base = cached_server
        body = _slice_body(seed=10)
        st1, d1, h1 = _post(base + "/v1/segment?output=mask", body, _raw_headers())
        assert st1 == 200 and h1["X-Nm03-Cache"] == "fill"
        etag = h1["ETag"]
        st2, d2, h2 = _post(base + "/v1/segment?output=mask", body, _raw_headers())
        assert st2 == 200 and h2["X-Nm03-Cache"] == "hit"
        assert h2["ETag"] == etag
        p1, p2 = json.loads(d1), json.loads(d2)
        assert p1["mask_sha256"] == p2["mask_sha256"]
        assert p1["cached"] is False and p2["cached"] is True
        # a hit bills zero device time and mints a fresh identity
        assert p2["device_seconds"] == 0.0 and p2["queue_wait_s"] == 0.0
        assert p2["request_id"] != p1["request_id"]
        # conditional revalidation: empty body, the cheapest possible hit
        st3, d3, h3 = _post(
            base + "/v1/segment?output=mask", body,
            _raw_headers(**{"If-None-Match": etag}),
        )
        assert st3 == 304 and d3 == b"" and h3["X-Nm03-Cache"] == "hit"

    def test_bit_identity_across_evict_recompute(self, cached_server):
        """The acceptance contract: cached and recomputed answers are the
        same bytes — the content ETag (sha256 of the stored payload)
        survives an evict/recompute cycle unchanged."""
        app, base = cached_server
        body = _slice_body(seed=11)
        _, _, h1 = _post(base + "/v1/segment?output=mask", body, _raw_headers())
        assert h1["X-Nm03-Cache"] == "fill"
        req = urllib.request.Request(
            base + "/debug/result-cache/evict", data=b"", method="POST"
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read())["evicted"] >= 1
        _, _, h2 = _post(base + "/v1/segment?output=mask", body, _raw_headers())
        assert h2["X-Nm03-Cache"] == "fill"  # store was cold again
        assert h2["ETag"] == h1["ETag"]

    def test_oversize_result_is_honest_miss(self, cached_server):
        app, base = cached_server
        body = _slice_body(seed=12)
        old_max = app.result_store.max_bytes
        app.result_store.max_bytes = 1  # nothing fits
        try:
            st, _, h = _post(
                base + "/v1/segment?output=mask", body, _raw_headers()
            )
            assert st == 200 and h["X-Nm03-Cache"] == "miss"
        finally:
            app.result_store.max_bytes = old_max

    def test_probe_traffic_bypasses_the_tier(self, cached_server):
        """A probation canary must exercise the real dispatch path and
        must not warm the cache for real traffic."""
        import numpy as np

        app, base = cached_server
        pixels = np.frombuffer(_slice_body(seed=13), "<f4").reshape(
            CANVAS, CANVAS
        )
        payload, state, etag = app.segment_cached(
            b"probe-body", pixels, render=False, probe=True
        )
        assert state is None and etag is None
        assert payload["mask_pixels"] >= 0

    def test_debug_surface_and_readyz_block(self, cached_server):
        app, base = cached_server
        body = _slice_body(seed=14)
        _post(base + "/v1/segment?output=mask", body, _raw_headers())
        dbg = _get_json(base + "/debug/result-cache")
        assert dbg["enabled"] and dbg["entries"] >= 1
        assert len(dbg["program_version"]) == 16
        assert {"digest", "algo", "bytes", "etag", "hits"} <= set(
            dbg["ls"][0]
        )
        rz = _get_json(base + "/readyz")
        assert rz["result_cache"]["enabled"]
        assert rz["result_cache"]["program_version"] == dbg["program_version"]
        # the tier-enabled signal nm03-top keys on: the bytes gauge exists
        assert any(
            m.name == "serving_result_cache_bytes"
            for m in app.registry.series()
        )

    def test_disabled_tier_has_no_surface(self):
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.serving.server import ServingApp

        app = ServingApp(
            cfg=PipelineConfig(canvas=CANVAS), buckets=(1,), lanes=1
        )
        try:
            assert app.result_store is None and app.volume_inflight is None
            assert app.result_digest(b"x", "segment", {}) is None
            assert not any(
                m.name == "serving_result_cache_bytes"
                for m in app.registry.series()
            )
            assert app.status()["result_cache"]["enabled"] is False
        finally:
            app.close()


class TestBatcherDedupWindow:
    def test_identical_inflight_slices_ride_one_dispatch(self):
        """Four identical requests admitted in one coalescing window:
        one leader computes, three ride its dispatch (tier=inflight),
        and all four answers are bit-identical."""
        from nm03_capstone_project_tpu.config import PipelineConfig
        from nm03_capstone_project_tpu.serving.server import (
            ServingApp,
            serve_in_thread,
        )

        app = ServingApp(
            cfg=PipelineConfig(canvas=CANVAS),
            queue_capacity=32,
            buckets=(4,),
            max_wait_s=0.4,  # a window wide enough to admit all four
            request_timeout_s=30.0,
            lanes=1,
            result_cache_bytes=8 << 20,
        )
        httpd, _, port = serve_in_thread(app)
        base = f"http://127.0.0.1:{port}"
        try:
            body = _slice_body(seed=20)
            results = []
            lock = threading.Lock()
            barrier = threading.Barrier(4)

            def one():
                barrier.wait()
                st, data, h = _post(
                    base + "/v1/segment?output=mask", body, _raw_headers()
                )
                with lock:
                    results.append((st, json.loads(data), h))

            threads = [threading.Thread(target=one) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert len(results) == 4
            assert all(st == 200 for st, _, _ in results)
            shas = {p["mask_sha256"] for _, p, _ in results}
            assert len(shas) == 1  # bit-identical answers
            inflight_hits = _counter_sum(
                app.registry,
                "serving_result_cache_hit_total",
                tier="inflight",
            )
            assert inflight_hits >= 1  # the window deduped
            # riders bill no device time
            zero_ds = sum(
                1 for _, p, _ in results if p["device_seconds"] == 0.0
            )
            assert zero_ds >= inflight_hits
        finally:
            app.begin_drain(reason="test_teardown")
            httpd.shutdown()
            httpd.server_close()
            app.close()


# -- router tier against fake replicas --------------------------------------


class _FakeCachingReplica:
    """Stdlib nm03-serve stand-in that publishes a result_cache block on
    /readyz and answers POSTs with an ETag, counting calls."""

    def __init__(self, program_version="deadbeefcafe0123"):
        self.program_version = program_version
        self.posts = 0
        self._lock = threading.Lock()
        fake = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _j(self, status, body, headers=()):
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._j(200, {
                    "ready": True, "capacity": 1.0, "queue_depth": 0,
                    "queue_capacity": 64,
                    "replica": {"id": "r", "pid": os.getpid()},
                    "result_cache": {
                        "enabled": True,
                        "program_version": fake.program_version,
                    },
                })

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(n)
                with fake._lock:
                    fake.posts += 1
                self._j(200, {
                    "mask_pixels": 5, "mask_sha256": "m" * 64,
                    "device_seconds": 0.25, "queue_wait_s": 0.001,
                    "trace_id": self.headers.get("X-Nm03-Request-Id", "t"),
                }, [("ETag", content_etag(body)),
                    ("X-Nm03-Cache", "fill")])

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class _RouterObs:
    def __init__(self):
        from nm03_capstone_project_tpu.obs.metrics import MetricsRegistry

        self.registry = MetricsRegistry()
        self.events = type("E", (), {"emit": lambda *a, **k: None})()

    def fault_injected(self, **kw):
        pass

    def close(self, **kw):
        pass


def _router(fakes, **kw):
    from nm03_capstone_project_tpu.fleet.router import FleetApp

    kw.setdefault("health_interval_s", 3600)
    app = FleetApp([f.url for f in fakes], obs=_RouterObs(), **kw)
    app._sweep()
    return app


class TestRouterResultTier:
    def test_hit_never_touches_a_replica(self):
        fake = _FakeCachingReplica()
        app = _router([fake], result_cache_bytes=4 << 20)
        try:
            body = bytes(16 * 16 * 4)
            hdrs = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Height": "16", "X-Nm03-Width": "16",
            }
            st1, d1, h1 = app.proxy_segment(body, dict(hdrs), query="output=mask")
            hm1 = dict(h1)
            assert st1 == 200 and hm1["X-Nm03-Cache"] == "fill"
            assert fake.posts == 1
            st2, d2, h2 = app.proxy_segment(body, dict(hdrs), query="output=mask")
            hm2 = dict(h2)
            assert st2 == 200 and hm2["X-Nm03-Cache"] == "hit"
            assert fake.posts == 1  # never proxied
            # the REPLICA's ETag is preserved across tiers: one stable
            # ETag per content, whichever tier answers
            assert hm2["ETag"] == hm1["ETag"]
            p2 = json.loads(d2)
            assert p2["cached"] is True and p2["device_seconds"] == 0.0
            assert p2["replica_hops"] == 0
            # a hit spends no WRR round: routed counts only the real proxy
            assert _counter_sum(
                app.obs.registry, "fleet_requests_routed_total"
            ) == 1
            # 304 at the router: zero bytes move
            st3, d3, _ = app.proxy_segment(
                body, {**hdrs, "If-None-Match": hm1["ETag"]},
                query="output=mask",
            )
            assert st3 == 304 and d3 == b"" and fake.posts == 1
        finally:
            app.close()
            fake.stop()

    def test_query_spelling_is_part_of_the_key(self):
        """The router hashes raw query params — a different spelling is a
        different key (two misses), never a wrong answer."""
        fake = _FakeCachingReplica()
        app = _router([fake], result_cache_bytes=4 << 20)
        try:
            body = bytes(16 * 16 * 4)
            hdrs = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Height": "16", "X-Nm03-Width": "16",
            }
            app.proxy_segment(body, dict(hdrs), query="output=mask")
            app.proxy_segment(body, dict(hdrs), query="output=png")
            assert fake.posts == 2
        finally:
            app.close()
            fake.stop()

    def test_mixed_program_versions_bypass_the_router_tier(self):
        """Mid-rolling-restart (old and new code both healthy) the router
        must not cache: its keyspace cannot name which version computed
        a result, so the tier disengages until the fleet converges."""
        a = _FakeCachingReplica(program_version="aaaa000011112222")
        b = _FakeCachingReplica(program_version="bbbb000011112222")
        app = _router([a, b], result_cache_bytes=4 << 20)
        try:
            assert app._fleet_result_version() is None
            body = bytes(16 * 16 * 4)
            hdrs = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Height": "16", "X-Nm03-Width": "16",
            }
            for _ in range(3):
                st, _, h = app.proxy_segment(
                    body, dict(hdrs), query="output=mask"
                )
                assert st == 200
            assert a.posts + b.posts == 3  # every request proxied
            assert app.status()["result_cache"]["entries"] == 0
            # converge the fleet: the tier re-engages on its own
            b.program_version = a.program_version
            app._sweep()
            assert app._fleet_result_version() == a.program_version
        finally:
            app.close()
            a.stop()
            b.stop()

    def test_disabled_tier_status_and_debug(self):
        fake = _FakeCachingReplica()
        app = _router([fake])  # no result_cache_bytes
        try:
            assert app.result_store is None
            assert app.status()["result_cache"]["enabled"] is False
        finally:
            app.close()
            fake.stop()


# -- slow subprocess acceptance drills --------------------------------------


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _cpu_env(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    # keep crash dumps out of the repo root if a spawned replica dies
    env["NM03_FLIGHTREC_DIR"] = str(tmp_path)
    return env


def _wait_ready(urls, timeout_s=300):
    deadline = time.monotonic() + timeout_s
    pending = set(urls)
    while pending and time.monotonic() < deadline:
        for url in list(pending):
            try:
                with urllib.request.urlopen(f"{url}/readyz", timeout=2.0) as r:
                    if r.status == 200 and json.loads(r.read()).get("ready"):
                        pending.discard(url)
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.2)
    assert not pending, f"never ready: {pending}"


def _spawn_replica(port, extra, env):
    return subprocess.Popen(
        [
            sys.executable, "-m",
            "nm03_capstone_project_tpu.serving.server",
            "--device", "cpu", "--port", str(port),
            "--canvas", str(CANVAS), "--buckets", "1,4", "--lanes", "1",
            "--max-wait-ms", "10", "--heartbeat-s", "0",
            "--queue-capacity", "64",
            "--result-cache-bytes", "64m",
            *extra,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO,
    )


def _spawn_fleet(port, targets, metrics_out, env, extra=()):
    return subprocess.Popen(
        [
            sys.executable, "-m",
            "nm03_capstone_project_tpu.fleet.cli", "serve",
            "--replicas", targets,
            "--port", str(port),
            "--health-interval-s", "0.25",
            "--health-timeout-s", "2.0",
            "--proxy-timeout-s", "120",
            "--result-cache-bytes", "64m",
            "--metrics-out", str(metrics_out),
            *extra,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO,
    )


def _terminate(procs, timeout=30):
    for p in procs:
        if p and p.poll() is None:
            p.terminate()
    for p in procs:
        if p:
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=10)


@pytest.mark.slow
class TestResultTierAcceptanceDrill:
    def test_zipfian_replay_collapses_p50_and_device_seconds(self, tmp_path):
        """The ISSUE 19 acceptance bar: a fleet of two cached replicas
        behind a cached router under an `nm03-loadgen --zipf 1.1` replay
        over 32 studies — hit ratio >= 0.5, repeat p50 under a quarter of
        the miss p50, hits billing zero device-seconds, gated through
        check_telemetry on the router's own counters."""
        from nm03_capstone_project_tpu.serving import loadgen

        env = _cpu_env(tmp_path)
        ports = _free_ports(3)
        metrics_out = tmp_path / "fleet_metrics.json"
        replicas = [
            _spawn_replica(ports[0], [], env),
            _spawn_replica(ports[1], [], env),
        ]
        fleet = None
        try:
            _wait_ready([f"http://127.0.0.1:{p}" for p in ports[:2]])
            targets = f"127.0.0.1:{ports[0]},127.0.0.1:{ports[1]}"
            fleet = _spawn_fleet(ports[2], targets, metrics_out, env)
            fleet_url = f"http://127.0.0.1:{ports[2]}"
            _wait_ready([fleet_url])
            results_json = tmp_path / "zipf_summary.json"
            rc = loadgen.main([
                "--url", fleet_url,
                "--requests", "96", "--concurrency", "8",
                "--zipf", "1.1", "--keyspace", "32",
                "--height", str(CANVAS), "--width", str(CANVAS),
                "--warmup", "0", "--timeout-s", "60",
                "--results-json", str(results_json),
            ])
            assert rc == 0
            summary = json.loads(results_json.read_text())
            assert summary["requests_ok"] == summary["requests_total"] == 96
            assert summary["zipf"] == {"s": 1.1, "keyspace": 32}
            # the headline gates
            assert summary["cache_hit_ratio"] >= 0.5, summary["cache"]
            cache = summary["cache"]
            assert cache["states"].get("hit", 0) > 0
            hit_p50 = cache["hit_latency_ms"]["p50"]
            miss_p50 = cache["miss_latency_ms"]["p50"]
            assert hit_p50 < 0.25 * miss_p50, (hit_p50, miss_p50)
            # hits bill no device time -> the per-request mean falls on
            # a repeat-heavy replay
            ds = summary["device_seconds_ms"]
            assert ds["hit_mean"] == 0.0
            assert ds["miss_mean"] is None or ds["miss_mean"] >= 0.0
            # drain the fleet so its registry lands in --metrics-out,
            # then gate the same events server-side
            _terminate([fleet])
            fleet = None
            assert metrics_out.exists()
            check = subprocess.run(
                [
                    sys.executable, CHECKER,
                    "--metrics", str(metrics_out),
                    "--expect-counter",
                    "serving_result_cache_hit_total=10",
                    "--expect-counter",
                    "serving_result_cache_fill_total=5",
                    "--expect-counter",
                    "serving_result_cache_miss_total=5",
                ],
                capture_output=True, text=True, timeout=60,
            )
            assert check.returncode == 0, check.stdout + check.stderr
        finally:
            _terminate([fleet, *replicas])

    def test_sigkill_idempotent_volume_retry_is_bit_identical(self, tmp_path):
        """A whole-study request survives losing its replica: the client
        retries with the same X-Nm03-Idempotency-Key after the serving
        replica is SIGKILLed, and the answer comes back bit-identical
        (same ETag, same mask_sha256) from the router's store — no gang
        program runs a second time anywhere."""
        env = _cpu_env(tmp_path)
        # the volume gang spans lanes=2 chips; fake them on the host
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        ports = _free_ports(3)
        metrics_out = tmp_path / "fleet_metrics.json"
        vol_extra = [
            "--volume-serving", "--volume-depth-buckets", "8",
            "--lanes", "2",
        ]
        replicas = [
            _spawn_replica(ports[0], vol_extra, env),
            _spawn_replica(ports[1], vol_extra, env),
        ]
        fleet = None
        try:
            import numpy as np

            from nm03_capstone_project_tpu.data.synthetic import (
                phantom_volume,
            )

            _wait_ready([f"http://127.0.0.1:{p}" for p in ports[:2]])
            targets = f"127.0.0.1:{ports[0]},127.0.0.1:{ports[1]}"
            fleet = _spawn_fleet(ports[2], targets, metrics_out, env)
            fleet_url = f"http://127.0.0.1:{ports[2]}"
            _wait_ready([fleet_url])
            vol = np.asarray(
                phantom_volume(8, CANVAS, CANVAS, seed=7), np.float32
            )
            body = vol.astype("<f4").tobytes()
            headers = {
                "Content-Type": "application/octet-stream",
                "X-Nm03-Depth": "8",
                "X-Nm03-Height": str(CANVAS),
                "X-Nm03-Width": str(CANVAS),
                "X-Nm03-Idempotency-Key": "study-42-attempt",
            }
            st1, d1, h1 = _post(
                fleet_url + "/v1/segment-volume?output=summary",
                body, dict(headers), timeout=240.0,
            )
            assert st1 == 200, d1[:300]
            p1 = json.loads(d1)
            served_by = h1.get("X-Nm03-Replica")
            assert served_by in targets.split(",")
            # kill the replica that computed it — the fleet failover
            # window an idempotent retry must survive
            victim = replicas[targets.split(",").index(served_by)]
            victim.send_signal(signal.SIGKILL)
            victim.wait(timeout=30)
            st2, d2, h2 = _post(
                fleet_url + "/v1/segment-volume?output=summary",
                body, dict(headers), timeout=240.0,
            )
            assert st2 == 200
            p2 = json.loads(d2)
            # bit-identical: same content ETag, same mask digest — and
            # served from the store (zero device seconds, zero hops)
            assert h2["X-Nm03-Cache"] == "hit"
            assert h2["ETag"] == h1["ETag"]
            assert p2["mask_sha256"] == p1["mask_sha256"]
            assert p2["cached"] is True and p2["device_seconds"] == 0.0
            assert h2["X-Nm03-Replica-Hops"] == "0"
            # no second gang dispatch: the SURVIVING replica never saw a
            # volume request at all
            survivor_port = ports[1] if served_by.endswith(
                str(ports[0])
            ) else ports[0]
            snap = _get_json(
                f"http://127.0.0.1:{survivor_port}/metrics.json"
            )
            gang_dispatches = sum(
                s.get("value", 0)
                for s in snap["metrics"]
                if s["name"] == "serving_volume_requests_total"
            )
            assert gang_dispatches == 0
        finally:
            _terminate([fleet, *replicas])
