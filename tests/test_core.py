import numpy as np
import pytest

from nm03_capstone_project_tpu.core import SliceBatch, pad_to_canvas, valid_mask


def test_pad_to_canvas_shapes_and_values():
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    b = np.ones((4, 4), dtype=np.float32)
    batch = pad_to_canvas([a, b], (8, 8))
    assert batch.pixels.shape == (2, 8, 8)
    assert batch.dims.tolist() == [[2, 3], [4, 4]]
    np.testing.assert_array_equal(batch.pixels[0, :2, :3], a)
    assert batch.pixels[0, 2:, :].sum() == 0
    assert batch.pixels[0, :, 3:].sum() == 0


def test_pad_to_canvas_rejects_oversize():
    with pytest.raises(ValueError):
        pad_to_canvas([np.zeros((9, 3), np.float32)], (8, 8))


def test_pad_to_canvas_rejects_non_2d():
    with pytest.raises(ValueError):
        pad_to_canvas([np.zeros((2, 3, 4), np.float32)], (8, 8))


def test_valid_mask_unbatched_and_batched():
    dims = np.array([[2, 3], [4, 4]], dtype=np.int32)
    m = np.asarray(valid_mask(dims, (8, 8)))
    assert m.shape == (2, 8, 8)
    assert m[0].sum() == 6
    assert m[1].sum() == 16
    assert m[0, :2, :3].all()
    single = np.asarray(valid_mask(dims[0], (8, 8)))
    np.testing.assert_array_equal(single, m[0])


def test_slicebatch_is_pytree():
    import jax

    batch = pad_to_canvas([np.zeros((2, 2), np.float32)], (4, 4))
    leaves = jax.tree_util.tree_leaves(batch)
    assert len(leaves) == 2
    out = jax.jit(lambda sb: SliceBatch(sb.pixels + 1, sb.dims))(batch)
    assert float(out.pixels[0, 0, 0]) == 1.0
