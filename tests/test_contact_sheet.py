"""Contact-sheet composition (render.contact_sheet) — the headless
MultiViewWindow (reference src/test/test_pipeline.cpp:148-158)."""

import numpy as np
import pytest

from nm03_capstone_project_tpu.render.contact_sheet import contact_sheet


def _panel(v, hw=(64, 64)):
    return np.full(hw, np.uint8(v), np.uint8)


class TestContactSheet:
    def test_five_pane_geometry(self):
        # the reference window: 5 panes, ~450 px each, black background
        sheet = contact_sheet([_panel(i * 40) for i in range(5)], pane_size=450, pad=10)
        assert sheet.shape == (470, 5 * 450 + 6 * 10)
        assert sheet.dtype == np.uint8
        assert sheet[0, 0] == 0  # padding stays background-black

    def test_panes_land_in_order(self):
        sheet = contact_sheet([_panel(10), _panel(200)], pane_size=8, pad=2)
        assert sheet[6, 6] == 10  # first cell
        assert sheet[6, 2 + 8 + 2 + 4] == 200  # second cell

    def test_resizes_mixed_sizes(self):
        sheet = contact_sheet(
            [_panel(7, (32, 32)), _panel(9, (128, 256))], pane_size=16, pad=1
        )
        assert sheet.shape == (18, 2 * 16 + 3)
        assert sheet[8, 8] == 7 and sheet[8, 1 + 16 + 1 + 8] == 9

    def test_rejects_empty_and_bad_dtype(self):
        with pytest.raises(ValueError, match="at least one"):
            contact_sheet([])
        with pytest.raises(ValueError, match="uint8"):
            contact_sheet([np.zeros((4, 4), np.float32)])

    def test_rejects_label_mismatch(self):
        with pytest.raises(ValueError, match="labels"):
            contact_sheet([_panel(1)], labels=["a", "b"])
