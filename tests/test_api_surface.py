"""The public API surface imports.

SURFACE is the supported import surface: everything docs/API.md names plus
the handful of companion helpers users reach next (loggers, valid_mask,
optimizer factories). A rename that breaks any of these fails here instead
of in a user's shell; deliberate surface changes update this list (and the
API guide when the symbol is documented there).
"""

import importlib

import pytest

SURFACE = {
    "nm03_capstone_project_tpu": ["config", "native"],
    "nm03_capstone_project_tpu.config": [
        "PipelineConfig",
        "BatchConfig",
        "DEFAULT_CONFIG",
    ],
    "nm03_capstone_project_tpu.core": ["pad_to_canvas", "valid_mask"],
    "nm03_capstone_project_tpu.pipeline": [
        "process_batch",
        "process_slice",
        "process_slice_stages",
        "process_volume",
    ],
    "nm03_capstone_project_tpu.ops": [
        "normalize",
        "clip_intensity",
        "vector_median_filter",
        "median_filter",
        "sharpen",
        "seed_mask",
        "region_grow",
        "region_grow_jump",
        "grow_dispatch",
        "cast_uint8",
        "dilate",
        "erode",
        "binary_threshold",
        "connected_components",
        "region_properties",
        "bounding_box",
        "extend_edges",
    ],
    "nm03_capstone_project_tpu.data.discovery": [
        "find_patient_dirs",
        "load_dicom_files_for_patient",
    ],
    "nm03_capstone_project_tpu.data.dicomlite": [
        "read_dicom",
        "read_dicom_frames",
        "write_dicom",
    ],
    "nm03_capstone_project_tpu.data.synthetic": [
        "phantom_slice",
        "phantom_series",
        "phantom_volume",
        "write_synthetic_cohort",
    ],
    # streaming ingest (ISSUE 11): the host->HBM data path, including the
    # prefetch helper absorbed from the retired data/prefetch.py
    "nm03_capstone_project_tpu.ingest": [
        "IngestPipeline",
        "IngestFailure",
        "StagingRing",
        "stage_batch",
        "prefetch_to_device",
    ],
    # the replica-fleet front-end (ISSUE 13): router, state machine,
    # rolling-restart orchestration — what docs/API.md's fleet section names
    "nm03_capstone_project_tpu.fleet": [
        "FleetApp",
        "ReplicaStates",
        "rolling_restart",
        "serve_in_thread",
        "RestartError",
    ],
    # the content-addressed result tier (ISSUE 19): store, in-flight
    # dedup index, key derivation — what docs/API.md's cache section names
    "nm03_capstone_project_tpu.cache": [
        "ResultStore",
        "ResultEntry",
        "InflightIndex",
        "ResultKey",
        "result_key",
        "digest_bytes",
        "content_etag",
        "etag_matches",
        "parse_bytes",
    ],
    # online serving incl. whole-volume gang serving (ISSUE 15): what
    # docs/API.md's serving sections name
    "nm03_capstone_project_tpu.serving": [
        "ServingApp",
        "AdmissionQueue",
        "DynamicBatcher",
        "WarmExecutor",
        "VolumeGang",
        "VolumeRequest",
        "GangUnavailable",
        "serve_in_thread",
    ],
    "nm03_capstone_project_tpu.serving.volumes": [
        "VolumeGang",
        "VolumeRequest",
        "GangUnavailable",
        "DEFAULT_VOLUME_DEPTH_BUCKETS",
    ],
    "nm03_capstone_project_tpu.data.codecs": [
        "rle_encode_frame",
        "rle_decode_frame",
        "jpeg_lossless_encode",
        "jpeg_lossless_decode",
        "jpegls_encode",
        "jpegls_decode",
    ],
    "nm03_capstone_project_tpu.data.imageio": [
        "write_metaimage",
        "read_metaimage",
        "write_image",
        "read_image",
    ],
    "nm03_capstone_project_tpu.render": [
        "render_gray",
        "render_segmentation",
        "render_overlay",
        "render_pair",
        "host_render_gray",
        "host_render_segmentation",
        "host_render_pair",
        "save_jpeg",
        "export_pairs",
        "render_export_pairs",
        "clean_directory",
        "contact_sheet",
    ],
    "nm03_capstone_project_tpu.parallel": [
        "make_mesh",
        "pad_to_multiple",
        "process_batch_sharded",
        "process_volume_zsharded",
        "process_volume_batch_zsharded",
        "distributed",
    ],
    "nm03_capstone_project_tpu.parallel.distributed": [
        "initialize",
        "global_mesh",
        "process_info",
    ],
    "nm03_capstone_project_tpu.models": [
        "init_unet",
        "init_unet3d",
        "apply_unet3d",
        "fit",
        "fit_sharded",
        "fit_distributed",
        "pad_local_shard",
        "predict_mask",
        "predict_mask3d",
        "distill_batch",
        "distill_volume",
        "prepare_student_inputs",
        "make_optimizer",
        "make_sharded_train_step",
    ],
    "nm03_capstone_project_tpu.models.checkpoint": ["save_params", "load_params"],
    "nm03_capstone_project_tpu.obs": [
        "MetricsRegistry",
        "SpanRecorder",
        "EventLog",
        "RunContext",
    ],
    # the SLO plane (ISSUE 14): objectives + burn-rate monitor — what
    # docs/OBSERVABILITY.md's "SLO plane" section names
    "nm03_capstone_project_tpu.obs.slo": [
        "SLOObjective",
        "SLOMonitor",
        "objective_from_args",
        "add_slo_args",
    ],
    "nm03_capstone_project_tpu.utils.manifest": ["Manifest"],
    "nm03_capstone_project_tpu.utils.timing": ["Timer", "write_results_json"],
    "nm03_capstone_project_tpu.utils.profiling": [
        "profile_trace",
        "capture_profile",  # the remote /debug/profile pull (ISSUE 14)
    ],
    "nm03_capstone_project_tpu.utils.reporter": ["configure_reporting", "get_logger"],
    "nm03_capstone_project_tpu.native": ["available", "load_batch_native"],
}


def _resolves(module, mod, name) -> bool:
    if hasattr(mod, name):
        return True
    try:  # a submodule not imported by the package __init__ still counts
        importlib.import_module(f"{module}.{name}")
        return True
    except ImportError:
        return False


@pytest.mark.parametrize("module", sorted(SURFACE))
def test_documented_surface_imports(module):
    mod = importlib.import_module(module)
    missing = [n for n in SURFACE[module] if not _resolves(module, mod, n)]
    assert not missing, f"{module} lacks public-surface symbols: {missing}"
