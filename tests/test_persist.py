"""Persistent AOT executable cache tests (ISSUE 9).

Four layers:

* key/format units: PersistKey completeness + determinism, entry
  composition, corrupt/stale classification — no device work;
* hub drills on the real pipeline: cold start compiles+stores, a fresh
  hub against the same dir loads with ZERO builds and bit-identical
  masks; corrupt (truncated) and stale (version-flipped) entries degrade
  to clean recompiles, counted, never raised; a FaultPlan ``cache``
  io_error aborts the store and the next start recompiles;
* the ``nm03-cache`` admin CLI: ls/verify/gc red+green, byte and age
  retention;
* the acceptance drill: ``nm03-serve --lanes 2 --compile-cache-dir`` in
  a subprocess, drained, then RESTARTED against the same dir under
  concurrent traffic — the second start warms with zero hub builds,
  ``total_compile_seconds`` ≤ 5% of the cold value, serves bit-identical
  masks, and passes ``check_telemetry`` with the exact-form cache
  counter expectations (``compile_cache_hits_total==N``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from nm03_capstone_project_tpu.compilehub.hub import (
    CompileHub,
    CompileSpec,
    aot_compile,
)
from nm03_capstone_project_tpu.compilehub.persist import (
    ENTRY_SUFFIX,
    ExecutableCache,
    PersistKey,
    config_digest,
    gc_entries,
    scan_entries,
)
from nm03_capstone_project_tpu.config import PipelineConfig
from nm03_capstone_project_tpu.data.synthetic import phantom_slice

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 96  # hub-drill canvas (small = fast compiles)
SERVE_CANVAS = 128  # the serving drill must clear the min_dim=100 guard


def _mask_build(spec):
    """The serving-style AOT build: vmapped mask program at the spec shape."""
    import jax
    import jax.numpy as jnp

    from nm03_capstone_project_tpu.pipeline.slice_pipeline import process_slice

    def one(px, dm):
        out = process_slice(px, dm, spec.cfg)
        return out["mask"], out["grow_converged"]

    b, c = spec.shape[0], spec.cfg.canvas
    return aot_compile(
        jax.jit(jax.vmap(one)),
        jax.ShapeDtypeStruct((b, c, c), jnp.float32),
        jax.ShapeDtypeStruct((b, 2), jnp.int32),
    )


def _spec(cfg, batch=1, **kw):
    return CompileSpec(
        name="serve_mask", cfg=cfg, shape=(batch, cfg.canvas, cfg.canvas), **kw
    )


def _batch(cfg, batch=1, seed=3):
    px = np.stack(
        [phantom_slice(cfg.canvas, cfg.canvas, seed=seed + i) for i in range(batch)]
    ).astype(np.float32)
    dm = np.full((batch, 2), cfg.canvas, np.int32)
    return px, dm


@pytest.fixture(scope="module")
def cfg():
    return PipelineConfig(canvas=CANVAS)


# -- key / format units ------------------------------------------------------


class TestPersistKey:
    def test_covers_every_compile_spec_field(self, cfg):
        """The NM381 contract, asserted dynamically: from_spec's output
        must change when ANY CompileSpec field changes (version fields
        aside, every key field traces back to a spec field)."""
        base = PersistKey.from_spec(_spec(cfg))
        variations = {
            "name": dataclasses.replace(_spec(cfg), name="other"),
            "variant": _spec(cfg, variant="pinned"),
            "shape": _spec(cfg, batch=2),
            "lane": _spec(cfg, lane=3),
            "backend": _spec(cfg, backend="cpu"),
            "donate": _spec(cfg, donate=True),
            "cfg": _spec(dataclasses.replace(cfg, grow_low=0.5)),
        }
        for field, spec in variations.items():
            other = PersistKey.from_spec(spec)
            assert other != base, f"CompileSpec.{field} does not reach the key"
            assert other.digest() != base.digest()

    def test_device_identity_in_key(self, cfg):
        import jax

        devs = jax.local_devices()
        assert len(devs) >= 2  # conftest forces 8 virtual devices
        k0 = PersistKey.from_spec(_spec(cfg, device=devs[0]))
        k1 = PersistKey.from_spec(_spec(cfg, device=devs[1]))
        assert k0.digest() != k1.digest()
        assert k0.filename() != k1.filename()

    def test_key_deterministic_and_config_equality(self, cfg):
        assert PersistKey.from_spec(_spec(cfg)) == PersistKey.from_spec(
            _spec(PipelineConfig(canvas=CANVAS))
        )
        assert config_digest(cfg) == config_digest(PipelineConfig(canvas=CANVAS))
        assert config_digest(cfg) != config_digest(
            dataclasses.replace(cfg, clip_high=1.0)
        )
        assert config_digest(None) != config_digest(cfg)

    def test_filename_is_safe_and_suffixed(self, cfg):
        import jax

        name = PersistKey.from_spec(
            _spec(cfg, device=jax.local_devices()[0])
        ).filename()
        assert name.endswith(ENTRY_SUFFIX)
        assert "/" not in name and " " not in name


# -- hub drills on the real pipeline ----------------------------------------


class TestHubCachePath:
    def test_cold_then_warm_bit_identical_zero_builds(self, cfg, tmp_path):
        cold = CompileHub()
        cold.attach_cache(ExecutableCache(tmp_path))
        fn1 = cold.get(_spec(cfg), _mask_build)
        s1 = cold.stats()
        assert s1["builds"] == 1 and s1["cache_loads"] == 0
        assert s1["cache_misses"] == 1 and s1["cache_hits"] == 0
        assert s1["total_compile_seconds"] > 0
        assert list(tmp_path.glob(f"*{ENTRY_SUFFIX}"))

        warm = CompileHub()
        warm.attach_cache(ExecutableCache(tmp_path))
        fn2 = warm.get(_spec(cfg), _mask_build)
        s2 = warm.stats()
        assert s2["builds"] == 0 and s2["cache_loads"] == 1
        assert s2["cache_hits"] == 1 and s2["cache_misses"] == 0
        # the honesty split: a loaded executable reports NO compile cost
        assert s2["total_compile_seconds"] == 0.0
        assert s2["cache_load_seconds"] > 0

        px, dm = _batch(cfg)
        m1, c1 = fn1(px, dm)
        m2, c2 = fn2(px, dm)
        assert np.array_equal(np.asarray(m1), np.asarray(m2))
        assert np.array_equal(np.asarray(c1), np.asarray(c2))
        assert np.asarray(m1).any()  # the phantom actually segments

    def test_corrupt_entry_is_silent_miss_with_recompile(self, cfg, tmp_path):
        """The torn-write drill: a truncated entry (the exact artifact a
        mid-write kill would leave WITHOUT atomic_write_bytes) recompiles
        cleanly, counted as corrupt, masks bit-identical."""
        seeder = CompileHub()
        seeder.attach_cache(ExecutableCache(tmp_path))
        ref = seeder.get(_spec(cfg), _mask_build)
        px, dm = _batch(cfg)
        want = np.asarray(ref(px, dm)[0])

        entry = next(tmp_path.glob(f"*{ENTRY_SUFFIX}"))
        raw = entry.read_bytes()
        for cut in (len(raw) // 2, 64, 0):  # payload torn, header torn, empty
            entry.write_bytes(raw[:cut])
            hub = CompileHub()
            cache = ExecutableCache(tmp_path)
            hub.attach_cache(cache)
            fn = hub.get(_spec(cfg), _mask_build)
            assert hub.stats()["builds"] == 1, f"cut={cut}"
            st = cache.stats()
            assert st["misses"] == 1 and st["corrupt"] == 1 and st["hits"] == 0
            assert np.array_equal(np.asarray(fn(px, dm)[0]), want)
            # the rebuild re-stored a good entry each round
            assert entry.read_bytes() != raw[:cut]
            raw = entry.read_bytes()

    def test_stale_version_is_silent_miss_with_recompile(self, cfg, tmp_path):
        seeder = CompileHub()
        seeder.attach_cache(ExecutableCache(tmp_path))
        ref = seeder.get(_spec(cfg), _mask_build)
        px, dm = _batch(cfg)
        want = np.asarray(ref(px, dm)[0])

        entry = next(tmp_path.glob(f"*{ENTRY_SUFFIX}"))
        head, _, payload = entry.read_bytes().partition(b"\n")
        header = json.loads(head)
        header["key"]["jaxlib_version"] = "0.0.0-stale"
        entry.write_bytes(
            json.dumps(header, sort_keys=True).encode() + b"\n" + payload
        )
        hub = CompileHub()
        cache = ExecutableCache(tmp_path)
        hub.attach_cache(cache)
        fn = hub.get(_spec(cfg), _mask_build)
        assert hub.stats()["builds"] == 1
        st = cache.stats()
        assert st["stale"] == 1 and st["misses"] == 1 and st["corrupt"] == 0
        assert np.array_equal(np.asarray(fn(px, dm)[0]), want)

    def test_fault_plan_cache_io_error_aborts_store(self, cfg, tmp_path):
        """The chaos satellite: a FaultPlan ``cache`` io_error rule kills
        the entry write; the hub still serves the freshly built
        executable and the NEXT start recompiles (miss, not crash)."""
        from nm03_capstone_project_tpu.resilience import FaultPlan
        from nm03_capstone_project_tpu.serving.server import _cache_fault_hook

        plan = FaultPlan.from_spec(
            {"faults": [{"site": "cache", "kind": "io_error", "count": 1}]}
        )
        hub = CompileHub()
        cache = ExecutableCache(tmp_path, fault_hook=_cache_fault_hook(plan, None))
        hub.attach_cache(cache)
        fn = hub.get(_spec(cfg), _mask_build)
        px, dm = _batch(cfg)
        assert np.asarray(fn(px, dm)[0]).any()
        assert plan.fired_total() == 1
        assert cache.stats()["store_errors"] == 1
        assert not list(tmp_path.glob(f"*{ENTRY_SUFFIX}"))
        # second start: plain miss+recompile, and (budget spent) the store
        # now succeeds — the cache heals itself
        hub2 = CompileHub()
        hub2.attach_cache(ExecutableCache(tmp_path, fault_hook=_cache_fault_hook(plan, None)))
        hub2.get(_spec(cfg), _mask_build)
        assert hub2.stats()["builds"] == 1
        assert len(list(tmp_path.glob(f"*{ENTRY_SUFFIX}"))) == 1

    def test_export_fallback_unpinned_only(self, cfg, tmp_path, monkeypatch):
        """Backends whose PJRT executables cannot serialize fall back to
        the jax-export StableHLO form — accounted as a DEFERRED load (aot
        False; XLA still compiles at first execute), masks bit-identical;
        device-pinned specs refuse the fallback entirely (an entry that
        collapses every lane onto the default device is worse than none)."""
        import jax
        from jax.experimental import serialize_executable

        from nm03_capstone_project_tpu.compilehub import persist as persist_mod

        def boom(*a, **k):
            raise RuntimeError("pjrt serialization unsupported here")

        monkeypatch.setattr(serialize_executable, "serialize", boom)

        seeder = CompileHub()
        cache = ExecutableCache(tmp_path)
        seeder.attach_cache(cache)
        ref = seeder.get(_spec(cfg), _mask_build)
        entry = next(tmp_path.glob(f"*{ENTRY_SUFFIX}"))
        head, _, _ = entry.read_bytes().partition(b"\n")
        assert json.loads(head)["format"] == persist_mod.FORMAT_EXPORT

        warm = CompileHub()
        warm.attach_cache(ExecutableCache(tmp_path))
        fn = warm.get(_spec(cfg), _mask_build)
        st = warm.stats()
        assert st["builds"] == 0 and st["cache_loads"] == 1
        assert st["aot"] == 0  # deferred: the export pays compile at first call
        px, dm = _batch(cfg)
        assert np.array_equal(np.asarray(fn(px, dm)[0]), np.asarray(ref(px, dm)[0]))

        # pinned spec: no entry, counted store_error, hub still serves
        pinned = _spec(cfg, device=jax.local_devices()[1], lane=1,
                       variant="pinned")
        hub2 = CompileHub()
        cache2 = ExecutableCache(tmp_path)
        hub2.attach_cache(cache2)
        fn2 = hub2.get(pinned, _mask_build)
        assert np.asarray(fn2(px, dm)[0]).any()
        assert cache2.stats()["store_errors"] == 1
        assert len(list(tmp_path.glob(f"*{ENTRY_SUFFIX}"))) == 1  # only the unpinned

    def test_different_cfg_never_false_hits(self, cfg, tmp_path):
        seeder = CompileHub()
        seeder.attach_cache(ExecutableCache(tmp_path))
        seeder.get(_spec(cfg), _mask_build)
        other_cfg = dataclasses.replace(cfg, grow_low=0.99, grow_high=0.999)
        hub = CompileHub()
        cache = ExecutableCache(tmp_path)
        hub.attach_cache(cache)
        hub.get(_spec(other_cfg), _mask_build)
        assert hub.stats()["builds"] == 1  # no cross-config hit
        assert cache.stats()["hits"] == 0
        assert len(list(tmp_path.glob(f"*{ENTRY_SUFFIX}"))) == 2

    def test_deferred_specs_bypass_the_cache(self, cfg, tmp_path):
        """shape=None (deferred-trace) specs must neither store nor count
        misses — only AOT executables are persistable."""
        hub = CompileHub()
        cache = ExecutableCache(tmp_path)
        hub.attach_cache(cache)

        def build(spec):
            return lambda x: x  # stands in for a deferred jit callable

        hub.get(CompileSpec(name="deferred", cfg=cfg), build)
        st = cache.stats()
        assert st["misses"] == 0 and st["stores"] == 0
        assert not list(tmp_path.glob(f"*{ENTRY_SUFFIX}"))


# -- the nm03-cache admin CLI -----------------------------------------------


class TestCacheCli:
    @pytest.fixture()
    def seeded_dir(self, cfg, tmp_path_factory):
        d = tmp_path_factory.mktemp("cachecli")
        hub = CompileHub()
        hub.attach_cache(ExecutableCache(d))
        for b in (1, 2):
            hub.get(_spec(cfg, batch=b), _mask_build)
        return d

    def _run(self, d, *args):
        return subprocess.run(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.compilehub.cache_cli",
                "--dir", str(d), "--format", "json", *args,
            ],
            capture_output=True, text=True, timeout=120, cwd=REPO,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )

    def test_ls_and_verify_green(self, seeded_dir):
        res = self._run(seeded_dir, "ls")
        assert res.returncode == 0, res.stderr
        rows = json.loads(res.stdout)["entries"]
        assert len(rows) == 2
        assert all(r["status"] == "ok" for r in rows)
        assert {tuple(r["shape"]) for r in rows} == {
            (1, CANVAS, CANVAS), (2, CANVAS, CANVAS),
        }
        res = self._run(seeded_dir, "verify")
        assert res.returncode == 0, res.stdout
        assert json.loads(res.stdout)["ok"] == 2

    def test_verify_red_on_corrupt(self, seeded_dir):
        victim = sorted(seeded_dir.glob(f"*{ENTRY_SUFFIX}"))[0]
        victim.write_bytes(victim.read_bytes()[:-7])
        res = self._run(seeded_dir, "verify")
        assert res.returncode == 1
        out = json.loads(res.stdout)
        assert [c["file"] for c in out["corrupt"]] == [victim.name]

    def test_gc_age_and_byte_retention(self, seeded_dir):
        entries = sorted(seeded_dir.glob(f"*{ENTRY_SUFFIX}"))
        old, young = entries[0], entries[1]
        past = time.time() - 7200
        os.utime(old, (past, past))
        # dry run: nothing deleted
        rep = gc_entries(seeded_dir, max_age_s=3600, dry_run=True)
        assert rep["removed"] == [old.name] and old.exists()
        res = self._run(seeded_dir, "gc", "--max-age", "1h")
        assert res.returncode == 0, res.stderr
        assert json.loads(res.stdout)["removed"] == [old.name]
        assert not old.exists() and young.exists()
        # byte budget of 0 clears the rest
        res = self._run(seeded_dir, "gc", "--max-bytes", "0")
        assert json.loads(res.stdout)["removed"] == [young.name]
        assert not list(seeded_dir.glob(f"*{ENTRY_SUFFIX}"))

    def test_gc_removes_corrupt_unconditionally(self, seeded_dir):
        victim = sorted(seeded_dir.glob(f"*{ENTRY_SUFFIX}"))[0]
        victim.write_bytes(b"garbage")
        rep = gc_entries(seeded_dir)  # no budgets at all
        assert rep["removed"] == [victim.name]
        assert not victim.exists()

    def test_gc_reclaims_orphaned_tmp_files(self, seeded_dir):
        """A SIGKILL mid-store leaks the atomic write's private temp; gc
        reclaims it once past the grace window (a fresh temp — possibly a
        live writer's — is left alone)."""
        orphan = seeded_dir / f"x{ENTRY_SUFFIX}.abc123.tmp"
        orphan.write_bytes(b"half-written entry")
        fresh = seeded_dir / f"y{ENTRY_SUFFIX}.def456.tmp"
        fresh.write_bytes(b"live writer")
        past = time.time() - 3600
        os.utime(orphan, (past, past))
        rep = gc_entries(seeded_dir)
        assert orphan.name in rep["removed"] and not orphan.exists()
        assert fresh.exists() and fresh.name not in rep["removed"]
        assert rep["kept"] == 2  # the real entries untouched

    def test_gc_removes_stale_unconditionally(self, seeded_dir):
        """Post-upgrade reclamation: a stale entry's filename digest embeds
        the old versions, so the new toolchain can never even open it —
        gc drops it with no budget flags, as the runbook promises."""
        victim = sorted(seeded_dir.glob(f"*{ENTRY_SUFFIX}"))[0]
        head, _, payload = victim.read_bytes().partition(b"\n")
        header = json.loads(head)
        header["key"]["jax_version"] = "0.0.0-old"
        victim.write_bytes(
            json.dumps(header, sort_keys=True).encode() + b"\n" + payload
        )
        rep = gc_entries(seeded_dir)
        assert rep["removed"] == [victim.name]
        assert not victim.exists() and rep["kept"] == 1

    @pytest.mark.skipif(os.geteuid() == 0, reason="root ignores file modes")
    def test_unreadable_entry_is_kept_by_gc(self, seeded_dir):
        """EACCES is not bit rot: a permissions mismatch (gc cron under a
        different uid) must report `unreadable` and survive gc — deleting
        a fleet's warm cache over a perms problem is the worst thing a
        janitor can do."""
        victim = sorted(seeded_dir.glob(f"*{ENTRY_SUFFIX}"))[0]
        victim.chmod(0)
        try:
            rows = {r["file"]: r for r in scan_entries(seeded_dir)}
            assert rows[victim.name]["status"] == "unreadable"
            rep = gc_entries(seeded_dir)
            assert victim.name not in rep["removed"] and victim.exists()
            # exempt from the age and byte budgets too, not just the
            # unconditional branch
            past = time.time() - 7200
            os.utime(victim, (past, past))
            rep = gc_entries(seeded_dir, max_age_s=60, max_bytes=0)
            assert victim.name not in rep["removed"] and victim.exists()
        finally:
            victim.chmod(0o644)

    def test_scan_reports_stale(self, seeded_dir):
        victim = sorted(seeded_dir.glob(f"*{ENTRY_SUFFIX}"))[0]
        head, _, payload = victim.read_bytes().partition(b"\n")
        header = json.loads(head)
        header["key"]["nm03_version"] = "0.0.0-old"
        victim.write_bytes(
            json.dumps(header, sort_keys=True).encode() + b"\n" + payload
        )
        rows = {r["file"]: r for r in scan_entries(seeded_dir)}
        assert rows[victim.name]["status"] == "stale"
        assert rows[victim.name]["stale_fields"] == ["nm03_version"]


# -- check_telemetry: the exact-form counter expectation ---------------------


class TestExactCounterExpectations:
    """``--expect-counter NAME==N`` (ISSUE 9 satellite): gauge-compatible
    exact equality for the cache counters — presence required, value
    exact; the single-equals floor form unchanged."""

    def _check(self, tmp_path, metrics, *expectations):
        snap = {
            "schema": "nm03.metrics.v1", "run_id": "r", "git_sha": "s",
            "created_unix": 1.0, "metrics": metrics,
        }
        p = tmp_path / "m.json"
        p.write_text(json.dumps(snap))
        return subprocess.run(
            [sys.executable, CHECKER, "--metrics", str(p), *expectations],
            capture_output=True, text=True, timeout=60,
        )

    def _counter(self, name, value, **labels):
        return {"name": name, "type": "counter",
                "labels": {k: str(v) for k, v in labels.items()},
                "value": value}

    def test_exact_green_and_red(self, tmp_path):
        metrics = [
            self._counter("compile_cache_hits_total", 8),
            self._counter("compile_cache_misses_total", 0),
        ]
        ok = self._check(
            tmp_path, metrics,
            "--expect-counter", "compile_cache_hits_total==8",
            "--expect-counter", "compile_cache_misses_total==0",
        )
        assert ok.returncode == 0, ok.stderr
        red = self._check(
            tmp_path, metrics, "--expect-counter",
            "compile_cache_hits_total==7",
        )
        assert red.returncode == 1 and "expected == 7" in red.stderr

    def test_exact_requires_presence(self, tmp_path):
        # ==0 on an ABSENT counter must fail: a run without the cache
        # enabled is not a run that proved zero misses
        res = self._check(
            tmp_path, [self._counter("other_total", 1)],
            "--expect-counter", "compile_cache_misses_total==0",
        )
        assert res.returncode == 1 and "absent" in res.stderr

    def test_floor_form_unchanged(self, tmp_path):
        metrics = [self._counter("compile_cache_hits_total", 8)]
        assert self._check(
            tmp_path, metrics,
            "--expect-counter", "compile_cache_hits_total=4",
        ).returncode == 0
        assert self._check(
            tmp_path, metrics,
            "--expect-counter", "compile_cache_hits_total=9",
        ).returncode == 1

    def test_exact_with_labeled_selector(self, tmp_path):
        metrics = [
            self._counter("serving_lane_batches_total", 3, lane=0),
            self._counter("serving_lane_batches_total", 5, lane=1),
        ]
        ok = self._check(
            tmp_path, metrics,
            "--expect-counter", "serving_lane_batches_total{lane=1}==5",
        )
        assert ok.returncode == 0, ok.stderr
        red = self._check(
            tmp_path, metrics,
            "--expect-counter", "serving_lane_batches_total{lane=1}==3",
        )
        assert red.returncode == 1


# -- serving integration ------------------------------------------------------


class TestServingColdStart:
    def test_in_process_cold_start_publishes_cache_telemetry(
        self, cfg, tmp_path
    ):
        """A cache-enabled ServingApp cold start: every (lane, bucket) spec
        misses then stores, /readyz's compile_hub carries the cache
        fields, and the counters are published at their exact values."""
        from nm03_capstone_project_tpu.compilehub import get_hub
        from nm03_capstone_project_tpu.serving.server import ServingApp

        app = ServingApp(
            cfg=cfg,
            buckets=(1,),
            lanes=1,
            compile_cache_dir=str(tmp_path),
        )
        try:
            app.start()
            st = app.status()
            hub_st = st["compile_hub"]
            assert hub_st["cache_hits"] == 0
            # misses >= the serve_mask spec count (other AOT programs the
            # process builds also go through the attached cache)
            assert hub_st["cache_misses"] >= 1
            assert hub_st["cache_bytes"] > 0
            assert list(tmp_path.glob(f"*{ENTRY_SUFFIX}"))
            snap = {
                (m["name"]): m["value"]
                for m in app.obs.metrics_snapshot()["metrics"]
                if m["name"].startswith("compile_cache")
            }
            assert snap["compile_cache_hits_total"] == 0
            assert snap["compile_cache_misses_total"] == hub_st["cache_misses"]
            assert "compile_cache_load_seconds" in snap
        finally:
            app.begin_drain(reason="test")
            app.close()
            get_hub().attach_cache(None)  # never leak into other tests

    def test_two_start_subprocess_drill(self, cfg, tmp_path):
        """The ISSUE 9 acceptance bar: nm03-serve --lanes 2, drain,
        restart against the same --compile-cache-dir under concurrent
        traffic. Second start: ZERO hub builds of serve specs (hits ==
        warm spec count, misses == 0), total_compile_seconds <= 5% of
        cold, masks bit-identical, exact-form counter gate green."""
        cache_dir = tmp_path / "cache"
        img = phantom_slice(SERVE_CANVAS, SERVE_CANVAS, seed=1)
        body = img.astype("<f4").tobytes()

        first = self._serve_round(
            tmp_path / "r1", cache_dir, body, n_requests=4
        )
        second = self._serve_round(
            tmp_path / "r2", cache_dir, body, n_requests=8
        )
        # same pixels in, same mask out, across a process boundary and a
        # compile-vs-deserialize divide
        assert first["mask_pixels"] == second["mask_pixels"]
        assert first["mask_pixels"] > 0

        cold_hub, warm_hub = first["compile_hub"], second["compile_hub"]
        specs = cold_hub["executables"]  # 2 lanes x 1 bucket = 2 AOT specs
        assert specs >= 2
        assert cold_hub["cache_hits"] == 0
        assert cold_hub["builds"] == specs
        assert warm_hub["cache_hits"] == specs
        assert warm_hub["cache_misses"] == 0
        assert warm_hub["builds"] == 0 and warm_hub["cache_loads"] == specs
        assert (
            warm_hub["total_compile_seconds"]
            <= 0.05 * cold_hub["total_compile_seconds"]
        ), (cold_hub, warm_hub)

        for metrics, hits, misses in (
            (first["metrics"], 0, specs),
            (second["metrics"], specs, 0),
        ):
            res = subprocess.run(
                [
                    sys.executable, CHECKER,
                    "--metrics", str(metrics),
                    "--expect-counter", f"compile_cache_hits_total=={hits}",
                    "--expect-counter", f"compile_cache_misses_total=={misses}",
                    "--expect-gauge", "serving_lanes_ready=2",
                ],
                capture_output=True, text=True, timeout=60,
            )
            assert res.returncode == 0, res.stderr

    def _serve_round(self, workdir, cache_dir, body, n_requests):
        workdir.mkdir()
        port_file = workdir / "port"
        metrics = workdir / "metrics.json"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=8",
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("NM03_COMPILE_CACHE_DIR", None)
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(SERVE_CANVAS), "--buckets", "1", "--lanes", "2",
                "--compile-cache-dir", str(cache_dir),
                "--max-wait-ms", "20", "--heartbeat-s", "0",
                "--metrics-out", str(metrics),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            deadline = time.monotonic() + 300
            while not port_file.exists() and time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail(f"server died: {proc.stdout.read()}")
                time.sleep(0.2)
            assert port_file.exists(), "server never became ready"
            base = f"http://127.0.0.1:{int(port_file.read_text())}"
            results = []
            lock = threading.Lock()

            def one():
                req = urllib.request.Request(
                    base + "/v1/segment?output=mask",
                    data=body,
                    headers={
                        "Content-Type": "application/octet-stream",
                        "X-Nm03-Height": str(SERVE_CANVAS),
                        "X-Nm03-Width": str(SERVE_CANVAS),
                    },
                )
                with urllib.request.urlopen(req, timeout=60) as r:
                    payload = json.loads(r.read())
                with lock:
                    results.append((r.status, payload))

            threads = [threading.Thread(target=one) for _ in range(n_requests)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == n_requests
            assert all(s == 200 for s, _ in results)
            pix = {p["mask_pixels"] for _, p in results}
            assert len(pix) == 1  # every rider identical
            with urllib.request.urlopen(base + "/readyz", timeout=10) as r:
                st = json.loads(r.read())
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out
        return {
            "mask_pixels": pix.pop(),
            "compile_hub": st["compile_hub"],
            "metrics": metrics,
        }
