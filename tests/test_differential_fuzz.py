"""Python-vs-native reader differential fuzz.

The two DICOM readers (data/dicomlite.py and csrc/nm03native.cpp) must
AGREE on every input inside their shared envelope: both reject, or both
accept with byte-identical pixel output. Acceptance divergence was a
recurring advisor theme (round-3: the SOS guard existed natively only);
this suite pins the property wholesale instead of per-finding — random
byte corruption and truncation over every shared transfer syntax, with
any disagreement reported as a failure.

(Deflated + baseline-JPEG are Python-reader-only BY DESIGN — the runner
retries native parse failures through the Python reader — so they are not
in the matrix.)

Round-4 exploratory run: 0 divergences / 1,868 trials.
"""

import pathlib
import zlib

import numpy as np
import pytest

GOLDEN = pathlib.Path(__file__).parent / "golden" / "dicom"

pytestmark = pytest.mark.slow  # ~1 min of pure decode churn


@pytest.fixture(scope="module")
def native():
    from nm03_capstone_project_tpu import native as native_mod

    if not native_mod.available():
        pytest.skip("native layer unavailable")
    return native_mod


def _outcome_py(p):
    from nm03_capstone_project_tpu.data.dicomlite import (
        DicomParseError,
        read_dicom,
    )

    try:
        return True, read_dicom(p).pixels
    except (DicomParseError, ValueError) as e:
        return False, str(e)


def _outcome_native(native, p):
    try:
        return True, native.read_dicom_native(p)
    except (ValueError, RuntimeError) as e:
        return False, str(e)


def _agree(native, p, tag):
    py_ok, py = _outcome_py(p)
    nat_ok, nat = _outcome_native(native, p)
    assert py_ok == nat_ok, (
        f"{tag}: acceptance divergence py_ok={py_ok} "
        f"({py if not py_ok else nat})"
    )
    if py_ok:
        np.testing.assert_array_equal(py, nat, err_msg=tag)


BASES = [
    "gdcm16_explicit.dcm",
    "gdcm16_implicit.dcm",
    "gdcm16_bigendian.dcm",
    "gdcm16_mono1.dcm",
    "gdcm16_rle.dcm",
    "gdcm16_jpegll.dcm",
    "charls16_jpegls.dcm",
    "gdcm8_explicit.dcm",
    # round-5 real-archive shapes: odd dims, presentation tags, multi-frame
    # (both readers serve frame 0; the IS NumberOfFrames parse is strictly
    # mirrored so mutated counts reject identically)
    "gdcm16_odd.dcm",
    "gdcm16_odd_jpegll.dcm",
    "gdcm16_window.dcm",
    "gdcm16_multiframe.dcm",
    "gdcm16_multiframe_rle.dcm",
]


@pytest.mark.parametrize("base", BASES)
def test_mutations_never_diverge(native, tmp_path, base):
    raw = (GOLDEN / base).read_bytes()
    # crc32, not hash(): PYTHONHASHSEED randomizes hash() per process and
    # would make any failure unreproducible
    rng = np.random.default_rng(zlib.crc32(base.encode()))
    p = tmp_path / "mut.dcm"
    # stay clear of the transfer-syntax UID (bytes ~272-294 in these
    # files): mutating it swaps envelopes, where the readers differ by
    # design (deflated/baseline are Python-only)
    lo = 300
    uid_at = raw.find(b"1.2.840.10008.1.2", 128)
    assert uid_at != -1 and uid_at + 24 < lo
    for trial in range(60):
        m = bytearray(raw)
        for _ in range(int(rng.integers(1, 6))):
            j = int(rng.integers(lo, len(m)))
            m[j] ^= int(rng.integers(1, 256))
        p.write_bytes(bytes(m))
        _agree(native, p, f"{base} mutation {trial}")


@pytest.mark.parametrize("base", BASES)
def test_truncations_never_diverge(native, tmp_path, base):
    raw = (GOLDEN / base).read_bytes()
    p = tmp_path / "trunc.dcm"
    for n in range(0, len(raw), max(1, len(raw) // 40)):
        p.write_bytes(raw[:n])
        _agree(native, p, f"{base} truncated to {n}")
