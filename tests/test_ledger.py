"""Device-time ledger tests (ISSUE 16).

Unit layer: proration conservation (the three accounts sum to the
executor's busy time exactly), HLO stage-map extraction with fusion
majority vote, Chrome-trace self-time reduction, capture round-trip, and
the ProfileSampler's never-collide-with-a-client-capture contract (the
bugfix regression: a busy profiler lock SKIPS and counts, never queues).

Integration layer: the batcher's charge site stamps every rider's
prorated share and excludes probe canaries from the histogram.

CLI layer: scripts/check_perf.py red/green at both the parse layer (bad
schema/usage -> 2) and the verdict layer (drift -> 1), plus the new
check_telemetry --expect-gauge-sum-range gate.

Acceptance: a live --lanes 4 drill whose post-drain snapshot passes the
ledger gates (request account charged, shares a pie, per-request
histogram observed) and whose artifact check_perf both baselines and
re-gates green/red.
"""

from __future__ import annotations

import base64
import gzip
import io
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
import zipfile
from types import SimpleNamespace

import numpy as np
import pytest

from nm03_capstone_project_tpu.obs.ledger import (
    DeviceTimeLedger,
    ProfileSampler,
    reduce_trace_events,
    stage_for_source,
    stage_map_from_hlo,
    trace_events_from_capture,
)
from nm03_capstone_project_tpu.obs.metrics import (
    LEDGER_PROFILE_SKIPPED_TOTAL,
    MetricsRegistry,
    SERVING_DEVICE_SECONDS_PER_REQUEST,
    SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN,
    SERVING_DEVICE_SECONDS_TOTAL,
    SERVING_DEVICE_TIME_SHARE,
    SERVING_EXECUTABLE_HBM_BYTES,
)
from nm03_capstone_project_tpu.serving.batcher import DynamicBatcher
from nm03_capstone_project_tpu.serving.queue import AdmissionQueue, ServeRequest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CHECK_PERF = os.path.join(REPO, "scripts", "check_perf.py")
CANVAS = 128


# -- proration ---------------------------------------------------------------


class TestProration:
    def test_three_accounts_conserve_busy_exactly(self):
        led = DeviceTimeLedger()
        charged = 0.0
        # mixed chunks: full, padded, probe-carrying, empty-busy
        for busy, rows, real, probes in (
            (2.0, 4, 4, 0),
            (1.5, 4, 2, 1),
            (0.75, 2, 1, 0),
            (0.0, 4, 3, 0),
        ):
            led.charge_chunk(busy, rows, real, probe_rows=probes)
            charged += busy
        snap = led.snapshot()
        assert sum(snap["accounts"].values()) == pytest.approx(
            charged, rel=1e-9
        )
        assert snap["device_seconds_total"] == pytest.approx(
            charged, rel=1e-9
        )

    def test_split_by_account(self):
        led = DeviceTimeLedger()
        # 4 rows at 4.0s busy -> 1.0s/row: 2 real, 1 probe, 1 dead
        share = led.charge_chunk(4.0, 4, 2, probe_rows=1)
        assert share == pytest.approx(1.0)
        snap = led.snapshot()
        assert snap["accounts"]["request"] == pytest.approx(2.0)
        assert snap["accounts"]["probe"] == pytest.approx(1.0)
        assert snap["accounts"]["padding"] == pytest.approx(1.0)

    def test_counters_mirror_accounts(self):
        reg = MetricsRegistry()
        led = DeviceTimeLedger(registry=reg)
        led.charge_chunk(4.0, 4, 2, probe_rows=1)
        for account, want in (("request", 2.0), ("probe", 1.0),
                              ("padding", 1.0)):
            c = reg.get(SERVING_DEVICE_SECONDS_TOTAL, account=account)
            assert c is not None and c.value == pytest.approx(want)

    def test_fallback_chunk_is_an_honest_zero(self):
        # a CPU-fallback chunk accumulated no device busy: share 0.0 and
        # no counter series materializes (0-valued noise helps nobody)
        reg = MetricsRegistry()
        led = DeviceTimeLedger(registry=reg)
        assert led.charge_chunk(0.0, 4, 4) == 0.0
        assert reg.get(SERVING_DEVICE_SECONDS_TOTAL, account="request") is None

    def test_histogram_and_mean_gauge(self):
        reg = MetricsRegistry()
        led = DeviceTimeLedger(registry=reg)
        led.observe_request(0.002)
        led.observe_request(0.004)
        hist = reg.get(SERVING_DEVICE_SECONDS_PER_REQUEST)
        assert hist.count == 2
        assert hist.sum == pytest.approx(0.006)
        led.publish()
        mean = reg.get(SERVING_DEVICE_SECONDS_PER_REQUEST_MEAN)
        assert mean is not None and mean.value == pytest.approx(0.003)
        snap = led.snapshot()
        assert snap["requests"]["count"] == 2
        assert snap["requests"]["device_seconds_mean"] == pytest.approx(
            0.003
        )

    def test_requeued_chunk_busy_accumulates_before_one_charge(self):
        # the contract the executor/batcher pair implements: every dispatch
        # attempt adds onto the chunk trace's device_busy_s, and the single
        # charge at success covers them all — conservation over requeues
        from nm03_capstone_project_tpu.obs.trace import ChunkTrace

        trace = ChunkTrace([], lane=0)
        assert trace.device_busy_s == 0.0
        trace.device_busy_s += 0.5  # attempt 1 (lane quarantined mid-run)
        trace.device_busy_s += 0.3  # attempt 2 (succeeded)
        led = DeviceTimeLedger()
        led.charge_chunk(trace.device_busy_s, 2, 2)
        assert led.snapshot()["accounts"]["request"] == pytest.approx(0.8)


# -- HLO stage map -----------------------------------------------------------


CANNED_HLO = """\
HloModule jit_one

%fused_computation.1 (param_0: f32[4]) -> f32[4] {
  %m1 = f32[4] multiply(%a, %b), metadata={op_name="med" source_file="/x/nm03/ops/pallas_median.py" source_line=1}
  %m2 = f32[4] add(%m1, %b), metadata={op_name="med" source_file="/x/nm03/ops/pallas_median.py" source_line=2}
  %m3 = f32[4] add(%m2, %b), metadata={op_name="glue" source_file="/x/nm03/utils/helpers.py" source_line=3}
}

ENTRY %main.9 (p: f32[4]) -> f32[4] {
  %norm.1 = f32[4] subtract(%p, %p), metadata={op_name="n" source_file="/x/nm03/ops/elementwise.py" source_line=9}
  %fusion.1 = f32[4] fusion(%norm.1), kind=kLoop, calls=%fused_computation.1
  %sharp.2 = f32[4] add(%fusion.1, %p), metadata={op_name="s" source_file="/x/nm03/ops/sharpen.py" source_line=4}
}
"""


class TestStageMap:
    def test_stage_for_source(self):
        assert stage_for_source("/x/ops/pallas_median.py") == "median7"
        assert stage_for_source("ops\\elementwise.py") == "normalize"
        assert stage_for_source("/x/ops/region_growing.py") == "grow"
        assert stage_for_source("/x/ops/morphology.py") == "morph"
        assert stage_for_source("/x/utils/helpers.py") == "other"
        assert stage_for_source("") == "other"

    def test_canned_hlo_plain_and_fusion(self):
        m = stage_map_from_hlo(CANNED_HLO)
        assert m["norm.1"] == "normalize"
        assert m["sharp.2"] == "sharpen"
        # fusion attributed by majority vote over its called computation:
        # 2 median instructions beat 1 "other"
        assert m["fusion.1"] == "median7"

    def test_fusion_of_untagged_body_is_other(self):
        hlo = (
            "%fused_computation.2 (p: f32[4]) -> f32[4] {\n"
            '  %g1 = f32[4] add(%a, %b), metadata={source_file="/x/glue.py"'
            " source_line=1}\n"
            "}\n"
            "ENTRY %main.2 (p: f32[4]) -> f32[4] {\n"
            "  %fusion.2 = f32[4] fusion(%p), kind=kLoop, "
            "calls=%fused_computation.2\n"
            "}\n"
        )
        assert stage_map_from_hlo(hlo)["fusion.2"] == "other"

    def test_empty_and_garbage_are_safe(self):
        assert stage_map_from_hlo("") == {}
        assert stage_map_from_hlo("not hlo at all") == {}


# -- trace reduction ---------------------------------------------------------


def _ev(op, ts, dur, pid=1, tid=1, ph="X", **extra_args):
    args = dict(extra_args)
    if op is not None:
        args["hlo_op"] = op
    return {"ph": ph, "ts": ts, "dur": dur, "pid": pid, "tid": tid,
            "name": op or "host", "args": args}


class TestReduceTrace:
    def test_nested_events_reduce_to_self_time(self):
        stage_of = {"fusion.1": "median7", "norm.1": "normalize"}
        events = [
            _ev("fusion.1", 0.0, 100.0),  # parent
            _ev("norm.1", 10.0, 30.0),  # nested child
        ]
        out = reduce_trace_events(events, stage_of)
        assert out["median7"] == pytest.approx(70e-6)
        assert out["normalize"] == pytest.approx(30e-6)
        assert sum(out.values()) == pytest.approx(100e-6)

    def test_host_and_incomplete_events_excluded(self):
        out = reduce_trace_events(
            [
                _ev(None, 0.0, 50.0),  # host event: no hlo_op
                _ev("x", 0.0, 40.0, ph="B"),  # not a complete event
                _ev("x", 0.0, 0.0),  # zero duration
                _ev("y", 0.0, 10.0),
            ],
            {"y": "grow"},
        )
        assert out == {"grow": pytest.approx(10e-6)}

    def test_threads_reduce_independently(self):
        # same timestamps on different tids must NOT nest across lanes
        events = [
            _ev("a", 0.0, 100.0, tid=1),
            _ev("b", 0.0, 100.0, tid=2),
        ]
        out = reduce_trace_events(events, {"a": "grow", "b": "render"})
        assert out["grow"] == pytest.approx(100e-6)
        assert out["render"] == pytest.approx(100e-6)

    def test_unmapped_ops_land_in_other(self):
        out = reduce_trace_events([_ev("mystery.7", 0.0, 10.0)], {})
        assert out == {"other": pytest.approx(10e-6)}


# -- capture round-trip ------------------------------------------------------


def _canned_capture(events) -> dict:
    """A capture_profile-shaped dict wrapping a gzipped Chrome trace."""
    trace = json.dumps({"traceEvents": events}).encode()
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as zf:
        zf.writestr("plugins/profile/run/host.trace.json.gz",
                    gzip.compress(trace))
        zf.writestr("plugins/profile/run/unrelated.pb", b"\x00")
    return {
        "duration_ms": 100,
        "zip_b64": base64.b64encode(buf.getvalue()).decode(),
        "zip_bytes": buf.tell(),
    }


class TestCaptureRoundTrip:
    def test_zip_b64_round_trip(self):
        cap = _canned_capture([_ev("a", 0.0, 5.0)])
        events = trace_events_from_capture(cap)
        assert len(events) == 1 and events[0]["args"]["hlo_op"] == "a"

    def test_zip_path_round_trip(self, tmp_path):
        cap = _canned_capture([_ev("a", 0.0, 5.0)])
        p = tmp_path / "capture.zip"
        p.write_bytes(base64.b64decode(cap.pop("zip_b64")))
        cap["zip_path"] = str(p)
        cap["zip_dropped"] = True
        assert len(trace_events_from_capture(cap)) == 1

    def test_empty_capture_is_no_events(self):
        assert trace_events_from_capture({"duration_ms": 50}) == []

    def test_ingest_capture_publishes_share_gauges(self):
        reg = MetricsRegistry()
        led = DeviceTimeLedger(registry=reg)
        led.ingest_hlo(CANNED_HLO)
        led.ingest_capture(
            _canned_capture(
                [_ev("fusion.1", 0.0, 60.0), _ev("sharp.2", 60.0, 40.0)]
            )
        )
        snap = led.publish()
        assert snap["stage_shares"] == {"median7": 0.6, "sharpen": 0.4}
        assert snap["profile_samples"]["taken"] == 1
        g = reg.get(SERVING_DEVICE_TIME_SHARE, stage="median7")
        assert g is not None and g.value == pytest.approx(0.6)
        # shares are a pie: sum <= 1 (the sum-range gate's invariant)
        assert sum(snap["stage_shares"].values()) <= 1.0 + 1e-9

    def test_shares_smooth_across_samples(self):
        led = DeviceTimeLedger()
        led.ingest_hlo(CANNED_HLO)
        led.ingest_capture(_canned_capture([_ev("fusion.1", 0.0, 100.0)]))
        led.ingest_capture(_canned_capture([_ev("sharp.2", 0.0, 100.0)]))
        snap = led.snapshot()
        # cumulative across samples, not last-sample-wins
        assert snap["stage_shares"] == {"median7": 0.5, "sharpen": 0.5}
        assert snap["profile_samples"]["taken"] == 2


# -- HBM ledger --------------------------------------------------------------


class TestHbmLedger:
    def test_per_bucket_kinds_published(self):
        reg = MetricsRegistry()
        led = DeviceTimeLedger(registry=reg)
        led.set_bucket_hbm(1, {
            "argument_bytes": 1000, "output_bytes": 500,
            "peak_hbm_bytes": 4096, "generated_code_size_in_bytes": 7,
        })
        led.set_bucket_hbm(8, {"peak_hbm_bytes": 9999})
        led.set_bucket_hbm(16, None)  # jaxlib without memory_analysis
        led.set_bucket_hbm(32, {"unrelated": 3})
        snap = led.publish()
        assert snap["hbm_bytes"] == {
            1: {"argument": 1000, "output": 500, "peak": 4096},
            8: {"peak": 9999},
        }
        g = reg.get(SERVING_EXECUTABLE_HBM_BYTES, bucket="1", kind="peak")
        assert g is not None and g.value == 4096
        assert reg.get(
            SERVING_EXECUTABLE_HBM_BYTES, bucket="16", kind="peak"
        ) is None


# -- the sampler's never-collide contract (the ISSUE 16 bugfix) --------------


class TestProfileSampler:
    def test_busy_lock_skips_and_counts_never_queues(self):
        # the regression: an operator's GET /debug/profile holds the
        # process-global capture lock; the cadence sampler must skip (and
        # count) — never block, never queue behind the client's capture
        from nm03_capstone_project_tpu.utils import profiling

        reg = MetricsRegistry()
        led = DeviceTimeLedger(registry=reg)
        sampler = ProfileSampler(led, interval_s=0.0, duration_ms=50)
        assert profiling._CAPTURE_LOCK.acquire(blocking=False)
        try:
            t0 = time.monotonic()
            assert sampler.sample_once() is False
            assert sampler.sample_once() is False
            # skipping is immediate — a sampler that WAITED for the lock
            # would sit here for the client capture's full duration
            assert time.monotonic() - t0 < 1.0
        finally:
            profiling._CAPTURE_LOCK.release()
        snap = led.snapshot()
        assert snap["profile_samples"] == {"taken": 0, "skipped": 2}
        c = reg.get(LEDGER_PROFILE_SKIPPED_TOTAL)
        assert c is not None and c.value == 2

    def test_capture_failure_is_swallowed_not_counted_as_skip(self):
        led = DeviceTimeLedger()

        def broken(_ms):
            raise RuntimeError("profiler exploded")

        sampler = ProfileSampler(led, interval_s=0.0, capture=broken)
        assert sampler.sample_once() is False
        assert led.snapshot()["profile_samples"] == {
            "taken": 0, "skipped": 0,
        }

    def test_injected_capture_lands_in_ledger(self):
        led = DeviceTimeLedger()
        led.ingest_hlo(CANNED_HLO)
        sampler = ProfileSampler(
            led, interval_s=0.0,
            capture=lambda ms: _canned_capture([_ev("norm.1", 0.0, 10.0)]),
        )
        assert sampler.sample_once() is True
        snap = led.snapshot()
        assert snap["profile_samples"]["taken"] == 1
        assert snap["stage_shares"] == {"normalize": 1.0}

    def test_zero_interval_never_starts_a_thread(self):
        sampler = ProfileSampler(DeviceTimeLedger(), interval_s=0.0)
        sampler.start()
        assert sampler._thread is None
        sampler.stop()


# -- batcher integration -----------------------------------------------------


class FakeLedgerExecutor:
    """Lane-aware, trace-aware executor stand-in carrying a real ledger."""

    supports_trace = True
    BUSY_PER_DISPATCH = 0.5

    def __init__(self, buckets=(4,), lanes=1, canvas=16, min_dim=4):
        self.cfg = SimpleNamespace(canvas=canvas, min_dim=min_dim)
        self.buckets = tuple(buckets)
        self.lane_count = lanes
        self.registry = MetricsRegistry()
        self.ledger = DeviceTimeLedger(registry=self.registry)

    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(n)

    def run_batch(self, pixels, dims, lane=0, trace=None):
        if trace is not None and hasattr(trace, "device_busy_s"):
            trace.device_busy_s += self.BUSY_PER_DISPATCH
        mask = (pixels > 0).astype(np.uint8)
        return mask, np.ones(pixels.shape[0], bool)


def _reqs(n, hw=16, probes=0):
    return [
        ServeRequest(
            request_id=f"r{i}",
            pixels=np.ones((hw, hw), np.float32),
            dims=(hw, hw),
            probe=i < probes,
        )
        for i in range(n)
    ]


class TestBatcherLedger:
    def test_chunk_charge_stamps_riders_and_skips_probe_histogram(self):
        ex = FakeLedgerExecutor(buckets=(4,), lanes=1)
        b = DynamicBatcher(AdmissionQueue(8), ex, max_wait_s=0.0)
        reqs = _reqs(3, probes=1)  # 3 riders pad into bucket 4, one canary
        b.execute(reqs)
        # 0.5s busy over 4 rows -> 0.125/row: 2 real, 1 probe, 1 dead
        snap = ex.ledger.snapshot()
        assert snap["accounts"]["request"] == pytest.approx(0.25)
        assert snap["accounts"]["probe"] == pytest.approx(0.125)
        assert snap["accounts"]["padding"] == pytest.approx(0.125)
        assert sum(snap["accounts"].values()) == pytest.approx(
            ex.BUSY_PER_DISPATCH, rel=1e-9
        )
        # every rider (canary included) carries its prorated cost...
        assert all(
            r.device_seconds == pytest.approx(0.125) for r in reqs
        )
        # ...but only non-probes land in the per-request histogram
        hist = ex.registry.get(SERVING_DEVICE_SECONDS_PER_REQUEST)
        assert hist.count == 2
        assert hist.sum == pytest.approx(0.25)

    def test_ledgerless_executor_keeps_working(self):
        # the ledger is strictly opt-in, like the saturation monitor
        class Bare:
            def __init__(self):
                self.cfg = SimpleNamespace(canvas=16, min_dim=4)
                self.buckets = (4,)
                self.max_batch = 4

            def bucket_for(self, n):
                return 4

            def run_batch(self, pixels, dims):
                return (pixels > 0).astype(np.uint8), np.ones(
                    pixels.shape[0], bool
                )

        b = DynamicBatcher(AdmissionQueue(8), Bare(), max_wait_s=0.0)
        reqs = _reqs(3)
        b.execute(reqs)  # must simply not raise
        assert all(r.device_seconds == 0.0 for r in reqs)


# -- check_perf CLI: red/green at parse and verdict layers -------------------


def _snapshot(path, metrics):
    path.write_text(json.dumps({
        "schema": "nm03.metrics.v1", "created_unix": 1.0,
        "run_id": "r", "git_sha": "s", "metrics": metrics,
    }))


def _ledger_metrics(mean=0.005, count=10, shares=None):
    shares = {"median7": 0.6, "normalize": 0.35} if shares is None else shares
    out = [{
        "name": "serving_device_seconds_per_request", "type": "histogram",
        "labels": {}, "count": count, "sum": mean * count,
        "buckets": [["+Inf", count]],
    }]
    for st, v in shares.items():
        out.append({
            "name": "serving_device_time_share", "type": "gauge",
            "labels": {"stage": st}, "value": v,
        })
    return out


def _run_check_perf(*args):
    return subprocess.run(
        [sys.executable, CHECK_PERF, *args],
        capture_output=True, text=True, timeout=60,
    )


class TestCheckPerfCLI:
    def test_write_then_gate_green(self, tmp_path):
        snap = tmp_path / "m.json"
        base = tmp_path / "base.json"
        _snapshot(snap, _ledger_metrics())
        w = _run_check_perf(
            "--metrics", str(snap), "--write-baseline", str(base)
        )
        assert w.returncode == 0, w.stderr
        doc = json.loads(base.read_text())
        assert doc["schema"] == "nm03.perf_baseline.v1"
        assert doc["device_seconds_per_slice"] == pytest.approx(0.005)
        g = _run_check_perf(
            "--metrics", str(snap), "--baseline", str(base)
        )
        assert g.returncode == 0, g.stderr
        assert "OK" in g.stdout

    def test_perturbed_share_trips_red(self, tmp_path):
        snap = tmp_path / "m.json"
        base = tmp_path / "base.json"
        _snapshot(snap, _ledger_metrics())
        _run_check_perf("--metrics", str(snap), "--write-baseline", str(base))
        doc = json.loads(base.read_text())
        doc["stage_shares"]["median7"] = 0.1  # "the median used to be 10%"
        base.write_text(json.dumps(doc))
        r = _run_check_perf("--metrics", str(snap), "--baseline", str(base))
        assert r.returncode == 1
        assert "PERF DRIFT stage_shares[median7]" in r.stderr

    def test_device_seconds_ratio_trips_both_directions(self, tmp_path):
        snap = tmp_path / "m.json"
        _snapshot(snap, _ledger_metrics(mean=0.005))
        for slow_or_fast in (0.0005, 0.05):  # 10x either way vs rel=4.0
            base = tmp_path / "base.json"
            base.write_text(json.dumps({
                "schema": "nm03.perf_baseline.v1",
                "device_seconds_per_slice": slow_or_fast,
                "stage_shares": {},
                "tolerance": {"device_seconds_rel": 4.0,
                              "stage_share_abs": 0.25},
                "min_share": 0.05,
            }))
            r = _run_check_perf(
                "--metrics", str(snap), "--baseline", str(base)
            )
            assert r.returncode == 1, (slow_or_fast, r.stderr)
            assert "PERF DRIFT device_seconds" in r.stderr

    def test_tiny_baseline_shares_are_not_gated(self, tmp_path):
        snap = tmp_path / "m.json"
        base = tmp_path / "base.json"
        # observed carries no "grow" at all; baseline's 1% grow is under
        # the min_share floor, so its absence must not trip
        _snapshot(snap, _ledger_metrics(shares={"median7": 0.99}))
        base.write_text(json.dumps({
            "schema": "nm03.perf_baseline.v1",
            "device_seconds_per_slice": None,
            "stage_shares": {"median7": 0.98, "grow": 0.01},
            "tolerance": {"device_seconds_rel": 4.0,
                          "stage_share_abs": 0.25},
            "min_share": 0.05,
        }))
        r = _run_check_perf("--metrics", str(snap), "--baseline", str(base))
        assert r.returncode == 0, r.stderr

    def test_missing_shares_fail_not_vacuously_pass(self, tmp_path):
        snap = tmp_path / "m.json"
        base = tmp_path / "base.json"
        _snapshot(snap, _ledger_metrics(shares={}))
        base.write_text(json.dumps({
            "schema": "nm03.perf_baseline.v1",
            "device_seconds_per_slice": 0.005,
            "stage_shares": {"median7": 0.6},
            "tolerance": {"device_seconds_rel": 4.0,
                          "stage_share_abs": 0.25},
            "min_share": 0.05,
        }))
        r = _run_check_perf("--metrics", str(snap), "--baseline", str(base))
        assert r.returncode == 1
        assert "never reduced a capture" in r.stderr

    def test_parse_layer_usage_errors(self, tmp_path):
        snap = tmp_path / "m.json"
        _snapshot(snap, [])
        # exactly one of --baseline/--write-baseline
        assert _run_check_perf("--metrics", str(snap)).returncode == 2
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"schema": "nm03.perf_baseline.v1"}))
        assert _run_check_perf(
            "--metrics", str(snap), "--baseline", str(base),
            "--write-baseline", str(tmp_path / "x.json"),
        ).returncode == 2
        # wrong metrics schema
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"schema": "nope", "metrics": []}))
        assert _run_check_perf(
            "--metrics", str(bad), "--baseline", str(base)
        ).returncode == 2
        # wrong baseline schema
        badbase = tmp_path / "badbase.json"
        badbase.write_text(json.dumps({"schema": "nope"}))
        assert _run_check_perf(
            "--metrics", str(snap), "--baseline", str(badbase)
        ).returncode == 2
        # unreadable artifacts
        assert _run_check_perf(
            "--metrics", str(tmp_path / "absent.json"),
            "--baseline", str(base),
        ).returncode == 2
        # nothing to baseline from an empty snapshot
        assert _run_check_perf(
            "--metrics", str(snap),
            "--write-baseline", str(tmp_path / "y.json"),
        ).returncode == 2


class TestCheckTelemetrySumRange:
    def _run(self, snap, *args):
        return subprocess.run(
            [sys.executable, CHECKER, "--metrics", str(snap), *args],
            capture_output=True, text=True, timeout=60,
        )

    def test_pie_sum_green_red_and_absent(self, tmp_path):
        snap = tmp_path / "m.json"
        _snapshot(snap, [
            {"name": "serving_device_time_share", "type": "gauge",
             "labels": {"stage": s}, "value": v}
            for s, v in (("median7", 0.6), ("normalize", 0.35))
        ])
        ok = self._run(snap, "--expect-gauge-sum-range",
                       "serving_device_time_share=(0..1]")
        assert ok.returncode == 0, ok.stderr
        red = self._run(snap, "--expect-gauge-sum-range",
                        "serving_device_time_share=(0..0.5]")
        assert red.returncode == 1
        assert "sums to 0.95" in red.stderr
        absent = self._run(snap, "--expect-gauge-sum-range",
                           "not_a_series=(0..1]")
        assert absent.returncode == 1
        assert "absent" in absent.stderr

    def test_usage_errors(self, tmp_path):
        snap = tmp_path / "m.json"
        _snapshot(snap, [])
        bad = self._run(snap, "--expect-gauge-sum-range", "name=zz")
        assert bad.returncode == 2
        no_metrics = subprocess.run(
            [sys.executable, CHECKER,
             "--expect-gauge-sum-range", "name=0..1"],
            capture_output=True, text=True, timeout=60,
        )
        assert no_metrics.returncode == 2


# -- acceptance: the live drill ----------------------------------------------


class TestLedgerAcceptance:
    @pytest.mark.slow
    def test_drill_charges_profiles_and_gates(self, tmp_path):
        """The ISSUE 16 acceptance bar: a 4-lane replica under load charges
        real riders to the ``request`` account, lands every request in the
        per-request histogram (echoed in the payload and in nm03-loadgen's
        ``device_seconds_p50/p95``), samples a live stage pie whose shares
        sum to <= 1, and passes check_perf both ways (fresh baseline
        green, perturbed share red) on the post-drain snapshot.
        """
        port_file = tmp_path / "port"
        metrics = tmp_path / "metrics.json"
        results = tmp_path / "loadgen.json"
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
        )
        env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [
                sys.executable, "-m",
                "nm03_capstone_project_tpu.serving.server",
                "--device", "cpu", "--port", "0",
                "--port-file", str(port_file),
                "--canvas", str(CANVAS), "--buckets", "1,2", "--lanes", "4",
                "--max-wait-ms", "60", "--heartbeat-s", "0",
                "--ledger-profile-interval-s", "0.4",
                "--ledger-profile-ms", "250",
                "--metrics-out", str(metrics),
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        try:
            deadline = time.monotonic() + 300
            while not port_file.exists() and time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail(f"server died: {proc.stdout.read()}")
                time.sleep(0.2)
            assert port_file.exists(), "server never became ready"
            base = f"http://127.0.0.1:{int(port_file.read_text())}"

            def loadgen(n):
                return subprocess.run(
                    [
                        sys.executable, "-m",
                        "nm03_capstone_project_tpu.serving.loadgen",
                        "--url", base, "--requests", str(n),
                        "--concurrency", "8", "--mode", "mask",
                        "--height", str(CANVAS), "--width", str(CANVAS),
                        "--warmup", "4", "--results-json", str(results),
                    ],
                    capture_output=True, text=True, timeout=300, cwd=REPO,
                )

            lg = loadgen(32)
            assert lg.returncode == 0, lg.stdout + lg.stderr
            summary = json.loads(results.read_text())
            assert summary["requests_ok"] == 32
            # the payload echo, client-side: every ok request billed > 0
            ds = summary.get("device_seconds_ms")
            assert ds is not None, "no device_seconds in any payload"
            assert ds["p50"] > 0 and ds["p95"] >= ds["p50"]
            assert "device_seconds_p50=" in lg.stdout
            recs = json.loads(results.read_text())["requests"]
            assert all(
                r["device_seconds"] > 0
                for r in recs if r["status"] == "ok"
            )

            # the pie needs a capture that OVERLAPPED traffic; drive small
            # bursts until the sampler lands one (bounded — the 0.4 s
            # cadence makes the first overlapping capture near-certain)
            def live_shares():
                with urllib.request.urlopen(
                    f"{base}/metrics.json", timeout=10
                ) as resp:
                    doc = json.loads(resp.read())
                return {
                    rec["labels"]["stage"]: rec["value"]
                    for rec in doc["metrics"]
                    if rec["name"] == "serving_device_time_share"
                }
            shares = live_shares()
            for _ in range(6):
                if shares:
                    break
                assert loadgen(16).returncode == 0
                time.sleep(1.0)
                shares = live_shares()
            assert shares, "profile sampler never landed a capture"
            # the acceptance pin: on this container the median network
            # dominates device time — the pie must say so
            assert shares.get("median7", 0.0) > 0.0
            assert sum(shares.values()) <= 1.0 + 1e-6

            # nm03-top renders the pie + ds/req column from the gauges
            tp = subprocess.run(
                [
                    sys.executable, "-m",
                    "nm03_capstone_project_tpu.serving.top",
                    "--url", base, "--once", "--format", "json",
                ],
                capture_output=True, text=True, timeout=60, cwd=REPO,
            )
            assert tp.returncode == 0, tp.stdout + tp.stderr
            view = json.loads(tp.stdout)
            assert view["device_time_share"], view
            assert view["device_time_share"].get("median7", 0) > 0
            assert view["device_seconds_per_request"] > 0
            proc.send_signal(signal.SIGTERM)
            out, _ = proc.communicate(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=30)
        assert proc.returncode == 0, out

        # post-drain gates: request account charged, pie sums to a pie,
        # every request in the histogram
        gates = [
            sys.executable, CHECKER,
            "--metrics", str(metrics),
            "--expect-counter",
            "serving_device_seconds_total{account=request}=0.000001",
            "--expect-histogram", "serving_device_seconds_per_request=32",
            "--expect-gauge-sum-range", "serving_device_time_share=(0..1]",
            "--expect-gauge-range",
            "serving_device_seconds_per_request_mean=(0..30]",
        ]
        snap_doc = json.loads(metrics.read_text())
        series = {m["name"] for m in snap_doc["metrics"]}
        if "executable_hbm_bytes" in series:
            # this jaxlib exposes memory_analysis (the compile-hub series
            # is present): the ledger's per-bucket twin must be too
            for bucket in ("1", "2"):
                gates += [
                    "--expect-gauge-range",
                    "serving_executable_hbm_bytes"
                    f"{{bucket={bucket},kind=peak}}=(0..1e15]",
                ]
        res = subprocess.run(
            gates, capture_output=True, text=True, timeout=60,
        )
        assert res.returncode == 0, res.stderr

        # conservation: the histogram's sum (per-rider stamps) must agree
        # with the request account (per-chunk charges) within 1%
        req_account = sum(
            m["value"] for m in snap_doc["metrics"]
            if m["name"] == "serving_device_seconds_total"
            and m["labels"].get("account") == "request"
        )
        hist_sum = sum(
            m["sum"] for m in snap_doc["metrics"]
            if m["name"] == "serving_device_seconds_per_request"
        )
        assert req_account > 0
        assert hist_sum == pytest.approx(req_account, rel=0.01)

        # check_perf joins the drill: fresh baseline green, perturbed red
        fresh = tmp_path / "fresh_baseline.json"
        w = _run_check_perf(
            "--metrics", str(metrics), "--write-baseline", str(fresh)
        )
        assert w.returncode == 0, w.stderr
        g = _run_check_perf(
            "--metrics", str(metrics), "--baseline", str(fresh)
        )
        assert g.returncode == 0, g.stderr
        doc = json.loads(fresh.read_text())
        # perturb the dominant stage far outside the band (upward, so the
        # perturbed share always stays above the min_share gating floor)
        top_stage = max(doc["stage_shares"], key=doc["stage_shares"].get)
        doc["stage_shares"][top_stage] += 0.5
        bad = tmp_path / "bad_baseline.json"
        bad.write_text(json.dumps(doc))
        r = _run_check_perf(
            "--metrics", str(metrics), "--baseline", str(bad)
        )
        assert r.returncode == 1, r.stdout + r.stderr
        assert "PERF DRIFT" in r.stderr

        # the committed tripwire baseline gates this very drill
        committed = os.path.join(REPO, "PERF_BASELINE.json")
        assert os.path.exists(committed), "PERF_BASELINE.json not committed"
        c = _run_check_perf(
            "--metrics", str(metrics), "--baseline", committed
        )
        assert c.returncode == 0, c.stderr
