"""Multi-process cohort processing through the REAL parallel driver.

Extends tests/test_multihost.py's pattern to the flagship CLI path: two OS
processes (4 virtual CPU devices each) join one jax.distributed job, split a
shared synthetic cohort round-robin, process their patients on their local
device meshes, and allgather the summary over the (simulated) DCN. Asserts
the partition is disjoint+complete, every JPEG pair exists, and rank 0's
results JSON carries the cluster-wide totals.
"""

import json
import textwrap
from pathlib import Path
import pytest


pytestmark = [pytest.mark.slow, pytest.mark.multiproc]


from tests.test_multihost import run_job_with_port_retry

_REPO = Path(__file__).parents[1]

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    cohort, outdir = sys.argv[4], sys.argv[5]

    from nm03_capstone_project_tpu.cli import parallel

    if cohort == "@synthetic":
        cohort_args = ["--synthetic", "3", "--synthetic-slices", "4"]
    else:
        cohort_args = ["--base-path", cohort]
    rc = parallel.main([
        *cohort_args,
        "--output", outdir,
        "--results-json", os.path.join(outdir, "results.json"),
        "--distributed",
        "--coordinator-address", f"127.0.0.1:{{port}}",
        "--num-processes", str(nproc),
        "--process-id", str(pid),
        "--canvas", "128", "--render-size", "128",
    ])
    assert rc == 0, f"driver rc={{rc}}"
    print(f"DCOK {{pid}}", flush=True)
    """
).format(repo=str(_REPO))


_VOL_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    cohort, outdir = sys.argv[4], sys.argv[5]

    from nm03_capstone_project_tpu.cli import volume

    rc = volume.main([
        "--base-path", cohort,
        "--output", outdir,
        "--results-json", os.path.join(outdir, "results.json"),
        "--z-shard",
        "--distributed",
        "--coordinator-address", f"127.0.0.1:{{port}}",
        "--num-processes", str(nproc),
        "--process-id", str(pid),
        "--canvas", "128", "--render-size", "128",
    ])
    assert rc == 0, f"volume driver rc={{rc}}"
    print(f"VGOK {{pid}}", flush=True)
    """
).format(repo=str(_REPO))


_VOL_FAIL_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    cohort, outdir = sys.argv[4], sys.argv[5]

    # Inject an export failure for PGBM-0001: only the exporting rank ever
    # calls render_export_pairs, so this fires on rank 0 alone — the exact
    # rank-0-only failure that must reach the outcome collective (ADVICE r2)
    import nm03_capstone_project_tpu.render.export as export_mod
    real = export_mod.render_export_pairs
    def failing(items, out_dir, cfg, max_workers=4):
        if "PGBM-0001" in str(out_dir):
            raise IOError("injected export failure")
        return real(items, out_dir, cfg, max_workers)
    export_mod.render_export_pairs = failing

    from nm03_capstone_project_tpu.cli import volume

    rc = volume.main([
        "--base-path", cohort,
        "--output", outdir,
        "--z-shard",
        "--distributed",
        "--coordinator-address", f"127.0.0.1:{{port}}",
        "--num-processes", str(nproc),
        "--process-id", str(pid),
        "--canvas", "128", "--render-size", "128",
    ])
    # BOTH ranks must agree the cohort partially failed (rc 1): before the
    # round-3 export-outcome collective, non-exporting ranks counted the
    # patient ok and exited 0 while rank 0 exited 1
    assert rc == 1, f"rank {{pid}} rc={{rc}} (want 1)"
    print(f"VFOK {{pid}}", flush=True)
    """
).format(repo=str(_REPO))


_TRAIN_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    cohort, outdir = sys.argv[4], sys.argv[5]

    from nm03_capstone_project_tpu.cli import train

    rc = train.main([
        "--base-path", cohort,
        "--output", outdir,
        "--results-json", os.path.join(outdir, "train.json"),
        "--distributed",
        "--coordinator-address", f"127.0.0.1:{{port}}",
        "--num-processes", str(nproc),
        "--process-id", str(pid),
        "--canvas", "128",
        "--steps", "12", "--base-channels", "8",
    ])
    assert rc == 0, f"train driver rc={{rc}}"
    print(f"TROK {{pid}}", flush=True)
    """
).format(repo=str(_REPO))


class TestDistributedCohort:
    def test_two_process_cohort_partitions_and_aggregates(self, tmp_path):
        from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

        cohort = tmp_path / "cohort"
        write_synthetic_cohort(
            cohort, n_patients=3, n_slices=4, height=128, width=120
        )
        outdir = tmp_path / "out"
        script = tmp_path / "dc_worker.py"
        script.write_text(_WORKER)
        nproc = 2
        outs = run_job_with_port_retry(
            script, tmp_path, nproc, extra_args=[str(cohort), str(outdir)]
        )
        for pid in range(nproc):
            assert f"DCOK {pid}" in outs[pid]

        # every patient exported by exactly one process; all pairs present
        patients = sorted(p.name for p in outdir.iterdir() if p.name.startswith("PGBM"))
        assert len(patients) == 3
        for p in patients:
            jpgs = sorted((outdir / p).glob("*.jpg"))
            assert len(jpgs) == 8, (p, jpgs)

        # rank manifests are disjoint and together cover the cohort
        m0 = json.loads((outdir / "manifest.rank0.json").read_text())
        m1 = json.loads((outdir / "manifest.rank1.json").read_text())
        assert set(m0) & set(m1) == set()
        assert sorted(set(m0) | set(m1)) == patients

        # rank 0 wrote the aggregated record
        rec = json.loads((outdir / "results.json").read_text())
        assert rec["process_count"] == 2
        assert rec["cluster"]["patients_ok"] == 3
        assert rec["cluster"]["slices_ok"] == 12
        # per-process split is 2 + 1 patients
        per = rec["cluster"]["per_process"]
        assert sorted(v["patients_total"] for v in per.values()) == [1, 2]

    def test_volume_global_zshard_spans_both_processes(self, tmp_path):
        # --z-shard --distributed: every volume's z axis spans the GLOBAL
        # 8-device set (4 per process) and the halo exchange crosses the
        # process boundary; rank 0 exports
        from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

        cohort = tmp_path / "cohort"
        write_synthetic_cohort(
            cohort, n_patients=2, n_slices=4, height=128, width=120
        )
        outdir = tmp_path / "out"
        script = tmp_path / "vg_worker.py"
        script.write_text(_VOL_WORKER)
        outs = run_job_with_port_retry(
            script, tmp_path, 2, extra_args=[str(cohort), str(outdir)]
        )
        for pid in range(2):
            assert f"VGOK {pid}" in outs[pid]
        # rank 0 exported every patient's full pair set exactly once
        for p in ("PGBM-0001", "PGBM-0002"):
            assert len(sorted((outdir / p).glob("*.jpg"))) == 8, p
        rec = json.loads((outdir / "results.json").read_text())
        assert rec["z_sharded"] is True and rec["z_global"] is True
        assert len(rec["patients"]) == 2
        assert all(v["mask_voxels"] > 0 for v in rec["patients"].values())

    def test_volume_zshard_export_failure_agrees_across_ranks(self, tmp_path):
        # rank 0's export crashes for one patient; the outcome collective
        # must (a) keep later patients' collectives paired — patient 2 still
        # exports fully — and (b) give every rank the same rc=1
        from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

        cohort = tmp_path / "cohort"
        write_synthetic_cohort(
            cohort, n_patients=2, n_slices=4, height=128, width=120
        )
        outdir = tmp_path / "out"
        script = tmp_path / "vf_worker.py"
        script.write_text(_VOL_FAIL_WORKER)
        outs = run_job_with_port_retry(
            script, tmp_path, 2, extra_args=[str(cohort), str(outdir)]
        )
        for pid in range(2):
            assert f"VFOK {pid}" in outs[pid]
        # the failed patient exported nothing; the next one is complete —
        # proof the collectives stayed paired after the rank-0-only failure
        assert list((outdir / "PGBM-0001").glob("*.jpg")) == []
        assert len(list((outdir / "PGBM-0002").glob("*.jpg"))) == 8

    def test_distributed_training_across_two_processes(self, tmp_path):
        # dp training over 2 hosts x 4 devices: shards distilled locally,
        # one global batch, gradients psummed over the global data axis,
        # rank 0 writes the checkpoint + aggregated IoU
        from nm03_capstone_project_tpu.data.synthetic import write_synthetic_cohort

        cohort = tmp_path / "cohort"
        write_synthetic_cohort(
            cohort, n_patients=2, n_slices=5, height=128, width=120
        )
        outdir = tmp_path / "out"
        script = tmp_path / "tr_worker.py"
        script.write_text(_TRAIN_WORKER)
        outs = run_job_with_port_retry(
            script, tmp_path, 2, extra_args=[str(cohort), str(outdir)]
        )
        for pid in range(2):
            assert f"TROK {pid}" in outs[pid]
        assert (outdir / "checkpoint").exists()
        rec = json.loads((outdir / "train.json").read_text())
        assert rec["slices"] == 10  # both ranks' shards scored + aggregated
        assert rec["final_loss"] is not None
        assert 0.0 <= rec["iou_vs_teacher"] <= 1.0

    def test_synthetic_cohort_generated_once_behind_barrier(self, tmp_path):
        # rank 0 generates the shared synthetic cohort; rank 1 must wait at
        # the barrier instead of listing a half-written tree
        outdir = tmp_path / "out"
        script = tmp_path / "dc_worker.py"
        script.write_text(_WORKER)
        outs = run_job_with_port_retry(
            script, tmp_path, 2, extra_args=["@synthetic", str(outdir)]
        )
        for pid in range(2):
            assert f"DCOK {pid}" in outs[pid]
        rec = json.loads((outdir / "results.json").read_text())
        assert rec["cluster"]["patients_ok"] == 3
        assert rec["cluster"]["slices_ok"] == 12
