"""Streaming-ingest subsystem tests (ISSUE 11).

Layers, mirroring the other subsystem test files:

* jax-free units: the staging ring (ordering, backpressure, occupancy
  with an injected clock, finish/close semantics) and the pipeline
  (ordered delivery under concurrent decode, per-item failure
  containment, reference release for donation, stats/overlap math,
  abort propagation);
* the ingest fault site (``decode_error``/``stall``) at the pipeline
  level and as chaos drills through BOTH batch drivers;
* staging helpers (jax): ``stage_batch`` host-ref preservation and the
  absorbed ``prefetch_to_device`` generator (retired ``data/prefetch.py``);
* driver integration (in-process): both drivers report the ``ingest``
  record + gauges + ``ingest_drained`` event, and ``--sanitize`` runs
  green through the new staging path (transfer guard armed);
* the subprocess acceptance drill: ``nm03-parallel`` on a synthetic
  cohort, gated by ``check_telemetry.py --expect-gauge-range
  pipeline_feed_stall_ratio=[0..0.15]`` plus the ingest gauges.
"""

from __future__ import annotations

import gc
import json
import os
import subprocess
import sys
import threading
import time
import weakref

import pytest

from nm03_capstone_project_tpu.ingest import (
    IngestFailure,
    IngestPipeline,
    RingClosed,
    RingFinished,
    StagingRing,
)
from nm03_capstone_project_tpu.resilience import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO, "scripts", "check_telemetry.py")
CANVAS = 128


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


# -- staging ring ------------------------------------------------------------


class TestStagingRing:
    def test_fifo_order_and_counts(self):
        r = StagingRing(4)
        for i in range(4):
            r.put(i)
        assert [r.get() for _ in range(4)] == [0, 1, 2, 3]
        s = r.stats()
        assert s["puts"] == 4 and s["gets"] == 4 and s["depth"] == 0
        assert s["peak"] == 4

    def test_put_blocks_when_full_until_get(self):
        r = StagingRing(1)
        r.put("a")
        landed = threading.Event()

        def producer():
            r.put("b")  # must block until the consumer frees the slot
            landed.set()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.05)
        assert not landed.is_set(), "put returned while the ring was full"
        assert r.get() == "a"
        t.join(timeout=5)
        assert landed.is_set() and r.get() == "b"

    def test_put_timeout(self):
        r = StagingRing(1)
        r.put(1)
        with pytest.raises(TimeoutError):
            r.put(2, timeout=0.05)

    def test_get_timeout(self):
        with pytest.raises(TimeoutError):
            StagingRing(1).get(timeout=0.05)

    def test_finish_drains_then_raises(self):
        r = StagingRing(2)
        r.put(1)
        r.finish()
        assert r.get() == 1
        with pytest.raises(RingFinished):
            r.get()
        with pytest.raises(RingClosed):
            r.put(2)  # finished ring takes no more items

    def test_close_wakes_blocked_producer(self):
        r = StagingRing(1)
        r.put(1)
        errs = []

        def producer():
            try:
                r.put(2)
            except RingClosed as e:
                errs.append(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.05)
        r.close()
        t.join(timeout=5)
        assert len(errs) == 1
        with pytest.raises(RingClosed):
            r.get()

    def test_occupancy_is_time_weighted(self):
        clk = FakeClock()
        r = StagingRing(2, clock=clk)
        clk.advance(1.0)  # 1 s empty
        r.put("a")
        clk.advance(1.0)  # 1 s at depth 1
        r.put("b")
        clk.advance(2.0)  # 2 s at depth 2
        # integral = 0*1 + 1*1 + 2*2 = 5 over 4 s * capacity 2 = 0.625
        assert r.occupancy_ratio() == pytest.approx(0.625)

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            StagingRing(0)


# -- pipeline (jax-free) -----------------------------------------------------


class Token:
    """weakref-able sentinel standing in for a staged device buffer."""


class TestIngestPipeline:
    def test_ordered_delivery_under_concurrent_decode(self):
        def dec(i):
            time.sleep((i % 3) * 0.01)  # out-of-order completion
            return i * 10

        with IngestPipeline(
            source=range(12), decode=dec, depth=3, decode_workers=4
        ) as pipe:
            out = list(pipe)
        assert out == [i * 10 for i in range(12)]
        assert pipe.stats()["counts"] == {
            "decoded": 12, "failed": 0, "staged": 12, "yielded": 12,
        }

    def test_stage_runs_in_order_one_at_a_time(self):
        staged = []

        def stg(i):
            staged.append(i)
            return i

        with IngestPipeline(
            source=range(8), decode=lambda i: i, stage=stg,
            depth=2, decode_workers=4,
        ) as pipe:
            out = list(pipe)
        assert out == list(range(8)) and staged == list(range(8))

    def test_decode_failure_contained_in_order(self):
        def dec(i):
            if i == 2:
                raise ValueError("boom")
            return i

        with IngestPipeline(
            source=range(5), decode=dec, depth=2, decode_workers=3
        ) as pipe:
            out = list(pipe)
        assert [o for o in out if not isinstance(o, IngestFailure)] == [0, 1, 3, 4]
        fail = out[2]
        assert isinstance(fail, IngestFailure)
        assert fail.index == 2 and "boom" in str(fail.error)
        assert pipe.stats()["counts"]["failed"] == 1

    def test_backpressure_bounds_decode_lookahead(self):
        decoded = []
        lock = threading.Lock()

        def dec(i):
            with lock:
                decoded.append(i)
            return i

        depth, workers, staged_depth = 1, 1, 1
        bound = depth + workers + staged_depth + 1  # +1 = the one in hand
        with IngestPipeline(
            source=range(10), decode=dec, depth=depth,
            decode_workers=workers, staged_depth=staged_depth,
        ) as pipe:
            for i in pipe:
                time.sleep(0.02)  # slow consumer: the ring must fill
                with lock:
                    ahead = len(decoded) - (i + 1)
                assert ahead <= bound, (
                    f"decode ran {ahead} items ahead (> {bound}): "
                    "backpressure is not holding"
                )
        assert pipe.stats()["ring"]["peak"] <= depth

    def test_released_refs_allow_donation(self):
        # the pipeline must drop its reference the moment a record is
        # handed out: a donated program input can only recycle its HBM if
        # nothing else keeps the buffer alive
        refs = []

        def stg(i):
            t = Token()
            refs.append(weakref.ref(t))
            return {"i": i, "token": t}

        seen = []
        with IngestPipeline(
            source=range(6), decode=lambda i: i, stage=stg,
            depth=2, decode_workers=2, staged_depth=1,
        ) as pipe:
            for rec in pipe:
                seen.append(rec["i"])
                del rec
        gc.collect()
        assert seen == list(range(6))
        assert all(r() is None for r in refs), "pipeline retained staged refs"

    def test_stage_exception_aborts_and_propagates(self):
        def stg(i):
            if i == 3:
                raise RuntimeError("device gone")
            return i

        got = []
        with pytest.raises(RuntimeError, match="device gone"):
            with IngestPipeline(
                source=range(10), decode=lambda i: i, stage=stg,
                depth=2, decode_workers=2,
            ) as pipe:
                for i in pipe:
                    got.append(i)
        assert got == [0, 1, 2]

    def test_consumer_break_frees_blocked_producers(self):
        # a consumer exception/break must not leave the feeder parked on
        # a full ring forever — close() wakes it with RingClosed
        with IngestPipeline(
            source=range(100), decode=lambda i: i, depth=1, decode_workers=1
        ) as pipe:
            for i in pipe:
                break
        # close() ran via __exit__; the daemon threads died with it
        assert pipe.stats()["counts"]["yielded"] >= 1

    def test_upload_overlap_ratio_math(self):
        from nm03_capstone_project_tpu.ingest.pipeline import (
            _intersection_seconds,
            _union,
        )

        assert _union([(3, 4), (1, 2), (1.5, 2.5)]) == [[1, 2.5], [3, 4]]
        assert _intersection_seconds(
            [(0, 2), (4, 6)], [(1, 5)]
        ) == pytest.approx(2.0)
        assert _intersection_seconds([(0, 1)], [(2, 3)]) == 0.0

    def test_empty_source(self):
        with IngestPipeline(source=[], decode=lambda i: i) as pipe:
            assert list(pipe) == []
        assert pipe.stats()["counts"]["decoded"] == 0

    def test_stats_frozen_after_close(self):
        with IngestPipeline(source=range(3), decode=lambda i: i) as pipe:
            list(pipe)
        snap = pipe.stats()
        assert snap == pipe.stats()  # drained snapshot is stable
        assert snap["counts"]["yielded"] == 3

    def test_validation(self):
        with pytest.raises(ValueError):
            IngestPipeline(source=[], decode=lambda i: i, depth=0)
        with pytest.raises(ValueError):
            IngestPipeline(source=[], decode=lambda i: i, decode_workers=0)

    def test_publish_sets_gauges(self):
        from nm03_capstone_project_tpu.obs.metrics import MetricsRegistry

        reg = MetricsRegistry()
        with IngestPipeline(
            source=range(4), decode=lambda i: i, stage=lambda i: i,
            depth=2, decode_workers=2,
        ) as pipe:
            list(pipe)
        pipe.publish(reg)
        occ = reg.get("ingest_ring_occupancy_ratio")
        depth = reg.get("ingest_decode_queue_depth")
        assert occ is not None and 0.0 <= occ.value <= 1.0
        assert depth is not None


# -- ingest fault site -------------------------------------------------------


class TestIngestFaultSite:
    def test_decode_error_rule_fires_once(self):
        plan = FaultPlan.from_spec(
            '{"faults": [{"site": "ingest", "kind": "decode_error",'
            ' "index": 1}]}'
        )
        with IngestPipeline(
            source=range(4), decode=lambda i: i, fault_plan=plan,
            depth=2, decode_workers=2,
        ) as pipe:
            out = list(pipe)
        fails = [o for o in out if isinstance(o, IngestFailure)]
        assert len(fails) == 1 and fails[0].index == 1
        assert plan.fired_total() == 1

    def test_stall_rule_delays_but_completes(self):
        plan = FaultPlan.from_spec(
            '{"faults": [{"site": "ingest", "kind": "stall", "index": 0,'
            ' "hang_s": 0.3}]}'
        )
        t0 = time.monotonic()
        with IngestPipeline(
            source=range(3), decode=lambda i: i, stage=lambda i: i,
            fault_plan=plan, depth=1, decode_workers=1,
        ) as pipe:
            out = list(pipe)
        assert out == [0, 1, 2]
        assert time.monotonic() - t0 >= 0.3

    def test_stall_is_cancel_aware(self):
        # close() mid-stall must not wait out hang_s
        plan = FaultPlan.from_spec(
            '{"faults": [{"site": "ingest", "kind": "stall", "index": 0,'
            ' "hang_s": 60}]}'
        )
        pipe = IngestPipeline(
            source=range(2), decode=lambda i: i, stage=lambda i: i,
            fault_plan=plan, depth=1, decode_workers=1,
        )
        pipe.start()
        time.sleep(0.1)  # let the stager enter the stall
        t0 = time.monotonic()
        pipe.close()
        assert time.monotonic() - t0 < 10

    def test_invalid_kind_rejected(self):
        with pytest.raises(ValueError):
            FaultPlan.from_spec(
                '{"faults": [{"site": "ingest", "kind": "hang"}]}'
            )


# -- staging helpers (jax) ---------------------------------------------------


class TestStaging:
    def test_stage_batch_keeps_host_refs(self):
        import jax
        import numpy as np

        from nm03_capstone_project_tpu.ingest import stage_batch

        item = {
            "pixels": np.ones((2, 4, 4), np.float32),
            "dims": np.ones((2, 2), np.int32),
            "stems": ["a", "b"],
        }
        out = stage_batch(item)
        assert isinstance(out["pixels"], jax.Array)
        assert isinstance(out["pixels_host"], np.ndarray)
        assert out["stems"] == ["a", "b"]
        # the input dict is not mutated
        assert isinstance(item["pixels"], np.ndarray)

    def test_stage_batch_no_host_refs(self):
        import jax
        import numpy as np

        from nm03_capstone_project_tpu.ingest import stage_batch

        out = stage_batch(
            {"pixels": np.zeros((1, 2, 2), np.float32)}, keep_host=False
        )
        assert isinstance(out["pixels"], jax.Array)
        assert "pixels_host" not in out

    # the absorbed data/prefetch.py contract (retired module, ISSUE 11)

    def test_prefetch_yields_all_items_in_order(self):
        import numpy as np

        from nm03_capstone_project_tpu.ingest import prefetch_to_device

        items = [
            {"x": np.full((4,), i, np.float32), "name": f"s{i}"}
            for i in range(7)
        ]
        out = list(prefetch_to_device(iter(items), depth=2))
        assert [o["name"] for o in out] == [f"s{i}" for i in range(7)]
        for i, o in enumerate(out):
            np.testing.assert_array_equal(np.asarray(o["x"]), items[i]["x"])

    def test_prefetch_arrays_land_on_device(self):
        import jax
        import numpy as np

        from nm03_capstone_project_tpu.ingest import prefetch_to_device

        (out,) = list(
            prefetch_to_device(iter([{"x": np.ones((3, 3), np.float32)}]))
        )
        assert isinstance(out["x"], jax.Array)
        assert out["x"].device == jax.devices()[0]

    def test_prefetch_non_array_and_none_leaves(self):
        import numpy as np

        from nm03_capstone_project_tpu.ingest import prefetch_to_device

        items = [{"x": None, "stems": []}, {"x": np.ones(2), "stems": ["a"]}]
        out = list(prefetch_to_device(iter(items), depth=2))
        assert out[0]["x"] is None and out[1]["stems"] == ["a"]

    def test_prefetch_empty_iterator(self):
        from nm03_capstone_project_tpu.ingest import prefetch_to_device

        assert list(prefetch_to_device(iter([]))) == []


# -- driver integration (in-process) -----------------------------------------


def _run_driver(mod, tmp_path, extra=(), slices=5):
    rj = tmp_path / "r.json"
    ej = tmp_path / "e.jsonl"
    rc = mod.main(
        [
            "--synthetic", "1", "--synthetic-slices", str(slices),
            "--device", "cpu", "--canvas", str(CANVAS),
            "--output", str(tmp_path / "out"),
            "--results-json", str(rj), "--log-json", str(ej),
            *extra,
        ]
    )
    rec = json.loads(rj.read_text()) if rj.exists() else None
    events = (
        [json.loads(line) for line in ej.read_text().splitlines() if line]
        if ej.exists()
        else []
    )
    return rc, rec, events


class TestDriverIngest:
    @pytest.mark.parametrize("mode", ["sequential", "parallel"])
    def test_drivers_report_ingest_next_to_feed_stall(self, tmp_path, mode):
        from nm03_capstone_project_tpu.cli import parallel, sequential

        mod = sequential if mode == "sequential" else parallel
        rc, rec, events = _run_driver(mod, tmp_path)
        assert rc == 0 and rec["summary"]["slices_ok"] == 5
        ing = rec["ingest"]
        assert ing["patients"] == 1
        assert 0.0 <= ing["ring_occupancy_ratio"] <= 1.0
        assert ing["decode_queue_peak"] >= 1
        assert ing["counts"]["yielded"] >= 1
        # the feed report still rides beside it — same accountant
        assert 0.0 <= rec["feed_stall"]["feed_stall_ratio"] < 1.0
        names = {m["name"] for m in rec["metrics"]["metrics"]}
        assert {
            "ingest_ring_occupancy_ratio", "ingest_decode_queue_depth",
        } <= names
        drained = [e for e in events if e["event"] == "ingest_drained"]
        assert len(drained) == 1 and drained[0]["mode"] == mode

    def test_sequential_ingest_decode_fault_contained(self, tmp_path):
        from nm03_capstone_project_tpu.cli import sequential

        rc, rec, _ = _run_driver(
            sequential, tmp_path,
            extra=[
                "--fault-plan",
                '{"faults": [{"site": "ingest", "kind": "decode_error",'
                ' "index": 2}]}',
            ],
        )
        assert rc == 0
        assert rec["summary"]["slices_ok"] == 4  # 5 - the injected failure
        counters = {
            (m["name"], tuple(sorted(m["labels"].items()))): m.get("value")
            for m in rec["metrics"]["metrics"]
        }
        key = (
            "resilience_faults_injected_total",
            (("kind", "decode_error"), ("site", "ingest")),
        )
        assert counters.get(key) == 1.0

    def test_parallel_stager_wedge_completes_late_never_wrong(self, tmp_path):
        from nm03_capstone_project_tpu.cli import parallel

        t0 = time.monotonic()
        rc, rec, _ = _run_driver(
            parallel, tmp_path, slices=8,
            extra=[
                "--batch-size", "4",
                "--fault-plan",
                '{"faults": [{"site": "ingest", "kind": "stall", "index": 0,'
                ' "hang_s": 1.0}]}',
            ],
        )
        assert rc == 0 and rec["summary"]["slices_ok"] == 8
        assert time.monotonic() - t0 >= 1.0  # the wedge really happened

    @pytest.mark.parametrize("mode", ["sequential", "parallel"])
    def test_sanitize_green_through_staging_path(self, tmp_path, mode):
        # the ISSUE 11 acceptance bar: transfer guard armed around the
        # ingest-staged dispatch, zero violations, rc=0
        from nm03_capstone_project_tpu.cli import parallel, sequential

        mod = sequential if mode == "sequential" else parallel
        rc, rec, _ = _run_driver(mod, tmp_path, extra=["--sanitize"])
        assert rc == 0 and rec["summary"]["slices_ok"] == 5
        names = {m["name"] for m in rec["metrics"]["metrics"]}
        assert "pipeline_recompiles_total" in names  # sanitize was armed


# -- subprocess acceptance ---------------------------------------------------


class TestIngestAcceptance:
    def test_parallel_cohort_feed_stall_gated(self, tmp_path):
        """The ISSUE 11 acceptance bar: a parallel-driver cohort through
        the streaming ingest holds ``pipeline_feed_stall_ratio`` ≤ 0.15
        (the serial feed's pinned stall erased), with the ingest gauges
        present in the drained snapshot — gated by check_telemetry.

        Canvas 256 — the bench canvas — on purpose: the stall ratio is a
        fraction of *wall*, and at toy canvases the fixed host tails
        (startup decode, final JPEG export) dominate wall and would gate
        the wrong thing.
        """
        metrics = tmp_path / "m.json"
        results = tmp_path / "r.json"
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PALLAS_AXON_POOL_IPS", None)
        res = subprocess.run(
            [
                sys.executable, "-m", "nm03_capstone_project_tpu.cli.parallel",
                "--synthetic", "1", "--synthetic-slices", "48",
                "--batch-size", "8", "--canvas", "256",
                "--device", "cpu",
                "--output", str(tmp_path / "out"),
                "--metrics-out", str(metrics),
                "--results-json", str(results),
            ],
            capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        rec = json.loads(results.read_text())
        assert rec["summary"]["slices_ok"] == 48
        gate = subprocess.run(
            [
                sys.executable, CHECKER,
                "--metrics", str(metrics),
                "--expect-gauge-range", "pipeline_feed_stall_ratio=[0..0.15]",
                "--expect-gauge-range", "ingest_ring_occupancy_ratio=[0..1]",
                "--expect-gauge-range", "ingest_decode_queue_depth=[1..4096]",
                "--expect-gauge-range", "ingest_upload_overlap_ratio=[0..1]",
            ],
            capture_output=True, text=True, timeout=60,
        )
        assert gate.returncode == 0, gate.stdout + gate.stderr


# -- bench streamed-feed leg --------------------------------------------------


class TestBenchStreamedFeed:
    def test_record_is_checksum_gated_and_carried(self, monkeypatch):
        import bench

        monkeypatch.setattr(bench, "CANVAS", 96)
        serial = bench._feed_stall_record(batch=2, reps=3)
        rec = bench._streamed_feed_record(batch=2, reps=3, serial_rec=serial)
        assert rec["checksum_ok"] is True
        assert 0.0 <= rec["feed_stall_ratio"] <= 1.0
        assert rec["slices_per_s"] > 0
        assert rec["busy_s"]["dispatch"] > 0
        assert rec["ingest"]["decode_queue_peak"] >= 1
        if serial["checksum_ok"]:
            assert rec["speedup_vs_serial"] > 0
        # rides _compose via _copy_optional -> the slim line
        out = {}
        bench._copy_optional(out, {"feed_streamed": rec})
        assert out["feed_streamed"] is rec

    def test_mismatched_checksum_nulls_the_headline(self, monkeypatch):
        import numpy as np

        import bench

        monkeypatch.setattr(bench, "CANVAS", 96)
        real_make = bench._make_batch
        calls = {"n": 0}

        def skewed(batch=None):
            pixels, dims = real_make(batch)
            calls["n"] += 1
            if calls["n"] > 1:  # the ref batch is the first call
                pixels = np.zeros_like(pixels)
            return pixels, dims

        monkeypatch.setattr(bench, "_make_batch", skewed)
        rec = bench._streamed_feed_record(batch=2, reps=2)
        assert rec["checksum_ok"] is False
        assert rec["feed_stall_ratio"] is None
        assert rec["slices_per_s"] is None
        assert "speedup_vs_serial" not in rec
        # the evidence fields stay: an operator can still see the phases
        assert rec["busy_s"]["dispatch"] > 0
